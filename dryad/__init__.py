"""``dryad`` — API-compatibility alias for :mod:`dryad_tpu` (BASELINE.json:5
names the public surface ``dryad.train`` / ``dryad.predict``)."""

from dryad_tpu import *  # noqa: F401,F403
from dryad_tpu import __version__, train, predict, Dataset, Booster, Params  # noqa: F401
