"""Device-truth observability (r12): compiled-program introspection,
recompile tripwire, fetch-stall watchdog, health-aware /healthz.

Pins the ISSUE 8 contracts on CPU:

* the serve tripwire — a forced bucket-miss AFTER warmup increments
  ``dryad_recompile_unexpected_total`` exactly once while warm repeats
  never fire (no false positives);
* the fetch-stall watchdog — a ``FaultInjector``-stalled fetch raises
  the in-flight age gauge and flips ``/healthz`` to degraded, recovery
  clears it;
* compile-boundary introspection — ``dryad_prog_*`` cost/memory series
  appear for the device trainer's chunk program, and the capture is
  memoized (no re-lower on a warm re-run);
* the ACCEPTANCE drill — a supervised CPU run with an injected stalled
  fetch plus a forced serve recompile, scraped over HTTP mid-run: stall
  gauge rising, ``/healthz`` 503, the recompile counter firing exactly
  once, ``dryad_prog_*`` present for BOTH growers — then completing
  bitwise-equal to the uninstrumented run.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.obs import (
    FetchWatchdog,
    Registry,
    default_health,
    healthz_payload,
    set_default_registry,
    set_default_watchdog,
    start_exporter,
)
from dryad_tpu.obs.tripwire import RecompileTripwire
from dryad_tpu.resilience import FaultInjector, RetryPolicy, supervise_train
from dryad_tpu.resilience import faults as F

PARAMS = dict(objective="binary", num_trees=8, num_leaves=7, max_bins=32,
              seed=3, min_data_in_leaf=5)


@pytest.fixture(scope="module")
def data():
    X, y = higgs_like(3000, seed=21)
    return dryad.Dataset(X, y, max_bins=32)


@pytest.fixture()
def fresh_registry():
    reg = Registry()
    old = set_default_registry(reg)
    yield reg
    set_default_registry(old)


@pytest.fixture(autouse=True)
def clean_health():
    """Every test starts AND ends with a clean process health state — a
    leaked degradation would 503 unrelated tests' /healthz probes."""
    default_health().reset()
    yield
    default_health().reset()


def _get(url, timeout=5):
    return urllib.request.urlopen(url, timeout=timeout).read()


# ---- health state -----------------------------------------------------------

def test_health_state_degrade_clear_and_payload(fresh_registry):
    h = default_health()
    code, body = healthz_payload()
    assert (code, body) == (200, {"ok": True})
    h.degrade("fetch_stall", "pending 31s")
    h.degrade("recompile", "serve bucket miss")
    code, body = healthz_payload()
    assert code == 503 and body["ok"] is False
    assert body["degraded"] == ["fetch_stall", "recompile"]
    # gauge mirror: 1 while active, 0 after recovery
    g = fresh_registry.gauge("dryad_health_degraded")
    assert g.labels(reason="fetch_stall").value() == 1
    h.clear("fetch_stall")
    h.clear("recompile")
    assert healthz_payload() == (200, {"ok": True})
    assert g.labels(reason="fetch_stall").value() == 0


def test_exporter_healthz_flips_with_health(fresh_registry):
    ex = start_exporter(fresh_registry, port=0)
    try:
        assert json.loads(_get(ex.url + "/healthz")) == {"ok": True}
        default_health().degrade("fetch_stall", "test")
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(ex.url + "/healthz")
        assert err.value.code == 503
        assert json.loads(err.value.read())["degraded"] == ["fetch_stall"]
        default_health().clear("fetch_stall")
        assert json.loads(_get(ex.url + "/healthz")) == {"ok": True}
    finally:
        ex.stop()


# ---- fetch-stall watchdog ---------------------------------------------------

def test_watchdog_stall_raises_gauge_then_recovery_clears(fresh_registry):
    dog = FetchWatchdog(fresh_registry, threshold_s=0.05,
                        poll_interval_s=0.01)
    gauge = fresh_registry.gauge("dryad_fetch_inflight_age_seconds")
    with dog.watch("eval", 7):
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and default_health().ok:
            time.sleep(0.01)
        # mid-stall: gauge above threshold, health degraded, counter up
        assert not default_health().ok
        assert "fetch_stall" in default_health().reasons()
        assert gauge.value() >= 0.05
        assert fresh_registry.counter("dryad_fetch_stalls_total").labels(
            site="eval").value() == 1
    # recovery: gauge back to 0, health clean, stall recorded for the
    # supervisor's correlation hook
    assert default_health().ok
    assert gauge.value() == 0.0
    stall = dog.last_stall()
    assert stall["site"] == "eval" and stall["iteration"] == 7
    assert stall["age_s"] >= 0.05


def test_watchdog_fast_fetches_never_stall(fresh_registry):
    dog = FetchWatchdog(fresh_registry, threshold_s=5.0,
                        poll_interval_s=0.01)
    for i in range(5):
        with dog.watch("runahead", i):
            pass
    assert default_health().ok
    assert fresh_registry.counter("dryad_fetch_stalls_total").labels(
        site="runahead").value() == 0
    assert dog.last_stall() is None


def test_watchdog_disabled_registry_is_null(fresh_registry):
    fresh_registry.disable()
    dog = FetchWatchdog(fresh_registry, threshold_s=0.01)
    w = dog.watch("eval", 0)
    with w:
        time.sleep(0.03)
    assert dog.watch("eval", 1) is dog.watch("eval", 2)  # shared null ctx
    fresh_registry.enable()
    assert fresh_registry.snapshot()["counters"] == {}


def test_injected_stall_through_the_device_trainer(data, fresh_registry):
    """A FaultInjector STALL point at a real trainer fetch site holds the
    hook inside the watch_fetch bracket: the watchdog must see it, count
    it, and the run must complete normally (a hang, not a death)."""
    dog = FetchWatchdog(fresh_registry, threshold_s=0.05,
                        poll_interval_s=0.01)
    old = set_default_watchdog(dog)
    injector = FaultInjector([(0, F.STALL, "fetch", 0.3)])
    try:
        booster = dryad.train(PARAMS, data, backend="tpu",
                              chunk_hook=injector)
    finally:
        set_default_watchdog(old)
    assert injector.pending == 0
    assert booster.num_iterations == PARAMS["num_trees"]
    stall = dog.last_stall()
    assert stall is not None and stall["age_s"] >= 0.05
    # the injected sleep fires at the FIRST fetch hook — the calibrate
    # site — inside its watch bracket: that watch stalls exactly once.
    # (Other sites may legitimately cross the tiny 50 ms test threshold
    # under CI load, so only the injected site is pinned exactly.)
    stalls = fresh_registry.counter("dryad_fetch_stalls_total")
    assert stalls.labels(site="calibrate").value() == 1
    assert default_health().ok     # recovered


# ---- recompile tripwire -----------------------------------------------------

def test_tripwire_unit_arm_and_key_change(fresh_registry):
    tw = RecompileTripwire(fresh_registry)
    fired = []
    remove = tw.add_listener(lambda program, detail: fired.append(detail))
    tw.begin_program("train.chunk")
    assert tw.note_compile("train.chunk", ("key", 1)) is True
    assert tw.note_compile("train.chunk", ("key", 1)) is False  # warm
    tw.arm("train.chunk")
    assert tw.note_compile("train.chunk", ("key", 1)) is False  # still warm
    assert fired == [] and default_health().ok
    tw.note_compile("train.chunk", ("key", 2))                  # p_key drift
    assert len(fired) == 1
    assert fresh_registry.counter(
        "dryad_recompile_unexpected_total").labels(
        program="train.chunk").value() == 1
    # degradation is scoped PER FAMILY: another family's lifecycle must
    # not clear this alarm, and re-arming THIS family is the recovery
    assert "recompile:train.chunk" in default_health().reasons()
    tw.begin_program("serve.predict")
    assert "recompile:train.chunk" in default_health().reasons()
    tw.arm("train.chunk")                                       # re-arm =
    assert default_health().ok                                  # recovery
    # a new run resets: disarmed, health cleared
    tw.begin_program("train.chunk")
    assert not tw.armed("train.chunk") and default_health().ok
    # arming a KEY-LESS family is inert: a run warmed under a disabled
    # registry must not false-fire when obs is enabled mid-run
    tw.arm("train.chunk")
    assert not tw.armed("train.chunk")
    tw.note_compile("train.chunk", ("key", 6))                  # no fire
    assert fresh_registry.counter(
        "dryad_recompile_unexpected_total").labels(
        program="train.chunk").value() == 1
    remove()
    tw.arm("train.chunk")
    tw.note_compile("train.chunk", ("key", 7))                  # fires, but
    assert len(fired) == 1                                      # no listener


def test_serve_bucket_miss_after_warmup_fires_once(fresh_registry):
    """The ISSUE satellite: forced serve bucket-miss increments
    dryad_recompile_unexpected_total while warm repeats don't."""
    from dryad_tpu.serve import PredictServer

    X, y = higgs_like(600, seed=5)
    booster = dryad.train(dict(PARAMS, num_trees=4), dryad.Dataset(
        X, y, max_bins=32), backend="cpu")
    server = PredictServer(backend="cpu", max_batch_rows=64, min_bucket=8)
    server.registry.add(booster)
    unexpected = fresh_registry.counter("dryad_recompile_unexpected_total")
    with server:
        for b in (8, 16):        # partial warmup, on purpose
            server.predict(X[:b])
        server.warmup_complete()
        for _ in range(3):       # warm repeats: no false positives
            server.predict(X[:8])
            server.predict(X[:13])   # still bucket 16
        assert unexpected.labels(program="serve.predict").value() == 0
        assert default_health().ok
        server.predict(X[:40])   # bucket 64 was never warmed: fires
        assert unexpected.labels(program="serve.predict").value() == 1
        assert "recompile:serve.predict" in default_health().reasons()
        server.predict(X[:40])   # the key is known now: exactly once
        assert unexpected.labels(program="serve.predict").value() == 1
        # recovery: re-arming (re-warm done — the key is in the set now)
        # clears the standing degradation
        server.warmup_complete()
        assert default_health().ok


def test_serve_warmup_arms_and_deploy_window(fresh_registry, tmp_path):
    """The PRODUCTION arming path: server.warmup() compiles every
    (version, bucket) program and arms; a later load_model opens a deploy
    window (no latched 503 on a routine deploy) and warmup() re-arms."""
    from dryad_tpu.serve import PredictServer

    X, y = higgs_like(600, seed=5)
    booster = dryad.train(dict(PARAMS, num_trees=4), dryad.Dataset(
        X, y, max_bins=32), backend="cpu")
    server = PredictServer(backend="cpu", max_batch_rows=32, min_bucket=8)
    server.registry.add(booster)
    unexpected = fresh_registry.counter("dryad_recompile_unexpected_total")
    with server:
        touched = server.warmup()
        assert touched == len(server.cache.buckets())
        for n in (1, 5, 20, 32):         # every bucket warm, tripwire armed
            server.predict(X[:n])
        assert unexpected.labels(program="serve.predict").value() == 0
        # deploy: a new version's compiles are NOT unexpected during the
        # window; warmup() closes it re-armed
        path = str(tmp_path / "v2.dryad")
        booster.save(path)
        v2 = server.load_model(path)
        server.predict(X[:5], version=v2)    # cold key, window open
        assert unexpected.labels(program="serve.predict").value() == 0
        assert default_health().ok
        server.warmup()
        server.predict(X[:5], version=v2)
        assert unexpected.labels(program="serve.predict").value() == 0


# ---- compile-boundary introspection ----------------------------------------

def test_introspect_records_prog_series(data, fresh_registry, monkeypatch):
    monkeypatch.setenv("DRYAD_PROG", "1")
    monkeypatch.setenv("DRYAD_PROG_MEMORY", "1")
    from dryad_tpu.engine import introspect

    introspect.reset_seen()
    booster = dryad.train(PARAMS, data, backend="tpu")
    snap = fresh_registry.snapshot()
    flops = snap["gauges"]["dryad_prog_flops"]
    label = next(iter(flops))
    assert 'program="train.chunk"' in label and flops[label] > 0
    assert snap["gauges"]["dryad_prog_bytes_accessed"]
    kinds = {lbl for lbl in snap["gauges"]["dryad_prog_memory_bytes"]}
    assert any('kind="temp"' in k for k in kinds)
    assert snap["counters"]["dryad_prog_compiles_total"][
        'program="train.chunk"'] == 1
    captures = fresh_registry.counter("dryad_prog_captures_total").labels(
        program="train.chunk")
    n0 = captures.value()
    assert n0 >= 1
    # warm re-run: memoized — no re-capture, no unexpected recompile
    dryad.train(PARAMS, data, backend="tpu")
    assert captures.value() == n0
    assert snap["counters"].get("dryad_recompile_unexpected_total", {}) == {}
    assert booster.num_iterations == PARAMS["num_trees"]


def test_introspect_off_by_default_in_suite(data, fresh_registry):
    """conftest pins DRYAD_PROG=0 for suite wall: no capture happens, and
    the registry carries no dryad_prog cost series after a train."""
    dryad.train(PARAMS, data, backend="tpu")
    snap = fresh_registry.snapshot()
    assert "dryad_prog_flops" not in snap["gauges"]


def test_predict_capture_single_and_sharded(data, fresh_registry,
                                            monkeypatch):
    monkeypatch.setenv("DRYAD_PROG", "1")
    from dryad_tpu.engine import introspect
    from dryad_tpu.engine.predict import (
        predict_binned_device,
        predict_binned_sharded,
    )

    introspect.reset_seen()
    booster = dryad.train(dict(PARAMS, num_trees=4), data, backend="cpu")
    Xb = data.X_binned[:64]
    raw_single = np.asarray(predict_binned_device(booster, Xb))
    raw_sharded = predict_binned_sharded(booster, Xb)
    np.testing.assert_array_equal(raw_single, raw_sharded)
    flops = fresh_registry.snapshot()["gauges"]["dryad_prog_flops"]
    arms = {lbl for lbl in flops if 'program="predict"' in lbl}
    assert any('arm="single"' in a for a in arms)
    assert any('arm="sharded"' in a for a in arms)


# ---- the acceptance drill ---------------------------------------------------

def test_acceptance_stall_recompile_prog_series_live(data, tmp_path,
                                                     fresh_registry,
                                                     monkeypatch):
    """Supervised CPU run (device trainer) with an injected stalled fetch
    + a forced serve recompile, scraped over HTTP mid-run: the stall
    gauge rises, /healthz goes 503, the recompile counter fires exactly
    once, dryad_prog_* cost series exist for BOTH growers — and the run
    completes bitwise-equal to the uninstrumented one."""
    monkeypatch.setenv("DRYAD_PROG", "1")
    monkeypatch.setenv("DRYAD_PROG_MEMORY", "1")
    from dryad_tpu.engine import introspect

    introspect.reset_seen()
    dog = FetchWatchdog(fresh_registry, threshold_s=0.2,
                        poll_interval_s=0.02)
    old_dog = set_default_watchdog(dog)
    injector = FaultInjector([(0, F.STALL, "fetch", 2.5)])
    jpath = str(tmp_path / "run.jsonl")
    ex = start_exporter(fresh_registry, port=0)
    result = {}

    def run():
        try:
            result["booster"] = supervise_train(
                PARAMS, data, backend="tpu",
                checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4,
                journal=jpath, fault_injector=injector,
                policy=RetryPolicy(backoff_base_s=0.0))
        except BaseException as e:  # noqa: BLE001 — surfaced below
            result["error"] = e

    thread = threading.Thread(target=run)
    thread.start()
    try:
        # scrape DURING the injected stall: /healthz 503 with fetch_stall
        # and the in-flight age gauge above the threshold
        deadline = time.monotonic() + 60
        saw_degraded = saw_gauge = False
        while time.monotonic() < deadline and thread.is_alive():
            try:
                _get(ex.url + "/healthz", timeout=2)
            except urllib.error.HTTPError as err:
                if err.code == 503 and "fetch_stall" in json.loads(
                        err.read()).get("degraded", []):
                    saw_degraded = True
                    stats = json.loads(_get(ex.url + "/stats"))
                    age = stats["gauges"][
                        "dryad_fetch_inflight_age_seconds"][""]
                    saw_gauge = age >= 0.2
                    break
            time.sleep(0.02)
        assert saw_degraded, "never saw /healthz degrade during the stall"
        assert saw_gauge, "stall gauge never rose past the threshold"
    finally:
        thread.join(180)
        set_default_watchdog(old_dog)
    assert "error" not in result, result.get("error")
    assert injector.pending == 0
    # recovered: /healthz green again, run complete
    assert json.loads(_get(ex.url + "/healthz")) == {"ok": True}

    # dryad_prog_* cost/memory series for BOTH growers: the supervised run
    # used the default leaf-wise growth; a short depthwise run adds the
    # level-synchronous grower's program
    dryad.train(dict(PARAMS, growth="depthwise", num_trees=2,
                     max_depth=4), data, backend="tpu")
    flops = json.loads(_get(ex.url + "/stats"))["gauges"]["dryad_prog_flops"]
    chunk_labels = [lbl for lbl in flops if 'program="train.chunk"' in lbl]
    growths = {g for lbl in chunk_labels
               for g in ("depthwise", "leafwise") if f'growth="{g}"' in lbl}
    assert growths == {"depthwise", "leafwise"}, chunk_labels
    mem = json.loads(_get(ex.url + "/stats"))["gauges"][
        "dryad_prog_memory_bytes"]
    assert any('program="train.chunk"' in lbl for lbl in mem)

    # forced serve recompile after warmup: counter fires EXACTLY once
    from dryad_tpu.serve import PredictServer

    server = PredictServer(backend="cpu", max_batch_rows=64, min_bucket=8)
    server.registry.add(result["booster"])
    X = np.asarray(data.X_binned[:64], data.X_binned.dtype)
    with server:
        server.predict(X[:8], binned=True)
        server.warmup_complete()
        server.predict(X[:40], binned=True)      # cold bucket 64: fires
        server.predict(X[:40], binned=True)      # warm now: still once
    unexpected = json.loads(_get(ex.url + "/stats"))["counters"][
        "dryad_recompile_unexpected_total"]
    assert unexpected['program="serve.predict"'] == 1
    ex.stop()

    # the journal recorded the chunk traffic of a completed run
    from dryad_tpu.resilience import RunJournal

    events = [e["event"] for e in RunJournal.read_last_run(jpath)]
    assert "complete" in events and "fault" not in events

    # bitwise: instrumented + stalled == uninstrumented
    default_health().reset()
    off = Registry(enabled=False)
    prev = set_default_registry(off)
    try:
        reference = dryad.train(PARAMS, data, backend="tpu")
    finally:
        set_default_registry(prev)
    np.testing.assert_array_equal(reference.feature,
                                  result["booster"].feature)
    np.testing.assert_array_equal(reference.value, result["booster"].value)
