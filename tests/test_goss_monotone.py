"""GOSS sampling and monotone constraints."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.metrics import auc


def test_goss_trains_and_matches_quality():
    X, y = higgs_like(6000, seed=71)
    ds = dryad.Dataset(X, y, max_bins=64)
    base = dict(objective="binary", num_trees=25, num_leaves=31, max_bins=64)
    b_full = dryad.train(base, ds, backend="cpu")
    b_goss = dryad.train(dict(base, boosting="goss", goss_top_rate=0.3,
                              goss_other_rate=0.2), ds, backend="cpu")
    a_full = auc(y, b_full.predict_binned(ds.X_binned))
    a_goss = auc(y, b_goss.predict_binned(ds.X_binned))
    assert a_goss > 0.7
    assert abs(a_full - a_goss) < 0.05


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_goss_backend_quality(backend):
    X, y = higgs_like(4000, seed=73)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective="binary", num_trees=15, num_leaves=15, max_bins=32,
             boosting="goss")
    b = dryad.train(p, ds, backend=backend)
    assert auc(y, b.predict_binned(ds.X_binned)) > 0.68


def test_goss_uniform_device_parity():
    """The device-drawn chunk-path uniforms must be BIT-identical to the
    host generator (cpu/trainer.goss_uniform) — the anchor that lets GOSS
    chunk without breaking CPU↔TPU selection parity (VERDICT r3 #4)."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.config import make_params
    from dryad_tpu.cpu.trainer import goss_uniform
    from dryad_tpu.engine.train import _goss_uniform_dev

    for seed in (0, 7, 123456789):
        p = make_params(dict(objective="binary", seed=seed))
        for it in (0, 1, 57, 4999):
            host = goss_uniform(p, it, 3001)
            dev = jax.jit(
                lambda i: _goss_uniform_dev(seed, i, 3001)
            )(jnp.int32(it))
            np.testing.assert_array_equal(host, np.asarray(dev))
            assert host.min() >= 0.0 and host.max() < 1.0


def test_goss_cpu_tpu_tree_parity():
    """GOSS trees must agree across backends with the shared counter-based
    uniforms (the TPU run rides the chunked path, generating them on
    device)."""
    X, y = higgs_like(4000, seed=79)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective="binary", num_trees=8, num_leaves=15, max_bins=32,
             boosting="goss", goss_top_rate=0.25, goss_other_rate=0.15,
             seed=5)
    b_cpu = dryad.train(p, ds, backend="cpu")
    b_dev = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(b_cpu.feature, b_dev.feature)
    np.testing.assert_array_equal(b_cpu.threshold, b_dev.threshold)
    # leaf values accumulate on different fp pipelines -> ulp-level noise;
    # structure above is the exact-parity assertion (CLAUDE.md invariant)
    np.testing.assert_allclose(
        b_cpu.predict_binned(ds.X_binned), b_dev.predict_binned(ds.X_binned),
        rtol=2e-6, atol=2e-6)


def test_goss_validation():
    X, y = higgs_like(500, seed=75)
    ds = dryad.Dataset(X, y, max_bins=16)
    with pytest.raises(ValueError, match="subsample"):
        dryad.train(dict(objective="binary", num_trees=1, boosting="goss",
                         subsample=0.5), ds, backend="cpu")
    with pytest.raises(ValueError, match="rates"):
        dryad.train(dict(objective="binary", num_trees=1, boosting="goss",
                         goss_top_rate=0.0), ds, backend="cpu")


def _monotone_violations(booster, X, feature, sign, delta=1.0):
    """Count rows where increasing `feature` moves the score against sign."""
    X2 = X.copy()
    X2[:, feature] += delta
    s1 = booster.predict(X, raw_score=True)
    s2 = booster.predict(X2, raw_score=True)
    return int((sign * (s2 - s1) < -1e-7).sum())


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_monotone_constraint_holds_on_stumps(backend):
    # depth-1 trees: the split-level constraint fully determines monotonicity
    rng = np.random.default_rng(77)
    X = rng.normal(size=(3000, 4)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] + 0.3 * rng.normal(size=3000) > 0).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=64)
    mono = (1, -1, 0, 0)
    b = dryad.train(dict(objective="binary", num_trees=30, num_leaves=2,
                         max_depth=1, max_bins=64, monotone_constraints=mono),
                    ds, backend=backend)
    assert _monotone_violations(b, X[:500], 0, +1) == 0
    assert _monotone_violations(b, X[:500], 1, -1) == 0
    # unconstrained run does use both features in the right direction anyway;
    # flip the constraint to prove enforcement bites
    b_flip = dryad.train(dict(objective="binary", num_trees=30, num_leaves=2,
                              max_depth=1, max_bins=64,
                              monotone_constraints=(-1, 1, 0, 0)),
                         ds, backend=backend)
    used = b_flip.feature[b_flip.feature >= 0]
    assert not np.isin(used, [0, 1]).any()  # constrained-out of both


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
@pytest.mark.parametrize("growth", ["leafwise", "depthwise"])
def test_monotone_constraint_holds_deep(backend, growth):
    # deep trees: only bound propagation (LightGBM "basic" mode) can stop a
    # descendant subtree from crossing a constrained ancestor's split
    rng = np.random.default_rng(81)
    X = rng.normal(size=(4000, 4)).astype(np.float32)
    y = (X[:, 0] + 0.8 * np.sin(2 * X[:, 1]) + 0.3 * rng.normal(size=4000)
         ).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=64)
    b = dryad.train(dict(objective="regression", num_trees=25, num_leaves=31,
                         max_depth=6, growth=growth, max_bins=64,
                         monotone_constraints=(1, 0, 0, 0)),
                    ds, backend=backend)
    assert b.max_depth_seen >= 3  # the constraint must not collapse the trees
    # exhaustive check along the constrained axis: predictions must be
    # non-decreasing in feature 0 for many random settings of the others
    base = rng.normal(size=(64, 4)).astype(np.float32)
    grid = np.linspace(X[:, 0].min(), X[:, 0].max(), 48, dtype=np.float32)
    pts = np.repeat(base, grid.size, axis=0)
    pts[:, 0] = np.tile(grid, base.shape[0])
    s = b.predict(pts, raw_score=True).reshape(base.shape[0], grid.size)
    assert (np.diff(s, axis=1) >= -1e-6).all()


def test_monotone_decreasing_deep():
    rng = np.random.default_rng(83)
    X = rng.normal(size=(3000, 3)).astype(np.float32)
    y = (-X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.2 * rng.normal(size=3000)
         ).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32)
    b = dryad.train(dict(objective="regression", num_trees=15, num_leaves=31,
                         max_bins=32, monotone_constraints=(-1, 0, 0)),
                    ds, backend="cpu")
    base = rng.normal(size=(32, 3)).astype(np.float32)
    grid = np.linspace(X[:, 0].min(), X[:, 0].max(), 32, dtype=np.float32)
    pts = np.repeat(base, grid.size, axis=0)
    pts[:, 0] = np.tile(grid, base.shape[0])
    s = b.predict(pts, raw_score=True).reshape(base.shape[0], grid.size)
    assert (np.diff(s, axis=1) <= 1e-6).all()


def test_monotone_cpu_tpu_parity():
    rng = np.random.default_rng(79)
    X = rng.normal(size=(3000, 5)).astype(np.float32)
    y = (X[:, 0] + np.sin(X[:, 2]) + 0.2 * rng.normal(size=3000)).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective="regression", num_trees=8, num_leaves=15, max_bins=32,
             monotone_constraints=(1, 0, 0, 0, 0))
    b_cpu = dryad.train(p, ds, backend="cpu")
    b_tpu = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(b_cpu.feature, b_tpu.feature)
    np.testing.assert_array_equal(b_cpu.threshold, b_tpu.threshold)
