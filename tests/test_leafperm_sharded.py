"""Wired growers (leaf-ordered layout, r10: root-anchored and live from
level 0 in BOTH level-synchronous growers) under shard_map: N-shard
training must reproduce 1-shard training through every wired level.

The wired path keeps every layout strictly shard-local (each shard
permutes its own rows into its own tile-aligned buffer); the ONLY
collective stays the fused grad/hess/count psum inside the histogram
builders — so sharded trees must match single-device trees exactly on
the tie-free fixtures tier-1 pins (CLAUDE.md invariant).

CPU-forced like the rest of tier-1 (conftest pins 8 virtual devices);
``hist_backend="pallas"`` routes through the interpret-mode kernels so
the wired gate admits the config.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

import dryad_tpu as dryad
from dryad_tpu.config import make_params
from dryad_tpu.datasets import higgs_like

# NOTE: only the mesh tests carry the `distributed` marker (it means
# "multi-device shard_map/psum" per pytest.ini) — the wired-vs-legacy
# parity pins below are single-device and must survive a
# `-m 'not distributed'` run.

# r19: the whole module is `slow` — its interpret-mode sharded compute
# pays the mandated run-bookkeeping tiles in Python across 8 virtual
# devices, which on the 2-core CI container pushed tier-1 past its 870 s
# budget (the seed tree's rc=124).  ci.sh runs tier-1 with `-m 'not
# slow'`; run this module explicitly (or the full unfiltered suite) on a
# wider host when touching leafperm or the wired growers.
pytestmark = pytest.mark.slow

# depth 6 > d_switch (both fori phases traced) with P_full = 32
# candidates: the tree runs wired from the root through both phase widths
_DEEP = dict(objective="binary", num_trees=2, num_leaves=64, max_bins=32,
             growth="depthwise", max_depth=6, hist_backend="pallas")


@pytest.fixture(scope="module")
def mesh():
    from dryad_tpu.engine.distributed import make_mesh

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(jax.devices()[:8])


def _gate_active(p, ds):
    from dryad_tpu.engine.levelwise import deep_layout_supported, phase_plan

    F = ds.X_binned.shape[1]
    B = int(ds.mapper.total_bins)
    d_switch, _, _ = phase_plan(p.max_depth, p.effective_num_leaves, True)
    return (deep_layout_supported(p, F, B, ds.X_binned.dtype.itemsize, "cpu")
            and d_switch < p.max_depth)


def test_wired_gate_admits_fixture():
    """The fixture must actually exercise the wired path — if the gate
    stops admitting it, this file would silently test the legacy path."""
    X, y = higgs_like(1024, seed=47)
    ds = dryad.Dataset(X, y, max_bins=32)
    assert _gate_active(make_params(_DEEP), ds)


@pytest.mark.distributed
def test_sharded_wired_deep_phase_parity(mesh):
    """N-shard ≡ 1-shard through the wired deep phase."""
    from dryad_tpu.engine.train import train_device

    X, y = higgs_like(4096, seed=47)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = make_params(_DEEP)
    assert _gate_active(p, ds)
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    for k in ("feature", "threshold", "left", "right", "is_cat"):
        np.testing.assert_array_equal(
            b1.tree_arrays()[k], b8.tree_arrays()[k],
            err_msg=f"wired deep phase: sharded vs single-device {k!r}")
    np.testing.assert_allclose(b1.value, b8.value, atol=1e-3)


@pytest.mark.distributed
def test_sharded_wired_with_padding_and_bagging(mesh):
    """Mesh-padded rows (N % 8 != 0) and out-of-bag rows enter the
    root-anchored layout sentinel-flagged and are dropped by level 0's
    move (never carried as dead weight) — sharded trees still match
    single-device."""
    from dryad_tpu.engine.train import train_device

    # seed chosen tie-free: deep bagged levels on this shape carry a few
    # fp32 near-tie gains whose argmax the psum reduction order can flip
    # (documented tolerance class — seeds 31/53/61 flip ONE node in BOTH
    # the wired and the legacy arm identically; not a layout property)
    X, y = higgs_like(4001, seed=43)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = make_params(dict(_DEEP, num_trees=2, subsample=0.7, seed=3,
                         min_data_in_leaf=5))
    assert _gate_active(p, ds)
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    np.testing.assert_array_equal(b1.feature, b8.feature)
    np.testing.assert_array_equal(b1.threshold, b8.threshold)


# batched leaf-wise expansion (r10 wiring): heap-node run bookkeeping with
# sentinel HN instead of leaf slots, run capacity 2^D — the second consumer
# of the carried layout
_LEAF = dict(objective="binary", num_trees=2, num_leaves=48, max_bins=32,
             growth="leafwise", max_depth=6, hist_backend="pallas")


def _leaf_gate_active(p, ds):
    from dryad_tpu.engine.leafwise_fast import (
        leafwise_layout_supported, supports,
    )

    F = ds.X_binned.shape[1]
    B = int(ds.mapper.total_bins)
    return (supports(p, F, B, ds.X_binned.shape[0])
            and leafwise_layout_supported(p, F, B,
                                          ds.X_binned.dtype.itemsize, "cpu"))


def test_leafwise_gate_admits_fixture():
    """The leaf-wise fixtures below must exercise the wired expansion —
    same canary as test_wired_gate_admits_fixture for the levelwise file."""
    X, y = higgs_like(1024, seed=47)
    ds = dryad.Dataset(X, y, max_bins=32)
    assert _leaf_gate_active(make_params(_LEAF), ds)


@pytest.mark.distributed
def test_sharded_wired_leafwise_parity(mesh):
    """N-shard ≡ 1-shard through the WIRED batched leaf-wise expansion:
    each shard carries its own root-anchored layout; the fused psum inside
    the histogram builders stays the only collective."""
    from dryad_tpu.engine.train import train_device

    X, y = higgs_like(4096, seed=47)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = make_params(_LEAF)
    assert _leaf_gate_active(p, ds)
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    for k in ("feature", "threshold", "left", "right", "is_cat"):
        np.testing.assert_array_equal(
            b1.tree_arrays()[k], b8.tree_arrays()[k],
            err_msg=f"wired leafwise: sharded vs single-device {k!r}")
    np.testing.assert_allclose(b1.value, b8.value, atol=1e-3)


@pytest.mark.distributed
def test_sharded_wired_leafwise_padding_and_bagging(mesh):
    """Mesh-padded rows (N % 8 != 0) and out-of-bag rows enter the
    root-anchored layout as sentinel-flagged records and are dropped by
    level 0's move — sharded wired leaf-wise trees still match
    single-device (wired vs legacy single-device parity lives in
    test_leafwise_fast.py::test_wired_batched_equals_legacy_batched)."""
    from dryad_tpu.engine.train import train_device

    X, y = higgs_like(4001, seed=43)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = make_params(dict(_LEAF, subsample=0.7, seed=3, min_data_in_leaf=5))
    assert _leaf_gate_active(p, ds)
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    np.testing.assert_array_equal(b1.feature, b8.feature)
    np.testing.assert_array_equal(b1.threshold, b8.threshold)


def test_wired_multi_level_chain_matches_legacy():
    """Depth 7 = TWO chained wired levels: the run bookkeeping must
    survive level-to-level advancement (advance_runs' renumbering, empty
    mandatory segments absorbed, right children appended in run order) —
    single-level fixtures cannot catch a chain bug.  min_data_in_leaf=2
    keeps deep levels splitting under the 128-leaf budget."""
    from dryad_tpu.engine.train import train_device

    X, y = higgs_like(4000, seed=29)
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(objective="binary", num_trees=1, num_leaves=128,
                max_bins=32, growth="depthwise", max_depth=7,
                hist_backend="pallas", min_data_in_leaf=2)
    bw = train_device(make_params(base), ds)
    bl = train_device(make_params(dict(base, deep_layout="legacy")), ds)
    for k in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(
            bw.tree_arrays()[k], bl.tree_arrays()[k], err_msg=k)
    np.testing.assert_allclose(bw.value, bl.value, atol=1e-5)
    # deep levels actually split (the chain was exercised, not skipped)
    assert int((np.asarray(bw.feature) >= 0).sum()) > 63


def test_wired_cat_missing_multiclass_matches_legacy():
    """The layout side derivation's categorical-bitset and learned-
    missing branches (packed_route bits 29/30) plus multiclass trees:
    wired and legacy deep phases must agree bitwise on structures."""
    from dryad_tpu.engine.train import train_device

    rng = np.random.default_rng(3)
    N = 3000
    X = rng.normal(size=(N, 8)).astype(np.float32)
    X[:, 3] = rng.integers(0, 12, N)
    X[rng.random((N, 8)) < 0.1] = np.nan       # learned default direction
    y = (((X[:, 0] > 0) | (np.nan_to_num(X[:, 3]) > 6)).astype(np.float32)
         + (X[:, 1] > 1))
    ds = dryad.Dataset(X, y, max_bins=32, categorical_features=[3])
    base = dict(objective="multiclass", num_class=3, num_trees=1,
                num_leaves=64, max_bins=32, growth="depthwise", max_depth=6,
                hist_backend="pallas", categorical_features=[3])
    bw = train_device(make_params(base), ds)
    bl = train_device(make_params(dict(base, deep_layout="legacy")), ds)
    for k in ("feature", "threshold", "left", "right", "is_cat",
              "cat_bitset", "default_left"):
        np.testing.assert_array_equal(
            bw.tree_arrays()[k], bl.tree_arrays()[k], err_msg=k)
    np.testing.assert_allclose(bw.value, bl.value, atol=1e-5)


def test_wired_no_subtraction_matches_legacy():
    """The r10 exclusion LIFT: ``hist_subtraction=False`` now rides the
    wired path too — the level histograms BOTH children in one 2P-column
    ``hist_from_layout`` pass over the new layout's contiguous runs
    instead of falling back to the legacy small-pass + full
    ``build_hist_multi`` pair.  Cited by name in
    ``deep_layout_supported``'s verdict list; pins the gate edge AND
    tree parity vs the legacy arm.

    Seed chosen tie-free: the wired no-subtraction arm is the only one
    summing BOTH children in post-permute layout order (legacy sums in
    natural order, the subtraction arms derive the large child by
    parent-minus-small), so its grad/hess sums sit an ulp apart from
    every other arm's and deep near-tie argmaxes can flip (seeds 59/47
    flip 1-2 deep nodes, cascading; 43/29/53/61/7 are clean — the
    documented program-shape tolerance class, counts stay exact per
    test_leafperm's hist_from_layout oracles)."""
    from dryad_tpu.engine.levelwise import deep_layout_supported
    from dryad_tpu.engine.train import train_device

    X, y = higgs_like(4096, seed=43)
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(_DEEP, hist_subtraction=False)
    p_w = make_params(base)
    assert deep_layout_supported(p_w, ds.X_binned.shape[1],
                                 int(ds.mapper.total_bins),
                                 ds.X_binned.dtype.itemsize, "cpu"), \
        "the hist_subtraction=False exclusion regressed (r10 lift)"
    bw = train_device(p_w, ds)
    bl = train_device(make_params(dict(base, deep_layout="legacy")), ds)
    for k in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(
            bw.tree_arrays()[k], bl.tree_arrays()[k],
            err_msg=f"wired (no-subtraction) vs legacy {k!r}")
    np.testing.assert_allclose(bw.value, bl.value, atol=1e-5)


def test_wired_matches_legacy_trees():
    """Wired vs legacy deep phase on the tie-free fixture: identical
    structures (the smoke gate's on-device assertion, pinned in CI too).
    Histogram sums regroup at ulp level between the two paths (documented
    tolerance class), so values compare to fp32 tolerance."""
    from dryad_tpu.engine.train import train_device

    X, y = higgs_like(4096, seed=59)
    ds = dryad.Dataset(X, y, max_bins=32)
    p_w = make_params(_DEEP)
    p_l = make_params(dict(_DEEP, deep_layout="legacy"))
    assert _gate_active(p_w, ds)
    bw = train_device(p_w, ds)
    bl = train_device(p_l, ds)
    for k in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(
            bw.tree_arrays()[k], bl.tree_arrays()[k],
            err_msg=f"wired vs legacy {k!r}")
    np.testing.assert_allclose(bw.value, bl.value, atol=1e-5)
