"""max_bins > 256: the uint16 bin path through sketch, both growers and
predict (the Pallas kernel supports <= 1024 bins; beyond that the XLA
histogram path takes over automatically)."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import covertype_like, higgs_like
from dryad_tpu.metrics import auc


def test_uint16_bins_cpu_tpu_parity():
    # seed chosen tie-free for the CURRENT container's XLA too: the old
    # seed 91 carried one fp32 near-tie gain whose argmax the 0.4.x CPU
    # lowering resolves differently from the f64 oracle (the documented
    # CLAUDE.md tolerance class; parity pins require tie-free fixtures)
    X, y = higgs_like(4000, seed=97)
    ds = dryad.Dataset(X, y, max_bins=512)
    assert ds.X_binned.dtype == np.uint16
    p = dict(objective="binary", num_trees=5, num_leaves=15, max_bins=512,
             growth="depthwise", max_depth=4)
    b_cpu = dryad.train(p, ds, backend="cpu")
    b_tpu = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(b_cpu.feature, b_tpu.feature)
    np.testing.assert_array_equal(b_cpu.threshold, b_tpu.threshold)
    np.testing.assert_allclose(b_cpu.value, b_tpu.value, atol=1e-2)
    # bit-identity: the SAME booster must predict identically on both backends
    np.testing.assert_array_equal(
        b_tpu.predict_binned(ds.X_binned, backend="cpu"),
        b_tpu.predict_binned(ds.X_binned, backend="tpu"))


def test_bins_beyond_pallas_cap_fall_back():
    X, y = higgs_like(2000, seed=93)
    ds = dryad.Dataset(X, y, max_bins=2048)
    p = dict(objective="binary", num_trees=3, num_leaves=7, max_bins=2048,
             growth="depthwise", max_depth=3, hist_backend="auto")
    b = dryad.train(p, ds, backend="tpu")
    assert auc(y, b.predict_binned(ds.X_binned)) > 0.6


def test_weighted_multiclass_depthwise():
    X, y = covertype_like(4000, seed=95)
    w = np.random.default_rng(95).uniform(0.5, 2.0, size=4000).astype(np.float32)
    ds = dryad.Dataset(X, y, weight=w, max_bins=64)
    p = dict(objective="multiclass", num_class=7, num_trees=3, num_leaves=15,
             growth="depthwise", max_depth=4, max_bins=64)
    b_cpu = dryad.train(p, ds, backend="cpu")
    b_tpu = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(b_cpu.feature, b_tpu.feature)
    pred = b_tpu.predict_binned(ds.X_binned)
    assert (pred.argmax(1) == y).mean() > 0.5
