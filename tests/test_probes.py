"""The canonical timed-fori harness (engine/probes) + the jax-free
profiler aggregation (obs/profiler).

Pins the r13 contracts: the runtime liveness proof REJECTS dead
perturbations (rounded-away casts, hoisted stages, order-symmetric
periodic walks) and passes live ones; probe results flow into
``dryad_stage_ms`` gauges and the stamped PROFILE artifact shape the
trend ledger ingests; the CLI selftest catches the seeded dead probe.

Probe executions here use tiny shapes (the suite budget rule:
interpret-mode pallas fixtures pay per-tile Python) — the full registry
sweep lives in ``python -m dryad_tpu profile --selftest`` (ci.sh).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dryad_tpu.engine import probes
from dryad_tpu.engine.probes import (
    DeadProbeError,
    dead_probe_step,
    run_probe,
    timed_fori,
)
from dryad_tpu.obs import Registry
from dryad_tpu.obs.profiler import (
    export_stages,
    profile_artifact,
    write_profile,
)

ROOT = __file__.rsplit("/tests/", 1)[0]


# ---- the harness ------------------------------------------------------------

def test_live_probe_times_and_reports_spread():
    x = jnp.asarray(np.random.default_rng(0).normal(size=2048)
                    .astype(np.float32))

    def step(s, x):
        y = jnp.sort(x + 0.125 * (s - jnp.floor(s / 8.0) * 8.0))
        return s + 1.0, y[0] + y[-1]

    ms, spread = timed_fori(step, 2, 2, x, label="live-sort")
    assert ms > 0.0 and spread >= 0.0


def test_dead_probe_rejected_at_runtime():
    """The seeded r5/r10 failure class MUST raise — the ISSUE's liveness
    acceptance, in-process."""
    x = jnp.asarray(np.random.default_rng(1).normal(size=2048)
                    .astype(np.float32))
    with pytest.raises(DeadProbeError, match="DEAD"):
        timed_fori(dead_probe_step(), 2, 1, x, label="seeded-dead")


def test_hoisted_stage_rejected():
    """A stage fed only by non-carried inputs (the r10 LICM class): the
    step ignores s entirely."""
    x = jnp.asarray(np.random.default_rng(2).normal(size=2048)
                    .astype(np.float32))

    def step(s, x):
        return s + 1.0, jnp.sort(x)[0]

    with pytest.raises(DeadProbeError):
        timed_fori(step, 2, 1, x, label="hoisted")


def test_period_symmetric_perturbation_rejected():
    """A period-2 walk under K=2 yields the same contrib MULTISET at both
    seeds (the accumulator is order-independent) — must read as dead."""
    x = jnp.asarray(np.random.default_rng(3).normal(size=1024)
                    .astype(np.float32))

    def step(s, x):
        par = s - jnp.floor(s / 2.0) * 2.0
        return s + 1.0, jnp.sort(x + par)[0]

    with pytest.raises(DeadProbeError):
        timed_fori(step, 2, 1, x, label="period-2")


def test_nonfinite_contrib_rejected():
    x = jnp.asarray(np.ones(16, np.float32))

    def step(s, x):
        return s + 1.0, jnp.log(x[0] * 0.0 - s * 0.0 - 1.0)  # nan

    with pytest.raises(DeadProbeError, match="non-finite"):
        timed_fori(step, 2, 1, x, label="nan-probe")


def test_check_live_false_skips_the_proof():
    x = jnp.asarray(np.zeros(64, np.float32))

    def step(s, x):
        return s + 1.0, jnp.sort(x)[0]        # dead, but unchecked

    ms, _ = timed_fori(step, 2, 1, x, label="unchecked", check_live=False)
    assert ms > 0.0


# ---- the registry probes (tiny-shape spot checks) ---------------------------

def test_registry_probe_runs_and_reports():
    # ONE representative probe end to end; the full registry sweep rides
    # test_selftest_passes_in_process below (and the ci.sh gate) — no
    # need to pay a second compile per probe against the suite budget
    r = run_probe("renewal_sort", rows=2048, K=2, reps=1, num_slots=8)
    assert r["stage"] == "renewal_sort" and r["ms"] > 0.0
    assert r["platform"] == "cpu" and r["rows"] == 2048


def test_k_at_walk_period_rejected_loudly():
    """K >= the probes' period-8 walk makes both liveness windows the
    same multiset — run_probe must fail the CONFIGURATION, not report a
    misleading 'dead stage'."""
    with pytest.raises(ValueError, match="walk period"):
        run_probe("split_scan", rows=512, K=probes.WALK_PERIOD, reps=1,
                  num_slots=4)
    # the escape hatch still times
    r = run_probe("renewal_sort", rows=512, K=probes.WALK_PERIOD, reps=1,
                  num_slots=4, check_live=False)
    assert r["ms"] > 0.0


def test_registry_covers_the_issue_stages():
    need = {"hist_masked", "hist_segmented", "split_scan",
            "permute_records", "hist_from_layout", "route_gather",
            "predict_traversal", "goss_sort", "renewal_sort"}
    assert need <= set(probes.PROBES)
    assert set(probes.SMOKE_PROBES) <= set(probes.PROBES)


def test_selftest_passes_in_process(capsys):
    """The full gate, exactly what ci.sh runs: dead probe caught, every
    shipped probe liveness-proven."""
    assert probes.run_selftest(rows=2048, num_slots=4, quiet=True) == 0
    out = capsys.readouterr().out
    assert "PROFILE SELFTEST OK" in out


# ---- the jax-free aggregation (obs/profiler) --------------------------------

RESULTS = [
    {"stage": "hist_segmented", "ms": 136.2, "spread": 0.02, "rows": 10_000},
    {"stage": "deep_level", "arm": "wired", "ms": 51.4, "spread": 0.01,
     "rows": 10_000},
]


def test_export_stages_gauges():
    reg = Registry()
    assert export_stages(RESULTS, reg) == 2
    fam = reg.gauge("dryad_stage_ms")
    assert fam.labels(stage="hist_segmented").value() == 136.2
    assert fam.labels(stage="deep_level", arm="wired").value() == 51.4
    sp = reg.gauge("dryad_stage_spread")
    assert sp.labels(stage="hist_segmented").value() == 0.02
    # zero-cost disabled: nothing recorded
    assert export_stages(RESULTS, Registry(enabled=False)) == 0


def test_profile_artifact_shape_and_stamp(tmp_path):
    art = write_profile(RESULTS, str(tmp_path / "PROFILE_r01.json"),
                        device_kind="cpu", root=ROOT)
    assert art["stage_ms_hist_segmented"] == 136.2
    assert art["stage_spread_hist_segmented"] == 0.02
    assert art["stage_ms_deep_level_wired"] == 51.4
    assert art["stage_rows_deep_level_wired"] == 10_000
    assert art["profile_schema"] == 1
    assert art["schema_version"] == 1 and art["git_rev"]
    import json

    on_disk = json.loads((tmp_path / "PROFILE_r01.json").read_text())
    assert on_disk == art


def test_profile_artifact_unstamped_outside_git(tmp_path):
    art = profile_artifact(RESULTS, root=str(tmp_path))
    assert art["git_rev"] is None       # best-effort stamp, never raises
