"""Learned per-node missing-value default direction (SURVEY.md §2 #3-6:
LightGBM/XGBoost-family engines learn which child missing rows follow)."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.metrics import auc


def _informative_missing(n=4000, seed=11):
    """Missing x0 behaves like LARGE x0: y = (x0 > 1) OR isnan(x0).

    A single stump can only be consistent with this rule by sending missing
    RIGHT at the x0 <= 1 split — the always-left rule needs two levels."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4)).astype(np.float32)
    miss = rng.random(n) < 0.3
    y = ((X[:, 0] > 1.0) | miss).astype(np.float32)
    X[miss, 0] = np.nan
    return X, y


def test_stump_learns_missing_right():
    X, y = _informative_missing()
    ds = dryad.Dataset(X, y, max_bins=64)
    assert ds.has_missing
    b = dryad.train(dict(objective="binary", num_trees=1, num_leaves=2,
                         max_depth=1, max_bins=64, learning_rate=1.0,
                         min_data_in_leaf=1), ds, backend="cpu")
    # the root must split on x0 with missing sent right
    assert b.feature[0, 0] == 0
    assert not b.default_left[0, 0]
    # and that stump separates the classes essentially perfectly
    a = auc(y, b.predict(X))
    assert a > 0.99


@pytest.mark.parametrize("growth", ["leafwise", "depthwise"])
def test_missing_direction_cpu_tpu_parity(growth):
    rng = np.random.default_rng(13)
    X = rng.normal(size=(3000, 6)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] + 0.3 * rng.normal(size=3000) > 0).astype(np.float32)
    X[rng.random(X.shape) < 0.2] = np.nan  # 20% missing everywhere
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective="binary", num_trees=10, num_leaves=15, max_bins=32)
    if growth == "depthwise":
        p.update(growth="depthwise", max_depth=4)
    b_cpu = dryad.train(p, ds, backend="cpu")
    b_tpu = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(b_cpu.feature, b_tpu.feature)
    np.testing.assert_array_equal(b_cpu.threshold, b_tpu.threshold)
    np.testing.assert_array_equal(b_cpu.default_left, b_tpu.default_left)
    # bit-identical predict on the same booster across backends
    np.testing.assert_array_equal(
        b_cpu.predict_binned(ds.X_binned, raw_score=True, backend="cpu"),
        b_cpu.predict_binned(ds.X_binned, raw_score=True, backend="tpu"),
    )
    # some direction bit must actually have been learned on this data
    internal = b_cpu.feature >= 0
    assert (~b_cpu.default_left[internal]).any()


def test_missing_free_data_keeps_all_left():
    rng = np.random.default_rng(17)
    X = rng.normal(size=(2000, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32)
    assert not ds.has_missing
    b = dryad.train(dict(objective="binary", num_trees=5, num_leaves=7,
                         max_bins=32), ds, backend="cpu")
    assert b.default_left.all()


def test_save_load_roundtrip_preserves_direction(tmp_path):
    X, y = _informative_missing(seed=19)
    ds = dryad.Dataset(X, y, max_bins=64)
    b = dryad.train(dict(objective="binary", num_trees=8, num_leaves=15,
                         max_bins=64), ds, backend="cpu")
    path = str(tmp_path / "m.dryad")
    b.save(path)
    b2 = dryad.Booster.load(path)
    np.testing.assert_array_equal(b.default_left, b2.default_left)
    np.testing.assert_array_equal(b.predict(X, raw_score=True),
                                  b2.predict(X, raw_score=True))


def test_native_predict_honors_direction():
    from dryad_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    X, y = _informative_missing(seed=23)
    ds = dryad.Dataset(X, y, max_bins=64)
    b = dryad.train(dict(objective="binary", num_trees=6, num_leaves=15,
                         max_bins=64), ds, backend="cpu")
    internal = b.feature >= 0
    assert (~b.default_left[internal]).any()
    got = native.predict_accumulate(
        np.ascontiguousarray(ds.X_binned, np.uint16), b.tree_arrays(),
        b.init_score, b.num_total_trees, 1, b.max_depth_seen)
    from dryad_tpu.cpu.predict import predict_tree_leaves

    want = np.broadcast_to(b.init_score, (X.shape[0], 1)).astype(np.float32).copy()
    for t in range(b.num_total_trees):
        leaves = predict_tree_leaves(b.tree_arrays(), ds.X_binned, t,
                                     b.max_depth_seen)
        want[:, 0] += b.value[t, leaves]
    np.testing.assert_array_equal(got, want)
