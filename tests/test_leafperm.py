"""Leaf-ordered permutation kernel (engine/leafperm.py): bitwise equality
with the numpy oracle in interpret mode, layout invariants, and the
multi-level refinement chain."""

import numpy as np
import pytest

import jax.numpy as jnp

from dryad_tpu.engine import leafperm

T = leafperm._TILE_ROWS


def _mk_layout(rng, seg_counts, WB=64):
    """Build a tile-aligned layout: records with distinctive bytes,
    sentinel rows zero.  Returns (rec, tile_slot, row_seg)."""
    lt = np.maximum(-(-np.asarray(seg_counts) // T), 1)
    n_tiles = int(lt.sum())
    rec = np.zeros((n_tiles * T, WB), np.uint8)
    tile_slot = np.repeat(np.arange(len(seg_counts)), lt).astype(np.int32)
    row_seg = np.full(n_tiles * T, -1, np.int32)
    base = np.concatenate([[0], np.cumsum(lt)])
    rid = 0
    for s, cnt in enumerate(seg_counts):
        r0 = base[s] * T
        for j in range(cnt):
            rec[r0 + j] = rng.integers(1, 255, WB, dtype=np.uint8)
            row_seg[r0 + j] = s
            rid += 1
    return rec, tile_slot, row_seg


def _sides(rng, row_seg, p_right=0.5):
    """Random left/right per real row; sentinel rows get 2."""
    side = np.where(row_seg >= 0,
                    (rng.random(row_seg.size) < p_right).astype(np.int32),
                    2).astype(np.int32)
    return side


def _counts(row_seg, side, n_seg):
    cl = np.zeros(n_seg, np.int32)
    cr = np.zeros(n_seg, np.int32)
    for s, sd in zip(row_seg, side):
        if s >= 0:
            if sd == 0:
                cl[s] += 1
            elif sd == 1:
                cr[s] += 1
    return cl, cr


@pytest.mark.parametrize("seg_counts,p_right", [
    ([700, 3, 1200, 0, 513], 0.5),      # ragged, incl. empty segment
    ([2048], 0.0),                      # pass-through (all left)
    ([100, 100, 100], 1.0),             # all right
    ([1, 1, 1, 1], 0.5),                # tiny segments, all mandatory pads
])
def test_permute_matches_oracle(seg_counts, p_right):
    rng = np.random.default_rng(hash((tuple(seg_counts), p_right)) % 2**31)
    rec, tile_slot, row_seg = _mk_layout(rng, seg_counts)
    side = _sides(rng, row_seg, p_right)
    cl, cr = _counts(row_seg, side, len(seg_counts))

    pos, dstl, dstr, base_l, base_r, n_out = leafperm.level_moves(
        jnp.asarray(tile_slot), jnp.asarray(side),
        jnp.asarray(cl), jnp.asarray(cr))
    bound = leafperm.tiles_bound(rec.shape[0], len(seg_counts))
    assert int(n_out) <= bound
    got = np.asarray(leafperm.permute_records(
        jnp.asarray(rec), pos, dstl, dstr, bound))
    want = leafperm.permute_records_np(rec, tile_slot, side, cl, cr, bound)
    np.testing.assert_array_equal(got[: int(n_out) * T],
                                  want[: int(n_out) * T])


def test_multi_level_chain():
    """Three refinement levels keep every real record exactly once and
    all pads zero — the invariant the grower integration relies on."""
    rng = np.random.default_rng(7)
    seg_counts = [5000, 2000]
    rec, tile_slot, row_seg = _mk_layout(rng, seg_counts)
    orig = {bytes(r) for r in rec if r.any()}
    for level in range(3):
        n_seg = int(tile_slot.max()) + 1
        side = _sides(rng, row_seg, 0.4)
        cl, cr = _counts(row_seg, side, n_seg)
        pos, dstl, dstr, base_l, base_r, n_out = leafperm.level_moves(
            jnp.asarray(tile_slot), jnp.asarray(side),
            jnp.asarray(cl), jnp.asarray(cr))
        bound = leafperm.tiles_bound(rec.shape[0], n_seg)
        rec = np.asarray(leafperm.permute_records(
            jnp.asarray(rec), pos, dstl, dstr, bound))[: int(n_out) * T]
        # rebuild bookkeeping for the next level from the returned bases:
        # every child AND each slack tile becomes its own segment (slack
        # = an empty segment: its rows are all sentinels), in LAYOUT order
        base_l, base_r = np.asarray(base_l), np.asarray(base_r)
        n_tiles = rec.shape[0] // T
        seg_list = (
            [(int(base_l[k]), int(cl[k])) for k in range(n_seg)]
            + [(int(base_l[-1]), 0)]                     # left slack
            + [(int(base_r[k]), int(cr[k])) for k in range(n_seg)]
            + [(int(base_r[-1]), 0)]                     # right slack
        )
        seg_list.sort(key=lambda t: t[0])
        tile_slot = np.zeros(n_tiles, np.int32)
        row_seg = np.full(n_tiles * T, -1, np.int32)
        for newid, (b, c) in enumerate(seg_list):
            lt = max(-(-c // T), 1)
            tile_slot[b:b + lt] = newid
            row_seg[b * T: b * T + c] = newid
        got = {bytes(r) for r in rec if r.any()}
        assert got == orig, f"level {level}: record set changed"
        # every row outside a segment's count range is a zero sentinel
        live = np.zeros(rec.shape[0], bool)
        for b, c in seg_list:
            live[b * T: b * T + c] = True
        assert not rec[~live].any(), f"level {level}: nonzero pad rows"


def test_stability_within_side():
    """Rows keep their source order within (segment, side) — the grower's
    determinism (and CPU parity) depends on stable partition."""
    rng = np.random.default_rng(3)
    cnt = 1500
    rec, tile_slot, row_seg = _mk_layout(rng, [cnt])
    # tag rows with their index in bytes 0..3 to check ordering
    idx = np.arange(cnt, dtype=np.uint32)
    rec[:cnt, :4] = idx.view(np.uint8).reshape(cnt, 4)
    side = _sides(rng, row_seg, 0.5)
    cl, cr = _counts(row_seg, side, 1)
    pos, dstl, dstr, base_l, base_r, n_out = leafperm.level_moves(
        jnp.asarray(tile_slot), jnp.asarray(side),
        jnp.asarray(cl), jnp.asarray(cr))
    bound = leafperm.tiles_bound(rec.shape[0], 1)
    out = np.asarray(leafperm.permute_records(
        jnp.asarray(rec), pos, dstl, dstr, bound))
    lrows = out[: int(cl[0])]
    rrows = out[int(base_r[0]) * T: int(base_r[0]) * T + int(cr[0])]
    lidx = lrows[:, :4].copy().view(np.uint32).ravel()
    ridx = rrows[:, :4].copy().view(np.uint32).ravel()
    assert (np.diff(lidx) > 0).all()
    assert (np.diff(ridx) > 0).all()
    np.testing.assert_array_equal(np.sort(np.concatenate([lidx, ridx])), idx)
