"""Leaf-ordered permutation kernel (engine/leafperm.py): bitwise equality
with the numpy oracle in interpret mode, layout invariants, and the
multi-level refinement chain — with the _ALIGN-rounded per-tile
contributions Mosaic's HBM slicing requires."""

import numpy as np
import pytest

import jax.numpy as jnp

from dryad_tpu.engine import leafperm

T = leafperm._TILE_ROWS


def _mk_layout(rng, seg_counts, WB=64):
    """Tile-aligned layout with contiguous-prefix segments (the level-0
    shape): distinctive record bytes, zero sentinels."""
    lt = np.maximum(-(-np.asarray(seg_counts) // T), 1)
    n_tiles = int(lt.sum())
    rec = np.zeros((n_tiles * T, WB), np.uint8)
    tile_slot = np.repeat(np.arange(len(seg_counts)), lt).astype(np.int32)
    row_seg = np.full(n_tiles * T, -1, np.int32)
    base = np.concatenate([[0], np.cumsum(lt)])
    for s, cnt in enumerate(seg_counts):
        r0 = base[s] * T
        rec[r0:r0 + cnt] = rng.integers(1, 255, (cnt, WB), dtype=np.uint8)
        row_seg[r0:r0 + cnt] = s
    return rec, tile_slot, row_seg


def _sides(rng, row_seg, p_right=0.5):
    return np.where(row_seg >= 0,
                    (rng.random(row_seg.size) < p_right).astype(np.int32),
                    2).astype(np.int32)


def _run_level(rec, tile_slot, side, n_seg):
    pos, dstl, dstr, base_l, base_r, n_out = leafperm.level_moves(
        jnp.asarray(tile_slot), jnp.asarray(side), n_seg)
    bound = leafperm.tiles_bound(rec.shape[0], n_seg)
    assert int(n_out) <= bound, (int(n_out), bound)
    got = np.asarray(leafperm.permute_records(
        jnp.asarray(rec), pos, dstl, dstr, bound))
    want, ts_new, rs_new = leafperm.permute_records_np(
        rec, tile_slot, side, n_seg, bound)
    return got, want, ts_new, rs_new, int(n_out)


@pytest.mark.parametrize("seg_counts,p_right", [
    ([700, 3, 1200, 0, 513], 0.5),      # ragged, incl. empty segment
    ([2048], 0.0),                      # pass-through (all left)
    ([100, 100, 100], 1.0),             # all right
    ([1, 1, 1, 1], 0.5),                # tiny segments, all mandatory pads
])
def test_permute_matches_oracle(seg_counts, p_right):
    rng = np.random.default_rng(hash((tuple(seg_counts), p_right)) % 2**31)
    rec, tile_slot, row_seg = _mk_layout(rng, seg_counts)
    side = _sides(rng, row_seg, p_right)
    got, want, _, _, n_out = _run_level(rec, tile_slot, side,
                                        len(seg_counts))
    np.testing.assert_array_equal(got[: n_out * T], want[: n_out * T])


def test_multi_level_chain():
    """Three refinement levels keep every real record exactly once, all
    pads zero, and the kernel bitwise-equal to the oracle at each level
    (the oracle's returned tile/segment maps drive the next level — the
    exact bookkeeping a grower integration would)."""
    rng = np.random.default_rng(7)
    rec, tile_slot, row_seg = _mk_layout(rng, [5000, 2000])
    orig = {bytes(r) for r in rec if r.any()}
    n_seg = 2
    for level in range(3):
        side = _sides(rng, row_seg, 0.4)
        got, want, ts_new, rs_new, n_out = _run_level(
            rec, tile_slot, side, n_seg)
        np.testing.assert_array_equal(got[: n_out * T], want[: n_out * T])
        rec = want[: n_out * T]
        tile_slot = ts_new[: n_out].astype(np.int32)
        row_seg = rs_new[: n_out * T].astype(np.int32)
        n_seg = 2 * n_seg
        assert {bytes(r) for r in rec if r.any()} == orig, \
            f"level {level}: record set changed"
        assert not rec[row_seg < 0].any(), f"level {level}: nonzero pads"


def test_stability_within_side():
    """Real rows keep their source order within (segment, side) — the
    grower's determinism (and CPU parity) rides on stable partition."""
    rng = np.random.default_rng(3)
    cnt = 1500
    rec, tile_slot, row_seg = _mk_layout(rng, [cnt])
    idx = np.arange(1, cnt + 1, dtype=np.uint32)     # nonzero tags
    rec[:cnt, :4] = idx.view(np.uint8).reshape(cnt, 4)
    side = _sides(rng, row_seg, 0.5)
    got, want, ts_new, rs_new, n_out = _run_level(rec, tile_slot, side, 1)
    np.testing.assert_array_equal(got[: n_out * T], want[: n_out * T])
    out = got[: n_out * T]
    rs = rs_new[: n_out * T]
    for seg in (0, 1):                               # left child, right child
        rows = out[rs == seg]
        tags = rows[:, :4].copy().view(np.uint32).ravel()
        assert (np.diff(tags) > 0).all(), f"segment {seg} order broken"
    all_tags = out[rs >= 0][:, :4].copy().view(np.uint32).ravel()
    np.testing.assert_array_equal(np.sort(all_tags), idx)


def test_alignment_of_all_writes():
    """Every destination offset is _ALIGN-divisible — the Mosaic HBM
    slicing constraint that forced the rounded layout (an arbitrary
    offset fails to lower: 'not divisible by the tiling (8)')."""
    rng = np.random.default_rng(9)
    rec, tile_slot, row_seg = _mk_layout(rng, [700, 3, 900])
    side = _sides(rng, row_seg, 0.37)
    pos, dstl, dstr, _, _, _ = leafperm.level_moves(
        jnp.asarray(tile_slot), jnp.asarray(side), 3)
    assert (np.asarray(dstl) % leafperm._ALIGN == 0).all()
    assert (np.asarray(dstr) % leafperm._ALIGN == 0).all()
