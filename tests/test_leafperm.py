"""Leaf-ordered permutation kernel (engine/leafperm.py): bitwise equality
with the numpy oracle in interpret mode, layout invariants, and the
multi-level refinement chain — with the _ALIGN-rounded per-tile
contributions Mosaic's HBM slicing requires."""

import numpy as np
import pytest

import jax.numpy as jnp

from dryad_tpu.engine import leafperm

T = leafperm._TILE_ROWS


def _mk_layout(rng, seg_counts, WB=64):
    """Tile-aligned layout with contiguous-prefix segments (the level-0
    shape): distinctive record bytes, zero sentinels."""
    lt = np.maximum(-(-np.asarray(seg_counts) // T), 1)
    n_tiles = int(lt.sum())
    rec = np.zeros((n_tiles * T, WB), np.uint8)
    tile_slot = np.repeat(np.arange(len(seg_counts)), lt).astype(np.int32)
    row_seg = np.full(n_tiles * T, -1, np.int32)
    base = np.concatenate([[0], np.cumsum(lt)])
    for s, cnt in enumerate(seg_counts):
        r0 = base[s] * T
        rec[r0:r0 + cnt] = rng.integers(1, 255, (cnt, WB), dtype=np.uint8)
        row_seg[r0:r0 + cnt] = s
    return rec, tile_slot, row_seg


def _sides(rng, row_seg, p_right=0.5):
    return np.where(row_seg >= 0,
                    (rng.random(row_seg.size) < p_right).astype(np.int32),
                    2).astype(np.int32)


def _run_level(rec, tile_slot, side, n_seg):
    pos, dstl, dstr, base_l, base_r, n_out = leafperm.level_moves(
        jnp.asarray(tile_slot), jnp.asarray(side), n_seg)
    bound = leafperm.tiles_bound(rec.shape[0], n_seg)
    assert int(n_out) <= bound, (int(n_out), bound)
    got = np.asarray(leafperm.permute_records(
        jnp.asarray(rec), pos, dstl, dstr, bound))
    want, ts_new, rs_new = leafperm.permute_records_np(
        rec, tile_slot, side, n_seg, bound)
    return got, want, ts_new, rs_new, int(n_out)


@pytest.mark.parametrize("seg_counts,p_right", [
    ([700, 3, 1200, 0, 513], 0.5),      # ragged, incl. empty segment
    ([2048], 0.0),                      # pass-through (all left)
    ([100, 100, 100], 1.0),             # all right
    ([1, 1, 1, 1], 0.5),                # tiny segments, all mandatory pads
])
def test_permute_matches_oracle(seg_counts, p_right):
    rng = np.random.default_rng(hash((tuple(seg_counts), p_right)) % 2**31)
    rec, tile_slot, row_seg = _mk_layout(rng, seg_counts)
    side = _sides(rng, row_seg, p_right)
    got, want, _, _, n_out = _run_level(rec, tile_slot, side,
                                        len(seg_counts))
    np.testing.assert_array_equal(got[: n_out * T], want[: n_out * T])


def test_multi_level_chain():
    """Three refinement levels keep every real record exactly once, all
    pads zero, and the kernel bitwise-equal to the oracle at each level
    (the oracle's returned tile/segment maps drive the next level — the
    exact bookkeeping a grower integration would)."""
    rng = np.random.default_rng(7)
    rec, tile_slot, row_seg = _mk_layout(rng, [5000, 2000])
    orig = {bytes(r) for r in rec if r.any()}
    n_seg = 2
    for level in range(3):
        side = _sides(rng, row_seg, 0.4)
        got, want, ts_new, rs_new, n_out = _run_level(
            rec, tile_slot, side, n_seg)
        np.testing.assert_array_equal(got[: n_out * T], want[: n_out * T])
        rec = want[: n_out * T]
        tile_slot = ts_new[: n_out].astype(np.int32)
        row_seg = rs_new[: n_out * T].astype(np.int32)
        n_seg = 2 * n_seg
        assert {bytes(r) for r in rec if r.any()} == orig, \
            f"level {level}: record set changed"
        assert not rec[row_seg < 0].any(), f"level {level}: nonzero pads"


def test_stability_within_side():
    """Real rows keep their source order within (segment, side) — the
    grower's determinism (and CPU parity) rides on stable partition."""
    rng = np.random.default_rng(3)
    cnt = 1500
    rec, tile_slot, row_seg = _mk_layout(rng, [cnt])
    idx = np.arange(1, cnt + 1, dtype=np.uint32)     # nonzero tags
    rec[:cnt, :4] = idx.view(np.uint8).reshape(cnt, 4)
    side = _sides(rng, row_seg, 0.5)
    got, want, ts_new, rs_new, n_out = _run_level(rec, tile_slot, side, 1)
    np.testing.assert_array_equal(got[: n_out * T], want[: n_out * T])
    out = got[: n_out * T]
    rs = rs_new[: n_out * T]
    for seg in (0, 1):                               # left child, right child
        rows = out[rs == seg]
        tags = rows[:, :4].copy().view(np.uint32).ravel()
        assert (np.diff(tags) > 0).all(), f"segment {seg} order broken"
    all_tags = out[rs >= 0][:, :4].copy().view(np.uint32).ravel()
    np.testing.assert_array_equal(np.sort(all_tags), idx)


def test_alignment_of_all_writes():
    """Every destination offset is _ALIGN-divisible — the Mosaic HBM
    slicing constraint that forced the rounded layout (an arbitrary
    offset fails to lower: 'not divisible by the tiling (8)')."""
    rng = np.random.default_rng(9)
    rec, tile_slot, row_seg = _mk_layout(rng, [700, 3, 900])
    side = _sides(rng, row_seg, 0.37)
    pos, dstl, dstr, _, _, _ = leafperm.level_moves(
        jnp.asarray(tile_slot), jnp.asarray(side), 3)
    assert (np.asarray(dstl) % leafperm._ALIGN == 0).all()
    assert (np.asarray(dstr) % leafperm._ALIGN == 0).all()


def test_wired_level_preserves_plan_order():
    """INTEGRATION contract (the wired deep phase rides on this, not just
    the kernel): after the handoff conversion (initial_layout) and one
    full wired level (level_moves -> permute_records -> advance_runs),
    every child segment holds its rows in the SAME stable row-id order
    the aligned tile plan would produce for that child's selection — the
    per-slot order convention shared by every histogram path."""
    rng = np.random.default_rng(33)
    N, L = 5000, 8
    WB = leafperm._REC_WB
    slot_of = rng.integers(0, 4, N).astype(np.int32)   # slots 0..3 live
    bag = rng.random(N) < 0.8
    # records tagged with the row id so order is observable
    rec_nat = np.zeros((N, WB), np.uint8)
    rec_nat[:, :4] = np.arange(1, N + 1, dtype=np.uint32).view(
        np.uint8).reshape(N, 4)
    rec_nat[:, 8] = 1                                  # valid flag

    import jax.numpy as jnp

    n_buf = leafperm.wired_tiles_bound(-(-N // T), L)
    sel = np.where(bag, slot_of, L).astype(np.int32)
    live = np.zeros(L, bool)
    live[:4] = True
    rec_lay, tile_run, run_slot = leafperm.initial_layout(
        jnp.asarray(rec_nat), jnp.asarray(sel), jnp.asarray(live), L, n_buf)
    assert [int(run_slot[r]) for r in range(4)] == [0, 1, 2, 3]

    # one level: slots 0 and 2 split (right children -> slots 4, 5)
    thr = 0.5
    u = rng.random(N)
    go_right = {0: u < thr, 2: u < 0.3}
    row_run = np.repeat(np.asarray(tile_run), T)
    rs_lay = np.asarray(run_slot)[row_run]
    tags_lay = np.asarray(rec_lay)[:, :4].copy().view(np.uint32).ravel()
    valid_lay = np.asarray(rec_lay)[:, 8] == 1
    side = np.full(n_buf * T, 2, np.int32)
    for i in np.nonzero(valid_lay)[0]:
        s = rs_lay[i]
        rid = int(tags_lay[i]) - 1
        if s in go_right:
            side[i] = 1 if go_right[s][rid] else 0
        else:
            side[i] = 0
    pos, dstl, dstr, base_l, base_r, _ = leafperm.level_moves(
        jnp.asarray(tile_run), jnp.asarray(side), L)
    out = np.asarray(leafperm.permute_records(
        rec_lay, pos, dstl, dstr, n_buf))
    run_do = np.zeros(L, bool)
    run_do[[0, 2]] = True
    run_right = np.zeros(L, np.int32)
    run_right[0], run_right[2] = 4, 5
    tile_run2, run_slot2 = leafperm.advance_runs(
        run_slot, jnp.asarray(run_do), jnp.asarray(run_right),
        base_l, base_r, n_buf)
    # runs: old 0..3 keep slots 0..3 (left children / pass-through),
    # new runs 4,5 carry the right-child slots in run order
    assert [int(run_slot2[r]) for r in range(6)] == [0, 1, 2, 3, 4, 5]

    # expected per-slot membership after the split
    child_rows = {s: [] for s in range(6)}
    for r in range(N):
        if not bag[r]:
            continue
        s = slot_of[r]
        if s in go_right and go_right[s][r]:
            child_rows[{0: 4, 2: 5}[s]].append(r + 1)
        else:
            child_rows[s].append(r + 1)
    row_run2 = np.repeat(np.asarray(tile_run2), T)
    rs2 = np.asarray(run_slot2)[row_run2]
    tags2 = out[:, :4].copy().view(np.uint32).ravel()
    for s in range(6):
        got = tags2[(rs2 == s) & (tags2 > 0)]
        # stable row-id order per slot — exactly the aligned plan's order
        np.testing.assert_array_equal(got, np.asarray(child_rows[s]),
                                      err_msg=f"slot {s} order")


def test_hist_from_layout_post_permute_vs_plan():
    """Histograms off a POST-permute layout (interior _ALIGN sentinels
    shift rows across tile boundaries) vs the tile-plan path: counts
    EXACT (sums of 1.0), grad/hess to the documented ulp-class tolerance
    — the wired grower's per-level histogram contract."""
    from dryad_tpu.engine.histogram import build_hist_segmented

    import jax.numpy as jnp

    rng = np.random.default_rng(37)
    N, F, B, L = 6000, 10, 64, 4
    Xb = rng.integers(1, B, size=(N, F), dtype=np.uint8)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.uniform(0.1, 1, N).astype(np.float32)
    slot_of = rng.integers(0, 2, N).astype(np.int32)   # slots 0,1 live

    rec_nat = leafperm.make_layout_records(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h))
    n_buf = leafperm.wired_tiles_bound(-(-N // T), L)
    live = np.zeros(L, bool)
    live[:2] = True
    rec_lay, tile_run, run_slot = leafperm.initial_layout(
        rec_nat, jnp.asarray(slot_of), jnp.asarray(live), L, n_buf)

    # split slot 0 -> (0, 2); slot 1 passes through
    u = rng.random(N)
    right = (slot_of == 0) & (u < 0.45)
    row_run = np.repeat(np.asarray(tile_run), T)
    rs_lay = np.asarray(run_slot)[row_run]
    valid_lay = np.asarray(rec_lay)[:, 8] == 1
    # recover row ids via the g bytes (unique floats) to map sides
    gl = np.asarray(rec_lay)[:, 0:4].copy().view(np.float32).ravel()
    order = {float(v): i for i, v in enumerate(g)}
    side = np.full(n_buf * T, 2, np.int32)
    for i in np.nonzero(valid_lay)[0]:
        rid = order[float(gl[i])]
        side[i] = 1 if (rs_lay[i] == 0 and right[rid]) else 0
    pos, dstl, dstr, base_l, base_r, _ = leafperm.level_moves(
        jnp.asarray(tile_run), jnp.asarray(side), L)
    out = leafperm.permute_records(rec_lay, pos, dstl, dstr, n_buf)

    # children: left of 0 (=slot 0), right of 0 (new), left of 1 (pass)
    lt_l = np.asarray(base_l[1:] - base_l[:-1])
    lt_r = np.asarray(base_r[1:] - base_r[:-1])
    seg_first = jnp.asarray([int(base_l[0]), int(base_r[0]),
                             int(base_l[1])], jnp.int32)
    seg_nt = jnp.asarray([int(lt_l[0]), int(lt_r[0]), int(lt_l[1])],
                         jnp.int32)
    bound = int(np.asarray(seg_nt).sum()) + 2
    got = np.asarray(leafperm.hist_from_layout(
        out, seg_first, seg_nt, 3, B, F, np.uint8, bound))

    sel = np.where(slot_of == 0, np.where(right, 1, 0), 2).astype(np.int32)
    want = np.asarray(build_hist_segmented(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(sel), 3, B, backend="pallas"))
    np.testing.assert_array_equal(got[:, 2], want[:, 2])  # counts exact
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-4)


def test_hist_from_layout_bitwise_vs_plan():
    """Histograms straight from a leaf-ordered layout (contiguous tile
    runs, no sort/row-gather) are BITWISE equal to the tile-plan path on
    the same selection — the integration's parity anchor."""
    from dryad_tpu.engine.histogram import build_hist_segmented

    rng = np.random.default_rng(21)
    N, F, B, S = 6000, 12, 64, 4
    Xb = rng.integers(1, B, size=(N, F), dtype=np.uint8)
    g = rng.normal(size=N).astype(np.float32)
    h = rng.uniform(0.1, 1, N).astype(np.float32)
    seg_of = rng.integers(0, S, N).astype(np.int32)   # 4 segments

    rec_nat = np.asarray(leafperm.make_layout_records(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h)))
    # build the layout: rows grouped by segment in ORIGINAL row order
    # (the plan path's stable sort produces the same per-slot order)
    lt = np.maximum(-(-np.bincount(seg_of, minlength=S) // T), 1)
    base = np.concatenate([[0], np.cumsum(lt)])
    rec = np.zeros(((base[-1]) * T, leafperm._REC_WB), np.uint8)
    fill = np.zeros(S, np.int64)
    for r in range(N):
        s = seg_of[r]
        rec[base[s] * T + fill[s]] = rec_nat[r]
        fill[s] += 1

    # select segments 2 and 0 out of order, PLUS a genuinely EMPTY
    # selection in the middle (its mandatory slot must zero-init its
    # output block and must NOT shift segment 0's tiles past the bound —
    # the review-caught truncation bug)
    sel_segs = [2, None, 0]
    seg_first = jnp.asarray(
        [int(base[s]) if s is not None else 0 for s in sel_segs], jnp.int32)
    seg_nt = jnp.asarray(
        [int(lt[s]) if s is not None else 0 for s in sel_segs], jnp.int32)
    bound = int(np.maximum(np.asarray(seg_nt), 1).sum())  # documented bound
    got = np.asarray(leafperm.hist_from_layout(
        jnp.asarray(rec), seg_first, seg_nt, 3, B, F, np.uint8, bound))

    colof = {2: 0, 0: 2}
    sel = np.asarray([colof.get(int(s), 3) for s in seg_of], np.int32)
    want = np.asarray(build_hist_segmented(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(sel), 3, B, backend="pallas"))
    np.testing.assert_array_equal(got, want)
    assert not got[1].any()                       # empty slot zero-inited
