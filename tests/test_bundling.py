"""Exclusive feature bundling (EFB) — data/bundling.py (SURVEY.md §7 step 6)."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.data.bundling import BundledMapper, plan_bundles
from dryad_tpu.metrics import auc


def _onehot_csr(n=6000, groups=6, levels=5, num_dense=3, seed=61):
    """num_dense dense numeric cols + groups x levels one-hot numeric cols
    (each group strictly exclusive), CSR encoded.  y depends on the groups."""
    rng = np.random.default_rng(seed)
    F = num_dense + groups * levels
    dense = rng.normal(size=(n, num_dense)).astype(np.float32)
    cat = rng.integers(0, levels, size=(n, groups))
    rows, cols, vals = [], [], []
    for i in range(n):
        for d in range(num_dense):
            rows.append(i); cols.append(d); vals.append(dense[i, d])
        for gix in range(groups):
            rows.append(i)
            cols.append(num_dense + gix * levels + cat[i, gix])
            vals.append(1.0)
    order = np.lexsort((cols, rows))
    rows = np.asarray(rows)[order]
    cols = np.asarray(cols, np.int64)[order]
    vals = np.asarray(vals, np.float32)[order]
    indptr = np.searchsorted(rows, np.arange(n + 1)).astype(np.int64)
    logits = (dense[:, 0] + (cat[:, 0] == 2) * 1.5 - (cat[:, 1] >= 3) * 1.0
              + 0.3 * rng.normal(size=n))
    y = (logits > 0).astype(np.float32)
    return (indptr, cols, vals, F), y


def test_plan_is_deterministic_and_strictly_exclusive():
    (indptr, cols, vals, F), y = _onehot_csr()
    ds = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64,
                       bundle=False)
    plan1 = plan_bundles(ds.X_binned, ds.mapper, 64)
    plan2 = plan_bundles(ds.X_binned, ds.mapper, 64)
    assert plan1 == plan2 and len(plan1) >= 1
    # strict exclusivity on the planned members
    from dryad_tpu.data.binning import zero_bins

    zb = zero_bins(ds.mapper)
    for members in plan1:
        nz = np.stack([ds.X_binned[:, f] != zb[f] for f in members])
        assert (nz.sum(axis=0) <= 1).all()


def test_fold_roundtrip_unique_encoding():
    (indptr, cols, vals, F), y = _onehot_csr(n=2000)
    ds = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64,
                       bundle=False)
    plan = plan_bundles(ds.X_binned, ds.mapper, 64)
    bm = BundledMapper(ds.mapper, plan)
    folded = bm.fold(ds.X_binned)
    assert folded.shape == (2000, bm.num_features)
    assert bm.num_features < F
    # each bundle bin decodes to exactly one (member, bin) pair: rebuild the
    # members' columns from the folded one and compare
    from dryad_tpu.data.binning import zero_bins

    zb = zero_bins(ds.mapper)
    nb = ds.mapper.n_bins
    for bi, members in enumerate(plan):
        enc = folded[:, bi].astype(np.int64)
        off = 1
        for f in members:
            inside = (enc >= off) & (enc < off + int(nb[f]))
            rebuilt = np.where(inside, enc - off, zb[f])
            np.testing.assert_array_equal(rebuilt, ds.X_binned[:, f])
            off += int(nb[f])


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_bundled_training_quality_and_speed_shape(backend):
    (indptr, cols, vals, F), y = _onehot_csr()
    ds_b = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64)
    ds_u = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64,
                         bundle=False)
    assert ds_b.num_features < ds_u.num_features  # bundling engaged
    p = dict(objective="binary", num_trees=20, num_leaves=15, max_bins=64)
    a_b = auc(y, dryad.train(p, ds_b, backend=backend).predict_binned(ds_b.X_binned))
    a_u = auc(y, dryad.train(p, ds_u, backend=backend).predict_binned(ds_u.X_binned))
    assert a_b > 0.8
    assert a_b > a_u - 0.01  # identical-or-better quality


def test_bundled_save_load_and_raw_predict(tmp_path):
    (indptr, cols, vals, F), y = _onehot_csr(n=3000)
    ds = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64)
    assert isinstance(ds.mapper, BundledMapper)
    b = dryad.train(dict(objective="binary", num_trees=8, num_leaves=15,
                         max_bins=64), ds, backend="cpu")
    # raw-X predict folds through the stored plan
    dense = np.zeros((3000, F), np.float32)
    for i in range(3000):
        sl = slice(indptr[i], indptr[i + 1])
        dense[i, cols[sl]] = vals[sl]
    p_raw = b.predict(dense, raw_score=True)
    p_binned = b.predict_binned(ds.X_binned, raw_score=True)
    np.testing.assert_array_equal(p_raw, p_binned)
    path = str(tmp_path / "m.dryad")
    b.save(path)
    b2 = dryad.Booster.load(path)
    np.testing.assert_array_equal(p_raw, b2.predict(dense, raw_score=True))


def test_monotone_constraints_reject_bundling():
    (indptr, cols, vals, F), y = _onehot_csr(n=2000)
    ds = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64)
    assert isinstance(ds.mapper, BundledMapper)
    with pytest.raises(ValueError, match="bundle=False"):
        dryad.train(dict(objective="binary", num_trees=2,
                         monotone_constraints=(1,) + (0,) * (F - 1)),
                    ds, backend="cpu")


def test_plan_verifies_exclusivity_beyond_sample():
    """Members exclusive in the planning prefix but conflicting later must
    be evicted by the full-data verification pass."""
    rng = np.random.default_rng(67)
    n, S = 3000, 1000
    X = np.zeros((n, 3), np.float32)
    X[:, 2] = rng.normal(size=n)          # dense col keeps sketch sane
    # cols 0/1: disjoint in the first S rows, overlapping after
    X[: S // 2, 0] = 1.0
    X[S // 2: S, 1] = 1.0
    X[S:, 0] = 1.0
    X[S:, 1] = 1.0                        # conflict zone
    from dryad_tpu.data.sketch import sketch_features

    mapper = sketch_features(X, max_bins=16)
    Xb = mapper.transform(X)
    plan = plan_bundles(Xb, mapper, 16, sample_rows=S)
    for members in plan:
        assert not (0 in members and 1 in members), plan
    (indptr, cols, vals, F), y = _onehot_csr()
    n_tr = 4500
    tr = (indptr[: n_tr + 1], cols[: indptr[n_tr]], vals[: indptr[n_tr]], F)
    ds = dryad.Dataset(None, y[:n_tr], csr=tr, max_bins=64)
    va_indptr = (indptr[n_tr:] - indptr[n_tr]).astype(np.int64)
    va = (va_indptr, cols[indptr[n_tr]:], vals[indptr[n_tr]:], F)
    dv = dryad.Dataset(None, y[n_tr:], csr=va, max_bins=64, mapper=ds.mapper)
    assert dv.X_binned.shape[1] == ds.X_binned.shape[1]
    b = dryad.train(dict(objective="binary", num_trees=10, num_leaves=15,
                         max_bins=64, early_stopping_rounds=5),
                    ds, valid_sets=[dv], backend="cpu")
    assert b.best_iteration > 0
