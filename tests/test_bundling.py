"""Exclusive feature bundling (EFB) — data/bundling.py (SURVEY.md §7 step 6)."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.data.bundling import BundledMapper, plan_bundles
from dryad_tpu.metrics import auc


def _onehot_csr(n=6000, groups=6, levels=5, num_dense=3, seed=61):
    """num_dense dense numeric cols + groups x levels one-hot numeric cols
    (each group strictly exclusive), CSR encoded.  y depends on the groups."""
    rng = np.random.default_rng(seed)
    F = num_dense + groups * levels
    dense = rng.normal(size=(n, num_dense)).astype(np.float32)
    cat = rng.integers(0, levels, size=(n, groups))
    rows, cols, vals = [], [], []
    for i in range(n):
        for d in range(num_dense):
            rows.append(i); cols.append(d); vals.append(dense[i, d])
        for gix in range(groups):
            rows.append(i)
            cols.append(num_dense + gix * levels + cat[i, gix])
            vals.append(1.0)
    order = np.lexsort((cols, rows))
    rows = np.asarray(rows)[order]
    cols = np.asarray(cols, np.int64)[order]
    vals = np.asarray(vals, np.float32)[order]
    indptr = np.searchsorted(rows, np.arange(n + 1)).astype(np.int64)
    logits = (dense[:, 0] + (cat[:, 0] == 2) * 1.5 - (cat[:, 1] >= 3) * 1.0
              + 0.3 * rng.normal(size=n))
    y = (logits > 0).astype(np.float32)
    return (indptr, cols, vals, F), y


def test_plan_is_deterministic_and_strictly_exclusive():
    (indptr, cols, vals, F), y = _onehot_csr()
    ds = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64,
                       bundle=False)
    plan1 = plan_bundles(ds.X_binned, ds.mapper, 64)
    plan2 = plan_bundles(ds.X_binned, ds.mapper, 64)
    assert plan1 == plan2 and len(plan1) >= 1
    # strict exclusivity on the planned members
    from dryad_tpu.data.binning import zero_bins

    zb = zero_bins(ds.mapper)
    for members in plan1:
        nz = np.stack([ds.X_binned[:, f] != zb[f] for f in members])
        assert (nz.sum(axis=0) <= 1).all()


def test_fold_roundtrip_unique_encoding():
    (indptr, cols, vals, F), y = _onehot_csr(n=2000)
    ds = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64,
                       bundle=False)
    plan = plan_bundles(ds.X_binned, ds.mapper, 64)
    bm = BundledMapper(ds.mapper, plan)
    folded = bm.fold(ds.X_binned)
    assert folded.shape == (2000, bm.num_features)
    assert bm.num_features < F
    # each bundle bin decodes to exactly one (member, bin) pair: rebuild the
    # members' columns from the folded one and compare
    from dryad_tpu.data.binning import zero_bins

    zb = zero_bins(ds.mapper)
    nb = ds.mapper.n_bins
    for bi, members in enumerate(plan):
        enc = folded[:, bi].astype(np.int64)
        off = 1
        for f in members:
            inside = (enc >= off) & (enc < off + int(nb[f]))
            rebuilt = np.where(inside, enc - off, zb[f])
            np.testing.assert_array_equal(rebuilt, ds.X_binned[:, f])
            off += int(nb[f])


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_bundled_training_quality_and_speed_shape(backend):
    (indptr, cols, vals, F), y = _onehot_csr()
    ds_b = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64)
    ds_u = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64,
                         bundle=False)
    assert ds_b.num_features < ds_u.num_features  # bundling engaged
    p = dict(objective="binary", num_trees=20, num_leaves=15, max_bins=64)
    a_b = auc(y, dryad.train(p, ds_b, backend=backend).predict_binned(ds_b.X_binned))
    a_u = auc(y, dryad.train(p, ds_u, backend=backend).predict_binned(ds_u.X_binned))
    assert a_b > 0.8
    assert a_b > a_u - 0.01  # identical-or-better quality


def test_bundled_save_load_and_raw_predict(tmp_path):
    (indptr, cols, vals, F), y = _onehot_csr(n=3000)
    ds = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64)
    assert isinstance(ds.mapper, BundledMapper)
    b = dryad.train(dict(objective="binary", num_trees=8, num_leaves=15,
                         max_bins=64), ds, backend="cpu")
    # raw-X predict folds through the stored plan
    dense = np.zeros((3000, F), np.float32)
    for i in range(3000):
        sl = slice(indptr[i], indptr[i + 1])
        dense[i, cols[sl]] = vals[sl]
    p_raw = b.predict(dense, raw_score=True)
    p_binned = b.predict_binned(ds.X_binned, raw_score=True)
    np.testing.assert_array_equal(p_raw, p_binned)
    path = str(tmp_path / "m.dryad")
    b.save(path)
    b2 = dryad.Booster.load(path)
    np.testing.assert_array_equal(p_raw, b2.predict(dense, raw_score=True))


def test_monotone_constraints_reject_bundling():
    (indptr, cols, vals, F), y = _onehot_csr(n=2000)
    ds = dryad.Dataset(None, y, csr=(indptr, cols, vals, F), max_bins=64)
    assert isinstance(ds.mapper, BundledMapper)
    with pytest.raises(ValueError, match="bundle=False"):
        dryad.train(dict(objective="binary", num_trees=2,
                         monotone_constraints=(1,) + (0,) * (F - 1)),
                    ds, backend="cpu")


def test_plan_verifies_exclusivity_beyond_sample():
    """Members exclusive in the planning prefix but conflicting later must
    be evicted by the full-data verification pass."""
    rng = np.random.default_rng(67)
    n, S = 3000, 1000
    X = np.zeros((n, 3), np.float32)
    X[:, 2] = rng.normal(size=n)          # dense col keeps sketch sane
    # cols 0/1: disjoint in the first S rows, overlapping after
    X[: S // 2, 0] = 1.0
    X[S // 2: S, 1] = 1.0
    X[S:, 0] = 1.0
    X[S:, 1] = 1.0                        # conflict zone
    from dryad_tpu.data.sketch import sketch_features

    mapper = sketch_features(X, max_bins=16)
    Xb = mapper.transform(X)
    plan = plan_bundles(Xb, mapper, 16, sample_rows=S)
    for members in plan:
        assert not (0 in members and 1 in members), plan
    (indptr, cols, vals, F), y = _onehot_csr()
    n_tr = 4500
    tr = (indptr[: n_tr + 1], cols[: indptr[n_tr]], vals[: indptr[n_tr]], F)
    ds = dryad.Dataset(None, y[:n_tr], csr=tr, max_bins=64)
    va_indptr = (indptr[n_tr:] - indptr[n_tr]).astype(np.int64)
    va = (va_indptr, cols[indptr[n_tr]:], vals[indptr[n_tr]:], F)
    dv = dryad.Dataset(None, y[n_tr:], csr=va, max_bins=64, mapper=ds.mapper)
    assert dv.X_binned.shape[1] == ds.X_binned.shape[1]
    b = dryad.train(dict(objective="binary", num_trees=10, num_leaves=15,
                         max_bins=64, early_stopping_rounds=5),
                    ds, valid_sets=[dv], backend="cpu")
    assert b.best_iteration > 0


def test_fold_conflict_warning_on_nontraining_data():
    """Validation/predict matrices can violate the training plan's
    exclusivity; the fold must count and WARN about dropped values
    (ADVICE r2: silent feature loss)."""
    import warnings

    rng = np.random.default_rng(71)
    n = 4000
    X = np.zeros((n, 3), np.float32)
    X[:, 2] = rng.normal(size=n)
    X[: n // 2, 0] = 1.0                   # cols 0/1 exclusive on train
    X[n // 2:, 1] = 1.0
    from dryad_tpu.data.sketch import sketch_features

    base = sketch_features(X, max_bins=16)
    Xb = base.transform(X)
    plan = plan_bundles(Xb, base, 16, min_default_frac=0.3)
    assert any(0 in m and 1 in m for m in plan), plan
    bm = BundledMapper(base, plan)
    bm.transform(X)
    assert bm.last_conflict_count == 0

    X_bad = X.copy()
    X_bad[:10, 0] = 1.0
    X_bad[:10, 1] = 1.0                    # both members non-default
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        bm.transform(X_bad)
    assert bm.last_conflict_count == 10
    assert any("EFB fold dropped" in str(x.message) for x in w)


def test_bundled_columns_excluded_from_missing_right_plane():
    """A bundled column's bin 0 means 'all members default', not 'missing' —
    the missing-right split plane must skip those columns in BOTH backends
    (ADVICE r2), pinned by cross-backend tree parity on NaN-bearing data."""
    rng = np.random.default_rng(73)
    n = 5000
    X = np.zeros((n, 4), np.float32)
    X[:, 2] = rng.normal(size=n)
    X[:, 3] = rng.normal(size=n)
    X[: n // 2, 0] = rng.uniform(1, 2, size=n // 2)
    X[n // 2:, 1] = rng.uniform(1, 2, size=n // 2)
    X[rng.permutation(n)[: n // 5], 3] = np.nan   # NaNs in an UNBUNDLED col
    y = ((np.nan_to_num(X[:, 3], nan=0.4) + X[:, 0] - X[:, 1]
          + 0.3 * rng.normal(size=n)) > 0).astype(np.float32)
    from dryad_tpu.data.binning import bin_matrix
    from dryad_tpu.data.sketch import sketch_features

    base = sketch_features(X, max_bins=32)
    plan = plan_bundles(bin_matrix(X, base), base, 128, min_default_frac=0.3)
    assert plan, "fixture must actually bundle"
    bm = BundledMapper(base, plan)
    ds = dryad.Dataset.from_binned(bm.transform(X), bm, y)
    assert ds.has_missing
    # 4 trees: long missing-heavy runs can hit the documented fp near-tie
    # argmax tolerance between backends (CLAUDE.md); the parity window here
    # is tie-free, and the bundled-column property is asserted on BOTH
    params = dict(objective="binary", num_trees=4, num_leaves=15, max_bins=32)
    bc = dryad.train(params, ds, backend="cpu")
    bt = dryad.train(params, ds, backend="tpu")
    np.testing.assert_array_equal(bc.feature, bt.feature)
    np.testing.assert_array_equal(bc.threshold, bt.threshold)
    np.testing.assert_array_equal(bc.default_left, bt.default_left)
    # no tree may route "missing" to the right on a bundled column
    for b in (bc, bt):
        for t in range(b.feature.shape[0]):
            for node in range(b.feature.shape[1]):
                f = b.feature[t, node]
                if f >= 0 and bm.bundled_mask[f]:
                    assert b.default_left[t, node], (
                        "bundled column learned a missing-right direction")


def test_split_finders_mask_bundled_from_missing_right_unit():
    """Unit: a histogram where the missing-right plane strictly wins on
    feature 0 — with bundled_mask marking that feature, both finders must
    fall back to the (worse) missing-left split instead."""
    import jax.numpy as jnp

    from dryad_tpu.cpu.histogram import find_best_split as cpu_find
    from dryad_tpu.engine.split import find_best_split as dev_find

    B = 4
    # bin0 carries positive-gradient mass; bins 1..3 split cleanly only when
    # bin0 goes right -> the right plane's gain dominates
    hg = np.array([[5.0, -8.0, 1.0, 2.0]], np.float64)
    hh = np.array([[2.0, 4.0, 1.0, 1.0]], np.float64)
    hc = np.array([[60.0, 60.0, 60.0, 60.0]], np.float64)
    hist = np.stack([hg, hh, hc])
    G, H, C = hg.sum(), hh.sum(), hc.sum()

    free = cpu_find(hist, G, H, C, lambda_l2=1.0, min_child_weight=1e-3,
                    min_data_in_leaf=1, min_split_gain=0.0,
                    learn_missing=True)
    masked = cpu_find(hist, G, H, C, lambda_l2=1.0, min_child_weight=1e-3,
                      min_data_in_leaf=1, min_split_gain=0.0,
                      learn_missing=True,
                      bundled_mask=np.array([True]))
    assert not free.default_left, "fixture must prefer missing-right unmasked"
    assert masked.default_left, "mask must forbid missing-right"

    fmask = jnp.ones((1,), bool)
    iscat = jnp.zeros((1,), bool)
    hist_j = jnp.asarray(hist.astype(np.float32))
    kw = dict(lambda_l2=1.0, min_child_weight=1e-3, min_data_in_leaf=1,
              min_split_gain=0.0, feat_mask=fmask, is_cat_feat=iscat,
              allow=jnp.bool_(True), has_cat=False, learn_missing=True)
    free_d = dev_find(hist_j, jnp.float32(G), jnp.float32(H), jnp.float32(C),
                      **kw)
    masked_d = dev_find(hist_j, jnp.float32(G), jnp.float32(H),
                        jnp.float32(C), bundled_mask=jnp.array([True]), **kw)
    assert not bool(free_d.default_left)
    assert bool(masked_d.default_left)
    assert int(masked_d.threshold) == int(masked.threshold)


def _sparse_cat_csr(n=8000, groups=4, per_group=6, levels=6, num_dense=3,
                    seed=77):
    """Mutually-exclusive sparse CATEGORICAL columns (one active column per
    group per row, multi-level category values) + dense numeric, CSR."""
    rng = np.random.default_rng(seed)
    num_cat = groups * per_group
    F = num_cat + num_dense
    present = np.zeros((n, F), bool)
    for gi in range(groups):
        choice = rng.integers(0, per_group, size=n)
        present[np.arange(n), gi * per_group + choice] = True
    present[:, num_cat:] = True
    vals = np.zeros((n, F), np.float32)
    vals[:, :num_cat] = rng.integers(1, levels, size=(n, num_cat))
    vals[:, num_cat:] = rng.normal(size=(n, num_dense))
    w = rng.normal(size=num_cat)
    logit = (vals[:, :num_cat] * present[:, :num_cat]) @ w * 0.3 \
        + vals[:, num_cat] * 0.5
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    rows, cols = np.nonzero(present)
    values = vals[rows, cols]
    counts = np.bincount(rows, minlength=n)
    indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    return (indptr, cols.astype(np.int64), values.astype(np.float32), F), y, \
        tuple(range(num_cat))


def test_categorical_bundling_end_to_end():
    """Sparse categorical columns bundle (with other categoricals only),
    the bundle column is itself categorical, subset splits address the
    offset-stacked bins, and CPU/TPU grow identical trees on it."""
    csr, y, cat_ids = _sparse_cat_csr()
    ds = dryad.Dataset(None, y, csr=csr, max_bins=64,
                       categorical_features=cat_ids)
    bm = ds.mapper
    assert isinstance(bm, BundledMapper)
    base_cat = bm.base.is_categorical
    cat_bundles = [m for m in bm.bundles if base_cat[m[0]]]
    assert cat_bundles, "sparse categorical columns must bundle"
    for m in bm.bundles:  # never mixed-kind
        kinds = {bool(base_cat[f]) for f in m}
        assert len(kinds) == 1
    # bundle columns inherit their members' kind
    for bi, m in enumerate(bm.bundles):
        assert bool(bm.is_categorical[bi]) == bool(base_cat[m[0]])

    params = dict(objective="binary", num_trees=10, num_leaves=15,
                  max_bins=64,
                  categorical_features=list(range(ds.num_features)))
    # categorical_features param is mapper-driven here; train from binned
    p2 = dict(objective="binary", num_trees=10, num_leaves=15, max_bins=64)
    bc = dryad.train(p2, ds, backend="cpu")
    bt = dryad.train(p2, ds, backend="tpu")
    np.testing.assert_array_equal(bc.feature, bt.feature)
    np.testing.assert_array_equal(bc.cat_bitset, bt.cat_bitset)
    # subset splits actually used the bundled categorical columns
    used = set(bc.feature[bc.is_cat].tolist())
    assert any(f < len(bm.bundles) and bm.is_categorical[f] for f in used), \
        "no subset split landed on a categorical bundle"
    a = auc(y, bc.predict_binned(ds.X_binned))
    assert a > 0.62, a

    # serialization keeps the plan and the categorical marking
    bm2 = BundledMapper.from_bytes(bm.to_bytes())
    np.testing.assert_array_equal(bm.is_categorical, bm2.is_categorical)
    assert bm2.bundles == bm.bundles
