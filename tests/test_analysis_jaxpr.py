"""jaxpr auditor (dryad_tpu/analysis layer 2): the collective/sort census
over the real grower arms, the _comm_stats cross-check, kernel dtype
discipline, and the digest tripwire — including the mutation direction
(a program with an EXTRA collective or sort must be caught).

Everything here traces with abstract inputs on the 8 fake CPU devices;
nothing compiles or runs, so the module stays cheap relative to the
training fixtures around it.
"""

from __future__ import annotations

from collections import Counter

import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dryad_tpu.analysis.digests import canonical_digest
from dryad_tpu.analysis.jaxpr_audit import (
    ARMS,
    Census,
    census_jaxpr,
    kernel_dtype_violations,
    run_audit,
    trace_arm,
)
from dryad_tpu.engine.distributed import AXIS, make_mesh
from dryad_tpu.engine.jax_compat import shard_map

pytestmark = pytest.mark.distributed


@pytest.fixture(scope="module")
def audit_report():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return run_audit()


def _arm(report, name):
    return next(a for a in report.arms if a.name == name)


# ---------------------------------------------------------------------------
# the documented invariants, arm by arm

def test_all_arms_pass_invariants(audit_report):
    for arm in audit_report.arms:
        assert arm.ok, f"{arm.name}: {arm.failures}"


def test_psum_census_matches_comm_stats_every_arm(audit_report):
    """The accounting (_comm_stats) and the traced program must agree —
    this is the cross-check that retires hand-maintained drift."""
    for arm in audit_report.arms:
        assert arm.census.collectives.get("psum", 0) == arm.expected_psums, \
            arm.name


def test_wired_paths_sort_free(audit_report):
    """'Nothing on the wired path sorts rows' (r10) — now machine-checked
    (the r16 feature-reduction arms ride the wired layout too)."""
    for name in ("levelwise_wired", "leafwise_wired",
                 "levelwise_feature", "leafwise_feature"):
        c = _arm(audit_report, name).census
        assert c.global_row_sorts == 0 and c.local_row_sorts == 0, name


def test_legacy_arm_keeps_its_tile_plan_sorts(audit_report):
    """The comparison arm must keep sorting — if the legacy path silently
    stopped sorting it is no longer the program the bench compares."""
    c = _arm(audit_report, "levelwise_legacy").census
    assert c.local_row_sorts > 0
    assert c.global_row_sorts == 0


def test_goss_adds_exactly_one_global_sort(audit_report):
    assert _arm(audit_report, "goss_iteration").census.global_row_sorts == 1


def test_renewal_adds_exactly_one_global_sort(audit_report):
    assert _arm(audit_report,
                "renewal_iteration").census.global_row_sorts == 1


def test_sharded_predict_collective_free(audit_report):
    c = _arm(audit_report, "sharded_predict").census
    assert not c.collectives
    assert c.global_row_sorts == 0 and c.local_row_sorts == 0


def test_only_documented_collectives_anywhere(audit_report):
    """fused arms: psum only.  feature arms (r16): psum (root) +
    reduce_scatter + all_gather (+ the communication-free axis_index the
    slice/offset derivation uses) — nothing else, anywhere."""
    feature_arms = {"levelwise_feature", "leafwise_feature"}
    for arm in audit_report.arms:
        allowed = {"psum"}
        if arm.name in feature_arms:
            allowed |= {"reduce_scatter", "all_gather", "axis_index"}
        extra = {k: v for k, v in arm.census.collectives.items()
                 if k not in allowed}
        assert not extra, (arm.name, extra)


def test_feature_arm_collective_plan_matches_comm_stats(audit_report):
    """The r16 collective plan, census-verified: on the feature arms the
    root keeps ONE psum, every level shows exactly one reduce_scatter and
    one combine all_gather (cross-checked against _comm_stats inside
    trace_arm; re-asserted here so the plan is visible in the test)."""
    for name, levels in (("levelwise_feature", 7), ("leafwise_feature", 5)):
        c = _arm(audit_report, name).census
        assert c.collectives.get("psum", 0) == 1, name
        assert c.collectives.get("reduce_scatter", 0) == levels, name
        assert c.collectives.get("all_gather", 0) == levels, name
    # the fused twins are untouched: same configs, psum-only plans
    for name in ("levelwise_wired", "leafwise_wired"):
        c = _arm(audit_report, name).census
        assert set(c.collectives) == {"psum"}, name


def test_wired_kernels_present_and_u8(audit_report):
    """The wired arms must actually run the layout kernels (the gates
    admitted) and every kernel's dominant integer operand stays u8/u16."""
    for name in ("levelwise_wired", "leafwise_wired"):
        c = _arm(audit_report, name).census
        assert "_hist_kernel" in c.pallas_kernels, name
        assert "_perm_kernel" in c.pallas_kernels, name
        assert not kernel_dtype_violations(c), name


def test_digests_match_committed_goldens(audit_report):
    assert audit_report.drift_ok, audit_report.drift


# ---------------------------------------------------------------------------
# census machinery: weighting, nesting, mutation direction

def _mesh8():
    return make_mesh(jax.devices()[:8])


def test_census_weights_scan_trip_counts():
    mesh = _mesh8()

    def inner(x):
        def body(i, c):
            return c + jax.lax.psum(x.sum() * i, AXIS)

        return jax.lax.fori_loop(0, 5, body, jnp.float32(0))

    fn = shard_map(inner, mesh=mesh, in_specs=(P(AXIS),), out_specs=P())
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((64,), jnp.float32))
    c = census_jaxpr(closed, row_threshold=8)
    assert c.collectives["psum"] == 5


def test_census_seeded_extra_psum_is_counted():
    """Mutation check: a second collective sneaking into a builder-shaped
    program must move the census (and thus fail the _comm_stats check)."""
    mesh = _mesh8()

    def one(x):
        return jax.lax.psum(x.sum(), AXIS)

    def two(x):
        return jax.lax.psum(x.sum(), AXIS) + jax.lax.psum(x.max(), AXIS)

    def trace(f):
        fn = shard_map(f, mesh=mesh, in_specs=(P(AXIS),), out_specs=P())
        return census_jaxpr(jax.make_jaxpr(fn)(
            jax.ShapeDtypeStruct((64,), jnp.float32)), 8)

    assert trace(one).collectives["psum"] == 1
    assert trace(two).collectives["psum"] == 2


def test_census_splits_global_vs_shard_local_sorts():
    mesh = _mesh8()
    N = 512

    def local_sorting(x):
        return jnp.sort(x)     # sorts the SHARD

    fn = shard_map(local_sorting, mesh=mesh, in_specs=(P(AXIS),),
                   out_specs=P(AXIS))

    def global_sorting(x):
        return jnp.sort(fn(x))  # sorts the GLOBAL array

    closed = jax.make_jaxpr(global_sorting)(
        jax.ShapeDtypeStruct((N,), jnp.float32))
    c = census_jaxpr(closed, row_threshold=N // 8)
    assert c.local_row_sorts == 1
    assert c.global_row_sorts == 1


def test_census_ignores_slot_scale_sorts():
    def f(gains, rows):
        return jnp.argsort(gains), rows * 2   # (31,) slot sort only

    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((31,), jnp.float32),
                               jax.ShapeDtypeStruct((4096,), jnp.float32))
    c = census_jaxpr(closed, row_threshold=512)
    assert c.global_row_sorts == 0 and c.local_row_sorts == 0


def test_kernel_dtype_rule_flags_i32_tiles():
    c = Census(collectives=Counter())
    c.pallas_kernels["_hist_kernel"] = {
        "(int32(4,),int32(4, 512, 128),bfloat16(4, 8, 512))"}
    bad = kernel_dtype_violations(c)
    assert bad and "int32" in bad[0]


def test_kernel_dtype_rule_accepts_u8_tiles():
    c = Census(collectives=Counter())
    c.pallas_kernels["_hist_kernel"] = {
        "(int32(4,),uint8(4, 512, 128),bfloat16(4, 8, 512))"}
    assert not kernel_dtype_violations(c)


# ---------------------------------------------------------------------------
# digests

def test_digest_stable_across_retrace():
    def f(x):
        return jnp.cumsum(x * 2)

    a = canonical_digest(jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((128,), jnp.float32)))
    b = canonical_digest(jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((128,), jnp.float32)))
    assert a == b


def test_digest_moves_when_program_changes():
    def f(x):
        return jnp.cumsum(x * 2)

    def g(x):
        return jnp.cumsum(x * 3)   # literal change

    def h(x):
        return jnp.cumsum(x + x)   # op change

    sds = jax.ShapeDtypeStruct((128,), jnp.float32)
    d = {canonical_digest(jax.make_jaxpr(fn)(sds)) for fn in (f, g, h)}
    assert len(d) == 3


def test_goldens_corruption_is_reported(tmp_path, audit_report):
    """The CI failure path: a stale/foreign golden must surface as drift
    (exit 4 in the CLI), never silently pass."""
    import json

    from dryad_tpu.analysis.digests import load_goldens, save_goldens
    from dryad_tpu.analysis.jaxpr_audit import run_audit as run

    gpath = str(tmp_path / "goldens.json")
    data = json.loads(json.dumps(load_goldens()))   # deep copy of committed
    data["arms"]["sharded_predict"]["digest"] = "not-the-digest"
    save_goldens(data, gpath)
    rep = run(arm_names=["sharded_predict"], goldens_path=gpath)
    assert rep.ok and not rep.drift_ok
    assert "digest" in rep.drift[0]


def test_update_goldens_roundtrip(tmp_path):
    gpath = str(tmp_path / "goldens.json")
    run_audit(arm_names=["sharded_predict"], goldens_path=gpath,
              update_goldens=True)
    rep = run_audit(arm_names=["sharded_predict"], goldens_path=gpath)
    assert rep.ok and rep.drift_ok


# ---------------------------------------------------------------------------
# the traced arm IS the trained program (spot anchor)

def test_wired_arm_gates_really_admit():
    """Guard against the silent-skip failure mode: if a fixture config
    stopped passing deep_layout_supported, the 'wired' arm would quietly
    trace the legacy program and the zero-sort check would pin nothing."""
    from dryad_tpu.config import make_params
    from dryad_tpu.engine.levelwise import deep_layout_supported
    from dryad_tpu.engine.leafwise_fast import leafwise_layout_supported

    p = make_params(dict(objective="binary", num_trees=1, num_leaves=127,
                         max_depth=7, growth="depthwise", max_bins=32,
                         hist_backend="pallas")).validate()
    assert deep_layout_supported(p, 8, 32, 1, "tpu")
    pl = make_params(dict(objective="binary", num_trees=1, num_leaves=31,
                          max_depth=5, growth="leafwise", max_bins=32,
                          hist_backend="pallas")).validate()
    assert leafwise_layout_supported(pl, 8, 32, 1, "tpu")


def test_single_arm_trace_smoke():
    rep = trace_arm("sharded_predict")
    assert rep.ok and rep.digest
    assert set(ARMS) >= {"levelwise_wired", "levelwise_legacy",
                         "leafwise_wired", "levelwise_feature",
                         "leafwise_feature", "goss_iteration",
                         "renewal_iteration", "multiclass_shared_roots",
                         "sharded_predict"}


def test_update_goldens_subset_merges_not_clobbers(tmp_path):
    """--arm X --update-goldens must refresh X's pin ONLY: wiping the
    other arms' goldens would force a full unreviewed re-baseline."""
    from dryad_tpu.analysis.digests import load_goldens

    gpath = str(tmp_path / "goldens.json")
    run_audit(arm_names=["sharded_predict"], goldens_path=gpath,
              update_goldens=True)
    run_audit(arm_names=["renewal_iteration"], goldens_path=gpath,
              update_goldens=True)
    arms = load_goldens(gpath)["arms"]
    assert set(arms) == {"sharded_predict", "renewal_iteration"}
    rep = run_audit(arm_names=["sharded_predict", "renewal_iteration"],
                    goldens_path=gpath)
    assert rep.ok and rep.drift_ok


def test_env_change_reported_as_rebaseline_not_code_drift(tmp_path):
    import json

    from dryad_tpu.analysis.digests import load_goldens, save_goldens

    gpath = str(tmp_path / "goldens.json")
    run_audit(arm_names=["sharded_predict"], goldens_path=gpath,
              update_goldens=True)
    data = json.loads(json.dumps(load_goldens(gpath)))
    data["jax_version"] = "0.0.1"
    save_goldens(data, gpath)
    rep = run_audit(arm_names=["sharded_predict"], goldens_path=gpath)
    assert not rep.drift_ok
    assert "re-baseline" in rep.drift[0]


def test_update_goldens_refuses_on_invariant_failure(tmp_path, monkeypatch):
    """Review r11: --update-goldens must never pin a program that fails
    its own invariants."""
    import os

    import dryad_tpu.analysis.jaxpr_audit as ja

    real = ja.trace_arm

    def broken(name):
        rep = real(name)
        rep.failures.append("seeded failure")
        return rep

    monkeypatch.setattr(ja, "trace_arm", broken)
    gpath = str(tmp_path / "goldens.json")
    rep = ja.run_audit(arm_names=["sharded_predict"], goldens_path=gpath,
                       update_goldens=True)
    assert not rep.ok
    assert not os.path.exists(gpath)
    assert any("refusing" in d for d in rep.drift)
