"""Chrome trace_event export (obs/trace_export) + the span trace sink.

Pins: valid trace_event JSON (object form, required keys), monotonic
non-decreasing ts, span NESTING preserved (child intervals inside their
parent's on the same tid), journal instant events and stage walls on
their own process tracks, the /trace endpoint, and the train CLI's
``--trace-out``.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from dryad_tpu.obs import (
    MetricsExporter,
    Registry,
    SpanTrace,
    default_trace,
    disable_tracing,
    enable_tracing,
)
from dryad_tpu.obs import spans
from dryad_tpu.obs.trace_export import (
    dumps_trace,
    to_trace_events,
    write_trace,
)


@pytest.fixture()
def sink():
    buf = SpanTrace(capacity=1024)
    spans.set_trace_sink(buf.record)
    yield buf
    spans.set_trace_sink(None)


def _nested_spans(reg, sink):
    with spans.span("tree", registry=reg):
        with spans.span("level", registry=reg):
            with spans.span("stage", registry=reg):
                time.sleep(0.002)
            time.sleep(0.001)
    return sink.events()


def test_sink_captures_nested_paths(sink):
    events = _nested_spans(Registry(), sink)
    paths = [e[0] for e in events]
    # spans complete innermost-first
    assert paths == ["tree/level/stage", "tree/level", "tree"]


def test_sink_disabled_registry_records_nothing(sink):
    with spans.span("quiet", registry=Registry(enabled=False)):
        pass
    assert sink.events() == []


def test_record_feeds_the_sink(sink):
    spans.record("loop_body", 0.004, registry=Registry())
    ((path, t0, dur, _tid, trace),) = sink.events()
    assert path == "loop_body" and abs(dur - 0.004) < 1e-9
    assert trace is None                     # record() is untagged


def test_record_at_tags_the_trace(sink):
    reg = Registry()
    spans.record_at("serve.request/predict", 10.0, 0.25,
                    trace="abc123", registry=reg)
    ((path, t0, dur, _tid, trace),) = sink.events()
    assert (path, t0, dur, trace) == (
        "serve.request/predict", 10.0, 0.25, "abc123")
    # the span series got the same completion
    assert reg.counter(spans.COUNT).labels(
        span="serve.request/predict").value() == 1
    # and the rendered trace carries the id in args
    evs = [e for e in to_trace_events(span_events=sink.events())
           if e["ph"] == "X"]
    assert evs[0]["args"]["trace"] == "abc123"


def test_trace_events_schema_monotonic_and_nested(sink):
    events = _nested_spans(Registry(), sink)
    trace = to_trace_events(span_events=events)
    data = [e for e in trace if e["ph"] == "X"]
    # required trace_event keys on every event
    for e in trace:
        assert {"ph", "pid", "tid", "name", "ts"} <= set(e) or e["ph"] == "M"
    # ts monotonic non-decreasing over the whole list
    ts = [e["ts"] for e in trace if "ts" in e]
    assert ts == sorted(ts)
    # nesting: child interval inside parent interval, parent sorts first
    by_path = {e["args"]["path"]: e for e in data}
    parent = by_path["tree"]
    child = by_path["tree/level/stage"]
    assert parent["ts"] <= child["ts"]
    assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + 1e-3
    assert data[0]["args"]["path"] == "tree"   # longest-first at equal ts


def test_trace_json_loads_and_has_object_form(sink):
    events = _nested_spans(Registry(), sink)
    doc = json.loads(dumps_trace(span_events=events))
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"


def test_journal_events_render_as_instants(tmp_path):
    journal = [
        {"event": "run_start", "elapsed_s": 0.0},
        {"event": "fault", "elapsed_s": 1.25, "kind": "fetch_death",
         "detail": {"nested": "dropped"}},
        {"event": "resume", "elapsed_s": 2.5, "from_iteration": 40},
    ]
    trace = to_trace_events(journal_events=journal)
    inst = [e for e in trace if e["ph"] == "i"]
    assert [e["name"] for e in inst] == ["run_start", "fault", "resume"]
    assert all(e["pid"] == 2 for e in inst)
    assert inst[1]["ts"] == 1.25e6
    assert inst[1]["args"]["kind"] == "fetch_death"
    assert "detail" not in inst[1]["args"]     # non-scalar args dropped
    assert inst[2]["args"]["from_iteration"] == 40


def test_stage_walls_lay_out_back_to_back():
    stages = [{"stage": "hist_segmented", "ms": 136.0, "spread": 0.02},
              {"stage": "deep_level", "arm": "wired", "ms": 51.4}]
    trace = to_trace_events(stages=stages)
    xs = [e for e in trace if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["hist_segmented", "deep_level[wired]"]
    assert xs[0]["ts"] == 0.0 and xs[0]["dur"] == 136.0 * 1e3
    assert xs[1]["ts"] == 136.0 * 1e3 and xs[1]["dur"] == 51.4 * 1e3
    assert all(e["pid"] == 3 for e in xs)


def test_ring_capacity_bounds_and_counts_drops():
    buf = SpanTrace(capacity=4)
    for i in range(10):
        buf.record(f"s{i}", float(i), 0.001)
    assert len(buf.events()) == 4 and buf.dropped == 6
    buf.clear()
    assert buf.events() == [] and buf.dropped == 0


def test_trace_endpoint_serves_the_default_ring():
    reg = Registry()
    buf = enable_tracing()
    try:
        buf.clear()
        assert default_trace() is buf
        with spans.span("served_span", registry=reg):
            pass
        with MetricsExporter(reg) as exporter:
            body = urllib.request.urlopen(exporter.url + "/trace",
                                          timeout=5).read()
        doc = json.loads(body)
        names = [e["name"] for e in doc["traceEvents"]
                 if e["ph"] == "X"]
        assert "served_span" in names
    finally:
        disable_tracing()
        buf.clear()


def test_write_trace_file(tmp_path, sink):
    events = _nested_spans(Registry(), sink)
    out = tmp_path / "trace.json"
    write_trace(str(out), span_events=events)
    doc = json.loads(out.read_text())
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 3


def test_train_cli_trace_out(tmp_path):
    """--trace-out on the train CLI writes a Perfetto-loadable document
    carrying the trainer's span tree."""
    from dryad_tpu.__main__ import main
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(1500, seed=13)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    cfg = dict(objective="binary", num_trees=3, num_leaves=7, max_bins=32)
    (tmp_path / "cfg.json").write_text(json.dumps(cfg))
    trace_path = tmp_path / "run.trace.json"
    rc = main(["train", "--config", str(tmp_path / "cfg.json"),
               "--data", str(tmp_path / "X.npy"),
               "--label", str(tmp_path / "y.npy"),
               "--backend", "cpu", "--quiet",
               "--trace-out", str(trace_path)])
    assert rc == 0 and trace_path.exists()
    # the sink must be uninstalled after the run
    assert spans._TRACE_SINK is None
    doc = json.loads(trace_path.read_text())
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert xs, "the trainer's spans must appear in the trace"
    ts = [e["ts"] for e in doc["traceEvents"] if "ts" in e]
    assert ts == sorted(ts)
