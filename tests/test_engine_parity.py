"""Device-engine parity vs the CPU canonical trainer (SURVEY.md §4 keystone).

The CPU trainer accumulates histograms in f64, the device engine in fp32 on
the matmul path; on continuous data the gain argmax agrees and the grown
trees are structurally identical.  Leaf values may differ by fp32 rounding
of G/H sums (asserted to 1e-2 absolute, typically ~1e-4)."""

from __future__ import annotations

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import covertype_like, higgs_like, mslr_like

pytestmark = pytest.mark.engine


def _structure_equal(a, b):
    for k in ("feature", "threshold", "left", "right", "is_cat", "cat_bitset"):
        np.testing.assert_array_equal(
            a.tree_arrays()[k], b.tree_arrays()[k], err_msg=f"tree array {k!r} diverged"
        )


def _train_both(params, ds, valid=None):
    b_cpu = dryad.train(params, ds, valid_sets=[valid] if valid else None, backend="cpu")
    b_dev = dryad.train(params, ds, valid_sets=[valid] if valid else None, backend="tpu")
    return b_cpu, b_dev


def test_binary_parity():
    X, y = higgs_like(2500)
    ds = dryad.Dataset(X, y, max_bins=64)
    params = dict(objective="binary", num_trees=8, num_leaves=15, max_bins=64,
                  learning_rate=0.2)
    b_cpu, b_dev = _train_both(params, ds)
    _structure_equal(b_cpu, b_dev)
    assert b_cpu.max_depth_seen == b_dev.max_depth_seen
    np.testing.assert_allclose(b_cpu.value, b_dev.value, atol=1e-2)


def test_regression_parity():
    rng = np.random.Generator(np.random.Philox(3))
    X = rng.normal(size=(2000, 12)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1]) + rng.normal(size=2000) * 0.1).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32)
    params = dict(objective="regression", num_trees=6, num_leaves=12, max_bins=32)
    b_cpu, b_dev = _train_both(params, ds)
    _structure_equal(b_cpu, b_dev)


@pytest.mark.slow  # r19 tier-1 re-budget: 30 s+; binary parity + the
# multiclass rf/wide-bins arms keep cross-backend multiclass covered.
def test_multiclass_parity():
    X, y = covertype_like(2500, num_features=20)
    ds = dryad.Dataset(X, y, max_bins=48)
    params = dict(objective="multiclass", num_class=7, num_trees=4, num_leaves=10,
                  max_bins=48)
    b_cpu, b_dev = _train_both(params, ds)
    _structure_equal(b_cpu, b_dev)
    acc_c = (b_cpu.predict(X).argmax(1) == y).mean()
    acc_d = (b_dev.predict(X).argmax(1) == y).mean()
    assert abs(acc_c - acc_d) < 0.02


def test_categorical_and_bagging_parity():
    rng = np.random.Generator(np.random.Philox(5))
    n = 2000
    cat = rng.integers(0, 12, size=n).astype(np.float32)
    Xnum = rng.normal(size=(n, 5)).astype(np.float32)
    X = np.column_stack([cat, Xnum])
    y = ((cat % 3 == 0).astype(np.float32) * 1.5 + Xnum[:, 0]
         + rng.normal(size=n) * 0.3 > 0.5).astype(np.float32)
    ds = dryad.Dataset(X, y, categorical_features=[0], max_bins=32)
    params = dict(objective="binary", num_trees=6, num_leaves=8, max_bins=32,
                  categorical_features=[0], subsample=0.8, colsample=0.8, seed=9)
    b_cpu, b_dev = _train_both(params, ds)
    _structure_equal(b_cpu, b_dev)
    # the chosen categorical split must actually appear
    assert b_cpu.is_cat.any()


def test_depthwise_parity():
    X, y = higgs_like(2000)
    ds = dryad.Dataset(X, y, max_bins=32)
    params = dict(objective="binary", num_trees=5, num_leaves=16, max_depth=4,
                  growth="depthwise", max_bins=32)
    b_cpu, b_dev = _train_both(params, ds)
    _structure_equal(b_cpu, b_dev)
    assert b_dev.max_depth_seen <= 4


def test_lambdarank_parity():
    X, y, group = mslr_like(num_queries=60, docs_per_query=(5, 30), num_features=16)
    ds = dryad.Dataset(X, y, group=group, max_bins=32)
    params = dict(objective="lambdarank", num_trees=5, num_leaves=8, max_bins=32)
    b_cpu, b_dev = _train_both(params, ds)
    # λ-gradients are fp32 on device vs f64 on host: allow rare structural
    # divergence but demand matching ranking quality
    from dryad_tpu.metrics import ndcg_at_k

    qoff = ds.query_offsets
    nc = ndcg_at_k(y, b_cpu.predict(X, raw_score=True), qoff, 10)
    nd = ndcg_at_k(y, b_dev.predict(X, raw_score=True), qoff, 10)
    assert abs(nc - nd) < 0.02
    assert nd > 0.6


def test_early_stopping_and_best_iteration_device():
    X, y = higgs_like(3000)
    ds = dryad.Dataset(X[:2000], y[:2000], max_bins=32)
    vds = ds.bind(X[2000:], y[2000:])
    params = dict(objective="binary", num_trees=40, num_leaves=8, max_bins=32,
                  learning_rate=0.3, early_stopping_rounds=5)
    b = dryad.train(params, ds, valid_sets=[vds], backend="tpu")
    assert b.best_iteration > 0
    assert b.num_iterations <= 40


def test_resume_device_matches_straight_run():
    X, y = higgs_like(2000)
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(objective="binary", num_leaves=8, max_bins=32, learning_rate=0.2)
    full = dryad.train(dict(base, num_trees=10), ds, backend="tpu")
    half = dryad.train(dict(base, num_trees=5), ds, backend="tpu")
    resumed = dryad.train(dict(base, num_trees=10), ds, backend="tpu",
                          init_booster=half)
    _structure_equal(full, resumed)
    np.testing.assert_allclose(full.value, resumed.value, atol=1e-2)


def test_predict_bit_identity_cpu_vs_device():
    X, y = higgs_like(2000)
    ds = dryad.Dataset(X, y, max_bins=64)
    b = dryad.train(dict(objective="binary", num_trees=10, num_leaves=15,
                         max_bins=64), ds, backend="cpu")
    p_cpu = b.predict(X, raw_score=True, backend="cpu")
    p_dev = b.predict(X, raw_score=True, backend="tpu")
    np.testing.assert_array_equal(p_cpu, p_dev)  # bit-identical, BASELINE.json:5


def test_predict_bit_identity_multiclass():
    X, y = covertype_like(1500, num_features=15)
    ds = dryad.Dataset(X, y, max_bins=32)
    b = dryad.train(dict(objective="multiclass", num_class=7, num_trees=3,
                         num_leaves=8, max_bins=32), ds, backend="cpu")
    p_cpu = b.predict(X, raw_score=True, backend="cpu")
    p_dev = b.predict(X, raw_score=True, backend="tpu")
    np.testing.assert_array_equal(p_cpu, p_dev)


def test_depthwise_budget_pressure_parity():
    """num_leaves budget cuts a level mid-way: gain-order application must
    match the CPU trainer's repeated-argmax sequence exactly."""
    X, y = higgs_like(3000)
    ds = dryad.Dataset(X, y, max_bins=32)
    params = dict(objective="binary", num_trees=4, num_leaves=21, max_depth=6,
                  growth="depthwise", max_bins=32, min_data_in_leaf=5)
    b_cpu, b_dev = _train_both(params, ds)
    _structure_equal(b_cpu, b_dev)


def test_depthwise_categorical_bagging_parity():
    rng = np.random.Generator(np.random.Philox(11))
    n = 2500
    cat = rng.integers(0, 9, size=n).astype(np.float32)
    Xnum = rng.normal(size=(n, 4)).astype(np.float32)
    X = np.column_stack([cat, Xnum])
    y = ((cat % 2 == 0) * 1.2 + Xnum[:, 0] + rng.normal(size=n) * 0.3 > 0.6).astype(np.float32)
    ds = dryad.Dataset(X, y, categorical_features=[0], max_bins=32)
    params = dict(objective="binary", num_trees=5, num_leaves=16, max_depth=4,
                  growth="depthwise", max_bins=32, categorical_features=[0],
                  subsample=0.8, seed=3)
    b_cpu, b_dev = _train_both(params, ds)
    _structure_equal(b_cpu, b_dev)


def test_weighted_training_parity():
    """Sample weights must flow through grads, histograms, and leaf values
    identically on both backends (and predict must reflect them)."""
    rng = np.random.Generator(np.random.Philox(13))
    X, y = higgs_like(2000)
    w = rng.uniform(0.25, 4.0, size=2000).astype(np.float32)
    ds = dryad.Dataset(X, y, weight=w, max_bins=32)
    params = dict(objective="binary", num_trees=5, num_leaves=10, max_bins=32)
    b_cpu, b_dev = _train_both(params, ds)
    _structure_equal(b_cpu, b_dev)
    # weights actually change the model
    ds_u = dryad.Dataset(X, y, max_bins=32)
    b_unw = dryad.train(params, ds_u, backend="cpu")
    assert not np.array_equal(b_cpu.feature, b_unw.feature) or not np.allclose(
        b_cpu.value, b_unw.value)


def test_weighted_lambdarank_device():
    X, y, group = mslr_like(num_queries=30, docs_per_query=(4, 20), num_features=8)
    rng = np.random.Generator(np.random.Philox(17))
    w = rng.uniform(0.5, 2.0, size=y.shape[0]).astype(np.float32)
    ds = dryad.Dataset(X, y, weight=w, group=group, max_bins=32)
    params = dict(objective="lambdarank", num_trees=3, num_leaves=6, max_bins=32)
    b = dryad.train(params, ds, backend="tpu")
    assert np.isfinite(b.value).all()


def test_weight_length_validated():
    X, y = higgs_like(500)
    with pytest.raises(ValueError, match="weight length"):
        dryad.Dataset(X, y, weight=np.ones(10, np.float32))
