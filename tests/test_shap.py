"""Exact TreeSHAP (pred_contrib) — efficiency property + brute-force
Shapley oracle on small trees (path-dependent cover weighting)."""

import itertools
import math

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like


def _brute_force_shap(trees, t, cover, xbins, F):
    """Shapley values by subset enumeration with the path-dependent
    conditional expectation TreeSHAP defines: features outside the
    coalition average children by training covers."""
    feature = trees["feature"][t]
    threshold = trees["threshold"][t]
    left, right = trees["left"][t], trees["right"][t]
    value = trees["value"][t]
    dleft = trees["default_left"][t]

    def f_S(S, node=0):
        f = feature[node]
        if f < 0:
            return float(value[node])
        if f in S:
            b = int(xbins[f])
            go_left = b <= threshold[node] and (dleft[node] or b != 0)
            return f_S(S, left[node] if go_left else right[node])
        cl, cr = float(cover[left[node]]), float(cover[right[node]])
        return (cl * f_S(S, left[node]) + cr * f_S(S, right[node])) / (cl + cr)

    phi = np.zeros(F + 1)
    feats = list(range(F))
    for i in feats:
        for r in range(F):
            for S in itertools.combinations([f for f in feats if f != i], r):
                w = math.factorial(r) * math.factorial(F - r - 1) / math.factorial(F)
                phi[i] += w * (f_S(set(S) | {i}) - f_S(set(S)))
    phi[F] = f_S(set())
    return phi


def test_contrib_matches_bruteforce_small_tree():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(600, 4)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.1 * rng.normal(size=600)
         ).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=16)
    b = dryad.train(dict(objective="regression", num_trees=3, num_leaves=7,
                         max_depth=3, max_bins=16, learning_rate=0.5),
                    ds, backend="cpu")
    Xb = ds.X_binned[:5]
    got = b.predict_binned(ds.X_binned[:5], pred_contrib=True)
    trees = b.tree_arrays()
    for n in range(5):
        want = np.zeros(5)
        want[4] = float(b.init_score[0])
        for t in range(b.num_total_trees):
            want += _brute_force_shap(trees, t, trees["cover"][t], Xb[n], 4)
        np.testing.assert_allclose(got[n], want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("params,objective", [
    (dict(objective="binary", num_trees=10, num_leaves=15, max_depth=4), "binary"),
    (dict(objective="regression", num_trees=8, num_leaves=31, subsample=0.8,
          seed=3, max_depth=5), "regression"),
    (dict(objective="multiclass", num_class=3, num_trees=5, num_leaves=7,
          max_depth=3), "multiclass"),
])
def test_contrib_efficiency_property(params, objective):
    """Contributions + bias column == raw prediction (SHAP efficiency),
    for binary, bagged regression, and multiclass."""
    rng = np.random.default_rng(9)
    X, y = higgs_like(2000, seed=17)
    if objective == "multiclass":
        y = rng.integers(0, 3, size=2000).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(params, max_bins=32)
    b = dryad.train(p, ds, backend="cpu")
    contrib = b.predict_binned(ds.X_binned[:50], pred_contrib=True)
    raw = b.predict_binned(ds.X_binned[:50], raw_score=True)
    if objective == "multiclass":
        total = contrib.sum(axis=2)
        np.testing.assert_allclose(total, raw, rtol=1e-4, atol=1e-5)
    else:
        total = contrib.sum(axis=1)
        np.testing.assert_allclose(total, raw, rtol=1e-4, atol=1e-5)


def test_contrib_device_trained_booster():
    """Device-trained boosters record the same covers (histogram counts),
    so pred_contrib works on them identically."""
    X, y = higgs_like(3000, seed=19)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective="binary", num_trees=5, num_leaves=15, max_depth=4,
             max_bins=32)
    b_dev = dryad.train(p, ds, backend="tpu")
    b_cpu = dryad.train(p, ds, backend="cpu")
    np.testing.assert_array_equal(b_dev.cover, b_cpu.cover)
    c_dev = b_dev.predict_binned(ds.X_binned[:20], pred_contrib=True)
    raw = b_dev.predict_binned(ds.X_binned[:20], raw_score=True)
    np.testing.assert_allclose(c_dev.sum(axis=1), raw, rtol=1e-4, atol=1e-5)


def test_contrib_old_model_without_covers_raises():
    X, y = higgs_like(1000, seed=21)
    ds = dryad.Dataset(X, y, max_bins=32)
    b = dryad.train(dict(objective="binary", num_trees=2, num_leaves=7,
                         max_bins=32), ds, backend="cpu")
    b.cover = np.zeros_like(b.cover)   # simulate a pre-round-4 model
    with pytest.raises(ValueError, match="cover"):
        b.predict_binned(ds.X_binned[:2], pred_contrib=True)
