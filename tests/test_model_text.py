"""Versioned model TEXT dump/load (VERDICT r4 missing #4): a stable,
inspectable JSON format whose round-trip predicts bit-identically —
covering categorical bitsets, per-node covers, gains and learned missing
directions."""

import json

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.booster import Booster


def _cat_nan_model():
    """Categoricals + NaNs + learned missing directions in one model."""
    rng = np.random.default_rng(7)
    N = 6000
    X = rng.normal(size=(N, 6)).astype(np.float32)
    X[:, 0] = rng.integers(0, 12, N)               # categorical
    X[rng.random((N, 6)) < 0.1] = np.nan           # missing everywhere
    y = ((X[:, 0] % 3 == 0) ^ (np.nan_to_num(X[:, 1]) > 0)).astype(np.float32)
    ds = dryad.Dataset(X, y, categorical_features=[0], max_bins=64)
    b = dryad.train(dict(objective="binary", num_trees=12, num_leaves=15),
                    ds, backend="cpu")
    return X, ds, b


def test_text_round_trip_bit_identical(tmp_path):
    X, ds, b = _cat_nan_model()
    path = str(tmp_path / "model.txt")
    b.save_text(path)
    rb = Booster.load_text(path)
    # every array round-trips exactly
    for key in ("feature", "threshold", "left", "right", "value", "is_cat",
                "cat_bitset", "gain", "cover", "default_left"):
        np.testing.assert_array_equal(getattr(b, key), getattr(rb, key),
                                      err_msg=key)
    np.testing.assert_array_equal(b.init_score, rb.init_score)
    # raw predict on RAW features (exercises the mapper round-trip too)
    np.testing.assert_array_equal(
        dryad.predict(b, X, raw_score=True),
        dryad.predict(rb, X, raw_score=True))
    # and on both backends
    np.testing.assert_array_equal(
        rb.predict_binned(ds.X_binned, raw_score=True, backend="cpu"),
        np.asarray(rb.predict_binned(ds.X_binned, raw_score=True,
                                     backend="tpu")))


def test_text_dump_is_inspectable_json():
    _, _, b = _cat_nan_model()
    doc = json.loads(b.dump_text())
    assert doc["format"] == "dryad-text"
    assert doc["format_version"] == 1
    assert doc["params"]["objective"] == "binary"
    t0 = doc["trees"][0]
    for key in ("feature", "threshold", "left", "right", "value", "is_cat",
                "default_left", "gain", "cover", "cat_bitset"):
        assert key in t0, key
    # the categorical split's bitset really appears
    assert any(tr["cat_bitset"] for tr in doc["trees"])


def test_text_version_guard():
    _, _, b = _cat_nan_model()
    doc = json.loads(b.dump_text())
    doc["format_version"] = 99
    with pytest.raises(ValueError, match="newer"):
        Booster.from_text(json.dumps(doc))
    with pytest.raises(ValueError, match="not a dryad"):
        Booster.from_text(json.dumps({"format": "something-else"}))


def test_text_round_trip_bundled_efb(tmp_path):
    """EFB-bundled (sparse) models carry the bundle plan through text."""
    rng = np.random.default_rng(3)
    N, F = 4000, 30
    X = np.zeros((N, F), np.float32)
    for f in range(F):            # mutually exclusive-ish sparse columns
        rows = rng.choice(N, N // F, replace=False)
        X[rows, f] = rng.normal(size=rows.size)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32)
    b = dryad.train(dict(objective="binary", num_trees=6, num_leaves=15),
                    ds, backend="cpu")
    path = str(tmp_path / "m.txt")
    b.save_text(path)
    rb = Booster.load_text(path)
    np.testing.assert_array_equal(
        dryad.predict(b, X, raw_score=True),
        dryad.predict(rb, X, raw_score=True))


def test_text_round_trip_multiclass_shap(tmp_path):
    """Covers survive: SHAP on the reloaded model equals the original."""
    from dryad_tpu.datasets import covertype_like

    X, y = covertype_like(3000, seed=5)
    ds = dryad.Dataset(X, y, max_bins=32)
    b = dryad.train(dict(objective="multiclass", num_class=7, num_trees=4,
                         num_leaves=15, max_bins=32), ds, backend="cpu")
    rb = Booster.from_text(b.dump_text())
    np.testing.assert_array_equal(
        b.predict_binned(ds.X_binned[:100], pred_contrib=True),
        rb.predict_binned(ds.X_binned[:100], pred_contrib=True))
