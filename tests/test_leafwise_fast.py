"""Batched (expansion+selection) leaf-wise grower vs the sequential slot
machine: identical trees, node numbering included.

Gains are order-independent, so the batched grower must reproduce the
sequential one EXACTLY whenever both see the same histogram values; these
fixtures are tie-free so fp noise cannot flip argmaxes.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dryad_tpu.config import make_params
from dryad_tpu.engine.grower import grow_any, grow_tree
from dryad_tpu.engine.leafwise_fast import (
    grow_tree_leafwise_batched,
    supports,
)


def _fixture(n=20_000, f=8, b=32, seed=3, cat=False):
    rng = np.random.default_rng(seed)
    Xb = jnp.asarray(rng.integers(1, b, size=(n, f), dtype=np.uint8))
    yv = rng.normal(size=n)
    g = jnp.asarray((yv + rng.normal(size=n) * 0.1).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.5, 1.5, size=n).astype(np.float32))
    bag = jnp.asarray(rng.random(n) < 0.85)
    fmask = jnp.ones((f,), bool)
    iscat = jnp.zeros((f,), bool)
    if cat:
        iscat = iscat.at[0].set(True).at[3].set(True)
    return Xb, g, h, bag, fmask, iscat


def _assert_same_tree(seq, bat):
    for key in ("feature", "threshold", "left", "right", "default_left",
                "is_cat", "cat_bitset"):
        np.testing.assert_array_equal(np.asarray(seq[key]),
                                      np.asarray(bat[key]), err_msg=key)
    # leaf stats ride different histogram programs (masked XLA pass vs
    # segmented tiles) -> ulp-level value differences; structure is exact
    np.testing.assert_allclose(np.asarray(seq["value"]),
                               np.asarray(bat["value"]), rtol=1e-4,
                               atol=2e-6)
    np.testing.assert_array_equal(np.asarray(seq["row_leaf"]),
                                  np.asarray(bat["row_leaf"]))
    assert int(seq["max_depth"]) == int(bat["max_depth"])


@pytest.mark.parametrize("leaves,depth,lm", [(31, 5, False), (15, 8, False),
                                             (63, 6, True)])
def test_batched_equals_sequential(leaves, depth, lm):
    Xb, g, h, bag, fmask, iscat = _fixture()
    p = make_params(dict(objective="l2", num_leaves=leaves, max_depth=depth,
                         growth="leafwise", min_data_in_leaf=20))
    seq = grow_tree(p, 32, Xb, g, h, bag, fmask, iscat, learn_missing=lm)
    bat = grow_tree_leafwise_batched(p, 32, Xb, g, h, bag, fmask, iscat,
                                     learn_missing=lm)
    _assert_same_tree(seq, bat)


def test_batched_equals_sequential_categorical():
    Xb, g, h, bag, fmask, iscat = _fixture(cat=True)
    p = make_params(dict(objective="l2", num_leaves=31, max_depth=6,
                         growth="leafwise", min_data_in_leaf=20))
    seq = grow_tree(p, 32, Xb, g, h, bag, fmask, iscat, has_cat=True)
    bat = grow_tree_leafwise_batched(p, 32, Xb, g, h, bag, fmask, iscat,
                                     has_cat=True)
    _assert_same_tree(seq, bat)


def test_batched_equals_sequential_monotone():
    Xb, g, h, bag, fmask, iscat = _fixture()
    p = make_params(dict(objective="l2", num_leaves=31, max_depth=6,
                         growth="leafwise", min_data_in_leaf=20,
                         monotone_constraints=[1, 0, -1, 0, 0, 0, 0, 0]))
    seq = grow_tree(p, 32, Xb, g, h, bag, fmask, iscat)
    bat = grow_tree_leafwise_batched(p, 32, Xb, g, h, bag, fmask, iscat)
    _assert_same_tree(seq, bat)


def test_grow_any_routes_by_depth():
    """max_depth set -> batched path; unset (-1) -> sequential (an unbounded
    tree cannot be pre-expanded)."""
    p_fast = make_params(dict(objective="l2", num_leaves=31, max_depth=6,
                              growth="leafwise"))
    p_seq = make_params(dict(objective="l2", num_leaves=31,
                             growth="leafwise"))
    assert supports(p_fast, 8, 32)
    assert not supports(p_seq, 8, 32)
    # huge expansion exceeds the hist-buffer budget -> sequential
    p_wide = make_params(dict(objective="l2", num_leaves=31, max_depth=14,
                              growth="leafwise"))
    assert not supports(p_wide, 2000, 256)
    # the routed result matches the sequential grower
    Xb, g, h, bag, fmask, iscat = _fixture(n=5000)
    seq = grow_tree(p_fast, 32, Xb, g, h, bag, fmask, iscat)
    routed = grow_any(p_fast, 32, Xb, g, h, bag, fmask, iscat)
    routed.pop("row_leaf")
    for key in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(np.asarray(seq[key]),
                                      np.asarray(routed[key]))
