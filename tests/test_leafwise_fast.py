"""Batched (expansion+selection) leaf-wise grower vs the sequential slot
machine: identical trees, node numbering included.

Gains are order-independent, so the batched grower must reproduce the
sequential one EXACTLY whenever both see the same histogram values; these
fixtures are tie-free so fp noise cannot flip argmaxes.
"""

import numpy as np
import pytest

import jax.numpy as jnp

# r19: slow — the wired batched-leafwise parity fixtures pay the
# run-bookkeeping tiles in interpret-mode Python (STATUS Round-10 note);
# part of the tier-1 870 s re-budget (ci.sh runs `-m 'not slow'`).
pytestmark = pytest.mark.slow

from dryad_tpu.config import make_params
from dryad_tpu.engine.grower import grow_any, grow_tree
from dryad_tpu.engine.leafwise_fast import (
    effective_depth_params,
    grow_tree_leafwise_batched,
    supports,
)


def _fixture(n=20_000, f=8, b=32, seed=3, cat=False):
    rng = np.random.default_rng(seed)
    Xb = jnp.asarray(rng.integers(1, b, size=(n, f), dtype=np.uint8))
    yv = rng.normal(size=n)
    g = jnp.asarray((yv + rng.normal(size=n) * 0.1).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.5, 1.5, size=n).astype(np.float32))
    bag = jnp.asarray(rng.random(n) < 0.85)
    fmask = jnp.ones((f,), bool)
    iscat = jnp.zeros((f,), bool)
    if cat:
        iscat = iscat.at[0].set(True).at[3].set(True)
    return Xb, g, h, bag, fmask, iscat


def _assert_same_tree(seq, bat):
    for key in ("feature", "threshold", "left", "right", "default_left",
                "is_cat", "cat_bitset"):
        np.testing.assert_array_equal(np.asarray(seq[key]),
                                      np.asarray(bat[key]), err_msg=key)
    # leaf stats ride different histogram programs (masked XLA pass vs
    # segmented tiles) -> ulp-level value differences; structure is exact
    np.testing.assert_allclose(np.asarray(seq["value"]),
                               np.asarray(bat["value"]), rtol=1e-4,
                               atol=2e-6)
    np.testing.assert_array_equal(np.asarray(seq["row_leaf"]),
                                  np.asarray(bat["row_leaf"]))
    assert int(seq["max_depth"]) == int(bat["max_depth"])


@pytest.mark.parametrize("leaves,depth,lm", [(31, 5, False), (15, 8, False),
                                             (63, 6, True)])
def test_batched_equals_sequential(leaves, depth, lm):
    Xb, g, h, bag, fmask, iscat = _fixture()
    p = make_params(dict(objective="l2", num_leaves=leaves, max_depth=depth,
                         growth="leafwise", min_data_in_leaf=20))
    seq = grow_tree(p, 32, Xb, g, h, bag, fmask, iscat, learn_missing=lm)
    bat = grow_tree_leafwise_batched(p, 32, Xb, g, h, bag, fmask, iscat,
                                     learn_missing=lm)
    _assert_same_tree(seq, bat)


def test_batched_equals_sequential_categorical():
    Xb, g, h, bag, fmask, iscat = _fixture(cat=True)
    p = make_params(dict(objective="l2", num_leaves=31, max_depth=6,
                         growth="leafwise", min_data_in_leaf=20))
    seq = grow_tree(p, 32, Xb, g, h, bag, fmask, iscat, has_cat=True)
    bat = grow_tree_leafwise_batched(p, 32, Xb, g, h, bag, fmask, iscat,
                                     has_cat=True)
    _assert_same_tree(seq, bat)


def test_batched_equals_sequential_monotone():
    Xb, g, h, bag, fmask, iscat = _fixture()
    p = make_params(dict(objective="l2", num_leaves=31, max_depth=6,
                         growth="leafwise", min_data_in_leaf=20,
                         monotone_constraints=[1, 0, -1, 0, 0, 0, 0, 0]))
    seq = grow_tree(p, 32, Xb, g, h, bag, fmask, iscat)
    bat = grow_tree_leafwise_batched(p, 32, Xb, g, h, bag, fmask, iscat)
    _assert_same_tree(seq, bat)


def test_batched_equals_sequential_cat_and_missing():
    """Combined categorical + learn_missing routing (ADVICE r3 #4): the
    packed-word partition applies the missing-direction AND before the
    categorical override — the interaction most likely to regress silently.
    Bin 0 plays 'missing' on the numeric features; categorical subset
    splits must override the missing plane entirely."""
    rng = np.random.default_rng(11)
    n, f, b = 20_000, 8, 32
    Xb_np = rng.integers(1, b, size=(n, f), dtype=np.uint8)
    # missing-heavy numeric columns + two categorical columns
    miss = rng.random((n, f)) < 0.25
    miss[:, 0] = False
    miss[:, 3] = False
    Xb_np[miss] = 0
    Xb = jnp.asarray(Xb_np)
    yv = rng.normal(size=n)
    g = jnp.asarray((yv + rng.normal(size=n) * 0.1).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.5, 1.5, size=n).astype(np.float32))
    bag = jnp.asarray(rng.random(n) < 0.9)
    fmask = jnp.ones((f,), bool)
    iscat = jnp.zeros((f,), bool).at[0].set(True).at[3].set(True)
    p = make_params(dict(objective="l2", num_leaves=31, max_depth=6,
                         growth="leafwise", min_data_in_leaf=20))
    seq = grow_tree(p, b, Xb, g, h, bag, fmask, iscat, has_cat=True,
                    learn_missing=True)
    bat = grow_tree_leafwise_batched(p, b, Xb, g, h, bag, fmask, iscat,
                                     has_cat=True, learn_missing=True)
    _assert_same_tree(seq, bat)


def _wired_params(extra=None, **kw):
    """A config the layout gate ADMITS on forced-CPU CI: interpret-mode
    Pallas (hist_backend="pallas") + a depth within the run-capacity cap."""
    base = dict(objective="l2", num_leaves=31, max_depth=6,
                growth="leafwise", min_data_in_leaf=20,
                hist_backend="pallas")
    base.update(extra or {})
    base.update(kw)
    return make_params(base)


def test_wired_gate_admits_fixture():
    """The fixtures below must actually exercise the layout-wired
    expansion — if the gate stops admitting them, this file would
    silently test the legacy path.  Also pins the gate's own edges:
    legacy opt-out, the run-capacity depth cap, and the XLA backend."""
    from dryad_tpu.engine.leafwise_fast import leafwise_layout_supported

    p = _wired_params()
    assert leafwise_layout_supported(p, 8, 32, 1, "cpu")
    assert not leafwise_layout_supported(
        p.replace(deep_layout="legacy"), 8, 32, 1, "cpu")
    # run-capacity cap: 2^max_depth must fit the dense run bookkeeping
    assert leafwise_layout_supported(
        _wired_params(num_leaves=512, max_depth=10), 8, 32, 1, "cpu")
    assert not leafwise_layout_supported(
        _wired_params(num_leaves=512, max_depth=11), 8, 32, 1, "cpu")
    # CPU 'auto' resolves to XLA -> no tile layout to feed
    assert not leafwise_layout_supported(
        _wired_params(hist_backend="auto"), 8, 32, 1, "cpu")


@pytest.mark.parametrize("leaves,depth,lm", [(31, 5, False), (15, 7, False),
                                             (63, 6, True)])
def test_wired_batched_equals_sequential(leaves, depth, lm):
    """Layout-wired expansion (r10) ≡ sequential leaf-wise, tree for tree
    incl. node numbering — the same equivalence the legacy expansion pins,
    now with sides derived from the carried layout records and histograms
    read as contiguous tile runs."""
    from dryad_tpu.engine.leafwise_fast import leafwise_layout_supported

    Xb, g, h, bag, fmask, iscat = _fixture()
    p = _wired_params(num_leaves=leaves, max_depth=depth)
    assert leafwise_layout_supported(p, Xb.shape[1], 32, 1, "cpu")
    seq = grow_tree(p, 32, Xb, g, h, bag, fmask, iscat, learn_missing=lm)
    bat = grow_tree_leafwise_batched(p, 32, Xb, g, h, bag, fmask, iscat,
                                     learn_missing=lm, platform="cpu")
    _assert_same_tree(seq, bat)


def test_wired_batched_equals_legacy_batched():
    """Wired vs legacy batched expansion on the tie-free fixture: bitwise
    tree structures AND row_leaf (both derive sides from the same packed
    arithmetic; only the histogram/movement programs differ)."""
    Xb, g, h, bag, fmask, iscat = _fixture()
    p_w = _wired_params()
    bat_w = grow_tree_leafwise_batched(p_w, 32, Xb, g, h, bag, fmask, iscat,
                                       platform="cpu")
    bat_l = grow_tree_leafwise_batched(p_w.replace(deep_layout="legacy"),
                                       32, Xb, g, h, bag, fmask, iscat,
                                       platform="cpu")
    for key in ("feature", "threshold", "left", "right", "default_left",
                "is_cat", "cat_bitset", "row_leaf"):
        np.testing.assert_array_equal(np.asarray(bat_w[key]),
                                      np.asarray(bat_l[key]), err_msg=key)
    np.testing.assert_allclose(np.asarray(bat_w["value"]),
                               np.asarray(bat_l["value"]), rtol=1e-4,
                               atol=2e-6)


def test_wired_batched_cat_and_missing_equals_sequential():
    """The wired side derivation's categorical-bitset and learned-missing
    branches (packed_route bits 29/30 against heap-node tables) — the
    interaction most likely to regress silently, now over the carried
    layout records."""
    rng = np.random.default_rng(11)
    n, f, b = 20_000, 8, 32
    Xb_np = rng.integers(1, b, size=(n, f), dtype=np.uint8)
    miss = rng.random((n, f)) < 0.25
    miss[:, 0] = False
    miss[:, 3] = False
    Xb_np[miss] = 0
    Xb = jnp.asarray(Xb_np)
    yv = rng.normal(size=n)
    g = jnp.asarray((yv + rng.normal(size=n) * 0.1).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.5, 1.5, size=n).astype(np.float32))
    bag = jnp.asarray(rng.random(n) < 0.9)
    fmask = jnp.ones((f,), bool)
    iscat = jnp.zeros((f,), bool).at[0].set(True).at[3].set(True)
    p = _wired_params()
    seq = grow_tree(p, b, Xb, g, h, bag, fmask, iscat, has_cat=True,
                    learn_missing=True)
    bat = grow_tree_leafwise_batched(p, b, Xb, g, h, bag, fmask, iscat,
                                     has_cat=True, learn_missing=True,
                                     platform="cpu")
    _assert_same_tree(seq, bat)


def test_effective_depth_policy():
    """max_depth=-1 maps to min(ceil(log2(L))+4, 14) under 'auto' whenever
    the batched grower can take the config; 'exact' and infeasible shapes
    keep true-unbounded (VERDICT r3 #3)."""
    p = make_params(dict(objective="l2", num_leaves=255, growth="leafwise"))
    assert effective_depth_params(p, 28, 256).max_depth == 12
    p31 = make_params(dict(objective="l2", num_leaves=31, growth="leafwise"))
    assert effective_depth_params(p31, 8, 32).max_depth == 9
    # explicit cap: untouched
    p_cap = p.replace(max_depth=7)
    assert effective_depth_params(p_cap, 28, 256) is p_cap
    # opt-out: untouched
    p_exact = p.replace(unbounded_depth="exact")
    assert effective_depth_params(p_exact, 28, 256) is p_exact
    # depthwise: untouched (policy is leaf-wise only)
    p_dw = make_params(dict(objective="l2", num_leaves=255,
                            growth="depthwise"))
    assert effective_depth_params(p_dw, 28, 256) is p_dw
    # expansion budget exceeded at the capped depth -> sequential unbounded
    assert effective_depth_params(p, 2000, 256) is p
    # subtraction disabled -> batched grower unavailable -> untouched
    p_nosub = p.replace(hist_subtraction=False)
    assert effective_depth_params(p_nosub, 28, 256) is p_nosub


def test_default_config_rides_batched_grower():
    """End-to-end: the out-of-the-box leaf-wise config (max_depth=-1) must
    train identically to the explicit effective-depth config on BOTH
    backends (the policy is applied identically in cpu/trainer.py and
    engine/train.py)."""
    import dryad_tpu as dryad

    rng = np.random.default_rng(5)
    X = rng.normal(size=(4000, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + rng.normal(size=4000) * 0.1 > 0.3)
    ds = dryad.Dataset(X, y.astype(np.float64), max_bins=32)
    p_auto = make_params(dict(objective="binary", num_trees=4,
                              num_leaves=31, growth="leafwise"))
    p_expl = p_auto.replace(max_depth=9)
    for backend in ("cpu", "tpu"):
        b_auto = dryad.train(p_auto, ds, backend=backend)
        b_expl = dryad.train(p_expl, ds, backend=backend)
        np.testing.assert_array_equal(b_auto.feature, b_expl.feature)
        np.testing.assert_array_equal(b_auto.threshold, b_expl.threshold)
        np.testing.assert_array_equal(
            b_auto.predict(X, raw_score=True),
            b_expl.predict(X, raw_score=True))
    # and CPU == device on the default config itself
    b_cpu = dryad.train(p_auto, ds, backend="cpu")
    b_dev = dryad.train(p_auto, ds, backend="tpu")
    np.testing.assert_array_equal(b_cpu.feature, b_dev.feature)
    np.testing.assert_array_equal(b_cpu.threshold, b_dev.threshold)


def test_grow_any_routes_by_depth():
    """max_depth set -> batched path; unset (-1) -> sequential (an unbounded
    tree cannot be pre-expanded)."""
    p_fast = make_params(dict(objective="l2", num_leaves=31, max_depth=6,
                              growth="leafwise"))
    p_seq = make_params(dict(objective="l2", num_leaves=31,
                             growth="leafwise"))
    assert supports(p_fast, 8, 32)
    assert not supports(p_seq, 8, 32)
    # huge expansion exceeds the hist-buffer budget -> sequential
    p_wide = make_params(dict(objective="l2", num_leaves=31, max_depth=14,
                              growth="leafwise"))
    assert not supports(p_wide, 2000, 256)
    # the routed result matches the sequential grower
    Xb, g, h, bag, fmask, iscat = _fixture(n=5000)
    seq = grow_tree(p_fast, 32, Xb, g, h, bag, fmask, iscat)
    routed = grow_any(p_fast, 32, Xb, g, h, bag, fmask, iscat)
    routed.pop("row_leaf")
    for key in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(np.asarray(seq[key]),
                                      np.asarray(routed[key]))


def test_memory_envelope_guard_pure_function():
    """The batched grower's envelope (VERDICT r3 #7) is a pure function of
    params + GLOBAL data shape: wide-feature deep caps reject on the pinned
    buffer, huge-N wide configs reject on peak residency, and the policy
    never consults the backend."""
    from dryad_tpu.config import (
        effective_depth_params, leafwise_fast_supported, make_params,
    )

    d12 = make_params(dict(num_leaves=4095, max_depth=12))
    assert not leafwise_fast_supported(d12, 2000, 256, 400_000)   # pinned
    d6 = make_params(dict(num_leaves=63, max_depth=6))
    assert leafwise_fast_supported(d6, 2000, 256, 400_000)
    assert not leafwise_fast_supported(d6, 2000, 256, 5_000_000)  # N-aware
    # max_depth=-1 auto policy consults the same envelope: the wide config
    # keeps true-unbounded sequential semantics instead of a doomed cap
    auto = make_params(dict(num_leaves=255))
    assert effective_depth_params(auto, 28, 256, 200_000).max_depth == 12
    assert effective_depth_params(auto, 2000, 256, 40_000_000).max_depth == -1


def test_envelope_fallback_trains_sequential():
    """An over-envelope depth-capped leaf-wise config must fall back to the
    sequential grower DETERMINISTICALLY (same trees as an in-envelope run
    forced sequential via hist_subtraction=False is not comparable — so we
    just pin: it trains, warns, and matches the CPU backend)."""
    import warnings

    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(1500, seed=31)
    ds = dryad.Dataset(X, y, max_bins=32)
    # depth 15 exceeds MAX_FAST_DEPTH -> batched grower rejects
    p = dict(objective="binary", num_trees=3, num_leaves=31, max_depth=15)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        b_dev = dryad.train(p, ds, backend="tpu")
    assert any("sequential grower" in str(x.message) for x in w)
    b_cpu = dryad.train(p, ds, backend="cpu")
    np.testing.assert_array_equal(b_dev.feature, b_cpu.feature)
    np.testing.assert_array_equal(b_dev.threshold, b_cpu.threshold)
