"""Protocol stub replica for fleet tests (NOT a test module).

Speaks the slice of the serve HTTP surface the fleet layer touches —
``/healthz``, ``/predict``, ``/metrics``, ``/models/load`` and the
``--port-file`` readiness handshake — in pure stdlib, so a fleet test
spawns replicas in ~100 ms instead of paying the jax import per
subprocess.  The REAL serve replica path is covered by
``scripts/smoke_fleet.py`` (ci.sh) and the fleet bench; these tests pin
the supervisor/router logic, which only ever sees the wire protocol.

r17 additions, still pure stdlib: ``/predict`` echoes ``X-Dryad-Trace``
back (the round-trip contract) and appends a span-shaped event to an
in-memory ring served by ``/trace/events``; ``/clock`` answers the
supervisor's offset handshake; ``/obs`` serves a registry-snapshot-shaped
JSON whose ``dryad_request_latency_seconds`` counts ride the FIXED
62-slot log-bucket layout (obs/registry.LOG_BUCKETS has 61 bounds — a
count array of any other length is SKIPPED by the router's merge, so a
mismatched stub silently contributes nothing), so router merge tests
run against the wire shape without a jax import.  r18: ``/obs`` also
carries a drift block (DriftMonitor.export_state shape) — balanced
counts by default, skewed under ``--drift-shift`` — for the router's
exact drift merge + ``/drift`` verdict tests.

Deterministic failure shapes, flag-armed:

    --crash-on-path     GET /boom hard-exits with code 23 (injected-crash
                        twin; same exit code as faults.REPLICA_CRASH_EXIT)
    --predict-503       every /predict answers 503 (stuck-shedding replica)
    --health-503-after N  /healthz answers 200 for the first N probes
                        (startup readiness passes), then latches 503
                        forever (the stuck-503 replica)
    --fail-start        exit(7) before binding (spawn-failure drill)
    --predict-delay S   hold each /predict S seconds (in-flight windows)
    --load-delay S      hold each /models/load S seconds

``/predict`` answers like serve does ({"predictions": [...], "version"})
with the version READ AT REQUEST START — the same pin-at-submit
semantics serve's registry gives, which is what makes the rolling-swap
drain assertions meaningful.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):
        pass

    def _send(self, code: int, payload, ctype="application/json") -> None:
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode())
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _authorized(self) -> bool:
        """Serve's bearer scheme: everything but /healthz 401s without
        the token (pins the router's authed replica scrape)."""
        token = self.server.cfg.auth_token
        if not token or self.path == "/healthz":
            return True
        if self.headers.get("Authorization") == f"Bearer {token}":
            return True
        self._send(401, {"error": "unauthorized"})
        return False

    def do_GET(self):  # noqa: N802 — stdlib handler API
        cfg = self.server.cfg
        if not self._authorized():
            return
        if self.path == "/healthz":
            self.server.health_probes += 1
            latched = (cfg.health_503_after >= 0
                       and self.server.health_probes > cfg.health_503_after)
            if latched:
                self._send(503, {"ok": False, "degraded": ["stub"]})
            else:
                self._send(200, {"ok": True})
        elif self.path == "/metrics":
            text = ("# HELP stub_requests_total requests seen\n"
                    "# TYPE stub_requests_total counter\n"
                    f"stub_requests_total {self.server.requests}\n"
                    'stub_latency_ms{path="/predict"} 1.5\n')
            self._send(200, text.encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/clock":
            self._send(200, {"perf_s": time.perf_counter(),
                             "wall_s": time.time()})
        elif self.path == "/trace/events":
            self._send(200, {"events": list(self.server.trace_events),
                             "dropped": 0,
                             "clock": {"perf_s": time.perf_counter(),
                                       "wall_s": time.time()}})
        elif self.path == "/obs":
            # 61 bounds + overflow — MUST match obs/registry.LOG_BUCKETS
            counts = [0] * 62
            n = self.server.requests
            counts[25] = n                     # ~31.6 ms bucket
            lbl = 'priority="interactive",stage="total"'
            doc = {"histograms": {
                "dryad_request_latency_seconds": {
                    lbl: {"counts": counts, "sum": 0.0316 * n,
                          "count": n, "log": True}}}}
            # r18 drift block (the serve DriftMonitor.export_state
            # shape): balanced window counts by default — PSI ~0 — or a
            # skewed window under --drift-shift, so router merge/verdict
            # tests run against the wire shape without a jax import
            window = ([[0, 0, 16, 16], [0, 0, 16, 16]]
                      if cfg.drift_shift else
                      [[8, 8, 8, 8], [8, 8, 8, 8]])
            doc["drift"] = {"stub": {
                "model": "stub", "rows": 32, "window_rows": 64,
                "bins": [4, 4], "features": window,
                "ref_features": [[8, 8, 8, 8], [8, 8, 8, 8]],
                "score": None, "ref_score": None}}
            self._send(200, doc)
        elif self.path == "/boom" and cfg.crash_on_path:
            os._exit(23)
        else:
            self._send(404, {"error": "unknown path"})

    def do_POST(self):  # noqa: N802 — stdlib handler API
        cfg = self.server.cfg
        if not self._authorized():
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b"{}"
        if self.path == "/predict":
            t0 = time.perf_counter()
            self.server.requests += 1
            version = self.server.version     # pin at request start
            trace = self.headers.get("X-Dryad-Trace")
            if cfg.predict_503:
                self._send(503, {"error": "stub shedding"})
                return
            if cfg.predict_delay > 0:
                time.sleep(cfg.predict_delay)
            try:
                rows = json.loads(body).get("rows", [])
            except ValueError:
                rows = []
            # serve-shaped trace behavior: echo the propagated id and
            # ring one span-shaped event for /trace/events
            self.server.trace_events.append(
                ["serve.request/predict", t0,
                 time.perf_counter() - t0, 1, trace])
            payload = json.dumps({"predictions": [0.5] * len(rows),
                                  "version": version}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            if trace:
                self.send_header("X-Dryad-Trace", trace)
            self.end_headers()
            self.wfile.write(payload)
        elif self.path == "/models/load":
            if cfg.load_delay > 0:
                time.sleep(cfg.load_delay)
            with self.server.version_lock:
                self.server.version += 1
                v = self.server.version
            self._send(200, {"version": v})
        else:
            self._send(404, {"error": "unknown path"})


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port-file", required=True)
    ap.add_argument("--version", type=int, default=1)
    ap.add_argument("--predict-delay", type=float, default=0.0)
    ap.add_argument("--load-delay", type=float, default=0.0)
    ap.add_argument("--crash-on-path", action="store_true")
    ap.add_argument("--predict-503", action="store_true")
    ap.add_argument("--health-503-after", type=int, default=-1)
    ap.add_argument("--auth-token", default=None)
    ap.add_argument("--fail-start", action="store_true")
    ap.add_argument("--drift-shift", action="store_true")
    cfg = ap.parse_args()
    if cfg.fail_start:
        return 7
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
    httpd.daemon_threads = True
    httpd.cfg = cfg
    httpd.version = cfg.version
    httpd.version_lock = threading.Lock()
    httpd.requests = 0
    httpd.health_probes = 0
    httpd.trace_events = []
    host, port = httpd.server_address[:2]
    tmp = cfg.port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{host} {port}\n")
    os.replace(tmp, cfg.port_file)
    httpd.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
