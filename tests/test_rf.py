"""Random-forest boosting mode (boosting="rf", LightGBM rf semantics —
SURVEY.md §2 #9/#10 de-facto surface; VERDICT r4 missing #2).

Semantics pinned here (config.py rf note): trees fit gradients at the
CONSTANT init score on per-iteration bags, shrinkage is forced to 1.0,
and predictions AVERAGE the trees: raw = init + Σ_t value_t / n_iter.
"""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.metrics import auc

PARAMS = dict(objective="binary", boosting="rf", num_trees=25,
              num_leaves=31, max_depth=6, max_bins=64, subsample=0.7,
              colsample=0.8, seed=5)


@pytest.fixture(scope="module")
def data():
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(8000, seed=3)
    return X, y, dryad.Dataset(X, y, max_bins=64)


def test_rf_requires_bagging():
    with pytest.raises(ValueError, match="subsample"):
        dryad.make_params(dict(PARAMS, subsample=1.0))


def test_rf_forces_unit_shrinkage():
    p = dryad.make_params(dict(PARAMS, learning_rate=0.05))
    assert p.effective_learning_rate == 1.0
    assert dryad.make_params(dict(PARAMS, boosting="gbdt", subsample=1.0,
                                  learning_rate=0.05)
                             ).effective_learning_rate == 0.05


def test_rf_cpu_device_parity(data):
    """CLAUDE.md invariant: identical structures; near-equal values
    (separately-trained value tables differ by reduction order, same
    tolerance class as DART); bit-identical predict on the SAME booster."""
    X, y, ds = data
    bc = dryad.train(PARAMS, ds, backend="cpu")
    bt = dryad.train(PARAMS, ds, backend="tpu")
    np.testing.assert_array_equal(bc.feature, bt.feature)
    np.testing.assert_array_equal(bc.threshold, bt.threshold)
    np.testing.assert_allclose(bc.value, bt.value, rtol=1e-4, atol=1e-6)
    p_cpu = bc.predict_binned(ds.X_binned, raw_score=True, backend="cpu")
    p_tpu = bc.predict_binned(ds.X_binned, raw_score=True, backend="tpu")
    np.testing.assert_array_equal(p_cpu, np.asarray(p_tpu))


def test_rf_prediction_is_average_of_trees(data):
    """raw == init + Σ_t value_t * (1/n) with the host-computed reciprocal."""
    X, y, ds = data
    b = dryad.train(PARAMS, ds, backend="cpu")
    raw = b.predict_binned(ds.X_binned, raw_score=True)
    from dryad_tpu.cpu.predict import predict_tree_leaves

    trees = b.tree_arrays()
    total = np.zeros(ds.X_binned.shape[0], np.float32)
    for t in range(b.num_total_trees):
        lv = predict_tree_leaves(trees, ds.X_binned, t, b.max_depth_seen)
        total += b.value[t, lv]
    inv = np.float32(1.0) / np.float32(b.num_iterations)
    expect = np.float32(b.init_score[0]) + total * inv
    np.testing.assert_allclose(raw, expect, rtol=1e-6, atol=1e-7)
    # trees are full-strength: averaging (not summing) keeps raw bounded
    assert np.abs(raw).max() < np.abs(total).max()


def test_rf_quality_and_differs_from_gbdt(data):
    X, y, ds = data
    b_rf = dryad.train(PARAMS, ds, backend="cpu")
    b_gb = dryad.train(dict(PARAMS, boosting="gbdt"), ds, backend="cpu")
    a_rf = auc(y, dryad.predict(b_rf, X, raw_score=True))
    a_gb = auc(y, dryad.predict(b_gb, X, raw_score=True))
    assert a_rf > 0.7                       # forest learns
    assert not np.array_equal(b_rf.value, b_gb.value)
    # rf trees all fit the SAME constant-gradient target: structures repeat
    # only bag-to-bag, so the model is valid but weaker than boosting here
    assert a_gb - a_rf < 0.15


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_rf_valid_bookkeeping_matches_predict(data, backend):
    """The metric streamed during training scores the AVERAGED model —
    exactly what predict serves."""
    X, y, ds = data
    seen = {}
    b = dryad.train(dict(PARAMS, num_trees=10), ds, [ds], backend=backend,
                    callback=lambda it, info: seen.update(info))
    # seen holds the LAST iteration's value; predict defaults to
    # best_iteration (recorded for rf — sound, unlike DART), so recompute
    # at the full length explicitly
    recomp = auc(y, b.predict_binned(ds.X_binned, raw_score=True,
                                     num_iteration=b.num_iterations))
    assert abs(seen["valid_auc"] - recomp) < 1e-5


def test_rf_chunked_deferred_eval_matches_recompute(data):
    """No callback / no early stopping -> the CHUNKED device program runs
    rf (constant-gradient grads + in-program averaged eval); its deferred
    history must score the model predict serves."""
    X, y, ds = data
    b = dryad.train(dict(PARAMS, num_trees=10), ds, [ds], backend="tpu")
    hist = b.train_state["eval_history"]["valid_auc"]
    assert [it for it, _ in hist] == list(range(10))
    recomp = auc(y, b.predict_binned(ds.X_binned, raw_score=True,
                                     num_iteration=b.num_iterations))
    # same math, different fusion shape (documented tolerance)
    np.testing.assert_allclose(hist[-1][1], recomp, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_rf_kill_and_resume_bit_identical(tmp_path, data, backend):
    X, y, ds = data
    p = dict(PARAMS, num_trees=12)
    full = dryad.train(p, ds, backend=backend)

    class Crash(RuntimeError):
        pass

    def crash_at(it, info):
        if it == 7:
            raise Crash

    ckdir = str(tmp_path / backend)
    with pytest.raises(Crash):
        dryad.train(p, ds, backend=backend, checkpoint_dir=ckdir,
                    checkpoint_every=3, callback=crash_at)
    resumed = dryad.train(p, ds, backend=backend, checkpoint_dir=ckdir,
                          checkpoint_every=3, resume=True)
    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_array_equal(full.value, resumed.value)
    np.testing.assert_array_equal(
        dryad.predict(full, X, raw_score=True),
        dryad.predict(resumed, X, raw_score=True))


def test_rf_mixed_mode_continuation_rejected(data):
    X, y, ds = data
    b_gb = dryad.train(dict(PARAMS, boosting="gbdt", num_trees=5), ds,
                       backend="cpu")
    with pytest.raises(ValueError, match="rf"):
        dryad.train(dict(PARAMS, num_trees=10), ds, backend="cpu",
                    init_booster=b_gb)


def test_rf_shap_efficiency(data):
    """contributions + bias == averaged raw prediction, exactly."""
    X, y, ds = data
    b = dryad.train(PARAMS, ds, backend="cpu")
    raw = b.predict_binned(ds.X_binned[:64], raw_score=True)
    contrib = b.predict_binned(ds.X_binned[:64], pred_contrib=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-5, atol=1e-6)


def test_rf_early_stopping_allowed(data):
    """rf + early stopping is sound (prefix of an rf model IS an rf model
    of fewer trees — unlike DART) and truncates predict at the best."""
    X, y, ds = data
    b = dryad.train(dict(PARAMS, num_trees=20, early_stopping_rounds=3),
                    ds, [ds], backend="cpu")
    assert b.best_iteration > 0
    raw_best = b.predict_binned(ds.X_binned, raw_score=True)
    raw_all = b.predict_binned(ds.X_binned, raw_score=True,
                               num_iteration=b.num_iterations)
    if b.best_iteration < b.num_iterations:
        assert not np.array_equal(raw_best, raw_all)


@pytest.mark.slow  # r19 tier-1 re-budget: K-class rf trains 30 s+ on CI
def test_rf_multiclass(data):
    from dryad_tpu.datasets import covertype_like

    X, y = covertype_like(4000, seed=11)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(PARAMS, objective="multiclass", num_class=7, max_bins=32,
             num_trees=8)
    bc = dryad.train(p, ds, backend="cpu")
    bt = dryad.train(p, ds, backend="tpu")
    # rf refits the SAME constant gradients every iteration, so fp32
    # near-tie argmax flips between backends recur more often than under
    # boosting (documented tolerance, CLAUDE.md) — bound the divergence
    # instead of requiring zero
    mismatch = (bc.feature != bt.feature).mean()
    assert mismatch < 0.02, f"{mismatch:.4f} of nodes diverged"
    acc = (dryad.predict(bc, X).argmax(axis=1) == y).mean()
    assert acc > 0.5
