"""DART boosting: cross-backend parity, drop determinism, score
bookkeeping consistency, and kill-and-resume bit identity."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.config import make_params
from dryad_tpu.cpu.trainer import dart_drop_set
from dryad_tpu.datasets import higgs_like
from dryad_tpu.metrics import auc

PARAMS = dict(objective="binary", boosting="dart", num_trees=20,
              num_leaves=15, max_depth=4, max_bins=32, drop_rate=0.4,
              skip_drop=0.3, seed=2)


@pytest.fixture(scope="module")
def data():
    X, y = higgs_like(4000, seed=23)
    return X, y, dryad.Dataset(X, y, max_bins=32)


def test_drop_set_deterministic_and_capped():
    p = make_params(dict(PARAMS, skip_drop=0.0, drop_rate=0.9, max_drop=5))
    a = dart_drop_set(p, 7, 30)
    b = dart_drop_set(p, 7, 30)
    np.testing.assert_array_equal(a, b)
    assert a.size <= 5
    assert dart_drop_set(p, 3, 0).size == 0
    p1 = make_params(dict(PARAMS, skip_drop=1.0))
    assert dart_drop_set(p1, 9, 9).size == 0   # always skipped


def test_dart_cpu_device_parity(data):
    X, y, ds = data
    bc = dryad.train(PARAMS, ds, backend="cpu")
    bt = dryad.train(PARAMS, ds, backend="tpu")
    np.testing.assert_array_equal(bc.feature, bt.feature)
    np.testing.assert_array_equal(bc.threshold, bt.threshold)
    np.testing.assert_allclose(bc.value, bt.value, rtol=1e-5, atol=1e-6)
    # drops actually happened: some trees carry rescaled (shrunk) values
    assert (np.abs(bt.value).max(axis=1)[1:]
            < np.abs(bt.value).max(axis=1).max()).any()


def test_dart_quality_and_differs_from_gbdt(data):
    X, y, ds = data
    b_dart = dryad.train(PARAMS, ds, backend="cpu")
    b_gbdt = dryad.train(dict(PARAMS, boosting="gbdt"), ds, backend="cpu")
    a_dart = auc(y, dryad.predict(b_dart, X, raw_score=True))
    a_gbdt = auc(y, dryad.predict(b_gbdt, X, raw_score=True))
    assert a_dart > 0.7                       # learns
    assert not np.array_equal(b_dart.value, b_gbdt.value)  # really dropped
    assert abs(a_dart - a_gbdt) < 0.08        # same ballpark


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_dart_valid_bookkeeping_consistent(data, backend):
    """Incrementally-adjusted valid scores (drop/rescale deltas applied
    in-place every iteration) must match a from-scratch recompute off the
    final rescaled tree table."""
    X, y, ds = data
    seen = {}
    b = dryad.train(dict(PARAMS, num_trees=10), ds, [ds], backend=backend,
                    callback=lambda it, info: seen.update(info))
    final = seen["valid_auc"]
    recomp = auc(y, b.predict_binned(ds.X_binned, raw_score=True))
    assert abs(final - recomp) < 1e-5
    # DART must NOT record best_iteration (ADVICE r4 high): drops after the
    # best iteration rescale EARLIER trees in place, so the prefix ending at
    # best_iteration is not the ensemble that was scored — predict must
    # default to the full (final, rescaled) model.
    assert b.best_iteration == -1


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_dart_kill_and_resume_bit_identical(tmp_path, data, backend):
    """The drop draw is keyed on (seed, iteration) and rescales live in the
    checkpointed value table, so resume reproduces the uninterrupted run."""
    X, y, ds = data
    p = dict(PARAMS, num_trees=12)
    full = dryad.train(p, ds, backend=backend)

    class Crash(RuntimeError):
        pass

    def crash_at(it, info):
        if it == 7:
            raise Crash

    ckdir = str(tmp_path / backend)
    with pytest.raises(Crash):
        dryad.train(p, ds, backend=backend, checkpoint_dir=ckdir,
                    checkpoint_every=3, callback=crash_at)
    resumed = dryad.train(p, ds, backend=backend, checkpoint_dir=ckdir,
                          checkpoint_every=3, resume=True)
    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_array_equal(full.value, resumed.value)
    np.testing.assert_array_equal(
        dryad.predict(full, X, raw_score=True),
        dryad.predict(resumed, X, raw_score=True))


def test_dart_rejects_early_stopping():
    with pytest.raises(ValueError, match="early_stopping"):
        make_params(dict(PARAMS, early_stopping_rounds=3)).validate()
