"""dryad.cv — k-fold cross-validation (LightGBM cv() surface)."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.cv import _fold_indices
from dryad_tpu.datasets import higgs_like


@pytest.fixture(scope="module")
def data():
    X, y = higgs_like(6000, seed=13)
    return X, y, dryad.Dataset(X, y, max_bins=32)


def test_fold_indices_partition_and_stratify():
    y = np.array([0] * 80 + [1] * 20, np.float32)
    folds = _fold_indices(y, 4, stratified=True, shuffle=True, seed=3)
    allr = np.sort(np.concatenate(folds))
    np.testing.assert_array_equal(allr, np.arange(100))     # exact partition
    for f in folds:
        assert abs((y[f] == 1).mean() - 0.2) < 0.05         # proportions kept


def test_cv_curves_and_quality(data):
    X, y, ds = data
    res = dryad.cv(dict(objective="binary", num_trees=12, num_leaves=15,
                        max_bins=32), ds, nfold=3, seed=5, backend="cpu")
    mean = res["valid_auc-mean"]
    stdv = res["valid_auc-stdv"]
    assert len(mean) == 12 and len(stdv) == 12
    assert mean[-1] > 0.70                    # learns on held-out rows
    assert mean[-1] > mean[0]                 # improves over iterations
    assert all(s >= 0 for s in stdv)


def test_cv_return_boosters_and_determinism(data):
    X, y, ds = data
    kw = dict(nfold=3, seed=9, backend="cpu", return_boosters=True)
    p = dict(objective="binary", num_trees=5, num_leaves=7, max_bins=32)
    r1 = dryad.cv(p, ds, **kw)
    r2 = dryad.cv(p, ds, **kw)
    assert len(r1["boosters"]) == 3
    np.testing.assert_array_equal(r1["valid_auc-mean"], r2["valid_auc-mean"])


def test_cv_early_stopping_truncates_to_shortest(data):
    X, y, ds = data
    res = dryad.cv(dict(objective="binary", num_trees=40, num_leaves=7,
                        max_bins=32, learning_rate=1.5,
                        early_stopping_rounds=2), ds, nfold=3, seed=2,
                   backend="cpu", return_boosters=True)
    shortest = min(len(b.train_state["eval_history"]["valid_auc"])
                   for b in res["boosters"])
    assert len(res["valid_auc-mean"]) == shortest


def test_cv_rejects_ranking_and_unlabeled():
    from dryad_tpu.datasets import mslr_like

    X, y, group = mslr_like(num_queries=20, seed=3)
    ds = dryad.Dataset(X, y, group=group, max_bins=32)
    with pytest.raises(ValueError, match="ranking"):
        dryad.cv(dict(objective="lambdarank", num_trees=2), ds)
    unlabeled = dryad.Dataset.from_binned(ds.X_binned, ds.mapper, None)
    with pytest.raises(ValueError, match="labels"):
        dryad.cv(dict(objective="binary", num_trees=2), unlabeled)
