import numpy as np

from dryad_tpu import metrics


def test_auc_perfect_and_random():
    y = np.array([0, 0, 1, 1])
    assert metrics.auc(y, np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
    assert metrics.auc(y, np.array([0.9, 0.8, 0.2, 0.1])) == 0.0
    assert abs(metrics.auc(y, np.array([0.5, 0.5, 0.5, 0.5])) - 0.5) < 1e-12


def test_auc_ties_midrank():
    y = np.array([0, 1, 0, 1])
    s = np.array([0.3, 0.3, 0.1, 0.9])
    # pairs: (pos .3 vs neg .3)=0.5, (pos .3 vs neg .1)=1, (pos .9 vs both)=2 → 3.5/4
    assert abs(metrics.auc(y, s) - 3.5 / 4) < 1e-12


def test_auc_matches_sklearn_formula_random():
    rng = np.random.default_rng(0)
    y = (rng.uniform(size=500) < 0.4).astype(float)
    s = rng.normal(size=500)
    # brute-force pair counting oracle
    pos, neg = s[y == 1], s[y == 0]
    wins = (pos[:, None] > neg[None, :]).sum() + 0.5 * (pos[:, None] == neg[None, :]).sum()
    assert abs(metrics.auc(y, s) - wins / (pos.size * neg.size)) < 1e-10


def test_logloss():
    y = np.array([1.0, 0.0])
    p = np.array([0.9, 0.1])
    expect = -np.mean([np.log(0.9), np.log(0.9)])
    assert abs(metrics.binary_logloss(y, p) - expect) < 1e-12


def test_ndcg():
    # single query, perfect ranking → 1.0
    y = np.array([3.0, 2.0, 1.0, 0.0])
    off = np.array([0, 4])
    assert abs(metrics.ndcg_at_k(y, np.array([4.0, 3.0, 2.0, 1.0]), off, k=4) - 1.0) < 1e-12
    worst = metrics.ndcg_at_k(y, np.array([1.0, 2.0, 3.0, 4.0]), off, k=4)
    assert 0.0 < worst < 1.0


def test_ndcg_zero_ideal_counts_one():
    y = np.zeros(4)
    off = np.array([0, 4])
    assert metrics.ndcg_at_k(y, np.arange(4.0), off, k=4) == 1.0


def test_rmse():
    assert metrics.rmse(np.array([1.0, 2.0]), np.array([1.0, 4.0])) == np.sqrt(2.0)
