"""Objective grad/hess: numpy canon vs jax impl vs jax.grad autodiff oracle
(SURVEY.md §4)."""

import numpy as np
import pytest

from dryad_tpu.config import Params
from dryad_tpu.objectives import Binary, LambdaRank, Multiclass, Regression, get_objective


def test_registry():
    assert isinstance(get_objective(Params(objective="binary")), Binary)
    assert isinstance(get_objective(Params(objective="regression")), Regression)
    assert isinstance(get_objective(Params(objective="multiclass", num_class=3)), Multiclass)
    assert isinstance(get_objective(Params(objective="lambdarank")), LambdaRank)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0)


def test_binary_matches_autodiff(rng):
    import jax
    import jax.numpy as jnp

    s = rng.normal(size=256).astype(np.float32)
    y = (rng.uniform(size=256) < 0.5).astype(np.float32)
    g_np, h_np = Binary().grad_hess_np(s, y)
    g_jx, h_jx = Binary().grad_hess_jax(jnp.array(s), jnp.array(y))
    np.testing.assert_allclose(g_np, np.asarray(g_jx), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h_np, np.asarray(h_jx), rtol=1e-5, atol=1e-6)

    def loss(si, yi):
        return jnp.mean(jnp.logaddexp(0.0, si) - yi * si) * si.shape[0]

    g_auto = jax.grad(loss)(jnp.array(s), jnp.array(y))
    np.testing.assert_allclose(g_np, np.asarray(g_auto), rtol=1e-3, atol=1e-4)


def test_regression_matches_autodiff(rng):
    import jax
    import jax.numpy as jnp

    s = rng.normal(size=64).astype(np.float32)
    y = rng.normal(size=64).astype(np.float32)
    g_np, h_np = Regression.grad_hess_np(s, y)
    g_auto = jax.grad(lambda si: 0.5 * jnp.sum((si - y) ** 2))(jnp.array(s))
    np.testing.assert_allclose(g_np, np.asarray(g_auto), rtol=1e-5, atol=1e-6)
    assert (h_np == 1.0).all()


def test_multiclass_matches_autodiff(rng):
    import jax
    import jax.numpy as jnp

    K, N = 5, 128
    s = rng.normal(size=(N, K)).astype(np.float32)
    y = rng.integers(0, K, size=N).astype(np.float32)
    obj = Multiclass(K)
    g_np, h_np = obj.grad_hess_np(s, y)
    g_jx, h_jx = obj.grad_hess_jax(jnp.array(s), jnp.array(y))
    np.testing.assert_allclose(g_np, np.asarray(g_jx), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h_np, np.asarray(h_jx), rtol=1e-4, atol=1e-5)

    def loss(si):
        logp = jax.nn.log_softmax(si, axis=1)
        return -jnp.sum(logp[jnp.arange(N), y.astype(int)])

    g_auto = jax.grad(loss)(jnp.array(s))
    np.testing.assert_allclose(g_np, np.asarray(g_auto), rtol=1e-4, atol=1e-5)


def test_lambdarank_pushes_relevant_up(rng):
    obj = LambdaRank(sigmoid=1.0, truncation=30)
    # one query: doc0 relevant but scored low → gradient must push it up (g<0)
    s = np.array([0.0, 1.0], np.float32)
    y = np.array([2.0, 0.0], np.float32)
    off = np.array([0, 2])
    g, h = obj.grad_hess_np(s, y, query_offsets=off)
    assert g[0] < 0 and g[1] > 0
    assert (h >= 0).all()
    # symmetric pair: gradients cancel in sum
    assert abs(g.sum()) < 1e-6


def test_lambdarank_no_pairs_zero_grad():
    obj = LambdaRank()
    s = np.array([0.5, -0.2, 0.1], np.float32)
    y = np.zeros(3, np.float32)  # all same relevance → no pairs
    g, h = obj.grad_hess_np(s, y, query_offsets=np.array([0, 3]))
    assert (g == 0).all() and (h == 0).all()


def test_scale_pos_weight_shifts_predictions():
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(3000, seed=101)
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(objective="binary", num_trees=10, num_leaves=15, max_bins=32)
    b1 = dryad.train(base, ds, backend="cpu")
    b2 = dryad.train(dict(base, scale_pos_weight=5.0), ds, backend="cpu")
    p1 = b1.predict_binned(ds.X_binned)
    p2 = b2.predict_binned(ds.X_binned)
    assert p2.mean() > p1.mean() + 0.05   # positives up-weighted
    # CPU/TPU parity with spw
    b3 = dryad.train(dict(base, scale_pos_weight=5.0), ds, backend="tpu")
    np.testing.assert_array_equal(b2.feature, b3.feature)


def test_pred_leaf_indices():
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(1000, seed=103)
    ds = dryad.Dataset(X, y, max_bins=32)
    b = dryad.train(dict(objective="binary", num_trees=4, num_leaves=7,
                         max_bins=32), ds, backend="cpu")
    leaves = b.predict_binned(ds.X_binned, pred_leaf=True)
    assert leaves.shape == (1000, 4) and leaves.dtype == np.int32
    # every reported node is a leaf of its tree
    for t in range(4):
        assert (b.feature[t, leaves[:, t]] == -1).all()


# ---- round-4 robust/count regression family --------------------------------

def _np_jax_agree(obj, s, y, w=None):
    import jax.numpy as jnp

    g_np, h_np = obj.grad_hess_np(s, y, w)
    g_jx, h_jx = obj.grad_hess_jax(jnp.array(s), jnp.array(y),
                                   None if w is None else jnp.array(w))
    np.testing.assert_allclose(g_np, np.asarray(g_jx), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h_np, np.asarray(h_jx), rtol=1e-5, atol=1e-6)


def test_robust_family_np_jax_agree(rng):
    from dryad_tpu.objectives import L1, Fair, Huber, Poisson, Quantile

    s = rng.normal(size=512).astype(np.float32) * 3
    y = rng.normal(size=512).astype(np.float32) * 3
    w = rng.uniform(0.5, 2.0, size=512).astype(np.float32)
    for obj in (L1(), Huber(0.7), Fair(1.3), Quantile(0.8)):
        _np_jax_agree(obj, s, y)
        _np_jax_agree(obj, s, y, w)
    yp = rng.poisson(3.0, size=512).astype(np.float32)
    _np_jax_agree(Poisson(0.7), s * 0.1, yp)
    _np_jax_agree(Poisson(0.7), s * 0.1, yp, w)


def test_robust_family_autodiff(rng):
    """Gradients match jax.grad of the written-out losses (hessians are the
    documented LightGBM surrogates, not second derivatives, for
    l1/huber/quantile)."""
    import jax
    import jax.numpy as jnp

    s = rng.normal(size=256).astype(np.float32) * 2
    y = rng.normal(size=256).astype(np.float32) * 2
    from dryad_tpu.objectives import Fair, Poisson, Quantile

    a = 0.8
    g_np, _ = Quantile(a).grad_hess_np(s, y)
    g_auto = jax.grad(lambda si: jnp.sum(
        jnp.maximum(a * (jnp.array(y) - si), (a - 1) * (jnp.array(y) - si))
    ))(jnp.array(s))
    np.testing.assert_allclose(g_np, np.asarray(g_auto), rtol=1e-4, atol=1e-5)

    c = 1.3
    g_np, h_np = Fair(c).grad_hess_np(s, y)
    g_auto = jax.grad(lambda si: jnp.sum(c * c * (
        jnp.abs(si - jnp.array(y)) / c
        - jnp.log1p(jnp.abs(si - jnp.array(y)) / c))))(jnp.array(s))
    np.testing.assert_allclose(g_np, np.asarray(g_auto), rtol=1e-4, atol=1e-4)

    yp = rng.poisson(3.0, size=256).astype(np.float32)
    g_np, _ = Poisson(0.7).grad_hess_np(s * 0.1, yp)
    g_auto = jax.grad(lambda si: jnp.sum(
        jnp.exp(si) - jnp.array(yp) * si))(jnp.array(s * 0.1))
    np.testing.assert_allclose(g_np, np.asarray(g_auto), rtol=1e-4, atol=1e-4)


def test_quantile_orders_predictions():
    """Higher alpha must give (weakly) higher predictions on noisy data."""
    import dryad_tpu as dryad

    rng = np.random.default_rng(5)
    X = rng.normal(size=(4000, 6)).astype(np.float32)
    y = (X[:, 0] + rng.normal(scale=1.0, size=4000)).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=64)
    preds = {}
    for a in (0.1, 0.5, 0.9):
        b = dryad.train(dict(objective="quantile", alpha=a, num_trees=30,
                             num_leaves=31, max_bins=64), ds, backend="cpu")
        preds[a] = dryad.predict(b, X)
    assert np.mean(preds[0.9] - preds[0.5]) > 0.3
    assert np.mean(preds[0.5] - preds[0.1]) > 0.3


def test_poisson_trains_and_predicts_rate():
    import dryad_tpu as dryad
    from dryad_tpu.metrics import poisson_deviance

    rng = np.random.default_rng(7)
    X = rng.normal(size=(4000, 5)).astype(np.float32)
    lam = np.exp(0.5 * X[:, 0] + 0.2)
    y = rng.poisson(lam).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=64)
    p = dict(objective="poisson", num_trees=40, num_leaves=31, max_bins=64)
    b = dryad.train(p, ds, backend="cpu")
    pred = dryad.predict(b, X)          # transformed: exp(raw) = rate
    assert (pred > 0).all()
    raw = dryad.predict(b, X, raw_score=True)
    base = poisson_deviance(y, np.full_like(y, np.log(y.mean())))
    assert poisson_deviance(y, raw) < 0.8 * base
    with np.testing.assert_raises(ValueError):
        dryad.train(p, dryad.Dataset(X, -np.abs(y) - 1), backend="cpu")


@pytest.mark.parametrize("objective,extra", [
    ("l1", {}),
    ("huber", {"alpha": 0.5}),
    ("fair", {"fair_c": 1.5}),
    ("quantile", {"alpha": 0.75}),
    ("poisson", {}),
])
def test_robust_family_cpu_device_parity(objective, extra):
    """CPU reference and device engine grow IDENTICAL trees for every new
    objective (the r4 family rides the same grad/hess -> histogram -> split
    machinery as regression)."""
    import dryad_tpu as dryad

    rng = np.random.default_rng(11)
    X = rng.normal(size=(3000, 6)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.normal(scale=0.5, size=3000)).astype(np.float32)
    if objective == "poisson":
        y = rng.poisson(np.exp(np.clip(0.4 * X[:, 0], -3, 3))).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective=objective, num_trees=8, num_leaves=15, max_bins=32,
             max_depth=5, **extra)
    b_cpu = dryad.train(p, ds, backend="cpu")
    b_dev = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(b_cpu.feature, b_dev.feature)
    np.testing.assert_array_equal(b_cpu.threshold, b_dev.threshold)
    # leaf VALUES may differ in last-ulp across backends (the pinned
    # invariant is identical structure + bit-identical predict on the SAME
    # booster — test_engine_parity)
    np.testing.assert_allclose(
        dryad.predict(b_cpu, X, raw_score=True),
        dryad.predict(b_dev, X, raw_score=True), rtol=1e-5, atol=1e-6)
