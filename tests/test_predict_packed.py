"""Packed node-word predict traversal (r21): packed ≡ legacy ≡ CPU, bitwise.

The packed arm stages every node's traversal fields in one (M, 2)-uint32
limb table so the per-level body pays a single small-table gather; the
accumulation scan is byte-for-byte the legacy one, so the identity is by
construction — these tests pin it across numeric/missing/categorical/
multiclass/rf models, ``num_iteration`` slicing, 1/2/8-shard meshes, and
the serve registry, plus the pack/unpack round trip and the width-overflow
fallbacks that keep "auto" safe on any model."""

from __future__ import annotations

import numpy as np
import pytest

import jax

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.engine.predict import (PACKED_CHILD_BITS,
                                      PACKED_FEATURE_BITS,
                                      PACKED_THRESHOLD_BITS,
                                      pack_node_words, packed_fields_fit,
                                      stage_trees, staged_layout,
                                      unpack_node_words)


def _train(params: dict, X, y, *, cat=()):
    ds = dryad.Dataset(X, y, max_bins=32, categorical_features=cat)
    return dryad.train(dict(params, max_bins=32), ds, backend="cpu"), ds


@pytest.fixture(scope="module")
def model_numeric_missing():
    """Binary model on missing-heavy rows: exercises default_left."""
    X, y = higgs_like(700, seed=11)
    X = X.copy()
    X[::5, 2] = np.nan
    X[1::7, 4] = np.nan
    return _train(dict(objective="binary", num_trees=8, num_leaves=15), X, y)


@pytest.fixture(scope="module")
def model_categorical():
    rng = np.random.default_rng(5)
    n = 800
    X = rng.standard_normal((n, 6)).astype(np.float32)
    X[:, 1] = rng.integers(0, 12, n)
    X[::9, 3] = np.nan
    y = (X[:, 0] + (X[:, 1] > 5) > 0).astype(np.float32)
    return _train(dict(objective="binary", num_trees=8, num_leaves=15),
                  X, y, cat=(1,))


@pytest.fixture(scope="module")
def model_multiclass():
    rng = np.random.default_rng(9)
    X = rng.standard_normal((600, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32) + (X[:, 2] > 0.4)
    return _train(dict(objective="multiclass", num_class=3, num_trees=5,
                       num_leaves=7), X, y)


@pytest.fixture(scope="module")
def model_rf():
    X, y = higgs_like(700, seed=13)
    return _train(dict(objective="binary", boosting="rf", num_trees=6,
                       num_leaves=15, subsample=0.6), X, y)


ALL_MODELS = ("model_numeric_missing", "model_categorical",
              "model_multiclass", "model_rf")


def _predict_layout(booster, Xb, layout, **kw):
    booster.params = booster.params.replace(predict_layout=layout)
    try:
        return booster.predict_binned(Xb, raw_score=True, backend="tpu", **kw)
    finally:
        booster.params = booster.params.replace(predict_layout="auto")


# ---- pack/unpack round trip -------------------------------------------------

def test_pack_unpack_round_trip():
    rng = np.random.default_rng(0)
    shape = (3, 2, 37)
    feature = rng.integers(-1, 1 << PACKED_FEATURE_BITS, shape)
    internal = feature >= 0
    threshold = rng.integers(0, 1 << PACKED_THRESHOLD_BITS, shape)
    left = rng.integers(0, 1 << PACKED_CHILD_BITS, shape)
    right = rng.integers(0, 1 << PACKED_CHILD_BITS, shape)
    default_left = rng.integers(0, 2, shape).astype(bool)
    is_cat = rng.integers(0, 2, shape).astype(bool)
    words = pack_node_words(feature, threshold, left, right,
                            default_left, is_cat)
    assert words.dtype == np.uint32 and words.shape == shape + (2,)
    got = unpack_node_words(words)
    # leaf fields are canonicalised to zero (feature to -1): the packing is
    # a pure function of the traversal-relevant content
    np.testing.assert_array_equal(got["feature"],
                                  np.where(internal, feature, -1))
    for name, ref in (("threshold", threshold), ("left", left),
                      ("right", right)):
        np.testing.assert_array_equal(got[name], np.where(internal, ref, 0))
    for name, ref in (("default_left", default_left), ("is_cat", is_cat)):
        np.testing.assert_array_equal(got[name], internal & ref)


def test_pack_width_overflow_raises():
    ones = np.ones(4, np.int64)
    for field, bad in (("feature", 1 << PACKED_FEATURE_BITS),
                       ("threshold", 1 << PACKED_THRESHOLD_BITS),
                       ("left", 1 << PACKED_CHILD_BITS),
                       ("right", 1 << PACKED_CHILD_BITS)):
        kw = dict(feature=ones, threshold=ones, left=ones, right=ones)
        kw[field] = np.where(np.arange(4) == 1, bad, 1)
        assert not packed_fields_fit(kw["feature"], kw["threshold"],
                                     kw["left"], kw["right"])
        with pytest.raises(ValueError, match=field):
            pack_node_words(kw["feature"], kw["threshold"], kw["left"],
                            kw["right"], ones.astype(bool),
                            np.zeros(4, bool))


def test_packed_fields_fit_all_leaves():
    leaf = -np.ones(5, np.int64)
    huge = np.full(5, 1 << 40)
    assert packed_fields_fit(leaf, huge, huge, huge)    # no internal nodes


# ---- stage_trees layout resolution -----------------------------------------

def test_stage_trees_key_sets(model_numeric_missing, model_categorical):
    num, _ = model_numeric_missing
    cat, _ = model_categorical
    trees, _, _ = stage_trees(num)
    assert sorted(trees) == ["node_word", "value"]
    assert staged_layout(trees) == "packed"
    trees, _, _ = stage_trees(cat)
    assert sorted(trees) == ["cat_bitset", "node_word", "value"]
    # legacy numeric drops the dead is_cat/cat_bitset gathers (satellite)
    trees, _, _ = stage_trees(num, layout="legacy")
    assert staged_layout(trees) == "legacy"
    assert "is_cat" not in trees and "cat_bitset" not in trees
    trees, _, _ = stage_trees(cat, layout="legacy")
    assert "is_cat" in trees and "cat_bitset" in trees


def test_stage_trees_auto_falls_back_on_overflow(model_numeric_missing):
    booster, ds = model_numeric_missing
    ref = booster.predict_binned(ds.X_binned, raw_score=True)
    saved = booster.feature.copy()
    try:
        idx = np.argwhere(booster.feature >= 0)[0]
        booster.feature[tuple(idx)] = 1 << PACKED_FEATURE_BITS
        trees, _, _ = stage_trees(booster)           # auto -> legacy
        assert staged_layout(trees) == "legacy"
        with pytest.raises(ValueError, match="feature"):
            stage_trees(booster, layout="packed")    # forced packed refuses
    finally:
        booster.feature[:] = saved
    np.testing.assert_array_equal(
        booster.predict_binned(ds.X_binned, raw_score=True), ref)


def test_params_validate_predict_layout():
    with pytest.raises(ValueError, match="predict_layout"):
        dryad.Params.from_dict({"predict_layout": "zigzag"})


# ---- bitwise parity ---------------------------------------------------------

@pytest.mark.parametrize("fixture", ALL_MODELS)
def test_packed_equals_legacy_equals_cpu(fixture, request):
    booster, ds = request.getfixturevalue(fixture)
    Xb = ds.X_binned
    cpu = booster.predict_binned(Xb, raw_score=True, backend="cpu")
    legacy = _predict_layout(booster, Xb, "legacy")
    packed = _predict_layout(booster, Xb, "packed")
    auto = booster.predict_binned(Xb, raw_score=True, backend="tpu")
    np.testing.assert_array_equal(legacy, packed, err_msg=fixture)
    np.testing.assert_array_equal(packed, auto, err_msg=fixture)
    np.testing.assert_array_equal(packed, cpu, err_msg=fixture)


def test_packed_num_iteration_slicing(model_numeric_missing):
    booster, ds = model_numeric_missing
    for n_iter in (1, 3):
        legacy = _predict_layout(booster, ds.X_binned, "legacy",
                                 num_iteration=n_iter)
        packed = _predict_layout(booster, ds.X_binned, "packed",
                                 num_iteration=n_iter)
        cpu = booster.predict_binned(ds.X_binned, raw_score=True,
                                     backend="cpu", num_iteration=n_iter)
        np.testing.assert_array_equal(legacy, packed)
        np.testing.assert_array_equal(packed, cpu)


@pytest.mark.parametrize("n_shards", [1, 2, 8])
def test_packed_sharded_parity(model_categorical, n_shards):
    from dryad_tpu.engine.distributed import make_mesh
    from dryad_tpu.engine.predict import predict_binned_sharded

    booster, ds = model_categorical
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(jax.devices()[:n_shards])
    single = _predict_layout(booster, ds.X_binned, "packed")
    booster.params = booster.params.replace(predict_layout="packed")
    try:
        # 13 rows does not divide the mesh: the pad path must not leak
        for n in (13, ds.X_binned.shape[0]):
            got = np.asarray(predict_binned_sharded(
                booster, ds.X_binned[:n], mesh=mesh))
            np.testing.assert_array_equal(
                got.reshape(n, -1), np.asarray(single)[:n].reshape(n, -1),
                err_msg=f"shards={n_shards} n={n}")
    finally:
        booster.params = booster.params.replace(predict_layout="auto")


# ---- serve path -------------------------------------------------------------

def test_registry_stages_packed_and_reports_layout(model_numeric_missing):
    from dryad_tpu.serve import ModelRegistry, PredictServer

    booster, ds = model_numeric_missing
    server = PredictServer(backend="tpu", max_batch_rows=64, max_wait_ms=0.2)
    v = server.registry.add(booster)
    with server:
        direct = booster.predict_binned(ds.X_binned[:33])
        served = server.predict(ds.X_binned[:33], binned=True)
        np.testing.assert_array_equal(served, direct)
        entry = server.registry.get(v)
        assert entry.staged_layout == "packed"
        mem = server.registry.memory()
        assert mem["staged_layouts"] == {v: "packed"}
    # a legacy-pinned model reports legacy through the same channel
    reg = ModelRegistry()
    booster.params = booster.params.replace(predict_layout="legacy")
    try:
        v2 = reg.add(booster)
        reg.get(v2).staged()
        assert reg.get(v2).staged_layout == "legacy"
    finally:
        booster.params = booster.params.replace(predict_layout="auto")
