"""Checkpoint/resume: crash mid-training, resume, reproduce the
uninterrupted run bit for bit (SURVEY.md §5 failure recovery)."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.checkpoint import Checkpointer
from dryad_tpu.datasets import higgs_like

PARAMS = dict(objective="binary", num_trees=12, num_leaves=7, max_bins=32,
              subsample=0.8, seed=3, min_data_in_leaf=5)


@pytest.fixture(scope="module")
def data():
    X, y = higgs_like(3000, seed=21)
    return dryad.Dataset(X, y, max_bins=32)


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_kill_and_resume_bit_identical(tmp_path, data, backend):
    full = dryad.train(PARAMS, data, backend=backend)

    class Crash(RuntimeError):
        pass

    def crash_at(it, info):
        if it == 6:
            raise Crash

    ckdir = str(tmp_path / backend)
    with pytest.raises(Crash):
        dryad.train(PARAMS, data, backend=backend, checkpoint_dir=ckdir,
                    checkpoint_every=3, callback=crash_at)

    ck = Checkpointer(ckdir)
    latest = ck.latest()
    assert latest is not None and latest[1] == 6

    resumed = dryad.train(PARAMS, data, backend=backend, checkpoint_dir=ckdir,
                          checkpoint_every=3, resume=True)
    assert resumed.num_iterations == full.num_iterations
    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_array_equal(full.threshold, resumed.threshold)
    np.testing.assert_array_equal(
        full.predict(np.zeros((4, data.num_features), np.float32)),
        resumed.predict(np.zeros((4, data.num_features), np.float32)),
    )


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_resume_with_valid_and_early_stopping(tmp_path, data, backend):
    """Eval metrics, best_iteration and early-stop state must survive resume."""
    X, y = higgs_like(1200, seed=22)
    valid = data.bind(X, y)
    params = dict(PARAMS, early_stopping_rounds=4)

    infos_full = []
    full = dryad.train(params, data, [valid], backend=backend,
                       callback=lambda it, info: infos_full.append(info))

    class Crash(RuntimeError):
        pass

    def crash_at(it, info):
        if it == 6:
            raise Crash

    ckdir = str(tmp_path / backend)
    with pytest.raises(Crash):
        dryad.train(params, data, [valid], backend=backend,
                    checkpoint_dir=ckdir, checkpoint_every=3, callback=crash_at)

    infos_res = []
    resumed = dryad.train(params, data, [valid], backend=backend,
                          checkpoint_dir=ckdir, checkpoint_every=3, resume=True,
                          callback=lambda it, info: infos_res.append(info))
    assert resumed.num_iterations == full.num_iterations
    assert resumed.best_iteration == full.best_iteration
    np.testing.assert_array_equal(full.feature, resumed.feature)
    # post-resume metric stream matches the uninterrupted run's tail
    tail = {i["iteration"]: i for i in infos_full if i["iteration"] >= 6}
    for info in infos_res:
        ref = tail[info["iteration"]]
        for k, v in info.items():
            assert v == pytest.approx(ref[k]), (info["iteration"], k)


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_resume_from_early_stop_boundary_grows_nothing(tmp_path, data, backend):
    """A checkpoint taken at the early-stop iteration must resume to the
    exact same booster — not train past the stop."""
    X, y = higgs_like(1200, seed=23)
    valid = data.bind(X, y)
    params = dict(PARAMS, early_stopping_rounds=2, num_trees=40,
                  learning_rate=1.5)  # aggressive lr -> overfits -> stops early
    ckdir = str(tmp_path / backend)
    stopped = dryad.train(params, data, [valid], backend=backend,
                          checkpoint_dir=ckdir, checkpoint_every=1)
    assert stopped.num_iterations < 40, "early stopping never fired"

    resumed = dryad.train(params, data, [valid], backend=backend,
                          checkpoint_dir=ckdir, checkpoint_every=1, resume=True)
    assert resumed.num_iterations == stopped.num_iterations
    assert resumed.best_iteration == stopped.best_iteration
    np.testing.assert_array_equal(stopped.feature, resumed.feature)


def test_checkpoint_pruning_and_atomicity(tmp_path, data):
    ckdir = str(tmp_path / "prune")
    dryad.train(PARAMS, data, backend="cpu", checkpoint_dir=ckdir,
                checkpoint_every=2)
    ck = Checkpointer(ckdir)
    assert len(ck.iterations()) <= 2          # keep=2 default
    assert ck.iterations()[-1] == 12
    # no stray tmp files
    import os

    assert not [f for f in os.listdir(ckdir) if f.endswith(".tmp")]


def test_resume_without_checkpoint_dir_raises(data):
    with pytest.raises(ValueError, match="checkpoint_dir"):
        dryad.train(PARAMS, data, resume=True, backend="cpu")


def test_gain_survives_roundtrip(tmp_path, data):
    b = dryad.train(PARAMS, data, backend="cpu")
    assert (b.gain > 0).any()
    path = str(tmp_path / "m.dryad")
    b.save(path)
    b2 = dryad.Booster.load(path)
    np.testing.assert_array_equal(b.gain, b2.gain)
    gi = b.feature_importance("gain")
    assert gi.shape == (data.num_features,) and gi.sum() > 0
    si = b.feature_importance("split")
    assert si.sum() == (b.feature >= 0).sum()
