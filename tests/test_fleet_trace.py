"""r17 fleet-wide request tracing + mergeable latency histograms.

Pinned here (the ISSUE's acceptance + test-coverage satellite):

* the fixed-log-bucket family: O(1) bucket index identical to the
  linear scan, 'le' edge semantics, and the EXACT-merge property —
  merged replica histograms bitwise-equal to the histogram of the
  concatenated observations (dyadic values make even the float sums
  associative, so the equality is ==, not approx);
* trace-context survival across the micro-batcher's worker-thread
  hand-off: a request submitted on one thread lands its queue-wait /
  batch-assembly / predict spans in the ring TAGGED with its id, even
  though collection and execution happen on other threads;
* zero-cost disabled: with obs off the request path allocates no
  per-request trace context (the spans null-context idiom);
* the router integration over protocol stubs: trace id echo (supplied
  and minted), both forward attempts of a retried request under one id,
  merged per-priority p50/p95/p99 gauges on /metrics (exact merge of
  replica /obs scrapes), the merged /trace document with router +
  replica + journal tracks, tail-sampling via ?k=, and the SLO gate's
  sustained-breach /healthz degradation;
* obs/trends.py tracks the fleet percentile fields like bench walls.
"""

from __future__ import annotations

import contextlib
import http.client
import json
import os
import sys
import threading
import time
import tracemalloc

import numpy as np
import pytest

from dryad_tpu.fleet import FleetRouter, FleetSupervisor
from dryad_tpu.obs import trace_export
from dryad_tpu.obs.registry import (LOG_BUCKETS, REQUEST_LATENCY, Registry,
                                    hist_quantile, log_bucket_index,
                                    merge_hist_states, set_default_registry)
from dryad_tpu.obs.slo import SloGate, parse_budgets
from dryad_tpu.obs.trace_export import SpanTrace, TailSampler
from dryad_tpu.resilience.policy import RetryPolicy
from dryad_tpu.serve.batcher import MicroBatcher, Request, RequestTrace
from dryad_tpu.serve.metrics import ServeMetrics

STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "fleet_stub_server.py")


# ---------------------------------------------------------------------------
# the histogram family


def test_log_bucket_index_matches_linear_scan_and_edges():
    def scan(v):
        i = 0
        while i < len(LOG_BUCKETS) and v > LOG_BUCKETS[i]:
            i += 1
        return i

    import random
    rng = random.Random(7)
    values = ([rng.uniform(0.0, 150.0) for _ in range(4000)]
              + [rng.uniform(0.0, 1e-3) for _ in range(1000)]
              + list(LOG_BUCKETS) + [0.0, -1.0, 1e-12, 1e9])
    for v in values:
        assert log_bucket_index(v) == scan(v), v
    # 'le' semantics: a value ON a bound lands in that bound's bucket
    for i, b in enumerate(LOG_BUCKETS):
        assert log_bucket_index(b) == i


def test_merge_is_bitwise_equal_to_concatenated_observations():
    """The acceptance pin: per-replica histograms, exactly merged, ==
    one histogram of the concatenated observations — counts AND sums."""
    replica_obs = [
        [2.0 ** -k for k in range(1, 9)],          # replica 0
        [0.75, 0.125, 3.0, 1.5, 0.25, 0.0625],     # replica 1
        [42.0, 2.0 ** -10, 0.5, 0.5, 8.0],         # replica 2
    ]
    states = []
    for obs in replica_obs:
        fam = Registry().log_histogram(REQUEST_LATENCY)
        for v in obs:
            fam.observe(v)
        states.append(fam.value())
    merged = merge_hist_states(states)
    ref = Registry().log_histogram(REQUEST_LATENCY)
    for obs in replica_obs:
        for v in obs:
            ref.observe(v)
    want = ref.value()
    assert merged[0] == want[0]          # bucket counts, bitwise
    assert merged[1] == want[1]          # dyadic sums are associative
    assert merged[2] == want[2]
    # and the quantiles of the merge are the quantiles of the whole
    for q in (0.5, 0.95, 0.99):
        assert hist_quantile(merged[0], q) == hist_quantile(want[0], q)


def test_merge_rejects_mismatched_layouts_and_quantile_shapes():
    with pytest.raises(ValueError):
        merge_hist_states([([0] * 62, 0.0, 0), ([0] * 10, 0.0, 0)])
    assert hist_quantile([0] * 62, 0.99) == 0.0         # empty -> 0
    counts = [0] * 62
    counts[5] = 100
    assert hist_quantile(counts, 0.5) == LOG_BUCKETS[5]
    counts[61] = 1000                                    # overflow bucket
    assert hist_quantile(counts, 0.99) == LOG_BUCKETS[-1]
    # monotone in q
    qs = [hist_quantile(counts, q) for q in (0.01, 0.5, 0.9, 0.999)]
    assert qs == sorted(qs)
    with pytest.raises(ValueError):
        # custom buckets would break the cross-process merge contract
        Registry()._family("x", "loghistogram", "", buckets=(1.0, 2.0))


def test_serve_metrics_percentiles_from_histogram():
    m = ServeMetrics(registry=Registry())
    for ms in (1, 2, 5, 10, 100):
        m.record_request(1, ms / 1e3, version=1)
    snap = m.snapshot()
    # bucket-resolution percentiles: p50 lands on the 5 ms observation's
    # upper bound, p99 on the 100 ms one's
    assert abs(snap["p50_ms"] - 5.012) < 0.1
    assert 100.0 <= snap["p99_ms"] <= 101.0
    assert abs(snap["mean_ms"] - 23.6) < 1e-6           # exact (sum/count)
    assert snap["models"][1]["p99_ms"] == snap["p99_ms"]


# ---------------------------------------------------------------------------
# trace context across the batcher hand-off


def test_trace_survives_batcher_thread_handoff():
    reg = Registry()
    old = set_default_registry(reg)
    ring = SpanTrace(capacity=256)
    try:
        from dryad_tpu.obs import spans
        spans.set_trace_sink(ring.record)
        m = ServeMetrics(registry=reg)
        submitter = threading.get_ident() & 0xFFFF

        def dispatch(batch):
            return [np.zeros(r.rows.shape[0]) for r in batch]

        b = MicroBatcher(dispatch, max_wait_ms=0.5, metrics=m)
        b.start()
        try:
            req = Request(np.zeros((3, 2), np.float32), version=1,
                          priority="bulk",
                          tctx=RequestTrace("feedc0de", "bulk"))
            b.submit(req, timeout=10.0)
        finally:
            b.stop()
        tagged = [e for e in ring.events() if e[4] == "feedc0de"]
        assert sorted(e[0] for e in tagged) == [
            "serve.request/batch_assembly", "serve.request/predict",
            "serve.request/queue_wait"]
        # the spans were emitted from the WORKER threads, not the
        # submitting one — the hand-off really crossed threads
        assert all(e[3] != submitter for e in tagged)
        # stage timestamps are ordered: queue_wait before batch_assembly
        # before predict on the shared perf_counter clock
        by = {e[0]: e for e in tagged}
        assert (by["serve.request/queue_wait"][1]
                <= by["serve.request/batch_assembly"][1]
                <= by["serve.request/predict"][1])
        # and the per-(priority, stage) histograms saw each stage
        fam = reg.log_histogram(REQUEST_LATENCY)
        for stage in ("queue_wait", "batch_assembly", "predict", "total"):
            assert fam.labels(priority="bulk", stage=stage).value()[2] == 1, \
                stage
    finally:
        from dryad_tpu.obs import spans
        spans.set_trace_sink(None)
        set_default_registry(old)


def test_tracing_disabled_allocates_no_request_context():
    """The zero-cost pin: with obs disabled, submitting requests leaves
    no net allocations from the trace-context sites (tctx stays None and
    every stamp site is one attribute check)."""
    reg = Registry(enabled=False)
    old = set_default_registry(reg)
    try:
        m = ServeMetrics(registry=reg)
        assert m.obs_enabled is False

        def dispatch(batch):
            return [np.zeros(r.rows.shape[0]) for r in batch]

        b = MicroBatcher(dispatch, max_wait_ms=0.2, metrics=m)
        b.start()
        rows = np.zeros((1, 2), np.float32)
        try:
            for _ in range(32):              # warm every code path
                b.submit(Request(rows, version=1), timeout=10.0)

            def leaked() -> list:
                tracemalloc.start()
                for _ in range(200):
                    b.submit(Request(rows, version=1), timeout=10.0)
                snap_mem = tracemalloc.take_snapshot()
                tracemalloc.stop()
                return [st for st in snap_mem.statistics("filename")
                        if st.traceback[0].filename.endswith(
                            ("obs/spans.py", "obs/trace_export.py"))]

            # re-measure up to 3x: tracemalloc attributes by file, and a
            # stray daemon thread from another test could touch obs once
            for _ in range(3):
                bad = leaked()
                if not bad:
                    break
            assert not bad, f"disabled trace path allocated: {bad}"
        finally:
            b.stop()
    finally:
        set_default_registry(old)


# ---------------------------------------------------------------------------
# SLO gate + tail sampler units


def test_slo_gate_sustained_breach_hold_and_recovery():
    reg = Registry()
    from dryad_tpu.obs.health import HealthState
    health = HealthState(registry=reg)
    gate = SloGate({"interactive": 10.0}, breach_after=2,
                   registry=reg, health=health)
    slow = Registry().log_histogram(REQUEST_LATENCY)
    for _ in range(5):
        slow.observe(0.5)                     # 500 ms >> 10 ms budget
    v1 = gate.evaluate({"interactive": slow.value()})
    assert v1["interactive"]["breached"] and not v1["interactive"]["sustained"]
    assert health.ok and gate.ok              # one breached window: telemetry
    v2 = gate.evaluate({"interactive": slow.value()})
    assert v2["interactive"]["sustained"] and not health.ok and not gate.ok
    assert "slo:interactive" in health.reasons()
    # an EMPTY window is no evidence: the degradation HOLDS (silence
    # must not clear an incident)
    v3 = gate.evaluate({"interactive": ([0] * 62, 0.0, 0)})
    assert v3["interactive"]["sustained"] and not health.ok
    # recovery needs a non-empty in-budget window
    fast = Registry().log_histogram(REQUEST_LATENCY)
    for _ in range(5):
        fast.observe(0.001)
    gate.evaluate({"interactive": fast.value()})
    assert health.ok and gate.ok
    assert reg.gauge("dryad_slo_breach_streak").labels(
        priority="interactive").value() == 0


def test_slo_gate_fires_at_exactly_breach_after():
    """The hysteresis edge the autoscaler steers on (r22): breach_after
    consecutive breached windows — not N-1, not a lifetime total — flip
    ``sustained``."""
    reg = Registry()
    from dryad_tpu.obs.health import HealthState
    health = HealthState(registry=reg)
    gate = SloGate({"interactive": 10.0}, breach_after=3,
                   registry=reg, health=health)
    slow = Registry().log_histogram(REQUEST_LATENCY)
    for _ in range(5):
        slow.observe(0.5)
    for i in range(1, 3):                     # windows 1, 2: not yet
        v = gate.evaluate({"interactive": slow.value()})
        assert v["interactive"]["breached"]
        assert v["interactive"]["streak"] == i
        assert not v["interactive"]["sustained"], \
            f"sustained fired at window {i} < breach_after"
        assert health.ok
    v = gate.evaluate({"interactive": slow.value()})    # window 3: exactly
    assert v["interactive"]["sustained"] and v["interactive"]["streak"] == 3
    assert not health.ok


def test_slo_gate_clean_window_resets_streak():
    """One in-budget NON-EMPTY window zeroes the streak — breaches on
    either side never add up across it."""
    gate = SloGate({"interactive": 10.0}, breach_after=2,
                   registry=Registry())
    slow = Registry().log_histogram(REQUEST_LATENCY)
    fast = Registry().log_histogram(REQUEST_LATENCY)
    for _ in range(5):
        slow.observe(0.5)
        fast.observe(0.001)
    assert gate.evaluate(
        {"interactive": slow.value()})["interactive"]["streak"] == 1
    clean = gate.evaluate({"interactive": fast.value()})["interactive"]
    assert clean["streak"] == 0 and not clean["breached"]
    again = gate.evaluate({"interactive": slow.value()})["interactive"]
    assert again["streak"] == 1 and not again["sustained"], \
        "a pre-reset breach leaked into the new streak"
    assert gate.ok


def test_slo_gate_priorities_are_independent():
    """interactive sustaining its breach neither advances bulk's streak
    nor degrades bulk's health key — each priority carries its own
    hysteresis."""
    reg = Registry()
    from dryad_tpu.obs.health import HealthState
    health = HealthState(registry=reg)
    gate = SloGate({"interactive": 10.0, "bulk": 2000.0}, breach_after=2,
                   registry=reg, health=health)
    slow = Registry().log_histogram(REQUEST_LATENCY)
    fast = Registry().log_histogram(REQUEST_LATENCY)
    for _ in range(5):
        slow.observe(0.5)                     # over 10 ms, under 2000 ms
        fast.observe(0.001)
    for _ in range(2):
        v = gate.evaluate({"interactive": slow.value(),
                           "bulk": fast.value()})
    assert v["interactive"]["sustained"]
    assert v["bulk"]["streak"] == 0 and not v["bulk"]["breached"]
    assert "slo:interactive" in health.reasons()
    assert "slo:bulk" not in health.reasons()
    assert not gate.ok                        # any sustained priority


def test_parse_budgets():
    assert parse_budgets("") == {"interactive": 250.0, "bulk": 2000.0}
    assert parse_budgets("interactive=5,bulk=80.5") == {
        "interactive": 5.0, "bulk": 80.5}
    # the off-switch: no budgets, no latency-based health gating
    assert parse_budgets("off") == {} and parse_budgets("none") == {}
    with pytest.raises(ValueError):
        parse_budgets("nonsense")


def test_slo_gate_no_budgets_never_degrades():
    gate = SloGate({}, breach_after=1, registry=Registry())
    assert gate.evaluate({"interactive": ([0] * 62, 0.0, 0)}) == {}
    assert gate.ok


def test_serve_metrics_percentiles_track_recent_window():
    """The recency contract the reservoir had: after a regression, the
    windowed percentiles reflect the NEW latencies within one window,
    however many fast requests came before."""
    m = ServeMetrics(latency_window=64, registry=Registry())
    for _ in range(10_000):
        m.record_request(1, 0.001)            # a long fast history
    assert m.snapshot()["p99_ms"] < 2.0
    for _ in range(64):
        m.record_request(1, 0.5)              # regression: 500 ms
    assert m.snapshot()["p99_ms"] > 400.0     # visible within one window
    assert m.snapshot()["requests"] == 10_064  # counters stay lifetime


def test_tail_sampler_keeps_slowest_k_per_window():
    s = TailSampler(window=4)
    for i, d in enumerate([0.9, 0.1, 0.2, 0.3, 0.4]):   # 0.9 evicted
        s.observe(f"t{i}", d)
    assert s.slowest(2) == {"t4", "t3"}
    assert s.slowest(0) == {"t1", "t2", "t3", "t4"}
    s.observe(None, 9.9)                                 # untraced: ignored
    assert len(s.slowest(0)) == 4


# ---------------------------------------------------------------------------
# router integration over protocol stubs


def stub_argv(*extra: str):
    def make(index: int, port_file: str) -> list:
        return [sys.executable, STUB, "--port-file", port_file, *extra]
    return make


@contextlib.contextmanager
def traced_fleet(tmp_path, n=2, *, router_kw=None, stub_args=()):
    reg = Registry()
    old = set_default_registry(reg)
    ring = trace_export.SpanTrace(capacity=4096)
    from dryad_tpu.obs import spans
    spans.set_trace_sink(ring.record)
    sup = FleetSupervisor(
        stub_argv(*stub_args), n, policy=RetryPolicy(backoff_base_s=0.0),
        journal=str(tmp_path / "fleet.jsonl"), registry=reg,
        probe_interval_s=0.05, probe_timeout_s=1.0, startup_timeout_s=20.0)
    sup.start()
    router = FleetRouter(sup, registry=reg, **(router_kw or {})).start()
    try:
        yield sup, router, reg
    finally:
        router.stop()
        sup.stop()
        spans.set_trace_sink(None)
        set_default_registry(old)


def http_call(host, port, method, path, body=None, headers=None,
              timeout=15.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        payload = (json.dumps(body).encode() if isinstance(body, dict)
                   else (body or b""))
        conn.request(method, path, body=payload, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def test_router_trace_roundtrip_merge_and_merged_trace(tmp_path):
    with traced_fleet(tmp_path) as (sup, router, reg):
        # supplied id round-trips; minted id is returned when absent
        st, _, hdrs = http_call(router.host, router.port, "POST", "/predict",
                                {"rows": [[1.0, 2.0]]},
                                {"X-Dryad-Trace": "abc123"})
        assert st == 200 and hdrs.get("X-Dryad-Trace") == "abc123"
        st, _, hdrs = http_call(router.host, router.port, "POST",
                                "/predict", {"rows": [[1.0, 2.0]]})
        minted = hdrs.get("X-Dryad-Trace")
        assert st == 200 and minted and minted != "abc123"
        # registration-time clock handshake succeeded against the stub
        assert all(s.clock_offset is not None for s in sup.slots)
        # /metrics: merged per-priority gauges from replica /obs scrapes
        # (the stubs report one 31.6 ms-bucket observation per request)
        st, body, _ = http_call(router.host, router.port, "GET", "/metrics")
        text = body.decode()
        assert st == 200
        line = [ln for ln in text.splitlines()
                if ln.startswith("dryad_fleet_latency_ms")
                and 'stage="total"' in ln and 'q="p99"' in ln]
        assert line, text[:1500]
        assert line[0].split()[-1].startswith("31.6")
        # the router's own end-to-end series merged through the same path
        assert any('stage="router"' in ln and 'q="p99"' in ln
                   for ln in text.splitlines()
                   if ln.startswith("dryad_fleet_latency_ms"))
        # /trace: router + replica tracks, journal track, one id end2end
        st, body, _ = http_call(router.host, router.port, "GET", "/trace?k=0")
        doc = json.loads(body)
        tracks = [e["args"]["name"] for e in doc["traceEvents"]
                  if e["ph"] == "M"]
        assert "fleet router" in tracks
        assert any(t.startswith("replica r") for t in tracks)
        assert "fleet journal (run-relative)" in tracks
        spans_of = {}
        for e in doc["traceEvents"]:
            if e["ph"] == "X" and e["args"].get("trace"):
                spans_of.setdefault(e["args"]["trace"], []).append(
                    (e["pid"], e["args"]["path"]))
        assert "abc123" in spans_of
        paths = spans_of["abc123"]
        assert ("fleet.request" in {p for _, p in paths})
        assert any(pid >= 10 and p == "serve.request/predict"
                   for pid, p in paths)
        # journal instants landed on the journal track (pid 0)
        assert any(e["ph"] == "i" and e["pid"] == 0
                   and e["name"] == "replica_ready"
                   for e in doc["traceEvents"])


def test_merged_gauges_skip_malformed_replica_blocks():
    """One bad replica /obs block (wrong keys, wrong layout) must be
    SKIPPED, never raise out of the /metrics path."""
    from dryad_tpu.fleet.router import _Handler, _RouterState

    class _NoSup:
        slots = ()

    reg = Registry()
    state = _RouterState(_NoSup(), registry=reg, max_inflight=4,
                         bulk_max_inflight=None, model_caps=None,
                         request_timeout_s=1.0, min_healthy=1,
                         auth_token=None)
    good = [0] * 62
    good[10] = 4
    blocks = [
        {'priority="interactive",stage="total"':
         {"counts": good, "sum": 0.01, "count": 4}},
        {"bad-no-keys": {}},                              # missing keys
        {'priority="interactive",stage="total"':
         {"counts": [1, 2], "sum": 1.0, "count": 3}},     # wrong layout
        "not-a-dict",                                     # wrong shape
    ]
    _Handler._merged_latency_gauges(state, blocks)        # must not raise
    v = reg.gauge("dryad_fleet_latency_ms").labels(
        priority="interactive", stage="total", q="p99").value()
    assert v == pytest.approx(hist_quantile(good, 0.99) * 1e3)


def test_router_tail_sampling_drops_fast_request_detail(tmp_path):
    with traced_fleet(tmp_path, router_kw=dict(tail_keep=1)) as (
            sup, router, reg):
        ids = []
        for i in range(4):
            st, _, hdrs = http_call(router.host, router.port, "POST",
                                    "/predict", {"rows": [[1.0, 2.0]]},
                                    {"X-Dryad-Trace": f"t{i:04d}"})
            assert st == 200
            ids.append(hdrs["X-Dryad-Trace"])
        st, body, _ = http_call(router.host, router.port, "GET", "/trace")
        doc = json.loads(body)
        kept = {e["args"]["trace"] for e in doc["traceEvents"]
                if e["ph"] == "X" and e["args"].get("trace")}
        assert len(kept) == 1 and kept <= set(ids)   # slowest-1 only
        # ?k=0 keeps everything
        st, body, _ = http_call(router.host, router.port, "GET",
                                "/trace?k=0")
        doc = json.loads(body)
        kept = {e["args"]["trace"] for e in doc["traceEvents"]
                if e["ph"] == "X" and e["args"].get("trace")}
        assert set(ids) <= kept


def test_router_healthz_degrades_on_sustained_slo_breach(tmp_path):
    # stub predicts take ~50 ms; a 1 ms interactive budget breaches each
    # window, and breach_after=2 needs two CONSECUTIVE breached windows
    # (each /healthz evaluates the delta since the previous one — fresh
    # slow traffic must arrive between probes)
    with traced_fleet(
            tmp_path, stub_args=("--predict-delay", "0.05"),
            router_kw=dict(slo_budgets_ms={"interactive": 1.0},
                           slo_breach_after=2)) as (sup, router, reg):
        for _ in range(2):
            assert http_call(router.host, router.port, "POST", "/predict",
                             {"rows": [[1.0, 2.0]]})[0] == 200
        st, body, _ = http_call(router.host, router.port, "GET", "/healthz")
        doc = json.loads(body)
        assert st == 200 and doc["ok"]            # 1st breached window: warn
        assert doc["slo"]["interactive"]["breached"]
        # an empty window between probes HOLDS the streak, never clears
        st, body, _ = http_call(router.host, router.port, "GET", "/healthz")
        doc = json.loads(body)
        assert st == 200 and doc["slo"]["interactive"]["streak"] == 1
        for _ in range(2):
            assert http_call(router.host, router.port, "POST", "/predict",
                             {"rows": [[1.0, 2.0]]})[0] == 200
        st, body, _ = http_call(router.host, router.port, "GET", "/healthz")
        doc = json.loads(body)
        assert st == 503 and not doc["ok"]        # 2nd breached window
        assert doc["slo"]["interactive"]["sustained"]
        assert "slo:interactive" in doc["degraded"]
        # the replicas themselves are fine — it is the SLO that tripped
        assert all(s["healthy"] for s in doc["replicas"].values())


# ---------------------------------------------------------------------------
# trends ingestion of the fleet percentile fields


def test_trends_track_fleet_percentiles():
    from dryad_tpu.obs.trends import _direction, _spread_fields_of, compare

    assert _direction("fleet_interactive_p99_ms_n2") == "lower_better"
    assert _direction("fleet_bulk_p50_ms_n4") == "lower_better"
    assert _direction("fleet_trace_mismatches_n2") is None   # context
    assert _spread_fields_of("fleet_interactive_p99_ms_n2") == (
        "fleet_spread_n2",)
    hist = [{"round": r, "path": f"BENCH_FLEET_r{r}.json", "metrics":
             {"fleet_interactive_p99_ms_n2": 40.0, "fleet_spread_n2": 0.01}}
            for r in (1, 2, 3)]
    hist.append({"round": 4, "path": "BENCH_FLEET_r4.json", "metrics":
                 {"fleet_interactive_p99_ms_n2": 80.0,
                  "fleet_spread_n2": 0.01}})
    report = compare(hist)
    assert report["metrics"]["fleet_interactive_p99_ms_n2"][
        "verdict"] == "regression"
    assert not report["ok"]
    # the spread veto still applies (suspect capture, never a regression)
    hist[-1]["metrics"]["fleet_spread_n2"] = 0.2
    report = compare(hist)
    assert report["metrics"]["fleet_interactive_p99_ms_n2"][
        "verdict"] == "suspect"
    assert report["ok"]
