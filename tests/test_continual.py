"""Continual boosting (r19): warm-start append training
(``dryad.train(init_model=...)``), the retrain scheduler's debounce and
profile gate, the probation publisher's promote/rollback state machine,
and the generation artifact round-trips.

The appended-model pins are the subsystem's bitwise anchor: a retrain is
only trustworthy if the same corpus always yields the same generation —
including through a mid-append fault and supervisor resume."""

import json
import os
import threading

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.continual import (
    JournalTailer,
    ProbationPublisher,
    RetrainScheduler,
    model_has_profile,
)
from dryad_tpu.datasets import higgs_like
from dryad_tpu.resilience import FaultInjector, RetryPolicy, RunJournal
from dryad_tpu.resilience import faults as F
from dryad_tpu.resilience import supervise_train

PARAMS = dict(objective="binary", num_trees=6, num_leaves=7, max_bins=32,
              seed=3, min_data_in_leaf=5)
APPEND_TREES = 4


@pytest.fixture(scope="module")
def corpus():
    X, y = higgs_like(1500, seed=21)
    return X, y


@pytest.fixture(scope="module")
def base_model(corpus):
    X, y = corpus
    ds = dryad.Dataset(X, y, max_bins=32)
    return dryad.train(PARAMS, ds, backend="cpu"), ds


@pytest.fixture(scope="module")
def fresh(corpus, base_model):
    """Fresh rows binned into the BASE model's frozen bin space — the
    only well-defined append corpus."""
    X, y = higgs_like(1100, seed=77)
    model, _ = base_model
    return dryad.Dataset(X, y, mapper=model.mapper)


# ---- warm-start append: the bitwise pins ------------------------------------

def test_append_bitwise_reproducible(base_model, fresh):
    model, _ = base_model
    p = dict(PARAMS, num_trees=APPEND_TREES)
    a = dryad.train(p, fresh, backend="cpu", init_model=model)
    b = dryad.train(p, fresh, backend="cpu", init_model=model)
    assert a.num_iterations == PARAMS["num_trees"] + APPEND_TREES
    np.testing.assert_array_equal(a.feature, b.feature)
    np.testing.assert_array_equal(a.threshold, b.threshold)
    np.testing.assert_array_equal(a.value, b.value)
    # the base model's trees are a strict prefix: an append never rewrites
    # what is already serving
    n0 = model.feature.shape[0]
    np.testing.assert_array_equal(a.feature[:n0], model.feature)
    np.testing.assert_array_equal(a.value[:n0], model.value)


def test_append_zero_trees_is_identity(base_model, fresh, corpus):
    """trees=0 is a pure re-wrap: predictions bitwise-identical to the
    input model — in particular the carried base score must come from the
    MODEL, not be re-derived from the fresh rows' label distribution."""
    model, _ = base_model
    X, _ = corpus
    out = dryad.train(dict(PARAMS, num_trees=0), fresh, backend="cpu",
                      init_model=model)
    assert out.num_iterations == model.num_iterations
    np.testing.assert_array_equal(model.predict(X), out.predict(X))
    np.testing.assert_array_equal(
        np.asarray(model.init_score, np.float32),
        np.asarray(out.init_score, np.float32))


def test_append_zero_trees_without_init_model_rejected(fresh):
    with pytest.raises(ValueError, match="num_trees=0"):
        dryad.train(dict(PARAMS, num_trees=0), fresh, backend="cpu")


def test_append_kill_and_resume_bitwise(base_model, fresh, tmp_path):
    """A faulted append resumes from checkpoint and finishes bitwise-equal
    to the uninterrupted append — the retrain subprocess can die mid-run
    without changing the generation it eventually ships."""
    model, _ = base_model
    p = dict(PARAMS, num_trees=APPEND_TREES)
    reference = dryad.train(p, fresh, backend="cpu", init_model=model)
    injector = FaultInjector([
        (model.num_iterations + 2, F.DEVICE_UNAVAILABLE, "dispatch")])
    jpath = str(tmp_path / "j.jsonl")
    resumed = supervise_train(
        p, fresh, backend="cpu", init_model=model,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1,
        journal=jpath, fault_injector=injector,
        policy=RetryPolicy(backoff_base_s=0.0))
    assert injector.pending == 0
    np.testing.assert_array_equal(reference.feature, resumed.feature)
    np.testing.assert_array_equal(reference.threshold, resumed.threshold)
    np.testing.assert_array_equal(reference.value, resumed.value)
    resumes = [e for e in RunJournal.read(jpath) if e["event"] == "resume"]
    # the retry continued PAST the warm start — it never redid the base
    assert resumes and resumes[0]["from_iteration"] > model.num_iterations


def test_append_rejects_foreign_bin_space(base_model, corpus):
    model, _ = base_model
    X, y = higgs_like(900, seed=91)
    resketched = dryad.Dataset(X, y, max_bins=32)   # its OWN mapper
    with pytest.raises(ValueError, match="frozen bin space"):
        dryad.train(dict(PARAMS, num_trees=2), resketched, backend="cpu",
                    init_model=model)


def test_append_rejects_tree_geometry_change(base_model, fresh):
    model, _ = base_model
    with pytest.raises(ValueError, match="max_nodes"):
        dryad.train(dict(PARAMS, num_trees=2, num_leaves=15), fresh,
                    backend="cpu", init_model=model)


# ---- generation artifacts ---------------------------------------------------

def test_generation_roundtrips_both_formats(fresh, base_model, tmp_path,
                                            monkeypatch):
    """A generation ships through either model format with its OWN fresh
    reference profile (the drift baseline the replicas monitor against)."""
    monkeypatch.setenv("DRYAD_PROFILE", "1")
    model, _ = base_model
    gen = dryad.train(dict(PARAMS, num_trees=APPEND_TREES), fresh,
                      backend="cpu", init_model=model)
    assert gen.profile is not None
    Xp = higgs_like(64, seed=1)[0]
    native = str(tmp_path / "g.dryad")
    text = str(tmp_path / "g.txt")
    gen.save(native)
    gen.save_text(text)
    for path in (native, text):
        back = dryad.Booster.load_any(path)
        assert back.num_iterations == PARAMS["num_trees"] + APPEND_TREES
        np.testing.assert_array_equal(gen.predict(Xp), back.predict(Xp))
        assert back.profile is not None, path
        assert model_has_profile(path)


def test_model_has_profile_sniffs_without_jax(base_model, tmp_path,
                                              monkeypatch):
    """The scheduler's gate reads artifact metadata only — profile-less
    (pre-r18) artifacts answer False in both formats."""
    monkeypatch.setenv("DRYAD_PROFILE", "0")
    model, ds = base_model
    bare = dryad.train(dict(PARAMS, num_trees=2), ds, backend="cpu")
    assert bare.profile is None
    native, text = str(tmp_path / "b.dryad"), str(tmp_path / "b.txt")
    bare.save(native)
    bare.save_text(text)
    assert not model_has_profile(native)
    assert not model_has_profile(text)


# ---- the retrain scheduler --------------------------------------------------

class Rec:
    """Recording journal callable (the FleetSupervisor.journal shape)."""

    def __init__(self):
        self.events = []

    def __call__(self, kind, **fields):
        self.events.append(dict(fields, event=kind))

    def of(self, kind, **match):
        return [e for e in self.events if e["event"] == kind
                and all(e.get(k) == v for k, v in match.items())]


def _sched(models, launch, journal, **kw):
    kw.setdefault("policy", RetryPolicy(backoff_base_s=0.0, retry_budget=3))
    kw.setdefault("has_profile", lambda p: True)
    return RetrainScheduler(models, launch, journal=journal, **kw)


def test_scheduler_skips_profileless_model(tmp_path, base_model, monkeypatch):
    """A pre-r18 artifact (no embedded profile) is SKIPPED with a
    journaled reason — no launch, no crash: there is no baseline to
    retrain against, so the breach is for a human."""
    monkeypatch.setenv("DRYAD_PROFILE", "0")
    model, ds = base_model
    path = str(tmp_path / "old.dryad")
    dryad.train(dict(PARAMS, num_trees=2), ds, backend="cpu").save(path)
    launched = []
    rec = Rec()
    rs = _sched({"legacy": path},
                lambda m, g, j, a: launched.append(m) or (True, a, ""),
                rec, has_profile=model_has_profile)
    assert rs.trigger("legacy") is False
    assert not launched
    skips = rec.of("retrain_skipped", model="legacy", reason="no_profile")
    assert len(skips) == 1
    assert not rs.state()["inflight"]


def test_scheduler_skips_unknown_and_unreadable(tmp_path):
    rec = Rec()
    rs = _sched({"m": str(tmp_path / "missing.dryad")},
                lambda *a: (True, "x", ""), rec,
                has_profile=model_has_profile)
    assert rs.trigger("ghost") is False
    assert rec.of("retrain_skipped", model="ghost", reason="unknown_model")
    # the artifact does not exist: sniffing raises, the scheduler survives
    assert rs.trigger("m") is False
    assert any(e["reason"].startswith("artifact_unreadable")
               for e in rec.of("retrain_skipped", model="m"))


def test_scheduler_debounce_inflight_and_cooldown(tmp_path):
    """One sustained breach = one retrain: concurrent duplicates fall to
    in_flight, post-completion duplicates to cooldown."""
    gate = threading.Event()
    done = threading.Event()
    launches = []

    def launch(model, gen, job, artifact):
        launches.append((model, gen, job))
        gate.wait(10.0)
        return True, f"{artifact}-g{gen}", ""

    rec = Rec()
    rs = _sched({"m": "art"}, launch, rec, cooldown_s=3600.0)
    orig = rs._retrain_job

    def tracked(*a, **kw):
        try:
            orig(*a, **kw)
        finally:
            done.set()

    rs._retrain_job = tracked
    assert rs.trigger("m") is True
    assert rs.trigger("m") is False          # worker still holds in_flight
    assert rec.of("retrain_skipped", model="m", reason="in_flight")
    gate.set()
    assert done.wait(10.0)
    assert rs.trigger("m") is False          # now inside the cooldown
    assert rec.of("retrain_skipped", model="m", reason="cooldown")
    assert launches == [("m", 1, 0)]
    assert len(rec.of("retrain_triggered", model="m")) == 1
    assert len(rec.of("retrain_complete", model="m", generation=1)) == 1
    rs.stop(timeout_s=5.0)


def test_scheduler_failure_backoff_and_budget():
    """Launch failures journal retrain_failed, arm the per-model backoff,
    and a spent retry budget stops the scheduler from flapping."""
    rec = Rec()
    rs = _sched({"m": "art"}, lambda *a: (False, None, "rc=9"), rec,
                cooldown_s=0.0,
                policy=RetryPolicy(backoff_base_s=0.0, backoff_max_s=0.0,
                                   retry_budget=1))
    for _ in range(4):
        rs.trigger("m")
        deadline = 100                        # wait the worker out
        while rs.state()["inflight"] and deadline:
            deadline -= 1
            threading.Event().wait(0.05)
    fails = rec.of("retrain_failed", model="m")
    assert fails and all(e["detail"] == "rc=9" for e in fails)
    # budget exhausted: later triggers are skipped, not launched
    assert rec.of("retrain_skipped", model="m",
                  reason="retry_budget_exhausted")
    assert rs.state()["generation"].get("m", 0) == 0


def test_journal_tailer_incremental_partial_lines(tmp_path):
    path = str(tmp_path / "j.jsonl")
    t = JournalTailer(path)
    assert t() == []                          # nothing yet: not an error
    with open(path, "a") as f:
        f.write(json.dumps({"event": "a"}) + "\n")
        f.write('{"event": "b", "tr')         # torn mid-record
    got = t()
    assert [e["event"] for e in got] == ["a"]
    with open(path, "a") as f:
        f.write('uncated": 1}\n')
        f.write("not json at all\n")
        f.write(json.dumps({"event": "c"}) + "\n")
    got = t()                                 # the torn line heals whole
    assert [e["event"] for e in got] == ["b", "c"]
    assert t() == []


# ---- the probation publisher ------------------------------------------------

def _verdict(rows=128, breached=False, sustained=False, psi=0.05):
    return {"rows": rows, "breached": breached, "sustained": sustained,
            "psi_max": psi, "score_psi": 0.0, "streak": 0, "top": []}


def _publisher(push, feed, rec, **kw):
    it = iter(feed)

    def verdicts():
        return {"m": next(it)}

    kw.setdefault("probation_polls", len(feed))
    kw.setdefault("poll_interval_s", 0.0)
    return ProbationPublisher(push, verdicts, journal=rec, **kw)


def test_publisher_promotes_on_clear(tmp_path):
    rec = Rec()
    pushes = []
    pub = _publisher(lambda p, m: pushes.append(p) or (True, ""),
                     [_verdict(rows=0), _verdict(), _verdict()], rec,
                     clear_after=2)
    out = pub.publish("gen1", model="m", prior_path="gen0", generation=1)
    assert out == "promoted"
    assert pushes == ["gen1"]                 # promote never re-pushes
    assert rec.of("push_probation", model="m", generation=1)
    promo = rec.of("generation_promoted", model="m", generation=1)
    assert len(promo) == 1 and promo[0]["path"] == "gen1"


def test_publisher_rolls_back_bad_generation():
    """Prior clean + pushed generation sustains a breach => the PRIOR
    ARTIFACT is re-pushed through the same rolling machinery — the
    registry is never mutated in place."""
    rec = Rec()
    pushes = []
    feed = [_verdict(),                                    # prior: clean
            _verdict(breached=True, psi=0.9),
            _verdict(breached=True, sustained=True, psi=0.9)]
    pub = _publisher(lambda p, m: pushes.append(p) or (True, ""), feed, rec)
    out = pub.publish("gen2", model="m", prior_path="gen1", generation=2)
    assert out == "rolled_back"
    assert pushes == ["gen2", "gen1"]         # the rollback IS a re-push
    rb = rec.of("generation_rolled_back", model="m", generation=2)
    assert len(rb) == 1
    assert rb[0]["prior"] == "gen1" and rb[0]["restore_ok"] is True
    assert not rec.of("generation_promoted", model="m", generation=2)


def test_publisher_no_rollback_when_prior_was_dirty():
    """If the PREDECESSOR was already breaching at push time, a breach in
    probation proves nothing against the new generation — rolling back
    to a known-bad model would flap forever."""
    rec = Rec()
    pushes = []
    feed = ([_verdict(breached=True, sustained=True)]      # prior: dirty
            + [_verdict(breached=True, sustained=True)] * 3)
    pub = _publisher(lambda p, m: pushes.append(p) or (True, ""), feed, rec)
    out = pub.publish("gen1", model="m", prior_path="gen0", generation=1)
    assert out == "promoted"                  # window expired, kept
    assert pushes == ["gen1"]
    promo = rec.of("generation_promoted", model="m", generation=1)
    assert len(promo) == 1 and promo[0]["verdict"] == "expired"
    assert not rec.of("generation_rolled_back")


def test_publisher_push_failure_is_terminal():
    rec = Rec()
    pub = _publisher(lambda p, m: (False, "drain timeout"), [_verdict()],
                     rec)
    out = pub.publish("gen1", model="m", prior_path="gen0", generation=1)
    assert out == "push_failed"
    assert rec.of("push_failed", model="m", generation=1)
    assert not rec.of("push_probation")


def test_publisher_empty_windows_do_not_clear():
    """rows == 0 is no evidence — a generation must not promote off an
    idle fleet's empty drift windows."""
    rec = Rec()
    feed = [_verdict()] + [_verdict(rows=0)] * 3
    pub = _publisher(lambda p, m: (True, ""), feed, rec, clear_after=1,
                     probation_polls=3)
    out = pub.publish("gen1", model="m", prior_path="gen0", generation=1)
    assert out == "promoted"
    assert rec.of("generation_promoted", model="m",
                  generation=1)[0]["verdict"] == "expired"
