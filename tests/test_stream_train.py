"""Out-of-core streamed training (Issue 17 / r20).

The headline invariant: training from a ``StreamedDataset`` (binned
matrix on disk, bounded chunk reads) is BITWISE identical to training
from the resident matrix — trees, eval metrics, and the early-stop
iteration — at two different chunkings, on both trainers, including
GOSS/bagging/early-stop and kill-and-resume through the supervisor.
Exactness is by construction (the streamed accessors return arrays
elementwise identical to resident slices, so every fold order is
unchanged); these tests pin that construction against the real trainers.
"""

import os

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.data.stream_dataset import (
    DEFAULT_CHUNK_ROWS,
    SpillSink,
    StreamedDataset,
)
from dryad_tpu.data.streaming import dataset_from_chunks
from dryad_tpu.datasets import higgs_like

KEYS = ("feature", "threshold", "left", "right", "value")
#: two deliberately ragged chunkings (neither divides 3000)
CHUNKINGS = (700, 1231)

PARAMS = dict(objective="binary", num_trees=8, num_leaves=7, max_bins=32,
              seed=3, min_data_in_leaf=5)


def assert_same_booster(a, b):
    for k in KEYS:
        np.testing.assert_array_equal(getattr(a, k), getattr(b, k))


@pytest.fixture(scope="module")
def data():
    X, y = higgs_like(3000, seed=21)
    return dryad.Dataset(X, y, max_bins=32)


@pytest.fixture(scope="module")
def valid(data):
    Xv, yv = higgs_like(800, seed=22)
    return dryad.Dataset(Xv, yv, mapper=data.mapper)


def spill(ds, tmp_path, chunk_rows, name="bins.stream"):
    return StreamedDataset.from_dataset(
        ds, str(tmp_path / f"{chunk_rows}_{name}"), chunk_rows=chunk_rows)


# ---- the spill + bounded accessors ------------------------------------------

def test_spill_roundtrip_and_accessors(data, tmp_path):
    sds = spill(data, tmp_path, 700)
    Xb = data.X_binned
    assert sds.num_rows == data.num_rows
    assert sds.num_features == data.num_features
    assert sds.num_chunks == -(-data.num_rows // 700)
    np.testing.assert_array_equal(sds.read_rows(0, data.num_rows), Xb)
    np.testing.assert_array_equal(sds.read_rows(693, 1402), Xb[693:1402])
    assert sds.read_rows(5, 5).shape == (0, data.num_features)
    # chunk iteration (prefetched AND inline) re-assembles the matrix
    for prefetch in (2, 0):
        got = np.concatenate(
            [buf for _lo, _hi, buf in sds.iter_chunks(prefetch)], axis=0)
        np.testing.assert_array_equal(got, Xb)
    # the profile subsample stride is exactly Xb[::stride]
    for stride in (1, 3, 700, 997):
        np.testing.assert_array_equal(sds.strided_rows(stride), Xb[::stride])
    assert sds.has_missing == data.has_missing
    with pytest.raises(ValueError, match="row range"):
        sds.read_rows(0, data.num_rows + 1)


def test_streamed_matrix_gathers_and_traps(data, tmp_path):
    sds = spill(data, tmp_path, 1231)
    view = sds.binned_view()
    Xb = data.X_binned
    assert view.shape == Xb.shape and len(view) == len(Xb)
    rng = np.random.default_rng(5)
    rows = np.sort(rng.choice(data.num_rows, 900, replace=False))
    np.testing.assert_array_equal(view[rows], Xb[rows])
    np.testing.assert_array_equal(view[rows, 7], Xb[rows, 7])
    dup = np.sort(rng.integers(0, data.num_rows, 400))  # repeats are fine
    np.testing.assert_array_equal(view[dup, 2], Xb[dup, 2])
    with pytest.raises(ValueError, match="ascending"):
        view[rows[::-1]]
    with pytest.raises(TypeError):
        sds.X_binned  # the resident attribute is a trap on this class
    sink = SpillSink(str(tmp_path / "over.bins"), 10, 4, np.dtype(np.uint8))
    sink.write(np.zeros((8, 4), np.uint8))
    with pytest.raises(ValueError, match="more than the declared"):
        sink.write(np.zeros((3, 4), np.uint8))
    with pytest.raises(ValueError, match="expected"):
        SpillSink(str(tmp_path / "short.bins"), 10, 4,
                  np.dtype(np.uint8)).finish()


def test_dataset_from_chunks_spill_bitwise(tmp_path):
    """The chunked builder's spill arm: same sketch, same two-pass keying,
    bins land on disk instead of in the resident matrix — bit for bit."""
    N, F = 2000, 16
    rng = np.random.default_rng(9)
    X = rng.standard_normal((N, F)).astype(np.float32)
    X[rng.random((N, F)) < 0.05] = np.nan          # exercise missing bins
    y = (X[:, 0] > 0.1).astype(np.float32)

    def chunks():
        for lo in range(0, N, 517):
            yield X[lo:lo + 517]

    res = dataset_from_chunks(chunks, y, N, F, max_bins=32)
    stm = dataset_from_chunks(chunks, y, N, F, max_bins=32,
                              spill=str(tmp_path / "cb.bins"), chunk_rows=601)
    assert stm.is_streamed and stm.chunk_rows == 601
    np.testing.assert_array_equal(stm.read_rows(0, N), res.X_binned)
    assert stm.has_missing == res.has_missing
    p = dict(PARAMS, num_trees=4)
    assert_same_booster(dryad.train(p, res, backend="cpu"),
                        dryad.train(p, stm, backend="cpu"))


def test_dataset_from_csr_chunks_spill_bitwise(tmp_path):
    """The sparse/EFB builder's spill arm: plan + exact verification
    passes unchanged, the BUNDLED (folded-width) fold lands on disk."""
    from dryad_tpu.data.bundling import BundledMapper
    from dryad_tpu.data.streaming import dataset_from_csr_chunks
    from tests.test_bundling import _onehot_csr

    (indptr, cols, vals, F), y = _onehot_csr(n=2048)

    def chunks():
        for lo in range(0, 2048, 600):
            hi = min(lo + 600, 2048)
            a, b = indptr[lo], indptr[hi]
            yield (indptr[lo:hi + 1] - a, cols[a:b], vals[a:b])

    res = dataset_from_csr_chunks(chunks, y, 2048, F, max_bins=64)
    stm = dataset_from_csr_chunks(chunks, y, 2048, F, max_bins=64,
                                  spill=str(tmp_path / "csr.bins"),
                                  chunk_rows=777)
    assert isinstance(res.mapper, BundledMapper) and res.mapper.bundles
    # the spill is sized by the FOLDED width, not the raw column count
    assert stm.num_features == res.num_features < F
    np.testing.assert_array_equal(stm.read_rows(0, 2048), res.X_binned)
    p = dict(PARAMS, num_trees=3)
    assert_same_booster(dryad.train(p, res, backend="cpu"),
                        dryad.train(p, stm, backend="cpu"))


# ---- the headline: streamed ≡ resident bitwise, both trainers ---------------

def test_cpu_streamed_bitwise_both_growers(data, tmp_path):
    for growth, extra in (("leafwise", {}),
                          ("depthwise", {"max_depth": 4})):
        p = dict(PARAMS, growth=growth, **extra)
        ref = dryad.train(p, data, backend="cpu")
        for chunk_rows in CHUNKINGS:
            got = dryad.train(p, spill(data, tmp_path, chunk_rows,
                                       f"{growth}.bins"), backend="cpu")
            assert_same_booster(ref, got)


def test_engine_streamed_bitwise_two_chunkings(data, tmp_path):
    p = dict(PARAMS, num_trees=4)
    ref = dryad.train(p, data, backend="tpu")
    for chunk_rows in CHUNKINGS:
        got = dryad.train(p, spill(data, tmp_path, chunk_rows, "eng.bins"),
                          backend="tpu")
        assert_same_booster(ref, got)


def test_cpu_streamed_goss_bagging_earlystop(data, valid, tmp_path):
    """Sampling keyed on global row id + eval on the chunked matrix:
    GOSS, bagging+colsample, and the early-stop iteration all match the
    resident run exactly — including a STREAMED valid set on CPU."""
    sds = spill(data, tmp_path, 700)
    svalid = spill(valid, tmp_path, 271, "valid.bins")
    for extra in ({"boosting": "goss"},
                  {"subsample": 0.7, "colsample": 0.7}):
        p = dict(PARAMS, num_trees=30, early_stopping_rounds=3, **extra)
        ref = dryad.train(p, data, valid_sets=[valid], backend="cpu")
        for vset in (valid, svalid):
            got = dryad.train(p, sds, valid_sets=[vset], backend="cpu")
            assert_same_booster(ref, got)
            assert got.best_iteration == ref.best_iteration
            assert (got.train_state["eval_history"]
                    == ref.train_state["eval_history"])


def test_supervised_kill_resume_streamed_bitwise(data, tmp_path):
    """Kill-and-resume mid-epoch: the supervisor's checkpoint replay path
    walks the streamed matrix too, and the resumed run reproduces the
    uninterrupted streamed run — which IS the resident run — bitwise."""
    from dryad_tpu.resilience import (FaultInjector, RetryPolicy, RunJournal,
                                      supervise_train)
    from dryad_tpu.resilience import faults as F

    sds = spill(data, tmp_path, 1231)
    p = dict(PARAMS, num_trees=12)
    ref = dryad.train(p, data, backend="cpu")
    injector = FaultInjector([(5, F.DEVICE_UNAVAILABLE, "dispatch"),
                              (9, F.OOM, "fetch")])
    jpath = str(tmp_path / "journal.jsonl")
    got = supervise_train(p, sds, backend="cpu",
                          checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_every=3, journal=jpath,
                          fault_injector=injector,
                          policy=RetryPolicy(backoff_base_s=0.0))
    assert injector.pending == 0
    assert_same_booster(ref, got)
    events = RunJournal.read(jpath)
    assert any(e["event"] == "resume" for e in events)


# ---- engine gates (fail loudly, never silently materialize) -----------------

def test_engine_streamed_gates(data, tmp_path):
    import jax

    from dryad_tpu.engine.distributed import make_mesh

    sds = spill(data, tmp_path, 700, "gates.bins")
    with pytest.raises(ValueError, match="streamed"):
        dryad.train(dict(PARAMS, num_trees=2), sds, backend="tpu",
                    mesh=make_mesh(jax.devices()[:2]))
    with pytest.raises(ValueError, match="materialize"):
        dryad.train(dict(PARAMS, num_trees=2), data,
                    valid_sets=[sds], backend="tpu")
    # materialize() really is the resident equivalent
    assert_same_booster(
        dryad.train(dict(PARAMS, num_trees=2), data, backend="cpu"),
        dryad.train(dict(PARAMS, num_trees=2), sds.materialize(),
                    backend="cpu"))


# ---- the retrain CLI's directory-of-shards corpus ---------------------------

def test_retrain_cli_directory_corpus(tmp_path):
    from dryad_tpu.__main__ import main

    N, F = 1200, 8
    rng = np.random.default_rng(31)
    X = rng.standard_normal((N, F)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0.2).astype(np.float32)
    base = dryad.train(dict(PARAMS, num_trees=6),
                       dryad.Dataset(X, y, max_bins=32), backend="cpu")
    mpath = str(tmp_path / "m.dryad")
    base.save(mpath)

    Xf = rng.standard_normal((900, F)).astype(np.float32)
    yf = (Xf[:, 0] + 0.5 * Xf[:, 1] > 0.2).astype(np.float32)
    shard_dir = tmp_path / "shards"
    shard_dir.mkdir()
    np.savez(shard_dir / "a.npz", X=Xf[:400], y=yf[:400])
    np.savez(shard_dir / "b.npz", X=Xf[400:], y=yf[400:])
    np.savez(tmp_path / "fresh.npz", X=Xf, y=yf)

    out_dir = str(tmp_path / "gen1_dir.dryad")
    out_npz = str(tmp_path / "gen1_npz.dryad")
    for out, src in ((out_dir, str(shard_dir)),
                     (out_npz, str(tmp_path / "fresh.npz"))):
        assert main(["retrain", "--model", mpath, "--data", src,
                     "--out", out, "--trees", "3", "--backend", "cpu"]) == 0
    a, b = dryad.Booster.load(out_dir), dryad.Booster.load(out_npz)
    assert_same_booster(a, b)          # shard stream ≡ one resident npz
    assert a.num_iterations == base.num_iterations + 3
    n0 = base.feature.shape[0]         # old trees are a bitwise prefix
    for k in KEYS:
        np.testing.assert_array_equal(np.asarray(getattr(a, k))[:n0],
                                      np.asarray(getattr(base, k)))
    assert not os.path.exists(out_dir + ".bins")  # spill cleaned up
