"""r23 self-tuning dispatch: the policy calibration subsystem.

The hard invariant under test: a policy flip NEVER changes traced-program
semantics — only which pre-audited arm dispatches — and under the
COMMITTED default table every gate resolves bitwise-identically to the
pre-r23 hand-tuned constants.  The oracle arms below are spelled as
literals (not derived from GATE_DEFAULTS), so a drifted default fails
here even though the code would still be self-consistent.
"""

from __future__ import annotations

import json
import warnings

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.policy import calibrate, device, gates
from dryad_tpu.policy import table as ptable

ROOT = __file__.rsplit("/tests/", 1)[0]


@pytest.fixture(autouse=True)
def _fresh_policy(monkeypatch):
    """Each test sees a fresh memoized table/device/decision state and
    cannot leak its own (reset is the documented test-isolation hook)."""
    monkeypatch.delenv(ptable.TABLE_ENV, raising=False)
    ptable.reset_cache()
    gates.reset_decisions()
    yield
    ptable.reset_cache()
    gates.reset_decisions()
    device.reset()


# ---------------------------------------------------------------------------
# the committed golden and the default-parity contract

def test_committed_golden_equals_code_defaults():
    tab = ptable.load_table(ptable.GOLDEN_PATH, explicit=False)
    assert tab.fallback_reason is None
    assert tab.devices[ptable.DEFAULT_DEVICE_KEY]["gates"] \
        == ptable.GATE_DEFAULTS
    # and the committed default caps still mirror their structural twins
    from dryad_tpu.engine import leafperm

    assert ptable.GATE_DEFAULTS["deep_layout"]["max_record_bytes"] \
        == leafperm._REC_WB


def test_selftest_green():
    # the ci.sh gate: default parity + exact perturbation flips +
    # round-trip + derive rules, all seeded CPU, no probes
    assert calibrate.run_selftest(quiet=True) == 0


def test_parity_cases_are_the_pre_policy_constants():
    """Every oracle case resolves to its hand-written arm under the
    committed table with NO device key (the parity anchor)."""
    golden = ptable.load_table(ptable.GOLDEN_PATH, explicit=False)
    for gate, cases in calibrate.PARITY_CASES.items():
        for feats, want in cases:
            got = gates.resolve(gate, feats, device_kind=None, table=golden)
            assert got == want, (gate, feats)


def test_call_sites_straddle_every_threshold():
    """The routed call sites (not just resolve()) honor the committed
    thresholds exactly at the boundary."""
    from dryad_tpu.config import Params, hist_reduce_resolved
    from dryad_tpu.engine.histogram import resolve_backend
    from dryad_tpu.engine.leafwise_fast import leafwise_layout_supported
    from dryad_tpu.engine.levelwise import partition_prefers_reduce
    from dryad_tpu.engine.predict import SHARDED_MIN_WORK
    from dryad_tpu.resilience.policy import RetryPolicy

    assert partition_prefers_reduce(4096, 1)
    assert not partition_prefers_reduce(4097, 1)
    assert partition_prefers_reduce(2048, 2)
    assert not partition_prefers_reduce(2049, 2)

    p = Params(num_trees=1)
    assert hist_reduce_resolved(p, 1024, 256, 2) == "feature"
    assert hist_reduce_resolved(p, 1023, 256, 2) == "fused"
    assert hist_reduce_resolved(p, 1024, 256, 1) == "fused"
    # explicit params skip the gate entirely
    pf = Params(num_trees=1, hist_reduce="fused")
    assert hist_reduce_resolved(pf, 4000, 256, 8) == "fused"

    assert resolve_backend("auto", platform="tpu") == "pallas"
    assert resolve_backend("auto", platform="axon") == "pallas"
    assert resolve_backend("auto", platform="cpu") == "xla"
    assert resolve_backend("xla", platform="tpu") == "xla"

    p10 = Params(num_trees=1, max_depth=10, hist_backend="pallas")
    p11 = Params(num_trees=1, max_depth=11, hist_backend="pallas")
    assert leafwise_layout_supported(p10, 28, 256, 1, platform="tpu")
    assert not leafwise_layout_supported(p11, 28, 256, 1, platform="tpu")

    assert SHARDED_MIN_WORK == 32768
    assert RetryPolicy().ch_max_ladder == (8, 4, 2)


def test_unknown_gate_raises():
    with pytest.raises(KeyError, match="unknown policy gate"):
        gates.resolve("no_such_gate", {})
    with pytest.raises(KeyError, match="no value"):
        gates.gate_value("partition", "no_such_key")


def test_gate_value_lists_come_back_as_tuples():
    assert gates.gate_value("chunk_cap", "ladder") == (8, 4, 2)


# ---------------------------------------------------------------------------
# device-keyed overlay: a device entry flips exactly its gate

def test_device_entry_flips_only_its_gate():
    golden = ptable.load_table(ptable.GOLDEN_PATH, explicit=False)
    tab = ptable.CalibrationTable(
        devices={**golden.devices,
                 "weird-accel": {"gates": {"leafwise_layout":
                                           {"max_segments": 512}}}},
        source="<test>")
    # depth 10 (1024 segments) flips to legacy on the calibrated device...
    assert gates.resolve("leafwise_layout", {"max_depth": 10},
                         device_kind="weird-accel", table=tab) == "legacy"
    assert gates.resolve("leafwise_layout", {"max_depth": 9},
                         device_kind="weird-accel", table=tab) == "layout"
    # ...while every other gate and every other device is untouched
    assert gates.resolve("leafwise_layout", {"max_depth": 10},
                         device_kind="other", table=tab) == "layout"
    assert gates.resolve("partition", {"num_features": 4096, "itemsize": 1},
                         device_kind="weird-accel", table=tab) == "reduce"


def test_default_table_resolution_never_probes_the_device(monkeypatch):
    """The committed table ships only ``_default`` — resolving against it
    must not wake a jax runtime (fleet control plane + audit-env
    ordering).  A table WITH device entries pays the probe."""
    calls = []

    def probe():
        calls.append(1)
        return "probed-kind"

    monkeypatch.setattr(gates, "current_device_kind", probe)
    golden = ptable.load_table(ptable.GOLDEN_PATH, explicit=False)
    assert gates.resolve("partition", {"num_features": 1, "itemsize": 1},
                         table=golden) == "reduce"
    assert calls == []
    keyed = ptable.CalibrationTable(
        devices={**golden.devices, "probed-kind": {"gates": {}}},
        source="<test>")
    gates.resolve("partition", {"num_features": 1, "itemsize": 1},
                  table=keyed)
    assert calls == [1]


# ---------------------------------------------------------------------------
# bitwise train/predict parity: explicit default table vs no table

def test_train_predict_bitwise_with_explicit_default_table(monkeypatch):
    X, y = higgs_like(1200)
    ds = dryad.Dataset(X, y, max_bins=32)
    params = dict(objective="binary", num_trees=3, num_leaves=15,
                  max_bins=32, learning_rate=0.2)

    ptable.reset_cache()
    base = dryad.train(params, ds, backend="tpu")
    base_pred = base.predict(X)

    monkeypatch.setenv(ptable.TABLE_ENV, ptable.GOLDEN_PATH)
    ptable.reset_cache()
    assert ptable.current_table().explicit
    tabbed = dryad.train(params, ds, backend="tpu")
    for k, v in base.tree_arrays().items():
        np.testing.assert_array_equal(v, tabbed.tree_arrays()[k],
                                      err_msg=f"tree array {k!r} diverged")
    np.testing.assert_array_equal(base_pred, tabbed.predict(X))


# ---------------------------------------------------------------------------
# loud-once fallback semantics

def test_corrupt_table_warns_once_and_resolves_on_defaults(
        tmp_path, monkeypatch):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    monkeypatch.setenv(ptable.TABLE_ENV, str(bad))
    ptable.reset_cache()
    with pytest.warns(RuntimeWarning, match="corrupt JSON"):
        tab = ptable.current_table()
    assert tab.fallback_reason and tab.explicit
    # resolution proceeds on the committed defaults
    assert gates.resolve("partition", {"num_features": 4096, "itemsize": 1},
                         device_kind=None) == "reduce"
    # loud ONCE: a second current_table() stays quiet
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        ptable.current_table()


def test_missing_and_wrong_schema_tables_fall_back(tmp_path):
    missing = ptable.load_table(str(tmp_path / "nope.json"))
    assert "unreadable" in missing.fallback_reason
    wrong = tmp_path / "wrong.json"
    wrong.write_text(json.dumps({"calibration_schema": 99, "devices": {}}))
    assert "schema" in ptable.load_table(str(wrong)).fallback_reason
    nomap = tmp_path / "nomap.json"
    nomap.write_text(json.dumps({"calibration_schema": 1, "devices": 3}))
    assert "malformed" in ptable.load_table(str(nomap)).fallback_reason
    # broken tables still resolve every gate on the code defaults
    for tab in (missing,):
        assert tab.gate_values("partition", None) \
            == ptable.GATE_DEFAULTS["partition"]


def test_explicit_table_unknown_device_warns_once_per_kind(tmp_path):
    p = tmp_path / "t.json"
    ptable.save_table({"_default": {"gates": {}}}, str(p))
    tab = ptable.load_table(str(p))       # path given -> explicit
    with pytest.warns(RuntimeWarning, match="no entry for device_kind"):
        tab.gate_values("partition", "TPU v99")
    with warnings.catch_warnings():       # once per kind
        warnings.simplefilter("error")
        tab.gate_values("hist_reduce", "TPU v99")
    with pytest.warns(RuntimeWarning):    # a new kind warns again
        tab.gate_values("partition", "TPU v100")


def test_committed_table_unknown_device_is_silent():
    golden = ptable.load_table(ptable.GOLDEN_PATH, explicit=False)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        vals = golden.gate_values("partition", "some-future-tpu")
    assert vals == ptable.GATE_DEFAULTS["partition"]


# ---------------------------------------------------------------------------
# calibration: round-trip, derive rules, check diff

def test_save_load_round_trip(tmp_path):
    devices = {"_default": {"gates": dict(ptable.GATE_DEFAULTS)},
               "TPU v5e": {"gates": {"partition":
                                     {"reduce_max_row_bytes": 8192}},
                           "git_rev": "abc1234"}}
    p = tmp_path / "cal.json"
    ptable.save_table(devices, str(p))
    loaded = ptable.load_table(str(p))
    assert loaded.fallback_reason is None
    assert loaded.devices == devices
    assert gates.resolve("partition", {"num_features": 8192, "itemsize": 1},
                         device_kind="TPU v5e", table=loaded) == "reduce"


def test_derive_overrides_rules_and_spread_veto():
    walls = {
        "partition": {512: {"reduce": {"ms": 1.0, "spread": 0.0},
                            "gather": {"ms": 9.0, "spread": 0.0}},
                      8192: {"reduce": {"ms": 9.0, "spread": 0.0},
                             "gather": {"ms": 1.0, "spread": 0.0}}},
        "predict_layout": {28: {"packed": {"ms": 2.0, "spread": 0.0},
                                "legacy": {"ms": 1.0, "spread": 0.0}}},
        "hist_backend": {28: {"masked": {"ms": 1.0, "spread": 0.0},
                              "segmented": {"ms": 2.0, "spread": 0.0}}},
    }
    ov, notes = calibrate.derive_overrides(walls)
    assert ov["partition"] == {"reduce_max_row_bytes": 512}
    assert ov["predict_layout"] == {"preferred": "legacy"}
    assert notes["hist_backend"] == "informational"
    walls["predict_layout"][28]["packed"]["spread"] = 0.2
    ov2, notes2 = calibrate.derive_overrides(walls)
    assert "predict_layout" not in ov2
    assert "suspect" in notes2["predict_layout"]


def test_check_calib_flags_resolution_drift(monkeypatch):
    """A sweep whose derived thresholds flip a committed resolution (with
    clean spreads) must fail the check; the same walls marked suspect
    must not."""
    walls = {
        "partition": {512: {"reduce": {"ms": 9.0, "spread": 0.0},
                            "gather": {"ms": 1.0, "spread": 0.0}},
                      4096: {"reduce": {"ms": 9.0, "spread": 0.0},
                             "gather": {"ms": 1.0, "spread": 0.0}},
                      8192: {"reduce": {"ms": 9.0, "spread": 0.0},
                             "gather": {"ms": 1.0, "spread": 0.0}}},
    }
    monkeypatch.setattr(calibrate, "run_sweep", lambda **kw: walls)
    report = calibrate.check_calib(device_kind="fake-kind")
    assert not report["ok"]
    assert report["gates"]["partition"]["verdict"] == "drift"
    assert report["gates"]["partition"]["diffs"]
    for width in walls["partition"]:
        walls["partition"][width]["gather"]["spread"] = 0.5
    report2 = calibrate.check_calib(device_kind="fake-kind")
    assert report2["ok"]
    assert report2["gates"]["partition"]["verdict"] in ("ok", "suspect")


# ---------------------------------------------------------------------------
# decisions / stats / the predict_layout fallback reason

def test_decisions_and_stats_block_record_the_fallback_reason():
    from dryad_tpu.engine.predict import packed_fallback_reason

    reason = packed_fallback_reason(
        np.array([0]), np.array([70000]), np.array([1]), np.array([2]))
    assert "threshold" in reason and "16-bit" in reason
    arm = gates.resolve("predict_layout", {"fits": reason is None},
                        device_kind=None, detail=reason)
    assert arm == "legacy"
    d = gates.decisions()["predict_layout"]
    assert d["arm"] == "legacy" and "threshold" in d["detail"]
    block = gates.stats_block()
    assert block["decisions"]["predict_layout"]["detail"] == reason
    assert block["fallback_reason"] is None
    assert "_default" in block["device_keys"]


def test_stage_trees_auto_records_policy_decision():
    X, y = higgs_like(400)
    ds = dryad.Dataset(X, y, max_bins=32)
    b = dryad.train(dict(objective="binary", num_trees=2, num_leaves=7,
                         max_bins=32), ds, backend="cpu")
    from dryad_tpu.engine.predict import stage_trees

    gates.reset_decisions()
    trees, _, _ = stage_trees(b)
    assert "node_word" in trees            # numeric model packs
    d = gates.decisions()["predict_layout"]
    assert d["arm"] == "packed" and d["detail"] is None


# ---------------------------------------------------------------------------
# the r23 lint rules (mutation checks, like test_analysis_lint.py)

def _lint(rule, overrides=None):
    from dryad_tpu.analysis.lint import run_lint

    rep = run_lint(ROOT, rule_names=[rule], overrides=overrides)
    return [v for v in rep.violations if v.rule == rule]


def test_gate_through_policy_clean_and_catches_folded_literal():
    assert _lint("gate-through-policy") == []
    src = open(f"{ROOT}/dryad_tpu/engine/levelwise.py").read()
    bad = src.replace(
        'return resolve("partition", {"num_features": num_features,\n'
        '                                 "itemsize": itemsize}) == "reduce"',
        "return num_features * itemsize <= (1 << 15)")
    assert bad != src
    hits = _lint("gate-through-policy",
                 {"dryad_tpu/engine/levelwise.py": bad})
    assert any("32768" in v.message and "partition_prefers_reduce"
               in v.message for v in hits)


def test_gate_through_policy_ignores_small_shape_arithmetic():
    src = open(f"{ROOT}/dryad_tpu/engine/levelwise.py").read()
    ok = src.replace(
        'return resolve("partition", {"num_features": num_features,\n'
        '                                 "itemsize": itemsize}) == "reduce"',
        "return num_features * itemsize <= 9 + 2 * 8")
    assert ok != src
    assert _lint("gate-through-policy",
                 {"dryad_tpu/engine/levelwise.py": ok}) == []


def test_policy_jax_free_clean_and_catches_direct_import():
    assert _lint("policy-jax-free") == []
    src = open(f"{ROOT}/dryad_tpu/policy/gates.py").read()
    bad = src + "\n\ndef _peek():\n    import jax\n    return jax\n"
    hits = _lint("policy-jax-free", {"dryad_tpu/policy/gates.py": bad})
    assert any("import jax" in v.message for v in hits)


def test_policy_jax_free_catches_transitive_chain():
    src = open(f"{ROOT}/dryad_tpu/policy/table.py").read()
    bad = "from dryad_tpu.engine.histogram import resolve_backend\n" + src
    hits = _lint("policy-jax-free", {"dryad_tpu/policy/table.py": bad})
    assert any("transitive jax import" in v.message for v in hits)
