import numpy as np

from dryad_tpu.data.binning import bin_csr, bin_matrix, zero_bins
from dryad_tpu.data.sketch import sketch_features
from dryad_tpu.dataset import Dataset
from dryad_tpu import datasets


def _dense_from_csr(indptr, indices, values, n, F):
    X = np.zeros((n, F), np.float32)
    for r in range(n):
        for k in range(indptr[r], indptr[r + 1]):
            X[r, indices[k]] = values[k]
    return X


def test_csr_matches_dense_bitwise():
    (indptr, indices, values, F), y, cat_ids = datasets.criteo_like(n=2000, seed=19)
    n = indptr.shape[0] - 1
    X = _dense_from_csr(indptr, indices, values, n, F)
    mapper = sketch_features(X, max_bins=64, categorical_features=cat_ids)
    dense_bins = bin_matrix(X, mapper)
    csr_bins = bin_csr(indptr, indices, values, F, mapper, block_rows=333)
    np.testing.assert_array_equal(dense_bins, csr_bins)


def test_csr_dataset_sketch_includes_zeros():
    (indptr, indices, values, F), y, cat_ids = datasets.criteo_like(n=3000, seed=23)
    ds = Dataset(csr=(indptr, indices, values, F), y=y, categorical_features=cat_ids, max_bins=64)
    n = indptr.shape[0] - 1
    X = _dense_from_csr(indptr, indices, values, n, F)
    ref = sketch_features(X, max_bins=64, categorical_features=cat_ids)
    np.testing.assert_array_equal(ds.X_binned, bin_matrix(X, ref))


def test_zero_bins_consistency():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(1000, 3)).astype(np.float32)
    X[:500, 1] = 0.0
    m = sketch_features(X, max_bins=32)
    zb = zero_bins(m)
    direct = m.transform(np.zeros((1, 3), np.float32))[0]
    np.testing.assert_array_equal(zb, direct.astype(np.int64))


def test_dataset_bind_uses_frozen_mapper():
    X, y = datasets.higgs_like(2000, seed=3)
    ds = Dataset(X, y, max_bins=32)
    Xv, yv = datasets.higgs_like(500, seed=4)
    dv = ds.bind(Xv, yv)
    assert dv.mapper is ds.mapper
    np.testing.assert_array_equal(dv.X_binned, ds.mapper.transform(Xv))


def test_group_validation():
    X, y, group = datasets.mslr_like(num_queries=10, seed=17)
    ds = Dataset(X, y, group=group)
    off = ds.query_offsets
    assert off[0] == 0 and off[-1] == ds.num_rows
    assert (np.diff(off) == group).all()
