"""Native C++ host layer vs the pure-numpy canonical spec — bit-for-bit.

The native .so (dryad_tpu/native) is the fast path for sketching, binning,
and CPU predict; the numpy implementations are the spec (BASELINE.json:5
bit-identity contract).  Every test here diffs the two exactly.
"""

import numpy as np
import pytest

from dryad_tpu import native
from dryad_tpu.data.sketch import (
    BinMapper,
    _sketch_categorical,
    _sketch_numerical_np,
    sketch_features,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no toolchain?)"
)


def _random_cols(rng):
    n = 4096
    yield "uniform", rng.standard_normal(n).astype(np.float32)
    yield "heavy-ties", rng.integers(0, 7, n).astype(np.float32)
    yield "constant", np.full(n, 3.25, np.float32)
    col = rng.standard_normal(n).astype(np.float32)
    col[rng.random(n) < 0.3] = np.nan
    yield "nan-mixed", col
    col2 = rng.standard_normal(n).astype(np.float32)
    col2[:16] = np.inf
    col2[16:32] = -np.inf
    yield "inf-tails", col2
    yield "all-nan", np.full(n, np.nan, np.float32)
    yield "tiny", rng.standard_normal(3).astype(np.float32)
    yield "denormal-range", (rng.standard_normal(n) * 1e-38).astype(np.float32)


@pytest.mark.parametrize("max_bins", [16, 256])
def test_sketch_numerical_bitwise(max_bins):
    rng = np.random.default_rng(0)
    for name, col in _random_cols(rng):
        want = _sketch_numerical_np(col, max_bins)
        got = native.sketch_numerical(col, max_bins)
        np.testing.assert_array_equal(
            got, want.edges, err_msg=f"sketch mismatch on {name}"
        )


def test_bin_matrix_bitwise():
    rng = np.random.default_rng(1)
    n, F = 2000, 9
    X = rng.standard_normal((n, F)).astype(np.float32)
    X[:, 2] = rng.integers(0, 40, n)            # categorical
    X[:, 5] = rng.integers(0, 500, n)           # categorical with overflow
    X[rng.random((n, F)) < 0.05] = np.nan
    X[:7, 0] = np.inf
    mapper = sketch_features(X, max_bins=64, categorical_features=(2, 5))
    want = mapper.transform(X)
    got = native.bin_matrix(X, mapper)
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(got, want)


def test_bin_matrix_bitwise_uint16():
    rng = np.random.default_rng(2)
    n = 3000
    X = rng.standard_normal((n, 3)).astype(np.float32)
    mapper = sketch_features(X, max_bins=1024)
    assert mapper.bin_dtype == np.uint16
    np.testing.assert_array_equal(native.bin_matrix(X, mapper), mapper.transform(X))


def test_predict_bitwise():
    import dryad_tpu as dryad

    rng = np.random.default_rng(3)
    n = 1500
    X = rng.standard_normal((n, 6)).astype(np.float32)
    X[:, 1] = rng.integers(0, 12, n)
    y = (X[:, 0] + (X[:, 1] > 5) > 0).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32, categorical_features=(1,))
    booster = dryad.train(
        dict(objective="binary", num_trees=12, num_leaves=15, max_bins=32),
        ds, backend="cpu",
    )
    Xb = ds.mapper.transform(X)
    want_score = native.predict_accumulate(
        Xb, booster.tree_arrays(), booster.init_score,
        booster.num_total_trees, booster.num_outputs, booster.max_depth_seen,
    )
    from dryad_tpu.cpu.predict import predict_tree_leaves

    score = np.broadcast_to(booster.init_score, (n, 1)).astype(np.float32).copy()
    trees = booster.tree_arrays()
    for t in range(booster.num_total_trees):
        leaves = predict_tree_leaves(trees, Xb, t, booster.max_depth_seen)
        score[:, 0] += booster.value[t, leaves]
    np.testing.assert_array_equal(want_score, score)


def test_predict_multiclass_bitwise():
    import dryad_tpu as dryad

    rng = np.random.default_rng(4)
    n = 900
    X = rng.standard_normal((n, 5)).astype(np.float32)
    y = rng.integers(0, 3, n).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32)
    booster = dryad.train(
        dict(objective="multiclass", num_class=3, num_trees=5, num_leaves=7,
             max_bins=32),
        ds, backend="cpu",
    )
    Xb = ds.mapper.transform(X)
    got = native.predict_accumulate(
        Xb, booster.tree_arrays(), booster.init_score,
        booster.num_total_trees, booster.num_outputs, booster.max_depth_seen,
    )
    from dryad_tpu.cpu.predict import predict_tree_leaves

    want = np.broadcast_to(booster.init_score, (n, 3)).astype(np.float32).copy()
    trees = booster.tree_arrays()
    for t in range(booster.num_total_trees):
        leaves = predict_tree_leaves(trees, Xb, t, booster.max_depth_seen)
        want[:, t % 3] += booster.value[t, leaves]
    np.testing.assert_array_equal(got, want)


def test_sketch_csr_parity_with_dense():
    """CSR ingest (native-accelerated sketch inside) ≡ dense ingest."""
    import dryad_tpu as dryad

    rng = np.random.default_rng(5)
    n, F = 800, 12
    X = np.zeros((n, F), np.float32)
    mask = rng.random((n, F)) < 0.2
    X[mask] = rng.standard_normal(int(mask.sum())).astype(np.float32)
    indptr = np.concatenate([[0], np.cumsum(mask.sum(1))]).astype(np.int64)
    indices = np.nonzero(mask)[1].astype(np.int64)
    values = X[mask]
    y = (X.sum(1) > 0).astype(np.float32)
    ds_dense = dryad.Dataset(X, y, max_bins=32)
    ds_csr = dryad.Dataset(None, y, csr=(indptr, indices, values, F), max_bins=32)
    np.testing.assert_array_equal(ds_dense.X_binned, ds_csr.X_binned)


def test_categorical_sketch_unchanged():
    """Categorical sketching stays on the numpy path — sanity anchor."""
    rng = np.random.default_rng(6)
    col = rng.integers(0, 50, 2000).astype(np.float32)
    fb = _sketch_categorical(col, 32)
    assert fb.is_categorical and fb.n_bins <= 32
