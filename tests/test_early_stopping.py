import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu import datasets


def test_valid_eval_and_early_stopping():
    X, y = datasets.higgs_like(12_000, seed=9)
    ds = dryad.Dataset(X[:8000], y[:8000])
    dv = ds.bind(X[8000:], y[8000:])
    seen = []
    b = dryad.train(
        {"objective": "binary", "num_trees": 60, "num_leaves": 63,
         "learning_rate": 0.5, "early_stopping_rounds": 5},
        ds, valid_sets=[dv], backend="cpu",
        callback=lambda it, info: seen.append(info),
    )
    assert any("valid_auc" in s for s in seen)
    assert b.best_iteration > 0
    # predictions default to best_iteration
    p_best = dryad.predict(b, X[8000:], raw_score=True)
    p_explicit = dryad.predict(b, X[8000:], raw_score=True, num_iteration=b.best_iteration)
    np.testing.assert_array_equal(p_best, p_explicit)


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_multiple_valid_sets(backend):
    X, y = datasets.higgs_like(9000, seed=21)
    ds = dryad.Dataset(X[:6000], y[:6000])
    dv1 = ds.bind(X[6000:7500], y[6000:7500])
    dv2 = ds.bind(X[7500:], y[7500:])
    seen = []
    b = dryad.train(
        {"objective": "binary", "num_trees": 15, "num_leaves": 15,
         "early_stopping_rounds": 4},
        ds, valid_sets=[dv1, dv2], backend=backend,
        callback=lambda it, info: seen.append(info),
    )
    evaled = [s for s in seen if len(s) > 1]
    # both sets scored every evaluation, under per-set names
    assert all("valid_0_auc" in s and "valid_1_auc" in s for s in evaled)
    assert b.best_iteration > 0
    # early stopping tracked the FIRST set: best_iteration argmaxes its curve
    curve = [s["valid_0_auc"] for s in evaled]
    assert curve[b.best_iteration - 1] == max(curve[: b.best_iteration])


def test_valid_names():
    X, y = datasets.higgs_like(4000, seed=23)
    ds = dryad.Dataset(X[:3000], y[:3000])
    dv = ds.bind(X[3000:], y[3000:])
    seen = []
    dryad.train({"objective": "binary", "num_trees": 5, "num_leaves": 7},
                ds, valid_sets=[dv, ds], valid_names=["holdout", "train"],
                backend="cpu", callback=lambda it, info: seen.append(info))
    assert all("holdout_auc" in s and "train_auc" in s for s in seen)
    with pytest.raises(ValueError, match="valid_names"):
        dryad.train({"objective": "binary", "num_trees": 2}, ds,
                    valid_sets=[dv], valid_names=["a", "b"], backend="cpu")


def test_depthwise_grows_balanced_levels():
    X, y = datasets.higgs_like(6000, seed=3)
    ds = dryad.Dataset(X, y)
    b = dryad.train(
        {"objective": "binary", "num_trees": 2, "growth": "depthwise", "max_depth": 4,
         "min_data_in_leaf": 1},
        ds, backend="cpu",
    )
    # depth-wise: every internal node at depth < d-1 was split before any
    # deeper node → the tree is level-complete: 2^4 = 16 leaves, 15 internal
    internal = (b.feature[0] >= 0).sum()
    assert internal == 15, internal


def test_resume_incompatible_raises():
    X, y = datasets.higgs_like(2000, seed=5)
    ds = dryad.Dataset(X, y)
    prev = dryad.train({"objective": "binary", "num_trees": 3, "num_leaves": 15}, ds, backend="cpu")
    with pytest.raises(ValueError, match="incompatible"):
        dryad.train({"objective": "binary", "num_trees": 6, "num_leaves": 31}, ds,
                    backend="cpu", init_booster=prev)
    with pytest.raises(ValueError, match="num_trees"):
        dryad.train({"objective": "binary", "num_trees": 2, "num_leaves": 15}, ds,
                    backend="cpu", init_booster=prev)


def test_categorical_max_bins_guard():
    with pytest.raises(ValueError, match="bitset"):
        dryad.Params.from_dict({"max_bins": 512, "categorical_features": [0]})


def test_eval_period_evaluates_tail():
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(2000, seed=107)
    ds = dryad.Dataset(X, y, max_bins=32)
    valid = ds.bind(X[:500], y[:500])
    infos = []
    b = dryad.train(dict(objective="binary", num_trees=20, num_leaves=7,
                         max_bins=32, eval_period=7), ds, [valid],
                    backend="cpu", callback=lambda it, i: infos.append(i))
    # detect evals by the metric key itself — info dicts also carry
    # non-metric metadata (ch_max_effective since r8, comm stats on mesh)
    evaled = [i["iteration"] for i in infos
              if any(k.startswith("valid_") for k in i)]
    assert evaled == [6, 13, 19]       # every 7th plus the forced final
    assert b.best_iteration > 0
