"""CLI front end: train/predict/dump round trips (SURVEY.md §5)."""

import json
import os

import numpy as np
import pytest

from dryad_tpu.__main__ import main
from dryad_tpu.datasets import criteo_like, higgs_like
from dryad_tpu.metrics import auc


@pytest.fixture()
def paths(tmp_path):
    X, y = higgs_like(2000, seed=41)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    np.save(tmp_path / "Xv.npy", X[:500])
    np.save(tmp_path / "yv.npy", y[:500])
    cfg = dict(objective="binary", num_trees=10, num_leaves=7, max_bins=32)
    (tmp_path / "cfg.json").write_text(json.dumps(cfg))
    return tmp_path


def test_train_predict_dump_roundtrip(paths):
    model = str(paths / "m.dryad")
    rc = main([
        "train", "--config", str(paths / "cfg.json"),
        "--data", str(paths / "X.npy"), "--label", str(paths / "y.npy"),
        "--valid", str(paths / "Xv.npy"), "--valid-label", str(paths / "yv.npy"),
        "--model", model, "--backend", "cpu", "--quiet",
        "--log-jsonl", str(paths / "log.jsonl"),
    ])
    assert rc == 0 and os.path.exists(model)
    lines = [json.loads(line) for line in open(paths / "log.jsonl")]
    assert len(lines) == 10 and "valid_auc" in lines[0]

    rc = main(["predict", "--model", model, "--data", str(paths / "X.npy"),
               "--out", str(paths / "p.npy")])
    assert rc == 0
    preds = np.load(paths / "p.npy")
    y = np.load(paths / "y.npy")
    assert auc(y, preds) > 0.6

    rc = main(["dump", "--model", model, "--out", str(paths / "m.json")])
    assert rc == 0
    dump = json.loads((paths / "m.json").read_text())
    assert dump["num_iterations"] == 10 and len(dump["trees"]) == 10


def test_cli_supervised_train(paths):
    """--supervise --journal: the resilient-run CLI path writes a
    well-formed journal and a model bitwise equal to the direct train."""
    model = str(paths / "m_sup.dryad")
    jpath = str(paths / "run.journal.jsonl")
    rc = main([
        "train", "--config", str(paths / "cfg.json"),
        "--data", str(paths / "X.npy"), "--label", str(paths / "y.npy"),
        "--model", model, "--backend", "cpu", "--quiet",
        "--checkpoint-dir", str(paths / "ck_sup"), "--checkpoint-every", "3",
        "--supervise", "--journal", jpath, "--retry-budget", "2",
    ])
    assert rc == 0 and os.path.exists(model)
    events = [json.loads(line) for line in open(jpath)]
    assert events[0]["event"] == "run_start"
    assert events[-1]["event"] == "complete" and events[-1]["faults"] == 0

    direct = str(paths / "m_direct.dryad")
    rc = main([
        "train", "--config", str(paths / "cfg.json"),
        "--data", str(paths / "X.npy"), "--label", str(paths / "y.npy"),
        "--model", direct, "--backend", "cpu", "--quiet",
    ])
    assert rc == 0
    import dryad_tpu as dryad

    a, b = dryad.Booster.load(model), dryad.Booster.load(direct)
    np.testing.assert_array_equal(a.feature, b.feature)
    np.testing.assert_array_equal(a.value, b.value)

    # guard rails: continuing a prior invocation's checkpoints must be
    # explicit — the first run left checkpoints in ck_sup
    with pytest.raises(SystemExit, match="existing checkpoints"):
        main(["train", "--config", str(paths / "cfg.json"),
              "--data", str(paths / "X.npy"), "--label", str(paths / "y.npy"),
              "--backend", "cpu", "--quiet", "--supervise",
              "--checkpoint-dir", str(paths / "ck_sup")])
    rc = main(["train", "--config", str(paths / "cfg.json"),
               "--data", str(paths / "X.npy"), "--label", str(paths / "y.npy"),
               "--backend", "cpu", "--quiet", "--supervise", "--resume",
               "--checkpoint-dir", str(paths / "ck_sup")])
    assert rc == 0                       # explicit --resume continues it

    # --supervise needs --checkpoint-dir; --journal needs --supervise
    with pytest.raises(SystemExit, match="checkpoint-dir"):
        main(["train", "--config", str(paths / "cfg.json"),
              "--data", str(paths / "X.npy"), "--label", str(paths / "y.npy"),
              "--backend", "cpu", "--quiet", "--supervise"])
    with pytest.raises(SystemExit, match="supervise"):
        main(["train", "--config", str(paths / "cfg.json"),
              "--data", str(paths / "X.npy"), "--label", str(paths / "y.npy"),
              "--backend", "cpu", "--quiet", "--journal", jpath])


def test_cli_csr_npz_train_predict(tmp_path):
    (indptr, indices, values, F), y, cat_ids = criteo_like(n=2000, seed=43)
    np.savez(tmp_path / "X.npz", indptr=indptr, indices=indices,
             values=values, num_features=F)
    np.save(tmp_path / "y.npy", y)
    cfg = dict(objective="binary", num_trees=8, num_leaves=15, max_bins=64,
               categorical_features=list(cat_ids))
    (tmp_path / "cfg.json").write_text(json.dumps(cfg))
    model = str(tmp_path / "m.dryad")
    rc = main(["train", "--config", str(tmp_path / "cfg.json"),
               "--data", str(tmp_path / "X.npz"), "--label",
               str(tmp_path / "y.npy"), "--model", model,
               "--backend", "cpu", "--quiet"])
    assert rc == 0
    rc = main(["predict", "--model", model, "--data", str(tmp_path / "X.npz"),
               "--out", str(tmp_path / "p.npy")])
    assert rc == 0
    preds = np.load(tmp_path / "p.npy")
    assert preds.shape == (2000,) and auc(y, preds) > 0.55


def test_cli_serve_one_shot_smoke(paths):
    """serve --request: one request through the full serving stack (bucketed
    compiled predict + micro-batcher), bitwise equal to the predict CLI."""
    model = str(paths / "m.dryad")
    rc = main([
        "train", "--config", str(paths / "cfg.json"),
        "--data", str(paths / "X.npy"), "--label", str(paths / "y.npy"),
        "--model", model, "--backend", "cpu", "--quiet",
    ])
    assert rc == 0
    rc = main(["serve", "--model", model, "--backend", "cpu",
               "--max-batch-rows", "64", "--request", str(paths / "X.npy"),
               "--out", str(paths / "served.npy"), "--quiet"])
    assert rc == 0
    rc = main(["predict", "--model", model, "--data", str(paths / "X.npy"),
               "--out", str(paths / "direct.npy")])
    assert rc == 0
    served = np.load(paths / "served.npy")
    direct = np.load(paths / "direct.npy")
    assert served.dtype == direct.dtype and np.array_equal(served, direct)


def test_cli_serve_r7_flags_and_named_models(paths):
    """r7 serving flags ride the one-shot path: NAME=path model aliases,
    --pipeline-depth 1 (serial loop), --sharded off, --device-budget-mb —
    output stays bitwise equal to the pipelined default."""
    model = str(paths / "m.dryad")
    rc = main([
        "train", "--config", str(paths / "cfg.json"),
        "--data", str(paths / "X.npy"), "--label", str(paths / "y.npy"),
        "--model", model, "--backend", "cpu", "--quiet",
    ])
    assert rc == 0
    rc = main(["serve", "--model", f"champion={model}", "--backend", "cpu",
               "--pipeline-depth", "1", "--sharded", "off",
               "--device-budget-mb", "64", "--max-batch-rows", "64",
               "--request", str(paths / "X.npy"),
               "--out", str(paths / "served_serial.npy"), "--quiet"])
    assert rc == 0
    rc = main(["serve", "--model", model, "--backend", "cpu",
               "--max-batch-rows", "64", "--request", str(paths / "X.npy"),
               "--out", str(paths / "served_piped.npy"), "--quiet"])
    assert rc == 0
    a = np.load(paths / "served_serial.npy")
    b = np.load(paths / "served_piped.npy")
    assert np.array_equal(a, b)


def test_cli_serve_arg_parsing(paths, capsys):
    with pytest.raises(SystemExit):                # --model is required
        main(["serve"])
    with pytest.raises(SystemExit):                # bad backend choice
        main(["serve", "--model", "m.dryad", "--backend", "gpu"])
    capsys.readouterr()
    with pytest.raises(SystemExit, match="--request requires --out"):
        main(["serve", "--model", str(paths / "nope.dryad"),
              "--request", str(paths / "X.npy")])


def test_profile_dir_captures_trace(tmp_path):
    import dryad_tpu as dryad

    X, y = higgs_like(1000, seed=47)
    ds = dryad.Dataset(X, y, max_bins=16)
    pdir = str(tmp_path / "trace")
    dryad.train(dict(objective="binary", num_trees=2, num_leaves=7,
                     max_bins=16), ds, backend="tpu", profile_dir=pdir)
    files = [os.path.join(dp, f) for dp, _, fs in os.walk(pdir) for f in fs]
    assert files, "no profiler trace written"
