"""Concurrency contract auditor (dryad_tpu/analysis layer 3, r15).

Static half: the guarded-by / no-blocking-under-lock / lock-order rules
follow the dryadlint mutation discipline — (a) clean on the shipped
tree, (b) FAIL on a seeded violation of their own class, (c) waivers and
goldens behave.  Dynamic half: the schedule harness is seed-
deterministic, its drills pass on the shipped tree, and each drill
DETECTS its recorded race when the shipped fix is mechanically reverted
— the r9 batcher stop/start generation race, the r14 injector
non-atomic check-and-clear, the r14 recovery-blocks-the-monitor bug,
and a torn lock-free registry snapshot.  CLI: concurrency violations
exit 6 (distinct from lint's 2), and the waiver-count ratchet fails CI
when waivers outgrow the committed budget.
"""

from __future__ import annotations

import json
import textwrap

import pytest

from dryad_tpu.analysis.concurrency import LOCK_ORDER_GOLDENS, RULE_NAMES
from dryad_tpu.analysis.lint import SourceTree, run_lint
from dryad_tpu.analysis.schedules import (DRILLS, DeadlockError,
                                          LockOrderError, run_schedule,
                                          run_schedules)

ROOT = __file__.rsplit("/tests/", 1)[0]


def _violations(rule, overrides=None):
    return run_lint(ROOT, rule_names=[rule], overrides=overrides)


def _rule_hits(report, rule):
    return [v for v in report.violations if v.rule == rule]


# ---------------------------------------------------------------------------
# the shipped tree is clean under the concurrency rules


def test_shipped_tree_clean_concurrency_rules():
    report = run_lint(ROOT, rule_names=list(RULE_NAMES))
    assert not report.violations, "\n".join(
        v.format() for v in report.violations)
    # the documented lock-free fast paths are waived, not invisible
    assert any(w.rule == "guarded-by" for _, w in report.waived)
    assert any(w.rule == "no-blocking-under-lock" for _, w in report.waived)


# ---------------------------------------------------------------------------
# guarded-by


def test_guarded_by_seeded_unguarded_access():
    src = SourceTree(ROOT).read("dryad_tpu/serve/batcher.py")
    bad = src + textwrap.dedent("""

        class _Sneaky:
            GUARDED_BY = {"_x": "_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0

            def bump(self):
                self._x += 1
    """)
    rep = _violations("guarded-by", {"dryad_tpu/serve/batcher.py": bad})
    hits = _rule_hits(rep, "guarded-by")
    assert hits and any("self._x" in v.message for v in hits)


def test_guarded_by_missing_declaration_on_lock_owner():
    src = SourceTree(ROOT).read("dryad_tpu/obs/health.py")
    bad = src + textwrap.dedent("""

        class _Bare:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
    """)
    rep = _violations("guarded-by", {"dryad_tpu/obs/health.py": bad})
    assert any("declares no GUARDED_BY" in v.message
               for v in _rule_hits(rep, "guarded-by"))


_COMMENT_FORM = textwrap.dedent("""
    import threading


    class Counted:
        def __init__(self):
            self._n = 0   # guarded-by: _lock
            self._lock = threading.Lock()

        def bump(self):
            BODY
""")


def test_guarded_by_comment_form_detects_and_passes():
    bad = _COMMENT_FORM.replace("BODY", "self._n += 1")
    rep = _violations("guarded-by", {"dryad_tpu/obs/_fixture_gb.py": bad})
    assert _rule_hits(rep, "guarded-by")
    ok = _COMMENT_FORM.replace(
        "BODY", "with self._lock:\n            self._n += 1")
    rep = _violations("guarded-by", {"dryad_tpu/obs/_fixture_gb.py": ok})
    assert not _rule_hits(rep, "guarded-by")


_LOCKED_HELPER = textwrap.dedent("""
    import threading


    class Cache:
        GUARDED_BY = {"_d": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._d = {}

        def _insert_locked(self, k, v):
            self._d[k] = v

        def put(self, k, v):
            BODY
""")


def test_guarded_by_locked_suffix_idiom():
    # the helper body is exempt; the CALL must hold the lock
    bad = _LOCKED_HELPER.replace("BODY", "self._insert_locked(k, v)")
    rep = _violations("guarded-by", {"dryad_tpu/serve/_fixture_gb.py": bad})
    assert any("_locked" in v.message
               for v in _rule_hits(rep, "guarded-by"))
    ok = _LOCKED_HELPER.replace(
        "BODY", "with self._lock:\n            self._insert_locked(k, v)")
    rep = _violations("guarded-by", {"dryad_tpu/serve/_fixture_gb.py": ok})
    assert not _rule_hits(rep, "guarded-by")


def test_guarded_by_declaration_must_name_a_real_lock():
    src = textwrap.dedent("""
        import threading


        class Typo:
            GUARDED_BY = {"_x": "_lokc"}

            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0
    """)
    rep = _violations("guarded-by", {"dryad_tpu/obs/_fixture_gb.py": src})
    assert any("_lokc" in v.message for v in _rule_hits(rep, "guarded-by"))


# ---------------------------------------------------------------------------
# no-blocking-under-lock


def test_no_blocking_seeded_sleep_under_lock():
    src = SourceTree(ROOT).read("dryad_tpu/obs/watchdog.py")
    bad = src + ("\n\ndef _stall(lock):\n"
                 "    with lock:\n"
                 "        time.sleep(1.0)\n")
    rep = _violations("no-blocking-under-lock",
                      {"dryad_tpu/obs/watchdog.py": bad})
    assert _rule_hits(rep, "no-blocking-under-lock")


def test_no_blocking_thread_join_flagged_str_join_clean():
    tmpl = ("import threading\n"
            "def f(lock, t, parts):\n"
            "    with lock:\n"
            "        {stmt}\n")
    rep = _violations("no-blocking-under-lock", {
        "dryad_tpu/fleet/_fixture_nb.py": tmpl.format(
            stmt="t.join(timeout=5.0)")})
    assert _rule_hits(rep, "no-blocking-under-lock")
    rep = _violations("no-blocking-under-lock", {
        "dryad_tpu/fleet/_fixture_nb.py": tmpl.format(
            stmt="out = ','.join(parts)")})
    assert not _rule_hits(rep, "no-blocking-under-lock")


def test_no_blocking_queue_get_flagged_dict_get_clean():
    tmpl = ("def f(lock, q, d, k):\n"
            "    with lock:\n"
            "        {stmt}\n")
    rep = _violations("no-blocking-under-lock", {
        "dryad_tpu/serve/_fixture_nb.py": tmpl.format(stmt="x = q.get()")})
    assert _rule_hits(rep, "no-blocking-under-lock")
    rep = _violations("no-blocking-under-lock", {
        "dryad_tpu/serve/_fixture_nb.py": tmpl.format(stmt="x = d.get(k)")})
    assert not _rule_hits(rep, "no-blocking-under-lock")


def test_no_blocking_user_callback_under_lock():
    src = textwrap.dedent("""
        import threading


        class Notifier:
            GUARDED_BY = {"_subs": "_lock"}

            def __init__(self, on_change):
                self._lock = threading.Lock()
                self._subs = []
                self.on_change = on_change

            def add(self, s):
                with self._lock:
                    self._subs.append(s)
                    self.on_change(s)
    """)
    rep = _violations("no-blocking-under-lock",
                      {"dryad_tpu/obs/_fixture_cb.py": src})
    assert any("constructor-injected user callback" in v.message
               for v in _rule_hits(rep, "no-blocking-under-lock"))


def test_no_blocking_injector_action_moved_under_lock_is_caught():
    # the r14 fix keeps fault ACTIONS outside the injector lock; pulling
    # the stall sleep back inside must trip the rule
    src = SourceTree(ROOT).read("dryad_tpu/resilience/faults.py")
    bad = src + ("\n\ndef _regressed(self, pt):\n"
                 "    with self._lock:\n"
                 "        import time\n"
                 "        time.sleep(pt.stall_s)\n")
    rep = _violations("no-blocking-under-lock",
                      {"dryad_tpu/resilience/faults.py": bad})
    assert _rule_hits(rep, "no-blocking-under-lock")


# ---------------------------------------------------------------------------
# lock-order


def test_lock_order_inversion_seeded_in_supervisor():
    src = SourceTree(ROOT).read("dryad_tpu/fleet/supervisor.py")
    anchor = "    # ---- plumbing"
    assert anchor in src
    method = ("    def _sneaky(self):\n"
              "        with self._journal_lock:\n"
              "            with self._swap_lock:\n"
              "                pass\n\n")
    bad = src.replace(anchor, method + anchor, 1)
    rep = _violations("lock-order", {"dryad_tpu/fleet/supervisor.py": bad})
    assert any("INVERTS" in v.message for v in _rule_hits(rep, "lock-order"))


_TWO_LOCKS = textwrap.dedent("""
    import threading


    class Pair:
        GUARDED_BY = {"_a": "_la", "_b": "_lb"}

        def __init__(self):
            self._la = threading.Lock()
            self._lb = threading.Lock()
            self._a = 0
            self._b = 0

        def both(self):
            with self._la:
                with self._lb:
                    self._a = self._b
""")


def test_lock_order_new_edge_needs_goldens_commit():
    rep = _violations("lock-order",
                      {"dryad_tpu/obs/_fixture_lo.py": _TWO_LOCKS})
    hits = _rule_hits(rep, "lock-order")
    assert hits and any("not in the committed partial order" in v.message
                        for v in hits)
    committed = json.dumps(
        {"edges": [["FleetSupervisor._swap_lock",
                    "FleetSupervisor._journal_lock"],
                   ["Pair._la", "Pair._lb"]]})
    rep = _violations("lock-order", {
        "dryad_tpu/obs/_fixture_lo.py": _TWO_LOCKS,
        LOCK_ORDER_GOLDENS: committed,
    })
    assert not _rule_hits(rep, "lock-order")


def test_lock_order_transitive_through_self_call():
    src = textwrap.dedent("""
        import threading


        class Chain:
            GUARDED_BY = {"_x": "_la"}

            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()
                self._x = 0

            def _inner(self):
                with self._lb:
                    pass

            def outer(self):
                with self._la:
                    self._inner()
    """)
    rep = _violations("lock-order", {"dryad_tpu/serve/_fixture_lo.py": src})
    hits = _rule_hits(rep, "lock-order")
    assert hits and any("Chain._la" in v.message and "Chain._lb" in v.message
                        for v in hits)


def test_lock_order_self_deadlock_direct_and_via_call():
    direct = textwrap.dedent("""
        import threading


        class Re:
            GUARDED_BY = {"_x": "_l"}

            def __init__(self):
                self._l = threading.Lock()
                self._x = 0

            def f(self):
                with self._l:
                    with self._l:
                        pass
    """)
    rep = _violations("lock-order", {"dryad_tpu/obs/_fixture_sd.py": direct})
    assert any("re-acquires" in v.message.lower()
               for v in _rule_hits(rep, "lock-order"))
    via_call = textwrap.dedent("""
        import threading


        class Re:
            GUARDED_BY = {"_x": "_l"}

            def __init__(self):
                self._l = threading.Lock()
                self._x = 0

            def g(self):
                with self._l:
                    pass

            def f(self):
                with self._l:
                    self.g()
    """)
    rep = _violations("lock-order",
                      {"dryad_tpu/obs/_fixture_sd.py": via_call})
    assert any("self-deadlock" in v.message
               for v in _rule_hits(rep, "lock-order"))


def test_lock_order_committed_cycle_rejected():
    cyclic = json.dumps({"edges": [["A._l1", "B._l2"], ["B._l2", "A._l1"]]})
    rep = _violations("lock-order", {LOCK_ORDER_GOLDENS: cyclic})
    assert any("CYCLIC" in v.message for v in _rule_hits(rep, "lock-order"))


# ---------------------------------------------------------------------------
# the schedule harness: shipped drills pass, same seed == same schedule


def test_drills_shipped_tree_pass_first_seeds():
    for name, (drill, _n, p, tf) in sorted(DRILLS.items()):
        run_schedules(drill, range(3), preempt_p=p, trace_files=tf)


def test_schedule_harness_is_seed_deterministic():
    for name in ("batcher-stop-start", "registry-snapshot"):
        drill, _n, p, tf = DRILLS[name]
        a = run_schedule(drill, 7, preempt_p=p, trace_files=tf)
        b = run_schedule(drill, 7, preempt_p=p, trace_files=tf)
        assert a.steps == b.steps, name
        assert sorted(a.lock_edges) == sorted(b.lock_edges), name
    # different seeds explore different interleavings (not a fixed path)
    drill, _n, p, tf = DRILLS["batcher-stop-start"]
    steps = {run_schedule(drill, s, preempt_p=p, trace_files=tf).steps
             for s in range(6)}
    assert len(steps) > 1, "every seed produced the identical schedule"


def test_supervisor_drill_records_runtime_lock_edges():
    drill, _n, p, tf = DRILLS["rolling-push-vs-death"]
    s = run_schedule(drill, 0, preempt_p=p, trace_files=tf)
    edges = sorted(s.lock_edges)
    assert any("supervisor.py" in a and "supervisor.py" in b
               for a, b in edges), edges


def test_abba_deadlock_gets_a_verdict_with_stacks():
    import threading

    def drill_abba(sched):
        la, lb = threading.Lock(), threading.Lock()

        def t1():
            with la:
                sched.pause()
                with lb:
                    pass

        def t2():
            with lb:
                sched.pause()
                with la:
                    pass

        sched.spawn(t1, "t1")
        sched.spawn(t2, "t2")
        return None

    hits = 0
    msgs = []
    for seed in range(12):
        try:
            run_schedule(drill_abba, seed)
        except (DeadlockError, LockOrderError) as e:
            hits += 1
            msgs.append(str(e))
    assert hits > 0, "no schedule produced the ABBA deadlock verdict"
    # the verdict carries the two halves: lock names and stacks
    assert any("Lock@" in m for m in msgs)


# ---------------------------------------------------------------------------
# mutation checks: each drill detects its recorded race when the shipped
# fix is mechanically reverted


def _first_failing_seed(drill_name, max_seeds, extra_trace=()):
    """First seed whose schedule detects the seeded race (invariant
    assertion, deadlock verdict, or budget blowup), else None."""
    drill, _n, p, tf = DRILLS[drill_name]
    for seed in range(max_seeds):
        try:
            run_schedule(drill, seed, preempt_p=p,
                         trace_files=tuple(tf) + tuple(extra_trace))
        except (AssertionError, RuntimeError):
            return seed
    return None


def test_harness_reproduces_r9_batcher_stop_race(monkeypatch):
    from dryad_tpu.serve.batcher import MicroBatcher

    monkeypatch.setattr(MicroBatcher, "_stop_live",
                        lambda self, token: True)
    seed = _first_failing_seed("batcher-stop-start", 200)
    assert seed is not None and seed < 200, \
        "the reverted r9 generation race was not reproduced in <200 schedules"


def test_harness_detects_torn_lock_free_snapshot(monkeypatch):
    from dryad_tpu.obs import registry as regmod

    def lockfree_value(self):
        fam = self._fam
        if fam.kind == regmod.HISTOGRAM:
            state = fam.values.get(self._key)
            if state is None:
                return ([0] * (len(fam.buckets) + 1), 0.0, 0)
            return (list(state[0]), state[1], state[2])
        return fam.values.get(self._key, 0.0)

    monkeypatch.setattr(regmod._Series, "value", lockfree_value)
    seed = _first_failing_seed("registry-snapshot", 60)
    assert seed is not None, \
        "a lock-free snapshot reader never produced a torn histogram"


def test_harness_detects_torn_drift_export(monkeypatch):
    """r18: a drift /obs export that reads the rotating window WITHOUT
    the monitor lock tears against concurrent observes/rotation — the
    drift-window-tear drill's counts-vs-rows invariant must catch it."""
    import contextlib

    from dryad_tpu.obs import drift as dmod

    real = dmod.DriftMonitor.export_state
    null = contextlib.nullcontext()

    def lockfree_export(self):
        lock, self._lock = self._lock, null
        try:
            return real(self)
        finally:
            self._lock = lock

    monkeypatch.setattr(dmod.DriftMonitor, "export_state", lockfree_export)
    seed = _first_failing_seed("drift-window-tear", 60)
    assert seed is not None, \
        "a lock-free drift export never produced a torn window block"


def test_harness_detects_nonatomic_injector_fire(monkeypatch):
    from dryad_tpu.resilience import faults as fmod

    def racy_call(self, site, iteration):
        # the pre-r14 shape: check-then-clear with no lock
        for i, pt in enumerate(self.points):
            if (self._armed[i] and site == pt.site
                    and iteration >= pt.iteration):
                if not pt.sticky:
                    self._armed[i] = False
                self.fired.append({"point": i, "site": site,
                                   "iteration": int(iteration),
                                   "kind": pt.kind})
                raise fmod.InjectedReject("injected")

    monkeypatch.setattr(fmod.FaultInjector, "__call__", racy_call)
    seed = _first_failing_seed("injector-concurrent-fire", 100,
                               extra_trace=("test_analysis_concurrency.py",))
    assert seed is not None, \
        "the non-atomic check-and-clear never double-fired"


def test_harness_detects_unlocked_scheduler_admit(monkeypatch):
    """r19: the retrain debounce's checks and its in-flight mark must be
    ONE critical section — the mechanically reverted unlocked version
    lets two concurrent breach deliveries both pass the checks before
    either marks, double-launching the retrain; the
    scheduler-breach-vs-push drill's exactly-once invariant catches it."""
    from dryad_tpu.continual import scheduler as cmod

    def racy_admit(self, model):
        # the unlocked-streak shape: check, then mark, no critical section
        now = cmod.time.monotonic()
        if model in self._inflight:
            return False, "in_flight", 0, 0
        if len(self._inflight) >= self.max_concurrent:
            return False, "budget", 0, 0
        if now < self._cooldown_until.get(model, 0.0):
            return False, "cooldown", 0, 0
        if self._fails.get(model, 0) > self.policy.retry_budget:
            return False, "retry_budget_exhausted", 0, 0
        self._inflight.add(model)
        gen = self._generation.get(model, 0) + 1
        job = self._jobs
        self._jobs += 1
        return True, "", gen, job

    monkeypatch.setattr(cmod.RetrainScheduler, "_admit", racy_admit)
    seed = _first_failing_seed("scheduler-breach-vs-push", 100,
                               extra_trace=("test_analysis_concurrency.py",))
    assert seed is not None, \
        "the unlocked debounce never double-launched a retrain"


def test_harness_detects_unlocked_capacity_admit(monkeypatch):
    """r22: the capacity decision's streak/bound/cooldown checks and its
    in-flight mark must be ONE critical section — the mechanically
    reverted unlocked version lets two concurrent pokes both pass the
    checks before either marks, double-spawning a replica past the
    declared bounds; the capacity-vs-breach-vs-push drill's exactly-one
    invariant catches it."""
    from dryad_tpu.fleet import autoscale as amod

    def racy_admit(self, pressure, headroom, census):
        # the unlocked shape: check, then mark, no critical section
        now = amod.time.monotonic()
        if pressure:
            self._down_streak = 0
            self._up_streak += 1
            direction, streak, sustain_n = ("up", self._up_streak,
                                            self.breach_after)
            bound_hit = census >= self.max_replicas
        elif headroom:
            self._up_streak = 0
            self._down_streak += 1
            direction, streak, sustain_n = ("down", self._down_streak,
                                            self.idle_after)
            bound_hit = census <= self.min_replicas
        else:
            self._up_streak = 0
            self._down_streak = 0
            self._last_skip = {"up": None, "down": None}
            return None, None, None, False
        if self._action is not None:
            reason = amod.SKIP_IN_FLIGHT
        elif bound_hit:
            reason = amod.SKIP_AT_BOUND
        elif streak < sustain_n:
            reason = amod.SKIP_SUSTAIN
        elif now < self._cooldown_until[direction]:
            reason = amod.SKIP_COOLDOWN
        else:
            self._action = direction
            if direction == "up":
                self._up_streak = 0
            else:
                self._down_streak = 0
            self._last_skip[direction] = None
            return ("scale_up" if direction == "up" else "scale_down",
                    direction, None, False)
        journal_skip = reason != self._last_skip[direction]
        self._last_skip[direction] = reason
        return None, direction, reason, journal_skip

    monkeypatch.setattr(amod.CapacityController, "_admit", racy_admit)
    seed = _first_failing_seed("capacity-vs-breach-vs-push", 100,
                               extra_trace=("test_analysis_concurrency.py",))
    assert seed is not None, \
        "the unlocked capacity debounce never double-launched a scale-up"


def test_harness_detects_wedged_prefetch_producer(monkeypatch):
    """r20: ChunkPrefetcher's producer must put through the cancellable
    timeout loop — mechanically reverting it to a plain blocking put lets
    a mid-stream close() strand the producer (the post-drain sentinel put
    wedges forever on the refilled queue); the stream-prefetch drill's
    thread-reaped assertion catches it."""
    from dryad_tpu.data import stream_dataset as smod

    def blocking_put(self, item):
        self._q.put(item)   # the pre-fix shape: no cancellation window
        return True

    monkeypatch.setattr(smod.ChunkPrefetcher, "_put_cancellable",
                        blocking_put)
    seed = _first_failing_seed("stream-prefetch", 60)
    assert seed is not None, \
        "a non-cancellable producer put never wedged past close()"


def test_harness_detects_recovery_blocking_the_monitor(monkeypatch):
    from dryad_tpu.fleet import supervisor as smod

    def sync_recover(self, slot, reason, exit_code=None):
        slot.recovering = True
        try:
            self._recover(slot, reason, exit_code=exit_code)
        finally:
            slot.recovering = False

    monkeypatch.setattr(smod.FleetSupervisor, "_recover_async", sync_recover)
    drill, _n, p, tf = DRILLS["supervisor-recovery"]
    with pytest.raises(Exception) as ei:
        run_schedules(drill, range(3), preempt_p=p, trace_files=tf)
    assert "slot 1 respawned" in str(ei.value) or "deadlock" in \
        str(ei.value).lower()


# ---------------------------------------------------------------------------
# CLI: exit code 6 + the waiver ratchet


def test_cli_concurrency_lint_violation_exits_6(tmp_path):
    pkg = tmp_path / "dryad_tpu" / "obs"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(textwrap.dedent("""
        import threading


        class Sneaky:
            GUARDED_BY = {"_x": "_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0

            def bump(self):
                self._x += 1
    """))
    goldens = tmp_path / "dryad_tpu" / "analysis" / "goldens"
    goldens.mkdir(parents=True)
    (goldens / "lock_order.json").write_text('{"edges": []}')
    budget = goldens / "waiver_budget.json"
    budget.write_text('{"waivers": 0}')
    from dryad_tpu.analysis.__main__ import main

    assert main(["--lint", "-q", "--root", str(tmp_path),
                 "--waiver-budget", str(budget)]) == 6


def test_cli_drill_failure_exits_6(monkeypatch):
    from dryad_tpu.analysis.__main__ import main
    from dryad_tpu.serve.batcher import MicroBatcher

    monkeypatch.setattr(MicroBatcher, "_stop_live",
                        lambda self, token: True)
    rc = main(["--concurrency", "-q", "--drill", "batcher-stop-start",
               "--schedules", "2"])
    assert rc == 6


def test_cli_shipped_concurrency_layer_passes():
    from dryad_tpu.analysis.__main__ import main

    assert main(["--concurrency", "-q", "--schedules", "2"]) == 0


def test_cli_waiver_ratchet_fails_over_budget(tmp_path):
    budget = tmp_path / "waiver_budget.json"
    budget.write_text('{"waivers": 0}')
    from dryad_tpu.analysis.__main__ import main

    # the shipped tree carries its documented waivers; budget 0 must fail
    assert main(["--lint", "-q", "--waiver-budget", str(budget)]) == 2


def test_waiver_budget_matches_shipped_tree_exactly():
    report = run_lint(ROOT)
    with open(f"{ROOT}/dryad_tpu/analysis/goldens/waiver_budget.json") as f:
        budget = json.load(f)["waivers"]
    assert len(report.waived) <= budget
    assert budget <= len(report.waived) + 2, (
        f"budget {budget} has slack over the real count "
        f"{len(report.waived)} — ratchet it down")


# ---------------------------------------------------------------------------
# docs cannot drift: every registered rule is in both catalogs


def test_rule_catalog_in_readme_and_claude_md():
    from dryad_tpu.analysis.lint import registry

    names = set(registry())
    for doc in ("README.md", "CLAUDE.md"):
        text = SourceTree(ROOT).read(doc)
        missing = {n for n in names if n not in text}
        assert not missing, f"{doc} is missing rule(s): {sorted(missing)}"
    readme = SourceTree(ROOT).read("README.md")
    assert "GUARDED_BY" in readme and "exit" in readme.lower()
