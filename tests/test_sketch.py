import numpy as np
import pytest

from dryad_tpu.data.sketch import MISSING_BIN, BinMapper, sketch_features


def test_distinct_small_gets_one_bin_per_value():
    col = np.array([3.0, 1.0, 2.0, 1.0, 3.0, 2.0], np.float32)
    m = sketch_features(col[:, None], max_bins=256)
    b = m.transform(col[:, None])[:, 0]
    # distinct values map to distinct bins, order-preserving, starting at 1
    assert b.tolist() == [3, 1, 2, 1, 3, 2]
    assert m.features[0].n_bins == 4  # missing bin + one bin per distinct value

def test_monotone_binning():
    rng = np.random.default_rng(0)
    col = rng.normal(size=10_000).astype(np.float32)
    m = sketch_features(col[:, None], max_bins=64)
    b = m.transform(col[:, None])[:, 0]
    order = np.argsort(col)
    assert (np.diff(b[order].astype(int)) >= 0).all()
    assert b.min() >= 1
    assert int(b.max()) <= 63


def test_heavy_ties_do_not_straddle():
    col = np.concatenate([np.zeros(5000), np.ones(100), np.full(100, 2.0)]).astype(np.float32)
    m = sketch_features(col[:, None], max_bins=8)
    b = m.transform(col[:, None])[:, 0]
    assert len(np.unique(b[col == 0.0])) == 1
    assert len(np.unique(b[col == 1.0])) == 1


def test_nan_goes_to_missing_bin():
    col = np.array([1.0, np.nan, 2.0, np.nan], np.float32)
    m = sketch_features(col[:, None], max_bins=16)
    b = m.transform(col[:, None])[:, 0]
    assert b[1] == MISSING_BIN and b[3] == MISSING_BIN
    assert b[0] != MISSING_BIN and b[2] != MISSING_BIN


def test_constant_column():
    col = np.full(100, 3.5, np.float32)
    m = sketch_features(col[:, None], max_bins=16)
    b = m.transform(col[:, None])[:, 0]
    assert len(np.unique(b)) == 1


def test_infinities():
    col = np.array([-np.inf, -1.0, 0.0, 1.0, np.inf], np.float32)
    m = sketch_features(col[:, None], max_bins=16)
    b = m.transform(col[:, None])[:, 0].astype(int)
    assert (np.diff(b) >= 0).all()
    assert b[0] >= 1  # -inf is a value, not missing


def test_categorical_ranking_and_overflow():
    col = np.array([5, 5, 5, 7, 7, 9] + [i + 100 for i in range(300)], np.float32)
    m = sketch_features(col[:, None], max_bins=8, categorical_features=[0])
    fb = m.features[0]
    assert fb.is_categorical
    b = m.transform(col[:, None])[:, 0]
    # most frequent category (5) gets bin 1
    assert (b[:3] == 1).all()
    assert (b[3:5] == 2).all()
    # rare categories overflow into the last bin
    assert (b[-100:] == fb.overflow_bin).all()
    # unseen value at predict time also overflows
    assert m.transform(np.array([[12345.0]], np.float32))[0, 0] == fb.overflow_bin


def test_roundtrip_serialization():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(500, 4)).astype(np.float32)
    X[::7, 2] = np.nan
    m = sketch_features(X, max_bins=32, categorical_features=[3])
    m2 = BinMapper.from_bytes(m.to_bytes())
    np.testing.assert_array_equal(m.transform(X), m2.transform(X))


def test_determinism():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(2000, 6)).astype(np.float32)
    a = sketch_features(X, max_bins=64)
    b = sketch_features(X, max_bins=64)
    np.testing.assert_array_equal(a.transform(X), b.transform(X))
    for fa, fb in zip(a.features, b.features):
        np.testing.assert_array_equal(fa.edges, fb.edges)


def test_quantile_balance():
    rng = np.random.default_rng(3)
    col = rng.exponential(size=100_000).astype(np.float32)
    m = sketch_features(col[:, None], max_bins=64)
    b = m.transform(col[:, None])[:, 0]
    counts = np.bincount(b)[1:]  # skip missing bin
    counts = counts[counts > 0]
    # equal-frequency: no bin should be wildly off 1/62 of the mass
    assert counts.max() < 3 * counts.mean()
