"""Unit tests for the device kernels against their CPU canonical semantics."""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from dryad_tpu.cpu.histogram import build_hist as build_hist_cpu
from dryad_tpu.cpu.histogram import find_best_split as find_best_split_cpu
from dryad_tpu.engine.histogram import build_hist_jit
from dryad_tpu.engine.split import find_best_split as find_best_split_dev

pytestmark = pytest.mark.engine


def _rand_case(n=5000, F=7, B=33, seed=0):
    rng = np.random.Generator(np.random.Philox(seed))
    Xb = rng.integers(0, B, size=(n, F)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
    return Xb, g, h


def test_histogram_matches_cpu():
    Xb, g, h = _rand_case()
    rows = np.arange(Xb.shape[0], dtype=np.int64)
    ref = build_hist_cpu(Xb, g, h, rows, 33)
    dev = np.asarray(build_hist_jit(jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
                                    jnp.ones(Xb.shape[0], bool), 33))
    np.testing.assert_allclose(dev, ref, rtol=2e-5, atol=2e-4)
    np.testing.assert_array_equal(dev[2], ref[2])  # counts exact in fp32


def test_histogram_masked_subset():
    Xb, g, h = _rand_case(seed=1)
    mask = np.zeros(Xb.shape[0], bool)
    mask[::3] = True
    rows = np.nonzero(mask)[0].astype(np.int64)
    ref = build_hist_cpu(Xb, g, h, rows, 33)
    dev = np.asarray(build_hist_jit(jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
                                    jnp.asarray(mask), 33))
    np.testing.assert_allclose(dev, ref, rtol=2e-5, atol=2e-4)


def test_histogram_chunking_invariant():
    """Chunk size must not change the result (padding rows are masked out)."""
    Xb, g, h = _rand_case(n=1000, seed=2)
    full = np.asarray(build_hist_jit(jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
                                     jnp.ones(1000, bool), 33, rows_per_chunk=1000))
    small = np.asarray(build_hist_jit(jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
                                      jnp.ones(1000, bool), 33, rows_per_chunk=96))
    np.testing.assert_allclose(small, full, rtol=1e-6, atol=1e-4)


def test_split_finder_matches_cpu():
    Xb, g, h = _rand_case(seed=3)
    rows = np.arange(Xb.shape[0], dtype=np.int64)
    hist = build_hist_cpu(Xb, g, h, rows, 33)
    G, H, C = hist[0, 0].sum(), hist[1, 0].sum(), float(rows.size)
    kw = dict(lambda_l2=1.0, min_child_weight=1e-3, min_data_in_leaf=20,
              min_split_gain=0.0)
    ref = find_best_split_cpu(hist, G, H, C, **kw)
    dev = find_best_split_dev(
        jnp.asarray(hist, jnp.float32), jnp.float32(G), jnp.float32(H), jnp.float32(C),
        feat_mask=jnp.ones(7, bool), is_cat_feat=jnp.zeros(7, bool),
        allow=jnp.bool_(True), has_cat=False, **kw,
    )
    assert int(dev.feature) == ref.feature
    assert int(dev.threshold) == ref.threshold
    np.testing.assert_allclose(float(dev.gain), ref.gain, rtol=1e-4)
    np.testing.assert_allclose(float(dev.c_left), ref.c_left)


def test_split_finder_categorical_matches_cpu():
    rng = np.random.Generator(np.random.Philox(4))
    n, B = 4000, 17
    Xb = rng.integers(1, B, size=(n, 2)).astype(np.uint8)
    g = (Xb[:, 0] % 3 - 1 + rng.normal(size=n) * 0.1).astype(np.float32)
    h = np.ones(n, np.float32)
    rows = np.arange(n, dtype=np.int64)
    hist = build_hist_cpu(Xb, g, h, rows, B)
    G, H, C = hist[0, 0].sum(), hist[1, 0].sum(), float(n)
    is_cat = np.array([True, False])
    kw = dict(lambda_l2=1.0, min_child_weight=1e-3, min_data_in_leaf=20,
              min_split_gain=0.0)
    ref = find_best_split_cpu(hist, G, H, C, is_categorical=is_cat, **kw)
    dev = find_best_split_dev(
        jnp.asarray(hist, jnp.float32), jnp.float32(G), jnp.float32(H), jnp.float32(C),
        feat_mask=jnp.ones(2, bool), is_cat_feat=jnp.asarray(is_cat),
        allow=jnp.bool_(True), has_cat=True, **kw,
    )
    assert int(dev.feature) == ref.feature
    assert ref.is_cat
    members_dev = np.nonzero(np.asarray(dev.cat_mask))[0]
    np.testing.assert_array_equal(members_dev, ref.cat_members)


def test_split_finder_respects_feature_mask():
    Xb, g, h = _rand_case(seed=5)
    rows = np.arange(Xb.shape[0], dtype=np.int64)
    hist = build_hist_cpu(Xb, g, h, rows, 33)
    G, H, C = hist[0, 0].sum(), hist[1, 0].sum(), float(rows.size)
    kw = dict(lambda_l2=1.0, min_child_weight=1e-3, min_data_in_leaf=20,
              min_split_gain=0.0)
    full = find_best_split_dev(
        jnp.asarray(hist, jnp.float32), jnp.float32(G), jnp.float32(H), jnp.float32(C),
        feat_mask=jnp.ones(7, bool), is_cat_feat=jnp.zeros(7, bool),
        allow=jnp.bool_(True), has_cat=False, **kw)
    banned = jnp.ones(7, bool).at[int(full.feature)].set(False)
    masked = find_best_split_dev(
        jnp.asarray(hist, jnp.float32), jnp.float32(G), jnp.float32(H), jnp.float32(C),
        feat_mask=banned, is_cat_feat=jnp.zeros(7, bool),
        allow=jnp.bool_(True), has_cat=False, **kw)
    assert int(masked.feature) != int(full.feature)


def test_lambdarank_device_matches_host():
    from dryad_tpu.config import Params
    from dryad_tpu.engine.lambdarank import grad_hess_ranking
    from dryad_tpu.objectives import get_objective

    from dryad_tpu.datasets import mslr_like

    X, y, group = mslr_like(num_queries=40, docs_per_query=(3, 25), num_features=8)
    qoff = np.concatenate([[0], np.cumsum(group)]).astype(np.int64)
    obj = get_objective(Params(objective="lambdarank"))
    rng = np.random.Generator(np.random.Philox(6))
    score = rng.normal(size=y.shape[0]).astype(np.float32)
    g_host, h_host = grad_hess_ranking(obj, score, y, None, qoff, use_device=False)
    g_dev, h_dev = grad_hess_ranking(obj, score, y, None, qoff, use_device=True)
    # device is fp32, host f64: observed max |Δ| ~5e-5 on unit-scale λ sums
    np.testing.assert_allclose(np.asarray(g_dev), np.asarray(g_host), rtol=1e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(h_dev), np.asarray(h_host), rtol=1e-3, atol=2e-4)


def test_segmented_histogram_matches_multi_and_cpu():
    from dryad_tpu.engine.histogram import build_hist_multi, build_hist_segmented
    import jax

    rng = np.random.Generator(np.random.Philox(9))
    n, F, B, P = 6000, 5, 33, 7
    Xb = rng.integers(0, B, size=(n, F)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
    sel = rng.integers(0, P + 1, size=n).astype(np.int32)  # includes drops
    multi = np.asarray(jax.jit(build_hist_multi, static_argnames=("num_cols", "total_bins"))(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h), jnp.asarray(sel), P, B))
    seg = np.asarray(jax.jit(build_hist_segmented, static_argnames=("num_cols", "total_bins"))(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h), jnp.asarray(sel), P, B))
    np.testing.assert_array_equal(seg[:, 2], multi[:, 2])  # counts exact
    np.testing.assert_allclose(seg, multi, rtol=2e-5, atol=2e-4)
    # vs CPU oracle per column
    for col in range(P):
        rows = np.nonzero(sel == col)[0].astype(np.int64)
        ref = build_hist_cpu(Xb, g, h, rows, B)
        np.testing.assert_allclose(seg[col], ref, rtol=2e-5, atol=2e-4)


def test_build_hist_classes_matches_per_class():
    """Shared-plan K-class root pass vs K separate build_hist calls (the
    grower consumes either interchangeably).

    Counts must be BITWISE (sums of 1.0 — grouping-independent); grad/
    hess sums compare to last-ulp tolerance here because the (2K+1)-row
    and 3-row HIGHEST dots are fusion-sensitive on some XLA CPU releases
    (this container's 0.4.x lowers them differently; the newer TPU-env
    jax folds them identically).  The BITWISE pin on real hardware —
    where roots_sharded's same-program rule rides on it — lives in
    scripts/smoke_tpu.py::smoke_shared_vs_per_class."""
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist, build_hist_classes

    rng = np.random.default_rng(53)
    N, F, B, K = 5000, 6, 32, 7
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, size=(N, K)).astype(np.float32))
    mask = jnp.asarray(rng.random(N) < 0.8)
    # rows_per_chunk=1024 forces the multi-chunk scan + row padding — the
    # parts of the shared implementation where accumulation order could
    # actually drift from the per-class path
    shared = np.asarray(build_hist_classes(Xb, g, h, mask, B,
                                           rows_per_chunk=1024))
    assert shared.shape == (K, 3, F, B)
    for k in range(K):
        single = np.asarray(build_hist(Xb, g[:, k], h[:, k], mask, B,
                                       rows_per_chunk=1024))
        np.testing.assert_array_equal(shared[k][2], single[2])
        np.testing.assert_allclose(shared[k], single, rtol=3e-5, atol=3e-5)
    # and the defaults (single chunk) agree with the chunked result's shape
    np.testing.assert_array_equal(
        np.asarray(build_hist_classes(Xb, g, h, mask, B))[0][2],
        np.asarray(build_hist(Xb, g[:, 0], h[:, 0], mask, B))[2])
    np.testing.assert_allclose(
        np.asarray(build_hist_classes(Xb, g, h, mask, B))[0],
        np.asarray(build_hist(Xb, g[:, 0], h[:, 0], mask, B)),
        rtol=3e-5, atol=3e-5)
