"""Sharded predict parity (ISSUE r7): shard_map inference over the 8
fake-CPU-device mesh must be BITWISE equal to the single-device predict —
rows are padded with zero bins to divide the mesh, trees are replicated,
and every predict stage is per-row, so sharding is a shape game that
cannot change a bit (the same structural argument as bucket padding).

Also pins the serving-layer integration: the (version, bucket, n_shards)
compiled-entry family, deterministic threshold routing (small interactive
buckets stay on the single-device fast path), and recompile-free warm
traffic across BOTH shard arms."""

from __future__ import annotations

import numpy as np
import pytest

import jax

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.serve import PredictServer


@pytest.fixture(scope="module")
def mesh():
    from dryad_tpu.engine.distributed import make_mesh

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(jax.devices()[:8])


@pytest.fixture(scope="module")
def model():
    X, y = higgs_like(600, seed=7)
    ds = dryad.Dataset(X, y, max_bins=32)
    booster = dryad.train(dict(objective="binary", num_trees=8, num_leaves=7,
                               max_bins=32), ds, backend="cpu")
    return booster, X


@pytest.fixture(scope="module")
def model_multiclass():
    rng = np.random.default_rng(3)
    X = rng.standard_normal((500, 8)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32) + (X[:, 2] > 0.5)
    ds = dryad.Dataset(X, y, max_bins=32)
    booster = dryad.train(dict(objective="multiclass", num_class=3,
                               num_trees=4, num_leaves=7, max_bins=32),
                          ds, backend="cpu")
    return booster, X


def test_engine_sharded_bitwise(mesh, model):
    """predict_binned_sharded == single-device == CPU, bitwise, including
    batches that do NOT divide the 8-way mesh (padding must not leak)."""
    from dryad_tpu.engine.predict import predict_binned_sharded

    booster, X = model
    Xb = booster.mapper.transform(X)
    for n in (1, 7, 8, 9, 13, 600):       # 1-row, sub-mesh, non-divisible
        ref = booster.predict_binned(Xb[:n], raw_score=True)
        single = booster.predict_binned(Xb[:n], raw_score=True, backend="tpu")
        sharded = np.asarray(predict_binned_sharded(booster, Xb[:n],
                                                    mesh=mesh))[:, 0]
        assert np.array_equal(sharded, ref), n
        assert np.array_equal(sharded, single), n


def test_booster_predict_sharded_passthrough(mesh, model_multiclass):
    """Booster.predict(..., backend='tpu', sharded=True) — multiclass K=3,
    non-divisible rows, link transform included."""
    booster, X = model_multiclass
    for n in (1, 9, 13, 500):
        ref = booster.predict(X[:n])
        got = booster.predict(X[:n], backend="tpu", sharded=True)
        assert got.shape == (n, 3)
        assert np.array_equal(got, ref), n


@pytest.mark.parametrize("batch_mode", ["forced", "auto"])
def test_server_sharded_parity(model, batch_mode):
    """Serving through the sharded compiled-entry family is bitwise equal
    to the direct predict at 1-row, bucket-boundary, and chunked sizes;
    'auto' keeps small buckets on the single-device arm (threshold gate),
    'forced' puts every bucket on the mesh."""
    booster, X = model
    kw = (dict(sharded=True) if batch_mode == "forced"
          else dict(sharded="auto", sharded_threshold=32))
    server = PredictServer(backend="tpu", max_batch_rows=64, max_wait_ms=0.5,
                           min_bucket=8, **kw)
    server.registry.add(booster)
    with server:
        for n in (1, 7, 8, 9, 16, 17, 33, 64, 100):
            for raw in (False, True):
                direct = booster.predict(X[:n], raw_score=raw)
                served = server.predict(X[:n], raw_score=raw)
                assert served.dtype == direct.dtype
                assert served.shape == direct.shape
                assert np.array_equal(served, direct), (batch_mode, n, raw)
    snap = server.stats()
    assert snap["mesh_shards"] == 8
    shard_arms = {k[2] for k in server.cache._warm}
    if batch_mode == "forced":
        assert shard_arms == {8}              # every bucket on the mesh
    else:
        # threshold 32 row-outputs: buckets 8/16 single-device, 32/64 sharded
        assert shard_arms == {1, 8}
        assert (1, 8, 1) in server.cache._warm
        assert (1, 64, 8) in server.cache._warm


def test_server_sharded_multiclass_binned(model_multiclass):
    booster, X = model_multiclass
    Xb = booster.mapper.transform(X)
    server = PredictServer(backend="tpu", sharded=True, max_batch_rows=32,
                           max_wait_ms=0.2)
    server.registry.add(booster)
    with server:
        for n in (1, 9, 33):
            direct = booster.predict_binned(Xb[:n])
            served = server.predict(Xb[:n], binned=True)
            assert direct.shape == (n, 3) and np.array_equal(served, direct)


def test_sharded_threshold_keeps_interactive_on_fast_path(model):
    """Default 'auto' threshold (32k row-outputs) routes small-bucket
    interactive traffic to the single-device arm only."""
    booster, X = model
    server = PredictServer(backend="tpu", max_batch_rows=64, max_wait_ms=0.2)
    server.registry.add(booster)
    with server:
        server.predict(X[:40])
    assert {k[2] for k in server.cache._warm} == {1}


def test_sharded_warm_traffic_never_recompiles(model):
    """Zero recompiles after warmup across the sharded family: warm every
    bucket once, then replay mixed sizes — compile count must not move."""
    booster, X = model
    server = PredictServer(backend="tpu", sharded=True, max_batch_rows=32,
                           max_wait_ms=0.2)
    server.registry.add(booster)
    with server:
        for b in server.cache.buckets():
            server.predict(X[:b])
        compiles = server.stats()["cache_compiles"]
        for n in (1, 5, 9, 17, 30, 33, 64):
            server.predict(X[:n])
        snap = server.stats()
    assert snap["cache_compiles"] == compiles
    assert snap["cache_hits"] > 0
