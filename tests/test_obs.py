"""Unified observability subsystem (dryad_tpu/obs).

Pins the registry contracts (thread-safety, bucket edges, the
zero-cost-when-disabled fast path), span nesting, the Prometheus text
round trip, journal-tail parity with ``RunJournal.read()``, the
``ServeMetrics`` snapshot-shape backward compatibility, both trainers'
span wiring, the HTTP exporter (+ bearer auth), and the ACCEPTANCE
criterion: a supervised CPU run with an injected fault exposes — over
HTTP, while the run is still going — per-stage span timings, the fault
classification, and the chunk-cap degradation."""

import json
import re
import threading
import time
import tracemalloc
import urllib.error
import urllib.request

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.obs import (
    JournalTail,
    Registry,
    set_default_registry,
    start_exporter,
)
from dryad_tpu.obs import spans as S
from dryad_tpu.resilience import (
    FaultInjector,
    RetryPolicy,
    RunJournal,
    supervise_train,
)
from dryad_tpu.resilience import faults as F

PARAMS = dict(objective="binary", num_trees=16, num_leaves=7, max_bins=32,
              seed=3, min_data_in_leaf=5)


@pytest.fixture(scope="module")
def data():
    X, y = higgs_like(3000, seed=21)
    return dryad.Dataset(X, y, max_bins=32)


@pytest.fixture()
def fresh_registry():
    """Swap the process-wide default for a private one so trainer/serve
    wiring tests see only their own series, then restore."""
    reg = Registry()
    old = set_default_registry(reg)
    yield reg
    set_default_registry(old)


def _get(url, token=None, timeout=5):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    req = urllib.request.Request(url, headers=headers)
    return urllib.request.urlopen(req, timeout=timeout).read()


# ---- registry ---------------------------------------------------------------

def test_counter_thread_safety_under_concurrent_writers():
    reg = Registry()
    c = reg.counter("writers_total")
    lab = c.labels(worker="a")

    def hammer():
        for _ in range(2000):
            c.inc()
            lab.inc(2)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 8 * 2000
    assert lab.value() == 8 * 2000 * 2


def test_kind_mismatch_and_counter_monotonicity():
    reg = Registry()
    reg.counter("x_total")
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("x_total").inc(-1)
    g = reg.gauge("g")
    g.set(5)
    g.set(2)
    assert g.value() == 2.0


def test_histogram_bucket_edges():
    """Prometheus 'le' semantics: a value exactly ON a bound counts into
    that bound's bucket; above the top bound lands in +Inf."""
    reg = Registry()
    h = reg.histogram("h_seconds", buckets=(1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.0000001, 2.0, 5.0, 5.0000001, 100.0):
        h.observe(v)
    counts, total, n = h.value()
    assert counts == [2, 2, 1, 2]          # [<=1, <=2, <=5, +Inf]
    assert n == 7 and total == pytest.approx(sum(
        (0.5, 1.0, 1.0000001, 2.0, 5.0, 5.0000001, 100.0)))
    # cumulative exposition mirrors the same edges
    expo = reg.exposition()
    assert 'h_seconds_bucket{le="1.0"} 2' in expo
    assert 'h_seconds_bucket{le="5.0"} 5' in expo
    assert 'h_seconds_bucket{le="+Inf"} 7' in expo
    assert "h_seconds_count 7" in expo


def test_disabled_mode_records_nothing_and_allocates_nothing():
    """The zero-cost contract: with the registry disabled, the bound-series
    record calls and span() leave NO net allocations behind (the disabled
    path is one attribute read + one branch)."""
    reg = Registry(enabled=False)
    c = reg.counter("c_total")
    h = reg.histogram("h_seconds")
    g = reg.gauge("g")
    # warm every code path first (method caches, the shared null span,
    # CPython's adaptive-specialization inline caches)
    for _ in range(64):
        c.inc()
        h.observe(1.0)
        g.set(1.0)
        with S.span("warm", reg):
            pass
        S.record("warm", 0.1, reg)

    def leaked_bytes() -> list:
        tracemalloc.start()
        for _ in range(1000):
            c.inc()
            h.observe(1.0)
            g.set(1.0)
            with S.span("hot", reg):
                pass
            S.record("hot", 0.1, reg)
        snap_mem = tracemalloc.take_snapshot()
        tracemalloc.stop()
        # no LIVE allocation traces back into dryad_tpu/obs source: the
        # disabled record paths neither allocate nor retain
        return [st for st in snap_mem.statistics("filename")
                if "dryad_tpu" in st.traceback[0].filename
                and "obs" in st.traceback[0].filename]

    # tracemalloc attributes by FILE, not thread: a stray daemon thread
    # (another test's batcher/exporter) touching obs mid-window would
    # show up here — re-measure, since the contract under test is about
    # THIS thread's record calls, which allocate nothing every time
    for _ in range(3):
        leaked = leaked_bytes()
        if not leaked:
            break
    assert not leaked, f"disabled path allocated: {leaked}"
    assert c.value() == 0 and g.value() == 0.0 and h.value()[2] == 0
    snap = reg.snapshot()
    # families exist (created eagerly at bind time) but hold NO series
    assert all(series == {} for group in snap.values()
               for series in group.values())
    # re-enabling starts recording without re-binding handles
    reg.enable()
    c.inc()
    assert c.value() == 1


def test_span_nesting_totals_bounded_by_parent_wall():
    reg = Registry()
    with S.span("tree", reg):
        for _ in range(3):
            with S.span("level", reg):
                with S.span("hist", reg):
                    time.sleep(0.002)
                with S.span("partition", reg):
                    time.sleep(0.001)
    snap = S.snapshot(reg)
    assert set(snap) == {"tree", "tree/level", "tree/level/hist",
                         "tree/level/partition"}
    assert snap["tree"]["count"] == 1 and snap["tree/level"]["count"] == 3
    children = (snap["tree/level/hist"]["total_s"]
                + snap["tree/level/partition"]["total_s"])
    assert children <= snap["tree/level"]["total_s"] <= snap["tree"]["total_s"]
    assert snap["tree/level/hist"]["total_s"] >= 3 * 0.002 * 0.5


def test_span_disabled_returns_shared_null():
    reg = Registry(enabled=False)
    assert S.span("a", reg) is S.span("b", reg)
    with S.span("a", reg):
        # a span opened inside a disabled registry must not pollute the
        # enabled nesting stack of a DIFFERENT registry
        reg2 = Registry()
        with S.span("inner", reg2):
            pass
    assert set(S.snapshot(reg2)) == {"inner"}


# ---- exposition round trip --------------------------------------------------

def _parse_exposition(text):
    """name{labels} -> float, plus per-family TYPE lines."""
    values, types = {}, {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            types[name] = kind
        elif line and not line.startswith("#"):
            name_lbl, val = line.rsplit(" ", 1)
            values[name_lbl] = float(val)
    return values, types


def test_exposition_round_trips_the_snapshot():
    reg = Registry()
    reg.counter("req_total", "requests").inc(7)
    reg.counter("req_total").labels(model="a b", path='x"y').inc(3)
    reg.gauge("depth").set(-2.5)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    values, types = _parse_exposition(reg.exposition())
    assert types == {"req_total": "counter", "depth": "gauge",
                     "lat_seconds": "histogram"}
    snap = reg.snapshot()
    assert values["req_total"] == snap["counters"]["req_total"][""] == 7
    # label escaping survives the round trip
    lbl = next(k for k in snap["counters"]["req_total"] if k)
    assert values[f"req_total{{{lbl}}}"] == 3
    assert values["depth"] == snap["gauges"]["depth"][""] == -2.5
    hs = snap["histograms"]["lat_seconds"][""]
    assert values["lat_seconds_count"] == hs["count"] == 3
    assert values["lat_seconds_sum"] == pytest.approx(hs["sum"])
    assert values['lat_seconds_bucket{le="0.1"}'] == 1
    assert values['lat_seconds_bucket{le="1.0"}'] == 2
    assert values['lat_seconds_bucket{le="+Inf"}'] == 3


# ---- journal tail -----------------------------------------------------------

def _write_events(jpath):
    with RunJournal(jpath) as j:
        j.event("run_start", checkpoint_dir="ck", retry_budget=5)
        j.event("segment_start", attempt=0, resume_iteration=0, ch_max=0)
        for i in (0, 4, 8):
            j.event("chunk_dispatch", iteration=i)
        j.event("chunk_fetch", iteration=8)
        j.event("fault", kind="fetch_death", site="fetch", iteration=8)
        j.event("backoff_chunks", ch_max_from=0, ch_max_to=2,
                cap_consulted=True, changed=True)
        j.event("resume", attempt=1, from_iteration=8, sleep_s=0.0)
        j.event("segment_start", attempt=1, resume_iteration=8, ch_max=2)
        j.event("complete", wall_s=1.25, iterations=16, faults=1)


def test_journal_tail_parity_with_read(tmp_path):
    """Post-hoc tailing reproduces exactly the aggregates of
    RunJournal.read() — no event lost, none double-counted."""
    jpath = str(tmp_path / "j.jsonl")
    _write_events(jpath)
    reg = Registry()
    tail = JournalTail(jpath, reg)
    n = tail.poll()
    events = RunJournal.read(jpath)
    assert n == len(events)
    per_kind = {}
    for e in events:
        per_kind[e["event"]] = per_kind.get(e["event"], 0) + 1
    ev_counter = reg.counter("dryad_run_events_total")
    for kind, cnt in per_kind.items():
        assert ev_counter.labels(event=kind).value() == cnt, kind
    assert reg.counter("dryad_run_faults_total").labels(
        kind="fetch_death").value() == 1
    assert reg.counter("dryad_run_chunk_backoffs_total").value() == 1
    assert reg.counter("dryad_run_resumes_total").value() == 1
    assert reg.gauge("dryad_run_ch_max").value() == 2
    assert reg.gauge("dryad_run_resume_iteration").value() == 8
    assert reg.gauge("dryad_run_iteration").value() == 8
    assert reg.gauge("dryad_run_wall_seconds").value() == 1.25
    assert reg.gauge("dryad_run_iterations").value() == 16
    # a second poll with nothing appended folds nothing new
    assert tail.poll() == 0
    assert ev_counter.labels(event="fault").value() == 1


def test_journal_tail_resets_on_new_run_start(tmp_path):
    """An appended/reused journal (--resume, repeated --supervise) starts a
    new run with run_start: the tail must drop the PRIOR run's series so
    the live endpoint mirrors RunJournal.read_last_run — without the reset
    a healthy resume scrapes as already-faulted."""
    jpath = str(tmp_path / "j.jsonl")
    _write_events(jpath)                         # run 1: one fault, one resume
    reg = Registry()
    tail = JournalTail(jpath, reg)
    tail.poll()
    assert reg.counter("dryad_run_faults_total").labels(
        kind="fetch_death").value() == 1
    with RunJournal(jpath) as j:                 # run 2 appends, fault-free
        j.event("run_start", checkpoint_dir="ck", retry_budget=5)
        j.event("segment_start", attempt=0, resume_iteration=16, ch_max=0)
        j.event("chunk_dispatch", iteration=16)
        j.event("complete", wall_s=0.5, iterations=24, faults=0)
    tail.poll()
    assert reg.counter("dryad_run_faults_total").labels(
        kind="fetch_death").value() == 0         # run 1's fault is gone
    assert reg.counter("dryad_run_resumes_total").value() == 0
    assert reg.gauge("dryad_run_wall_seconds").value() == 0.5
    assert reg.gauge("dryad_run_iterations").value() == 24
    # run 2's own events are counted post-reset, run_start included
    assert reg.counter("dryad_run_events_total").labels(
        event="run_start").value() == 1
    assert reg.counter("dryad_run_events_total").labels(
        event="chunk_dispatch").value() == 1


def test_journal_tail_carries_partial_lines(tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    reg = Registry()
    tail = JournalTail(jpath, reg)
    assert tail.poll() == 0                      # no file yet: not an error
    with open(jpath, "a") as fh:
        fh.write('{"event": "run_start"}\n{"event": "fau')
        fh.flush()
        assert tail.poll() == 1                  # torn tail line carried
        fh.write('lt", "kind": "oom"}\n')
        fh.flush()
    assert tail.poll() == 1
    assert reg.counter("dryad_run_faults_total").labels(
        kind="oom").value() == 1


# ---- ServeMetrics over the shared registry ----------------------------------

def test_serve_metrics_snapshot_shape_backward_compatible():
    """snapshot() keys and values are the pre-obs contract, bit for bit;
    the same recordings ALSO land on the private registry as
    dryad_serve_* series."""
    reg = Registry()
    from dryad_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics(latency_window=64, registry=reg)
    m.record_request(5, 0.010, version=1)
    m.record_request(3, 0.020)
    m.record_batch(8, 16)
    m.record_cache(hit=False, version=1)
    m.record_cache(hit=True, version=1)
    m.record_timeout()
    m.record_rejected()
    m.record_error(version=1)
    m.record_eviction(version=1)
    m.record_restage(version=1)
    m.sample_queue_depth(3)
    snap = m.snapshot()
    assert set(snap) == {
        "requests", "rows", "batches", "batch_rows", "batch_fill_ratio",
        "p50_ms", "p99_ms", "mean_ms", "cache_hits", "cache_compiles",
        "timeouts", "rejected", "errors", "evictions", "restages",
        "queue_depth", "queue_depth_peak", "models"}
    assert snap["requests"] == 2 and snap["rows"] == 8
    assert snap["batch_fill_ratio"] == 0.5
    assert set(snap["models"]) == {1}
    assert set(snap["models"][1]) == {
        "requests", "rows", "p50_ms", "p99_ms", "cache_hits",
        "cache_compiles", "evictions", "restages", "errors"}
    # registry mirror
    assert reg.counter("dryad_serve_requests_total").value() == 2
    # per-version counts live in a SEPARATE family so family-level PromQL
    # sums (sum(dryad_serve_requests_total)) never double-count
    assert reg.counter("dryad_serve_requests_by_version_total").labels(
        version=1).value() == 1
    assert reg.counter("dryad_serve_errors_by_version_total").labels(
        version=1).value() == 1
    assert reg.counter("dryad_serve_rows_total").value() == 8
    assert reg.counter("dryad_serve_cache_hits_total").value() == 1
    assert reg.counter("dryad_serve_cache_compiles_total").value() == 1
    assert reg.counter("dryad_serve_timeouts_total").value() == 1
    assert reg.counter("dryad_serve_errors_total").value() == 1
    assert reg.gauge("dryad_serve_queue_depth").value() == 3
    # r17: the latency mirror rides the mergeable log-bucket family
    assert reg.log_histogram(
        "dryad_serve_request_latency_seconds").value()[2] == 2
    # ... and the per-(priority, stage) family saw both totals
    assert reg.log_histogram(
        "dryad_request_latency_seconds").labels(
        priority="interactive", stage="total").value()[2] == 2


# ---- trainer wiring ---------------------------------------------------------

def test_cpu_trainer_emits_per_iteration_spans(data, fresh_registry):
    dryad.train(PARAMS, data, backend="cpu")
    snap = S.snapshot(fresh_registry)
    assert snap["train.iteration"]["count"] == PARAMS["num_trees"]
    assert snap["train.grow"]["count"] == PARAMS["num_trees"]
    assert snap["train.grow"]["total_s"] <= snap["train.iteration"]["total_s"]
    assert fresh_registry.gauge("dryad_train_iteration").value() \
        == PARAMS["num_trees"] - 1


def test_device_trainer_emits_chunk_and_fetch_spans(data, fresh_registry):
    dryad.train(PARAMS, data, backend="tpu")     # device trainer, CPU jax
    snap = S.snapshot(fresh_registry)
    assert snap.get("train.chunk_dispatch", {}).get("count", 0) >= 1
    assert "train.fetch.final" in snap
    assert fresh_registry.counter("dryad_train_chunks_total").value() >= 1


def test_disabled_registry_unchanged_by_training(data, fresh_registry):
    fresh_registry.disable()
    dryad.train(PARAMS, data, backend="cpu")
    assert fresh_registry.snapshot() == {
        "counters": {}, "gauges": {}, "histograms": {}}


# ---- exporter ---------------------------------------------------------------

def test_exporter_endpoints_and_bearer_auth():
    reg = Registry()
    reg.counter("dryad_thing_total", "a thing").inc(3)
    with S.span("stage", reg):
        pass
    ex = start_exporter(reg, port=0, auth_token="s3cret")
    try:
        assert json.loads(_get(ex.url + "/healthz")) == {"ok": True}
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(ex.url + "/stats")
        assert err.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(ex.url + "/stats", token="wrong")
        assert err.value.code == 401
        stats = json.loads(_get(ex.url + "/stats", token="s3cret"))
        assert stats["counters"]["dryad_thing_total"][""] == 3
        assert stats["spans"]["stage"]["count"] == 1
        assert stats["uptime_s"] >= 0
        text = _get(ex.url + "/metrics", token="s3cret").decode()
        assert "# TYPE dryad_thing_total counter" in text
        values, _ = _parse_exposition(text)
        assert values["dryad_thing_total"] == 3
    finally:
        ex.stop()


# ---- the acceptance criterion: live fleet endpoint during a faulted run -----

def test_live_endpoint_during_supervised_faulted_run(data, tmp_path,
                                                     fresh_registry):
    """A supervised CPU training run with an injected fetch-death exposes,
    over HTTP while the run is still in progress, (a) per-stage span
    timings, (b) the fault classification, (c) the chunk-cap degradation
    — the ISSUE 5 acceptance gate, fully automated: a post-resume
    callback parks the training thread until the main thread has scraped
    and asserted the live endpoint."""
    jpath = str(tmp_path / "run.jsonl")
    injector = FaultInjector([(3, F.FETCH_DEATH, "fetch")])
    scrape_done = threading.Event()
    parked = threading.Event()

    def gate(it, info):
        if it >= 8 and info.get("supervise_attempt", 0) >= 1:
            parked.set()
            assert scrape_done.wait(60), "scraper never released the run"

    result = {}

    def run():
        try:
            result["booster"] = supervise_train(
                PARAMS, data, backend="cpu",
                checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
                journal=jpath, fault_injector=injector, callback=gate,
                policy=RetryPolicy(backoff_base_s=0.0, ch_max_ladder=(2,)))
        except BaseException as e:  # noqa: BLE001 — surfaced below
            result["error"] = e

    tail = JournalTail(jpath, fresh_registry, poll_interval_s=0.02).start()
    ex = start_exporter(fresh_registry, port=0)
    thread = threading.Thread(target=run)
    thread.start()
    try:
        assert parked.wait(60), f"run never reached the gate: {result}"
        # the run is alive and parked mid-segment: everything asserted
        # below was served DURING the run
        assert thread.is_alive()
        deadline = time.monotonic() + 30
        stats = None
        while time.monotonic() < deadline:
            stats = json.loads(_get(ex.url + "/stats"))
            counters = stats["counters"]
            if ("dryad_run_faults_total" in counters
                    and "dryad_run_chunk_backoffs_total" in counters):
                break
            time.sleep(0.02)
        # (a) per-stage span timings from the CPU trainer's loop
        assert stats["spans"]["train.iteration"]["count"] >= 1
        assert stats["spans"]["train.iteration"]["total_s"] > 0
        assert stats["spans"]["supervise.segment"]["count"] >= 1
        # (b) the fault classification event
        assert stats["counters"]["dryad_run_faults_total"][
            'kind="fetch_death"'] == 1
        assert stats["counters"]["dryad_run_events_total"][
            'event="fault"'] == 1
        # (c) the chunk-cap degradation
        assert stats["counters"]["dryad_run_chunk_backoffs_total"][""] == 1
        assert stats["gauges"]["dryad_run_ch_max"][""] == 2
        assert stats["counters"]["dryad_run_resumes_total"][""] == 1
    finally:
        scrape_done.set()
        thread.join(120)
        tail.stop()
        ex.stop()
    assert "error" not in result, result.get("error")
    assert injector.pending == 0
    assert result["booster"].num_iterations == PARAMS["num_trees"]
    # the supervised run remains bitwise-identical to the uninterrupted one
    reference = dryad.train(PARAMS, data, backend="cpu")
    np.testing.assert_array_equal(reference.feature,
                                  result["booster"].feature)
    np.testing.assert_array_equal(reference.value, result["booster"].value)
