"""Resilient training subsystem (dryad_tpu/resilience): fault
classification against the recorded tunnel signatures, deterministic
injection, ch_max threading/precedence, the supervised mixed-fault soak
(bitwise vs the uninterrupted run), and every fail-closed path."""

import os

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.resilience import (
    FaultError,
    FaultInjector,
    FaultPoint,
    RetryPolicy,
    RunJournal,
    classify_fault,
    make_fault,
    supervise_train,
)
from dryad_tpu.resilience import faults as F
from dryad_tpu.resilience.policy import ChunkCapPolicy

PARAMS = dict(objective="binary", num_trees=16, num_leaves=7, max_bins=32,
              seed=3, min_data_in_leaf=5)


@pytest.fixture(scope="module")
def data():
    X, y = higgs_like(3000, seed=21)
    return dryad.Dataset(X, y, max_bins=32)


# ---- classification ---------------------------------------------------------

def test_classify_recorded_signatures():
    """The real messages from STATUS r5 map onto their classes; the
    UNAVAILABLE family splits on the fetch-site signal."""
    unavailable = RuntimeError(
        "UNAVAILABLE: TPU device error: worker process crashed")
    assert classify_fault(unavailable) == F.DEVICE_UNAVAILABLE
    assert classify_fault(unavailable, at_fetch=True) == F.FETCH_DEATH
    # a deadline-class message announces the fetch death itself
    assert classify_fault(RuntimeError("DEADLINE_EXCEEDED: ..."),
                          at_fetch=False) == F.FETCH_DEATH
    assert classify_fault(RuntimeError(
        "RESOURCE_EXHAUSTED: out of memory allocating 1.3G")) == F.OOM
    assert classify_fault(RuntimeError(
        "ABORTED: the TPU worker was preempted")) == F.PREEMPTION
    assert classify_fault(RuntimeError(
        "Preempted by the scheduler")) == F.PREEMPTION


def test_classify_fails_closed_on_everything_else():
    # user/config errors must NEVER be retried, whatever their message
    assert classify_fault(ValueError("UNAVAILABLE: looks tunnely")) == F.UNKNOWN
    assert classify_fault(RuntimeError("some novel explosion")) == F.UNKNOWN
    assert classify_fault(KeyboardInterrupt()) == F.UNKNOWN
    # prose "aborted" is not the grpc ABORTED status — a deterministic bug
    # must not classify as a retryable preemption
    assert classify_fault(RuntimeError(
        "compilation aborted: invalid argument")) == F.UNKNOWN


def test_make_fault_roundtrips_through_classification():
    for kind in F.RETRYABLE:
        exc = make_fault(kind)
        assert isinstance(exc, RuntimeError)
        # the contract holds at ANY site: injected messages self-describe
        assert classify_fault(exc, at_fetch=False) == kind
        assert classify_fault(exc, at_fetch=True) in (kind, F.FETCH_DEATH)
    assert classify_fault(make_fault(F.UNKNOWN)) == F.UNKNOWN
    with pytest.raises(ValueError):
        make_fault("nope")


# ---- injector ---------------------------------------------------------------

def test_injector_fires_exactly_once_at_first_event_at_or_after():
    inj = FaultInjector([(5, F.OOM, "dispatch")])
    inj("fetch", 7)                    # wrong site: no fire
    inj("dispatch", 3)                 # too early: no fire
    with pytest.raises(RuntimeError):
        inj("dispatch", 6)             # first dispatch >= 5
    inj("dispatch", 6)                 # spent: silent on replay
    assert inj.pending == 0
    assert inj.fired == [{"point": 0, "site": "dispatch", "iteration": 6,
                          "kind": F.OOM}]
    with pytest.raises(ValueError):
        FaultPoint(0, site="telepathy")


# ---- ch_max threading (satellite) ------------------------------------------

def test_ch_max_param_caps_chunks_and_lands_in_info(data, monkeypatch):
    monkeypatch.delenv("DRYAD_CH_MAX", raising=False)
    seen, infos = [], []
    dryad.train(dict(PARAMS, ch_max=3), data, backend="tpu",
                chunk_hook=lambda s, it: seen.append(it) if s == "dispatch"
                else None,
                callback=lambda it, info: infos.append(info))
    assert seen == [0, 3, 6, 9, 12, 15]
    assert infos and all(i["ch_max_effective"] == 3 for i in infos)


def test_ch_max_env_overrides_param(data, monkeypatch):
    """Documented precedence: DRYAD_CH_MAX, when set, beats Params.ch_max."""
    monkeypatch.setenv("DRYAD_CH_MAX", "2")
    seen, infos = [], []
    b = dryad.train(dict(PARAMS, ch_max=5), data, backend="tpu",
                    chunk_hook=lambda s, it: seen.append(it)
                    if s == "dispatch" else None,
                    callback=lambda it, info: infos.append(info))
    assert seen == list(range(0, 16, 2))
    assert all(i["ch_max_effective"] == 2 for i in infos)
    assert b.train_state["ch_max_effective"] == 2


def test_ch_max_key_present_on_per_iteration_path(data):
    """The documented info/train_state key exists on EVERY path — the
    per-iteration dispatch (DART pins it) reports 0: no chunks, no cap."""
    infos = []
    b = dryad.train(dict(PARAMS, boosting="dart", num_trees=4), data,
                    backend="tpu", callback=lambda it, i: infos.append(i))
    assert infos and all(i["ch_max_effective"] == 0 for i in infos)
    assert b.train_state["ch_max_effective"] == 0


def test_ch_max_does_not_change_the_model(data, monkeypatch):
    """Chunk length is a traced scalar of one shared program — capping it
    must be invisible in the trees (the property the supervisor's
    degradation lever rests on)."""
    monkeypatch.delenv("DRYAD_CH_MAX", raising=False)
    a = dryad.train(PARAMS, data, backend="tpu")
    b = dryad.train(dict(PARAMS, ch_max=2), data, backend="tpu")
    np.testing.assert_array_equal(a.feature, b.feature)
    np.testing.assert_array_equal(a.threshold, b.threshold)
    np.testing.assert_array_equal(a.value, b.value)


# ---- chunk-cap policy -------------------------------------------------------

def test_chunk_cap_ladder_degrade_and_rewiden():
    cap = ChunkCapPolicy(RetryPolicy(rewiden_after_clean_chunks=2))
    assert cap.cap() == 0
    # first degrade with NO length observed: ladder top, nothing fatal yet
    assert cap.degrade() == 8
    cap.note_clean_chunk()
    assert cap.cap() == 8                  # not yet
    cap.note_clean_chunk()
    assert cap.cap() == 0                  # no fatal on record: uncapped again
    # full walk-down: each further degrade means the CURRENT length faulted,
    # so every visited length lands on the fatal record
    assert cap.degrade() == 8 and cap.degrade() == 4 and cap.degrade() == 2
    assert cap.degrade() == 2              # floor holds
    for _ in range(4):
        cap.note_clean_chunk()
    assert cap.cap() == 2                  # 4 and 8 both faulted: hold at floor
    # a start below the ladder floor must never be WIDENED by degrade()
    tight = ChunkCapPolicy(RetryPolicy(ch_max_start=1))
    assert tight.degrade() == 1
    # degrade targets a step STRICTLY below the observed chunk length —
    # a ladder top at/above the calibrated CH would replay the fatal length.
    # The length is known from DISPATCH (the r5 first-fetch-death mode:
    # the fatal chunk never completed cleanly)
    seen = ChunkCapPolicy(RetryPolicy())
    seen.note_dispatch(6)                  # calibrated CH ~6 was dispatched
    assert seen.degrade() == 4
    # a cap ABOVE the calibrated CH never governed what ran: the observed
    # length is the reference the first step must undercut
    wide = ChunkCapPolicy(RetryPolicy(ch_max_start=8))
    wide.note_dispatch(3)                  # chunks really ran at 3
    assert wide.degrade() == 2 and wide.last_shrunk
    # fatal length already at/below the floor: cap lands on the floor but
    # the journal must read "remedy exhausted", not "applied"
    exhausted = ChunkCapPolicy(RetryPolicy())
    exhausted.note_dispatch(2)
    assert exhausted.degrade() == 2 and not exhausted.last_shrunk
    # an ascending user ladder is normalized widest-first, not inverted
    asc = ChunkCapPolicy(RetryPolicy(ch_max_ladder=(2, 4, 8)))
    assert asc.degrade() == 8
    with pytest.raises(ValueError, match="at least one step"):
        ChunkCapPolicy(RetryPolicy(ch_max_ladder=()))
    # re-widening never returns to a known-fatal length: a persistent
    # tunnel phase must not oscillate safe -> fatal -> safe and burn the
    # retry budget (the recorded r5 mode: 6-8 fatal, <= 2 always clean)
    osc = ChunkCapPolicy(RetryPolicy(rewiden_after_clean_chunks=1))
    osc.note_dispatch(6)
    assert osc.degrade() == 4              # fatal length 6 on record
    assert osc.degrade() == 2              # faulted again at 4 -> fatal 4
    osc.note_clean_chunk()
    assert osc.cap() == 2                  # no ladder step in (2, 4): hold
    # cadence tightening is monotone non-increasing with a floor well
    # above per-iteration checkpointing (a materialize fetch per iteration
    # is the tunnel-killing pattern)
    pol = RetryPolicy()
    assert pol.next_checkpoint_every(50) == 25
    assert pol.next_checkpoint_every(6) == 5
    assert pol.next_checkpoint_every(2) == 2   # never loosened to the floor


# ---- the supervised soak (acceptance criterion) -----------------------------

def test_supervised_soak_mixed_faults_bitwise(data, tmp_path):
    """>= 3 injected faults of mixed classes — including a fetch-death that
    degrades the chunk cap to 2 — complete bitwise-identical to the
    uninterrupted run, with the journal recording every classification,
    backoff, and resume."""
    reference = dryad.train(PARAMS, data, backend="tpu")
    injector = FaultInjector([
        (3, F.DEVICE_UNAVAILABLE, "dispatch"),
        (6, F.OOM, "dispatch"),
        (10, F.FETCH_DEATH, "fetch"),
    ])
    jpath = str(tmp_path / "journal.jsonl")
    infos = []
    booster = supervise_train(
        PARAMS, data, backend="tpu",
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
        journal=jpath, fault_injector=injector,
        callback=lambda it, info: infos.append(info),
        policy=RetryPolicy(backoff_base_s=0.0, ch_max_ladder=(2,)))

    assert injector.pending == 0
    np.testing.assert_array_equal(reference.feature, booster.feature)
    np.testing.assert_array_equal(reference.threshold, booster.threshold)
    np.testing.assert_array_equal(reference.value, booster.value)
    Xp = np.zeros((4, data.num_features), np.float32)
    np.testing.assert_array_equal(reference.predict(Xp), booster.predict(Xp))

    events = RunJournal.read(jpath)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "complete"
    faults = [e for e in events if e["event"] == "fault"]
    assert [f["kind"] for f in faults] == [
        F.DEVICE_UNAVAILABLE, F.OOM, F.FETCH_DEATH]
    # exactly-once resume per fault, and resume points advance (the
    # same-point breaker never engaged)
    assert kinds.count("resume") == 3 and kinds.count("segment_start") == 4
    resume_points = [e["from_iteration"] for e in events
                     if e["event"] == "resume"]
    assert resume_points == sorted(resume_points)
    backoff = [e for e in events if e["event"] == "backoff_chunks"]
    assert len(backoff) == 1 and backoff[0]["ch_max_to"] == 2
    # the faulted segment ran the chunked path, so the cap was really in
    # force there — "remedy applied", not "remedy inapplicable"
    assert backoff[0]["cap_consulted"] is True
    # replayed iterations (checkpoint..fault span, re-grown bitwise) carry
    # the attempt marker so consumers can dedupe: keep the highest attempt
    assert all("supervise_attempt" in i for i in infos)
    assert {i["supervise_attempt"] for i in infos} == {0, 1, 2, 3}
    its_seen = [i["iteration"] for i in infos]
    assert len(its_seen) > len(set(its_seen)), "no replayed iterations?"
    # degraded segments record the live cap in the callback info dicts via
    # the chunk events; the journal carries dispatch/fetch traffic too
    assert any(e["event"] == "chunk_dispatch" for e in events)
    assert any(e["event"] == "chunk_fetch" for e in events)
    assert events[-1]["faults"] == 3


def test_supervised_warm_start_resumes_from_checkpoint(data, tmp_path):
    """A caller-supplied init_booster seeds only the checkpoint-less first
    segment — post-fault retries must continue from the newest checkpoint
    (which embodies warm start + progress), not redo the faulted segment
    from the warm booster."""
    warm = dryad.train(dict(PARAMS, num_trees=4), data, backend="tpu")
    full = dryad.train(PARAMS, data, backend="tpu", init_booster=warm)
    injector = FaultInjector([(8, F.DEVICE_UNAVAILABLE, "dispatch")])
    jpath = str(tmp_path / "j.jsonl")
    resumed = supervise_train(
        PARAMS, data, backend="tpu", init_booster=warm,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
        journal=jpath, fault_injector=injector,
        policy=RetryPolicy(backoff_base_s=0.0))
    assert injector.pending == 0
    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_array_equal(full.value, resumed.value)
    resumes = [e for e in RunJournal.read(jpath) if e["event"] == "resume"]
    # the retry really continued past the warm start instead of redoing it
    assert resumes and resumes[0]["from_iteration"] > warm.num_iterations


def test_supervised_cpu_backend_bitwise(data, tmp_path):
    """The same supervision loop covers the CPU reference trainer (its
    per-iteration loop exposes the same hook sites)."""
    reference = dryad.train(PARAMS, data, backend="cpu")
    injector = FaultInjector([(5, F.DEVICE_UNAVAILABLE, "dispatch"),
                              (9, F.OOM, "fetch")])
    infos = []
    booster = supervise_train(
        PARAMS, data, backend="cpu",
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=3,
        callback=lambda it, i: infos.append(i),
        fault_injector=injector, policy=RetryPolicy(backoff_base_s=0.0))
    assert injector.pending == 0
    # the documented info-dict contract holds on the CPU backend too
    assert infos and all(i["ch_max_effective"] == 0 for i in infos)
    np.testing.assert_array_equal(reference.feature, booster.feature)
    np.testing.assert_array_equal(reference.value, booster.value)


# ---- fail-closed paths ------------------------------------------------------

def test_unknown_fault_fails_closed(data, tmp_path):
    injector = FaultInjector([(2, F.UNKNOWN, "dispatch")])
    jpath = str(tmp_path / "j.jsonl")
    with pytest.raises(FaultError) as ei:
        supervise_train(PARAMS, data, backend="tpu",
                        checkpoint_dir=str(tmp_path / "ck"),
                        checkpoint_every=2, journal=jpath,
                        fault_injector=injector,
                        policy=RetryPolicy(backoff_base_s=0.0))
    assert ei.value.reason == "unknown_fault"
    assert ei.value.__cause__ is not None        # original exception chained
    events = RunJournal.read(jpath)
    kinds = [e["event"] for e in events]
    assert kinds.count("segment_start") == 1     # no retry happened
    assert kinds[-1] == "fail_closed"
    assert events[-1]["reason"] == "unknown_fault"


def test_retry_budget_exhausted_fails_closed(data, tmp_path):
    injector = FaultInjector([(2, F.DEVICE_UNAVAILABLE, "dispatch"),
                              (8, F.DEVICE_UNAVAILABLE, "dispatch")])
    jpath = str(tmp_path / "j.jsonl")
    with pytest.raises(FaultError) as ei:
        supervise_train(PARAMS, data, backend="tpu",
                        checkpoint_dir=str(tmp_path / "ck"),
                        checkpoint_every=2, journal=jpath,
                        fault_injector=injector,
                        policy=RetryPolicy(retry_budget=1,
                                           backoff_base_s=0.0))
    assert ei.value.reason == "retry_budget_exhausted"
    events = RunJournal.read(jpath)
    assert events[-1]["reason"] == "retry_budget_exhausted"
    assert [e["event"] for e in events].count("resume") == 1  # first fault only


def test_repeated_same_point_fails_closed(data, tmp_path):
    """Faults with NO checkpoint progress in between (cadence too wide for
    any checkpoint to land) trip the same-point breaker."""
    injector = FaultInjector([(0, F.DEVICE_UNAVAILABLE, "dispatch"),
                              (0, F.DEVICE_UNAVAILABLE, "dispatch"),
                              (0, F.DEVICE_UNAVAILABLE, "dispatch")])
    with pytest.raises(FaultError) as ei:
        supervise_train(PARAMS, data, backend="tpu",
                        checkpoint_dir=str(tmp_path / "ck"),
                        checkpoint_every=100,
                        fault_injector=injector,
                        policy=RetryPolicy(backoff_base_s=0.0,
                                           same_point_retries=2))
    assert ei.value.reason == "repeated_fault_at_same_iteration"


def test_same_point_device_unavailable_degrades_as_fallback(data, tmp_path):
    """A killed fetch can surface at the NEXT enqueue (a dispatch site),
    classifying as device_unavailable — on a no-progress repeat the chunk
    remedy must still be tried before the same-point breaker fires."""
    injector = FaultInjector([(0, F.DEVICE_UNAVAILABLE, "dispatch"),
                              (0, F.DEVICE_UNAVAILABLE, "dispatch")])
    jpath = str(tmp_path / "j.jsonl")
    booster = supervise_train(
        PARAMS, data, backend="tpu",
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=100,
        journal=jpath, fault_injector=injector,
        policy=RetryPolicy(backoff_base_s=0.0))
    assert injector.pending == 0
    assert booster.num_iterations == PARAMS["num_trees"]
    events = RunJournal.read(jpath)
    backoffs = [e for e in events if e["event"] == "backoff_chunks"]
    # first fault: plain resume; the same-point repeat engages the remedy
    assert len(backoffs) == 1
    assert backoffs[0]["trigger"] == "same_point_device_unavailable"


def test_supervise_requires_checkpoint_dir(data):
    with pytest.raises(ValueError, match="checkpoint_dir"):
        supervise_train(PARAMS, data, backend="cpu")


def test_supervise_owns_resume_kwarg(data, tmp_path):
    """A caller's resume= must not collide with the supervisor's own
    resume=True (dryad.train would raise an opaque TypeError), and the
    composed hook surfaces are rejected up front with a clear error."""
    b = supervise_train(PARAMS, data, backend="cpu", resume=True,
                        checkpoint_dir=str(tmp_path / "ck"))
    assert b.num_iterations == PARAMS["num_trees"]
    # an explicit resume=False is contradictory, not silently swallowed
    with pytest.raises(ValueError, match="resume=False is contradictory"):
        supervise_train(PARAMS, data, backend="cpu", resume=False,
                        checkpoint_dir=str(tmp_path / "ck3"))
    with pytest.raises(ValueError, match="composes its own chunk_hook"):
        supervise_train(PARAMS, data, backend="cpu",
                        checkpoint_dir=str(tmp_path / "ck2"),
                        chunk_hook=lambda s, i: None)


def test_journal_closed_on_error_outside_classified_path(data, tmp_path):
    """An exception raised OUTSIDE the classified try (bad cadence) still
    closes an owned journal."""
    jpath = str(tmp_path / "j.jsonl")
    with pytest.raises(ValueError):
        supervise_train(PARAMS, data, backend="cpu", journal=jpath,
                        checkpoint_dir=str(tmp_path / "ck"),
                        checkpoint_every=0)
    events = RunJournal.read(jpath)          # parseable: handle was closed
    assert events and events[0]["event"] == "run_start"


def test_mesh_with_cpu_backend_rejected(data):
    import jax

    from dryad_tpu.engine.distributed import make_mesh

    with pytest.raises(ValueError, match="mesh requires"):
        dryad.train(PARAMS, data, backend="cpu",
                    mesh=make_mesh(jax.devices()[:2]))


# ---- journal ----------------------------------------------------------------

def test_journal_shape_and_ownership(data, tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    supervise_train(PARAMS, data, backend="cpu",
                    checkpoint_dir=str(tmp_path / "ck"), journal=jpath)
    events = RunJournal.read(jpath)
    assert events[0]["event"] == "run_start"
    assert events[-1]["event"] == "complete"
    assert all("elapsed_s" in e for e in events)
    assert events[-1]["iterations"] == PARAMS["num_trees"]
    assert events[-1]["faults"] == 0
    # fault-free supervision leaves no fault/backoff/resume records
    assert not any(e["event"] in ("fault", "resume", "backoff_chunks",
                                  "fail_closed") for e in events)
    assert os.path.getsize(jpath) > 0
