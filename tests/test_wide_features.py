"""Epsilon-shaped wide-feature stress (BASELINE.json config 3, scaled for
CI): many-feature regression must train correctly through the feature-
chunked histogram path on both backends."""

import numpy as np

import dryad_tpu as dryad
from dryad_tpu.datasets import epsilon_like
from dryad_tpu.metrics import rmse

PARAMS = dict(objective="regression", num_trees=5, num_leaves=31,
              max_depth=5, growth="depthwise", max_bins=64)


def test_wide_regression_cpu_tpu_parity():
    # seed 81 stopped being tie-free under the 0.4.x container's XLA CPU
    # lowering (one near-tie argmax flips vs the f64 oracle — documented
    # tolerance class); 87 is tie-free on both jax generations
    X, y = epsilon_like(n=3000, num_features=300, seed=87)
    ds = dryad.Dataset(X, y, max_bins=64)
    b_cpu = dryad.train(PARAMS, ds, backend="cpu")
    b_tpu = dryad.train(PARAMS, ds, backend="tpu")
    np.testing.assert_array_equal(b_cpu.feature, b_tpu.feature)
    np.testing.assert_array_equal(b_cpu.threshold, b_tpu.threshold)
    r = rmse(y, b_cpu.predict_binned(ds.X_binned))
    assert r < np.sqrt(np.var(y))            # learned something


def test_no_hist_subtraction_path():
    # exercises the build_hist_multi large-child branch (hist_subtraction off)
    X, y = epsilon_like(n=2000, num_features=20, seed=83)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(PARAMS, max_bins=32, hist_subtraction=False)
    b_cpu = dryad.train(p, ds, backend="cpu")
    b_tpu = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(b_cpu.feature, b_tpu.feature)


def test_wide_forces_multiple_feature_chunks():
    from dryad_tpu.engine.pallas_hist import _feature_chunk, _pow2_bins

    Fc = _feature_chunk(300, _pow2_bins(64))
    assert Fc < 300                          # the chunked path is exercised
