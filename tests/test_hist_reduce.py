"""Feature-parallel histogram reduction (r16, ``Params.hist_reduce``):

* the packed combine key reproduces the fused scan's feature-major
  first-max argmax order EXACTLY (tie-convention unit tests on seeded
  equal-gain grids, incl. the learn_missing plane order and categorical
  winners);
* N-shard ≡ 1-shard ≡ fused bitwise tree structures on the tie-free
  fixtures across 1/2/8 fake devices — incl. GOSS, L1 renewal,
  multiclass K=3, and ragged feature counts (28 % 8 != 0, plus an F=10
  fixture whose tail shards own ONLY padding);
* the accounted collective payload at the Epsilon shape (F=2000, B=256,
  8 shards) shrinks ≥ 4x on the feature arm — the same accounting the
  jaxpr census cross-checks call-for-call (test_analysis_jaxpr).

The sliced scan + combine are exercised both as pure functions (no mesh
— a host-side simulation of the shard slices) and end-to-end through
``train_device`` on the virtual 8-CPU-device mesh.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import dryad_tpu as dryad
from dryad_tpu.config import make_params
from dryad_tpu.datasets import higgs_like
from dryad_tpu.engine.split import (
    NEG_INF,
    combine_local_splits,
    find_best_split,
    find_best_split_sliced,
    pack_local_split,
)

# r19: slow — interpret-mode sharded compute on the 8-fake-device
# mesh pays the virtual-collective overhead in Python; on the 2-core
# CI container this module helped push tier-1 past its 870 s budget.
# ci.sh tier-1 runs `-m 'not slow'`; run this module explicitly (or
# the full unfiltered suite) on a wider host when touching it.
pytestmark = [pytest.mark.distributed, pytest.mark.slow]


# ---------------------------------------------------------------------------
# tie-convention unit tests: sliced + combine == fused, field for field

def _sliced_combine(hist, G, H, C, n, *, feat_mask, is_cat_feat, allow,
                    has_cat=False, learn_missing=False, min_split_gain=0.0,
                    lambda_l2=1.0, min_child_weight=1e-3,
                    min_data_in_leaf=1):
    """Host-side simulation of the feature arm: slice the reduced hist
    into n contiguous shards (zero/False padding like
    distributed.feature_shard_slice), run the sliced scan per shard, pack
    + stack the records like the all_gather would, combine."""
    F = hist.shape[1]
    Fs = -(-F // n)
    pad = Fs * n - F
    hist_p = jnp.pad(hist, ((0, 0), (0, pad), (0, 0)))
    fmask_p = jnp.pad(feat_mask, (0, pad))
    iscat_p = jnp.pad(is_cat_feat, (0, pad))
    words, cats = [], []
    for s in range(n):
        lo, hi = s * Fs, (s + 1) * Fs
        rec = find_best_split_sliced(
            hist_p[:, lo:hi], G, H, C,
            feat_offset=jnp.int32(lo), num_features_total=F,
            lambda_l2=lambda_l2, min_child_weight=min_child_weight,
            min_data_in_leaf=min_data_in_leaf,
            feat_mask=fmask_p[lo:hi], is_cat_feat=iscat_p[lo:hi],
            has_cat=has_cat, learn_missing=learn_missing)
        words.append(pack_local_split(rec))
        cats.append(rec.cat_mask)
    return combine_local_splits(
        jnp.stack(words), jnp.stack(cats) if has_cat else None,
        allow=allow, min_split_gain=min_split_gain, has_cat=has_cat)


def _assert_same_split(got, want, msg=""):
    for field in ("gain", "feature", "threshold", "g_left", "h_left",
                  "c_left", "default_left"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, field)), np.asarray(getattr(want, field)),
            err_msg=f"{msg}: {field}")
    np.testing.assert_array_equal(np.asarray(got.cat_mask),
                                  np.asarray(want.cat_mask),
                                  err_msg=f"{msg}: cat_mask")


def _rand_hist(rng, F, B, scale=100.0):
    return jnp.asarray(np.stack([
        rng.normal(size=(F, B)),
        rng.uniform(0.1, 1.0, size=(F, B)),
        rng.uniform(0.5, 2.0, size=(F, B)),
    ]).astype(np.float32) * scale)


def test_combine_matches_fused_on_random_grids():
    rng = np.random.default_rng(5)
    for F, B in ((28, 32), (10, 16), (5, 8)):
        hist = _rand_hist(rng, F, B)
        G, H, C = (hist[k].sum() for k in range(3))
        fmask = jnp.ones((F,), bool)
        iscat = jnp.zeros((F,), bool)
        allow = jnp.bool_(True)
        want = find_best_split(
            hist, G, H, C, lambda_l2=1.0, min_child_weight=1e-3,
            min_data_in_leaf=1, min_split_gain=0.0, feat_mask=fmask,
            is_cat_feat=iscat, allow=allow, has_cat=False)
        for n in (1, 2, 4, 8):
            got = _sliced_combine(hist, G, H, C, n, feat_mask=fmask,
                                  is_cat_feat=iscat, allow=allow)
            _assert_same_split(got, want, f"F={F} n={n}")


def test_combine_tie_breaks_like_fused_feature_major():
    """Two IDENTICAL per-feature histogram rows land in DIFFERENT shards:
    equal gains to the last bit, and the fused first-max picks the lower
    feature id — the packed min-key combine must agree."""
    rng = np.random.default_rng(7)
    F, B = 16, 8
    hist = np.asarray(_rand_hist(rng, F, B))
    for f_lo, f_hi in ((1, 9), (0, 15), (3, 12), (7, 8)):
        h2 = hist.copy()
        h2[:, f_hi] = h2[:, f_lo]          # bitwise-equal gain rows
        # make the duplicated feature the undisputed winner: boost its
        # gradient asymmetry so its best gain dominates the rest
        h2[0, f_lo] *= 50.0
        h2[0, f_hi] = h2[0, f_lo]
        hj = jnp.asarray(h2)
        G, H, C = (hj[k].sum() for k in range(3))
        fmask = jnp.ones((F,), bool)
        iscat = jnp.zeros((F,), bool)
        want = find_best_split(
            hj, G, H, C, lambda_l2=1.0, min_child_weight=1e-3,
            min_data_in_leaf=1, min_split_gain=0.0, feat_mask=fmask,
            is_cat_feat=iscat, allow=jnp.bool_(True), has_cat=False)
        assert int(want.feature) == f_lo, "fixture lost its tie"
        for n in (2, 4, 8):
            got = _sliced_combine(hj, G, H, C, n, feat_mask=fmask,
                                  is_cat_feat=iscat, allow=jnp.bool_(True))
            _assert_same_split(got, want, f"tie {f_lo}/{f_hi} n={n}")


def test_combine_tie_breaks_plane_major_with_learn_missing():
    """learn_missing scans two planes, missing-left FIRST across ALL
    features: a plane-1 candidate in a LOW shard must lose an equal-gain
    plane-0 candidate in a HIGH shard (the fused flattened order is
    plane-major) — the key's plane stride pins exactly this."""
    rng = np.random.default_rng(11)
    F, B = 12, 8
    hist = np.array(np.asarray(_rand_hist(rng, F, B)))
    hist[:, :, 0] = 0.0                    # no missing stats: the two
    hj = jnp.asarray(hist)                 # planes are numerically equal
    G, H, C = (hj[k].sum() for k in range(3))
    fmask = jnp.ones((F,), bool)
    iscat = jnp.zeros((F,), bool)
    want = find_best_split(
        hj, G, H, C, lambda_l2=1.0, min_child_weight=1e-3,
        min_data_in_leaf=1, min_split_gain=0.0, feat_mask=fmask,
        is_cat_feat=iscat, allow=jnp.bool_(True), has_cat=False,
        learn_missing=True)
    assert bool(want.default_left), "missing-left plane must win the tie"
    for n in (1, 2, 4):
        got = _sliced_combine(hj, G, H, C, n, feat_mask=fmask,
                              is_cat_feat=iscat, allow=jnp.bool_(True),
                              learn_missing=True)
        _assert_same_split(got, want, f"plane tie n={n}")


def test_combine_categorical_winner_carries_its_mask():
    rng = np.random.default_rng(13)
    F, B = 8, 16
    hist = _rand_hist(rng, F, B)
    G, H, C = (hist[k].sum() for k in range(3))
    fmask = jnp.ones((F,), bool)
    iscat = jnp.asarray(np.arange(F) % 2 == 1)   # odd features categorical
    want = find_best_split(
        hist, G, H, C, lambda_l2=1.0, min_child_weight=1e-3,
        min_data_in_leaf=1, min_split_gain=0.0, feat_mask=fmask,
        is_cat_feat=iscat, allow=jnp.bool_(True), has_cat=True)
    for n in (1, 2, 4):
        got = _sliced_combine(hist, G, H, C, n, feat_mask=fmask,
                              is_cat_feat=iscat, allow=jnp.bool_(True),
                              has_cat=True)
        _assert_same_split(got, want, f"cat n={n}")


def test_combine_all_invalid_matches_fused_defaults():
    """Every candidate -inf (allow False / empty grids): the combine must
    reproduce the fused scan's not-ok record (gain -inf, feature -1,
    default_left True) — shard 0's plane-0 key-0 record wins, exactly the
    fused flat argmax of an all--inf grid."""
    F, B = 8, 8
    hist = jnp.zeros((3, F, B), jnp.float32)
    G = H = C = jnp.float32(0.0)
    fmask = jnp.ones((F,), bool)
    iscat = jnp.zeros((F,), bool)
    for allow in (jnp.bool_(True), jnp.bool_(False)):
        want = find_best_split(
            hist, G, H, C, lambda_l2=1.0, min_child_weight=1e-3,
            min_data_in_leaf=1, min_split_gain=0.0, feat_mask=fmask,
            is_cat_feat=iscat, allow=allow, has_cat=False)
        for n in (1, 4):
            got = _sliced_combine(hist, G, H, C, n, feat_mask=fmask,
                                  is_cat_feat=iscat, allow=allow)
            _assert_same_split(got, want, f"invalid allow={bool(allow)} n={n}")
        assert float(want.gain) == NEG_INF


# ---------------------------------------------------------------------------
# end-to-end: feature ≡ fused ≡ cross-shard, bitwise on tie-free fixtures

@pytest.fixture(scope="module")
def meshes():
    from dryad_tpu.engine.distributed import make_mesh

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return {n: make_mesh(jax.devices()[:n]) for n in (1, 2, 8)}


def _train(params_dict, ds, mesh=None):
    from dryad_tpu.engine.train import train_device

    return train_device(make_params(params_dict), ds, mesh=mesh)


def _assert_trees_equal(a, b, msg, values="bitwise"):
    for k in ("feature", "threshold", "left", "right", "is_cat"):
        np.testing.assert_array_equal(a.tree_arrays()[k], b.tree_arrays()[k],
                                      err_msg=f"{msg}: {k}")
    if values == "bitwise":
        np.testing.assert_array_equal(a.value, b.value, err_msg=f"{msg}: value")
    else:
        np.testing.assert_allclose(a.value, b.value, atol=1e-3,
                                   err_msg=f"{msg}: value")


@pytest.fixture(scope="module")
def depthwise_boosters(meshes):
    """Fused + feature boosters at every mesh size on ONE tie-free
    fixture (F=28: 28 % 8 != 0, so the 8-shard slices are ragged) —
    shared by the bitwise-vs-fused and shard-count-invariance tests."""
    X, y = higgs_like(4096)
    ds = dryad.Dataset(X, y, max_bins=64)
    base = dict(objective="binary", num_trees=3, num_leaves=15, max_depth=4,
                growth="depthwise", max_bins=64, learning_rate=0.2)
    return {(arm, n): _train(dict(base, hist_reduce=arm), ds, mesh)
            for arm in ("fused", "feature")
            for n, mesh in meshes.items()}


def test_feature_equals_fused_bitwise_every_shard_count(depthwise_boosters):
    """The acceptance anchor: at EVERY shard count the feature arm's trees
    — values included — are bitwise the fused arm's (the reduce-scattered
    slices are bitwise the psum's, and the combine picks the fused
    winner)."""
    for n in (1, 2, 8):
        bf = depthwise_boosters[("fused", n)]
        bx = depthwise_boosters[("feature", n)]
        _assert_trees_equal(bx, bf, f"depthwise n={n}")
        np.testing.assert_array_equal(bx.tree_arrays()["gain"],
                                      bf.tree_arrays()["gain"])


def test_feature_arm_shard_count_invariant(depthwise_boosters):
    """feature @ 1 shard ≡ feature @ 2 ≡ feature @ 8 (tree structures;
    values to the documented fp32 reduction-order tolerance, same class
    as the fused arm's own N-shard ≡ 1-shard invariant)."""
    for n in (2, 8):
        _assert_trees_equal(depthwise_boosters[("feature", n)],
                            depthwise_boosters[("feature", 1)],
                            f"1-vs-{n} shards", values="close")


def test_feature_equals_fused_leafwise(meshes):
    X, y = higgs_like(4096)
    ds = dryad.Dataset(X, y, max_bins=64)
    base = dict(objective="binary", num_trees=3, num_leaves=15, max_depth=5,
                growth="leafwise", max_bins=64)
    for n in (1, 8):
        bf = _train(dict(base, hist_reduce="fused"), ds, meshes[n])
        bx = _train(dict(base, hist_reduce="feature"), ds, meshes[n])
        _assert_trees_equal(bx, bf, f"leafwise n={n}")


def test_feature_arm_goss(meshes):
    X, y = higgs_like(4096, seed=41)
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(objective="binary", num_trees=3, num_leaves=15, max_depth=4,
                growth="depthwise", max_bins=32, boosting="goss",
                goss_top_rate=0.3, goss_other_rate=0.2, seed=7)
    for n in (8,):
        bf = _train(dict(base, hist_reduce="fused"), ds, meshes[n])
        bx = _train(dict(base, hist_reduce="feature"), ds, meshes[n])
        _assert_trees_equal(bx, bf, f"goss n={n}")


def test_feature_arm_l1_renewal(meshes):
    X, y = higgs_like(4096, seed=43)
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(objective="l1", num_trees=3, num_leaves=15, max_depth=4,
                growth="leafwise", max_bins=32)
    for n in (8,):
        bf = _train(dict(base, hist_reduce="fused"), ds, meshes[n])
        bx = _train(dict(base, hist_reduce="feature"), ds, meshes[n])
        _assert_trees_equal(bx, bf, f"l1 n={n}")


def test_feature_arm_multiclass_k3(meshes):
    rng = np.random.Generator(np.random.Philox(21))
    X = rng.normal(size=(4096, 10)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32) + (X[:, 2] > 1) * 1.0
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(objective="multiclass", num_class=3, num_trees=2,
                num_leaves=8, max_depth=3, growth="depthwise", max_bins=32)
    for n in (8,):
        bf = _train(dict(base, hist_reduce="fused"), ds, meshes[n])
        bx = _train(dict(base, hist_reduce="feature"), ds, meshes[n])
        _assert_trees_equal(bx, bf, f"multiclass n={n}")


def test_feature_arm_all_padding_shards(meshes):
    """F=10 over 8 shards: Fs=2, Fpad=16 — shards 5..7 own ONLY padding
    and must contribute harmless -inf records."""
    rng = np.random.Generator(np.random.Philox(29))
    X = rng.normal(size=(2048, 10)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 3] > 0).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(objective="binary", num_trees=3, num_leaves=8, max_depth=3,
                growth="depthwise", max_bins=32)
    bf = _train(dict(base, hist_reduce="fused"), ds, meshes[8])
    bx = _train(dict(base, hist_reduce="feature"), ds, meshes[8])
    _assert_trees_equal(bx, bf, "all-padding shards")


# ---------------------------------------------------------------------------
# accounting: the ≥4x wide-shape payload cut, and the auto gate

def test_comm_stats_wide_shape_payload_ratio():
    """Acceptance: at F=2000, B=256, 8 shards the feature arm's accounted
    per-iteration collective payload (the same accounting the jaxpr
    census verifies call-for-call) is ≥ 4x below the fused arm's."""
    from dryad_tpu.engine.train import _comm_stats

    base = dict(objective="binary", num_trees=1, num_leaves=64, max_depth=6,
                growth="depthwise", max_bins=256)
    fused = _comm_stats(make_params(dict(base, hist_reduce="fused")),
                        2000, 256, 1, 8, num_rows=400_000,
                        padded_rows=400_000, platform="tpu")
    feat = _comm_stats(make_params(dict(base, hist_reduce="feature")),
                       2000, 256, 1, 8, num_rows=400_000,
                       padded_rows=400_000, platform="tpu")
    assert fused["hist_reduce"] == "fused"
    assert feat["hist_reduce"] == "feature"
    ratio = (fused["collective_bytes_per_iter"]
             / feat["collective_bytes_per_iter"])
    assert ratio >= 4.0, ratio
    # the arm swaps the level psums for reduce-scatter + combine gathers
    assert feat["psum_calls_per_iter"] == 1            # the root only
    assert feat["reduce_scatter_calls_per_iter"] == 6  # one per level
    assert feat["all_gather_calls_per_iter"] == 6


def test_hist_reduce_auto_gate():
    """auto = feature iff wide AND sharded — never a function of rows."""
    from dryad_tpu.config import hist_reduce_resolved

    p = make_params(dict(objective="binary", growth="depthwise",
                         max_depth=6, num_leaves=64, max_bins=256))
    assert p.hist_reduce == "auto"
    assert hist_reduce_resolved(p, 2000, 256, 8) == "feature"
    assert hist_reduce_resolved(p, 2000, 256, 1) == "fused"   # unsharded
    assert hist_reduce_resolved(p, 28, 256, 8) == "fused"     # narrow
    pf = p.replace(hist_reduce="feature")
    assert hist_reduce_resolved(pf, 28, 256, 1) == "feature"  # explicit
    with pytest.raises(ValueError):
        p.replace(hist_reduce="bogus")


def test_comm_gauges_exported():
    from dryad_tpu.obs.comm import export_comm_stats
    from dryad_tpu.obs.registry import Registry

    comm = {"n_shards": 8, "hist_reduce": "feature",
            "psum_bytes_per_iter": 3072,
            "reduce_scatter_bytes_per_iter": 86016,
            "all_gather_bytes_per_iter": 14336,
            "collective_bytes_per_iter": 103424,
            "collective_calls_per_iter": 15}
    reg = Registry(enabled=True)
    n = export_comm_stats(comm, growth="depthwise", registry=reg)
    assert n == 5
    text = reg.exposition()
    assert "dryad_comm_psum_bytes_per_iter" in text
    assert "dryad_comm_collective_calls_per_iter" in text
    assert 'arm="feature"' in text
    # zero-cost when disabled
    off = Registry(enabled=False)
    assert export_comm_stats(comm, growth="depthwise", registry=off) == 0
