"""Device-side eval metrics vs the canonical numpy oracle (fp32 tolerance)."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu import metrics as M
from dryad_tpu.metrics import device as D


@pytest.fixture(scope="module")
def scores():
    rng = np.random.default_rng(31)
    n = 20_000
    y = (rng.random(n) < 0.4).astype(np.float32)
    s = (y * 0.8 + rng.normal(size=n) * 1.2).astype(np.float32)
    # heavy ties: quantize a third of the scores
    s[: n // 3] = np.round(s[: n // 3] * 4) / 4
    return y, s


def test_auc_matches_with_ties(scores):
    import jax.numpy as jnp

    y, s = scores
    got = float(D.auc_device(jnp.asarray(y), jnp.asarray(s)))
    want = M.auc(y, s)
    assert abs(got - want) < 1e-5


def test_auc_degenerate_is_nan():
    import jax.numpy as jnp

    y = np.ones(64, np.float32)
    s = np.linspace(0, 1, 64, dtype=np.float32)
    assert np.isnan(float(D.auc_device(jnp.asarray(y), jnp.asarray(s))))


def test_scalar_metrics_match(scores):
    import jax.numpy as jnp

    y, s = scores
    yd, sd = jnp.asarray(y), jnp.asarray(s)
    assert abs(float(D.binary_logloss_device(yd, sd))
               - M.binary_logloss(y, 1 / (1 + np.exp(-s)))) < 1e-5
    assert abs(float(D.rmse_device(yd, sd)) - M.rmse(y, s)) < 1e-5
    assert abs(float(D.mse_device(yd, sd)) - M.mse(y, s)) < 1e-4
    assert abs(float(D.mae_device(yd, sd)) - M.mae(y, s)) < 1e-5
    want_err = 1.0 - float((y.astype(np.int64) == (s > 0)).mean())
    assert abs(float(D.error_device(yd, sd)) - want_err) < 1e-6


def test_binary_logloss_saturated_scores():
    """Scores beyond f32 sigmoid saturation (~|s|>17) must stay finite and
    match the numpy oracle's eps-clipped values."""
    import jax.numpy as jnp

    y = np.array([1, 0, 1, 0], np.float32)
    s = np.array([40.0, -40.0, -40.0, 40.0], np.float32)  # 2 perfect, 2 worst
    got = float(D.binary_logloss_device(jnp.asarray(y), jnp.asarray(s)))
    want = M.binary_logloss(y, 1 / (1 + np.exp(-s.astype(np.float64))))
    assert np.isfinite(got)
    # the oracle's f64 clip boundary (log(1 - (1-1e-15))) carries its own
    # rounding; the stable-form cap agrees to ~1e-5 relative, not bitwise
    assert abs(got - want) < 1e-3


def test_multi_logloss_matches():
    import jax.numpy as jnp

    rng = np.random.default_rng(37)
    n, K = 5000, 7
    y = rng.integers(0, K, n).astype(np.float32)
    s = rng.normal(size=(n, K)).astype(np.float32)
    e = np.exp(s - s.max(axis=1, keepdims=True))
    want = M.multi_logloss(y, e / e.sum(axis=1, keepdims=True))
    got = float(D.multi_logloss_device(jnp.asarray(y), jnp.asarray(s)))
    assert abs(got - want) < 1e-5


def test_ndcg_matches_ragged_queries():
    import jax.numpy as jnp

    rng = np.random.default_rng(41)
    sizes = rng.integers(1, 40, 300)
    qoff = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
    n = int(qoff[-1])
    y = rng.integers(0, 5, n).astype(np.float32)
    s = (y + rng.normal(size=n) * 2).astype(np.float32)
    want = M.ndcg_at_k(y, s, qoff, k=10)
    qids = jnp.asarray(D._pad_queries(qoff)[0])
    got = float(D.ndcg_device(jnp.asarray(y), jnp.asarray(s), qids, 10))
    assert abs(got - want) < 1e-5


def test_checkpointer_keeps_deferred_eval_and_resume_merges_history(tmp_path):
    """A checkpointer must not force per-eval fetches: deferred evals flush
    at due() boundaries, and a resumed run merges the prior segment's
    history so it matches the uninterrupted run."""
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(5000, seed=47)
    ds = dryad.Dataset(X[:4000], y[:4000], max_bins=32)
    dv = ds.bind(X[4000:], y[4000:])
    p = dict(objective="binary", num_trees=12, num_leaves=7, max_bins=32)
    full = dryad.train(p, ds, valid_sets=[dv], backend="tpu")
    # interrupted: checkpoint every 5, resume from iteration 5 or 10
    d = str(tmp_path / "ck")
    dryad.train(dict(p, num_trees=7), ds, valid_sets=[dv], backend="tpu",
                checkpoint_dir=d, checkpoint_every=5)
    b = dryad.train(p, ds, valid_sets=[dv], backend="tpu",
                    checkpoint_dir=d, checkpoint_every=5, resume=True)
    want = full.train_state["eval_history"]["valid_auc"]
    got = b.train_state["eval_history"]["valid_auc"]
    assert [it for it, _ in got] == [it for it, _ in want] == list(range(12))
    np.testing.assert_allclose([v for _, v in got], [v for _, v in want],
                               rtol=1e-6)
    assert b.best_iteration == full.best_iteration


def test_trainer_uses_device_eval_and_sets_best_iteration():
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(8000, seed=43)
    ds = dryad.Dataset(X[:6000], y[:6000], max_bins=64)
    dv = ds.bind(X[6000:], y[6000:])
    p = dict(objective="binary", num_trees=20, num_leaves=15, max_bins=64,
             learning_rate=0.4)
    # no callback / no early stopping / no checkpointer: the deferred path
    b = dryad.train(p, ds, valid_sets=[dv], backend="tpu")
    b_cpu = dryad.train(p, ds, valid_sets=[dv], backend="cpu")
    assert b.best_iteration > 0
    assert b.best_iteration == b_cpu.best_iteration
    # the deferred path surfaces the full eval history on the booster
    hist = b.train_state["eval_history"]["valid_auc"]
    assert len(hist) == 20 and hist[0][0] == 0
    assert abs(hist[b.best_iteration - 1][1] - b.train_state["best_value"]) < 1e-7
    # synchronous path (callback present) agrees with the deferred path
    seen = []
    b_sync = dryad.train(p, ds, valid_sets=[dv], backend="tpu",
                         callback=lambda it, info: seen.append(info))
    assert b_sync.best_iteration == b.best_iteration
    assert any("valid_auc" in s for s in seen)


def test_ndcg_skewed_groups_fall_back_to_host():
    """A skewed ranking valid set (many tiny queries + one huge one) must
    not densify a (Q, S) plan with Q*S >> N — make_evaluator falls back to
    the host-side NDCG (one fetch per eval, no memory blow-up) and the
    value matches the oracle."""
    import dryad_tpu as dryad
    from dryad_tpu.metrics import ndcg_at_k
    from dryad_tpu.metrics.device import make_evaluator

    rng = np.random.default_rng(5)
    # 60k singleton queries + one 12k-row group: Q*S ~ 7.2e8 >> 8*N
    sizes = np.concatenate([np.ones(60_000, np.int64), [12_000]])
    N = int(sizes.sum())
    y = rng.integers(0, 3, size=N).astype(np.float32)
    X = rng.normal(size=(N, 3)).astype(np.float32)
    ds = dryad.Dataset(X, y, group=sizes)
    name, higher, fn = make_evaluator("lambdarank", "ndcg", ds, 10)
    assert name == "ndcg" and higher
    import jax.numpy as jnp

    score = rng.normal(size=N).astype(np.float32)
    got = float(fn(jnp.asarray(score[:, None])))
    want = ndcg_at_k(y, score, ds.query_offsets, 10)
    assert abs(got - want) < 1e-6


def _split_higgs(n=24_000, seed=11):
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(n, seed=seed)
    cut = int(n * 0.8)
    tr = dryad.Dataset(X[:cut], y[:cut])
    va = dryad.Dataset(X[cut:], y[cut:], mapper=tr.mapper)
    return tr, va


def test_chunked_valid_eval_matches_per_iteration_values():
    """The chunked trainer evaluates INSIDE its device program; the values
    it defers must equal what the per-iteration sync path (callback forces
    a per-eval fetch) reports for the same run."""
    from dryad_tpu.config import make_params
    from dryad_tpu.engine.train import train_device

    tr, va = _split_higgs()
    params = make_params(dict(objective="binary", num_trees=8, num_leaves=15,
                              max_depth=4, growth="depthwise"))
    # deferred (chunked): no callback, no early stopping
    b = train_device(params, tr, valid=va)
    hist = b.train_state["eval_history"]["valid_auc"]
    assert [it for it, _ in hist] == list(range(8))

    # sync path: a callback forces the per-eval fetch with the same model
    seen = {}
    train_device(params, tr, valid=va,
                 callback=lambda it, info: seen.update(
                     {it: info.get("valid_auc")}))
    for it, v in hist:
        assert seen[it] is not None
        # same math, different fusion shape (documented tolerance)
        np.testing.assert_allclose(v, seen[it], rtol=2e-5, atol=2e-6)


@pytest.mark.slow  # r19 tier-1 re-budget: 60 s+ on the CI container;
# the chunk-boundary invariant stays pinned by the valid-eval and
# best-iteration tests below, which run every tier-1.
def test_chunked_early_stop_matches_per_iteration(monkeypatch):
    """With eval_period >= 2 the chunked path ends chunks on eval
    boundaries, so early stopping halts at the SAME iteration — compared
    against the per-iteration path forced via a host-only evaluator mark
    (host-only metrics are never chunked)."""
    import dryad_tpu.metrics.device as dev_metrics
    from dryad_tpu.config import make_params
    from dryad_tpu.engine.train import train_device

    tr, va = _split_higgs(seed=13)
    params = make_params(dict(objective="binary", num_trees=40,
                              num_leaves=7, max_depth=3,
                              growth="depthwise", learning_rate=1.5,
                              early_stopping_rounds=2, eval_period=2))
    b_chunk = train_device(params, tr, valid=va)
    assert b_chunk.num_iterations < 40, "fixture must actually early-stop"

    real = dev_metrics.make_evaluator

    def host_marked(*a, **k):
        name, higher, fn = real(*a, **k)
        fn.host_only = True    # the chunk gate refuses host-only metrics
        return name, higher, fn

    monkeypatch.setattr(dev_metrics, "make_evaluator", host_marked)
    b_iter = train_device(params, tr, valid=va)
    assert b_iter.num_iterations == b_chunk.num_iterations
    assert b_iter.best_iteration == b_chunk.best_iteration
