"""Pallas histogram kernel vs the XLA one-hot matmul oracle (SURVEY.md §4:
Pallas interpret-mode checks stand in for GPU sanitizers)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dryad_tpu.engine.histogram import build_hist, build_hist_segmented
from dryad_tpu.engine.pallas_hist import (
    _split3,
    build_hist_pallas,
    build_hist_segmented_pallas,
)


def _data(n=1000, f=5, b=16, seed=0):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, b, size=(n, f), dtype=np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = (np.abs(rng.normal(size=n)) + 0.1).astype(np.float32)
    return jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h)


def test_split3_reconstructs_f32():
    rng = np.random.default_rng(1)
    x = jnp.asarray(
        np.concatenate([
            rng.normal(size=1000) * 10.0 ** rng.integers(-20, 20, size=1000),
            [0.0, 1.0, -1.0, 1e-30, 1e30],
        ]).astype(np.float32)
    )
    hi, mid, lo = _split3(x)
    rec = hi.astype(jnp.float32) + mid.astype(jnp.float32) + lo.astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), rtol=1e-7)


def test_masked_hist_matches_xla():
    Xb, g, h = _data()
    mask = jnp.asarray(np.random.default_rng(2).random(1000) < 0.7)
    ref = build_hist(Xb, g, h, mask, 16)
    out = build_hist_pallas(Xb, g, h, mask, 16)
    assert out.shape == ref.shape == (3, 5, 16)
    np.testing.assert_array_equal(np.asarray(out[2]), np.asarray(ref[2]))  # counts exact
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-4)


def test_segmented_hist_matches_xla():
    Xb, g, h = _data(n=3000, f=7, b=32, seed=3)
    P = 6
    sel_np = np.random.default_rng(4).integers(0, P + 1, size=3000)  # P = dropped
    sel = jnp.asarray(sel_np.astype(np.int32))
    ref = build_hist_segmented(Xb, g, h, sel, P, 32)
    out = build_hist_segmented_pallas(Xb, g, h, sel, P, 32)
    assert out.shape == ref.shape == (P, 3, 7, 32)
    np.testing.assert_array_equal(np.asarray(out[:, 2]), np.asarray(ref[:, 2]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-4)


def test_segmented_records_path_bitwise():
    """records= (per-tree fused-gather table) must reproduce the plain path
    BITWISE, including F not divisible by 4 (the record rows pad to whole
    int32 words) and uint16 bins (2-byte units)."""
    from dryad_tpu.engine.pallas_hist import make_records

    for f, b, dtype in ((6, 32, np.uint8), (9, 32, np.uint8),
                        (5, 300, np.uint16)):
        rng = np.random.default_rng(f)
        Xb = jnp.asarray(rng.integers(0, b, size=(3000, f)).astype(dtype))
        g = jnp.asarray(rng.normal(size=3000).astype(np.float32))
        h = jnp.asarray(rng.uniform(0.1, 1.0, size=3000).astype(np.float32))
        sel = jnp.asarray(rng.integers(0, 7, size=3000).astype(np.int32))
        plain = build_hist_segmented_pallas(Xb, g, h, sel, 6, b)
        rec = build_hist_segmented_pallas(Xb, g, h, sel, 6, b,
                                          records=make_records(Xb, g, h))
        np.testing.assert_array_equal(np.asarray(plain), np.asarray(rec))


def test_segmented_hist_empty_and_single_leaf():
    Xb, g, h = _data(n=500, f=3, b=8, seed=5)
    P = 4
    sel = jnp.asarray(np.full(500, 2, np.int32))  # all rows in leaf 2
    out = np.asarray(build_hist_segmented_pallas(Xb, g, h, sel, P, 8))
    assert out.shape == (P, 3, 3, 8)
    np.testing.assert_array_equal(out[[0, 1, 3]], 0.0)  # empty leaves are zero
    assert out[2, 2].sum(axis=1) == pytest.approx(500)


def test_wide_features_blocking():
    # force multiple feature blocks: F*B > lane budget
    Xb, g, h = _data(n=600, f=40, b=128, seed=6)
    mask = jnp.ones((600,), bool)
    ref = build_hist(Xb, g, h, mask, 128)
    out = build_hist_pallas(Xb, g, h, mask, 128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-4)


def test_violated_rows_bound_degrades_gracefully():
    """A caller-supplied rows_bound that undercounts must never produce
    uninitialized output blocks — rows drop, histograms stay finite."""
    Xb, g, h = _data(n=4000, f=4, b=16, seed=7)
    sel = jnp.asarray((np.arange(4000) % 4).astype(np.int32))  # ALL rows selected
    out = np.asarray(build_hist_segmented_pallas(
        Xb, g, h, sel, 4, 16, rows_bound=1000))
    assert np.isfinite(out).all()
    # rows beyond the squeezed allotment really drop: strictly fewer counted
    # than the 4000 selected (count plane repeats per feature; sum one)
    assert 0 < out[:, 2, 0, :].sum() < 4000


def test_train_with_pallas_backend_matches_xla_trees():
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(4000, seed=9)
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(objective="binary", num_trees=5, num_leaves=15, max_bins=32,
                growth="depthwise", max_depth=4)
    b_xla = dryad.train(dict(base, hist_backend="xla"), ds, backend="tpu")
    b_pl = dryad.train(dict(base, hist_backend="pallas"), ds, backend="tpu")
    np.testing.assert_array_equal(b_xla.feature, b_pl.feature)
    np.testing.assert_array_equal(b_xla.threshold, b_pl.threshold)
    np.testing.assert_allclose(b_xla.value, b_pl.value, atol=1e-4)


def test_train_pallas_with_bagging_matches_xla_trees():
    # exercises the segmented pallas path with an out-of-bag slot.
    # seed 13 (was 11): the pallas and xla builders group f32 partial
    # sums differently, so the structural-equality pin needs a tie-free
    # fixture — seed 11 carries one near-tie gain that the 0.4.x
    # container's XLA resolves the other way (documented tolerance class)
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(4000, seed=13)
    ds = dryad.Dataset(X, y, max_bins=32)
    base = dict(objective="binary", num_trees=4, num_leaves=15, max_bins=32,
                growth="depthwise", max_depth=4, subsample=0.7, seed=5,
                min_data_in_leaf=5)
    b_xla = dryad.train(dict(base, hist_backend="xla"), ds, backend="tpu")
    b_pl = dryad.train(dict(base, hist_backend="pallas"), ds, backend="tpu")
    np.testing.assert_array_equal(b_xla.feature, b_pl.feature)
    np.testing.assert_array_equal(b_xla.threshold, b_pl.threshold)
    np.testing.assert_allclose(b_xla.value, b_pl.value, atol=1e-4)


def test_leafwise_pallas_matches_xla_trees():
    # leaf-wise growth routed through the masked Pallas histogram
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(4000, seed=13)
    ds = dryad.Dataset(X, y, max_bins=32)
    # explicit max_depth bounds the wired expansion's run capacity (r10:
    # the pallas arm rides the layout-wired path; at the auto-policy's
    # depth 8 its 2^D-run buffer is pathological under interpret mode —
    # ~130 s for this 4k-row fixture vs ~25 s at depth 6, same coverage)
    base = dict(objective="binary", num_trees=2, num_leaves=15, max_bins=32,
                max_depth=6)
    b_xla = dryad.train(dict(base, hist_backend="xla"), ds, backend="tpu")
    b_pl = dryad.train(dict(base, hist_backend="pallas"), ds, backend="tpu")
    np.testing.assert_array_equal(b_xla.feature, b_pl.feature)
    np.testing.assert_array_equal(b_xla.threshold, b_pl.threshold)
    np.testing.assert_allclose(b_xla.value, b_pl.value, atol=1e-4)


def test_natural_order_multislot_matches_oracle():
    """build_hist_nat (no sort/no gather shallow-level pass) vs the XLA
    segmented oracle: counts exact, sums to fp tolerance; drop sentinel
    and padded tail rows contribute nothing."""
    from dryad_tpu.engine.pallas_hist import (
        _NAT_DROP, build_hist_nat, natural_tiles,
    )

    rng = np.random.default_rng(9)
    N, F, B, P = 3000, 7, 32, 6
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F)).astype(np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    sel_np = rng.integers(0, P + 3, size=N).astype(np.int32)
    sel_np = np.where(sel_np < P, sel_np, _NAT_DROP)
    sel = jnp.asarray(sel_np)
    got = np.asarray(build_hist_nat(natural_tiles(Xb, B), g, h, sel,
                                    total_bins=B, num_features=F))
    want = np.asarray(build_hist_segmented(
        Xb, g, h, jnp.minimum(sel, P), P, B, backend="xla"))
    np.testing.assert_array_equal(got[:P, 2], want[:, 2])
    np.testing.assert_allclose(got[:P], want, rtol=1e-5, atol=1e-4)
    assert np.all(got[P:] == 0)   # unused slots stay empty


def test_tile_plan_aligned_matches_tile_plan():
    """The pad-injected aligned sort must reproduce the generic plan
    VALUE-IDENTICALLY (buf, tile_leaf, tile_first) — empty slots, dropped
    rows, a full-coverage slot, and a rows_bound all exercised — so every
    downstream histogram program is unchanged."""
    from dryad_tpu.engine.pallas_hist import (
        _TILE_ROWS, tile_plan, tile_plan_aligned,
    )

    rng = np.random.default_rng(21)
    T = _TILE_ROWS
    for N, P, bound in ((3000, 6, None), (5000, 4, 2501), (T + 3, 3, None)):
        sel_np = rng.integers(0, P + 2, size=N).astype(np.int32)
        sel_np = np.where(sel_np <= P, sel_np, P)   # P = dropped
        sel_np[sel_np == 1] = 0                     # slot 1 empty
        if bound is not None:
            # keep the selection under the claimed bound
            keep = np.cumsum(sel_np < P) <= bound
            sel_np = np.where(keep, sel_np, P)
            total = (sel_np < P).sum()
            assert total <= bound
        counts = np.bincount(sel_np[sel_np < P], minlength=P)[:P]
        sel = jnp.asarray(sel_np)
        cnt = jnp.asarray(counts.astype(np.int32))
        b0, l0, f0 = tile_plan(sel, N, P, T, rows_bound=bound)
        b1, l1, f1 = tile_plan_aligned(sel, cnt, N, P, T, rows_bound=bound)
        np.testing.assert_array_equal(np.asarray(b0), np.asarray(b1))
        np.testing.assert_array_equal(np.asarray(l0), np.asarray(l1))
        np.testing.assert_array_equal(np.asarray(f0), np.asarray(f1))


def test_segmented_sel_counts_bitwise():
    """sel_counts= (the aligned-plan fast path) must reproduce the generic
    plan path BITWISE, with and without a records table."""
    from dryad_tpu.engine.pallas_hist import make_records

    rng = np.random.default_rng(22)
    N, F, B, P = 4000, 6, 32, 5
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F)).astype(np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    sel_np = rng.integers(0, P + 1, size=N).astype(np.int32)
    sel = jnp.asarray(sel_np)
    cnt = jnp.asarray(np.bincount(sel_np[sel_np < P],
                                  minlength=P)[:P].astype(np.int32))
    plain = build_hist_segmented_pallas(Xb, g, h, sel, P, B)
    fast = build_hist_segmented_pallas(Xb, g, h, sel, P, B, sel_counts=cnt)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(fast))
    rec = make_records(Xb, g, h)
    plain_r = build_hist_segmented_pallas(Xb, g, h, sel, P, B, records=rec)
    fast_r = build_hist_segmented_pallas(Xb, g, h, sel, P, B, records=rec,
                                         sel_counts=cnt)
    np.testing.assert_array_equal(np.asarray(plain_r), np.asarray(fast_r))
