"""sklearn-style estimator surface."""

import numpy as np

from dryad_tpu.datasets import covertype_like, higgs_like, mslr_like
from dryad_tpu.metrics import auc, ndcg_at_k
from dryad_tpu.sklearn import DryadClassifier, DryadRanker, DryadRegressor

FAST = dict(num_trees=20, num_leaves=15, max_bins=64, backend="cpu")


def test_classifier_binary():
    X, y = higgs_like(4000, seed=31)
    clf = DryadClassifier(**FAST).fit(X[:3000], y[:3000])
    proba = clf.predict_proba(X[3000:])
    assert proba.shape == (1000, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
    assert auc(y[3000:], proba[:, 1]) > 0.62
    pred = clf.predict(X[3000:])
    assert set(np.unique(pred)) <= set(clf.classes_)
    assert clf.feature_importances_.shape == (X.shape[1],)


def test_classifier_multiclass_with_label_remap():
    X, y = covertype_like(4000, seed=33)
    y_lab = y * 10 + 3                       # non-contiguous labels
    clf = DryadClassifier(**FAST).fit(X, y_lab)
    proba = clf.predict_proba(X[:100])
    assert proba.shape == (100, 7)
    pred = clf.predict(X[:500])
    assert set(np.unique(pred)) <= set(np.unique(y_lab))
    assert (pred == y_lab[:500]).mean() > 0.5


def test_regressor_with_eval_set():
    rng = np.random.default_rng(35)
    X = rng.normal(size=(3000, 10)).astype(np.float32)
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + rng.normal(scale=0.1, size=3000)
    reg = DryadRegressor(early_stopping_rounds=5, **FAST)
    reg.fit(X[:2500], y[:2500], eval_set=(X[2500:], y[2500:]))
    pred = reg.predict(X[2500:])
    mse = float(np.mean((pred - y[2500:]) ** 2))
    assert mse < np.var(y) * 0.5
    assert reg.best_iteration_ > 0


def test_ranker():
    X, y, group = mslr_like(num_queries=80, seed=37)
    rk = DryadRanker(**FAST).fit(X, y, group=group)
    scores = rk.predict(X)
    qoff = np.concatenate([[0], np.cumsum(group)])
    n = ndcg_at_k(y, scores, qoff, 10)
    base = ndcg_at_k(y, np.zeros_like(scores), qoff, 10)
    assert n > base


def test_get_set_params_roundtrip():
    clf = DryadClassifier(num_trees=7, learning_rate=0.3)
    p = clf.get_params()
    assert p["num_trees"] == 7 and p["learning_rate"] == 0.3
    clf.set_params(num_trees=9, num_class=3)
    assert clf.num_trees == 9 and clf.extra_params["num_class"] == 3
