"""dryadlint (dryad_tpu/analysis layer 1): every rule must (a) pass on the
shipped tree, (b) FAIL on a seeded violation — the mutation check: a rule
that cannot catch its own violation class is a green light painted on a
wall — and (c) honor the waiver syntax, reasons mandatory.

Mutation fixtures patch REAL repo files in memory (SourceTree overrides),
so the checks exercise the exact file set CI lints, not toy snippets.
"""

from __future__ import annotations

import textwrap

import pytest

from dryad_tpu.analysis.lint import SourceTree, parse_waivers, run_lint
from dryad_tpu.analysis.lint import LintReport

ROOT = __file__.rsplit("/tests/", 1)[0]


def _violations(rule, overrides=None):
    report = run_lint(ROOT, rule_names=[rule], overrides=overrides)
    return report


def _rule_hits(report, rule):
    return [v for v in report.violations if v.rule == rule]


# ---------------------------------------------------------------------------
# the shipped tree is clean

def test_shipped_tree_clean_all_rules():
    report = run_lint(ROOT)
    assert report.ok, "\n".join(v.format() for v in report.violations)
    # the waiver budget is intentional and visible — additions are a
    # review event, not background noise; since r15 the bound is the
    # COMMITTED ratchet the CLI enforces (goldens/waiver_budget.json),
    # so the test and CI can never disagree about it
    import json

    with open(f"{ROOT}/dryad_tpu/analysis/goldens/waiver_budget.json") as f:
        budget = json.load(f)["waivers"]
    assert len(report.waived) <= budget


# ---------------------------------------------------------------------------
# wired-grower-sort

def test_wired_grower_sort_seeded_tile_plan():
    src = SourceTree(ROOT).read("dryad_tpu/engine/levelwise.py")
    bad = src + "\n_resurrected = tile_plan\n"
    rep = _violations("wired-grower-sort",
                      {"dryad_tpu/engine/levelwise.py": bad})
    assert any("tile_plan" in v.message for v in
               _rule_hits(rep, "wired-grower-sort"))


def test_wired_grower_sort_seeded_row_sort():
    src = SourceTree(ROOT).read("dryad_tpu/engine/leafwise_fast.py")
    bad = src + ("\ndef _sneaky(rows):\n"
                 "    return jnp.argsort(rows)\n")
    rep = _violations("wired-grower-sort",
                      {"dryad_tpu/engine/leafwise_fast.py": bad})
    assert _rule_hits(rep, "wired-grower-sort")


def test_wired_grower_existing_slot_argsort_is_waived():
    rep = _violations("wired-grower-sort")
    assert not rep.violations
    assert any(w.rule == "wired-grower-sort" for _, w in rep.waived), \
        "the (L,)-slot gain argsort must be waived, not invisible"


# ---------------------------------------------------------------------------
# no-block-until-ready

def test_block_until_ready_seeded_in_serve():
    src = SourceTree(ROOT).read("dryad_tpu/serve/metrics.py")
    bad = src + "\ndef _wait(x):\n    return x.block_until_ready()\n"
    rep = _violations("no-block-until-ready",
                      {"dryad_tpu/serve/metrics.py": bad})
    assert _rule_hits(rep, "no-block-until-ready")


def test_block_until_ready_seeded_in_obs():
    src = SourceTree(ROOT).read("dryad_tpu/obs/registry.py")
    bad = src + "\ndef _wait(x):\n    x.block_until_ready()\n"
    rep = _violations("no-block-until-ready",
                      {"dryad_tpu/obs/registry.py": bad})
    assert _rule_hits(rep, "no-block-until-ready")


# ---------------------------------------------------------------------------
# batcher-device-fetch

@pytest.mark.parametrize("snippet", [
    "import jax\n",
    "from jax import numpy as jnp\n",
    "def _f(x):\n    return np.asarray(x)\n",
    "def _f(x):\n    return jax_dev.device_get(x)\n",
])
def test_batcher_fetch_seeded(snippet):
    src = SourceTree(ROOT).read("dryad_tpu/serve/batcher.py")
    rep = _violations("batcher-device-fetch",
                      {"dryad_tpu/serve/batcher.py": src + "\n" + snippet})
    assert _rule_hits(rep, "batcher-device-fetch")


# ---------------------------------------------------------------------------
# obs-jax-free (direct + transitive)

def test_obs_direct_jax_import_seeded():
    src = SourceTree(ROOT).read("dryad_tpu/obs/spans.py")
    rep = _violations("obs-jax-free",
                      {"dryad_tpu/obs/spans.py": src + "\nimport jax\n"})
    assert _rule_hits(rep, "obs-jax-free")


def test_obs_lazy_function_level_jax_import_also_banned():
    # obs is STRICTLY jax-free: even a lazy in-function import is flagged
    src = SourceTree(ROOT).read("dryad_tpu/obs/spans.py")
    bad = src + "\ndef _lazy():\n    import jax\n    return jax\n"
    rep = _violations("obs-jax-free", {"dryad_tpu/obs/spans.py": bad})
    assert _rule_hits(rep, "obs-jax-free")


# ---------------------------------------------------------------------------
# fleet-jax-free (direct + transitive, r14)

def test_fleet_direct_jax_import_seeded():
    src = SourceTree(ROOT).read("dryad_tpu/fleet/router.py")
    rep = _violations("fleet-jax-free",
                      {"dryad_tpu/fleet/router.py": src + "\nimport jax\n"})
    assert _rule_hits(rep, "fleet-jax-free")


def test_fleet_lazy_jax_import_also_banned():
    src = SourceTree(ROOT).read("dryad_tpu/fleet/supervisor.py")
    bad = src + "\ndef _lazy():\n    from jax import numpy\n    return numpy\n"
    rep = _violations("fleet-jax-free",
                      {"dryad_tpu/fleet/supervisor.py": bad})
    assert _rule_hits(rep, "fleet-jax-free")


def test_fleet_transitive_jax_import_seeded():
    # an innocent-looking module-level import of an engine helper pulls
    # jax into `import dryad_tpu.fleet` — the chain must be reported
    src = SourceTree(ROOT).read("dryad_tpu/fleet/replica.py")
    bad = "from dryad_tpu.engine.jax_compat import shard_map\n" + src
    rep = _violations("fleet-jax-free",
                      {"dryad_tpu/fleet/replica.py": bad})
    hits = _rule_hits(rep, "fleet-jax-free")
    assert hits and any("transitive" in v.message for v in hits)


def test_fleet_device_fetch_shape_banned():
    src = SourceTree(ROOT).read("dryad_tpu/fleet/router.py")
    bad = src + "\ndef _peek(x):\n    return x.addressable_data(0)\n"
    rep = _violations("fleet-jax-free", {"dryad_tpu/fleet/router.py": bad})
    assert _rule_hits(rep, "fleet-jax-free")


def test_block_until_ready_seeded_in_fleet():
    # the real-fetch discipline covers fleet throttles like serve's
    src = SourceTree(ROOT).read("dryad_tpu/fleet/supervisor.py")
    bad = src + "\ndef _wait(x):\n    return x.block_until_ready()\n"
    rep = _violations("no-block-until-ready",
                      {"dryad_tpu/fleet/supervisor.py": bad})
    assert _rule_hits(rep, "no-block-until-ready")


def test_obs_transitive_jax_import_seeded():
    # registry.py -> engine.jax_compat -> jax: no obs file mentions jax,
    # only the import-graph walk can see it (the r11 upgrade over grep)
    src = SourceTree(ROOT).read("dryad_tpu/obs/registry.py")
    bad = ("from dryad_tpu.engine.jax_compat import shard_map  # innocent\n"
           + src)
    rep = _violations("obs-jax-free", {"dryad_tpu/obs/registry.py": bad})
    hits = _rule_hits(rep, "obs-jax-free")
    assert any("transitive" in v.message for v in hits), \
        [v.message for v in hits]


def test_obs_transitive_through_new_internal_module():
    # two hops through a module that itself looks harmless
    helper = "import jax\n\ndef now():\n    return 0.0\n"
    src = SourceTree(ROOT).read("dryad_tpu/obs/spans.py")
    bad = "from dryad_tpu._timeutil import now\n" + src
    rep = _violations("obs-jax-free", {
        "dryad_tpu/_timeutil.py": helper,
        "dryad_tpu/obs/spans.py": bad,
    })
    assert any("transitive" in v.message
               for v in _rule_hits(rep, "obs-jax-free"))


def test_obs_clean_tree_has_no_transitive_jax():
    rep = _violations("obs-jax-free")
    assert not rep.violations


# ---------------------------------------------------------------------------
# jit-closure-constant

_CLOSURE_BAD = textwrap.dedent("""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def run(n):
        big = np.zeros((n,), np.float32)

        @jax.jit
        def f(x):
            return x + big

        return f
""")

_CLOSURE_OK = textwrap.dedent("""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def run(n):
        big = np.zeros((n,), np.float32)

        @jax.jit
        def f(x, big):
            return x + big

        return f(jnp.ones((n,)), big)
""")


def test_jit_closure_constant_seeded():
    rep = _violations("jit-closure-constant",
                      {"dryad_tpu/_fixture_jit.py": _CLOSURE_BAD})
    hits = _rule_hits(rep, "jit-closure-constant")
    assert hits and "big" in hits[0].message


def test_jit_closure_constant_lambda_and_partial_forms():
    src = textwrap.dedent("""
        import jax
        import jax.numpy as jnp
        from functools import partial

        def run(n):
            table = jnp.arange(n)
            f = jax.jit(lambda x: x + table)
            g = partial(jax.jit, static_argnames=())(lambda x: x * table)
            return f, g
    """)
    rep = _violations("jit-closure-constant",
                      {"dryad_tpu/_fixture_jit.py": src})
    assert len(_rule_hits(rep, "jit-closure-constant")) == 2


def test_jit_closure_constant_argument_passing_is_clean():
    rep = _violations("jit-closure-constant",
                      {"dryad_tpu/_fixture_jit.py": _CLOSURE_OK})
    assert not _rule_hits(rep, "jit-closure-constant")


def test_jit_closure_shipped_tree_clean():
    rep = _violations("jit-closure-constant")
    assert not rep.violations


# ---------------------------------------------------------------------------
# bench-real-fetch

_BENCH_BAD = textwrap.dedent("""
    import time
    import jax

    def probe(step, s0):
        prog = jax.jit(lambda s: jax.lax.fori_loop(0, 8, step, s))
        t0 = time.perf_counter()
        prog(s0)
        return time.perf_counter() - t0
""")


def test_bench_real_fetch_seeded():
    rep = _violations("bench-real-fetch",
                      {"scripts/_fixture_probe.py": _BENCH_BAD})
    assert _rule_hits(rep, "bench-real-fetch")


def test_bench_real_fetch_float_fetch_is_clean():
    ok = _BENCH_BAD.replace("prog(s0)\n", "float(prog(s0))\n")
    rep = _violations("bench-real-fetch",
                      {"scripts/_fixture_probe.py": ok})
    assert not _rule_hits(rep, "bench-real-fetch")


def test_bench_real_fetch_shipped_bench_is_clean():
    rep = _violations("bench-real-fetch")
    assert not rep.violations


# ---------------------------------------------------------------------------
# dead-perturbation

def test_dead_perturbation_seeded_astype():
    src = ("import jax.numpy as jnp\n"
           "def f(s, tab):\n"
           "    return tab[(s + 0.001).astype(jnp.int32)]\n")
    rep = _violations("dead-perturbation",
                      {"scripts/_fixture_perturb.py": src})
    assert _rule_hits(rep, "dead-perturbation")


def test_dead_perturbation_seeded_int_cast():
    src = ("import jax.numpy as jnp\n"
           "def f(s, tab):\n"
           "    return tab[jnp.int32(s + 1e-3)]\n")
    rep = _violations("dead-perturbation",
                      {"scripts/_fixture_perturb.py": src})
    assert _rule_hits(rep, "dead-perturbation")


def test_dead_perturbation_whole_unit_advance_is_clean():
    src = ("import jax.numpy as jnp\n"
           "def f(s, tab):\n"
           "    return tab[(s + 1.0).astype(jnp.int32)]\n")
    rep = _violations("dead-perturbation",
                      {"scripts/_fixture_perturb.py": src})
    assert not _rule_hits(rep, "dead-perturbation")


# ---------------------------------------------------------------------------
# waiver machinery

def test_waiver_suppresses_and_is_counted():
    src = SourceTree(ROOT).read("dryad_tpu/serve/metrics.py")
    bad = (src + "\ndef _wait(x):\n"
           "    # dryadlint: disable=no-block-until-ready -- fixture reason\n"
           "    return x.block_until_ready()\n")
    rep = _violations("no-block-until-ready",
                      {"dryad_tpu/serve/metrics.py": bad})
    assert not _rule_hits(rep, "no-block-until-ready")
    assert any(w.reason == "fixture reason" for _, w in rep.waived)


def test_waiver_without_reason_is_an_error():
    rep = LintReport()
    parse_waivers("x.py", "y = 1  # dryadlint: disable=some-rule\n", rep)
    assert rep.errors and "reason" in rep.errors[0]


def test_file_level_waiver_covers_whole_file():
    src = SourceTree(ROOT).read("dryad_tpu/serve/metrics.py")
    bad = ("# dryadlint: disable-file=no-block-until-ready -- fixture\n"
           + src + "\ndef _wait(x):\n    return x.block_until_ready()\n")
    rep = _violations("no-block-until-ready",
                      {"dryad_tpu/serve/metrics.py": bad})
    assert not _rule_hits(rep, "no-block-until-ready")
    assert rep.waived


def test_unknown_rule_name_rejected():
    with pytest.raises(ValueError):
        run_lint(ROOT, rule_names=["no-such-rule"])


# ---------------------------------------------------------------------------
# CLI

def test_cli_list_rules_and_lint_pass():
    from dryad_tpu.analysis.__main__ import main

    assert main(["--list-rules"]) == 0
    assert main(["--lint", "-q"]) == 0


def test_cli_lint_failure_exit_code(tmp_path):
    # a minimal bad tree: exit code 2 distinguishes lint from audit fails
    pkg = tmp_path / "dryad_tpu" / "obs"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text("import jax\n")
    from dryad_tpu.analysis.__main__ import main

    assert main(["--lint", "-q", "--root", str(tmp_path)]) == 2


def test_wired_grower_sort_seeded_aliased_import():
    """Review r11: `from ... import tile_plan as _tp` dodges a Name scan —
    the import itself must trip the rule."""
    src = SourceTree(ROOT).read("dryad_tpu/engine/levelwise.py")
    bad = src + "\nfrom dryad_tpu.engine.pallas_hist import tile_plan as _tp\n"
    rep = _violations("wired-grower-sort",
                      {"dryad_tpu/engine/levelwise.py": bad})
    assert any("import" in v.message for v in
               _rule_hits(rep, "wired-grower-sort"))


def test_wired_grower_sort_seeded_lexsort():
    src = SourceTree(ROOT).read("dryad_tpu/engine/levelwise.py")
    bad = src + "\ndef _sneaky(a, b):\n    return jnp.lexsort((a, b))\n"
    rep = _violations("wired-grower-sort",
                      {"dryad_tpu/engine/levelwise.py": bad})
    assert _rule_hits(rep, "wired-grower-sort")


def test_bench_real_fetch_host_scalar_float_is_not_a_fetch():
    """Review r11: float(K) converts a host scalar — it must NOT satisfy
    the fetch requirement (only conversions of call results count)."""
    bad = _BENCH_BAD.replace("return time.perf_counter() - t0\n",
                             "return (time.perf_counter() - t0) / float(8)\n")
    rep = _violations("bench-real-fetch",
                      {"scripts/_fixture_probe.py": bad})
    assert _rule_hits(rep, "bench-real-fetch")


def test_bench_real_fetch_float_of_call_result_name_counts():
    ok = _BENCH_BAD.replace("prog(s0)\n", "r = prog(s0)\n        float(r)\n")
    rep = _violations("bench-real-fetch",
                      {"scripts/_fixture_probe.py": ok})
    assert not _rule_hits(rep, "bench-real-fetch")


# ---------------------------------------------------------------------------
# introspect-compile-only (r12)

def test_introspect_cost_analysis_seeded_outside_introspect():
    src = SourceTree(ROOT).read("dryad_tpu/engine/levelwise.py")
    bad = src + ("\ndef _peek(fn, x):\n"
                 "    return fn.lower(x).cost_analysis()\n")
    rep = _violations("introspect-compile-only",
                      {"dryad_tpu/engine/levelwise.py": bad})
    assert any("cost_analysis" in v.message for v in
               _rule_hits(rep, "introspect-compile-only"))


def test_introspect_aot_compile_seeded_in_serve():
    src = SourceTree(ROOT).read("dryad_tpu/serve/cache.py")
    bad = src + ("\ndef _aot(fn, x):\n"
                 "    return fn.lower(x).compile()\n")
    rep = _violations("introspect-compile-only",
                      {"dryad_tpu/serve/cache.py": bad})
    assert any(".compile()" in v.message for v in
               _rule_hits(rep, "introspect-compile-only"))


def test_introspect_re_compile_with_args_is_clean():
    # re.compile(pattern) takes arguments — only the zero-arg AOT form is
    # the banned shape (resilience/faults.py uses re.compile today)
    src = SourceTree(ROOT).read("dryad_tpu/resilience/faults.py")
    bad = src + '\n_EXTRA_PAT = re.compile("x")\n'
    rep = _violations("introspect-compile-only",
                      {"dryad_tpu/resilience/faults.py": bad})
    assert not _rule_hits(rep, "introspect-compile-only")


def test_introspect_capture_inside_traced_body_seeded():
    src = SourceTree(ROOT).read("dryad_tpu/engine/levelwise.py")
    bad = src + (
        "\ndef _hot(n, s, fn):\n"
        "    def body(i, carry):\n"
        "        introspect.capture('train.chunk', ('k',), fn)\n"
        "        return carry\n"
        "    return jax.lax.fori_loop(0, n, body, s)\n")
    rep = _violations("introspect-compile-only",
                      {"dryad_tpu/engine/levelwise.py": bad})
    assert any("traced body" in v.message for v in
               _rule_hits(rep, "introspect-compile-only"))


def test_introspect_expensive_call_in_loop_inside_introspect_py():
    src = SourceTree(ROOT).read("dryad_tpu/engine/introspect.py")
    bad = src + ("\ndef _sweep(lowereds):\n"
                 "    out = []\n"
                 "    for low in lowereds:\n"
                 "        out.append(low.cost_analysis())\n"
                 "    return out\n")
    rep = _violations("introspect-compile-only",
                      {"dryad_tpu/engine/introspect.py": bad})
    assert _rule_hits(rep, "introspect-compile-only")


def test_introspect_shipped_tree_clean():
    rep = _violations("introspect-compile-only")
    assert not rep.violations, "\n".join(
        v.format() for v in rep.violations)


def test_obs_trends_is_covered_by_the_transitive_jax_walk():
    """The r12 satellite's explicit check: obs/trends.py rides the
    obs-jax-free TRANSITIVE walk — a jax import seeded there (directly or
    through an innocent-looking helper) must be flagged."""
    src = SourceTree(ROOT).read("dryad_tpu/obs/trends.py")
    rep = _violations("obs-jax-free",
                      {"dryad_tpu/obs/trends.py": src + "\nimport jax\n"})
    assert _rule_hits(rep, "obs-jax-free")
    helper = "import jax\n\ndef rev():\n    return 'x'\n"
    bad = "from dryad_tpu._gitutil import rev\n" + src
    rep = _violations("obs-jax-free", {
        "dryad_tpu/_gitutil.py": helper,
        "dryad_tpu/obs/trends.py": bad,
    })
    assert any("transitive" in v.message
               for v in _rule_hits(rep, "obs-jax-free"))


# ---------------------------------------------------------------------------
# unharnessed-timed-fori (r13)

_UNHARNESSED = textwrap.dedent("""
    import time
    import jax

    def my_loop_time(step, s0):
        prog = jax.jit(lambda s: jax.lax.fori_loop(0, 8, step, s))
        float(prog(s0))
        t0 = time.perf_counter()
        float(prog(s0))
        return time.perf_counter() - t0
""")


def test_unharnessed_fori_seeded_in_profile_script():
    """A hand-rolled timed fori in a living measurement script is a
    violation — the discipline lives in engine/probes.timed_fori."""
    rep = _violations("unharnessed-timed-fori",
                      {"scripts/profile_fixture.py": _UNHARNESSED})
    assert _rule_hits(rep, "unharnessed-timed-fori")


def test_unharnessed_fori_seeded_in_bench():
    src = SourceTree(ROOT).read("bench.py")
    rep = _violations("unharnessed-timed-fori",
                      {"bench.py": src + "\n" + _UNHARNESSED})
    assert _rule_hits(rep, "unharnessed-timed-fori")


def test_unharnessed_fori_harness_call_is_clean():
    ok = textwrap.dedent("""
        from dryad_tpu.engine.probes import timed_fori

        def measure(step, args):
            ms, spread = timed_fori(step, 3, 2, *args, label="x")
            return ms
    """)
    rep = _violations("unharnessed-timed-fori",
                      {"scripts/profile_fixture.py": ok})
    assert not _rule_hits(rep, "unharnessed-timed-fori")


def test_unharnessed_fori_shipped_tree_clean_and_exps_out_of_scope():
    """The migrated bench/profile/bench_* scripts are clean, and the
    archived exp_* one-shots (kept verbatim for provenance) are OUTSIDE
    the rule's targets rather than waived: the same seeded violation
    that fires in a profile script must produce zero hits in an exp_
    fixture."""
    rep = _violations("unharnessed-timed-fori")
    assert not rep.violations
    rep = _violations("unharnessed-timed-fori",
                      {"scripts/exp_fixture_probe.py": _UNHARNESSED})
    assert not _rule_hits(rep, "unharnessed-timed-fori")


def test_bench_real_fetch_covers_the_harness_module():
    """r13 rescope: engine/probes.py is in bench-real-fetch's targets —
    strip the harness's terminal fetches and the rule must fire."""
    src = SourceTree(ROOT).read("dryad_tpu/engine/probes.py")
    assert src.count("float(out[1])") == 3      # the three fetch sites
    bad = src.replace("float(out[1])", "out[1]")
    rep = _violations("bench-real-fetch",
                      {"dryad_tpu/engine/probes.py": bad})
    assert any(v.path == "dryad_tpu/engine/probes.py"
               for v in _rule_hits(rep, "bench-real-fetch"))


def test_dead_perturbation_covers_the_harness_module():
    src = SourceTree(ROOT).read("dryad_tpu/engine/probes.py")
    bad = src + ("\ndef _sneaky(s, tab):\n"
                 "    import jax.numpy as jnp\n"
                 "    return tab[(s + 0.001).astype(jnp.int32)]\n")
    rep = _violations("dead-perturbation",
                      {"dryad_tpu/engine/probes.py": bad})
    assert any(v.path == "dryad_tpu/engine/probes.py"
               for v in _rule_hits(rep, "dead-perturbation"))
