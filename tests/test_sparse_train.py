"""Criteo-shaped sparse end-to-end: CSR ingest + categorical splits,
CPU vs TPU tree parity (SURVEY.md §2 #3-4; BASELINE.json config 5)."""

import numpy as np

import dryad_tpu as dryad
from dryad_tpu.datasets import criteo_like
from dryad_tpu.metrics import auc

PARAMS = dict(objective="binary", num_trees=10, num_leaves=15, max_bins=64)


def test_criteo_like_csr_cpu_tpu_parity():
    # Sparse data is tie-heavy: near-equal leaf gains make the leaf-wise pick
    # order sensitive to f64(CPU)-vs-f32(TPU) histogram rounding (the
    # documented tolerance, SURVEY.md §7c), so parity here is behavioral —
    # both backends must learn categorical splits and match in quality.
    (indptr, indices, values, F), y, cat_ids = criteo_like(n=5000, seed=51)
    ds = dryad.Dataset(None, y, csr=(indptr, indices, values, F),
                       categorical_features=cat_ids, max_bins=64)
    assert ds.mapper.is_categorical.sum() == len(cat_ids)
    p = dict(PARAMS, categorical_features=list(cat_ids))
    b_cpu = dryad.train(p, ds, backend="cpu")
    b_tpu = dryad.train(p, ds, backend="tpu")
    assert b_cpu.is_cat.any() and b_tpu.is_cat.any()
    auc_cpu = auc(y, b_cpu.predict_binned(ds.X_binned))
    auc_tpu = auc(y, b_tpu.predict_binned(ds.X_binned))
    assert auc_cpu > 0.6 and auc_tpu > 0.6
    assert abs(auc_cpu - auc_tpu) < 0.01
    # root split of tree 0 agrees (no ties at the root)
    assert b_cpu.feature[0, 0] == b_tpu.feature[0, 0]


def test_sparse_dense_training_equivalence():
    (indptr, indices, values, F), y, cat_ids = criteo_like(n=3000, seed=53)
    dense = np.zeros((3000, F), np.float32)
    for i in range(3000):
        sl = slice(indptr[i], indptr[i + 1])
        dense[i, indices[sl]] = values[sl]
    # bundle=False: the equivalence contract is against the IDENTICAL
    # feature layout (EFB reshapes columns; it has its own tests)
    ds_csr = dryad.Dataset(None, y, csr=(indptr, indices, values, F),
                           categorical_features=cat_ids, max_bins=64,
                           bundle=False)
    ds_dense = dryad.Dataset(dense, y, categorical_features=cat_ids,
                             max_bins=64)
    p = dict(PARAMS, categorical_features=list(cat_ids), num_trees=5)
    b1 = dryad.train(p, ds_csr, backend="cpu")
    b2 = dryad.train(p, ds_dense, backend="cpu")
    np.testing.assert_array_equal(b1.feature, b2.feature)
    np.testing.assert_array_equal(b1.value, b2.value)
