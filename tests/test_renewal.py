"""L1-family leaf renewal (objectives.renew_alpha — LightGBM
RenewTreeOutput semantics; VERDICT r4 missing #3): post-growth refit of
leaf values to residual percentiles on both backends."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.config import make_params
from dryad_tpu.objectives import renew_alpha


def _toy(n=6000, seed=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3)
         + rng.standard_t(2.0, n) * 0.5).astype(np.float32)
    return X, y


def test_renew_alpha_levels():
    assert renew_alpha(make_params(objective="l1")) == 0.5
    assert renew_alpha(make_params(objective="huber")) == 0.5
    assert renew_alpha(make_params(objective="quantile", alpha=0.73)) == 0.73
    assert renew_alpha(make_params(objective="regression")) is None
    assert renew_alpha(make_params(objective="binary")) is None


def test_single_tree_leaves_are_residual_medians():
    """One depth-2 L1 tree: every leaf value must be exactly the type-1
    median of its residuals (y - init) times the learning rate."""
    X, y = _toy(2000)
    ds = dryad.Dataset(X, y, max_bins=64)
    p = dict(objective="l1", num_trees=1, num_leaves=4, max_depth=2,
             learning_rate=0.3, min_data_in_leaf=20)
    b = dryad.train(p, ds, backend="cpu")
    from dryad_tpu.cpu.predict import predict_tree_leaves

    lv = predict_tree_leaves(b.tree_arrays(), ds.X_binned, 0,
                             b.max_depth_seen)
    r = (y - np.float32(b.init_score[0])).astype(np.float32)
    for node in np.unique(lv):
        rs = np.sort(r[lv == node])
        kf = np.ceil(np.float32(0.5) * np.float32(rs.size))
        kidx = min(max(int(kf) - 1, 0), rs.size - 1)
        expect = np.float32(rs[kidx]) * np.float32(0.3)
        assert b.value[0, node] == expect, (node, b.value[0, node], expect)


@pytest.mark.parametrize("obj,alpha", [("l1", None), ("huber", None),
                                       ("quantile", 0.9)])
def test_renewal_cpu_device_parity(obj, alpha):
    """Both backends renew identically: same structures, near-equal values
    (tie-free short fixture, CLAUDE.md parity convention)."""
    X, y = _toy()
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective=obj, num_trees=8, num_leaves=15, max_bins=32,
             learning_rate=0.2)
    if alpha:
        p["alpha"] = alpha
    bc = dryad.train(p, ds, backend="cpu")
    bt = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(bc.feature, bt.feature)
    np.testing.assert_array_equal(bc.threshold, bt.threshold)
    np.testing.assert_allclose(bc.value, bt.value, rtol=1e-4, atol=1e-5)


def test_renewal_improves_quantile_loss():
    """The alpha-percentile refit must beat Newton-only leaves on pinball
    loss (the property LightGBM's renewal exists for)."""
    import dryad_tpu.objectives as O

    X, y = _toy(12000)
    ds = dryad.Dataset(X[:9000], y[:9000])
    Xt, yt = X[9000:], y[9000:]
    p = dict(objective="quantile", alpha=0.9, num_trees=40, num_leaves=31)
    b_on = dryad.train(p, ds, backend="cpu")
    real = O.renew_alpha
    try:
        O.renew_alpha = lambda *a, **k: None
        b_off = dryad.train(p, ds, backend="cpu")
    finally:
        O.renew_alpha = real

    def pinball(yv, s, a):
        d = yv - s
        return float(np.mean(np.maximum(a * d, (a - 1) * d)))

    on = pinball(yt, dryad.predict(b_on, Xt), 0.9)
    off = pinball(yt, dryad.predict(b_off, Xt), 0.9)
    assert on < off, (on, off)


def test_weighted_data_skips_renewal():
    """Weighted datasets keep Newton leaves (unweighted percentile only —
    documented divergence): unit weights must reproduce the
    renewal-disabled run exactly."""
    import dryad_tpu.objectives as O

    X, y = _toy(3000)
    p = dict(objective="l1", num_trees=4, num_leaves=15)
    w = np.ones_like(y)
    b_w = dryad.train(p, dryad.Dataset(X, y, weight=w), backend="cpu")
    real = O.renew_alpha
    try:
        O.renew_alpha = lambda *a, **k: None
        b_off = dryad.train(p, dryad.Dataset(X, y), backend="cpu")
    finally:
        O.renew_alpha = real
    np.testing.assert_array_equal(b_w.value, b_off.value)


def test_renewal_with_bagging_uses_bag_rows():
    """Renewal statistics come from the in-bag rows only; the run must
    stay cross-backend consistent under bagging."""
    X, y = _toy()
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective="l1", num_trees=6, num_leaves=15, max_bins=32,
             subsample=0.6, seed=9)
    bc = dryad.train(p, ds, backend="cpu")
    bt = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(bc.feature, bt.feature)
    np.testing.assert_allclose(bc.value, bt.value, rtol=1e-4, atol=1e-5)


def test_sharded_renewal_parity():
    """The renewal sort under a mesh is a GSPMD global sort (same class as
    the GOSS quantile, CLAUDE.md): N-shard must equal 1-shard."""
    import jax

    from dryad_tpu.engine.distributed import make_mesh
    from dryad_tpu.engine.train import train_device

    X, y = _toy(4096, seed=41)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = make_params(dict(objective="l1", num_trees=5, num_leaves=15,
                         max_bins=32, seed=7))
    mesh = make_mesh(jax.devices()[:8])
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    for k in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(b1.tree_arrays()[k],
                                      b8.tree_arrays()[k])
    np.testing.assert_allclose(b1.value, b8.value, atol=1e-3)


# ---- Booster.refit (LightGBM-style model adaptation) -----------------------

def test_refit_decay_one_is_identity():
    X, y = _toy(3000)
    ds = dryad.Dataset(X, y)
    b = dryad.train(dict(objective="regression", num_trees=5,
                         num_leaves=15), ds, backend="cpu")
    rb = b.refit(X, y, decay_rate=1.0)
    np.testing.assert_array_equal(rb.value, b.value)
    np.testing.assert_array_equal(rb.feature, b.feature)


def test_refit_same_data_reproduces_l2_values():
    """decay=0 on the training data re-derives the SAME Newton leaves the
    trainer computed (histogram sums vs direct sums — allclose)."""
    X, y = _toy(4000)
    ds = dryad.Dataset(X, y)
    b = dryad.train(dict(objective="regression", num_trees=6,
                         num_leaves=15, min_data_in_leaf=20),
                    ds, backend="cpu")
    rb = b.refit(X, y, decay_rate=0.0)
    np.testing.assert_allclose(rb.value, b.value, rtol=1e-4, atol=1e-5)


def test_refit_adapts_to_shifted_data():
    """Refit on shifted labels must beat the stale model there."""
    X, y = _toy(8000)
    ds = dryad.Dataset(X[:4000], y[:4000])
    b = dryad.train(dict(objective="regression", num_trees=30,
                         num_leaves=31), ds, backend="cpu")
    Xs, ys = X[4000:], y[4000:] + 2.5          # shifted domain
    rb = b.refit(Xs[:3000], ys[:3000], decay_rate=0.1)
    mse_old = float(np.mean((dryad.predict(b, Xs[3000:]) - ys[3000:]) ** 2))
    mse_new = float(np.mean((dryad.predict(rb, Xs[3000:]) - ys[3000:]) ** 2))
    assert mse_new < mse_old, (mse_new, mse_old)


def test_refit_l1_uses_renewal_convention():
    """L1 refit at decay 0 on the training data matches a renewal pass."""
    X, y = _toy(3000)
    ds = dryad.Dataset(X, y)
    b = dryad.train(dict(objective="l1", num_trees=4, num_leaves=15),
                    ds, backend="cpu")
    rb = b.refit(X, y, decay_rate=0.0)
    np.testing.assert_allclose(rb.value, b.value, rtol=1e-4, atol=1e-5)


def test_refit_rejects_dart_and_bad_decay():
    X, y = _toy(2000)
    ds = dryad.Dataset(X, y)
    bd = dryad.train(dict(objective="regression", boosting="dart",
                          num_trees=4, num_leaves=7), ds, backend="cpu")
    with pytest.raises(ValueError, match="DART"):
        bd.refit(X, y)
    b = dryad.train(dict(objective="regression", num_trees=2,
                         num_leaves=7), ds, backend="cpu")
    with pytest.raises(ValueError, match="decay_rate"):
        b.refit(X, y, decay_rate=1.5)


def test_refit_rf_keeps_average_semantics():
    X, y = _toy(4000)
    ds = dryad.Dataset(X, y)
    b = dryad.train(dict(objective="regression", boosting="rf",
                         num_trees=10, num_leaves=15, subsample=0.7),
                    ds, backend="cpu")
    rb = b.refit(X, y, decay_rate=0.5)
    pred = dryad.predict(rb, X)
    assert np.isfinite(pred).all()
    # still averaged: magnitudes stay in label range, not 10x
    assert np.abs(pred - y.mean()).max() < 10 * np.abs(y - y.mean()).max()


def test_monotone_constraints_disable_renewal():
    """Renewal is gated off under monotone constraints: the grower clamps
    Newton values to the monotone bounds, and an unclamped percentile
    could re-break the ordering (objectives.renew_alpha)."""
    assert renew_alpha(make_params(
        objective="l1", monotone_constraints=(1, 0, 0, 0, 0, 0, 0, 0))) is None
    X, y = _toy(4000)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective="l1", num_trees=8, num_leaves=15, max_bins=32,
             monotone_constraints=[1] + [0] * 7)
    b = dryad.train(p, ds, backend="cpu")
    # monotonicity holds: bumping the constrained feature never lowers pred
    Xa = X[:500].copy()
    Xb2 = Xa.copy()
    Xb2[:, 0] += 2.0
    assert (dryad.predict(b, Xb2) >= dryad.predict(b, Xa) - 1e-6).all()


def test_refit_rejects_lambdarank():
    from dryad_tpu.datasets import mslr_like

    X, y, group = mslr_like(num_queries=30, seed=3)
    ds = dryad.Dataset(X, y, group=group, max_bins=32)
    b = dryad.train(dict(objective="lambdarank", num_trees=3,
                         num_leaves=7, max_bins=32), ds, backend="cpu")
    with pytest.raises(ValueError, match="lambdarank"):
        b.refit(X, y)
