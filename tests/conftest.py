"""Test env: force JAX onto 8 virtual CPU devices (SURVEY.md §4).

The same shard_map/psum code paths that run on a real TPU pod then execute
in CI with no TPU attached.  The environment may pin JAX_PLATFORMS to the
TPU plugin, so the env var alone is not enough — the config update below
overrides it even after the plugin registers.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Compile-boundary introspection (engine/introspect.py) re-traces each new
# program once (~0.7 s for a small chunk program on CPU) — across the full
# suite's hundreds of compile boundaries that would blow the 870 s tier-1
# budget, so the suite pins it OFF and the obs/introspection tests opt
# back in per test (monkeypatch.setenv("DRYAD_PROG", "1")).  Production
# default stays ON (bench/smokes/CLI), where captures amortize over runs.
os.environ.setdefault("DRYAD_PROG", "0")
# The r18 train-completion reference-profile capture (data/profile.py) is
# likewise pinned OFF for the suite: hundreds of tiny trains would each
# pay a subsample + CPU predict for a baseline no test reads.  Drift/
# profile tests opt back in per test (monkeypatch.setenv) or call
# build_reference_profile directly; production default stays ON.
os.environ.setdefault("DRYAD_PROFILE", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Drop compiled-executable caches between test MODULES.

    With the round-4 test additions the full suite accumulates enough XLA
    CPU executables that the compiler deterministically segfaults inside
    backend_compile_and_load at ~70% (three identical crashes at
    test_sparse_train; no half-suite subset reproduces it).  Clearing per
    module caps live executables; shared programs recompile at most once
    per module."""
    yield
    jax.clear_caches()
