"""Test env: force JAX onto 8 virtual CPU devices (SURVEY.md §4).

Must run before any jax import: the same shard_map/psum code paths that run
on a real TPU pod then execute in CI with no TPU attached.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
