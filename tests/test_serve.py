"""Online inference subsystem (dryad_tpu/serve/).

The keystone invariant: a served prediction is BITWISE equal to the
direct ``Booster.predict`` on the same rows, no matter how the serving
layer buckets, pads, chunks, or coalesces the request — predict is
per-row arithmetic end to end, so shape games cannot change a bit.
Everything runs forced-CPU (tests/conftest.py) and stays tier-1 fast.
"""

import threading
import time

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.serve import (MicroBatcher, ModelRegistry, PredictServer,
                             Request, ServeOverloaded, ServeTimeout,
                             bucket_rows, run_bench)


@pytest.fixture(scope="module")
def model():
    X, y = higgs_like(600, seed=7)
    ds = dryad.Dataset(X, y, max_bins=32)
    booster = dryad.train(dict(objective="binary", num_trees=8, num_leaves=7,
                               max_bins=32), ds, backend="cpu")
    return booster, X


@pytest.fixture(scope="module")
def model_multiclass():
    rng = np.random.default_rng(3)
    X = rng.standard_normal((500, 8)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32) + (X[:, 2] > 0.5)
    ds = dryad.Dataset(X, y, max_bins=32)
    booster = dryad.train(dict(objective="multiclass", num_class=3,
                               num_trees=4, num_leaves=7, max_bins=32),
                          ds, backend="cpu")
    return booster, X


def test_bucket_rows():
    assert [bucket_rows(n) for n in (1, 7, 8, 9, 16, 17)] == [8, 8, 8, 16, 16, 32]
    assert bucket_rows(100, 8, 64) == 64           # capped at max bucket
    with pytest.raises(ValueError):
        bucket_rows(0)


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_served_predict_bitwise_parity(model, backend):
    """ISSUE satellite: padded/bucketed serve == direct predict, bitwise —
    empty batch, 1-row, bucket boundaries (8|9, 16|17), and a request
    bigger than the largest bucket (33 > 16 → chunked)."""
    booster, X = model
    server = PredictServer(backend=backend, max_batch_rows=16,
                           max_wait_ms=0.5, min_bucket=8)
    server.registry.add(booster)
    with server:
        for n in (0, 1, 7, 8, 9, 15, 16, 17, 33):
            for raw in (False, True):
                direct = booster.predict(X[:n], raw_score=raw)
                served = server.predict(X[:n], raw_score=raw)
                assert served.dtype == direct.dtype
                assert served.shape == direct.shape
                assert np.array_equal(served, direct), (backend, n, raw)
    snap = server.stats()
    assert snap["cache_compiles"] <= 2          # buckets {8, 16} only
    assert snap["cache_hits"] > 0


def test_served_binned_and_multiclass_parity(model_multiclass):
    booster, X = model_multiclass
    Xb = booster.mapper.transform(X)
    server = PredictServer(backend="cpu", max_batch_rows=64, max_wait_ms=0.5)
    server.registry.add(booster)
    with server:
        for n in (1, 9, 33):
            direct = booster.predict_binned(Xb[:n])
            served = server.predict(Xb[:n], binned=True)
            assert direct.shape == (n, 3) and np.array_equal(served, direct)


def test_registry_hot_swap_and_rollback(model, model_multiclass):
    booster_a, X = model
    booster_b, _ = model_multiclass
    reg = ModelRegistry()
    v1 = reg.add(booster_a)                             # v1 active
    v2 = reg.add(booster_b, activate=False)
    assert (reg.active_version, reg.versions()) == (v1, [v1, v2])
    reg.activate(v2)
    assert reg.active_version == v2
    assert reg.rollback() == v1 and reg.active_version == v1
    with pytest.raises(ValueError):
        reg.unload(v1)                                  # active is protected
    reg.unload(v2)
    assert reg.versions() == [v1]
    with pytest.raises(KeyError):
        reg.get(v2)
    with pytest.raises(LookupError):
        ModelRegistry().get()


def test_hot_swap_changes_served_model(model, model_multiclass):
    booster_a, X = model
    booster_b, Xm = model_multiclass
    server = PredictServer(backend="cpu", max_wait_ms=0.2)
    v1 = server.registry.add(booster_a)
    v2 = server.registry.add(booster_b, activate=False)
    with server:
        assert np.array_equal(server.predict(X[:5]), booster_a.predict(X[:5]))
        server.activate(v2)
        assert np.array_equal(server.predict(Xm[:5]), booster_b.predict(Xm[:5]))
        # pinned versions still address the inactive model
        assert np.array_equal(server.predict(X[:5], version=v1),
                              booster_a.predict(X[:5]))
        assert server.rollback() == v1
        assert np.array_equal(server.predict(X[:5]), booster_a.predict(X[:5]))


def test_registry_loads_text_binary_checkpoint(model, tmp_path):
    booster, X = model
    booster.save(str(tmp_path / "m.dryad"))
    booster.save_text(str(tmp_path / "m.txt"))
    from dryad_tpu.checkpoint import Checkpointer

    Checkpointer(str(tmp_path / "ck")).save(booster, 8)
    reg = ModelRegistry()
    v_bin = reg.load(str(tmp_path / "m.dryad"))
    v_txt = reg.load(str(tmp_path / "m.txt"))
    v_ck = reg.load_latest_checkpoint(str(tmp_path / "ck"))
    ref = booster.predict(X[:10])
    for v in (v_bin, v_txt, v_ck):
        got = reg.get(v).booster.predict(X[:10])
        assert np.array_equal(got, ref)
    with pytest.raises(FileNotFoundError):
        reg.load_latest_checkpoint(str(tmp_path / "empty_ck"))


def test_concurrent_requests_coalesce_bitwise(model):
    """Many threads in flight at once: answers stay request-exact, and the
    deadline coalescer folds them into fewer dispatches."""
    booster, X = model
    server = PredictServer(backend="cpu", max_batch_rows=128,
                           max_wait_ms=20.0, queue_size=64)
    server.registry.add(booster)
    sizes = [1, 3, 5, 8, 13]
    outs: dict[int, np.ndarray] = {}
    start = threading.Barrier(len(sizes))

    def worker(i, n):
        start.wait()
        outs[i] = server.predict(X[i:i + n])

    with server:
        threads = [threading.Thread(target=worker, args=(i, n))
                   for i, n in enumerate(sizes)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for i, n in enumerate(sizes):
        assert np.array_equal(outs[i], booster.predict(X[i:i + n]))
    snap = server.stats()
    assert snap["requests"] == len(sizes)
    assert snap["batches"] < len(sizes)          # coalescing actually happened
    assert 0 < snap["batch_fill_ratio"] <= 1


def test_batcher_backpressure_and_timeout():
    """Bounded queue rejects excess load; a per-request timeout abandons a
    stuck request instead of hanging the caller."""
    release = threading.Event()

    def slow_dispatch(batch):
        release.wait(5.0)
        return [np.zeros(r.rows.shape[0], np.float32) for r in batch]

    from dryad_tpu.serve import ServeMetrics

    metrics = ServeMetrics()
    batcher = MicroBatcher(slow_dispatch, max_batch_rows=4, max_wait_ms=1.0,
                           queue_size=1, metrics=metrics)
    batcher.start()
    rows = np.zeros((2, 3), np.uint8)
    errs: list[BaseException] = []

    def blocked():
        try:
            batcher.submit(Request(rows), timeout=0.05)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=blocked)
    t.start()
    time.sleep(0.2)       # worker is now stuck inside slow_dispatch
    # worker busy: the next submit queues then times out (and stays queued,
    # abandoned), so the one after bounces off the full queue
    with pytest.raises(ServeTimeout):
        batcher.submit(Request(rows), timeout=0.01)
    with pytest.raises(ServeOverloaded):
        batcher.submit(Request(rows), timeout=0.01)
    release.set()
    t.join(5.0)
    assert errs and isinstance(errs[0], ServeTimeout)
    assert metrics.timeouts >= 1 and metrics.rejected >= 1
    batcher.stop()


def test_stop_drains_stranded_requests():
    """A request enqueued behind the stop token must be failed, not left
    waiting forever on a dead worker."""
    from dryad_tpu.serve.batcher import _StopToken

    batcher = MicroBatcher(lambda b: [None] * len(b), queue_size=4)
    stranded = Request(np.zeros((1, 2), np.uint8))
    # stamped with the current generation (start() below leaves it alone —
    # no timed-out stop pending), so the worker honors it as a live stop
    # and drains what's queued behind it
    batcher._q.put(_StopToken(batcher._gen))
    batcher._q.put(stranded)
    batcher.start()
    assert stranded.event.wait(5.0)
    assert isinstance(stranded.error, ServeOverloaded)
    batcher.stop()


def test_unloaded_version_fails_only_its_group(model):
    """A batch mixing a dead pinned version with live requests fails only
    the dead group's requests."""
    booster, X = model
    server = PredictServer(backend="cpu", max_wait_ms=0.2)
    server.registry.add(booster)
    Xb = booster.mapper.transform(X[:4])
    good = Request(Xb, version=server.registry.active_version)
    dead = Request(Xb, version=99)
    results = server._dispatch([good, dead])
    assert isinstance(results[1], KeyError)
    assert np.array_equal(results[0], booster.predict(X[:4]))


def test_dispatch_error_propagates():
    def bad_dispatch(batch):
        raise RuntimeError("boom")

    batcher = MicroBatcher(bad_dispatch, max_wait_ms=0.1, queue_size=4)
    batcher.start()
    with pytest.raises(RuntimeError, match="boom"):
        batcher.submit(Request(np.zeros((1, 2), np.uint8)), timeout=5.0)
    batcher.stop()


def test_pipeline_and_serial_dispatch_agree(model):
    """The overlapped two-deep pipeline returns the same bits as the
    strictly serial loop — pipelining changes WHEN a batch runs, never
    what runs."""
    booster, X = model
    outs = {}
    for depth in (1, 2, 3):
        server = PredictServer(backend="cpu", max_batch_rows=32,
                               max_wait_ms=0.5, pipeline_depth=depth)
        server.registry.add(booster)
        with server:
            outs[depth] = [server.predict(X[:n]) for n in (1, 9, 33)]
        assert server.stats()["pipeline_depth"] == (depth if depth >= 2 else 1)
    for n_i in range(3):
        direct = booster.predict(X[: (1, 9, 33)[n_i]])
        for depth in (1, 2, 3):
            assert np.array_equal(outs[depth][n_i], direct), depth


def test_pipeline_concurrent_bitwise(model):
    """Concurrent load through the pipeline: request-exact answers while
    collector and executor overlap."""
    booster, X = model
    server = PredictServer(backend="cpu", max_batch_rows=64, max_wait_ms=5.0,
                           pipeline_depth=2, queue_size=64)
    server.registry.add(booster)
    sizes = [1, 3, 5, 8, 13, 21]
    outs: dict[int, np.ndarray] = {}
    start = threading.Barrier(len(sizes))

    def worker(i, n):
        start.wait()
        outs[i] = server.predict(X[i:i + n])

    with server:
        threads = [threading.Thread(target=worker, args=(i, n))
                   for i, n in enumerate(sizes)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for i, n in enumerate(sizes):
        assert np.array_equal(outs[i], booster.predict(X[i:i + n]))


def test_registry_budget_evicts_lru_not_active(model, model_multiclass):
    """Device-memory budget: staging past the budget evicts the LRU staged
    entry; the active version is pinned; an evicted model transparently
    re-stages on its next request with bitwise-identical output; its
    metrics history survives eviction."""
    booster_a, X = model
    booster_b, Xm = model_multiclass
    reg = ModelRegistry(budget_bytes=1)       # everything non-pinned evicts
    server = PredictServer(reg, backend="tpu", max_wait_ms=0.2)
    vA = reg.add(booster_a)                   # active
    vB = reg.add(booster_b, activate=False, name="challenger")
    with server:
        outB1 = server.predict(Xm[:5], version=vB)
        eA, eB = reg.get(vA), reg.get(vB)
        assert eB.is_staged
        server.predict(X[:5])                 # stages A → B is the LRU victim
        assert not eB.is_staged, "inactive LRU entry must be evicted"
        assert eA.is_staged, "active version is pinned"
        reqs_before = server.stats()["models"][vB]["requests"]
        outB2 = server.predict(Xm[:5], version=vB)   # transparent re-stage
        assert eB.is_staged
        assert np.array_equal(outB1, outB2)
        assert np.array_equal(outB2, booster_b.predict(Xm[:5]))
    snap = server.stats()
    assert snap["evictions"] >= 1 and snap["restages"] >= 1
    mB = snap["models"][vB]
    assert mB["evictions"] >= 1 and mB["restages"] >= 1
    assert mB["requests"] == reqs_before + 1, "stats must survive eviction"
    assert snap["memory"]["budget_bytes"] == 1


def test_unbudgeted_registry_never_evicts(model, model_multiclass):
    booster_a, X = model
    booster_b, Xm = model_multiclass
    server = PredictServer(backend="tpu", max_wait_ms=0.2)
    vA = server.registry.add(booster_a)
    vB = server.registry.add(booster_b, activate=False)
    with server:
        server.predict(Xm[:5], version=vB)
        server.predict(X[:5], version=vA)
    assert server.registry.get(vA).is_staged
    assert server.registry.get(vB).is_staged
    assert server.stats()["evictions"] == 0
    assert server.stats()["memory"]["staged_versions"] == [vA, vB]


def test_named_model_routing(model, model_multiclass):
    """Multi-model co-serving routes by name; re-adding under the same
    name repoints the alias (deploy gesture); unload drops the alias."""
    booster_a, X = model
    booster_b, Xm = model_multiclass
    server = PredictServer(backend="cpu", max_wait_ms=0.2)
    v1 = server.registry.add(booster_a, name="champion")
    v2 = server.registry.add(booster_b, activate=False, name="challenger")
    with server:
        assert np.array_equal(server.predict(X[:5], model="champion"),
                              booster_a.predict(X[:5]))
        assert np.array_equal(server.predict(Xm[:5], model="challenger"),
                              booster_b.predict(Xm[:5]))
        with pytest.raises(KeyError):
            server.predict(X[:2], model="nobody")
        with pytest.raises(ValueError):
            server.predict(X[:2], version=v1, model="champion")
        v3 = server.registry.add(booster_b, activate=False, name="champion")
        assert np.array_equal(server.predict(Xm[:5], model="champion"),
                              booster_b.predict(Xm[:5]))
        assert server.registry.aliases() == {"champion": v3,
                                             "challenger": v2}
        server.registry.unload(v2)
        assert server.registry.aliases() == {"champion": v3}


def test_unload_frees_staged_and_cache_entries(model, model_multiclass):
    """Unloading a co-served model must actually release it: the registry
    drops its staged/device arrays immediately (the budget can never
    reach them again) and server.unload purges the compiled-cache
    closures that would otherwise pin the entry alive."""
    booster_a, X = model
    booster_b, Xm = model_multiclass
    server = PredictServer(backend="tpu", max_wait_ms=0.2)
    vA = server.registry.add(booster_a)
    vB = server.registry.add(booster_b, activate=False, name="retired")
    with server:
        server.predict(Xm[:5], version=vB)
        entry_b = server.registry.get(vB)
        assert entry_b.is_staged
        assert any(k[0] == vB for k in server.cache._fns)
        server.unload(vB)
        assert not entry_b.is_staged, "unload must free the staged arrays"
        assert not any(k[0] == vB for k in server.cache._fns)
        assert not any(k[0] == vB for k in server.cache._warm)
        assert server.registry.aliases() == {}
        # the survivor still serves, bitwise
        assert np.array_equal(server.predict(X[:5]), booster_a.predict(X[:5]))


def test_malformed_request_fails_alone(model):
    """Width validation happens at submit time, in the caller's thread:
    binning is deferred into the coalesced _prepare, so without the check
    one wrong-width request would poison every co-batched request of the
    same version."""
    booster, X = model
    server = PredictServer(backend="cpu", max_batch_rows=64, max_wait_ms=20.0)
    server.registry.add(booster)
    results: dict = {}
    start = threading.Barrier(2)

    def good():
        start.wait()
        results["good"] = server.predict(X[:5])

    def bad():
        start.wait()
        try:
            server.predict(X[:3, :-1])          # one feature short
            results["bad"] = "no error"
        except ValueError as e:
            results["bad"] = e

    with server:
        threads = [threading.Thread(target=good), threading.Thread(target=bad)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert isinstance(results["bad"], ValueError)
        assert np.array_equal(results["good"], booster.predict(X[:5]))
        with pytest.raises(ValueError, match="expected"):
            server.predict(booster.mapper.transform(X[:2])[:, :-1],
                           binned=True)


def test_per_model_stats(model, model_multiclass):
    booster_a, X = model
    booster_b, Xm = model_multiclass
    server = PredictServer(backend="cpu", max_wait_ms=0.2)
    v1 = server.registry.add(booster_a)
    v2 = server.registry.add(booster_b, activate=False)
    with server:
        for _ in range(3):
            server.predict(X[:4], version=v1)
        server.predict(Xm[:7], version=v2)
    snap = server.stats()
    assert snap["models"][v1]["requests"] == 3
    assert snap["models"][v1]["rows"] == 12
    assert snap["models"][v2]["requests"] == 1
    assert snap["models"][v2]["rows"] == 7
    assert snap["models"][v2]["p99_ms"] >= 0.0


def test_bench_compare_pipeline_vs_serial(model):
    """The A/B harness reports both arms + the speedup field and stays
    recompile-free; the ≥1.3× acceptance number itself is recorded by
    scripts/bench_serve.py --compare (timing asserts would be flaky in
    a shared CI container)."""
    from dryad_tpu.serve import run_bench_compare

    booster, X = model
    report = run_bench_compare(booster, backend="cpu", clients=3,
                               duration_s=0.3, sizes=(1, 5, 9),
                               max_batch_rows=32, max_wait_ms=1.0, seed=0,
                               arms=2, feature_pool=X)
    assert report["recompiles_after_warmup"] == 0
    assert report["serial"]["pipeline_depth"] == 1
    assert report["pipeline"]["pipeline_depth"] == 2
    assert report["pipeline_speedup"] > 0
    for arm in ("serial", "pipeline"):
        assert report[arm]["bench_arms"] == 2
        assert "spread_rows_per_s" in report[arm]
        assert isinstance(report[arm]["suspect_capture"], bool)


def test_http_structured_request_logging(model):
    """--log-requests emits one JSON line per request with version, rows,
    latency, and status (including error statuses)."""
    import io
    import json
    import urllib.error
    import urllib.request

    from dryad_tpu.serve.http import make_http_server

    booster, X = model
    server = PredictServer(backend="cpu", max_wait_ms=0.5)
    server.registry.add(booster)
    stream = io.StringIO()
    httpd = make_http_server(server, port=0, log_requests=True,
                             log_stream=stream)
    port = httpd.server_address[1]
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=json.dumps({"rows": X[:3].tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10).read()
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/predict",
                data=json.dumps({"rows": X[:2].tolist(),
                                 "version": 99}).encode(),
                headers={"Content-Type": "application/json"}), timeout=10)
        urllib.request.urlopen(f"http://127.0.0.1:{port}/stats",
                               timeout=10).read()
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.stop()
    lines = [json.loads(l) for l in stream.getvalue().splitlines()]
    assert len(lines) == 3
    ok = lines[0]
    assert ok["path"] == "/predict" and ok["status"] == 200
    assert ok["version"] == 1 and ok["rows"] == 3
    assert ok["latency_ms"] >= 0
    assert lines[1]["status"] == 400 and lines[1]["version"] is None
    assert lines[2]["path"] == "/stats" and lines[2]["status"] == 200


def test_bench_serve_zero_recompiles_after_warmup(model):
    """Acceptance gate: the closed-loop bench on forced CPU reports zero
    recompiles after warmup — warm traffic only ever hits warm buckets."""
    booster, X = model
    report = run_bench(booster, backend="cpu", clients=3, duration_s=0.5,
                       sizes=(1, 5, 9, 17), max_batch_rows=32,
                       max_wait_ms=1.0, seed=0, feature_pool=X)
    assert report["recompiles_after_warmup"] == 0
    assert report["cache_hits"] > 0
    assert report["bench_requests"] > 0
    assert report["cache_compiles"] == 3         # buckets {8, 16, 32}, once


def test_http_round_trip(model):
    """Loopback smoke of the HTTP front end: /predict parity (through JSON
    — exact, since Python floats widen f32 losslessly), /stats, /models,
    and error mapping for an unknown version."""
    import json
    import urllib.error
    import urllib.request

    from dryad_tpu.serve.http import make_http_server

    booster, X = model
    server = PredictServer(backend="cpu", max_wait_ms=0.5)
    server.registry.add(booster)
    httpd = make_http_server(server, port=0)
    port = httpd.server_address[1]
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()

    def post(path, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req, timeout=10).read())

    try:
        out = post("/predict", {"rows": X[:5].tolist()})
        assert np.array_equal(np.asarray(out["predictions"], np.float32),
                              booster.predict(X[:5]))
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10).read())
        assert stats["requests"] >= 1 and stats["backend"] == "cpu"
        models = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/models", timeout=10).read())
        assert models["active"] in models["versions"]
        assert out["version"] == models["active"]
        # pre-binned rows arrive as JSON ints and must be cast to the
        # model's bin dtype, not float32
        Xb = booster.mapper.transform(X[:3])
        binned_out = post("/predict", {"rows": Xb.tolist(), "binned": True})
        assert np.array_equal(np.asarray(binned_out["predictions"], np.float32),
                              booster.predict_binned(Xb))
        with pytest.raises(urllib.error.HTTPError) as err:
            post("/predict", {"rows": X[:2].tolist(), "version": 99})
        assert err.value.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.stop()


def test_stop_timeout_keeps_stuck_worker_handle():
    """The r8-flagged stop() race: when join() times out because the worker
    is stuck in a stalled dispatch, the thread handle must NOT be cleared —
    a cleared handle would let the next start() race a SECOND collector
    onto the same queue.  Once the worker really exits, stop() clears it."""
    release = threading.Event()

    def stuck_dispatch(batch):
        release.wait(30.0)
        return [np.zeros(r.rows.shape[0], np.float32) for r in batch]

    batcher = MicroBatcher(stuck_dispatch, max_batch_rows=4, max_wait_ms=0.5,
                           queue_size=4)
    batcher.start()
    req = Request(np.zeros((1, 3), np.uint8))
    batcher._q.put_nowait(req)
    deadline = time.monotonic() + 5.0
    while not batcher._q.empty() and time.monotonic() < deadline:
        time.sleep(0.005)          # worker has dequeued: now inside dispatch
    worker = batcher._thread
    assert worker is not None and worker.is_alive()

    batcher.stop(timeout=0.05)     # join times out — worker still stuck
    assert batcher._thread is worker, "handle cleared while worker alive"
    batcher.start()                # must NOT spawn a second collector
    assert batcher._thread is worker

    release.set()
    assert req.event.wait(5.0)     # the stuck dispatch completes delivery
    batcher.stop(timeout=5.0)
    assert batcher._thread is None


def test_restart_after_stop_timeout_keeps_serving():
    """start() after a timed-out stop() CANCELS the pending stop: the
    queued stop token goes stale, so when the stuck dispatch finally
    completes the worker ignores it and keeps collecting — without the
    generation stamp it would honor the stale token, exit, and leave the
    queue permanently collector-less (no path re-runs start())."""
    entered = threading.Event()
    release = threading.Event()
    stuck_once = []

    def dispatch(batch):
        if not stuck_once:
            stuck_once.append(1)
            entered.set()
            release.wait(30.0)
        return [np.zeros(r.rows.shape[0], np.float32) for r in batch]

    batcher = MicroBatcher(dispatch, max_batch_rows=4, max_wait_ms=0.5,
                           queue_size=4)
    batcher.start()
    req = Request(np.zeros((1, 3), np.uint8))
    batcher._q.put_nowait(req)
    # synchronize on DISPATCH entry (not _q.empty(), which can observe the
    # worker still inside _collect's coalesce window — a stop token eaten
    # there latches stopping before start() can invalidate it)
    assert entered.wait(5.0)       # worker is inside the stalled dispatch
    worker = batcher._thread

    batcher.stop(timeout=0.05)     # join times out; stop token stays queued
    batcher.start()                # operator restart — must cancel the stop
    release.set()
    assert req.event.wait(5.0)

    # the SAME worker must still be collecting: a fresh request round-trips
    out = batcher.submit(Request(np.zeros((2, 3), np.uint8)), timeout=5.0)
    assert out.shape == (2,)
    assert batcher._thread is worker and worker.is_alive()
    batcher.stop(timeout=5.0)      # un-cancelled stop still works
    assert batcher._thread is None


def test_plain_start_does_not_cancel_pending_stop():
    """PredictServer.predict() auto-calls start() on every request, so a
    start() against a live batcher with NO timed-out stop must not bump
    the stop generation — otherwise any concurrent request would silently
    cancel an operator shutdown and stop() would hang its full join
    timeout with the collector leaked."""
    batcher = MicroBatcher(
        lambda b: [np.zeros(r.rows.shape[0], np.float32) for r in b],
        max_batch_rows=4, max_wait_ms=0.5, queue_size=4)
    batcher.start()
    gen = batcher._gen
    batcher.start()                # per-request auto-start: must be inert
    batcher.start()
    assert batcher._gen == gen
    batcher.stop(timeout=5.0)      # the stop token is still honored
    assert batcher._thread is None


def test_http_bearer_auth_and_metrics_endpoint(model):
    """--auth-token: 401 without/with a wrong bearer on every endpoint,
    200 with the right one; /healthz stays open; /metrics exposes the
    shared registry; the /stats snapshot shape is the pre-obs contract."""
    import json
    import urllib.error
    import urllib.request

    from dryad_tpu.serve.http import make_http_server

    booster, X = model
    server = PredictServer(backend="cpu", max_wait_ms=0.5)
    server.registry.add(booster)
    httpd = make_http_server(server, port=0, auth_token="tok3n")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"

    def get(path, token=None):
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        return urllib.request.urlopen(
            urllib.request.Request(base + path, headers=headers), timeout=10)

    try:
        assert json.loads(get("/healthz").read()) == {"ok": True}
        for path in ("/stats", "/models", "/metrics"):
            with pytest.raises(urllib.error.HTTPError) as err:
                get(path)
            assert err.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as err:
            get("/stats", token="wrong")
        assert err.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(urllib.request.Request(
                base + "/predict",
                data=json.dumps({"rows": X[:2].tolist()}).encode(),
                headers={"Content-Type": "application/json"}), timeout=10)
        assert err.value.code == 401

        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"rows": X[:2].tolist()}).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": "Bearer tok3n"})
        out = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert np.array_equal(np.asarray(out["predictions"], np.float32),
                              booster.predict(X[:2]))
        stats = json.loads(get("/stats", token="tok3n").read())
        assert stats["requests"] >= 1      # unchanged pre-obs snapshot shape
        assert "counters" not in stats
        text = get("/metrics", token="tok3n").read().decode()
        assert "# TYPE dryad_serve_requests_total counter" in text
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.stop()
