import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu import datasets
from dryad_tpu.metrics import auc, binary_logloss, rmse, multi_logloss, accuracy


@pytest.fixture(scope="module")
def higgs_small():
    X, y = datasets.higgs_like(20_000, seed=7)
    return X[:16_000], y[:16_000], X[16_000:], y[16_000:]


def test_binary_end_to_end(higgs_small):
    Xtr, ytr, Xte, yte = higgs_small
    ds = dryad.Dataset(Xtr, ytr, max_bins=64)
    b = dryad.train(
        {"objective": "binary", "num_trees": 30, "num_leaves": 31, "learning_rate": 0.2},
        ds, backend="cpu",
    )
    p_tr = dryad.predict(b, Xtr)
    p_te = dryad.predict(b, Xte)
    auc_tr, auc_te = auc(ytr, p_tr), auc(yte, p_te)
    assert auc_tr > 0.80, auc_tr
    assert auc_te > 0.70, auc_te
    # boosting actually reduces train loss vs prior
    base = np.clip(ytr.mean(), 1e-9, 1 - 1e-9)
    prior_ll = binary_logloss(ytr, np.full_like(ytr, base))
    assert binary_logloss(ytr, p_tr) < prior_ll * 0.9


def test_training_monotone_improvement(higgs_small):
    Xtr, ytr, _, _ = higgs_small
    ds = dryad.Dataset(Xtr, ytr, max_bins=64)
    b = dryad.train({"objective": "binary", "num_trees": 20, "num_leaves": 15}, ds, backend="cpu")
    p5 = dryad.predict(b, Xtr, num_iteration=5)
    p20 = dryad.predict(b, Xtr, num_iteration=20)
    assert binary_logloss(ytr, p20) < binary_logloss(ytr, p5)


def test_regression():
    X, y = datasets.epsilon_like(4000, num_features=50, seed=3)
    ds = dryad.Dataset(X, y)
    b = dryad.train({"objective": "regression", "num_trees": 40, "num_leaves": 31, "learning_rate": 0.2}, ds, backend="cpu")
    pred = dryad.predict(b, X)
    assert rmse(y, pred) < 0.7 * np.std(y)


def test_multiclass():
    X, y = datasets.covertype_like(8000, seed=5)
    ds = dryad.Dataset(X, y)
    b = dryad.train(
        {"objective": "multiclass", "num_class": 7, "num_trees": 15, "num_leaves": 15, "learning_rate": 0.3},
        ds, backend="cpu",
    )
    prob = dryad.predict(b, X)
    assert prob.shape == (8000, 7)
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, atol=1e-5)
    assert accuracy(y, prob) > 0.55
    assert multi_logloss(y, prob) < np.log(7) * 0.8


def test_min_data_in_leaf_respected():
    X, y = datasets.higgs_like(2000, seed=1)
    ds = dryad.Dataset(X, y)
    b = dryad.train(
        {"objective": "binary", "num_trees": 3, "num_leaves": 64, "min_data_in_leaf": 200},
        ds, backend="cpu",
    )
    Xb = ds.X_binned
    from dryad_tpu.cpu.predict import predict_tree_leaves

    for t in range(b.num_total_trees):
        leaves = predict_tree_leaves(b.tree_arrays(), Xb, t, b.max_depth_seen)
        counts = np.bincount(leaves)
        assert counts[counts > 0].min() >= 200


def test_max_depth_respected():
    X, y = datasets.higgs_like(5000, seed=2)
    ds = dryad.Dataset(X, y)
    b = dryad.train(
        {"objective": "binary", "num_trees": 5, "num_leaves": 256, "max_depth": 3},
        ds, backend="cpu",
    )
    assert b.max_depth_seen <= 3
    # depth 3 -> at most 8 leaves => at most 15 nodes
    assert (b.feature >= 0).sum(axis=1).max() <= 7


def test_depthwise_growth_param():
    X, y = datasets.higgs_like(3000, seed=4)
    ds = dryad.Dataset(X, y)
    b = dryad.train(
        {"objective": "binary", "num_trees": 3, "growth": "depthwise", "max_depth": 4, "num_leaves": 10_000},
        ds, backend="cpu",
    )
    assert b.params.effective_num_leaves == 16


def test_bagging_and_colsample_deterministic():
    X, y = datasets.higgs_like(5000, seed=6)
    ds = dryad.Dataset(X, y)
    params = {"objective": "binary", "num_trees": 10, "subsample": 0.7, "colsample": 0.7, "seed": 42}
    b1 = dryad.train(params, ds, backend="cpu")
    b2 = dryad.train(params, ds, backend="cpu")
    np.testing.assert_array_equal(b1.feature, b2.feature)
    np.testing.assert_array_equal(b1.value, b2.value)
    p = dryad.predict(b1, X)
    assert auc(y, p) > 0.7


def test_save_load_roundtrip(tmp_path, higgs_small):
    Xtr, ytr, Xte, _ = higgs_small
    ds = dryad.Dataset(Xtr, ytr)
    b = dryad.train({"objective": "binary", "num_trees": 5}, ds, backend="cpu")
    path = str(tmp_path / "model.dryad")
    b.save(path)
    b2 = dryad.Booster.load(path)
    np.testing.assert_array_equal(
        dryad.predict(b, Xte, raw_score=True), dryad.predict(b2, Xte, raw_score=True)
    )


def test_resume_matches_straight_run(higgs_small):
    Xtr, ytr, _, _ = higgs_small
    ds = dryad.Dataset(Xtr, ytr)
    params = {"objective": "binary", "num_trees": 10, "num_leaves": 15}
    full = dryad.train(params, ds, backend="cpu")
    half = dryad.train({**params, "num_trees": 5}, ds, backend="cpu")
    resumed = dryad.train(params, ds, backend="cpu", init_booster=half)
    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_allclose(full.value, resumed.value, rtol=1e-6, atol=1e-7)


def test_feature_importance(higgs_small):
    Xtr, ytr, _, _ = higgs_small
    ds = dryad.Dataset(Xtr, ytr)
    b = dryad.train({"objective": "binary", "num_trees": 5}, ds, backend="cpu")
    imp = b.feature_importance()
    assert imp.shape == (Xtr.shape[1],)
    assert imp.sum() == (b.feature >= 0).sum()
