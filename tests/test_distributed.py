"""Distributed invariant (SURVEY.md §4): N-shard training on the virtual
8-CPU-device mesh must reproduce 1-device training.

The only cross-device exchange is the fused histogram psum; split decisions
derive from the (replicated) summed histogram, so tree structures must agree
exactly whenever the psum reduction order doesn't flip an argmax (continuous
features, distinct gains — asserted structurally here; leaf values to fp32
tolerance)."""

from __future__ import annotations

import numpy as np
import pytest

import jax

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like

# r19: slow — interpret-mode sharded compute on the 8-fake-device
# mesh pays the virtual-collective overhead in Python; on the 2-core
# CI container this module helped push tier-1 past its 870 s budget.
# ci.sh tier-1 runs `-m 'not slow'`; run this module explicitly (or
# the full unfiltered suite) on a wider host when touching it.
pytestmark = [pytest.mark.distributed, pytest.mark.slow]


@pytest.fixture(scope="module")
def mesh():
    from dryad_tpu.engine.distributed import make_mesh

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(jax.devices()[:8])


def test_sharded_equals_single_device(mesh):
    X, y = higgs_like(4096)
    ds = dryad.Dataset(X, y, max_bins=64)
    params = dict(objective="binary", num_trees=6, num_leaves=15, max_bins=64,
                  learning_rate=0.2)
    from dryad_tpu.engine.train import train_device
    from dryad_tpu.config import make_params

    p = make_params(params)
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    for k in ("feature", "threshold", "left", "right", "is_cat"):
        np.testing.assert_array_equal(
            b1.tree_arrays()[k], b8.tree_arrays()[k],
            err_msg=f"sharded vs single-device {k!r} diverged",
        )
    np.testing.assert_allclose(b1.value, b8.value, atol=1e-3)


def test_sharded_row_padding(mesh):
    """Row count not divisible by the mesh: padded rows must not leak."""
    X, y = higgs_like(4001)  # 4001 % 8 != 0
    ds = dryad.Dataset(X, y, max_bins=32)
    from dryad_tpu.engine.train import train_device
    from dryad_tpu.config import make_params

    p = make_params(dict(objective="binary", num_trees=4, num_leaves=8, max_bins=32))
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    for k in ("feature", "threshold"):
        np.testing.assert_array_equal(b1.tree_arrays()[k], b8.tree_arrays()[k])


def test_sharded_multiclass_and_bagging(mesh):
    rng = np.random.Generator(np.random.Philox(21))
    X = rng.normal(size=(4096, 10)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32) + (X[:, 2] > 1) * 1.0
    ds = dryad.Dataset(X, y, max_bins=32)
    from dryad_tpu.engine.train import train_device
    from dryad_tpu.config import make_params

    p = make_params(dict(objective="multiclass", num_class=3, num_trees=3,
                         num_leaves=8, max_bins=32, subsample=0.7, seed=3))
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    np.testing.assert_array_equal(b1.feature, b8.feature)
    np.testing.assert_array_equal(b1.threshold, b8.threshold)


def test_sharded_depthwise_levelwise_path(mesh):
    """The level-synchronous grower under shard_map: one fused psum per
    level must reproduce single-device trees."""
    X, y = higgs_like(4096)
    ds = dryad.Dataset(X, y, max_bins=32)
    from dryad_tpu.engine.train import train_device
    from dryad_tpu.config import make_params

    p = make_params(dict(objective="binary", num_trees=4, num_leaves=31,
                         max_depth=5, growth="depthwise", max_bins=32))
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    for k in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(b1.tree_arrays()[k], b8.tree_arrays()[k])


def test_sharded_goss_parity(mesh):
    """GOSS's global |grad| quantile (a GSPMD sort over the sharded array —
    the one collective beyond the histogram psum, documented in CLAUDE.md)
    must select identical rows on any mesh."""
    X, y = higgs_like(4096, seed=41)
    ds = dryad.Dataset(X, y, max_bins=32)
    from dryad_tpu.engine.train import train_device
    from dryad_tpu.config import make_params

    p = make_params(dict(objective="binary", num_trees=5, num_leaves=15,
                         max_bins=32, boosting="goss", goss_top_rate=0.3,
                         goss_other_rate=0.2, seed=7))
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    for k in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(b1.tree_arrays()[k], b8.tree_arrays()[k])
    np.testing.assert_allclose(b1.value, b8.value, atol=1e-3)


def test_sharded_goss_padded_rows(mesh):
    """Padded rows carry fake zero gradients — they must never enter the
    top-quantile pick nor the Bernoulli pool when N % mesh != 0."""
    X, y = higgs_like(4001, seed=43)
    ds = dryad.Dataset(X, y, max_bins=32)
    from dryad_tpu.engine.train import train_device
    from dryad_tpu.config import make_params

    p = make_params(dict(objective="binary", num_trees=4, num_leaves=8,
                         max_bins=32, boosting="goss"))
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    np.testing.assert_array_equal(b1.feature, b8.feature)
    np.testing.assert_array_equal(b1.threshold, b8.threshold)


def test_sharded_lambdarank_parity(mesh):
    """LambdaMART's padded-query scatter (PaddingPlan row/col ids) crosses
    shard boundaries when queries straddle them; the sharded run must still
    reproduce the single-device trees."""
    from dryad_tpu.datasets import mslr_like
    from dryad_tpu.engine.train import train_device
    from dryad_tpu.config import make_params

    X, y, group = mslr_like(120, seed=45)  # ragged queries, N % 8 != 0 likely
    ds = dryad.Dataset(X, y, group=group, max_bins=32)
    p = make_params(dict(objective="lambdarank", num_trees=4, num_leaves=15,
                         max_bins=32))
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    for k in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(b1.tree_arrays()[k], b8.tree_arrays()[k])
    np.testing.assert_allclose(b1.value, b8.value, atol=1e-3)


def test_sharded_weighted_parity(mesh):
    """Weights survive mesh padding/sharding (pad rows excluded by bag mask)."""
    rng = np.random.Generator(np.random.Philox(23))
    X, y = higgs_like(4001)
    w = rng.uniform(0.5, 2.0, size=4001).astype(np.float32)
    ds = dryad.Dataset(X, y, weight=w, max_bins=32)
    from dryad_tpu.engine.train import train_device
    from dryad_tpu.config import make_params

    p = make_params(dict(objective="binary", num_trees=3, num_leaves=8, max_bins=32))
    b1 = train_device(p, ds)
    b8 = train_device(p, ds, mesh=mesh)
    np.testing.assert_array_equal(b1.feature, b8.feature)
    np.testing.assert_array_equal(b1.threshold, b8.threshold)


def test_sharded_predict_bitwise(mesh):
    """r7 serving tentpole anchor: shard_map predict over the mesh is
    bitwise equal to the CPU reference — raw scores are per-row, so row
    sharding (incl. the zero-bin padding for non-divisible batches) is a
    pure shape game (tests/test_serve_sharded.py covers the serving
    layer; this pins the engine primitive next to its training peers)."""
    from dryad_tpu.engine.predict import predict_binned_sharded

    X, y = higgs_like(2001)   # 2001 % 8 != 0
    ds = dryad.Dataset(X, y, max_bins=64)
    b = dryad.train(dict(objective="binary", num_trees=6, num_leaves=15,
                         max_bins=64), ds, backend="cpu")
    Xb = ds.X_binned
    ref = b.predict_binned(Xb, raw_score=True)
    sharded = np.asarray(predict_binned_sharded(b, Xb, mesh=mesh))[:, 0]
    assert np.array_equal(sharded, ref)
