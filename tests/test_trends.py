"""Bench trend ledger (dryad_tpu/obs/trends.py + scripts/bench_trend.py).

Pins: the backfill-tolerant reader over unstamped r1–r7 artifacts AND
stamped r12+ ones, the spread-aware median comparison (a suspect capture
is never a regression verdict), the registry ingest, the artifact stamp,
and the CLI gate over the repo's real committed history."""

import json
import os
import subprocess
import sys

import pytest

from dryad_tpu.obs import Registry
from dryad_tpu.obs.trends import (
    SCHEMA_VERSION,
    artifact_stamp,
    compare,
    ingest,
    load_history,
    stats_provider,
)

ROOT = __file__.rsplit("/tests/", 1)[0]


def _write(path, doc):
    with open(path, "w") as f:
        json.dump(doc, f)


def _history(tmp_path, points, stamp_last=False):
    """points: list of metric dicts, written as driver-wrapper artifacts
    BENCH_r01..; stamp_last adds the r12 stamps to the newest."""
    for i, metrics in enumerate(points, start=1):
        doc = {"n": i, "cmd": "python bench.py", "rc": 0,
               "parsed": dict(metrics)}
        if stamp_last and i == len(points):
            doc["parsed"].update(schema_version=SCHEMA_VERSION,
                                 git_rev="abc1234", device_kind="TPU v4")
        _write(str(tmp_path / f"BENCH_r{i:02d}.json"), doc)
    return str(tmp_path)


# ---- reader -----------------------------------------------------------------

def test_load_history_backfill_tolerant(tmp_path):
    # r1: driver wrapper, unstamped; r2: flat bench.py line saved raw;
    # r3: stamped wrapper; plus junk that must be skipped, not fatal
    _write(str(tmp_path / "BENCH_r01.json"),
           {"n": 1, "rc": 0, "parsed": {"metric": "m", "value": 3.0}})
    _write(str(tmp_path / "BENCH_r02.json"),
           {"metric": "m", "value": 3.5, "rows": 200000})
    _write(str(tmp_path / "BENCH_r03.json"),
           {"n": 3, "parsed": {"metric": "m", "value": 4.0,
                               "schema_version": 1, "git_rev": "deadbee",
                               "device_kind": "cpu"}})
    with open(str(tmp_path / "BENCH_r04.json"), "w") as f:
        f.write("{ not json")
    _write(str(tmp_path / "BENCH_r05.json"), {"n": 5, "tail": "no metrics"})
    hist = load_history(str(tmp_path))
    assert [p["round"] for p in hist] == [1, 2, 3]
    assert hist[0]["git_rev"] is None            # backfill: unstamped
    assert hist[1]["metrics"]["value"] == 3.5    # flat artifact accepted
    assert hist[2]["git_rev"] == "deadbee"
    assert hist[2]["device_kind"] == "cpu"
    assert hist[2]["schema_version"] == 1
    # non-numeric fields never become metrics
    assert "metric" not in hist[0]["metrics"]


def test_load_history_real_committed_files():
    hist = load_history(ROOT)
    assert len(hist) >= 5
    assert hist[-1]["round"] == max(p["round"] for p in hist)
    assert all("value" in p["metrics"] for p in hist)


# ---- comparison -------------------------------------------------------------

BASE = {"value": 10.0, "marginal_s_per_iter_10m": 2.5,
        "spread_2tree_10m": 0.01, "spread_8tree_10m": 0.01}


def test_compare_ok_and_improved(tmp_path):
    root = _history(tmp_path, [BASE, BASE,
                               dict(BASE, value=14.0,
                                    marginal_s_per_iter_10m=2.4)])
    report = compare(load_history(root))
    assert report["ok"] and report["newest"] == "BENCH_r03.json"
    assert report["metrics"]["value"]["verdict"] == "improved"
    assert report["metrics"]["marginal_s_per_iter_10m"]["verdict"] == "ok"


def test_compare_flags_regression_against_median(tmp_path):
    # median of (2.4, 2.5, 2.6) = 2.5; newest 5.0 is 2x worse
    root = _history(tmp_path, [
        dict(BASE, marginal_s_per_iter_10m=2.4),
        dict(BASE, marginal_s_per_iter_10m=2.6),
        dict(BASE, marginal_s_per_iter_10m=2.5),
        dict(BASE, marginal_s_per_iter_10m=5.0)])
    report = compare(load_history(root))
    entry = report["metrics"]["marginal_s_per_iter_10m"]
    assert not report["ok"] and entry["verdict"] == "regression"
    assert entry["median"] == 2.5 and entry["n_history"] == 3


def test_compare_spread_vetoes_regression(tmp_path):
    """Suspect capture, never a regression verdict (CLAUDE.md): the same
    2x-worse point under a >5% per-arm spread downgrades to suspect."""
    bad = dict(BASE, marginal_s_per_iter_10m=5.0, spread_8tree_10m=0.2)
    root = _history(tmp_path, [BASE, BASE, bad])
    report = compare(load_history(root))
    assert report["ok"]
    assert report["metrics"]["marginal_s_per_iter_10m"][
        "verdict"] == "suspect"


def test_compare_new_metric_and_single_point(tmp_path):
    root = _history(tmp_path, [BASE, dict(BASE, obs_overhead_ms=1.5)])
    report = compare(load_history(root))
    assert report["metrics"]["obs_overhead_ms"]["verdict"] == "new"
    solo = compare(load_history(root)[:1])
    assert solo["ok"] and solo["metrics"]["value"]["verdict"] == "new"


def test_compare_higher_better_direction(tmp_path):
    root = _history(tmp_path, [BASE, BASE, dict(BASE, value=5.0)])
    report = compare(load_history(root))
    assert report["metrics"]["value"]["verdict"] == "regression"
    assert not report["ok"]


# ---- ingest + provider ------------------------------------------------------

def test_ingest_registry_series(tmp_path):
    root = _history(tmp_path, [BASE, dict(BASE, value=12.0)],
                    stamp_last=True)
    reg = Registry()
    n = ingest(load_history(root), reg)
    assert n > 0
    fam = reg.gauge("dryad_bench_value")
    assert fam.labels(metric="value", round=1).value() == 10.0
    assert fam.labels(metric="value", round=2).value() == 12.0
    assert reg.gauge("dryad_bench_rounds").value() == 2
    # spreads/rows are context, not tracked series
    assert not any("spread" in lbl for lbl in fam.series())
    disabled = Registry(enabled=False)
    assert ingest(load_history(root), disabled) == 0


def test_stats_provider_shape(tmp_path):
    root = _history(tmp_path, [BASE, BASE, BASE])
    provide = stats_provider(root)
    out = provide()
    assert out["bench_trends"]["ok"] and out["bench_trends"]["n_points"] == 3
    assert provide() is not None        # cached second call


# ---- PROFILE_r*.json ingestion (r13 stage profiler) ------------------------

PROF = {"profile_schema": 1, "stage_ms_hist_segmented": 136.0,
        "stage_spread_hist_segmented": 0.01,
        "stage_ms_route_gather": 30.0, "stage_spread_route_gather": 0.02,
        "stage_rows_hist_segmented": 10_000_000}


def _profile_history(tmp_path, points):
    for i, metrics in enumerate(points, start=1):
        _write(str(tmp_path / f"PROFILE_r{i:02d}.json"), dict(metrics))
    return str(tmp_path)


def test_profile_history_loads_and_tracks_stage_metrics(tmp_path):
    from dryad_tpu.obs.trends import PROFILE_PATTERN

    root = _profile_history(tmp_path, [PROF, dict(PROF,
                                                  stage_ms_route_gather=28.0)])
    hist = load_history(root, pattern=PROFILE_PATTERN)
    assert [p["round"] for p in hist] == [1, 2]
    report = compare(hist)
    assert report["ok"]
    assert report["metrics"]["stage_ms_route_gather"]["verdict"] == "ok"
    # context fields (rows) are never tracked metrics
    assert "stage_rows_hist_segmented" not in report["metrics"]


def test_profile_regression_flagged_and_spread_vetoed(tmp_path):
    """A 2x-slower stage regresses vs the median; the SAME point with a
    seeded noisy spread downgrades to suspect (the CLAUDE.md veto)."""
    from dryad_tpu.obs.trends import PROFILE_PATTERN

    bad = dict(PROF, stage_ms_hist_segmented=270.0)
    root = _profile_history(tmp_path, [PROF, PROF, PROF, bad])
    report = compare(load_history(root, pattern=PROFILE_PATTERN))
    entry = report["metrics"]["stage_ms_hist_segmented"]
    assert not report["ok"] and entry["verdict"] == "regression"

    noisy = dict(bad, stage_spread_hist_segmented=0.2)
    _write(str(tmp_path / "PROFILE_r04.json"), noisy)
    report = compare(load_history(root, pattern=PROFILE_PATTERN))
    entry = report["metrics"]["stage_ms_hist_segmented"]
    assert report["ok"] and entry["verdict"] == "suspect"


def test_profile_history_backfill_tolerant(tmp_path):
    """An unstamped artifact (no schema_version — the stamp is
    best-effort) still loads via its profile_schema marker; junk files
    skip, never fatal."""
    from dryad_tpu.obs.trends import PROFILE_PATTERN

    unstamped = {k: v for k, v in PROF.items()}     # no schema_version
    _write(str(tmp_path / "PROFILE_r01.json"), unstamped)
    _write(str(tmp_path / "PROFILE_r02.json"),
           dict(PROF, schema_version=1, git_rev="abc", device_kind="cpu"))
    with open(str(tmp_path / "PROFILE_r03.json"), "w") as f:
        f.write("{ torn")
    hist = load_history(str(tmp_path), pattern=PROFILE_PATTERN)
    assert [p["round"] for p in hist] == [1, 2]
    assert hist[0]["git_rev"] is None and hist[1]["git_rev"] == "abc"


def test_stats_provider_mounts_profile_trends(tmp_path):
    root = _history(tmp_path, [BASE, BASE])
    out = stats_provider(root)()
    assert "profile_trends" not in out          # no PROFILE files
    _profile_history(tmp_path, [PROF, PROF])
    out = stats_provider(root)()
    assert out["profile_trends"]["ok"]
    assert out["profile_trends"]["n_points"] == 2


def test_profile_ingest_registry_series(tmp_path):
    from dryad_tpu.obs.trends import PROFILE_PATTERN

    root = _profile_history(tmp_path, [PROF])
    reg = Registry()
    n = ingest(load_history(root, pattern=PROFILE_PATTERN), reg)
    assert n == 2        # two stage_ms_* metrics, spreads/rows untracked
    fam = reg.gauge("dryad_bench_value")
    assert fam.labels(metric="stage_ms_route_gather", round=1).value() == 30.0


# ---- artifact stamp ---------------------------------------------------------

def test_artifact_stamp_in_repo_and_outside(tmp_path):
    stamp = artifact_stamp(device_kind="cpu", root=ROOT)
    assert stamp["schema_version"] == SCHEMA_VERSION
    assert stamp["device_kind"] == "cpu"
    assert stamp["git_rev"]          # this repo IS a git checkout
    lost = artifact_stamp(device_kind=None, root=str(tmp_path))  # no git here
    assert lost["git_rev"] is None and lost["device_kind"] is None
    # r23: the default resolves through the ONE derivation
    from dryad_tpu.policy.device import current_device_kind
    auto = artifact_stamp(root=str(tmp_path))
    assert auto["device_kind"] == current_device_kind()


# ---- the CLI gate -----------------------------------------------------------

@pytest.mark.parametrize("args,rc", [(["--check"], 0), (["--selftest"], 0)])
def test_bench_trend_cli_on_committed_history(args, rc):
    env = dict(os.environ, PYTHONPATH=ROOT)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "bench_trend.py"),
         "--root", ROOT] + args,
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == rc, proc.stdout + proc.stderr


def test_bench_trend_cli_check_fails_on_seeded_regression(tmp_path):
    _history(tmp_path, [BASE, BASE, BASE,
                        dict(BASE, marginal_s_per_iter_10m=6.0)])
    env = dict(os.environ, PYTHONPATH=ROOT)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "bench_trend.py"),
         "--root", str(tmp_path), "--check"],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 1
    assert "TREND REGRESSION" in proc.stderr
