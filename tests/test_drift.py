"""Model-quality drift telemetry (obs/drift.py + data/profile.py, r18).

Pinned here (the ISSUE's satellites):

* reference-profile round-trip: text save -> ``load_any`` -> bitwise-
  equal profile; binary likewise; profile-less (pre-r18) files still
  load; ``dryad.train`` attaches a profile unless DRYAD_PROFILE=0;
* PSI exact-merge property: the fleet verdict on counts merged across
  1/2/4 monitors equals the verdict on the concatenated observations
  BITWISE (merge counts, never ratios);
* the serve path: monitors ride the batcher's binned ``_prepare``
  output + the executed raw scores, shifted traffic breaches, training-
  distribution traffic does not, and the two-epoch window forgets;
* zero-cost disabled: with the obs registry off the request path
  allocates NO drift state (tracemalloc, the r17 RequestTrace contract);
* the router: exact merge across stub replicas, ``dryad_fleet_drift_*``
  gauges, ``GET /drift`` verdicts, warn-only /healthz, journaled
  ``drift_breach``;
* DriftGate semantics: sustained breach, empty-window hold, recovery,
  on_breach fired once per transition.

Everything runs forced-CPU and jax-free below the profile build.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
import tracemalloc

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.data.profile import (ReferenceProfile,
                                    build_reference_profile,
                                    profile_from_binned)
from dryad_tpu.datasets import higgs_like
from dryad_tpu.obs.drift import (DEFAULT_PSI_BUDGET, SCORE_BUCKETS,
                                 DriftGate, DriftMonitor, drift_report,
                                 merge_drift_states, parse_psi_budget, psi,
                                 score_bucket_index)
from dryad_tpu.obs.registry import Registry, set_default_registry
from dryad_tpu.serve import PredictServer

DISABLED = Registry(enabled=False)


@pytest.fixture(scope="module")
def model():
    X, y = higgs_like(900, seed=7)
    ds = dryad.Dataset(X, y, max_bins=32)
    booster = dryad.train(dict(objective="binary", num_trees=8, num_leaves=7,
                               max_bins=32, seed=3), ds, backend="cpu")
    booster.profile = build_reference_profile(booster, ds)
    return booster, X, ds


def _shift_binned(Xb: np.ndarray, by: int = 8) -> np.ndarray:
    top = np.iinfo(Xb.dtype).max if Xb.dtype.kind == "u" else 255
    return np.minimum(Xb.astype(np.int64) + by,
                      min(top, 31)).astype(Xb.dtype)


# ---------------------------------------------------------------------------
# reference profile: build + round-trips + back-compat


def test_profile_shape_and_missing_rate(model):
    booster, X, ds = model
    p = booster.profile
    assert p.num_features == X.shape[1]
    assert p.n_rows == X.shape[0]
    # counts cover every row, per feature
    for c in p.feature_counts:
        assert sum(c) == p.n_rows
    assert p.missing_rate() == [c[0] / p.n_rows for c in p.feature_counts]
    assert "train" in p.score_hist
    counts, total, n = p.score_hist["train"]
    assert n == p.n_rows and sum(counts) == n


def test_profile_valid_split_and_subsample(model):
    booster, X, ds = model
    Xv, yv = higgs_like(300, seed=8)
    vds = dryad.Dataset(Xv, yv, mapper=ds.mapper)
    p = build_reference_profile(booster, ds, [vds])
    assert sorted(p.score_hist) == ["train", "valid"]
    assert p.score_hist["valid"][2] == 300
    # the stride subsample caps the profile deterministically
    p_small = build_reference_profile(booster, ds, max_rows=100)
    assert p_small.n_rows <= 100
    p_small2 = build_reference_profile(booster, ds, max_rows=100)
    assert p_small == p_small2


def test_profile_text_roundtrip_bitwise(model, tmp_path):
    booster, _X, _ds = model
    path = str(tmp_path / "m.txt")
    booster.save_text(path)
    again = dryad.Booster.load_any(path)
    assert again.profile is not None
    assert again.profile == booster.profile
    # and the re-dump is byte-identical (floats round-trip exactly)
    assert again.dump_text() == booster.dump_text()


def test_profile_binary_roundtrip_bitwise(model, tmp_path):
    booster, _X, _ds = model
    path = str(tmp_path / "m.dryad")
    booster.save(path)
    again = dryad.Booster.load_any(path)
    assert again.profile == booster.profile


def test_profileless_models_still_load(model, tmp_path):
    """Back-compat pin: pre-r18 artifacts carry no profile section and
    must keep loading (profile None), in BOTH formats."""
    booster, _X, _ds = model
    saved = booster.profile
    try:
        booster.profile = None
        bin_path = str(tmp_path / "old.dryad")
        txt_path = str(tmp_path / "old.txt")
        booster.save(bin_path)
        booster.save_text(txt_path)
    finally:
        booster.profile = saved
    assert dryad.Booster.load_any(bin_path).profile is None
    old = dryad.Booster.load_any(txt_path)
    assert old.profile is None
    assert "profile" not in json.loads(old.dump_text())
    # predictions unaffected by the missing section
    Xb = _ds_head(model)
    np.testing.assert_array_equal(
        old.predict_binned(Xb, raw_score=True),
        booster.predict_binned(Xb, raw_score=True))


def _ds_head(model, n: int = 64) -> np.ndarray:
    return model[2].X_binned[:n]


def test_train_attaches_profile_env_gated(monkeypatch):
    X, y = higgs_like(200, seed=11)
    params = dict(objective="binary", num_trees=2, num_leaves=4, max_bins=16)
    monkeypatch.setenv("DRYAD_PROFILE", "1")
    b_on = dryad.train(params, dryad.Dataset(X, y, max_bins=16),
                       backend="cpu")
    assert isinstance(b_on.profile, ReferenceProfile)
    assert b_on.profile.n_rows == 200
    monkeypatch.setenv("DRYAD_PROFILE", "0")
    b_off = dryad.train(params, dryad.Dataset(X, y, max_bins=16),
                        backend="cpu")
    assert b_off.profile is None


# ---------------------------------------------------------------------------
# PSI + score buckets


def test_score_bucket_index_le_semantics():
    for i, b in enumerate(SCORE_BUCKETS):
        assert score_bucket_index(b) == i                 # on the bound
    assert score_bucket_index(SCORE_BUCKETS[0] - 1.0) == 0
    assert score_bucket_index(SCORE_BUCKETS[-1] * 2) == len(SCORE_BUCKETS)
    assert score_bucket_index(float("nan")) == len(SCORE_BUCKETS)
    assert score_bucket_index(0.0) == len(SCORE_BUCKETS) // 2


def test_psi_properties():
    assert psi([10, 10, 10], [10, 10, 10]) == 0.0
    assert psi([10, 10, 10], [1, 1, 28]) > 0.5
    assert psi([0, 0, 0], [1, 2, 3]) == 0.0               # no evidence
    # symmetric-ish in magnitude, always finite with empty bins
    assert np.isfinite(psi([30, 0, 0], [0, 0, 30]))
    with pytest.raises(ValueError):
        psi([1, 2], [1, 2, 3])
    assert parse_psi_budget("") == DEFAULT_PSI_BUDGET
    assert parse_psi_budget("off") is None
    assert parse_psi_budget("0.35") == 0.35


def test_exact_merge_property_1_2_4_replicas(model):
    """The fleet invariant: counts merged across k monitors equal one
    monitor fed the concatenation — bitwise, for k in {1, 2, 4} — and
    PSI on the merge equals PSI on the concatenation exactly."""
    booster, _X, ds = model
    p = booster.profile
    Xb = ds.X_binned
    batches = [Xb[i * 60:(i + 1) * 60] for i in range(8)]
    scores = [booster.predict_binned(b, raw_score=True) for b in batches]

    def fed(k: int):
        mons = [DriftMonitor(p.feature_counts,
                             ref_score_state=p.score_hist["train"],
                             model="m", window_rows=10 ** 6,
                             registry=DISABLED) for _ in range(k)]
        for i, (b, s) in enumerate(zip(batches, scores)):
            mons[i % k].observe_features(b)
            mons[i % k].observe_scores(s)
        return merge_drift_states([m.export_state() for m in mons])

    want = fed(1)
    for k in (2, 4):
        got = fed(k)
        assert got["features"] == want["features"]
        assert got["rows"] == want["rows"]
        assert got["score"][0] == want["score"][0]
        assert got["score"][2] == want["score"][2]
        ra = drift_report(got, budget_psi=0.2)
        rb = drift_report(want, budget_psi=0.2)
        assert ra["psi_max"] == rb["psi_max"]          # bitwise floats
        assert ra["score_psi"] == rb["score_psi"]
        assert ra["top"] == rb["top"]
    with pytest.raises(ValueError):
        merge_drift_states([want, {"model": "m", "rows": 1, "bins": [2],
                                   "features": [[1, 0]]}])


def test_monitor_breach_and_no_false_positive(model):
    booster, _X, ds = model
    p = booster.profile
    Xb = ds.X_binned

    def mon():
        return DriftMonitor(p.feature_counts,
                            ref_score_state=p.score_hist["train"],
                            model="m", window_rows=1024, registry=DISABLED)

    clean = mon()
    clean.observe_features(Xb[:500])
    clean.observe_scores(booster.predict_binned(Xb[:500], raw_score=True))
    r = clean.snapshot(DEFAULT_PSI_BUDGET)
    assert r["rows"] == 500 and not r["breached"]

    shifted = mon()
    sb = _shift_binned(Xb[:500])
    shifted.observe_features(sb)
    shifted.observe_scores(booster.predict_binned(sb, raw_score=True))
    r2 = shifted.snapshot(DEFAULT_PSI_BUDGET)
    assert r2["breached"] and r2["psi_max"] > DEFAULT_PSI_BUDGET
    assert r2["top"] and r2["features_over"] >= 1


def test_window_rotation_forgets_old_traffic(model):
    """The two-epoch recency contract: a shift burst followed by >= one
    full window of clean traffic drops back under budget."""
    booster, _X, ds = model
    p = booster.profile
    Xb = ds.X_binned
    m = DriftMonitor(p.feature_counts, model="m", window_rows=800,
                     registry=DISABLED)
    m.observe_features(_shift_binned(Xb[:400]))
    assert drift_report(m.export_state(),
                        budget_psi=DEFAULT_PSI_BUDGET)["breached"]
    for start in range(0, 800, 400):        # two full epochs of clean rows
        m.observe_features(Xb[start:start + 400])
    r = drift_report(m.export_state(), budget_psi=DEFAULT_PSI_BUDGET)
    assert not r["breached"], r


def test_monitor_ignores_malformed_batches(model):
    booster, _X, ds = model
    p = booster.profile
    m = DriftMonitor(p.feature_counts, model="m", registry=DISABLED)
    m.observe_features(np.zeros((0, p.num_features), np.uint8))
    m.observe_features(np.zeros((4, p.num_features + 3), np.uint8))
    m.observe_scores(np.zeros((0,), np.float32))
    assert m.export_state()["rows"] == 0
    # out-of-range bin ids clip into the last bin instead of corrupting
    # the flat layout
    wild = np.full((3, p.num_features), 255, np.uint8)
    m.observe_features(wild)
    st = m.export_state()
    assert st["rows"] == 3
    for f, c in enumerate(st["features"]):
        assert c[-1] == 3 and sum(c) == 3
    # ...and NEGATIVE ids (the signed direct API) floor into bin 0
    # instead of bleeding into the previous feature's flat range
    m.observe_features(np.full((2, p.num_features), -1, np.int32))
    st = m.export_state()
    assert st["rows"] == 5
    for c in st["features"]:
        assert c[0] == 2 and sum(c) == 5


def test_monitor_gauges(model):
    booster, _X, ds = model
    p = booster.profile
    reg = Registry()
    m = DriftMonitor(p.feature_counts, model="m1", window_rows=256,
                     registry=reg)
    m.observe_features(_shift_binned(ds.X_binned[:100]))
    r = m.snapshot(DEFAULT_PSI_BUDGET)
    snap = reg.snapshot()["gauges"]
    assert snap["dryad_drift_psi_max"]['model="m1"'] == r["psi_max"]
    assert snap["dryad_drift_rows"]['model="m1"'] == 100
    assert any(k.startswith('feature=')
               for k in snap["dryad_drift_psi"])


# ---------------------------------------------------------------------------
# DriftGate verdicts


def test_gate_sustained_breach_hold_and_recovery():
    breaches: list = []
    gate = DriftGate(0.2, breach_after=2, registry=DISABLED,
                     on_breach=lambda m, v: breaches.append((m, v)))
    bad = {"m": {"rows": 100, "psi_max": 1.5, "score_psi": 0.0, "top": []}}
    good = {"m": {"rows": 100, "psi_max": 0.01, "score_psi": 0.0, "top": []}}
    empty = {"m": {"rows": 0, "psi_max": 0.0, "score_psi": 0.0, "top": []}}
    v1 = gate.evaluate(bad)
    assert v1["m"]["breached"] and not v1["m"]["sustained"]
    assert gate.ok and not breaches and gate.warnings() == []
    v2 = gate.evaluate(bad)
    assert v2["m"]["sustained"] and not gate.ok
    assert breaches == [("m", v2["m"])]            # fired exactly once
    assert gate.warnings() == ["drift:m"]
    # an empty window is no evidence: warning holds, no re-fire
    v3 = gate.evaluate(empty)
    assert v3["m"]["sustained"] and gate.warnings() == ["drift:m"]
    assert len(breaches) == 1
    # recovery needs a non-empty in-budget window
    v4 = gate.evaluate(good)
    assert not v4["m"]["sustained"] and gate.ok and gate.warnings() == []
    # a NEW sustained breach fires on_breach again (a fresh incident)
    gate.evaluate(bad)
    gate.evaluate(bad)
    assert len(breaches) == 2
    assert gate.verdicts()["m"]["sustained"]


def test_gate_score_psi_alone_breaches():
    gate = DriftGate(0.2, breach_after=1, registry=DISABLED)
    v = gate.evaluate({"m": {"rows": 10, "psi_max": 0.0, "score_psi": 0.9,
                             "top": []}})
    assert v["m"]["sustained"]


# ---------------------------------------------------------------------------
# the serve path


def test_serve_path_monitors_and_report(model):
    booster, X, ds = model
    reg = Registry()
    old = set_default_registry(reg)
    try:
        server = PredictServer(backend="cpu", max_batch_rows=512,
                               max_wait_ms=0.5, drift_window=2048)
        server.registry.add(booster)
        with server:
            server.predict(X[:300])                       # raw path (binned
            server.predict(ds.X_binned[:200], binned=True)  # + binned path)
            report = server.drift_report(DEFAULT_PSI_BUDGET)
        assert list(report) == ["v1"]
        r = report["v1"]
        assert r["rows"] == 500 and not r["breached"]
        # scores were observed from the executed raw margins
        state = server.drift_state()["v1"]
        assert state["score"][2] == 500
        assert state["ref_score"] is not None
        # the stats surface
        snap = server.stats()
        assert snap["drift"]["v1"]["rows"] == 500
    finally:
        set_default_registry(old)


def test_serve_shifted_traffic_breaches(model):
    booster, X, ds = model
    reg = Registry()
    old = set_default_registry(reg)
    try:
        server = PredictServer(backend="cpu", max_batch_rows=64,
                               max_wait_ms=0.5, drift_window=256)
        server.registry.add(booster)
        with server:
            server.predict(_shift_binned(ds.X_binned[:300]), binned=True)
            report = server.drift_report(DEFAULT_PSI_BUDGET)
        assert report["v1"]["breached"]
    finally:
        set_default_registry(old)


def test_serve_profileless_model_costs_one_probe(model):
    booster, X, ds = model
    saved = booster.profile
    reg = Registry()
    old = set_default_registry(reg)
    try:
        booster.profile = None
        server = PredictServer(backend="cpu", max_batch_rows=64,
                               max_wait_ms=0.5)
        server.registry.add(booster)
        with server:
            server.predict(X[:8])
            assert server._drift_monitors == {1: None}   # cached verdict
            assert server.drift_report() == {}
            assert server.drift_state() == {}
        assert "drift" not in server.stats()
    finally:
        booster.profile = saved
        set_default_registry(old)


def test_serve_drift_disabled_allocates_nothing(model):
    """The zero-cost pin (the r17 RequestTrace contract): with the obs
    registry disabled the request path allocates NO drift state — the
    monitor table stays None and no frame of obs/drift.py or
    data/profile.py allocates."""
    booster, X, _ds = model
    reg = Registry(enabled=False)
    old = set_default_registry(reg)
    try:
        server = PredictServer(backend="cpu", max_batch_rows=64,
                               max_wait_ms=0.2)
        assert server._drift_monitors is None
        server.registry.add(booster)
        with server:
            rows = X[:2]
            for _ in range(16):                  # warm every code path
                server.predict(rows)

            def leaked() -> list:
                tracemalloc.start()
                for _ in range(100):
                    server.predict(rows)
                snap_mem = tracemalloc.take_snapshot()
                tracemalloc.stop()
                return [st for st in snap_mem.statistics("filename")
                        if st.traceback[0].filename.endswith(
                            ("obs/drift.py", "data/profile.py"))]

            for _ in range(3):
                bad = leaked()
                if not bad:
                    break
            assert not bad, f"disabled drift path allocated: {bad}"
        assert server.drift_report() == {}
    finally:
        set_default_registry(old)


def test_serve_drift_off_flag(model):
    booster, X, _ds = model
    server = PredictServer(backend="cpu", drift="off")
    assert server._drift_monitors is None
    server2 = PredictServer(backend="cpu", drift_window=0)
    assert server2._drift_monitors is None


# ---------------------------------------------------------------------------
# the router (stub replicas — the real-replica path is smoke_fleet.py)

STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "fleet_stub_server.py")


def _stub_argv(*extra: str):
    def make(index: int, port_file: str) -> list:
        return [sys.executable, STUB, "--port-file", port_file, *extra]
    return make


@contextlib.contextmanager
def _stub_fleet(tmp_path, n=2, stub_flags=(), router_kw=None):
    from dryad_tpu.fleet import FleetRouter, FleetSupervisor
    from dryad_tpu.resilience.policy import RetryPolicy

    reg = Registry()
    journal = str(tmp_path / "fleet.jsonl")
    sup = FleetSupervisor(_stub_argv(*stub_flags), n,
                          policy=RetryPolicy(backoff_base_s=0.0),
                          journal=journal, registry=reg,
                          probe_interval_s=0.05, startup_timeout_s=20.0)
    sup.start()
    router = FleetRouter(sup, registry=reg, **(router_kw or {})).start()
    try:
        yield sup, router, reg, journal
    finally:
        router.stop()
        sup.stop()


def _get(router, path):
    import http.client

    conn = http.client.HTTPConnection(router.host, router.port, timeout=15.0)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_router_drift_disabled_by_default(tmp_path):
    with _stub_fleet(tmp_path) as (_sup, router, _reg, _journal):
        status, body = _get(router, "/drift")
        assert status == 200 and json.loads(body) == {"enabled": False}


def test_router_merges_and_verdicts_shifted_stubs(tmp_path):
    """Two shifted stub replicas: the router merges their drift counts
    exactly (2x one stub's counts), flips the verdict, journals ONE
    drift_breach, serves the fleet gauges, and /healthz stays 200 with
    the warning in its payload (warn-only)."""
    from dryad_tpu.resilience.journal import RunJournal

    kw = {"drift_budget_psi": 0.2, "drift_breach_after": 2}
    with _stub_fleet(tmp_path, stub_flags=("--drift-shift",),
                     router_kw=kw) as (_sup, router, reg, journal):
        status, body = _get(router, "/drift")
        doc1 = json.loads(body)
        assert status == 200 and doc1["enabled"]
        v1 = doc1["models"]["stub"]
        # exact merge: 2 replicas x 32 rows, counts doubled not averaged
        assert v1["rows"] == 64
        assert v1["breached"] and not v1["sustained"]
        status, body = _get(router, "/drift")
        doc2 = json.loads(body)
        v2 = doc2["models"]["stub"]
        assert v2["sustained"] and doc2["warnings"] == ["drift:stub"]
        assert v2["top"]                        # offending features named
        # warn-only: health stays 200, payload carries the warning
        status, body = _get(router, "/healthz")
        health = json.loads(body)
        assert status == 200 and health["ok"]
        assert health["drift"]["warnings"] == ["drift:stub"]
        # merged gauges ride the aggregated scrape
        status, body = _get(router, "/metrics")
        text = body.decode()
        assert 'dryad_fleet_drift_psi_max{model="stub"}' in text
        assert 'dryad_fleet_drift_rows{model="stub"} 64' in text
        events = RunJournal.read(journal)
    breaches = [e for e in events if e["event"] == "drift_breach"]
    assert len(breaches) == 1 and breaches[0]["model"] == "stub"
    assert breaches[0]["features"]


def test_router_clean_stubs_stay_green(tmp_path):
    kw = {"drift_budget_psi": 0.2, "drift_breach_after": 1}
    with _stub_fleet(tmp_path, router_kw=kw) as (_s, router, _r, journal):
        for _ in range(2):
            _status, body = _get(router, "/drift")
        doc = json.loads(body)
        v = doc["models"]["stub"]
        assert not v["breached"] and doc["warnings"] == []
        status, body = _get(router, "/healthz")
        assert json.loads(body)["drift"]["warnings"] == []


# ---------------------------------------------------------------------------
# bench + trends plumbing


def test_trends_track_drift_overhead():
    from dryad_tpu.obs.trends import _direction, _spread_fields_of

    assert _direction("drift_overhead_ms") == "lower_better"
    assert _direction("drift_overhead_pct") == "lower_better"
    assert _spread_fields_of("drift_overhead_ms") == (
        "drift_overhead_spread",)
    assert _direction("drift_overhead_spread") is None   # context field


def test_bench_drift_arm_smoke(model):
    """run_bench_drift measures a LIVE monitor (raises otherwise) and
    reports the overhead fields (values are noise at this duration; the
    shape and the live-monitor proof are the pins)."""
    from dryad_tpu.serve.bench import run_bench_drift

    booster, _X, _ds = model
    out = run_bench_drift(booster, backend="cpu", clients=2,
                          duration_s=0.2, sizes=(1, 3), arms=1,
                          max_batch_rows=64)
    for key in ("drift_overhead_ms", "drift_overhead_pct",
                "drift_overhead_spread"):
        assert key in out
    assert out["drift_windows"]                  # the monitor really ran


def test_profile_from_binned_synthesizes_baseline(model):
    booster, _X, ds = model
    p = profile_from_binned(booster, ds.X_binned[:128])
    assert p.n_rows == 128 and "train" in p.score_hist
