"""Multi-host entry points on the 8-fake-device CPU mesh (SURVEY.md §4:
the same shard_map/psum code paths run in CI with no TPU)."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
# r19: slow — the mocked multi-host drills replay the sharded interpret
# paths across 8 fake devices; part of the tier-1 870 s re-budget
# (ci.sh runs `-m 'not slow'`; run explicitly when touching distributed/).
pytestmark = pytest.mark.slow

from dryad_tpu.distributed import (
    global_mesh,
    host_row_range,
    sketch_distributed,
    train_distributed,
)
from dryad_tpu.data.streaming import dataset_from_chunks, sketch_stream


def test_global_mesh_spans_all_devices():
    import jax

    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices()) == 8


def test_host_row_range_partitions_exactly():
    start, stop = host_row_range(1003)
    assert (start, stop) == (0, 1003)  # single process owns everything


def test_train_distributed_matches_single_device():
    X, y = higgs_like(2048, seed=61)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective="binary", num_trees=4, num_leaves=7, max_bins=32)
    b_mesh = train_distributed(p, ds)
    b_one = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(b_mesh.feature, b_one.feature)
    np.testing.assert_array_equal(b_mesh.threshold, b_one.threshold)
    np.testing.assert_allclose(b_mesh.value, b_one.value, atol=1e-3)


def test_sketch_distributed_invariant_to_partitioning():
    X, _ = higgs_like(5000, seed=63)
    # one "host" with everything vs simulated two-host partition exchanging
    # samples through a fake allgather
    m_all = sketch_distributed(X, 5000, 0, max_bins=32, sample_rows=1024)

    # emulate: collect both hosts' samples, then sketch the union per host
    samples = {}
    for who, (lo, hi) in enumerate([(0, 2600), (2600, 5000)]):
        from dryad_tpu.distributed import _global_row_uniform

        keep = _global_row_uniform(lo, hi - lo, 0) < 1024 / 5000
        samples[who] = X[lo:hi][keep]
    union = [samples[0], samples[1]]
    m_two = sketch_distributed(
        X[0:2600], 5000, 0, max_bins=32, sample_rows=1024,
        allgather=lambda arr: union,
    )
    for fa, fb in zip(m_all.features, m_two.features):
        np.testing.assert_array_equal(fa.edges, fb.edges)


def test_streaming_dataset_matches_in_memory_bins():
    X, y = higgs_like(3000, seed=65)

    def chunks():
        for s in range(0, 3000, 700):
            yield X[s : s + 700]

    ds_stream = dataset_from_chunks(chunks, y, 3000, X.shape[1], max_bins=32)
    # binning through the SAME mapper must equal the in-memory transform
    np.testing.assert_array_equal(
        ds_stream.X_binned, ds_stream.mapper.transform(X))
    # sketch is chunking-invariant
    m2 = sketch_stream(lambda: (X[s:s + 1100] for s in range(0, 3000, 1100)),
                       3000, max_bins=32)
    for fa, fb in zip(ds_stream.mapper.features, m2.features):
        np.testing.assert_array_equal(fa.edges, fb.edges)
    # and trains
    b = dryad.train(dict(objective="binary", num_trees=3, num_leaves=7,
                         max_bins=32), ds_stream, backend="cpu")
    assert b.num_iterations == 3


def test_default_allgather_multiprocess_branch(monkeypatch):
    """_default_allgather's process_count>1 path (pad to max local length,
    allgather, slice back) — exercised with mocked multihost primitives
    since CI has one process (VERDICT r1 weak item 4)."""
    import dryad_tpu.distributed as D

    parts = [np.arange(5, dtype=np.float32).reshape(5, 1),
             np.arange(3, dtype=np.float32).reshape(3, 1) + 100,
             np.zeros((0, 1), np.float32)]  # one host holds NOTHING

    class FakeJax:
        @staticmethod
        def process_count():
            return len(parts)

    class FakeMHU:
        calls = []

        @staticmethod
        def process_allgather(arr):
            # scalar length exchange, then the padded-array exchange
            FakeMHU.calls.append(np.asarray(arr))
            if np.asarray(arr).ndim == 0:
                return np.array([p.shape[0] for p in parts], np.int64)
            m = max(p.shape[0] for p in parts)
            stacked = np.stack([
                np.concatenate([p, np.zeros((m - p.shape[0],) + p.shape[1:],
                                            p.dtype)])
                for p in parts
            ])
            return stacked

    import jax as real_jax
    from jax.experimental import multihost_utils as real_mhu

    monkeypatch.setattr(real_jax, "process_count", FakeJax.process_count)
    monkeypatch.setattr(real_mhu, "process_allgather",
                        FakeMHU.process_allgather)

    out = D._default_allgather(parts[0])
    assert len(out) == 3
    np.testing.assert_array_equal(out[0], parts[0])
    np.testing.assert_array_equal(out[1], parts[1])
    assert out[2].shape == (0, 1)  # empty shard survives the pad/slice


def test_csr_stream_bundle_mesh_train_end_to_end():
    """The Criteo-1TB composition (VERDICT r2 #5): sparse CSR chunk stream
    -> distributed sketch -> streamed EFB (prefix plan + exact streaming
    verification + chunkwise fold) -> sharded mesh training.  The streamed
    dataset must be BIT-IDENTICAL to in-memory CSR ingest of the same rows
    (same bins, same bundles), and mesh training must match single-device
    training on it (N-shard ≡ 1-shard)."""
    from dryad_tpu.data.bundling import BundledMapper
    from dryad_tpu.data.streaming import dataset_from_csr_chunks
    from dryad_tpu.distributed import sketch_distributed
    from dryad_tpu.engine.distributed import make_mesh
    from dryad_tpu.engine.train import train_device
    from tests.test_bundling import _onehot_csr

    (indptr, cols, vals, F), y = _onehot_csr(n=4096)
    n = 4096

    def chunks():
        for lo in range(0, n, 1000):
            hi = min(lo + 1000, n)
            a, b = indptr[lo], indptr[hi]
            yield (indptr[lo:hi + 1] - a, cols[a:b], vals[a:b])

    # distributed sketch over the (densified) local sample shard — single
    # process: the allgather is identity, but the keyed subsample is the
    # same partition-invariant path multi-host uses
    dense = np.zeros((n, F), np.float32)
    for r in range(n):
        a, b = indptr[r], indptr[r + 1]
        dense[r, cols[a:b]] = vals[a:b]
    mapper = sketch_distributed(dense, n, 0, max_bins=64)

    ds_stream = dataset_from_csr_chunks(
        chunks, y, n, F, max_bins=64, mapper=mapper, plan_rows=1500)
    # the prefix plan may differ from a full-matrix plan (fewer rows seen),
    # but the CONTRACT holds: every streamed bundle is strictly exclusive
    # over the full data, and the streamed fold is bit-identical to folding
    # the whole matrix through the stream's own plan
    from dryad_tpu.data.binning import bin_csr, zero_bins

    assert isinstance(ds_stream.mapper, BundledMapper)
    assert ds_stream.mapper.bundles, "stream must actually bundle"
    Xb0 = bin_csr(indptr, cols, vals, F, mapper)
    zb = zero_bins(mapper)
    for members in ds_stream.mapper.bundles:
        nz = (Xb0[:, members] != zb[members][None, :])
        assert (nz.sum(axis=1) <= 1).all(), "bundle not exclusive end to end"
    np.testing.assert_array_equal(ds_stream.X_binned,
                                  ds_stream.mapper.fold(Xb0))

    import jax

    from dryad_tpu.config import make_params

    params = make_params(dict(objective="binary", num_trees=4, num_leaves=15,
                              max_bins=64, max_depth=5, growth="depthwise"))
    mesh = make_mesh(jax.devices()[:8])
    b_mesh = train_device(params, ds_stream, mesh=mesh)
    b_one = train_device(params, ds_stream)
    np.testing.assert_array_equal(b_mesh.feature, b_one.feature)
    np.testing.assert_array_equal(b_mesh.threshold, b_one.threshold)


def test_multihost_kill_resume_drill(tmp_path, monkeypatch):
    """Worker-loss drill (SURVEY.md §5 failure detection), multi-host
    branches mocked: a mesh training run with NaN-bearing data (so the
    learn_missing process_allgather agreement executes) checkpoints, is
    killed mid-run, and a fresh "restarted worker" resumes from the last
    snapshot under the same mocks — reproducing the uninterrupted run's
    trees and predictions bit for bit."""
    import jax as real_jax
    from jax.experimental import multihost_utils as real_mhu

    from dryad_tpu.checkpoint import Checkpointer
    from dryad_tpu.config import make_params
    from dryad_tpu.engine.distributed import make_mesh
    from dryad_tpu.engine.train import train_device

    # two mocked processes that happen to share one test process: the
    # allgather agreement sees both hosts' flags
    gathered = []

    def fake_allgather(arr):
        gathered.append(np.asarray(arr))
        return np.stack([np.asarray(arr), np.asarray(arr)])

    monkeypatch.setattr(real_jax, "process_count", lambda: 2)
    monkeypatch.setattr(real_mhu, "process_allgather", fake_allgather)

    X, y = higgs_like(2048, seed=71)
    X = X.copy()
    X[::13, 2] = np.nan                       # exercises the allgather
    ds = dryad.Dataset(X, y, max_bins=32)
    params = make_params(dict(objective="binary", num_trees=9, num_leaves=7,
                              max_bins=32, max_depth=4, growth="depthwise"))
    mesh = make_mesh(real_jax.devices()[:4])

    # uninterrupted reference
    b_ref = train_device(params, ds, mesh=mesh)
    assert gathered, "learn_missing agreement must have run"

    # killed run: checkpoints every 3 iterations, "crashes" after 5
    ck = Checkpointer(str(tmp_path), every=3)
    killed = {}

    def bomb(it, info):
        if it == 5:
            killed["at"] = it
            raise KeyboardInterrupt("worker lost")

    try:
        train_device(params, ds, mesh=mesh, callback=bomb, checkpointer=ck)
    except KeyboardInterrupt:
        pass
    assert killed["at"] == 5

    # restarted worker: fresh Checkpointer (new process), same mocks
    ck2 = Checkpointer(str(tmp_path), every=3)
    prev, done = ck2.latest()
    assert 0 < done < 9
    b_res = train_device(params, ds, mesh=mesh, init_booster=prev,
                         checkpointer=ck2)
    np.testing.assert_array_equal(b_res.feature, b_ref.feature)
    np.testing.assert_array_equal(b_res.threshold, b_ref.threshold)
    np.testing.assert_array_equal(
        b_res.predict_binned(ds.X_binned, raw_score=True),
        b_ref.predict_binned(ds.X_binned, raw_score=True))
    # comm observability rides the booster state on mesh runs
    cs = b_res.train_state["comm_stats"]
    assert cs["n_shards"] == 4 and cs["psum_bytes_per_iter"] > 0
