"""Multi-host entry points on the 8-fake-device CPU mesh (SURVEY.md §4:
the same shard_map/psum code paths run in CI with no TPU)."""

import numpy as np

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.distributed import (
    global_mesh,
    host_row_range,
    sketch_distributed,
    train_distributed,
)
from dryad_tpu.data.streaming import dataset_from_chunks, sketch_stream


def test_global_mesh_spans_all_devices():
    import jax

    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices()) == 8


def test_host_row_range_partitions_exactly():
    start, stop = host_row_range(1003)
    assert (start, stop) == (0, 1003)  # single process owns everything


def test_train_distributed_matches_single_device():
    X, y = higgs_like(2048, seed=61)
    ds = dryad.Dataset(X, y, max_bins=32)
    p = dict(objective="binary", num_trees=4, num_leaves=7, max_bins=32)
    b_mesh = train_distributed(p, ds)
    b_one = dryad.train(p, ds, backend="tpu")
    np.testing.assert_array_equal(b_mesh.feature, b_one.feature)
    np.testing.assert_array_equal(b_mesh.threshold, b_one.threshold)
    np.testing.assert_allclose(b_mesh.value, b_one.value, atol=1e-3)


def test_sketch_distributed_invariant_to_partitioning():
    X, _ = higgs_like(5000, seed=63)
    # one "host" with everything vs simulated two-host partition exchanging
    # samples through a fake allgather
    m_all = sketch_distributed(X, 5000, 0, max_bins=32, sample_rows=1024)

    # emulate: collect both hosts' samples, then sketch the union per host
    samples = {}
    for who, (lo, hi) in enumerate([(0, 2600), (2600, 5000)]):
        from dryad_tpu.distributed import _global_row_uniform

        keep = _global_row_uniform(lo, hi - lo, 0) < 1024 / 5000
        samples[who] = X[lo:hi][keep]
    union = [samples[0], samples[1]]
    m_two = sketch_distributed(
        X[0:2600], 5000, 0, max_bins=32, sample_rows=1024,
        allgather=lambda arr: union,
    )
    for fa, fb in zip(m_all.features, m_two.features):
        np.testing.assert_array_equal(fa.edges, fb.edges)


def test_streaming_dataset_matches_in_memory_bins():
    X, y = higgs_like(3000, seed=65)

    def chunks():
        for s in range(0, 3000, 700):
            yield X[s : s + 700]

    ds_stream = dataset_from_chunks(chunks, y, 3000, X.shape[1], max_bins=32)
    # binning through the SAME mapper must equal the in-memory transform
    np.testing.assert_array_equal(
        ds_stream.X_binned, ds_stream.mapper.transform(X))
    # sketch is chunking-invariant
    m2 = sketch_stream(lambda: (X[s:s + 1100] for s in range(0, 3000, 1100)),
                       3000, max_bins=32)
    for fa, fb in zip(ds_stream.mapper.features, m2.features):
        np.testing.assert_array_equal(fa.edges, fb.edges)
    # and trains
    b = dryad.train(dict(objective="binary", num_trees=3, num_leaves=7,
                         max_bins=32), ds_stream, backend="cpu")
    assert b.num_iterations == 3


def test_default_allgather_multiprocess_branch(monkeypatch):
    """_default_allgather's process_count>1 path (pad to max local length,
    allgather, slice back) — exercised with mocked multihost primitives
    since CI has one process (VERDICT r1 weak item 4)."""
    import dryad_tpu.distributed as D

    parts = [np.arange(5, dtype=np.float32).reshape(5, 1),
             np.arange(3, dtype=np.float32).reshape(3, 1) + 100,
             np.zeros((0, 1), np.float32)]  # one host holds NOTHING

    class FakeJax:
        @staticmethod
        def process_count():
            return len(parts)

    class FakeMHU:
        calls = []

        @staticmethod
        def process_allgather(arr):
            # scalar length exchange, then the padded-array exchange
            FakeMHU.calls.append(np.asarray(arr))
            if np.asarray(arr).ndim == 0:
                return np.array([p.shape[0] for p in parts], np.int64)
            m = max(p.shape[0] for p in parts)
            stacked = np.stack([
                np.concatenate([p, np.zeros((m - p.shape[0],) + p.shape[1:],
                                            p.dtype)])
                for p in parts
            ])
            return stacked

    import jax as real_jax
    from jax.experimental import multihost_utils as real_mhu

    monkeypatch.setattr(real_jax, "process_count", FakeJax.process_count)
    monkeypatch.setattr(real_mhu, "process_allgather",
                        FakeMHU.process_allgather)

    out = D._default_allgather(parts[0])
    assert len(out) == 3
    np.testing.assert_array_equal(out[0], parts[0])
    np.testing.assert_array_equal(out[1], parts[1])
    assert out[2].shape == (0, 1)  # empty shard survives the pad/slice
