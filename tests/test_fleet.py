"""Replicated serving fleet (dryad_tpu/fleet/).

The supervisor/router logic only ever sees the wire protocol, so these
tests spawn the pure-stdlib protocol stub (tests/fleet_stub_server.py,
~100 ms per replica) instead of paying a jax import per subprocess —
the REAL ``python -m dryad_tpu serve`` replica path runs in
``scripts/smoke_fleet.py`` (ci.sh) and the fleet bench.

Pinned here (the ISSUE's test-coverage satellite):

* rolling swap drains in-flight requests at the pinned version, zero
  requests dropped, and the journal records drain -> swap per replica;
* shed ordering under overload — interactive survives while bulk sheds
  first, and the per-model admission cap binds;
* crash -> respawn journal sequence, and retry-budget exhaustion fails
  the slot closed while the rest of the fleet keeps serving;
* fleet /metrics aggregation: per-replica labels injected, existing
  labels preserved, router-side families present;
* the replica fault drills (resilience/faults.py r14) through the REAL
  serve HTTP front end, in-process.
"""

from __future__ import annotations

import contextlib
import http.client
import json
import os
import sys
import threading
import time

import pytest

from dryad_tpu.fleet import FleetRouter, FleetSupervisor, ReplicaStartupError
from dryad_tpu.fleet.router import relabel_exposition
from dryad_tpu.obs.registry import Registry
from dryad_tpu.resilience import faults as F
from dryad_tpu.resilience.journal import RunJournal
from dryad_tpu.resilience.policy import RetryPolicy

STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "fleet_stub_server.py")


def stub_argv(*extra: str):
    """make_argv for a fleet where every replica runs the stub with the
    same flags; per-index shapes build their own closure."""
    def make(index: int, port_file: str) -> list:
        return [sys.executable, STUB, "--port-file", port_file, *extra]
    return make


@contextlib.contextmanager
def fleet(make_argv, n, tmp_path, *, policy=None, router_kw=None, **sup_kw):
    reg = Registry()
    journal = str(tmp_path / "fleet.jsonl")
    sup_kw.setdefault("startup_timeout_s", 20.0)
    sup = FleetSupervisor(
        make_argv, n,
        policy=policy or RetryPolicy(backoff_base_s=0.0),
        journal=journal, registry=reg,
        probe_interval_s=0.05, probe_timeout_s=1.0, **sup_kw)
    sup.start()
    router = FleetRouter(sup, registry=reg, **(router_kw or {})).start()
    try:
        yield sup, router, reg, journal
    finally:
        router.stop()
        sup.stop()


def http_call(host, port, method, path, body=None, headers=None,
              timeout=15.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        payload = (json.dumps(body).encode() if isinstance(body, dict)
                   else (body or b""))
        conn.request(method, path, body=payload, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def predict(router, rows=1, headers=None, timeout=15.0):
    status, body = http_call(router.host, router.port, "POST", "/predict",
                             {"rows": [[1.0, 2.0]] * rows},
                             headers=headers, timeout=timeout)
    try:
        return status, json.loads(body or b"{}")
    except ValueError:
        return status, {}


def wait_until(cond, timeout_s=10.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return False


def events_of(journal_path, kind):
    return [e for e in RunJournal.read(journal_path) if e["event"] == kind]


# ---------------------------------------------------------------------------
# fault-point plumbing (no subprocess)

def test_replica_fault_points_roundtrip_and_validation():
    pts = [F.FaultPoint(site="request", iteration=3, kind=F.REPLICA_CRASH),
           F.FaultPoint(site="health", iteration=1, kind=F.SLOW_HEALTH,
                        stall_s=2.5, sticky=True),
           F.FaultPoint(site="request", iteration=2, kind=F.REJECT_503,
                        sticky=True)]
    assert F.decode_points(F.encode_points(pts)) == pts
    assert F.injector_from_env({}) is None
    assert F.injector_from_env({F.REPLICA_FAULTS_ENV: ""}) is None
    with pytest.raises(ValueError):
        F.decode_points("request:replica_crash")       # missing iteration
    with pytest.raises(ValueError):
        # a misspelt "sticky" must fail loudly, not arm the one-shot form
        F.decode_points("health:1:reject_503:0:stikcy")
    with pytest.raises(ValueError):
        F.FaultPoint(site="nowhere", iteration=1, kind=F.REPLICA_CRASH)
    with pytest.raises(ValueError):
        F.FaultPoint(site="health", iteration=1, kind=F.SLOW_HEALTH)  # no stall
    with pytest.raises(ValueError):
        # kinds and sites partition strictly: a replica kind at a trainer
        # site would os._exit a training run (or never fire)
        F.FaultPoint(site="dispatch", iteration=1, kind=F.REPLICA_CRASH)
    with pytest.raises(ValueError):
        F.FaultPoint(site="request", iteration=1, kind=F.FETCH_DEATH)
    # drilled rejections must never classify as a retryable device fault
    assert F.classify_fault(F.InjectedReject("injected 503")) == F.UNKNOWN


def test_spawn_env_strips_inherited_fault_spec():
    """Replicas inherit the fleet process's environment: a
    DRYAD_REPLICA_FAULTS set there must be overridden to empty for every
    slot the supervisor is not deliberately arming — and even an armed
    slot is clean from generation 1 on (one drill = one death, never a
    respawn crash loop)."""
    sup = FleetSupervisor(lambda i, pf: ["true"], 2,
                          fault_env={0: "request:2:replica_crash"})
    armed, clean = sup.slots
    assert sup._spawn_env(armed) == {
        F.REPLICA_FAULTS_ENV: "request:2:replica_crash"}
    assert sup._spawn_env(clean) == {F.REPLICA_FAULTS_ENV: ""}
    armed.generation = 1                       # post-respawn: clean again
    assert sup._spawn_env(armed) == {F.REPLICA_FAULTS_ENV: ""}


def test_sticky_point_fires_repeatedly_exactly_once_otherwise():
    inj = F.FaultInjector([
        F.FaultPoint(site="request", iteration=2, kind=F.REJECT_503,
                     sticky=True),
        F.FaultPoint(site="health", iteration=2, kind=F.REJECT_503)])
    inj("request", 1)                                  # below threshold
    for n in (2, 3, 4):                                # sticky: every time
        with pytest.raises(F.InjectedReject):
            inj("request", n)
    with pytest.raises(F.InjectedReject):
        inj("health", 5)
    inj("health", 6)                                   # one-shot: disarmed
    assert [f["kind"] for f in inj.fired] == [F.REJECT_503] * 4
    assert inj.pending == 1                            # the sticky point


def test_relabel_exposition():
    text = ("# HELP x_total help\n# TYPE x_total counter\n"
            "x_total 3\n"
            'x_latency{path="/p",code="200"} 1.5\n'
            "x_hist_bucket{le=\"+Inf\"} 7\n")
    out = relabel_exposition(text, "r1")
    assert '# HELP' not in out                         # comments dropped
    assert 'x_total{replica="r1"} 3' in out
    assert 'x_latency{replica="r1",path="/p",code="200"} 1.5' in out
    assert 'x_hist_bucket{replica="r1",le="+Inf"} 7' in out


# ---------------------------------------------------------------------------
# routing + aggregation

def test_routing_metrics_aggregation_and_health(tmp_path):
    with fleet(stub_argv(), 2, tmp_path) as (sup, router, reg, journal):
        status, doc = predict(router, rows=3)
        assert status == 200 and len(doc["predictions"]) == 3
        # spread a few requests so both replicas serve
        for _ in range(5):
            assert predict(router)[0] == 200
        status, body = http_call(router.host, router.port, "GET", "/healthz")
        doc = json.loads(body)
        assert status == 200 and doc["ok"] is True
        assert set(doc["replicas"]) == {"r0", "r1"}
        status, body = http_call(router.host, router.port, "GET", "/metrics")
        text = body.decode()
        assert status == 200
        # per-replica labels injected, existing labels preserved, comments
        # not duplicated per replica
        assert 'stub_requests_total{replica="r0"}' in text
        assert 'stub_requests_total{replica="r1"}' in text
        assert 'stub_latency_ms{replica="r0",path="/predict"}' in text
        assert "# HELP stub_requests_total" not in text
        assert "dryad_fleet_request_total" in text
        # both replicas actually served (round robin)
        routed = reg.counter("dryad_fleet_routed_total", "")
        assert routed.labels(replica="r0").value() > 0
        assert routed.labels(replica="r1").value() > 0
        status, body = http_call(router.host, router.port, "GET", "/stats")
        snap = json.loads(body)
        assert snap["replicas"]["r0"]["healthy"] is True
        assert snap["max_inflight"] == 64


def test_authed_fleet_still_aggregates_replica_metrics(tmp_path):
    """With bearer auth on, the router must scrape replicas WITH the
    token (regression: an unauthed scrape 401s and every per-replica
    series silently vanishes), forward authed predicts, and 401 clients
    that skip the token — while /healthz stays open."""
    token = "sekrit-42"
    with fleet(stub_argv("--auth-token", token), 2, tmp_path,
               router_kw=dict(auth_token=token)) as (
            sup, router, reg, journal):
        auth = {"Authorization": f"Bearer {token}"}
        status, doc = predict(router, headers=auth)
        assert status == 200 and doc["version"] == 1
        status, body = http_call(router.host, router.port, "GET",
                                 "/metrics", headers=auth)
        text = body.decode()
        assert status == 200
        assert 'stub_requests_total{replica="r0"}' in text
        assert 'stub_requests_total{replica="r1"}' in text
        # no token -> the router itself 401s; /healthz stays exempt
        assert http_call(router.host, router.port, "GET",
                         "/metrics")[0] == 401
        assert predict(router)[0] == 401
        assert http_call(router.host, router.port, "GET", "/healthz")[0] == 200


# ---------------------------------------------------------------------------
# shed ordering + per-model admission

def test_shed_bulk_before_interactive(tmp_path):
    router_kw = dict(max_inflight=4, bulk_max_inflight=1)
    with fleet(stub_argv("--predict-delay", "0.4"), 2, tmp_path,
               router_kw=router_kw) as (sup, router, reg, journal):
        results = []

        def bg():
            results.append(predict(
                router, headers={"X-Dryad-Priority": "interactive"})[0])

        threads = [threading.Thread(target=bg) for _ in range(2)]
        for t in threads:
            t.start()
        # both interactive requests are in flight (delay 0.4s)
        assert wait_until(lambda: router._httpd.state.inflight_total >= 2,
                          timeout_s=2.0)
        # bulk sheds first: total inflight (2) >= bulk_max_inflight (1)
        status, doc = predict(router, headers={"X-Dryad-Priority": "bulk"})
        assert status == 503 and "shed" in doc["error"]
        # ... while interactive still admits (2 < max_inflight 4)
        assert predict(
            router, headers={"X-Dryad-Priority": "interactive"})[0] == 200
        for t in threads:
            t.join()
        assert results == [200, 200]
        shed = reg.counter("dryad_fleet_shed_total", "")
        assert shed.labels(priority="bulk").value() == 1
        assert shed.labels(priority="interactive").value() == 0


def test_per_model_admission_cap_and_body_priority(tmp_path):
    router_kw = dict(max_inflight=8, model_caps={"fraud": 1})
    with fleet(stub_argv("--predict-delay", "0.4"), 1, tmp_path,
               router_kw=router_kw) as (sup, router, reg, journal):
        codes = []

        def bg():
            codes.append(http_call(
                router.host, router.port, "POST", "/predict",
                {"rows": [[1.0]], "model": "fraud"})[0])

        t = threading.Thread(target=bg)
        t.start()
        assert wait_until(lambda: router._httpd.state.inflight_total >= 1,
                          timeout_s=2.0)
        # the capped model sheds its second in-flight request ...
        status, body = http_call(router.host, router.port, "POST",
                                 "/predict", {"rows": [[1.0]],
                                              "model": "fraud"})
        assert status == 503 and b"admission cap" in body
        # ... while other models still admit
        assert predict(router)[0] == 200
        t.join()
        assert codes == [200]
        # body-parsed priority (no header) still classifies the shed
        assert reg.counter("dryad_fleet_shed_total", "").labels(
            priority="interactive").value() == 1


# ---------------------------------------------------------------------------
# retry against a different replica

def test_retry_once_on_a_different_replica(tmp_path):
    def make(index, port_file):
        extra = ("--predict-503",) if index == 0 else ()
        return [sys.executable, STUB, "--port-file", port_file, *extra]

    with fleet(make, 2, tmp_path) as (sup, router, reg, journal):
        # every request answers 200: r0's stuck 503s are absorbed by the
        # single retry against r1
        for _ in range(6):
            assert predict(router)[0] == 200
        assert reg.counter("dryad_fleet_upstream_5xx_total", "").labels(
            replica="r0").value() >= 1
        assert reg.counter("dryad_fleet_retry_total", "").value() >= 1


# ---------------------------------------------------------------------------
# rolling swap: zero drops, pinned versions, journaled drains

def test_rolling_swap_zero_drop_and_pinned_versions(tmp_path):
    with fleet(stub_argv("--predict-delay", "0.1"), 2, tmp_path,
               router_kw=dict(max_inflight=32)) as (
            sup, router, reg, journal):
        seen = []
        seen_lock = threading.Lock()
        stop = [False]

        def client():
            while not stop[0]:
                status, doc = predict(router)
                with seen_lock:
                    seen.append((status, doc.get("version")))

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)                      # requests in flight
        status, body = http_call(router.host, router.port, "POST",
                                 "/models/push", {"path": "v2.dryad"},
                                 timeout=60.0)
        push = json.loads(body)
        time.sleep(0.3)                      # traffic on the new version
        stop[0] = True
        for t in threads:
            t.join()
        assert status == 200, push
        assert push["errors"] == {} and push["skipped"] == []
        assert push["versions"] == {"r0": 2, "r1": 2}
        # ZERO dropped/failed requests across the swap ...
        assert {s for s, _ in seen} == {200}
        # ... and both versions served: old for requests pinned before
        # their replica swapped, new after
        assert {v for _, v in seen} == {1, 2}
        # the journal shows drain -> swap per replica, in order
        drains = events_of(journal, "replica_drain")
        swaps = events_of(journal, "replica_swapped")
        assert [e["replica"] for e in drains] == ["r0", "r1"]
        assert [(e["replica"], e["version"]) for e in swaps] == [
            ("r0", 2), ("r1", 2)]


# ---------------------------------------------------------------------------
# crash -> respawn, budget exhaustion, stuck-503 recycle ladder

def test_crash_respawn_journal_sequence(tmp_path):
    with fleet(stub_argv("--crash-on-path"), 2, tmp_path,
               policy=RetryPolicy(backoff_base_s=0.0, retry_budget=3)) as (
            sup, router, reg, journal):
        # hard-kill r0 through its crash path (connection dies mid-request)
        slot = sup.slots[0]
        with pytest.raises(OSError):
            slot.proc.request("GET", "/boom", timeout_s=2.0)
        # the monitor notices the corpse and respawns under the budget
        assert wait_until(lambda: slot.routable and slot.generation == 1)
        assert predict(router)[0] == 200
        crashes = events_of(journal, "replica_crash")
        assert crashes and crashes[0]["replica"] == "r0"
        assert crashes[0]["exit_code"] == F.REPLICA_CRASH_EXIT
        respawns = events_of(journal, "replica_respawn")
        assert respawns and respawns[0]["reason"] == "crash"
        assert events_of(journal, "replica_ready")[-1]["generation"] == 1
        assert slot.respawns == 1
        assert reg.counter("dryad_fleet_crash_total", "").labels(
            replica="r0").value() == 1


def test_respawn_budget_exhaustion_fails_closed(tmp_path):
    journal = str(tmp_path / "fleet.jsonl")
    sup = FleetSupervisor(
        stub_argv("--fail-start"), 1,
        policy=RetryPolicy(backoff_base_s=0.0, retry_budget=2),
        journal=journal, registry=Registry(),
        probe_interval_s=0.05, startup_timeout_s=20.0)
    with pytest.raises(ReplicaStartupError):
        sup.start()
    # initial attempt + 2 budgeted retries, then the slot fails closed
    fails = events_of(journal, "replica_spawn_failed")
    assert len(fails) == 3 and all(e["exit_code"] == 7 for e in fails)
    closed = events_of(journal, "replica_fail_closed")
    assert closed and closed[0]["reason"] == "retry_budget_exhausted"
    assert closed[0]["respawns"] == 2
    assert sup.slots[0].fail_closed


def test_stuck_503_walks_the_recycle_ladder(tmp_path):
    def make(index, port_file):
        extra = ("--health-503-after", "5") if index == 0 else ()
        return [sys.executable, STUB, "--port-file", port_file, *extra]

    with fleet(make, 2, tmp_path,
               policy=RetryPolicy(backoff_base_s=0.0, retry_budget=1),
               unhealthy_after=2, recycle_after=3,
               startup_timeout_s=1.0) as (sup, router, reg, journal):
        slot = sup.slots[0]
        # rung 1: out of routing; rung 2: recycled; the respawned stub
        # latches 503 again, so the budget exhausts and the slot fails
        # closed — while r1 keeps the fleet healthy throughout
        assert wait_until(lambda: slot.fail_closed, timeout_s=20.0)
        kinds = [e["event"] for e in RunJournal.read(journal)]
        assert "replica_unhealthy" in kinds
        assert "replica_hang" in kinds
        assert "replica_fail_closed" in kinds
        for _ in range(3):
            assert predict(router)[0] == 200        # r1 serves on
        status, body = http_call(router.host, router.port, "GET", "/healthz")
        assert status == 200 and json.loads(body)["ok"] is True


# ---------------------------------------------------------------------------
# the drills through the REAL serve HTTP front end (in-process)

@pytest.fixture(scope="module")
def served_model():
    import numpy as np

    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(400, seed=5)
    ds = dryad.Dataset(X, y, max_bins=32)
    booster = dryad.train(dict(objective="binary", num_trees=4,
                               num_leaves=7, max_bins=32), ds,
                          backend="cpu")
    return booster, np.asarray(X[:2], np.float32)


def test_serve_front_end_honors_reject_503_drill(served_model):
    from dryad_tpu.serve import PredictServer
    from dryad_tpu.serve.http import make_http_server

    booster, X = served_model
    server = PredictServer(backend="cpu", max_wait_ms=0.2)
    server.registry.add(booster)
    injector = F.FaultInjector([
        F.FaultPoint(site="request", iteration=2, kind=F.REJECT_503,
                     sticky=True),
        F.FaultPoint(site="health", iteration=3, kind=F.REJECT_503)])
    httpd = make_http_server(server, port=0, fault_hook=injector)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    host, port = httpd.server_address[:2]
    try:
        body = {"rows": X.tolist()}
        assert http_call(host, port, "POST", "/predict", body)[0] == 200
        for _ in range(2):                   # sticky from request #2 on
            assert http_call(host, port, "POST", "/predict", body)[0] == 503
        assert http_call(host, port, "GET", "/healthz")[0] == 200
        assert http_call(host, port, "GET", "/healthz")[0] == 200
        assert http_call(host, port, "GET", "/healthz")[0] == 503  # probe 3
        assert http_call(host, port, "GET", "/healthz")[0] == 200  # one-shot
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.stop()
        thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# elastic capacity (r22): mutable slot registry + CapacityController

def test_elastic_add_and_retire_slot(tmp_path):
    with fleet(stub_argv(), 1, tmp_path) as (sup, router, reg, journal):
        assert [s.name for s in sup.slots] == ["r0"]
        slot = sup.add_slot()
        assert slot is not None and slot.name == "r1"
        assert wait_until(lambda: slot.routable)
        assert predict(router)[0] == 200
        # the census gauge and the router's admission gauges are live
        status, body = http_call(router.host, router.port, "GET", "/metrics")
        text = body.decode()
        assert status == 200
        assert 'dryad_fleet_replicas{state="total"} 2' in text
        assert 'dryad_fleet_inflight{priority="total"}' in text
        assert 'dryad_fleet_slot_inflight{replica="r1"}' in text
        # a held in-flight request stalls the drain (zero-drop), then
        # releasing it lets the retire complete
        slot.inflight_inc()
        done = []
        t = threading.Thread(target=lambda: done.append(
            sup.retire_slot("r1", drain_timeout_s=10.0)))
        t.start()
        assert wait_until(lambda: slot.retiring)
        assert not slot.routable
        time.sleep(0.1)
        assert not done, "retire completed with a request still in flight"
        slot.inflight_dec()
        t.join(timeout=10.0)
        assert done == [True]
        assert [s.name for s in sup.slots] == ["r0"]
        kinds = [e["event"] for e in RunJournal.read(journal)]
        assert "replica_retire" in kinds and "replica_retired" in kinds
        # retiring an unknown slot refuses cleanly
        assert sup.retire_slot("r1") is False


def test_retire_aborts_rather_than_dropping_inflight(tmp_path):
    with fleet(stub_argv(), 2, tmp_path) as (sup, router, reg, journal):
        slot = sup.slots[1]
        slot.inflight_inc()
        try:
            assert sup.retire_slot("r1", drain_timeout_s=0.1) is False
        finally:
            slot.inflight_dec()
        assert not slot.retiring, "aborted retire left the slot non-routable"
        assert slot.routable
        assert [s.name for s in sup.slots] == ["r0", "r1"]
        assert events_of(journal, "replica_retire_aborted")


def test_monitor_skips_retiring_slot(tmp_path):
    """A scale-down kills its process ON PURPOSE; the monitor must read
    that as the planned death it is, never as a crash to respawn."""
    with fleet(stub_argv(), 2, tmp_path) as (sup, router, reg, journal):
        slot = sup.slots[1]
        slot.retiring = True
        assert not slot.routable
        assert slot.state()["retiring"] is True
        slot.proc.stop()                 # the planned death
        time.sleep(0.5)                  # ~10 monitor cycles
        assert slot.generation == 0 and not slot.recovering
        assert not [e for e in events_of(journal, "replica_crash")
                    if e.get("replica") == "r1"], \
            "the monitor read a planned retire death as a crash"


def test_monitor_retiring_guard_is_load_bearing(tmp_path, monkeypatch):
    """Mechanical revert of the r22 guard: drop ``retiring`` from the
    monitor's skip predicate and the drained replica is resurrected —
    the exact bug the shipped predicate prevents."""
    monkeypatch.setattr(
        FleetSupervisor, "_monitor_skips",
        staticmethod(lambda slot: slot.fail_closed or slot.recovering
                     or slot.proc is None))
    with fleet(stub_argv(), 2, tmp_path) as (sup, router, reg, journal):
        slot = sup.slots[1]
        slot.retiring = True
        slot.proc.stop()
        assert wait_until(lambda: slot.generation == 1 and slot.healthy), \
            "without the revert the monitor no longer resurrects — " \
            "update this test alongside _monitor_skips"
        slot.retiring = False            # let teardown see a normal slot


def test_stop_reaps_in_flight_scale_up(tmp_path):
    """stop() during add_slot's ready wait: the half-born slot is
    registered BEFORE the wait, so the teardown sweep terminates its
    child, add_slot unblocks promptly and leaves no ghost slot."""
    def make(index: int, port_file: str) -> list:
        if index == 0:
            return [sys.executable, STUB, "--port-file", port_file]
        # a replica that never reports ready (the jax-import phase)
        return [sys.executable, "-c", "import time; time.sleep(60)"]

    sup = FleetSupervisor(
        make, 1, policy=RetryPolicy(backoff_base_s=0.0),
        journal=str(tmp_path / "fleet.jsonl"), registry=Registry(),
        probe_interval_s=0.05, probe_timeout_s=1.0,
        startup_timeout_s=30.0).start()
    try:
        got = []
        t = threading.Thread(target=lambda: got.append(sup.add_slot()))
        t.start()
        assert wait_until(lambda: len(sup.slots) == 2), \
            "half-born slot not registered before the ready wait"
        half = sup.slots[1]
        assert wait_until(lambda: half.proc is not None)
        sup.stop()
        t.join(timeout=15.0)
        assert not t.is_alive(), "add_slot stayed wedged past stop()"
        assert got == [None]
        assert [s.name for s in sup.slots] == ["r0"], \
            "failed scale-up left a ghost slot in the registry"
        assert not half.proc.alive, "stop() leaked the half-born child"
    finally:
        sup.stop()


def test_add_slot_registers_before_spawn_is_load_bearing(tmp_path,
                                                         monkeypatch):
    """Mechanical revert: register the slot only AFTER the spawn and
    stop()'s sweeps can no longer see the half-born child — it outlives
    the fleet, the leak the shipped ordering prevents."""
    from dryad_tpu.fleet.supervisor import ReplicaSlot

    seen = []

    def late_register(self):
        if self._stop.is_set():
            return None
        with self._slots_lock:
            slot = ReplicaSlot(self._next_index)
            self._next_index += 1
        seen.append(slot)
        slot.recovering = True
        try:
            ok = self._spawn(slot, first=True)
        finally:
            slot.recovering = False
        if not ok:
            return None
        with self._slots_lock:
            self._slots.append(slot)
        return slot

    monkeypatch.setattr(FleetSupervisor, "add_slot", late_register)

    def make(index: int, port_file: str) -> list:
        if index == 0:
            return [sys.executable, STUB, "--port-file", port_file]
        return [sys.executable, "-c", "import time; time.sleep(60)"]

    sup = FleetSupervisor(
        make, 1, policy=RetryPolicy(backoff_base_s=0.0),
        journal=str(tmp_path / "fleet.jsonl"), registry=Registry(),
        probe_interval_s=0.05, probe_timeout_s=1.0,
        startup_timeout_s=30.0).start()
    t = threading.Thread(target=lambda: sup.add_slot())
    t.start()
    try:
        assert wait_until(lambda: seen and seen[0].proc is not None
                          and seen[0].proc.alive)
        sup.stop()
        assert seen[0].proc.alive, \
            "the sweep saw the unregistered child — revert test is stale"
    finally:
        if seen and seen[0].proc is not None:
            seen[0].proc.stop()          # reap the demonstrated leak
        t.join(timeout=15.0)


# ---------------------------------------------------------------------------
# CapacityController decision logic (no subprocesses)

class _CtrlSlot:
    def __init__(self, index: int):
        self.index = index
        self.name = f"r{index}"
        self.fail_closed = False
        self.retiring = False
        self.routable = True
        self.inflight = 0


class _CtrlSup:
    """Supervisor stand-in: exactly the surface the controller drives."""

    def __init__(self, n: int):
        self._slots = [_CtrlSlot(i) for i in range(n)]
        self.events: list = []

    @property
    def slots(self):
        return list(self._slots)

    def journal(self, kind, /, **fields):
        self.events.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.events]

    def skip_reasons(self):
        return [f["reason"] for k, f in self.events if k == "scale_skipped"]

    def gauge_replicas(self):
        pass

    def routable_slots(self):
        return [s for s in self._slots if s.routable and not s.retiring]

    def add_slot(self):
        s = _CtrlSlot(len(self._slots))
        self._slots.append(s)
        return s

    def retire_slot(self, name, *, drain_timeout_s=30.0):
        s = next((x for x in self._slots if x.name == name), None)
        if s is None:
            return False
        self._slots.remove(s)
        return True


def _sig(mode: str) -> dict:
    return {
        "pressure": {"slo": {"interactive": {"breached": True,
                                             "sustained": True}},
                     "inflight": 9, "max_inflight": 10},
        "saturated": {"slo": {}, "inflight": 9, "max_inflight": 10},
        "headroom": {"slo": {}, "inflight": 0, "max_inflight": 10},
        "calm": {"slo": {}, "inflight": 5, "max_inflight": 10},
    }[mode]


def _controller(sup, sig, **kw):
    from dryad_tpu.fleet.autoscale import CapacityController

    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("breach_after", 2)
    kw.setdefault("idle_after", 2)
    kw.setdefault("cooldown_up_s", 0.0)
    kw.setdefault("cooldown_down_s", 0.0)
    return CapacityController(sup, lambda: _sig(sig["mode"]),
                              registry=Registry(enabled=False), **kw)


def _settle(ctrl):
    assert wait_until(lambda: ctrl.state()["action_in_flight"] is None)


def test_capacity_sustain_admits_at_exactly_breach_after():
    sup = _CtrlSup(1)
    sig = {"mode": "pressure"}
    ctrl = _controller(sup, sig, breach_after=3)
    assert ctrl.poke() is None
    assert ctrl.poke() is None
    # two refusals, ONE journaled skip (debounced on the reason)
    assert sup.skip_reasons() == ["insufficient-sustain"]
    assert ctrl.poke() == "scale_up"
    _settle(ctrl)
    assert sup.kinds().count("scale_up") == 1
    assert len(sup.slots) == 2
    assert ctrl.state()["actions_total"] == {"up": 1, "down": 0}


def test_capacity_flapping_resets_streaks():
    sup = _CtrlSup(1)
    sig = {"mode": "pressure"}
    ctrl = _controller(sup, sig, breach_after=2)
    assert ctrl.poke() is None
    sig["mode"] = "calm"
    assert ctrl.poke() is None
    assert ctrl.state()["up_streak"] == 0
    sig["mode"] = "pressure"
    assert ctrl.poke() is None, "flapping traffic accumulated to an action"
    assert sup.kinds().count("scale_up") == 0


def test_capacity_saturation_alone_is_pressure():
    sup = _CtrlSup(1)
    ctrl = _controller(sup, {"mode": "saturated"}, breach_after=1)
    assert ctrl.poke() == "scale_up"
    _settle(ctrl)
    up = next(f for k, f in sup.events if k == "scale_up")
    assert up["saturated"] is True and up["slo_sustained"] == []


def test_capacity_bound_and_cooldown_refusals():
    sup = _CtrlSup(2)
    sig = {"mode": "pressure"}
    ctrl = _controller(sup, sig, breach_after=1, max_replicas=3,
                       cooldown_up_s=60.0)
    assert ctrl.poke() == "scale_up"
    _settle(ctrl)
    assert len(sup.slots) == 3
    assert ctrl.poke() is None
    assert sup.skip_reasons()[-1] == "at-bound"
    sup._slots.pop()                     # headroom to grow again, but...
    assert ctrl.poke() is None           # ...inside the up cooldown
    assert sup.skip_reasons()[-1] == "cooldown"
    assert sup.kinds().count("scale_up") == 1


def test_capacity_never_below_min_never_last_routable():
    sup = _CtrlSup(2)
    ctrl = _controller(sup, {"mode": "headroom"}, idle_after=1,
                       min_replicas=2)
    assert ctrl.poke() is None
    assert sup.skip_reasons() == ["at-bound"]
    # min allows a drain, but only one slot is routable: the victim
    # picker refuses (zero routable is an outage) and journals the miss
    sup2 = _CtrlSup(2)
    sup2._slots[0].routable = False
    ctrl2 = _controller(sup2, {"mode": "headroom"}, idle_after=1,
                        min_replicas=1)
    assert ctrl2.poke() == "scale_down"
    _settle(ctrl2)
    assert sup2.kinds().count("scale_down") == 0
    failed = next(f for k, f in sup2.events if k == "scale_failed")
    assert failed["direction"] == "down"
    assert len(sup2.slots) == 2


def test_capacity_in_flight_action_refuses_second():
    sup = _CtrlSup(3)
    gate = threading.Event()
    orig = sup.retire_slot

    def slow_retire(name, *, drain_timeout_s=30.0):
        gate.wait(10.0)
        return orig(name, drain_timeout_s=drain_timeout_s)

    sup.retire_slot = slow_retire
    ctrl = _controller(sup, {"mode": "headroom"}, idle_after=1)
    try:
        assert ctrl.poke() == "scale_down"
        assert ctrl.poke() is None
        assert sup.skip_reasons() == ["already-in-flight"]
    finally:
        gate.set()
    _settle(ctrl)
    assert sup.kinds().count("scale_down") == 1
    assert [s.name for s in sup.slots] == ["r0", "r1"]
    ctrl.stop(timeout_s=5.0)


def test_capacity_poll_loop_runs_and_stops():
    sup = _CtrlSup(1)
    sig = {"mode": "pressure"}
    ctrl = _controller(sup, sig, breach_after=1, max_replicas=2,
                       poll_interval_s=0.01).start()
    try:
        assert wait_until(lambda: sup.kinds().count("scale_up") == 1)
        assert wait_until(lambda: "at-bound" in sup.skip_reasons())
    finally:
        ctrl.stop(timeout_s=5.0)
    n = len(sup.events)
    time.sleep(0.1)
    assert len(sup.events) == n, "the poll loop survived stop()"


def test_capacity_validates_bounds():
    sup = _CtrlSup(1)
    with pytest.raises(ValueError):
        _controller(sup, {"mode": "calm"}, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        _controller(sup, {"mode": "calm"}, breach_after=0)
