"""Kill-at-every-checkpoint-boundary sweep: one injected fault at each
boundary of a short validated run, supervised result bitwise equal to the
uninterrupted booster (trees, eval metrics, early-stop state) — single
process on both backends, and under the mocked multi-host drill."""

import numpy as np
import pytest

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.resilience import FaultInjector, RetryPolicy, supervise_train
from dryad_tpu.resilience import faults as F

PARAMS = dict(objective="binary", num_trees=10, num_leaves=7, max_bins=32,
              seed=3, min_data_in_leaf=5, subsample=0.8,
              early_stopping_rounds=4)
EVERY = 2
BOUNDARIES = (0, 2, 4, 6, 8)


@pytest.fixture(scope="module")
def data():
    # test_checkpoint.py's fixture shape: this draw trains all 10
    # iterations without stopping early, so every boundary is reachable
    # (early-stop STATE is still live and compared below)
    X, y = higgs_like(3000, seed=21)
    return dryad.Dataset(X, y, max_bins=32)


@pytest.fixture(scope="module")
def valid(data):
    X, y = higgs_like(1200, seed=22)
    return data.bind(X, y)


@pytest.fixture(scope="module")
def references(data, valid):
    return {backend: dryad.train(PARAMS, data, [valid], backend=backend)
            for backend in ("cpu", "tpu")}


def _assert_bitwise(full, resumed):
    assert resumed.num_iterations == full.num_iterations
    assert resumed.best_iteration == full.best_iteration
    assert resumed.train_state["best_value"] == full.train_state["best_value"]
    assert resumed.train_state["stale"] == full.train_state["stale"]
    # the CPU backend records eval_history always, the device backend only
    # on the deferred-eval path (sync early stopping consumes evals live) —
    # whatever the reference carries, the supervised run must match
    assert (resumed.train_state.get("eval_history")
            == full.train_state.get("eval_history"))
    np.testing.assert_array_equal(full.feature, resumed.feature)
    np.testing.assert_array_equal(full.threshold, resumed.threshold)
    np.testing.assert_array_equal(full.value, resumed.value)
    Xp = np.zeros((4, full.mapper.num_features), np.float32)
    np.testing.assert_array_equal(full.predict(Xp), resumed.predict(Xp))


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
@pytest.mark.parametrize("boundary", BOUNDARIES)
def test_kill_at_checkpoint_boundary(tmp_path, data, valid, references,
                                     backend, boundary):
    """A device fault at the first dispatch at/after each boundary —
    including iteration 0, before any checkpoint exists — must supervise
    back to the exact uninterrupted run."""
    injector = FaultInjector([(boundary, F.DEVICE_UNAVAILABLE, "dispatch")])
    resumed = supervise_train(
        PARAMS, data, [valid], backend=backend,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=EVERY,
        fault_injector=injector, policy=RetryPolicy(backoff_base_s=0.0))
    assert injector.pending == 0, "the boundary fault never fired"
    _assert_bitwise(references[backend], resumed)


def test_multihost_supervised_drill(tmp_path, monkeypatch):
    """The mocked multi-host drill (test_multihost.py conventions), driven
    by the supervisor instead of hand-rolled kill/resume: mocked 2-process
    allgather agreement, NaN-bearing data, 4-device mesh, one injected
    device fault mid-run — supervised output bitwise equals the
    uninterrupted mesh run."""
    import jax as real_jax
    from jax.experimental import multihost_utils as real_mhu

    from dryad_tpu.config import make_params
    from dryad_tpu.engine.distributed import make_mesh
    from dryad_tpu.engine.train import train_device

    gathered = []

    def fake_allgather(arr):
        gathered.append(np.asarray(arr))
        return np.stack([np.asarray(arr), np.asarray(arr)])

    monkeypatch.setattr(real_jax, "process_count", lambda: 2)
    monkeypatch.setattr(real_mhu, "process_allgather", fake_allgather)

    X, y = higgs_like(2048, seed=71)
    X = X.copy()
    X[::13, 2] = np.nan                     # exercises the allgather
    ds = dryad.Dataset(X, y, max_bins=32)
    params = make_params(dict(objective="binary", num_trees=9, num_leaves=7,
                              max_bins=32, max_depth=4, growth="depthwise"))
    mesh = make_mesh(real_jax.devices()[:4])

    b_ref = train_device(params, ds, mesh=mesh)
    assert gathered, "learn_missing agreement must have run"

    injector = FaultInjector([(5, F.DEVICE_UNAVAILABLE, "dispatch")])
    b_sup = supervise_train(
        params, ds, backend="tpu", mesh=mesh,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=3,
        fault_injector=injector, policy=RetryPolicy(backoff_base_s=0.0))
    assert injector.pending == 0
    np.testing.assert_array_equal(b_ref.feature, b_sup.feature)
    np.testing.assert_array_equal(b_ref.threshold, b_sup.threshold)
    np.testing.assert_array_equal(
        b_ref.predict_binned(ds.X_binned, raw_score=True),
        b_sup.predict_binned(ds.X_binned, raw_score=True))
