"""Bisect the composed level-wise body cost at 10M rows.

profile_plan.py's isolated stages sum to ~300 ms/level but the real grower
pays ~800+ ms/level — this script rebuilds the level body stage by stage
(cumulative variants inside one 8-trip fori, like the real grower) to find
where the composed program loses the time.

Usage: PYTHONPATH=... python scripts/exp_level_bisect.py [rows] [stage...]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.config import make_params
from dryad_tpu.engine.histogram import build_hist_segmented
from dryad_tpu.engine.pallas_hist import make_records
from dryad_tpu.engine.split import NEG_INF, find_best_split

N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
F, B, L, P = 28, 256, 255, 128
DEPTH = 8
rng = np.random.default_rng(0)
plat = jax.devices()[0].platform
print(f"rows={N} P={P} levels={DEPTH} device={jax.devices()[0]}")

Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
g = jnp.asarray(rng.normal(size=N).astype(np.float32))
h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
row_slot0 = jnp.asarray(rng.integers(0, L, size=N).astype(np.int32))
fmask = jnp.ones((F,), bool)
iscat = jnp.zeros((F,), bool)
p = make_params(dict(objective="binary", num_leaves=L, max_depth=DEPTH,
                     growth="depthwise"))


def loop_time(tag, prog, *arrays):
    f = jax.jit(prog)
    _ = float(f(jnp.float32(0.0), *arrays))
    t0 = time.perf_counter()
    _ = float(f(jnp.float32(0.0), *arrays))
    dt = time.perf_counter() - t0
    print(f"{tag:46s} {dt*1e3/DEPTH:9.1f} ms/level  ({dt:.2f}s total)")
    return dt


def make_prog(stage):
    def prog(s0, Xb, g, h, row_slot0):
        records = make_records(Xb, g, h)
        hists0 = jnp.zeros((L, 3, F, B), jnp.float32)

        def body(d, carry):
            acc, row_slot, hists = carry
            # synthetic per-level candidate state; the j32-style opaque
            # zero keeps a TRUE loop dependency (acc % 1 folds to 0
            # statically; (acc*1e-30).astype(int32) cannot be folded)
            sj = (jnp.arange(P, dtype=jnp.int32) * 2
                  + (acc * 1e-30).astype(jnp.int32))
            do = jnp.ones((P,), bool)
            right_slot = jnp.minimum(sj + 1, L - 1)

            # ---- stage >= 1: smallsel derivation from row_slot ----------
            colof = jnp.full((L + 1,), P, jnp.int32).at[
                jnp.where(do, sj, L + 1)].set(
                    jnp.arange(P, dtype=jnp.int32), mode="drop")
            smallsel = colof[jnp.minimum(row_slot, L)]

            if stage == 0:
                smallsel = jnp.minimum(
                    (row_slot + (acc * 1e-30).astype(jnp.int32)) % (P + 1), P)

            # ---- seg hist (always) --------------------------------------
            # records carries g/h, so perturbing g here would be dead —
            # smallsel (via sj/acc) carries the loop dependency instead
            hist_small = build_hist_segmented(
                Xb, g, h, smallsel, P, B,
                rows_per_chunk=p.rows_per_chunk,
                precision="exact", backend="auto",
                rows_bound=N // 2 + 1, platform=plat, records=records)

            out = hist_small[0, 0, 0, 0]

            # ---- stage >= 2: subtraction + hists writes ------------------
            if stage >= 2:
                hist_large = hists[sj] - hist_small
                ls = (jnp.arange(P) % 2 == 0)[:, None, None, None]
                hist_l = jnp.where(ls, hist_small, hist_large)
                hist_r = jnp.where(ls, hist_large, hist_small)
                hists = hists.at[jnp.where(do, sj, L)].set(
                    hist_l, mode="drop")
                hists = hists.at[jnp.where(do, right_slot, L)].set(
                    hist_r, mode="drop")
                out = out + hists[0, 0, 0, 0]

            # ---- stage >= 3: vmapped split finder ------------------------
            if stage >= 3:
                ch_hist = jnp.concatenate([hist_l, hist_r])
                GHC = jnp.abs(ch_hist[:, :3].sum(axis=(2, 3)))
                allow = jnp.ones((2 * P,), bool)

                def best(hist, G, H, C, al):
                    return find_best_split(
                        hist, G, H, C, lambda_l2=1.0, min_child_weight=1e-3,
                        min_data_in_leaf=20, min_split_gain=0.0,
                        feat_mask=fmask, is_cat_feat=iscat, allow=al,
                        has_cat=False)
                res = jax.vmap(best)(ch_hist, GHC[:, 0], GHC[:, 1],
                                     GHC[:, 2], allow)
                out = out + res.gain[0]

            # ---- stage >= 4: row partition ------------------------------
            if stage >= 4:
                rs = jnp.minimum(row_slot, L - 1)
                rf = rs % F
                bins_rf = jnp.take_along_axis(
                    Xb, rf[:, None].astype(jnp.int32), axis=1)[:, 0]
                go_left = bins_rf.astype(jnp.int32) <= (rs % B)
                row_slot = jnp.where(go_left, row_slot,
                                     jnp.minimum(row_slot + 1, L - 1))

            return (out * 1e-30 + acc, row_slot, hists)

        acc, _, _ = jax.lax.fori_loop(0, DEPTH, body,
                                      (s0, row_slot0, hists0))
        return acc
    return prog


stages = [int(a) for a in sys.argv[2:]] or [0, 1, 2, 3, 4]
names = {0: "seg hist only (synthetic sel)",
         1: "+ smallsel from row_slot",
         2: "+ subtraction + hists writes",
         3: "+ vmap split finder",
         4: "+ row partition"}
for st in stages:
    loop_time(names[st], make_prog(st), Xb, g, h, row_slot0)
