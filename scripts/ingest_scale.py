"""Scale-prove streamed ingest (VERDICT r4 #8): ingest ~1e8 synthetic
sparse rows through the out-of-core CSR path under a RECORDED peak-RSS
budget, and assert stream ≡ in-memory bins on a subsample.

The Criteo envelope claim (streaming.py: 1e9 x 39 = 39 GB/pod, per-host
slices) has only been e2e-tested at 500k rows; this drives the same code
at 1e8 x 32 sparse features (3.2 GB binned — a realistic single-host
slice of the 39 GB pod matrix) while holding peak RSS well under the
naive dense-float footprint (1e8 x 32 f32 = 12.8 GB raw floats, which
this path never materializes).

Usage: python scripts/ingest_scale.py [rows] [--budget-gb 8]
(CPU-only — run it while the chip is idle; it is host-heavy.)
"""

import argparse
import resource
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def peak_rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("rows", nargs="?", type=int, default=100_000_000)
    ap.add_argument("--features", type=int, default=32)
    ap.add_argument("--chunk", type=int, default=2_000_000)
    ap.add_argument("--budget-gb", type=float, default=8.0)
    args = ap.parse_args()
    N, F, C = args.rows, args.features, args.chunk

    from dryad_tpu.data.streaming import dataset_from_csr_chunks

    # synthetic sparse generator: ~10% density, deterministic per chunk;
    # NOTHING big is kept — each chunk is rebuilt on every pass
    nnz_per_row = max(F // 10, 3)

    def make_chunk(c0, n):
        rng = np.random.default_rng(1000 + c0 // C)
        indptr = np.arange(0, (n + 1) * nnz_per_row, nnz_per_row,
                           dtype=np.int64)
        # unique columns per row by construction: distinct offsets mod F
        # rotated per row (duplicate columns would make the dense
        # reference order-dependent)
        offs = rng.choice(F, nnz_per_row, replace=False).astype(np.int32)
        rows_local = np.arange(c0, c0 + n, dtype=np.int64)[:, None]
        cols = ((rows_local + offs[None, :]) % F).astype(np.int32).ravel()
        vals = rng.normal(size=n * nnz_per_row).astype(np.float32)
        return indptr, cols, vals

    def chunks():
        for c0 in range(0, N, C):
            n = min(C, N - c0)
            yield make_chunk(c0, n)

    rng_y = np.random.default_rng(5)
    y = (rng_y.random(N) < 0.5).astype(np.float32)

    t0 = time.perf_counter()
    ds = dataset_from_csr_chunks(chunks, y, N, F, max_bins=64,
                                 sample_rows=1 << 20, seed=3)
    wall = time.perf_counter() - t0
    rss = peak_rss_gb()
    binned_gb = ds.X_binned.nbytes / 1e9
    print(f"ingested {N:,} x {F} sparse rows in {wall:.0f}s | "
          f"binned matrix {binned_gb:.2f} GB | peak RSS {rss:.2f} GB "
          f"(budget {args.budget_gb} GB)", flush=True)

    # ---- stream ≡ in-memory on a subsample ---------------------------------
    sub = 500_000
    indptr, cols, vals = make_chunk(0, sub)
    # densify the first `sub` rows for the in-memory reference (vectorized:
    # fixed nnz per row makes the row index a repeat)
    dense = np.zeros((sub, F), np.float32)
    rows_idx = np.repeat(np.arange(sub), nnz_per_row)
    dense[rows_idx, cols[: sub * nnz_per_row]] = vals[: sub * nnz_per_row]
    Xb_ref = ds.mapper.transform(dense)
    np.testing.assert_array_equal(np.asarray(ds.X_binned[:sub]), Xb_ref)
    print("stream == in-memory bins on 500k-row subsample: EXACT",
          flush=True)

    ok = rss <= args.budget_gb
    print(f"RSS budget: {'OK' if ok else 'EXCEEDED'}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
