"""Reliable device-side histogram timing: loop inside ONE jit program.

Per-call host timing through the axon tunnel is wildly unreliable (parts
measure slower than their sum).  Here K dependent iterations run under one
lax.fori_loop inside one jit, so wall-clock/K is true device time.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.engine.histogram import build_hist, build_hist_segmented

N, F, B = 200_000, 28, 256
K = 10


def loop_time(step, init=0.0):
    """step: scalar f32 -> scalar f32 (must consume + produce dependency)."""
    f = jax.jit(lambda s0: jax.lax.fori_loop(0, K, lambda i, s: step(s), s0))
    _ = float(f(jnp.float32(init)))          # compile + warm
    t0 = time.perf_counter()
    _ = float(f(jnp.float32(init)))
    return (time.perf_counter() - t0) / K


def main():
    X, y = higgs_like(N, seed=7)
    ds = dryad.Dataset(X, y, max_bins=B)
    Xb = jnp.asarray(ds.X_binned)
    g0 = jax.random.normal(jax.random.PRNGKey(0), (N,), jnp.float32)
    h0 = jnp.abs(g0) + 0.1
    mask = jnp.ones((N,), bool)
    sel = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, 128).astype(jnp.int32)

    for backend in ("xla", "pallas"):
        t1 = loop_time(lambda s: build_hist(
            Xb, g0 + s, h0, mask, B, backend=backend)[0, 0, 0] * 1e-30)
        t2 = loop_time(lambda s: build_hist_segmented(
            Xb, g0 + s, h0, sel, 128, B, backend=backend)[0, 0, 0, 0] * 1e-30)
        print(f"{backend:7s} single: {t1*1e3:7.2f} ms   seg P=128: {t2*1e3:7.2f} ms")


if __name__ == "__main__":
    main()
