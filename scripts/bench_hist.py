"""Reliable device-side histogram timing: loop inside ONE jit program.

Per-call host timing through the axon tunnel is wildly unreliable (parts
measure slower than their sum), so both backends' builders are timed
through the canonical harness (engine/probes.timed_fori since r13): K
dependent iterations under one lax.fori_loop, carried perturbation
liveness-proven at runtime, terminal real fetch, min-of-reps + spread.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.engine.histogram import build_hist, build_hist_segmented
from dryad_tpu.engine.probes import timed_fori

N, F, B = 200_000, 28, 256
K = 10


def main():
    X, y = higgs_like(N, seed=7)
    ds = dryad.Dataset(X, y, max_bins=B)
    Xb = jnp.asarray(ds.X_binned)
    g0 = jax.random.normal(jax.random.PRNGKey(0), (N,), jnp.float32)
    h0 = jnp.abs(g0) + 0.1
    mask = jax.random.uniform(jax.random.PRNGKey(2), (N,)) < 0.8
    sel = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, 128).astype(
        jnp.int32)

    for backend in ("xla", "pallas"):
        def single_step(s, Xb, g, h, mask):
            si = s.astype(jnp.int32)
            hist = build_hist(Xb, g, h, jnp.roll(mask, si), B,
                              backend=backend)
            return s + 1.0, hist[0].sum()

        def seg_step(s, Xb, g, h, sel):
            si = s.astype(jnp.int32)
            hist = build_hist_segmented(Xb, g, h, (sel + si) % 128, 128, B,
                                        backend=backend)
            return s + 1.0, hist[0, 0].sum()

        t1, sp1 = timed_fori(single_step, K, 2, Xb, g0, h0, mask,
                             label=f"single-{backend}")
        t2, sp2 = timed_fori(seg_step, K, 2, Xb, g0, h0, sel,
                             label=f"seg-{backend}")
        print(f"{backend:7s} single: {t1:7.2f} ms (spread {sp1:.3f})   "
              f"seg P=128: {t2:7.2f} ms (spread {sp2:.3f})")


if __name__ == "__main__":
    main()
