"""Round-4 levers, measured composed (CLAUDE.md fori doctrine):

  A. generic plan (packed sort + alignment gather)  [r3 shipping path]
  B. aligned plan (count-injected sort, no alignment gather)
  C. each at two fill factors — 50% selected (the static worst case the
     grid is sized for) and 15% selected (a realistic deep level) — so the
     skip-empty kernel's saving is visible separately from the plan's.

The perturbation flips sel entries (the sort key), so plan, gathers, tiles
and kernel all stay live; counts are recomputed from the perturbed sel via
a chunked one-hot reduce INSIDE the loop (exactness preserved).

Usage: PYTHONPATH=... python scripts/exp_r4_aligned.py [rows] [P] [reps]
"""
# dryadlint: disable-file=no-block-until-ready -- r4-era setup materialization outside the timed region; results recorded (STATUS r4)

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.engine.pallas_hist import (
    _TILE_ROWS, hist_from_plan, make_records, tile_plan, tile_plan_aligned,
)


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    K = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    F, B = 28, 256
    T = _TILE_ROWS
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} P={P} reps={K} device={jax.devices()[0]}", flush=True)

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    bound = N // 2 + 1
    rec = jax.block_until_ready(make_records(Xb, g, h))

    def mksel(frac):
        # frac of rows spread over P slots, rest dropped (sentinel P)
        s = rng.integers(0, P, size=N).astype(np.int32)
        drop = rng.random(N) >= frac
        return jnp.asarray(np.where(drop, P, s))

    def loop_time(tag, step, *arrays):
        f = jax.jit(lambda s0, *a: jax.lax.fori_loop(
            0, K, lambda i, s: step(s, *a), s0))
        _ = float(f(jnp.float32(0.0), *arrays))
        t0 = time.perf_counter()
        _ = float(f(jnp.float32(0.0), *arrays))
        dt = (time.perf_counter() - t0) / K
        print(f"{tag:52s} {dt*1e3:9.1f} ms", flush=True)
        return dt

    def psel(s, ss):
        flip = (s * 1e-30).astype(jnp.int32)
        return ss.at[0].set(jnp.minimum(ss[0] + flip, P))

    def full_generic(s, ss, rc):
        sp = psel(s, ss)
        buf, tl, tf = tile_plan(sp, N, P, T, rows_bound=bound)
        hist = hist_from_plan(Xb, g, h, buf, tl, tf, P, B, platform=plat,
                              records=rc)
        return hist[0, 0, 0, 0] * 1e-30 + s * 0.0

    def full_aligned(s, ss, cnt, rc):
        # counts ride precomputed (the grower reads them off its own
        # histograms for free); the sel[0] perturbation's off-by-one vs cnt
        # misplaces at most one row — irrelevant for timing
        sp = psel(s, ss)
        buf, tl, tf = tile_plan_aligned(sp, cnt, N, P, T, rows_bound=bound)
        hist = hist_from_plan(Xb, g, h, buf, tl, tf, P, B, platform=plat,
                              records=rc)
        return hist[0, 0, 0, 0] * 1e-30 + s * 0.0

    for frac in (0.5, 0.15):
        sel = mksel(frac)
        sel_np = np.asarray(sel)
        cnt = jnp.asarray(np.bincount(sel_np[sel_np < P],
                                      minlength=P)[:P].astype(np.int32))
        loop_time(f"generic plan, fill={frac:.2f}", full_generic, sel, rec)
        loop_time(f"aligned plan, fill={frac:.2f}", full_aligned, sel, cnt,
                  rec)


if __name__ == "__main__":
    main()
