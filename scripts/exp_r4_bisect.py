"""Round-4 composed-pipeline bisect at 10M: where do the ~300 ms that are
invisible in isolated stage timings (profile_plan.py) live?

profile_plan r4 re-run: parts sum to ~378 ms (plan 78 + X gather 122 +
g/h gather 46 + transpose 28 + pack 23 + kernel 82) but the composed
build_hist_segmented measures 679 ms.  This script times PREFIXES of the
composed pipeline (plan -> gather -> unpack -> transpose -> pack ->
kernel), all inside one jit with the sort key perturbed per iteration
(CLAUDE.md doctrine: the perturbation must reach every live stage), so the
jump between prefixes locates the composition cost.

Usage: PYTHONPATH=... python scripts/exp_r4_bisect.py [rows] [P] [reps]
"""
# dryadlint: disable-file=no-block-until-ready -- r4-era setup materialization outside the timed region; results recorded (STATUS r4)

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.engine import pallas_hist as ph
from dryad_tpu.engine.pallas_hist import (
    _TILE_ROWS, _hist_tiles, _pack_weights, _tiles_from_rows,
    hist_from_plan, make_records, tile_plan,
)


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    K = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    F, B = 28, 256
    T = _TILE_ROWS
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} P={P} reps={K} device={jax.devices()[0]}", flush=True)

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    sel_np = rng.integers(0, 2 * P, size=N).astype(np.int32)
    sel_np = np.where(sel_np < P, sel_np, P)
    sel = jnp.asarray(sel_np)
    bound = N // 2 + 1
    rec = jax.block_until_ready(make_records(Xb, g, h))

    def loop_time(tag, step, *arrays):
        f = jax.jit(lambda s0, *a: jax.lax.fori_loop(
            0, K, lambda i, s: step(s, *a), s0))
        _ = float(f(jnp.float32(0.0), *arrays))
        t0 = time.perf_counter()
        _ = float(f(jnp.float32(0.0), *arrays))
        dt = (time.perf_counter() - t0) / K
        print(f"{tag:46s} {dt*1e3:9.1f} ms", flush=True)
        return dt

    # the perturbation flips a few sel entries per trip -> the sort key,
    # hence the plan, hence every downstream gather/tile/kernel, changes
    def psel(s, ss):
        flip = (s * 1e-30).astype(jnp.int32)
        return ss.at[0].set(jnp.minimum(ss[0] + flip, P))

    def pfx_plan(s, ss):
        buf, tl, tf = tile_plan(psel(s, ss), N, P, T, rows_bound=bound)
        return buf[0].astype(jnp.float32) * 1e-30 + s * 0.0

    def pfx_gather(s, ss, rc):
        buf, tl, tf = tile_plan(psel(s, ss), N, P, T, rows_bound=bound)
        safe = jnp.minimum(buf, N - 1)
        r = rc[safe]
        return r[0, 0].astype(jnp.float32) * 1e-30

    def pfx_unpack(s, ss, rc):
        buf, tl, tf = tile_plan(psel(s, ss), N, P, T, rows_bound=bound)
        n_tiles = buf.shape[0] // T
        safe = jnp.minimum(buf, N - 1)
        r = rc[safe]
        gh = jax.lax.bitcast_convert_type(r[:, :2], jnp.float32)
        fw = r.shape[1] - 2
        Xr = jax.lax.bitcast_convert_type(
            r[:, 2:], jnp.uint8).reshape(n_tiles * T, fw * 4)[:, :F]
        return (Xr[0, 0].astype(jnp.float32) + gh[0, 0]) * 1e-30

    def pfx_tiles(s, ss, rc):
        buf, tl, tf = tile_plan(psel(s, ss), N, P, T, rows_bound=bound)
        n_tiles = buf.shape[0] // T
        safe = jnp.minimum(buf, N - 1)
        r = rc[safe]
        gh = jax.lax.bitcast_convert_type(r[:, :2], jnp.float32)
        fw = r.shape[1] - 2
        Xr = jax.lax.bitcast_convert_type(
            r[:, 2:], jnp.uint8).reshape(n_tiles * T, fw * 4)[:, :F]
        Xt = _tiles_from_rows(Xr, n_tiles, T, B)
        return (Xt[0, 0, 0, 0].astype(jnp.float32) + gh[0, 0]) * 1e-30

    def pfx_pack(s, ss, rc):
        buf, tl, tf = tile_plan(psel(s, ss), N, P, T, rows_bound=bound)
        n_tiles = buf.shape[0] // T
        valid = (buf < N).reshape(n_tiles, T)
        safe = jnp.minimum(buf, N - 1)
        r = rc[safe]
        gh = jax.lax.bitcast_convert_type(r[:, :2], jnp.float32)
        gt = gh[:, 0].reshape(n_tiles, T)
        ht = gh[:, 1].reshape(n_tiles, T)
        fw = r.shape[1] - 2
        Xr = jax.lax.bitcast_convert_type(
            r[:, 2:], jnp.uint8).reshape(n_tiles * T, fw * 4)[:, :F]
        Xt = _tiles_from_rows(Xr, n_tiles, T, B)
        Wt = _pack_weights(gt, ht, valid)
        return (Xt[0, 0, 0, 0].astype(jnp.float32) + Wt[0, 0, 0]
                .astype(jnp.float32)) * 1e-30

    def pfx_full(s, ss, rc):
        buf, tl, tf = tile_plan(psel(s, ss), N, P, T, rows_bound=bound)
        hist = hist_from_plan(Xb, g, h, buf, tl, tf, P, B, platform=plat,
                              records=rc)
        return hist[0, 0, 0, 0] * 1e-30

    loop_time("plan", pfx_plan, sel)
    loop_time("plan+recgather", pfx_gather, sel, rec)
    loop_time("plan+recgather+unpack", pfx_unpack, sel, rec)
    loop_time("plan+recgather+unpack+tiles", pfx_tiles, sel, rec)
    loop_time("plan+...+pack_weights", pfx_pack, sel, rec)
    loop_time("FULL hist_from_plan (records)", pfx_full, sel, rec)

    # non-records variant for reference (what profile_plan measured);
    # Xb/g/h ride as ARGUMENTS — as closure constants the 280 MB matrix
    # blows the remote-compile request limit (HTTP 413)
    def pfx_full_norec(s, ss, X, gg, hh):
        buf, tl, tf = tile_plan(psel(s, ss), N, P, T, rows_bound=bound)
        hist = hist_from_plan(X, gg, hh, buf, tl, tf, P, B, platform=plat,
                              records=None)
        return hist[0, 0, 0, 0] * 1e-30
    loop_time("FULL hist_from_plan (no records)", pfx_full_norec, sel, Xb,
              g, h)


if __name__ == "__main__":
    main()
