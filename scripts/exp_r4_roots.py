"""Multiclass root-path policy measurement (VERDICT r3 #8): shared-plan
XLA classes-builder (ONE (2K+1)-row pass) vs K separate masked Pallas
passes, at Covertype shape for K in {3, 7}.  Stall-robust: fori-loop
methodology + 3 repeats per arm, min taken (stalls only add).

Usage: PYTHONPATH=... python scripts/exp_r4_roots.py [rows] [reps]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.engine.histogram import build_hist_classes
from dryad_tpu.engine.pallas_hist import build_hist_pallas


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 581_000
    K_REP = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    F, B = 54, 256
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} F={F} B={B} reps={K_REP} device={jax.devices()[0]}",
          flush=True)

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    bag = jnp.ones((N,), bool)

    def loop_time(tag, step, *arrays):
        f = jax.jit(lambda s0, *a: jax.lax.fori_loop(
            0, K_REP, lambda i, s: step(s, *a), s0))
        _ = float(f(jnp.float32(0.0), *arrays))
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            _ = float(f(jnp.float32(0.0), *arrays))
            dt = (time.perf_counter() - t0) / K_REP
            best = dt if best is None else min(best, dt)
        print(f"{tag:44s} {best*1e3:9.1f} ms (min of 3)", flush=True)
        return best

    for K in (3, 7):
        g = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))
        h = jnp.asarray(rng.uniform(0.1, 1.0, size=(N, K)).astype(np.float32))

        def xla_shared(s, gg, hh):
            roots = build_hist_classes(Xb, gg + s, hh, bag, B,
                                       rows_per_chunk=65536,
                                       precision="exact")
            return roots[0, 0, 0, 0] * 1e-30

        def pallas_k(s, gg, hh):
            acc = jnp.float32(0.0)
            for k in range(K):
                hist = build_hist_pallas(Xb, gg[:, k] + s, hh[:, k], bag, B,
                                         platform=plat)
                acc = acc + hist[0, 0, 0] * 1e-30
            return acc

        loop_time(f"K={K} shared-plan XLA classes root", xla_shared, g, h)
        loop_time(f"K={K} {K}x masked Pallas roots", pallas_k, g, h)


if __name__ == "__main__":
    main()
