"""Observability smoke (scripts/ci.sh): the CLI's live metrics endpoint.

Trains 5 trees through ``python -m dryad_tpu train --metrics-port`` (the
CLI entry invoked in-process on a background thread) and scrapes the
endpoint while the run is up:

* ``/healthz`` answers (before the dataset is even loaded),
* ``/stats`` serves non-empty span series from the training loop,
* counters are monotone across two scrapes,
* ``/metrics`` serves parseable Prometheus text,
* (r12) the device-truth families are live: ``dryad_prog_*``
  cost/compile series from the compile-boundary introspection and the
  ``dryad_fetch_*`` watchdog gauge from the trainer's fetch sites — the
  run uses the DEVICE trainer (backend tpu on the CPU jax platform) so
  those boundaries actually exist.

DRYAD_METRICS_HOLD_S keeps the endpoint up a few seconds past the run so
the final scrape can never race a fast train; the scrape itself happens
as soon as spans appear, normally DURING training.  Exit 0 on success,
1 with a reason otherwise.
"""

import json
import os
import socket
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get_json(url: str):
    return json.loads(urllib.request.urlopen(url, timeout=2).read())


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the hold only needs to cover the final scrapes if the train outruns
    # them (<1 s of HTTP work); cmd_train's finally always sleeps the full
    # hold, so every extra second here is unconditional CI wall
    os.environ["DRYAD_METRICS_HOLD_S"] = "2"
    # device-truth families (r12): introspection on (it is the production
    # default; tests pin it off for suite wall) plus the opt-in
    # memory_analysis capture — cheap here, the compile is local CPU
    os.environ["DRYAD_PROG"] = "1"
    os.environ["DRYAD_PROG_MEMORY"] = "1"
    from dryad_tpu.__main__ import main as cli_main

    rng = np.random.default_rng(0)
    X = rng.normal(size=(20_000, 10)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(size=20_000) > 0).astype(
        np.float32)
    port = _free_port()
    with tempfile.TemporaryDirectory() as td:
        np.save(f"{td}/X.npy", X)
        np.save(f"{td}/y.npy", y)
        with open(f"{td}/cfg.json", "w") as f:
            json.dump(dict(objective="binary", num_trees=5, num_leaves=31,
                           max_bins=64), f)
        rc: dict = {}

        def run():
            try:
                rc["code"] = cli_main([
                    "train", "--config", f"{td}/cfg.json",
                    "--data", f"{td}/X.npy", "--label", f"{td}/y.npy",
                    "--backend", "tpu", "--quiet",
                    "--metrics-port", str(port)])
            except BaseException as e:  # noqa: BLE001 — reported below
                rc["error"] = e

        thread = threading.Thread(target=run)
        thread.start()
        base = f"http://127.0.0.1:{port}"

        deadline = time.monotonic() + 60
        healthy = False
        while time.monotonic() < deadline and thread.is_alive():
            try:
                healthy = _get_json(base + "/healthz")["ok"]
                break
            except Exception:
                time.sleep(0.02)
        if not healthy:
            print(f"OBS SMOKE FAIL: /healthz never answered ({rc})")
            thread.join(30)
            return 1

        snap1 = None
        while time.monotonic() < deadline:
            try:
                snap = _get_json(base + "/stats")
                if snap["spans"]:
                    snap1 = snap
                    break
            except Exception:
                pass
            time.sleep(0.02)
        if snap1 is None:
            print(f"OBS SMOKE FAIL: span series never appeared ({rc})")
            thread.join(30)
            return 1

        time.sleep(0.1)
        snap2 = _get_json(base + "/stats")
        metrics_text = urllib.request.urlopen(base + "/metrics",
                                              timeout=2).read().decode()
        thread.join(120)

        if rc.get("code") != 0 or "error" in rc:
            print(f"OBS SMOKE FAIL: CLI train failed ({rc})")
            return 1
        # the device trainer's chunked path emits chunk_dispatch series;
        # per-iteration dispatch (or the CPU trainer) emits train.iteration
        if not ({"train.chunk_dispatch", "train.iteration"}
                & set(snap1["spans"])):
            print(f"OBS SMOKE FAIL: no trainer loop span: "
                  f"{sorted(snap1['spans'])}")
            return 1
        # monotone counters: every series present at scrape 1 is >= at 2
        for name, series in snap1["counters"].items():
            for lbl, v1 in series.items():
                v2 = snap2["counters"].get(name, {}).get(lbl, -1)
                if v2 < v1:
                    print(f"OBS SMOKE FAIL: counter {name}{{{lbl}}} went "
                          f"backwards ({v1} -> {v2})")
                    return 1
        if "# TYPE dryad_span_count_total counter" not in metrics_text:
            print("OBS SMOKE FAIL: /metrics missing span families")
            return 1
        # r12 device-truth families must be live on the same scrape: the
        # compile-boundary cost/memory series and the fetch watchdog gauge
        dt_families = ("dryad_prog_flops", "dryad_prog_bytes_accessed",
                       "dryad_prog_memory_bytes", "dryad_prog_compiles_total",
                       "dryad_fetch_inflight_age_seconds")
        for family in dt_families:
            if family not in metrics_text:
                print(f"OBS SMOKE FAIL: /metrics missing {family}")
                return 1
        if "bench_trends" not in snap2:
            print("OBS SMOKE FAIL: /stats missing the bench_trends ledger")
            return 1
        n_spans = len(snap2["spans"])
        print(f"OBS SMOKE OK: {n_spans} span series, "
              f"{len(snap2['counters'])} counter families, "
              f"device_truth_families={len(dt_families)}, "
              f"iters={snap2['gauges'].get('dryad_train_iteration', {}).get('', '?')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
