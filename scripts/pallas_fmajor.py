"""Variant test: feature-major (Fc, T) tiles — no XLA lane padding on Xt.

Compares correctness + speed of the current (T, Fc)-tile kernel vs a
feature-major variant where the one-hot is built as (Fc*Bp, T) via sublane
tiling and the dot contracts both operands' trailing dim.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, F, B = 200_000, 28, 256
T, Fc, Bp = 512, 32, 256
n_tiles = N // T + 1
n_fb = 1
W = 128


def kern_cur(x_ref, w_ref, o_ref):   # x (1,1,T,Fc)
    x = x_ref[0, 0]
    shift = Fc.bit_length() - 1
    x_rep = pltpu.repeat(x, Bp, axis=1)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (T, Fc * Bp), 1) >> shift
    onehot = (x_rep == iota_b).astype(jnp.bfloat16)
    part = jax.lax.dot_general(w_ref[0], onehot, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)[:8]
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        o_ref[0] = part

    @pl.when(i != 0)
    def _():
        o_ref[0] = o_ref[0] + part


def kern_fm(x_ref, w_ref, o_ref):    # x (1,1,Fc,T)
    x = x_ref[0, 0]
    shift = Fc.bit_length() - 1
    x_rep = pltpu.repeat(x, Bp, axis=0)                       # (Fc*Bp, T)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (Fc * Bp, T), 0) >> shift
    onehot = (x_rep == iota_b).astype(jnp.bfloat16)
    part = jax.lax.dot_general(w_ref[0], onehot, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)[:8]
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        o_ref[0] = part

    @pl.when(i != 0)
    def _():
        o_ref[0] = o_ref[0] + part


def bench(name, kern, Xt):
    def call(s):
        return pl.pallas_call(
            kern,
            grid_spec=pl.GridSpec(
                grid=(n_tiles,),
                in_specs=[pl.BlockSpec((1, 1) + Xt.shape[2:], lambda i: (0, i, 0, 0)),
                          pl.BlockSpec((1, W, T), lambda i: (i, 0, 0))],
                out_specs=pl.BlockSpec((1, 8, Fc * Bp), lambda i: (0, 0, 0)),
            ),
            out_shape=jax.ShapeDtypeStruct((1, 8, Fc * Bp), jnp.float32),
        )(Xt, Wt + s.astype(jnp.bfloat16))
    f = jax.jit(call)
    try:
        s = jnp.float32(0.0)
        out0 = np.asarray(f(s))
        t0 = time.perf_counter()
        for _ in range(10):
            out = f(s)
            s = jnp.ravel(out)[0].astype(jnp.float32) * 1e-30
        _ = float(s)
        print(f"{name}: {(time.perf_counter()-t0)/10*1e3:8.2f} ms")
        return out0
    except Exception as ex:
        print(f"{name} FAILED: {str(ex)[:250]}")
        return None


rng = np.random.default_rng(0)
Xrows = rng.integers(0, B, size=(n_tiles, T, Fc)).astype(np.int32)
Wt = jnp.asarray(rng.normal(size=(n_tiles, W, T)).astype(np.float32)).astype(jnp.bfloat16)

Xt_cur = jnp.asarray(Xrows[None])                       # (1, n_tiles, T, Fc)
Xt_fm = jnp.asarray(Xrows.transpose(0, 2, 1)[None])     # (1, n_tiles, Fc, T)

a = bench("current (T,Fc) tiles  ", kern_cur, Xt_cur)
b = bench("feature-major (Fc,T)  ", kern_fm, Xt_fm)
if a is not None and b is not None:
    print("outputs equal:", np.allclose(a, b, atol=1e-3))
print("HBM bytes: cur(padded)", n_tiles*T*128*4, " fm(unpadded)", n_tiles*Fc*T*4)
