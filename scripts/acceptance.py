"""Run the five BASELINE.json acceptance configs end-to-end on the attached
device and print one result line each (recorded in STATUS.md).

Shapes follow BASELINE.json:7-11; synthetic stand-ins from
dryad_tpu.datasets since the real datasets aren't present in this
environment. Scale knob: ACCEPT_SCALE in (0, 1] shrinks row counts for
quick runs.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

import dryad_tpu as dryad
from dryad_tpu.datasets import (
    covertype_like,
    criteo_like,
    epsilon_like,
    higgs_like,
    mslr_like,
)
from dryad_tpu.metrics import accuracy as _acc
from dryad_tpu.metrics import auc, ndcg_at_k, rmse

SCALE = float(os.environ.get("ACCEPT_SCALE", 1.0))


def _n(n):
    return max(1000, int(n * SCALE))


def run(name, fn):
    t0 = time.perf_counter()
    try:
        metrics = fn()
        metrics.update(status="ok", seconds=round(time.perf_counter() - t0, 1))
    except Exception as e:  # noqa: BLE001 — acceptance report must not die
        metrics = {"status": f"FAIL: {type(e).__name__}: {e}",
                   "seconds": round(time.perf_counter() - t0, 1)}
    print(json.dumps({"config": name, **metrics}), flush=True)


def higgs_100k():
    X, y = higgs_like(_n(100_000), seed=7)
    ds = dryad.Dataset(X, y)
    p = dict(objective="binary", num_trees=100, num_leaves=63, max_depth=6,
             growth="depthwise")
    b = dryad.train(p, ds, backend="tpu")
    b_cpu = dryad.train(p, ds, backend="cpu")
    same = bool(np.array_equal(b.feature, b_cpu.feature))
    return {"auc": round(auc(y, b.predict_binned(ds.X_binned)), 4),
            "cpu_tree_parity": same}


def covertype():
    X, y = covertype_like(_n(581_000), seed=11)
    ds = dryad.Dataset(X, y)
    p = dict(objective="multiclass", num_class=7, num_trees=30, num_leaves=63,
             max_depth=6, growth="depthwise")
    b = dryad.train(p, ds, backend="tpu")
    pred = b.predict_binned(ds.X_binned)
    return {"accuracy": round(_acc(y, pred), 4)}


def epsilon():
    X, y = epsilon_like(_n(400_000), num_features=2000, seed=13)
    ds = dryad.Dataset(X, y)
    p = dict(objective="regression", num_trees=20, num_leaves=63, max_depth=6,
             growth="depthwise")
    b = dryad.train(p, ds, backend="tpu")
    r = rmse(y, b.predict_binned(ds.X_binned))
    return {"rmse": round(r, 4), "label_std": round(float(np.std(y)), 4)}


def mslr():
    X, y, group = mslr_like(num_queries=_n(3000) // 3, seed=17)
    ds = dryad.Dataset(X, y, group=group)
    # max_depth set -> the batched leaf-wise grower (exact best-first
    # selection over a depth-capped expansion) replaces the sequential
    # O(N·leaves) slot machine
    p = dict(objective="lambdarank", num_trees=50, num_leaves=31,
             max_depth=10)
    b = dryad.train(p, ds, backend="tpu")
    qoff = np.concatenate([[0], np.cumsum(group)])
    scores = b.predict_binned(ds.X_binned, raw_score=True)
    base = ndcg_at_k(y, np.zeros_like(scores), qoff, 10)
    return {"ndcg@10": round(ndcg_at_k(y, scores, qoff, 10), 4),
            "random_ndcg": round(base, 4)}


def criteo():
    (indptr, indices, values, F), y, cat_ids = criteo_like(_n(500_000), seed=19)
    ds = dryad.Dataset(None, y, csr=(indptr, indices, values, F),
                       categorical_features=cat_ids, max_bins=256)
    p = dict(objective="binary", num_trees=30, num_leaves=63, max_depth=6,
             growth="depthwise", categorical_features=list(cat_ids))
    b = dryad.train(p, ds, backend="tpu")
    return {"auc": round(auc(y, b.predict_binned(ds.X_binned)), 4),
            "cat_splits": int(b.is_cat.sum())}


if __name__ == "__main__":
    run("higgs_100k_depth6_100trees", higgs_100k)
    run("covertype_581k_softmax", covertype)
    run("epsilon_400kx2000_regression", epsilon)
    run("mslr_lambdarank_ndcg", mslr)
    run("criteo_sparse_categorical", criteo)
