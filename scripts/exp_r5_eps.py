"""r5 Epsilon-axis measurements (VERDICT r4 #4).

Three questions, CLAUDE.md methodology (K dependent reps in ONE jit,
perturbation reaching every stage, device-resident inputs):

1. partition: masked reduce vs per-row gather at the Epsilon shape
   (400k x 2000 u8) — backs the partition_prefers_reduce gate.
2. natural-order pass at the 800 MB Epsilon matrix: the nat gate has
   excluded this shape since r3 WITHOUT a measurement; record
   admit/reject evidence (kernel wall + any buffer-pressure stall).
3. warm per-iteration marginal with the r5 settings, for STATUS.

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/exp_r5_eps.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

N, F, B = 400_000, 2000, 256


def loop_time(fn, *arrays, K=8):
    def prog(s0, *arrays):
        return jax.lax.fori_loop(0, K, lambda i, s: fn(s, *arrays), s0)

    f = jax.jit(prog)
    # REAL fetches: block_until_ready returned instantly through this
    # tunnel and measured 0.0 ms until the float() fetch was added
    # (CLAUDE.md measuring notes, r5)
    float(f(jnp.float32(0), *arrays))                  # compile + warm
    t0 = time.perf_counter()
    float(f(jnp.float32(1), *arrays))
    return (time.perf_counter() - t0) / K * 1000


def main():
    rng = np.random.default_rng(0)
    print(f"device={jax.devices()[0]}  shape {N}x{F}x{B}", flush=True)
    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, N).astype(np.float32))
    rf_np = rng.integers(0, F, N).astype(np.int32)
    rf = jnp.asarray(rf_np)

    # ---- 1. partition: reduce vs gather ------------------------------------
    def part_reduce(s, Xb, rf):
        # integer-meaningful perturbation: s advances by whole units per
        # rep (the earlier s + eps*sum draft rounded to a CONSTANT under
        # the int cast and XLA hoisted the whole stage — the CLAUDE.md
        # dead-input trap)
        rfp = (rf + s.astype(jnp.int32)) % F
        iota_f = jnp.arange(F, dtype=jnp.int32)
        bins = jnp.max(jnp.where(rfp[:, None] == iota_f[None, :], Xb,
                                 jnp.zeros((), Xb.dtype)),
                       axis=1).astype(jnp.int32)
        return s + 1.0 + jnp.sum(bins).astype(jnp.float32) * 1e-20

    def part_gather(s, Xb, rf):
        rfp = (rf + s.astype(jnp.int32)) % F
        bins = jnp.take_along_axis(Xb, rfp[:, None], axis=1)[:, 0]
        return s + 1.0 + jnp.sum(bins.astype(jnp.int32)).astype(jnp.float32) * 1e-20

    t_red = loop_time(part_reduce, Xb, rf)
    t_gat = loop_time(part_gather, Xb, rf)
    print(f"partition  masked-reduce {t_red:7.1f} ms   "
          f"per-row gather {t_gat:7.1f} ms", flush=True)

    # ---- 2. natural-order pass at the Epsilon shape ------------------------
    from dryad_tpu.engine import pallas_hist

    P = 16
    sel_np = rng.integers(0, P, N).astype(np.int32)
    sel = jnp.asarray(sel_np)
    t0 = time.perf_counter()
    nat = pallas_hist.natural_tiles(Xb, B)
    float(jnp.sum(nat[0, 0, 0].astype(jnp.float32)))   # REAL fetch
    t_tiles = time.perf_counter() - t0
    print(f"nat tiles build: {t_tiles:.1f} s "
          f"(buffer {nat.size * nat.dtype.itemsize / 1e9:.2f} GB)",
          flush=True)

    def nat_step(s, nat, g, h, sel):
        selp = (sel + s.astype(jnp.int32)) % P          # perturb the SLOT
        out = pallas_hist.build_hist_small(nat, g, h, selp, P, B, F)
        return s + 1.0 + out[0, 0, 0, 0] * 1e-20

    t_nat = loop_time(nat_step, nat, g, h, sel, K=3)

    # plan-path comparison at the same selection
    from dryad_tpu.engine.histogram import build_hist_segmented

    def plan_step(s, Xb, g, h, sel):
        selp = (sel + s.astype(jnp.int32)) % P
        out = build_hist_segmented(Xb, g, h, selp, P, B, backend="pallas")
        return s + 1.0 + out[0, 0, 0, 0] * 1e-20

    t_plan = loop_time(plan_step, Xb, g, h, sel, K=3)
    print(f"16-slot level pass  nat {t_nat:7.0f} ms   plan(sort+gather+"
          f"kernel) {t_plan:7.0f} ms", flush=True)

    # ---- 3. warm marginal with r5 settings ---------------------------------
    import dryad_tpu as dryad

    y_np = (rng.random(N) < 0.5).astype(np.float32)
    X_np = np.asarray(Xb, np.float32) + rng.random((N, F)).astype(np.float32)
    ds = dryad.Dataset(X_np, y_np)
    for trees in (2, 6):
        t0 = time.perf_counter()
        dryad.train(dict(objective="regression", num_trees=trees,
                         num_leaves=255, max_depth=8), ds, backend="tpu")
        print(f"{trees}-tree wall {time.perf_counter() - t0:6.1f} s",
              flush=True)


if __name__ == "__main__":
    main()
