"""Fleet smoke for scripts/ci.sh (runs under JAX_PLATFORMS=cpu).

REAL subprocess replicas — each spawns ``python -m dryad_tpu serve`` and
pays the full jax import — not the protocol stub the tier-1 tests use:
this is the end-to-end drill the ISSUE's acceptance asks for.  A
2-replica fleet takes an injected replica_crash (armed through the
DRYAD_REPLICA_FAULTS env on replica 0, the production drill wire) while
a closed loop of interactive requests runs through the router; the smoke
asserts:

* ZERO failed interactive requests — the crash lands inside the router's
  single-retry budget (the dying forward is retried on the healthy
  replica),
* the supervisor detected the crash (journal ``replica_crash`` with the
  canonical injected exit code) and respawned the slot
  (``replica_respawn`` -> ``replica_ready`` at generation 1),
* the respawned replica serves again and fleet /healthz is 200 with both
  replicas routable.

r17 (request tracing) — the smoke additionally asserts:

* every interactive request's ``X-Dryad-Trace`` id round-trips (the
  response echoes the id the client sent — zero mismatches),
* the merged router ``/trace`` contains ONE trace id with BOTH forward
  attempts (the crash-killed forward to r0 and the retried forward that
  answered) — the request that survived the replica crash shows its
  whole story under one id,
* end-to-end span assembly: some traced request shows the router span
  AND the owning replica's queue_wait/batch_assembly/predict spans under
  the same id (the clock-aligned per-replica tracks),
* the aggregated router ``/metrics`` reports merged per-priority fleet
  p99 gauges (``dryad_fleet_latency_ms{q="p99",...}``).

r18 (drift telemetry) — the end-to-end model-quality drill:

* the trained model carries its reference profile; baseline traffic
  drawn from the TRAINING rows keeps every fleet drift verdict green
  (no false positive),
* a 3x covariate-shift burst flips the merged ``GET /drift`` verdict
  within one window; a second evaluation makes it SUSTAINED: the
  journal records ``drift_breach``, ``/healthz`` stays 200 (warn-only —
  a drifted model still serves) with ``drift:<model>`` in its payload,
  and the aggregated /metrics carries ``dryad_fleet_drift_*`` gauges.

r22 (elastic capacity) — the ramp drill on REAL replicas:

* a min=1/max=3 fleet under a sustained closed-loop ramp: the
  CapacityController reads the router's admission saturation, journals
  ``scale_up``, and the new replica becomes routable — with ZERO shed
  and zero failed interactive requests end to end (capacity arrives
  before the router ever degrades to shedding),
* continued pressure inside the up-cooldown and sustained idle at the
  min bound journal ``scale_skipped`` with the canonical ``cooldown`` /
  ``at-bound`` reasons (one burst = one action),
* sustained idle drains the added replica back out through the retire
  path (``scale_down`` -> ``replica_retired``) with zero dropped
  in-flight requests, and the pool settles at min_replicas.

Prints one JSON summary line on success, exits 1 with a reason otherwise.
"""

import http.client
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import dryad_tpu as dryad  # noqa: E402
from dryad_tpu.datasets import higgs_like  # noqa: E402
from dryad_tpu.fleet import FleetRouter, FleetSupervisor, serve_argv  # noqa: E402
from dryad_tpu.fleet.bench import _closed_loop  # noqa: E402
from dryad_tpu.obs.registry import Registry  # noqa: E402
from dryad_tpu.obs.trace_export import enable_tracing  # noqa: E402
from dryad_tpu.resilience import faults as F  # noqa: E402
from dryad_tpu.resilience.journal import RunJournal  # noqa: E402
from dryad_tpu.resilience.policy import RetryPolicy  # noqa: E402

PARAMS = dict(objective="binary", num_trees=10, num_leaves=7, max_bins=32,
              seed=5)


def fail(reason: str) -> int:
    print(f"FLEET SMOKE FAIL: {reason}", flush=True)
    return 1


def main() -> int:
    # the r18 drift phase needs the model's embedded reference profile
    # (the production default; ON regardless of the caller's env)
    os.environ["DRYAD_PROFILE"] = "1"
    X, y = higgs_like(1200, seed=17)
    ds = dryad.Dataset(X, y, max_bins=32)
    booster = dryad.train(PARAMS, ds, backend="cpu")
    if booster.profile is None:
        return fail("dryad.train attached no reference profile")
    num_features = X.shape[1]

    with tempfile.TemporaryDirectory(prefix="dryad-fleet-smoke-") as td:
        model_path = os.path.join(td, "model.dryad")
        booster.save(model_path)
        journal_path = os.path.join(td, "fleet.jsonl")
        reg = Registry()
        enable_tracing()          # the router-side span ring (/trace)

        def make_argv(index: int, port_file: str) -> list:
            return serve_argv([model_path], port_file, backend="cpu",
                              max_batch_rows=64, max_wait_ms=0.5,
                              drift_window=1024)

        crash_spec = F.encode_points(
            [F.FaultPoint(site="request", iteration=2,
                          kind=F.REPLICA_CRASH)])
        sup = FleetSupervisor(
            make_argv, 2,
            policy=RetryPolicy(backoff_base_s=0.1, retry_budget=3),
            journal=journal_path, registry=reg,
            probe_interval_s=0.1, startup_timeout_s=180.0,
            fault_env={0: crash_spec})
        sup.start()
        router = FleetRouter(sup, registry=reg, max_inflight=16,
                             drift_budget_psi=0.25,
                             drift_breach_after=2).start()
        try:
            # closed interactive loop through the router while the crash
            # drill fires on replica 0's second /predict
            from dryad_tpu.fleet.bench import _payloads

            payloads = _payloads(num_features, (1, 3), seed=11)
            loop = _closed_loop(router.host, router.port, payloads,
                                clients=3, duration_s=4.0, seed=2,
                                priority="interactive", trace=True)
            # the respawned replica (a fresh jax import) must come back
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                if all(s.routable for s in sup.slots):
                    break
                time.sleep(0.2)
            else:
                return fail("replica 0 never respawned to routable "
                            f"(states: {sup.states()})")
            tail = _closed_loop(router.host, router.port, payloads,
                                clients=2, duration_s=1.0, seed=3,
                                trace=True)
            # the merged trace + aggregated metrics while the fleet is up
            conn = http.client.HTTPConnection(router.host, router.port,
                                              timeout=30.0)
            conn.request("GET", "/trace?k=0")
            resp = conn.getresponse()
            trace_doc = json.loads(resp.read())
            conn.request("GET", "/metrics")
            metrics_text = conn.getresponse().read().decode()

            # ---- r18 drift phase -------------------------------------------
            # Baseline traffic drawn from the TRAINING rows (the traffic
            # the profile describes): the fleet verdict must stay green
            # — the no-false-positive half of the acceptance drill.
            def slice_payloads(scale: float) -> dict:
                out = {}
                for n, start in ((37, 0), (83, 100), (129, 300), (211, 500)):
                    rows = (X[start:start + n] * scale).tolist()
                    out[n] = json.dumps({"rows": rows}).encode()
                return out

            _closed_loop(router.host, router.port, slice_payloads(1.0),
                         clients=3, duration_s=2.5, seed=5)
            conn.request("GET", "/drift")
            drift_clean = json.loads(conn.getresponse().read())
            # Covariate-shift burst: the same rows scaled 3x bin into
            # the tails of every feature's sketch — within one window
            # the merged fleet verdict must flip, and a second
            # evaluation makes the breach SUSTAINED (breach_after=2:
            # journal + /healthz warning).
            shifted = _closed_loop(router.host, router.port,
                                   slice_payloads(3.0), clients=3,
                                   duration_s=2.5, seed=6)
            conn.request("GET", "/drift")
            json.loads(conn.getresponse().read())     # evaluation 1
            conn.request("GET", "/drift")
            drift_doc = json.loads(conn.getresponse().read())
            conn.request("GET", "/healthz")
            health_resp = conn.getresponse()
            health_code = health_resp.status
            health_doc = json.loads(health_resp.read())
            conn.request("GET", "/metrics")
            drift_metrics = conn.getresponse().read().decode()
            conn.close()
        finally:
            router.stop()
            sup.stop()
        events = RunJournal.read(journal_path)

        # ---- r22 elastic capacity ramp (its own fleet: min=1, max=3) -------
        from dryad_tpu.fleet import CapacityController

        journal2_path = os.path.join(td, "fleet_elastic.jsonl")
        reg2 = Registry()
        sup2 = FleetSupervisor(
            make_argv, 1,
            policy=RetryPolicy(backoff_base_s=0.1, retry_budget=3),
            journal=journal2_path, registry=reg2,
            probe_interval_s=0.1, startup_timeout_s=180.0)
        sup2.start()
        # generous budgets: this drill's pressure is admission saturation;
        # a latency breach would HOLD its streak through the idle phase
        # (empty windows are no evidence) and block the drain half
        router2 = FleetRouter(sup2, registry=reg2, max_inflight=8,
                              slo_budgets_ms={"interactive": 30000.0,
                                              "bulk": 30000.0}).start()
        # saturation pressure: 6 closed-loop clients against max_inflight=8
        # keep admission depth near 6 (>= 0.6 * 8) without ever shedding
        ctrl = CapacityController(
            sup2, router2.state.capacity_signals,
            min_replicas=1, max_replicas=3,
            breach_after=2, idle_after=6,
            cooldown_up_s=120.0, cooldown_down_s=5.0,
            saturation=0.6, poll_interval_s=0.25,
            drain_timeout_s=30.0, registry=reg2).start()
        ramp_failures = ramp_requests = 0
        try:
            heavy = {}
            for n, start in ((200, 0), (600, 100)):
                heavy[n] = json.dumps(
                    {"rows": X[start:start + n].tolist()}).encode()
            # ramp until the controller's replica is routable (the spawn
            # pays a full jax import) — pressure stays on throughout
            deadline = time.monotonic() + 150.0
            ramp_seed = 21
            while time.monotonic() < deadline:
                leg = _closed_loop(router2.host, router2.port, heavy,
                                   clients=6, duration_s=2.0,
                                   seed=ramp_seed, priority="interactive")
                ramp_seed += 1
                ramp_failures += leg["failures"]
                ramp_requests += leg["requests"]
                if len(sup2.slots) >= 2 and sup2.slots[1].routable:
                    break
            else:
                return fail("the ramp never scaled up to a routable "
                            f"replica (states: {sup2.states()}, journal: "
                            f"{RunJournal.read(journal2_path)[-5:]})")
            # one more pressured leg across BOTH replicas: proves the
            # grown fleet serves, and pokes inside the up-cooldown now
            # journal the canonical 'cooldown' skip
            leg = _closed_loop(router2.host, router2.port, heavy,
                               clients=6, duration_s=2.5, seed=ramp_seed,
                               priority="interactive")
            ramp_failures += leg["failures"]
            ramp_requests += leg["requests"]
            peak_replicas = len(sup2.slots)
            # sustained idle: the controller must drain the added replica
            # back out (zero in-flight to drop) and then hold at-bound
            drain_deadline = time.monotonic() + 45.0
            while time.monotonic() < drain_deadline:
                k2 = [e["event"] for e in RunJournal.read(journal2_path)]
                if "replica_retired" in k2 and len(sup2.slots) == 1:
                    break
                time.sleep(0.25)
            else:
                return fail("sustained idle never drained the scaled-up "
                            f"replica (states: {sup2.states()})")
            # a few more idle polls at the min bound -> 'at-bound' skips
            time.sleep(2.5)
            shed2 = reg2.counter("dryad_fleet_shed_total", "").value()
        finally:
            ctrl.stop(timeout_s=10.0)
            router2.stop()
            sup2.stop()
        elastic_events = RunJournal.read(journal2_path)

    if loop["failures"] or tail["failures"]:
        return fail(f"{loop['failures']} + {tail['failures']} failed "
                    "interactive request(s) — the single-retry budget did "
                    "not absorb the crash")
    if loop["requests"] < 20:
        return fail(f"only {loop['requests']} requests made it through — "
                    "the loop never exercised the fleet")
    crashes = [e for e in events if e["event"] == "replica_crash"]
    if not (crashes and crashes[0]["replica"] == "r0"
            and crashes[0]["exit_code"] == F.REPLICA_CRASH_EXIT):
        return fail(f"no injected crash on r0 in the journal: {crashes}")
    respawns = [e for e in events if e["event"] == "replica_respawn"]
    readies = [e for e in events if e["event"] == "replica_ready"]
    if not (respawns and respawns[0]["reason"] == "crash"):
        return fail(f"no crash-respawn in the journal: {respawns}")
    if not any(e["replica"] == "r0" and e["generation"] == 1
               for e in readies):
        return fail("replica 0 never reached generation 1 readiness")
    retries = reg.counter("dryad_fleet_retry_total", "").value()

    # ---- r17 tracing assertions -------------------------------------------
    if loop["trace_mismatches"] or tail["trace_mismatches"]:
        return fail(f"{loop['trace_mismatches']} + "
                    f"{tail['trace_mismatches']} response(s) did not echo "
                    "their X-Dryad-Trace id")
    spans_by_trace: dict = {}
    for ev in trace_doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        tid = ev.get("args", {}).get("trace")
        if tid:
            spans_by_trace.setdefault(tid, []).append(
                (ev["pid"], ev["args"]["path"]))
    # the crash-surviving request: both forward attempts under ONE id
    crash_traces = [
        t for t, evs in spans_by_trace.items()
        if len({p for _, p in evs if p.startswith("fleet.forward/")}) >= 2]
    if not crash_traces:
        return fail("no trace shows two forward attempts — the crashed "
                    "request's retry is not assembled under one id "
                    f"({len(spans_by_trace)} traces seen)")
    # end-to-end assembly: router span + the owning replica's stage spans
    replica_stages = {"serve.request/queue_wait",
                      "serve.request/batch_assembly",
                      "serve.request/predict"}
    full = [t for t, evs in spans_by_trace.items()
            if any(p == "fleet.request" for _, p in evs)
            and replica_stages <= {p for pid, p in evs if pid >= 10}]
    if not full:
        return fail("no trace assembles the router span with the "
                    "replica's queue/batch/predict spans under one id")
    if 'dryad_fleet_latency_ms{' not in metrics_text \
            or 'q="p99"' not in metrics_text:
        return fail("router /metrics lacks the merged per-priority p99 "
                    "gauges (dryad_fleet_latency_ms)")

    # ---- r18 drift assertions ---------------------------------------------
    if shifted["failures"]:
        return fail(f"{shifted['failures']} failed request(s) during the "
                    "covariate-shift burst — a drifted model must still "
                    "serve")
    clean_models = drift_clean.get("models") or {}
    if not (drift_clean.get("enabled") and clean_models):
        return fail(f"GET /drift reported no models under baseline "
                    f"traffic: {drift_clean}")
    false_pos = {m: v for m, v in clean_models.items() if v.get("breached")}
    if false_pos:
        return fail(f"drift verdict breached on training-distribution "
                    f"traffic (false positive): {false_pos}")
    drifted = {m: v for m, v in (drift_doc.get("models") or {}).items()
               if v.get("sustained")}
    if not drifted:
        return fail(f"the 3x covariate-shift burst never flipped the "
                    f"fleet verdict to sustained: {drift_doc}")
    model, verdict = next(iter(drifted.items()))
    if not verdict.get("top"):
        return fail(f"breached verdict names no offending features: "
                    f"{verdict}")
    if f"drift:{model}" not in (drift_doc.get("warnings") or []):
        return fail(f"/drift warnings lack drift:{model}: {drift_doc}")
    if health_code != 200:
        return fail(f"/healthz went {health_code} on a drift breach — "
                    "drift is warn-only, a drifted model still serves")
    if f"drift:{model}" not in (health_doc.get("drift", {})
                                .get("warnings") or []):
        return fail(f"/healthz payload lacks the drift:{model} warning: "
                    f"{health_doc.get('drift')}")
    if "dryad_fleet_drift_psi_max{" not in drift_metrics:
        return fail("router /metrics lacks the merged "
                    "dryad_fleet_drift_* gauges")
    breaches = [e for e in events if e["event"] == "drift_breach"]
    if not (breaches and breaches[0].get("model") == model):
        return fail(f"no drift_breach journal event for {model}: "
                    f"{breaches}")

    # ---- r22 elastic capacity assertions ------------------------------------
    if ramp_failures:
        return fail(f"{ramp_failures} failed interactive request(s) during "
                    "the capacity ramp — the fleet degraded before the "
                    "scale-up landed")
    if shed2:
        return fail(f"the router shed {shed2} request(s) during the ramp — "
                    "capacity must arrive before shedding starts")
    ekinds = [e["event"] for e in elastic_events]
    if ekinds.count("scale_up") != 1:
        return fail(f"expected exactly one scale_up for the burst, got "
                    f"{ekinds.count('scale_up')}: {ekinds}")
    if not any(e["event"] == "replica_ready" and e.get("replica") == "r1"
               for e in elastic_events):
        return fail("the scaled-up replica r1 never journaled ready")
    if ekinds.count("scale_down") != 1 \
            or ekinds.count("replica_retired") != 1:
        return fail(f"sustained idle did not drain exactly one replica: "
                    f"{ekinds}")
    skip_reasons = {e.get("reason") for e in elastic_events
                    if e["event"] == "scale_skipped"}
    for want in ("cooldown", "at-bound"):
        if want not in skip_reasons:
            return fail(f"no '{want}' scale_skipped journaled "
                        f"(saw: {sorted(skip_reasons)})")
    if ekinds.index("scale_up") > ekinds.index("scale_down"):
        return fail("scale_down journaled before scale_up")

    print(json.dumps({
        "fleet_smoke": "ok",
        "requests": loop["requests"] + tail["requests"],
        "failed_interactive": 0,
        "trace_mismatches": 0,
        "crash_traces": len(crash_traces),
        "assembled_traces": len(full),
        "crashes": len(crashes),
        "respawns": len(respawns),
        "router_retries": retries,
        "journal_events": len(events),
        "drift_model": model,
        "drift_psi_max": verdict.get("psi_max"),
        "drift_clean_psi_max": max(v.get("psi_max", 0.0)
                                   for v in clean_models.values()),
        "drift_breaches_journaled": len(breaches),
        "ramp_requests": ramp_requests,
        "ramp_failures": 0,
        "fleet_scale_up_total": ekinds.count("scale_up"),
        "fleet_scale_down_total": ekinds.count("scale_down"),
        "fleet_replicas": peak_replicas,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
