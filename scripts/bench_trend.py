"""Bench trend ledger CLI (engine: dryad_tpu/obs/trends.py).

    python scripts/bench_trend.py --check [--root .] [--tolerance 0.15]
    python scripts/bench_trend.py --selftest
    python scripts/bench_trend.py --json report.json

``--check`` loads the committed ``BENCH_r*.json`` history, compares the
newest point against the history median (spread-aware: a per-arm spread
> 5% in the newest artifact makes a would-be regression ``suspect``,
never a verdict — CLAUDE.md), prints the machine-readable report, and
exits 1 only on a ``regression`` verdict.  scripts/ci.sh runs it over
the committed files (must exit 0) and then ``--selftest``, which seeds a
synthetic regression fixture in a temp dir and exits 0 only if the
checker actually flags it — the gate proves both directions.

Stdlib only (the ledger is jax-free by the obs package lint).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def _write_fixture(td: str, regressed: bool) -> None:
    """Three healthy rounds + a newest point that either holds the trend
    or regresses the 10M marginal ~2x with a CLEAN spread (a dirty spread
    must downgrade to ``suspect`` — also asserted by --selftest)."""
    base = {"value": 13.0, "iters_per_sec_10m": 0.40,
            "marginal_s_per_iter_10m": 2.5, "wall_8tree_10m": 21.0,
            "spread_2tree_10m": 0.01, "spread_8tree_10m": 0.01}
    for i, rnd in enumerate((1, 2, 3)):
        point = dict(base, value=base["value"] + i * 0.1)
        with open(os.path.join(td, f"BENCH_r{rnd:02d}.json"), "w") as f:
            json.dump({"n": rnd, "parsed": point}, f)
    newest = dict(base, schema_version=1, git_rev="fixture",
                  device_kind="cpu")
    if regressed:
        newest["marginal_s_per_iter_10m"] = 5.2     # ~2x worse
        newest["iters_per_sec_10m"] = 0.19
    with open(os.path.join(td, "BENCH_r04.json"), "w") as f:
        json.dump({"n": 4, "parsed": newest}, f)


def _selftest() -> int:
    from dryad_tpu.obs.trends import compare, load_history

    with tempfile.TemporaryDirectory() as td:
        _write_fixture(td, regressed=False)
        clean = compare(load_history(td))
        if not clean["ok"]:
            print("SELFTEST FAIL: healthy fixture flagged", clean)
            return 1
        _write_fixture(td, regressed=True)
        bad = compare(load_history(td))
        verdicts = {m: e["verdict"] for m, e in bad["metrics"].items()}
        if bad["ok"] or verdicts.get("marginal_s_per_iter_10m") != "regression":
            print("SELFTEST FAIL: seeded regression not flagged", verdicts)
            return 1
        # the spread veto: the same regression under a suspect capture
        # must NOT produce a regression verdict
        _write_fixture(td, regressed=True)
        with open(os.path.join(td, "BENCH_r04.json")) as f:
            doc = json.load(f)
        doc["parsed"]["spread_8tree_10m"] = 0.3
        doc["parsed"]["spread_2tree_10m"] = 0.3
        with open(os.path.join(td, "BENCH_r04.json"), "w") as f:
            json.dump(doc, f)
        vetoed = compare(load_history(td))
        verdicts = {m: e["verdict"] for m, e in vetoed["metrics"].items()}
        if (not vetoed["ok"]
                or verdicts.get("marginal_s_per_iter_10m") != "suspect"):
            print("SELFTEST FAIL: spread veto missing", verdicts)
            return 1
    print("TREND SELFTEST OK: regression flagged, spread veto honored")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_trend")
    ap.add_argument("--root", default=".",
                    help="directory holding BENCH_r*.json (default: cwd)")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="relative regression tolerance vs the history "
                         "median (default 0.15 — trends, not points)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any regression verdict")
    ap.add_argument("--selftest", action="store_true",
                    help="seed a regression fixture and verify the "
                         "checker flags it (ci.sh's proof of the gate)")
    ap.add_argument("--json", help="also write the report here")
    args = ap.parse_args(argv)

    if args.selftest:
        return _selftest()

    from dryad_tpu.obs.trends import DEFAULT_TOLERANCE, compare, load_history

    tol = args.tolerance if args.tolerance is not None else DEFAULT_TOLERANCE
    history = load_history(args.root)
    report = compare(history, tol)
    print(json.dumps(report, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    if len(history) < 2:
        print("bench_trend: <2 history points — nothing to compare",
              file=sys.stderr)
        return 0
    if args.check and not report["ok"]:
        bad = [m for m, e in report["metrics"].items()
               if e["verdict"] == "regression"]
        print(f"TREND REGRESSION: {bad} vs the history median "
              f"(tolerance {tol:.0%})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
