"""The headline artifact (VERDICT r4 #1): the REAL north-star config —
Higgs-10M, depth-8, 500 trees — executed end-to-end on the attached chip,
with a validation set so chunked eval runs at scale, THEN a kill at
~iteration 250 and a resume proving checkpoint bit-identity at 10M.

BASELINE.json:2 defines the metric on exactly this run ("boosting
iters/sec + final AUC (Higgs-10M, depth-8, 500 trees)"); every prior
round extrapolated it from short-run marginals.  This script produces the
recorded wall-clock, iters/s, and final train/valid AUC, written to
HEADLINE_r5.json.

Usage:
  PYTHONPATH=/root/.axon_site:/root/repo python scripts/headline_10m.py \
      [--trees 500] [--no-drill] [--out HEADLINE_r5.json]

Methodology notes (CLAUDE.md): inputs are device-cached via
Dataset.device_arrays inside train; the wall for the headline run is one
cold end-to-end wall (compile included, reported separately from the
steady-state marginal); nothing else may run against the chip while this
does.
"""

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import dryad_tpu as dryad  # noqa: E402
from dryad_tpu.datasets import higgs_like  # noqa: E402
from dryad_tpu.metrics import auc  # noqa: E402

PARAMS = dict(objective="binary", num_trees=500, num_leaves=255,
              max_depth=8, max_bins=256, learning_rate=0.1,
              growth="depthwise", seed=11)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trees", type=int, default=500)
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--valid-rows", type=int, default=1_000_000)
    ap.add_argument("--no-drill", action="store_true",
                    help="skip the kill-and-resume drill")
    ap.add_argument("--out", default="HEADLINE_r5.json")
    ap.add_argument("--ckdir", default="/tmp/headline_ck")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    print(f"device: {dev} ({dev.platform})", flush=True)

    t0 = time.perf_counter()
    X, y = higgs_like(args.rows + args.valid_rows, seed=7)
    Xt, yt = X[:args.rows], y[:args.rows]
    Xv, yv = X[args.rows:], y[args.rows:]
    ds = dryad.Dataset(Xt, yt)
    vds = dryad.Dataset(Xv, yv, mapper=ds.mapper)
    t_data = time.perf_counter() - t0
    print(f"data ready in {t_data:.1f}s", flush=True)

    p = dict(PARAMS, num_trees=args.trees)

    # ---- headline run: uninterrupted, checkpointed, deferred eval ----------
    # checkpoints every 50 iters guard the ~21 min run against tunnel
    # faults (one died at ~minute 5 on 2026-07-31); resume=True continues
    # from the newest checkpoint if a previous attempt crashed — the
    # recorded wall is only clean when start_fresh ran (reported below)
    import os

    main_ck = args.ckdir + "_main"
    fresh = not (os.path.isdir(main_ck) and os.listdir(main_ck))
    t0 = time.perf_counter()
    b = dryad.train(p, ds, [vds], backend="tpu", checkpoint_dir=main_ck,
                    checkpoint_every=50, resume=True)
    wall = time.perf_counter() - t0
    if not fresh:
        # a resumed run's wall covers only the REMAINDER: writing
        # trees/wall would inflate the headline metric — refuse
        print("NOTE: resumed from a prior crash — wall covers the "
              "remainder only; NOT writing the headline iters/s "
              f"(remainder wall {wall:.1f}s). Clear {main_ck} and rerun "
              "for a clean artifact.", flush=True)
        return 1
    iters_per_sec = args.trees / wall
    hist = b.train_state["eval_history"]["valid_auc"]
    valid_auc = hist[-1][1]
    t0 = time.perf_counter()
    train_auc = auc(yt, b.predict_binned(ds.X_binned, raw_score=True))
    t_eval = time.perf_counter() - t0
    print(f"HEADLINE: {args.trees} trees in {wall:.1f}s = "
          f"{iters_per_sec:.4f} iters/s | valid AUC {valid_auc:.5f} "
          f"| train AUC {train_auc:.5f} (eval {t_eval:.0f}s)", flush=True)

    result = {
        "config": "Higgs-10M depth-8 x " + str(args.trees) + " trees "
                  "(BASELINE.json:2), 1M-row valid set, chunked device loop",
        "uninterrupted": fresh,
        "rows": args.rows,
        "trees": args.trees,
        "wall_s": round(wall, 1),
        "iters_per_sec": round(iters_per_sec, 4),
        "valid_auc": round(float(valid_auc), 5),
        "train_auc": round(float(train_auc), 5),
        "eval_history_tail": [[it, round(float(v), 5)]
                              for it, v in hist[-5:]],
        "device": str(dev),
    }

    # ---- kill-and-resume drill at 10M (checkpoint bit-identity) ------------
    if not args.no_drill:
        import shutil

        shutil.rmtree(args.ckdir, ignore_errors=True)

        class Crash(RuntimeError):
            pass

        def crash_at(it, info):
            if it >= args.trees // 2:
                raise Crash(f"drill kill at iteration {it}")

        t0 = time.perf_counter()
        try:
            dryad.train(p, ds, [vds], backend="tpu",
                        checkpoint_dir=args.ckdir, checkpoint_every=50,
                        callback=crash_at)
            raise AssertionError("drill crash did not fire")
        except Crash as e:
            print(f"killed: {e} after {time.perf_counter() - t0:.1f}s",
                  flush=True)
        t0 = time.perf_counter()
        rb = dryad.train(p, ds, [vds], backend="tpu",
                         checkpoint_dir=args.ckdir, checkpoint_every=50,
                         resume=True)
        t_resume = time.perf_counter() - t0
        same_struct = bool(np.array_equal(b.feature, rb.feature)
                           and np.array_equal(b.threshold, rb.threshold))
        same_value = bool(np.array_equal(b.value, rb.value))
        pr = rb.predict_binned(ds.X_binned[:100_000], raw_score=True)
        pb = b.predict_binned(ds.X_binned[:100_000], raw_score=True)
        same_pred = bool(np.array_equal(pr, np.asarray(pb)))
        print(f"resume: {t_resume:.1f}s | structures identical: "
              f"{same_struct} | values identical: {same_value} | predict "
              f"bitwise: {same_pred}", flush=True)
        result["drill"] = {
            "killed_at_iteration": args.trees // 2,
            "resume_wall_s": round(t_resume, 1),
            "structures_bitwise": same_struct,
            "values_bitwise": same_value,
            "predict_bitwise": same_pred,
        }
        if not (same_struct and same_value and same_pred):
            print("DRILL FAILED: resume is not bit-identical", flush=True)

    with open(args.out, "w") as f:
        f.write(json.dumps(result, indent=1))
    print(f"wrote {args.out}", flush=True)
    drill_ok = args.no_drill or (result.get("drill", {})
                                 .get("predict_bitwise", False))
    return 0 if drill_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
