"""The headline artifact (VERDICT r4 #1): the REAL north-star config —
Higgs-10M, depth-8, 500 trees — executed end-to-end on the attached chip,
with a validation set so chunked eval runs at scale, THEN a supervised
kill-and-resume drill proving checkpoint bit-identity at 10M.

BASELINE.json:2 defines the metric on exactly this run ("boosting
iters/sec + final AUC (Higgs-10M, depth-8, 500 trees)"); every prior
round extrapolated it from short-run marginals.  This script produces the
recorded wall-clock, iters/s, and final train/valid AUC, written to
HEADLINE_r5.json.

Since r8 the run is SUPERVISED (dryad_tpu/resilience): the tunnel fault
classes that killed r5's attempts (STATUS r5 — `UNAVAILABLE` device
errors, first-fetch deaths on ~20 s chunks) are classified, chunking is
degraded toward the known-safe CH=2, and training auto-resumes from its
own checkpoints — the ad-hoc resume/restart plumbing this script used to
carry is gone.  The journal (<out>.journal.jsonl) records every
dispatch/fetch/fault/backoff/resume event; the recorded wall is the
supervised end-to-end wall, with the fault count reported beside it so a
faulted capture is visible in the artifact.

Usage:
  PYTHONPATH=/root/.axon_site:/root/repo python scripts/headline_10m.py \
      [--trees 500] [--no-drill] [--out HEADLINE_r5.json]

Methodology notes (CLAUDE.md): inputs are device-cached via
Dataset.device_arrays inside train; the wall for the headline run is one
cold end-to-end wall (compile included, reported separately from the
steady-state marginal); nothing else may run against the chip while this
does.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import dryad_tpu as dryad  # noqa: E402
from dryad_tpu.datasets import higgs_like  # noqa: E402
from dryad_tpu.metrics import auc  # noqa: E402
from dryad_tpu.resilience import (  # noqa: E402
    FaultInjector,
    RetryPolicy,
    RunJournal,
    supervise_train,
)
from dryad_tpu.resilience import faults as F  # noqa: E402

PARAMS = dict(objective="binary", num_trees=500, num_leaves=255,
              max_depth=8, max_bins=256, learning_rate=0.1,
              growth="depthwise", seed=11)

# tunnel-calibrated supervision: short first backoff (the faults are not
# load-induced), tight same-point budget, and the documented chunk ladder
# ending on the known-safe 2
POLICY = RetryPolicy(retry_budget=8, backoff_base_s=5.0, backoff_max_s=30.0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trees", type=int, default=500)
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--valid-rows", type=int, default=1_000_000)
    ap.add_argument("--no-drill", action="store_true",
                    help="skip the kill-and-resume drill")
    ap.add_argument("--out", default="HEADLINE_r5.json")
    ap.add_argument("--ckdir", default="/tmp/headline_ck")
    args = ap.parse_args()

    import jax

    dev = jax.devices()[0]
    print(f"device: {dev} ({dev.platform})", flush=True)

    t0 = time.perf_counter()
    X, y = higgs_like(args.rows + args.valid_rows, seed=7)
    Xt, yt = X[:args.rows], y[:args.rows]
    Xv, yv = X[args.rows:], y[args.rows:]
    ds = dryad.Dataset(Xt, yt)
    vds = dryad.Dataset(Xv, yv, mapper=ds.mapper)
    t_data = time.perf_counter() - t0
    print(f"data ready in {t_data:.1f}s", flush=True)

    p = dict(PARAMS, num_trees=args.trees)

    # ---- headline run: supervised, checkpointed, deferred eval -------------
    # checkpoints every 50 iters + the supervisor guard the ~21 min run
    # against tunnel faults; in-run faults auto-resume (wall covers them,
    # faults count reported).  A PRE-EXISTING checkpoint dir means a prior
    # INVOCATION crashed — the wall would cover only the remainder, so the
    # headline metric is refused exactly as before.
    main_ck = args.ckdir + "_main"
    journal_path = args.out + ".journal.jsonl"
    # has_checkpoints, not "dir non-empty": a crash mid-atomic-write leaves
    # only a ckpt_*.tmp stray, and the rerun that then trains CLEAN from
    # scratch must not have its artifact refused as "resumed"
    from dryad_tpu.checkpoint import Checkpointer
    fresh = not Checkpointer.has_checkpoints(main_ck)
    # 50 at the real 500-tree config; scaled down for small validation runs
    # so checkpoints (and the drill's post-checkpoint fault) exist at all
    ck_every = min(50, max(2, args.trees // 10))
    t0 = time.perf_counter()
    b = supervise_train(p, ds, [vds], backend="tpu", checkpoint_dir=main_ck,
                        checkpoint_every=ck_every, policy=POLICY,
                        journal=journal_path)
    wall = time.perf_counter() - t0
    # last-run slice: the journal is append-only across invocations
    events = RunJournal.read_last_run(journal_path)
    n_faults = sum(e["event"] == "fault" for e in events)
    if not fresh:
        print("NOTE: resumed from a prior invocation's checkpoints — wall "
              "covers the remainder only; NOT writing the headline iters/s "
              f"(remainder wall {wall:.1f}s). Clear {main_ck} and rerun "
              "for a clean artifact.", flush=True)
        return 1
    iters_per_sec = args.trees / wall
    hist = b.train_state["eval_history"]["valid_auc"]
    valid_auc = hist[-1][1]
    t0 = time.perf_counter()
    train_auc = auc(yt, b.predict_binned(ds.X_binned, raw_score=True))
    t_eval = time.perf_counter() - t0
    print(f"HEADLINE: {args.trees} trees in {wall:.1f}s = "
          f"{iters_per_sec:.4f} iters/s | valid AUC {valid_auc:.5f} "
          f"| train AUC {train_auc:.5f} (eval {t_eval:.0f}s) "
          f"| supervised faults absorbed: {n_faults}", flush=True)

    result = {
        "config": "Higgs-10M depth-8 x " + str(args.trees) + " trees "
                  "(BASELINE.json:2), 1M-row valid set, chunked device loop",
        # non-fresh invocations returned above, so only the fault count can
        # disqualify the artifact here
        "uninterrupted": n_faults == 0,
        "supervised": True,
        "faults_absorbed": n_faults,
        "rows": args.rows,
        "trees": args.trees,
        "wall_s": round(wall, 1),
        "iters_per_sec": round(iters_per_sec, 4),
        "valid_auc": round(float(valid_auc), 5),
        "train_auc": round(float(train_auc), 5),
        "eval_history_tail": [[it, round(float(v), 5)]
                              for it, v in hist[-5:]],
        "device": str(dev),
    }

    # ---- supervised kill-and-resume drill at 10M (checkpoint bit-identity) -
    # an injected device fault at ~iteration trees/2 exercises the REAL
    # recovery path (classify -> resume from the latest checkpoint) instead
    # of the old hand-rolled crash-callback + manual-resume plumbing
    if not args.no_drill:
        import shutil

        shutil.rmtree(args.ckdir, ignore_errors=True)
        drill_journal = args.out + ".drill.journal.jsonl"
        injector = FaultInjector(
            [(args.trees // 2, F.DEVICE_UNAVAILABLE, "dispatch")])
        t0 = time.perf_counter()
        rb = supervise_train(p, ds, [vds], backend="tpu",
                             checkpoint_dir=args.ckdir,
                             checkpoint_every=ck_every,
                             policy=POLICY, journal=drill_journal,
                             fault_injector=injector)
        t_drill = time.perf_counter() - t0
        assert injector.fired, "drill fault did not fire"
        drill_events = RunJournal.read_last_run(drill_journal)
        resumes = [e for e in drill_events if e["event"] == "resume"]
        same_struct = bool(np.array_equal(b.feature, rb.feature)
                           and np.array_equal(b.threshold, rb.threshold))
        same_value = bool(np.array_equal(b.value, rb.value))
        pr = rb.predict_binned(ds.X_binned[:100_000], raw_score=True)
        pb = b.predict_binned(ds.X_binned[:100_000], raw_score=True)
        same_pred = bool(np.array_equal(pr, np.asarray(pb)))
        print(f"drill: killed at it>={args.trees // 2}, "
              f"{len(resumes)} supervised resume(s), wall {t_drill:.1f}s | "
              f"structures identical: {same_struct} | values identical: "
              f"{same_value} | predict bitwise: {same_pred}", flush=True)
        result["drill"] = {
            "killed_at_iteration": injector.fired[0]["iteration"],
            "supervised_resumes": len(resumes),
            "drill_wall_s": round(t_drill, 1),
            "structures_bitwise": same_struct,
            "values_bitwise": same_value,
            "predict_bitwise": same_pred,
        }
        if not (same_struct and same_value and same_pred):
            print("DRILL FAILED: supervised resume is not bit-identical",
                  flush=True)

    with open(args.out, "w") as f:
        f.write(json.dumps(result, indent=1))
    print(f"wrote {args.out}", flush=True)
    drill_ok = args.no_drill or (result.get("drill", {})
                                 .get("predict_bitwise", False))
    return 0 if drill_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
