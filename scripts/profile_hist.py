"""Microbenchmark: where does a depth-8 boosting iteration spend its time?

Times each device stage of the levelwise grower in isolation on the
Higgs-200k shape (N=200k, F=28, B=256): single-leaf histogram, per-level
segmented histogram (P=128), split scan, argsort, predict traversal.
"""
# dryadlint: disable-file=no-block-until-ready -- r2-era stage probe; per-call walls recorded in BENCH_r01/r02, superseded by the timed-fori doctrine (bench._timed_fori)
# dryadlint: disable-file=jit-closure-constant -- r2-era probe: 200k-shape closures stay well under the ~tens-of-MB HTTP-413 limit; kept verbatim for provenance
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.engine.histogram import build_hist, build_hist_multi, build_hist_segmented
from dryad_tpu.engine.split import find_best_split


def timeit(fn, *args, n=5, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    N, F, B = 200_000, 28, 256
    X, y = higgs_like(N, seed=7)
    ds = dryad.Dataset(X, y, max_bins=B)
    Xb = jnp.asarray(ds.X_binned)
    key = jax.random.PRNGKey(0)
    g = jax.random.normal(key, (N,), jnp.float32)
    h = jnp.abs(g) + 0.1
    mask = jnp.ones((N,), bool)
    sel128 = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, 128).astype(jnp.int32)

    f_single = jax.jit(lambda m: build_hist(Xb, g, h, m, B))
    f_single_fast = jax.jit(lambda m: build_hist(Xb, g, h, m, B, precision="fast"))
    f_seg = jax.jit(lambda s: build_hist_segmented(Xb, g, h, s, 128, B))
    f_seg_fast = jax.jit(lambda s: build_hist_segmented(Xb, g, h, s, 128, B, precision="fast"))
    f_multi = jax.jit(lambda s: build_hist_multi(Xb, g, h, s, 16, B))
    f_sort = jax.jit(lambda s: jnp.argsort(s, stable=True))
    hist = f_single(mask)

    f_split = jax.jit(lambda hh: find_best_split(
        hh, hh[0].sum(), hh[1].sum(), hh[2].sum(),
        lambda_l2=1.0, min_child_weight=1e-3, min_data_in_leaf=20,
        min_split_gain=0.0, feat_mask=jnp.ones((F,), bool),
        is_cat_feat=jnp.zeros((F,), bool), allow=jnp.bool_(True), has_cat=False))

    print(f"devices: {jax.devices()}")
    print(f"single-leaf hist (exact):    {timeit(f_single, mask)*1e3:8.2f} ms")
    print(f"single-leaf hist (fast):     {timeit(f_single_fast, mask)*1e3:8.2f} ms")
    print(f"segmented P=128 (exact):     {timeit(f_seg, sel128)*1e3:8.2f} ms")
    print(f"segmented P=128 (fast):      {timeit(f_seg_fast, sel128)*1e3:8.2f} ms")
    print(f"multi dense P=16 (exact):    {timeit(f_multi, sel128 % 16)*1e3:8.2f} ms")
    print(f"argsort 200k:                {timeit(f_sort, sel128)*1e3:8.2f} ms")
    print(f"split scan (full tree hist): {timeit(f_split, hist)*1e3:8.2f} ms")


if __name__ == "__main__":
    main()
