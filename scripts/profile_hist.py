"""Microbenchmark: where does a depth-8 boosting iteration spend its time?

Times each device stage of the levelwise grower in isolation on the
Higgs-200k shape (N=200k, F=28, B=256): single-leaf histogram, per-level
segmented histogram (P=128), split scan, argsort, predict-shaped sort.

r13: rides the canonical harness (engine/probes.timed_fori — K dependent
iterations in ONE jit, carried whole-unit perturbation, terminal real
fetch, runtime liveness proof), replacing the r2-era per-call walls this
script carried under ``no-block-until-ready`` waivers.  Arrays ride as
jit ARGUMENTS (the HTTP-413 closure rule).

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/profile_hist.py [rows]
"""
from __future__ import annotations

import sys

import jax
import jax.numpy as jnp

import dryad_tpu as dryad
from dryad_tpu.datasets import higgs_like
from dryad_tpu.engine.histogram import (
    build_hist,
    build_hist_multi,
    build_hist_segmented,
)
from dryad_tpu.engine.probes import timed_fori
from dryad_tpu.engine.split import find_best_split


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
    F, B, P = 28, 256, 128
    K, reps = 3, 2
    X, y = higgs_like(N, seed=7)
    ds = dryad.Dataset(X, y, max_bins=B)
    Xb = jnp.asarray(ds.X_binned)
    key = jax.random.PRNGKey(0)
    g = jax.random.normal(key, (N,), jnp.float32)
    h = jnp.abs(g) + 0.1
    mask = jax.random.uniform(jax.random.PRNGKey(2), (N,)) < 0.8
    sel = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, P).astype(
        jnp.int32)
    print(f"devices: {jax.devices()}  rows={N}")

    def show(tag, step, *args):
        ms, spread = timed_fori(step, K, reps, *args, label=tag)
        flag = "  SUSPECT" if spread > 0.05 else ""
        print(f"{tag:28s} {ms:8.2f} ms  spread {spread:.3f}{flag}")

    # single-leaf masked histogram — roll the MASK by the carried scalar
    def single(precision):
        def step(s, Xb, g, h, mask):
            si = s.astype(jnp.int32)
            hist = build_hist(Xb, g, h, jnp.roll(mask, si), B,
                              precision=precision, backend="auto")
            # plane sum: a single bin can be empty in binned Higgs data
            return s + 1.0, hist[0].sum()
        return step

    show("single-leaf hist (exact)", single("exact"), Xb, g, h, mask)
    show("single-leaf hist (fast)", single("fast"), Xb, g, h, mask)

    # segmented P=128 — rotate the SORT KEY (slot ids), selection fixed
    def seg(precision):
        def step(s, Xb, g, h, sel):
            si = s.astype(jnp.int32)
            hist = build_hist_segmented(Xb, g, h, (sel + si) % P, P, B,
                                        precision=precision, backend="auto")
            return s + 1.0, hist[0, 0].sum()
        return step

    show("segmented P=128 (exact)", seg("exact"), Xb, g, h, sel)
    show("segmented P=128 (fast)", seg("fast"), Xb, g, h, sel)

    # dense multi P=16
    def multi_step(s, Xb, g, h, sel):
        si = s.astype(jnp.int32)
        hist = build_hist_multi(Xb, g, h, (sel + si) % 16, 16, B)
        return s + 1.0, hist[0, 0].sum()

    show("multi dense P=16 (exact)", multi_step, Xb, g, h, sel)

    # the stable argsort a legacy level pays — rotated sort key
    def sort_step(s, sel):
        si = s.astype(jnp.int32)
        srt = jnp.argsort((sel + si) % P, stable=True)
        return s + 1.0, srt[0].astype(jnp.float32) + srt[-1].astype(
            jnp.float32)

    show("stable argsort (N,)", sort_step, sel)

    # split scan over the full-tree histogram
    hist0 = build_hist(Xb, g, h, mask, B, backend="auto")
    fmask = jnp.ones((F,), bool)
    iscat = jnp.zeros((F,), bool)

    def split_step(s, hh, fmask, iscat):
        smod = s - jnp.floor(s / 8.0) * 8.0
        hh2 = hh * (1.0 + 0.01 * smod)
        res = find_best_split(
            hh2, hh2[0].sum(), hh2[1].sum(), hh2[2].sum(),
            lambda_l2=1.0, min_child_weight=1e-3, min_data_in_leaf=20,
            min_split_gain=0.0, feat_mask=fmask, is_cat_feat=iscat,
            allow=jnp.bool_(True), has_cat=False)
        return s + 1.0, res.gain

    show("split scan (tree hist)", split_step, hist0, fmask, iscat)


if __name__ == "__main__":
    main()
