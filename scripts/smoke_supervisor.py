"""Supervisor smoke for scripts/ci.sh (runs under JAX_PLATFORMS=cpu).

Two injected faults (one generic device error at a dispatch site, one
fetch-death that must degrade the chunk cap) drive a short supervised run;
the smoke asserts:

* EXACTLY-ONCE resume per fault (2 faults -> 2 resumes -> 3 segments),
* the fetch-death triggered a backoff_chunks event,
* the journal is well-formed (every line parses; run_start first,
  complete last; every fault is followed by exactly one resume),
* the supervised model is bitwise identical to the uninterrupted run.

Prints one JSON summary line on success, exits 1 with a reason otherwise.
"""

import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import dryad_tpu as dryad  # noqa: E402
from dryad_tpu.datasets import higgs_like  # noqa: E402
from dryad_tpu.resilience import (  # noqa: E402
    FaultInjector,
    RetryPolicy,
    RunJournal,
    supervise_train,
)
from dryad_tpu.resilience import faults as F  # noqa: E402

PARAMS = dict(objective="binary", num_trees=12, num_leaves=7, max_bins=32,
              seed=3, min_data_in_leaf=5)


def fail(reason: str) -> int:
    print(f"SUPERVISOR SMOKE FAIL: {reason}", flush=True)
    return 1


def main() -> int:
    X, y = higgs_like(2500, seed=29)
    ds = dryad.Dataset(X, y, max_bins=32)
    reference = dryad.train(PARAMS, ds, backend="tpu")

    injector = FaultInjector([
        (3, F.DEVICE_UNAVAILABLE, "dispatch"),
        (8, F.FETCH_DEATH, "fetch"),
    ])
    with tempfile.TemporaryDirectory() as td:
        journal_path = os.path.join(td, "journal.jsonl")
        booster = supervise_train(
            PARAMS, ds, backend="tpu",
            checkpoint_dir=os.path.join(td, "ck"), checkpoint_every=2,
            journal=journal_path, fault_injector=injector,
            policy=RetryPolicy(backoff_base_s=0.0, ch_max_ladder=(2,)))
        events = RunJournal.read(journal_path)

    if injector.pending:
        return fail(f"{injector.pending} injected fault(s) never fired")
    kinds = [e["event"] for e in events]
    n_fault = kinds.count("fault")
    n_resume = kinds.count("resume")
    n_segment = kinds.count("segment_start")
    if not (n_fault == 2 and n_resume == 2 and n_segment == 3):
        return fail(f"expected 2 faults/2 resumes/3 segments, got "
                    f"{n_fault}/{n_resume}/{n_segment}")
    # exactly-once resume per fault: fault and resume events alternate
    fr = [k for k in kinds if k in ("fault", "resume")]
    if fr != ["fault", "resume", "fault", "resume"]:
        return fail(f"fault/resume stream not exactly-once: {fr}")
    if kinds[0] != "run_start" or kinds[-1] != "complete":
        return fail("journal must open with run_start and end with complete")
    backoffs = [e for e in events if e["event"] == "backoff_chunks"]
    if not (backoffs and backoffs[-1]["ch_max_to"] == 2):
        return fail(f"fetch-death did not degrade the chunk cap to 2: "
                    f"{backoffs}")
    if not (np.array_equal(reference.feature, booster.feature)
            and np.array_equal(reference.threshold, booster.threshold)
            and np.array_equal(reference.value, booster.value)):
        return fail("supervised model is not bitwise equal to the "
                    "uninterrupted run")

    print(json.dumps({
        "supervisor_smoke": "ok",
        "faults": n_fault,
        "resumes": n_resume,
        "ch_max_after_backoff": backoffs[-1]["ch_max_to"],
        "bitwise": True,
        "journal_events": len(events),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
