"""Natural-order multi-slot histogram experiment (levels with <= 16 leaves).

Shallow depthwise levels pay the full tile plan (sort over N) + row gather
for a handful of candidates.  But 16 slots x 8 weight rows = the 128-row
MXU tile exactly: packing per-slot limb rows (slot s rows 8s..8s+6, row
8s+7 carries the slot id itself) lets ONE natural-order pass compute all
slots' histograms — no sort, no gather, and the (n_fb, n_tiles, Fc, T)
bin tiles are a pure function of Xb (buildable once per tree).

Measures the kernel vs the segmented path at 10M rows, P=8, and checks
values against the XLA oracle.
"""
# dryadlint: disable-file=no-block-until-ready -- r3-era one-shot tile materialization outside the timed region; results recorded (STATUS r3)

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dryad_tpu.engine.pallas_hist import (
    _TILE_ROWS, _feature_chunk, _pow2_bins, _split3, _tiles_from_rows,
)

T = _TILE_ROWS
_NSLOTS = 16
_ROWS_PER_SLOT = 8


def _nat_kernel(x_ref, w_ref, o_ref, *, padded_bins):
    i = pl.program_id(1)
    x = x_ref[0, 0].astype(jnp.int32)              # (Fc, T)
    Fc, Tl = x.shape
    Bp = padded_bins
    shift = Fc.bit_length() - 1
    x_rep = pltpu.repeat(x, Bp, axis=0)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (Fc * Bp, Tl), 0) >> shift
    onehot = (x_rep == iota_b).astype(jnp.bfloat16)

    limbs = w_ref[0]                               # (8, T): 7 limbs + sel row
    sel = limbs[7:8, :].astype(jnp.int32)          # (1, T) slot per row
    w = pltpu.repeat(limbs, _NSLOTS, axis=0)       # (128, T), row r = limbs[r%8]
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (_NSLOTS * 8, Tl), 0)
    slot_of_row = row_iota >> 3
    keep = (slot_of_row == sel) & ((row_iota & 7) != 7)
    w = jnp.where(keep, w, jnp.bfloat16(0))
    part = jax.lax.dot_general(
        w, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)        # (128, Fc*Bp)

    @pl.when(i == 0)
    def _():
        o_ref[0] = part

    @pl.when(i != 0)
    def _():
        o_ref[0] = o_ref[0] + part


@functools.partial(jax.jit, static_argnames=("total_bins", "num_features"))
def hist_nat16(Xt, g, h, sel, *, total_bins, num_features):
    """(16, 3, F, B) from natural-order tiles; sel (N,) in [0, 16]=drop."""
    B = int(total_bins)
    F = int(num_features)
    Bp = _pow2_bins(B)
    n_fb, n_tiles, Fc, Tl = Xt.shape
    N = g.shape[0]
    pad = n_tiles * Tl - N
    gp = jnp.pad(g.astype(jnp.float32), (0, pad))
    hp = jnp.pad(h.astype(jnp.float32), (0, pad))
    sp = jnp.pad(sel.astype(jnp.int32), (0, pad), constant_values=31)
    valid = (sp < _NSLOTS).astype(jnp.float32)
    gv = (gp * valid).reshape(n_tiles, Tl)
    hv = (hp * valid).reshape(n_tiles, Tl)
    cnt = valid.astype(jnp.bfloat16).reshape(n_tiles, Tl)
    selr = jnp.minimum(sp, 31).astype(jnp.bfloat16).reshape(n_tiles, Tl)
    W = jnp.stack([*_split3(gv), *_split3(hv), cnt, selr], axis=-2)  # (nt,8,T)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(n_fb, n_tiles),
        in_specs=[
            pl.BlockSpec((1, 1, Fc, Tl), lambda j, i: (j, i, 0, 0)),
            pl.BlockSpec((1, 8, Tl), lambda j, i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _NSLOTS * 8, Fc * Bp), lambda j, i: (j, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_nat_kernel, padded_bins=Bp),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_fb, _NSLOTS * 8, Fc * Bp),
                                       jnp.float32),
        interpret=jax.default_backend() == "cpu",
    )(Xt, W)
    # untangle: (n_fb, 128, Fc*Bp) -> (16, 8, F, B)
    out = (out.reshape(n_fb, _NSLOTS, 8, Bp, Fc)
              .transpose(1, 2, 0, 4, 3)
              .reshape(_NSLOTS, 8, n_fb * Fc, Bp))[:, :, :F, :B]
    hg = out[:, 0] + out[:, 1] + out[:, 2]
    hh = out[:, 3] + out[:, 4] + out[:, 5]
    hc = out[:, 6]
    return jnp.stack([hg, hh, hc], axis=1)         # (16, 3, F, B)


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    P = 8
    K = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    F, B = 28, 256
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} P={P} device={jax.devices()[0]}")

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    sel_np = rng.integers(0, 2 * P, size=N).astype(np.int32)
    sel_np = np.where(sel_np < P, sel_np, 31)
    sel = jnp.asarray(sel_np)

    pad = (-N) % T
    n_tiles = (N + pad) // T
    Xt = jax.block_until_ready(jax.jit(
        lambda X: _tiles_from_rows(jnp.pad(X, ((0, pad), (0, 0))),
                                   n_tiles, T, B))(Xb))

    # correctness vs XLA segmented oracle
    from dryad_tpu.engine.histogram import build_hist_segmented

    want = np.asarray(jax.jit(
        lambda X, gg, hh, ss: build_hist_segmented(
            X, gg, hh, jnp.where(ss < 16, ss, 16), 16, B, backend="xla"))(
        Xb, g, h, sel))
    got = np.asarray(hist_nat16(Xt, g, h, sel, total_bins=B,
                                num_features=F))
    np.testing.assert_array_equal(got[:, 2], want[:, 2])   # counts exact
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-5)
    print("nat16 matches XLA oracle (counts exact)")

    def loop_time(tag, step, *arrays):
        f = jax.jit(lambda s0, *a: jax.lax.fori_loop(
            0, K, lambda i, s: step(s, *a), s0))
        _ = float(f(jnp.float32(0.0), *arrays))
        t0 = time.perf_counter()
        _ = float(f(jnp.float32(0.0), *arrays))
        print(f"{tag:42s} {(time.perf_counter()-t0)/K*1e3:9.1f} ms")

    j32 = lambda s: (s * 1e-30).astype(jnp.int32)
    loop_time("nat16 (no sort, no gather)", lambda s, xt, gg, hh, ss:
              hist_nat16(xt, gg, hh, ss + j32(s), total_bins=B,
                         num_features=F)[0, 0, 0, 0] * 1e-30, Xt, g, h, sel)
    loop_time("segmented pallas P=8 (plan+gather)", lambda s, X, gg, hh, ss:
              build_hist_segmented(
                  X, gg, hh, jnp.minimum(ss + j32(s), 8), 8, B,
                  backend="pallas", rows_bound=N // 2 + 1,
                  platform=plat)[0, 0, 0, 0] * 1e-30, Xb, g, h, sel)


if __name__ == "__main__":
    main()
