"""Per-level cost breakdown of the depthwise grower at scale.

Times the pieces a LEGACY deep level pays (segmented histogram + its
tile-plan sort, row partition gathers, vmapped split finder, the hists
scatter) to locate the non-kernel tail.  r13: every stage rides the
canonical harness (engine/probes.timed_fori), which liveness-proves each
perturbation at runtime — the old hand-rolled loop here consumed its
scalar through ``(s * 1e-30).astype(int32)`` in two stages, i.e. a DEAD
input the harness now rejects (exactly the 2x-too-fast class CLAUDE.md
records for r5/r10).  Arrays ride as jit ARGUMENTS.

Usage: PYTHONPATH=... python scripts/profile_level.py [rows] [P] [reps]
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.config import make_params
from dryad_tpu.engine.histogram import build_hist_segmented
from dryad_tpu.engine.probes import timed_fori
from dryad_tpu.engine.split import find_best_split


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    K = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    F, B, L = 28, 256, 255
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} P={P} reps={K} device={jax.devices()[0]}")

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    row_slot = jnp.asarray(rng.integers(0, L, size=N).astype(np.int32))
    sel_np = rng.integers(0, 2 * P, size=N).astype(np.int32)
    sel_np = np.where(sel_np < P, sel_np, P)  # ~half the rows selected
    # rows_bound must be MATHEMATICALLY guaranteed (tile_plan contract —
    # rows beyond it drop silently): a binomial ~N/2 draw exceeds N//2+1
    # about half the time, so the bound is the EXACT draw count (the
    # rotation perturbation never changes the selected SET)
    bound = int((sel_np < P).sum())
    sel = jnp.asarray(sel_np)
    p = make_params(dict(objective="binary", num_leaves=L, max_depth=8,
                         growth="depthwise"))

    def show(tag, step, *args):
        ms, spread = timed_fori(step, K, 2, *args, label=tag)
        flag = "  SUSPECT" if spread > 0.05 else ""
        print(f"{tag:28s} {ms:9.1f} ms  spread {spread:.3f}{flag}")

    # segmented histogram (the per-level kernel call, incl. its tile plan)
    # — perturb the SORT KEY (rotate slot ids; the selected set is fixed)
    def seg_step(s, Xb, g, h, sel):
        si = s.astype(jnp.int32)
        sel2 = jnp.where(sel < P, (sel + si) % P, P)
        hist = build_hist_segmented(Xb, g, h, sel2, P, B,
                                    rows_per_chunk=p.rows_per_chunk,
                                    platform=plat, rows_bound=bound)
        # slot-0 plane sum (bins here start at 1 — a bin-0 contrib is
        # constant zero and the harness rejects it as dead)
        return s + 1.0, hist[0, 0].sum()

    show(f"seg hist P={P} (exact bound)", seg_step, Xb, g, h, sel)

    # the tile-plan's stable sort alone — rotated sort key (the old
    # (s*1e-30).astype(int32) perturbation was dead; harness-rejected now)
    def sort_step(s, sel):
        si = s.astype(jnp.int32)
        srt = jnp.argsort(jnp.where(sel < P, (sel + si) % P, P),
                          stable=True)
        return s + 1.0, (srt[0] + srt[N // 2]).astype(jnp.float32)

    show("stable argsort (N,)", sort_step, sel)

    # row partition gathers (one level's worth) — the gather COLUMN
    # rotates with the carried scalar, so the gather stays in the loop
    def part_step(s, Xb, rs):
        si = s.astype(jnp.int32)
        rf = (jnp.maximum(rs % F, 0) + si) % F
        bins_rf = jnp.take_along_axis(
            Xb, rf[:, None].astype(jnp.int32), axis=1)[:, 0].astype(
            jnp.int32)
        go_left = bins_rf <= rs
        new_slot = jnp.where(go_left, rs, rs + 1)
        # full-N sum: two sampled rows can both be column-insensitive
        # (bins <= slot for every feature) and read as dead
        return s + 1.0, jnp.sum(new_slot.astype(jnp.float32))

    show("partition gathers", part_step, Xb, row_slot)

    # vmapped split finder over 2P children — gains are scale-sensitive
    hists = jnp.asarray(
        np.stack([rng.normal(size=(2 * P, F, B)),
                  rng.uniform(0.1, 1.0, size=(2 * P, F, B)),
                  rng.uniform(0.5, 2.0, size=(2 * P, F, B))],
                 axis=1).astype(np.float32))
    fmask = jnp.ones((F,), bool)
    iscat = jnp.zeros((F,), bool)
    allow = jnp.ones((2 * P,), bool)

    def split_step(s, hh, fmask, iscat, allow):
        smod = s - jnp.floor(s / 8.0) * 8.0
        hh2 = hh * (1.0 + 0.01 * smod)
        G = hh2[:, 0].sum(axis=(1, 2))
        H = hh2[:, 1].sum(axis=(1, 2))
        C = hh2[:, 2].sum(axis=(1, 2))

        def best(hh_, G_, H_, C_, a_):
            return find_best_split(
                hh_, G_, H_, C_, lambda_l2=1.0, min_child_weight=1e-3,
                min_data_in_leaf=20, min_split_gain=0.0, feat_mask=fmask,
                is_cat_feat=iscat, allow=a_, has_cat=False)

        res = jax.vmap(best)(hh2, G, H, C, allow)
        return s + 1.0, res.gain[0] + res.gain[-1]

    show("vmap split finder 2P", split_step, hists, fmask, iscat, allow)

    # hists scatter update (two (L,3,F,B) .at[].set per level)
    big = jnp.zeros((L, 3, F, B), jnp.float32)
    idx = jnp.arange(P, dtype=jnp.int32)

    def scat_step(s, bg, hh, idx):
        bg = bg.at[idx].set(hh[:P] + s)
        bg = bg.at[idx + P].set(hh[P:])
        return s + 1.0, bg[0, 0, 0, 0]

    show("hists scatter 2x(L,...)", scat_step, big, hists, idx)


if __name__ == "__main__":
    main()
