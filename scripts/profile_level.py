"""Per-level cost breakdown of the depthwise grower at scale.

Times the pieces a deep level pays (segmented histogram + its tile-plan
sort, row partition gathers, vmapped split finder) with the fori-loop
methodology, to locate the non-kernel tail (CLAUDE.md open item).

Usage: PYTHONPATH=... python scripts/profile_level.py [rows] [P]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.config import make_params
from dryad_tpu.engine.histogram import build_hist_segmented
from dryad_tpu.engine.split import find_best_split


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    K = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    F, B, L = 28, 256, 255
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} P={P} reps={K} device={jax.devices()[0]}")

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    row_slot = jnp.asarray(rng.integers(0, L, size=N).astype(np.int32))
    sel = jnp.asarray(rng.integers(0, 2 * P, size=N).astype(np.int32))
    sel = jnp.where(sel < P, sel, P)  # half the rows selected
    p = make_params(dict(objective="binary", num_leaves=L, max_depth=8,
                         growth="depthwise"))

    def loop_time(step, *arrays):
        f = jax.jit(lambda s0, *a: jax.lax.fori_loop(
            0, K, lambda i, s: step(s, *a), s0))
        _ = float(f(jnp.float32(0.0), *arrays))
        t0 = time.perf_counter()
        _ = float(f(jnp.float32(0.0), *arrays))
        return (time.perf_counter() - t0) / K

    # segmented histogram (the per-level kernel call, incl. its tile plan)
    t = loop_time(lambda s, X, gg, hh, ss: build_hist_segmented(
        X, gg + s, hh, ss, P, B, rows_per_chunk=p.rows_per_chunk,
        platform=plat, rows_bound=N // 2 + 1)[0, 0, 0, 0] * 1e-30,
        Xb, g, h, sel)
    print(f"seg hist P={P} (bound N/2): {t*1e3:9.1f} ms")

    # the tile-plan's stable sort alone
    t = loop_time(lambda s, ss: jnp.argsort(
        ss + (s * 1e-30).astype(jnp.int32), stable=True)[0].astype(jnp.float32)
        * 1e-30, sel)
    print(f"stable argsort (N,):       {t*1e3:9.1f} ms")

    # row partition gathers (one level's worth)
    def part(s, X, rs):
        rf = jnp.maximum(rs % F, 0)
        bins_rf = jnp.take_along_axis(
            X, rf[:, None].astype(jnp.int32), axis=1)[:, 0].astype(jnp.int32)
        go_left = bins_rf <= (rs + s.astype(jnp.int32))
        new_slot = jnp.where(go_left, rs, rs + 1)
        return new_slot[0].astype(jnp.float32) * 1e-30
    t = loop_time(part, Xb, row_slot)
    print(f"partition gathers:         {t*1e3:9.1f} ms")

    # vmapped split finder over 2P children
    hists = jnp.asarray(rng.normal(size=(2 * P, 3, F, B)).astype(np.float32))
    fmask = jnp.ones((F,), bool)
    iscat = jnp.zeros((F,), bool)

    def best(hist, G, H, C, allow):
        return find_best_split(
            hist, G, H, C, lambda_l2=1.0, min_child_weight=1e-3,
            min_data_in_leaf=20, min_split_gain=0.0, feat_mask=fmask,
            is_cat_feat=iscat, allow=allow, has_cat=False)
    GHC = jnp.abs(hists[:, :3, :, :].sum(axis=(2, 3)))
    allow = jnp.ones((2 * P,), bool)

    def split_step(s, hh):
        res = jax.vmap(best, in_axes=(0, 0, 0, 0, 0))(
            hh + s, GHC[:, 0], GHC[:, 1], GHC[:, 2], allow)
        return res.gain[0] * 1e-30
    t = loop_time(split_step, hists)
    print(f"vmap split finder 2P:      {t*1e3:9.1f} ms")

    # hists scatter update (two (L,3,F,B) .at[].set per level)
    big = jnp.zeros((L, 3, F, B), jnp.float32)
    idx = jnp.arange(P, dtype=jnp.int32)

    def scat(s, bg, hh):
        bg = bg.at[idx].set(hh[:P] + s)
        bg = bg.at[idx + P].set(hh[P:])
        return bg[0, 0, 0, 0] * 1e-30
    t = loop_time(scat, big, hists)
    print(f"hists scatter 2x(L,...):   {t*1e3:9.1f} ms")


if __name__ == "__main__":
    main()
