"""Experimental v2 segmented-histogram pipeline (HISTORICAL: integrated into
engine/pallas_hist.py — kept as the measurement record; the integrated
copies are canonical and this script is not maintained against them).

Changes vs engine/pallas_hist.py, each separately toggleable:
  1. tile_plan: packed uint32 single-key sort (slot<<24 | row) replacing
     argsort + sel[order]; plan construction reads slot and row id from the
     same sorted word.
  2. One per-level gather of (9,) int32 RECORDS [g, h, X as 7 words] from a
     per-TREE record table, replacing separate X row + g/h gathers and the
     per-level sentinel concatenates.
  3. uint8 tile buffers with in-kernel cast (4x less tile HBM traffic).
  4. Weight rows packed (n_tiles, 8, T) instead of padded to 128; the
     kernel pads to the MXU tile in VMEM.

Prints times and bitwise-compares against the current pipeline.
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dryad_tpu.engine.pallas_hist import (
    _MXU_M, _TILE_ROWS, _WROWS, _feature_chunk, _hist_tiles, _pack_weights,
    _pow2_bins, _split3, _tiles_from_rows, tile_plan,
)

T = _TILE_ROWS


# ---------------------------------------------------------------------------
# 1. packed-sort tile plan
# ---------------------------------------------------------------------------
def tile_plan_v2(sel, N, P, T, rows_bound=None):
    """Same plan as tile_plan, via ONE uint32 sort of (slot<<24 | row_id).

    Valid when N <= 2^24 and P < 256.  Returns (buf, tile_leaf, tile_first)
    with identical values to tile_plan (stable grouping by construction:
    row id in the low bits makes keys strictly increasing within a slot).
    """
    bound = N if rows_bound is None else min(int(rows_bound), N)
    n_tiles = bound // T + P + 1
    key = (sel.astype(jnp.uint32) << jnp.uint32(24)) | jnp.arange(
        N, dtype=jnp.uint32)
    srt = jnp.sort(key)
    sel_sorted = (srt >> jnp.uint32(24)).astype(jnp.int32)
    start = jnp.searchsorted(sel_sorted, jnp.arange(P + 1, dtype=jnp.int32),
                             side="left").astype(jnp.int32)
    counts = start[1:] - start[:-1]
    leaf_tiles = jnp.maximum((counts + (T - 1)) // T, 1)
    seg_base = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(leaf_tiles).astype(jnp.int32)])
    seg_base = jnp.minimum(
        seg_base, jnp.int32(n_tiles) - (P - jnp.arange(P + 1, dtype=jnp.int32)))
    cap_rows = (seg_base[1:] - seg_base[:-1]) * T

    tile_leaf = jnp.searchsorted(seg_base[1:],
                                 jnp.arange(n_tiles, dtype=jnp.int32),
                                 side="right").astype(jnp.int32)
    tile_idx = jnp.arange(n_tiles, dtype=jnp.int32)
    lc = jnp.minimum(tile_leaf, P - 1)
    base_t = tile_idx * T - seg_base[lc] * T
    cnt_t = jnp.minimum(counts[lc], cap_rows[lc])
    start_t = start[lc]
    j = jnp.arange(T, dtype=jnp.int32)
    off = base_t[:, None] + j[None, :]
    ok = (tile_leaf < P)[:, None] & (off >= 0) & (off < cnt_t[:, None])
    src = start_t[:, None] + off
    row_sorted = (srt & jnp.uint32(0xFFFFFF)).astype(jnp.int32)
    buf = jnp.where(ok, row_sorted[jnp.clip(src, 0, N - 1)], N).reshape(-1)
    tile_leaf = jnp.minimum(tile_leaf, P - 1)
    tile_first = jnp.concatenate([
        jnp.ones((1,), jnp.int32),
        (tile_leaf[1:] != tile_leaf[:-1]).astype(jnp.int32),
    ])
    return buf, tile_leaf, tile_first


# ---------------------------------------------------------------------------
# 2+3+4. record-gather pipeline + u8 kernel with in-kernel weight pad
# ---------------------------------------------------------------------------
def make_records(Xb, g, h):
    """Per-TREE (N, 2 + ceil(F/4)) int32 record table: [g, h, X words]."""
    N, F = Xb.shape
    fw = -(-F // 4)
    Xw = jnp.pad(Xb, ((0, 0), (0, fw * 4 - F)))
    Xw = jax.lax.bitcast_convert_type(Xw.reshape(N, fw, 4),
                                      jnp.int32).reshape(N, fw)
    gw = jax.lax.bitcast_convert_type(g.astype(jnp.float32), jnp.int32)
    hw = jax.lax.bitcast_convert_type(h.astype(jnp.float32), jnp.int32)
    return jnp.concatenate([gw[:, None], hw[:, None], Xw], axis=1)


def _hist_kernel_v2(tile_leaf_ref, tile_first_ref, x_ref, w_ref, o_ref, *,
                    padded_bins: int):
    i = pl.program_id(1)
    x = x_ref[0, 0].astype(jnp.int32)              # (Fc, T) u8 -> i32
    Fc, Tl = x.shape
    Bp = padded_bins
    shift = Fc.bit_length() - 1
    x_rep = pltpu.repeat(x, Bp, axis=0)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (Fc * Bp, Tl), 0) >> shift
    onehot = (x_rep == iota_b).astype(jnp.bfloat16)
    w = jnp.concatenate(
        [w_ref[0], jnp.zeros((_MXU_M - _WROWS, Tl), jnp.bfloat16)], axis=0)
    part = jax.lax.dot_general(
        w, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:_WROWS]

    @pl.when(tile_first_ref[i] == 1)
    def _():
        o_ref[0] = part

    @pl.when(tile_first_ref[i] == 0)
    def _():
        o_ref[0] = o_ref[0] + part


@functools.partial(jax.jit, static_argnames=("num_cols", "total_bins",
                                             "num_features", "wpad"))
def _hist_tiles_v2(Xt, Wt, tile_leaf, tile_first, *, num_cols, total_bins,
                   num_features, wpad=False):
    n_fb, n_tiles, Fc, Tl = Xt.shape
    B = int(total_bins)
    P = int(num_cols)
    F = int(num_features)
    Bp = _pow2_bins(B)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_fb, n_tiles),
        in_specs=[
            pl.BlockSpec((1, 1, Fc, Tl), lambda j, i, tl, tf: (j, i, 0, 0)),
            pl.BlockSpec((1, _WROWS, Tl), lambda j, i, tl, tf: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _WROWS, Fc * Bp),
                               lambda j, i, tl, tf: (tl[i], 0, j)),
    )
    out = pl.pallas_call(
        functools.partial(_hist_kernel_v2, padded_bins=Bp),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((P, _WROWS, n_fb * Fc * Bp),
                                       jnp.float32),
    )(tile_leaf, tile_first, Xt, Wt)

    out = (out.reshape(P, _WROWS, n_fb, Bp, Fc)
              .transpose(0, 1, 2, 4, 3)
              .reshape(P, _WROWS, n_fb * Fc, Bp))[:, :, :F, :B]
    hg = out[:, 0] + out[:, 1] + out[:, 2]
    hh = out[:, 3] + out[:, 4] + out[:, 5]
    hc = out[:, 6]
    return jnp.stack([hg, hh, hc], axis=1)


def hist_v2(records, sel, N, F, P, B, rows_bound):
    """Whole v2 per-level pipeline from the per-tree record table."""
    buf, tile_leaf, tile_first = tile_plan_v2(sel, N, P, T,
                                              rows_bound=rows_bound)
    n_tiles = buf.shape[0] // T
    safe = jnp.minimum(buf, N - 1)
    rec = records[safe]                            # ONE gather (n_rows, 2+fw)
    valid = (buf < N).reshape(n_tiles, T)
    gh = jax.lax.bitcast_convert_type(rec[:, :2], jnp.float32)
    gt = jnp.where(valid.reshape(-1), gh[:, 0], 0.0).reshape(n_tiles, T)
    ht = jnp.where(valid.reshape(-1), gh[:, 1], 0.0).reshape(n_tiles, T)
    fw = rec.shape[1] - 2
    Xr = jax.lax.bitcast_convert_type(rec[:, 2:], jnp.uint8).reshape(
        n_tiles * T, fw * 4)[:, :F]
    # u8 feature-chunked tiles (no int32 cast — the kernel converts)
    Fc = _feature_chunk(F, _pow2_bins(B))
    fpad = (-F) % Fc
    if fpad:
        Xr = jnp.pad(Xr, ((0, 0), (0, fpad)))
    n_fb = (F + fpad) // Fc
    Xt = Xr.reshape(n_tiles, T, n_fb, Fc).transpose(2, 0, 3, 1)
    # 8-row weight pack (no 128 pad)
    v = valid.astype(jnp.float32)
    gv = gt * v
    hv = ht * v
    Wt = jnp.stack([*_split3(gv), *_split3(hv), v.astype(jnp.bfloat16)],
                   axis=-2)
    Wt = jnp.pad(Wt, ((0, 0), (0, _WROWS - 7), (0, 0)))
    return _hist_tiles_v2(Xt, Wt, tile_leaf, tile_first, num_cols=P,
                          total_bins=B, num_features=F)


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    K = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    F, B = 28, 256
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} P={P} reps={K} device={jax.devices()[0]}")
    bound = N // 2 + 1

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    sel_np = rng.integers(0, 2 * P, size=N).astype(np.int32)
    sel_np = np.where(sel_np < P, sel_np, P)
    sel = jnp.asarray(sel_np)

    def loop_time(tag, step, *arrays):
        f = jax.jit(lambda s0, *a: jax.lax.fori_loop(
            0, K, lambda i, s: step(s, *a), s0))
        _ = float(f(jnp.float32(0.0), *arrays))
        t0 = time.perf_counter()
        _ = float(f(jnp.float32(0.0), *arrays))
        dt = (time.perf_counter() - t0) / K
        print(f"{tag:42s} {dt*1e3:9.1f} ms")
        return dt

    j32 = lambda s: (s * 1e-30).astype(jnp.int32)

    # correctness: v2 plan == v1 plan
    b1, tl1, tf1 = jax.jit(lambda s: tile_plan(s, N, P, T, rows_bound=bound))(sel)
    b2, tl2, tf2 = jax.jit(lambda s: tile_plan_v2(s, N, P, T, rows_bound=bound))(sel)
    print("plan buf equal:", bool((b1 == b2).all()),
          " tl equal:", bool((tl1 == tl2).all()),
          " tf equal:", bool((tf1 == tf2).all()))

    # correctness: v2 hist vs current segmented pallas path
    from dryad_tpu.engine.pallas_hist import build_hist_segmented_pallas

    hist1 = jax.jit(lambda X, gg, hh, ss: build_hist_segmented_pallas(
        X, gg, hh, ss, P, B, rows_bound=bound, platform=plat))(Xb, g, h, sel)
    records = jax.jit(make_records)(Xb, g, h)
    hist2 = jax.jit(lambda r, ss: hist_v2(r, ss, N, F, P, B, bound))(records, sel)
    hist1, hist2 = np.asarray(hist1), np.asarray(hist2)
    print("hist bitwise equal:", bool((hist1 == hist2).all()),
          " max abs diff:", float(np.abs(hist1 - hist2).max()))

    loop_time("tile_plan v1", lambda s, ss: tile_plan(
        ss + j32(s), N, P, T, rows_bound=bound)[0][0].astype(jnp.float32)
        * 1e-30, sel)
    loop_time("tile_plan v2 (packed sort)", lambda s, ss: tile_plan_v2(
        ss + j32(s), N, P, T, rows_bound=bound)[0][0].astype(jnp.float32)
        * 1e-30, sel)

    # perturb SEL in both arms: it feeds the plan sort and every gather,
    # so no stage is loop-invariant (the kernel weight path in v2 reads g/h
    # from the records table; perturbing gg there would be dead — CLAUDE.md
    # methodology requires a true dependency in each trip)
    loop_time("v1 whole (current)", lambda s, X, gg, hh, ss:
              build_hist_segmented_pallas(
                  X, gg, hh, ss + j32(s), P, B, rows_bound=bound,
                  platform=plat)[0, 0, 0, 0] * 1e-30, Xb, g, h, sel)
    loop_time("v2 whole (records+u8+packed)", lambda s, r, ss:
              hist_v2(r, ss + j32(s), N, F, P, B,
                      bound)[0, 0, 0, 0] * 1e-30, records, sel)
    loop_time("make_records (per tree, /8 levels)", lambda s, X, gg, hh:
              make_records(X, gg + s, hh)[0, 0].astype(jnp.float32) * 1e-30,
              Xb, g, h)


if __name__ == "__main__":
    main()
