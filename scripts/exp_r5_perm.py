"""Measure the leaf-ordered permutation kernel against the per-level
sort + record-gather pair it is designed to replace (VERDICT r4 #2).

Configuration mirrors the 10M depth-8 worst case: N rows across P
segments, a random split per segment.  CLAUDE.md methodology: K dependent
reps inside ONE jit, the perturbation reaching the moved data (the side
bits derive from a loop-carried scalar), device-resident inputs.

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/exp_r5_perm.py [N] [P]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.engine import leafperm
from dryad_tpu.engine import pallas_hist

T = leafperm._TILE_ROWS


def loop_time(fn, *a_, K=5):
    def prog(s0, *a):
        return jax.lax.fori_loop(0, K, lambda i, s: fn(s, *a), s0)

    f = jax.jit(prog)
    # REAL fetches — block_until_ready is a no-op through this tunnel
    # (CLAUDE.md measuring notes, r5)
    float(f(jnp.float32(0), *a_))
    t0 = time.perf_counter()
    float(f(jnp.float32(1), *a_))
    return (time.perf_counter() - t0) / K * 1000


def device_correctness_check():
    """Small-N bitwise check vs the numpy oracle ON THE REAL DEVICE —
    interpret mode zero-fills uninitialized buffers and cannot catch
    hardware-layout bugs (the zero-alias finding), so the measurement run
    opens with this."""
    rng = np.random.default_rng(11)
    seg_counts = [700, 3, 1200, 0, 513]
    lt = np.maximum(-(-np.asarray(seg_counts) // T), 1)
    n_tiles = int(lt.sum())
    rec = np.zeros((n_tiles * T, 128), np.uint8)
    tile_slot = np.repeat(np.arange(len(seg_counts)), lt).astype(np.int32)
    row_seg = np.full(n_tiles * T, -1, np.int32)
    base = np.concatenate([[0], np.cumsum(lt)])
    for s, cnt in enumerate(seg_counts):
        r0 = base[s] * T
        rec[r0: r0 + cnt] = rng.integers(1, 255, (cnt, 128), dtype=np.uint8)
        row_seg[r0: r0 + cnt] = s
    side = np.where(row_seg >= 0,
                    (rng.random(row_seg.size) < 0.5).astype(np.int32),
                    2).astype(np.int32)
    pos, dstl, dstr, _, _, n_out = leafperm.level_moves(
        jnp.asarray(tile_slot), jnp.asarray(side), len(seg_counts))
    bound = leafperm.tiles_bound(rec.shape[0], len(seg_counts))
    got = np.asarray(leafperm.permute_records(
        jnp.asarray(rec), pos, dstl, dstr, bound))
    want, _, _ = leafperm.permute_records_np(rec, tile_slot, side,
                                             len(seg_counts), bound)
    np.testing.assert_array_equal(got[: int(n_out) * T],
                                  want[: int(n_out) * T])
    print("on-device bitwise vs oracle: OK", flush=True)


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    WB = 128
    rng = np.random.default_rng(0)
    print(f"device={jax.devices()[0]} N={N} P={P} WB={WB}", flush=True)
    device_correctness_check()

    # tile-aligned layout with P roughly-equal segments
    cnt = np.full(P, N // P, np.int32)
    cnt[: N % P] += 1
    lt = np.maximum(-(-cnt // T), 1)
    n_tiles = int(lt.sum())
    tile_slot = np.repeat(np.arange(P), lt).astype(np.int32)
    base = np.concatenate([[0], np.cumsum(lt)])
    row_seg = np.full(n_tiles * T, -1, np.int32)
    for s in range(P):
        row_seg[base[s] * T: base[s] * T + cnt[s]] = s
    rec = rng.integers(0, 255, (n_tiles * T, WB), dtype=np.uint8)
    rec[row_seg < 0] = 0
    rec_d = jnp.asarray(rec)
    tile_slot_d = jnp.asarray(tile_slot)
    row_seg_d = jnp.asarray(row_seg)
    u = jnp.asarray(rng.random(n_tiles * T).astype(np.float32))
    bound = leafperm.tiles_bound(rec.shape[0], P)

    # ---- permutation kernel: bookkeeping + move ---------------------------
    def perm_step(s, rec_d, tile_slot_d, row_seg_d, u):
        # perturbed split: the side bits change with s, reaching every stage
        # s advances by whole units per rep (dead-input trap note
        # in CLAUDE.md): thr alternates between reps
        thr = 0.45 + 0.05 * (s - jnp.floor(s / 2) * 2)
        side = jnp.where(row_seg_d >= 0,
                         (u < thr).astype(jnp.int32), 2)
        pos, dstl, dstr, _, _, _ = leafperm.level_moves(
            tile_slot_d, side, P)
        out = leafperm.permute_records(rec_d, pos, dstl, dstr, bound)
        return s + 1.0 + out[0, 0].astype(jnp.float32) * 1e-20

    t_perm = loop_time(perm_step, rec_d, tile_slot_d, row_seg_d, u, K=3)
    print(f"leafperm (bookkeeping + move, full N): {t_perm:8.1f} ms/level",
          flush=True)

    # ---- current pipeline: packed sort + record gather --------------------
    sel_np = rng.integers(0, P, N).astype(np.int32)
    sel_d = jnp.asarray(sel_np)
    records = jnp.asarray(
        rng.integers(-2**31, 2**31 - 1, (N, 9), dtype=np.int64)
        .astype(np.int32))

    def sort_step(s, sel_d):
        selp = (sel_d + s.astype(jnp.int32)) % P      # perturb the SORT KEY
        key = ((selp.astype(jnp.uint32) << jnp.uint32(24))
               | jnp.arange(N, dtype=jnp.uint32))
        srt = jnp.sort(key)
        return s + 1.0 + srt[0].astype(jnp.float32) * 1e-20

    t_sort = loop_time(sort_step, sel_d, K=3)

    half = N // 2
    # a RANDOM permutation prefix — the real plan gathers rows scattered
    # across the whole table (an earlier draft used slot ids as indices,
    # touching only P distinct rows: a degenerate tiny-working-set gather
    # that under-measured the baseline ~10x; caught in review)
    perm_idx = jnp.asarray(rng.permutation(N)[:half].astype(np.int32))

    def gather_step(s, records, perm_idx):
        idx = (perm_idx + s.astype(jnp.int32)) % N    # perturb the INDEX
        r = records[idx]
        return s + 1.0 + r[0, 0].astype(jnp.float32) * 1e-20

    t_gath = loop_time(gather_step, records, perm_idx, K=3)
    print(f"current  packed sort(full N) {t_sort:8.1f} ms   "
          f"record gather(N/2) {t_gath:8.1f} ms   "
          f"sum {t_sort + t_gath:8.1f} ms", flush=True)
    print(f"projected saving: {t_sort + t_gath - t_perm:8.1f} ms/level",
          flush=True)


if __name__ == "__main__":
    main()
