"""Closed-loop serving benchmark CLI (engine: dryad_tpu/serve/bench.py).

    PYTHONPATH=/root/.axon_site:/root/repo python scripts/bench_serve.py \
        [--model m.dryad] [--backend auto|tpu|cpu] [--clients 8] \
        [--duration 5] [--max-batch-rows 256] [--max-wait-ms 1.0] \
        [--sizes 1,3,9,17,40] [--json report.json]

Without --model it trains a small throwaway booster first.  Acceptance
gate: a forced-CPU run must report ``recompiles_after_warmup: 0`` — the
shape-bucketed cache makes warm traffic structurally recompile-free
(bench.py warms every reachable bucket before measuring).
"""

from __future__ import annotations

import argparse
import json
import sys


def _train_throwaway(n_rows: int = 4000):
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(n_rows, seed=11)
    ds = dryad.Dataset(X, y, max_bins=64)
    return dryad.train(dict(objective="binary", num_trees=50, num_leaves=31,
                            max_bins=64), ds, backend="cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_serve")
    ap.add_argument("--model", help="model path; trains a throwaway if absent")
    ap.add_argument("--backend", default="cpu",
                    choices=["auto", "tpu", "cpu"])
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--max-batch-rows", type=int, default=256)
    ap.add_argument("--max-wait-ms", type=float, default=1.0)
    ap.add_argument("--sizes", default="1,3,9,17,40",
                    help="comma-separated request row sizes")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", help="also write the report here")
    args = ap.parse_args(argv)

    from dryad_tpu.serve.bench import run_bench

    model = args.model if args.model else _train_throwaway()
    report = run_bench(
        model, backend=args.backend, clients=args.clients,
        duration_s=args.duration,
        sizes=[int(s) for s in args.sizes.split(",")],
        max_batch_rows=args.max_batch_rows, max_wait_ms=args.max_wait_ms,
        seed=args.seed, verbose=True)
    print(json.dumps(report, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    if report["recompiles_after_warmup"] != 0:
        print("WARNING: cache recompiled after warmup", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
