"""Closed-loop serving benchmark CLI (engine: dryad_tpu/serve/bench.py).

    PYTHONPATH=/root/.axon_site:/root/repo python scripts/bench_serve.py \
        [--model m.dryad] [--backend auto|tpu|cpu] [--clients 8] \
        [--duration 5] [--arms 2] [--max-batch-rows 256] [--max-wait-ms 1.0] \
        [--sizes 1,3,9,17,40] [--pipeline-depth 2] [--compare] [--sharded] \
        [--smoke] [--json report.json]

Without --model it trains a small throwaway booster first.  The last
stdout line is ONE flat JSON summary (bench.py's format) with rows/s,
p50/p99, batch fill, recompile count, and the per-arm spread —
``suspect_capture`` flags spread > 5% per CLAUDE.md.

Arms:
  --compare   pipeline-vs-serial A/B (records ``pipeline_speedup``;
              ISSUE r7 acceptance wants ≥ 1.3× on CPU)
  --sharded   adds a forced-sharded arm (backend tpu, every bucket on the
              mesh — on CPU CI this is the 8 fake devices)
  --smoke     short CI mode: tiny model, short loops, exit 1 unless BOTH
              the bucketed and sharded arms report zero recompiles after
              warmup (scripts/ci.sh runs this)
  --drift     drift-monitor overhead A/B (r18): the same closed loop with
              the model-drift monitor on vs off —
              ``drift_overhead_ms/_pct/_spread`` (obs/trends.py tracks
              them); exit 1 when the cost exceeds 2% and the spread does
              not veto the capture
  --layout    packed-vs-legacy predict traversal layout A/B (r21): the
              same closed loop with ``predict_layout`` forced to packed
              (one node-word table gather per level) vs legacy (~7) on
              the jax backend — ``layout_rows_per_s_packed/_legacy`` +
              ``predict_layout_speedup`` (obs/trends.py tracks them);
              recompiles in either arm fail the run
  --fleet     closed-loop fleet arm (r14, dryad_tpu/fleet/bench.py): REAL
              subprocess replicas behind the router at N=1/2/4
              (``fleet_rows_per_s_nN`` + spreads + ``fleet_scaling_nN``)
              plus a rolling-swap drill under load (``fleet_swap_*``;
              zero failed requests is the acceptance bar).  r17: every
              request carries an ``X-Dryad-Trace`` id (non-echoing
              responses fail the arm) and the report records per-priority
              latency percentiles from the router's mergeable histograms
              (``fleet_<priority>_p{50,95,99}_ms_nN`` — the ROADMAP's
              "p99 budgets per priority class, not just rows/s";
              obs/trends.py tracks them like bench walls).  Standalone
              mode: the in-process arms are skipped.

Acceptance gate: a forced-CPU run must report
``recompiles_after_warmup: 0`` — the shape-bucketed cache makes warm
traffic structurally recompile-free (bench warms every reachable bucket
before measuring, and shard-arm routing is deterministic per bucket).
"""

from __future__ import annotations

import argparse
import json
import sys


def _train_throwaway(n_rows: int = 4000, num_trees: int = 50):
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(n_rows, seed=11)
    ds = dryad.Dataset(X, y, max_bins=64)
    return dryad.train(dict(objective="binary", num_trees=num_trees,
                            num_leaves=31, max_bins=64), ds, backend="cpu")


def run_fleet_arm(args) -> int:
    """The r14 fleet arm: spawn real serve replicas (they pay the jax
    import; this process only drives HTTP), measure scaling + the
    rolling-swap drill, stamp, and print the bench.py-format summary."""
    import os
    import tempfile

    from dryad_tpu.fleet.bench import run_fleet_bench
    from dryad_tpu.obs.trends import artifact_stamp

    tmpdir = None
    if args.model:
        model_path = args.model
        from dryad_tpu.booster import Booster

        booster = Booster.load_any(model_path)
    else:
        booster = _train_throwaway(n_rows=1500 if args.smoke else 4000,
                                   num_trees=20 if args.smoke else 50)
        tmpdir = tempfile.TemporaryDirectory(prefix="dryad-fleet-bench-")
        model_path = os.path.join(tmpdir.name, "model.dryad")
        booster.save(model_path)
    mapper = booster.mapper
    num_features = getattr(mapper, "base", mapper).num_features

    sizes = [int(s) for s in (args.sizes or "1,3,9,17").split(",")]
    duration = args.duration if args.duration is not None else 2.0
    replicas = tuple(int(n) for n in args.fleet_replicas.split(","))
    if args.smoke:
        duration, replicas = min(duration, 1.0), (1, 2)
    try:
        report = run_fleet_bench(
            model_path, num_features, backend=args.backend,
            replica_counts=replicas, clients=args.clients,
            duration_s=duration, sizes=sizes, arms=args.arms,
            seed=args.seed,
            max_batch_rows=args.max_batch_rows or 256,
            max_wait_ms=args.max_wait_ms or 1.0,
            swap_replicas=min(2, max(replicas)), verbose=not args.smoke)
    finally:
        if tmpdir is not None:
            tmpdir.cleanup()
    report.update(artifact_stamp(device_kind=None))

    print(json.dumps(report, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    if report.get("suspect_capture"):
        print("WARNING: per-arm spread > 5% — suspect capture (CLAUDE.md)",
              file=sys.stderr)
    # the one-line summary is the LAST stdout line (bench.py's format)
    print(json.dumps(report))
    failed = report.get("fleet_swap_failed", 0) + sum(
        v for k, v in report.items() if k.startswith("fleet_failures_n"))
    if failed:
        print(f"ERROR: {failed} failed fleet request(s) — the zero-drop "
              "contract is broken", file=sys.stderr)
        return 1
    mismatches = sum(v for k, v in report.items()
                     if k.startswith("fleet_trace_mismatches_n"))
    if mismatches:
        print(f"ERROR: {mismatches} response(s) did not echo their "
              "X-Dryad-Trace id — trace propagation is broken",
              file=sys.stderr)
        return 1
    if report.get("fleet_swap_versions_seen", 2) < 2:
        print("ERROR: the swap drill never observed both versions — the "
              "push did not happen under load", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_serve")
    ap.add_argument("--model", help="model path; trains a throwaway if absent")
    ap.add_argument("--backend", default="cpu",
                    choices=["auto", "tpu", "cpu"])
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=None)
    ap.add_argument("--arms", type=int, default=2,
                    help="measured-loop repetitions (per-arm spread)")
    ap.add_argument("--max-batch-rows", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--sizes", default=None,
                    help="comma-separated request row sizes")
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="overlapped dispatch run-ahead (1 = serial loop)")
    ap.add_argument("--compare", action="store_true",
                    help="pipeline-vs-serial A/B (pipeline_speedup)")
    ap.add_argument("--sharded", action="store_true",
                    help="add a forced-sharded arm (backend tpu over the "
                         "mesh; CI runs it on the 8 fake CPU devices)")
    ap.add_argument("--smoke", action="store_true",
                    help="short CI mode: bucketed + sharded arms, exit 1 "
                         "on any recompile after warmup")
    ap.add_argument("--drift", action="store_true",
                    help="drift-monitor overhead A/B (instrumented vs "
                         "disabled; drift_overhead_ms/_pct/_spread, exit 1 "
                         "over the 2% budget unless the spread vetoes)")
    ap.add_argument("--layout", action="store_true",
                    help="packed-vs-legacy predict layout A/B on the jax "
                         "backend (layout_rows_per_s_packed/_legacy + "
                         "predict_layout_speedup; exit 1 on any recompile "
                         "after warmup in either arm)")
    ap.add_argument("--fleet", action="store_true",
                    help="closed-loop fleet arm: real subprocess replicas "
                         "at N=1/2/4 + a rolling-swap drill (standalone; "
                         "exit 1 on any failed swap-drill request)")
    ap.add_argument("--fleet-replicas", default="1,2,4",
                    help="comma-separated fleet sizes for the scaling arm")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", help="also write the report here")
    args = ap.parse_args(argv)

    if args.fleet:
        return run_fleet_arm(args)

    from dryad_tpu.serve.bench import run_bench, run_bench_compare, summary_line

    # --compare measures the BULK-scoring regime (the north star's "giant
    # batches"): pow2-aligned requests big enough that both pipeline
    # stages are dominated by GIL-releasing native/XLA work — that is
    # where host/device overlap is physical rather than GIL-interleaved.
    # Interactive-sized defaults otherwise.
    if args.sizes is None:
        args.sizes = "2048,4096" if args.compare else "1,3,9,17,40"
    if args.max_batch_rows is None:
        args.max_batch_rows = 4096 if args.compare else 256
    if args.max_wait_ms is None:
        args.max_wait_ms = 0.5 if args.compare else 1.0
    if args.duration is None:
        args.duration = 2.0 if args.compare else 5.0
    if args.smoke:
        args.duration = min(args.duration, 0.5)
        args.arms = 1
        args.clients = min(args.clients, 4)
    model = args.model if args.model else _train_throwaway(
        n_rows=1500 if args.smoke else 4000,
        num_trees=20 if args.smoke else 50)
    kw = dict(clients=args.clients, duration_s=args.duration,
              sizes=[int(s) for s in args.sizes.split(",")],
              max_batch_rows=args.max_batch_rows,
              max_wait_ms=args.max_wait_ms, seed=args.seed, arms=args.arms,
              verbose=not args.smoke)

    report: dict
    if args.compare:
        report = run_bench_compare(model, backend=args.backend,
                                   pipeline_depth=args.pipeline_depth, **kw)
        summary = summary_line(report["pipeline"], "serve_pipeline")
        summary["serial_rows_per_s"] = round(report["serial"]["rows_per_s"], 1)
        summary["pipeline_speedup"] = report["pipeline_speedup"]
        summary["suspect_capture"] = report["suspect_capture"]
        # the exit gate must cover BOTH arms — a serial-only recompile
        # regression would otherwise pass --compare runs silently
        summary["recompiles_after_warmup"] = report["recompiles_after_warmup"]
    else:
        report = run_bench(model, backend=args.backend,
                           pipeline_depth=args.pipeline_depth, **kw)
        summary = summary_line(report, "serve")

    if args.drift:
        # r18 drift-monitor overhead A/B (instrumented vs disabled, the
        # obs_overhead_ms shape); obs/trends.py tracks the fields with
        # the spread veto, and the <= 2% gate fails the run below
        from dryad_tpu.serve.bench import run_bench_drift

        drift = run_bench_drift(model, backend=args.backend,
                                pipeline_depth=args.pipeline_depth, **kw)
        drift.pop("drift_windows", None)
        report["drift_overhead"] = drift
        summary.update({k: v for k, v in drift.items()
                        if k.startswith("drift_overhead")})

    if args.layout:
        # r21 packed-vs-legacy traversal layout A/B: always on the jax
        # backend ('tpu'; the 8 fake CPU devices in CI) — the cpu predict
        # path never stages device tables, so it has no layout to compare
        from dryad_tpu.serve.bench import run_bench_layout

        layout = run_bench_layout(model,
                                  pipeline_depth=args.pipeline_depth, **kw)
        report["layout"] = layout
        summary.update({k: v for k, v in layout.items()
                        if k.startswith(("layout_", "predict_layout"))})
        summary["suspect_capture"] = (summary.get("suspect_capture", False)
                                      or layout["suspect_capture"])

    if args.sharded:
        # forced-sharded arm: every bucket takes the shard_map family
        sharded_report = run_bench(model, backend="tpu", sharded=True,
                                   pipeline_depth=args.pipeline_depth, **kw)
        if args.smoke and sharded_report["mesh_shards"] <= 1:
            # a 1-device mesh silently degrades this arm to a duplicate
            # single-device check — the CI gate must not pass on that
            print("ERROR: sharded smoke got a 1-device mesh (set "
                  "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
                  file=sys.stderr)
            return 1
        report = {"bucketed": report, "sharded": sharded_report}
        summary["sharded_rows_per_s"] = round(
            sharded_report["rows_per_s"], 1)
        summary["sharded_recompiles_after_warmup"] = (
            sharded_report["recompiles_after_warmup"])
        summary["mesh_shards"] = sharded_report["mesh_shards"]

    # artifact stamp (r12): schema_version + git rev + device kind ride
    # both the full report and the one-line summary so the trend ledger
    # (dryad_tpu/obs/trends.py) keys serve history off data, not filenames
    from dryad_tpu.obs.trends import artifact_stamp

    # r23: device_kind rides the stamp's "auto" default — the ONE
    # derivation (policy/device.py), best-effort like the old inline probe
    stamp = artifact_stamp()
    report.update(stamp)
    summary.update(stamp)

    print(json.dumps(report, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    if summary.get("suspect_capture"):
        print("WARNING: per-arm spread > 5% — suspect capture (CLAUDE.md)",
              file=sys.stderr)
    # the one-line summary is the LAST stdout line (bench.py's format)
    print(json.dumps(summary))

    recompiles = summary.get("recompiles_after_warmup", 0)
    recompiles += summary.get("sharded_recompiles_after_warmup", 0)
    recompiles += summary.get("layout_recompiles_after_warmup", 0)
    if recompiles != 0:
        print("WARNING: cache recompiled after warmup", file=sys.stderr)
        return 1
    # drift-overhead gate (<= 2%), with the standard spread veto: a
    # noisy capture is "suspect", never a verdict (CLAUDE.md)
    pct = summary.get("drift_overhead_pct")
    if pct is not None and pct > 0.02:
        if summary.get("drift_overhead_spread", 0.0) > 0.05:
            print("WARNING: drift overhead gate skipped — per-arm spread "
                  "> 5% (suspect capture)", file=sys.stderr)
        else:
            print(f"ERROR: drift monitoring costs {pct:.1%} rows/s — over "
                  "the 2% budget", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
