#!/usr/bin/env bash
# Tier-1 gate — the ROADMAP.md "Tier-1 verify" command VERBATIM, so local
# builders and CI run the identical check (same timeout, same marker
# filter, same pass-count accounting).  Run from anywhere:
#
#     bash scripts/ci.sh
#
cd "$(dirname "$0")/.." || exit 1

# Static analysis (r11, +concurrency r15): dryadlint + the jaxpr auditor
# + the schedule harness.  Layer 1 replaces the r6-r10 grep lints and (r15)
# machine-checks the threaded host plane's lock discipline (guarded-by
# declarations, no blocking under a lock, the committed lock partial
# order in analysis/goldens/lock_order.json) with the waiver count
# RATCHETED against analysis/goldens/waiver_budget.json.  Layer 2 checks
# the trip-weighted collective census against train._comm_stats, the
# wired-path zero-row-sort contract, kernel-boundary u8/u16 discipline,
# and the committed program digests.  Layer 3 (r15) runs the recorded
# race classes as seed-deterministic schedule drills (batcher stop/start,
# supervisor recovery, rolling push vs death, registry snapshot tearing,
# injector concurrent fire) with runtime deadlock/lock-cycle verdicts.
# Exit codes: 2 = lint/ratchet, 3 = IR invariant, 4 = digest drift,
# 5 = crash, 6 = concurrency contract (static rule or failing drill).
# Intentional program changes: python -m dryad_tpu.analysis --update-goldens
# and commit the goldens diff; new lock nestings edit lock_order.json in
# the same spirit.  CPU-only (traces, never compiles).
env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m dryad_tpu.analysis --ci -q > /tmp/_analysis.log 2>&1
analysis_rc=$?
if [ $analysis_rc -ne 0 ]; then
  echo "ANALYSIS FAIL (exit $analysis_rc): python -m dryad_tpu.analysis --ci (see /tmp/_analysis.log)" >&2
  tail -15 /tmp/_analysis.log >&2
  exit 1
fi
tail -2 /tmp/_analysis.log

# Bench trend ledger (r12): the committed BENCH_r*.json history must be
# regression-free under the spread-aware median check, and the checker
# must actually FLAG a seeded regression (--selftest proves the gate
# fires in both directions, including the suspect-capture veto).
if ! PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/bench_trend.py --check > /tmp/_trend.log 2>&1; then
  echo "TREND FAIL: bench_trend.py --check (see /tmp/_trend.log)" >&2
  tail -8 /tmp/_trend.log >&2
  exit 1
fi
if ! PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/bench_trend.py --selftest > /tmp/_trend_self.log 2>&1; then
  echo "TREND SELFTEST FAIL: the seeded regression was not flagged" >&2
  tail -5 /tmp/_trend_self.log >&2
  exit 1
fi
tail -1 /tmp/_trend_self.log

# Stage-profiler selftest (r13): the timed-fori harness's runtime
# liveness proof must FIRE on the seeded dead-perturbation probe (the
# r5/r10 2x-fast class the AST lint cannot fully catch) and PASS on
# every shipped stage probe — CPU, seconds.
if ! env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m dryad_tpu profile --selftest --quiet > /tmp/_profile_self.log 2>&1; then
  echo "PROFILE SELFTEST FAIL: python -m dryad_tpu profile --selftest (see /tmp/_profile_self.log)" >&2
  tail -5 /tmp/_profile_self.log >&2
  exit 1
fi
tail -1 /tmp/_profile_self.log

# Calibration-policy selftest (r23): seeded CPU, NO probes — the
# committed calibration.json must equal the code defaults, every gate
# must resolve bitwise-identically to the pre-policy hand-tuned
# constants across shapes straddling each threshold, a perturbed table
# entry must flip EXACTLY the intended gate and nothing else, and
# save/load must round-trip resolutions.
if ! env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m dryad_tpu profile --calibrate --selftest > /tmp/_calib_self.log 2>&1; then
  echo "CALIB SELFTEST FAIL: python -m dryad_tpu profile --calibrate --selftest (see /tmp/_calib_self.log)" >&2
  tail -5 /tmp/_calib_self.log >&2
  exit 1
fi
tail -1 /tmp/_calib_self.log

# Observability smoke (r9; r12 adds the device-truth families): the CLI's
# live metrics endpoint — train 5 trees through the DEVICE trainer with
# --metrics-port, scrape /healthz + /stats + /metrics while the run is
# up, assert span series non-empty, counters monotone, and the
# dryad_prog_* / dryad_fetch_* families live on the same scrape.
if ! env JAX_PLATFORMS=cpu DRYAD_OBS=1 \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/smoke_obs.py > /tmp/_obs_smoke.log 2>&1; then
  echo "OBS SMOKE FAIL: scripts/smoke_obs.py (see /tmp/_obs_smoke.log)" >&2
  tail -5 /tmp/_obs_smoke.log >&2
  exit 1
fi
tail -1 /tmp/_obs_smoke.log

# Supervisor smoke (r8): two injected faults (one fetch-death) through a
# short supervised run — exactly-once resume per fault, chunk backoff to
# the known-safe 2, well-formed journal, bitwise-equal final model.
if ! env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/smoke_supervisor.py > /tmp/_sup_smoke.log 2>&1; then
  echo "SUPERVISOR SMOKE FAIL: scripts/smoke_supervisor.py (see /tmp/_sup_smoke.log)" >&2
  tail -5 /tmp/_sup_smoke.log >&2
  exit 1
fi
tail -1 /tmp/_sup_smoke.log

# Fleet smoke (r14): REAL subprocess serve replicas behind the router —
# an injected replica_crash (DRYAD_REPLICA_FAULTS drill wire) mid-load
# must cost ZERO failed interactive requests (single-retry budget), and
# the supervisor must journal the crash and respawn the slot.
if ! env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/smoke_fleet.py > /tmp/_fleet_smoke.log 2>&1; then
  echo "FLEET SMOKE FAIL: scripts/smoke_fleet.py (see /tmp/_fleet_smoke.log)" >&2
  tail -5 /tmp/_fleet_smoke.log >&2
  exit 1
fi
tail -1 /tmp/_fleet_smoke.log

# Continual smoke (r19): the multi-generation drill on REAL replicas —
# a sustained covariate shift journals drift_breach, the RetrainScheduler
# append-trains gen-1 (warm-start init_model subprocess), the rolling
# push clears the breach in probation (generation_promoted), and a
# forced bad_generation retrain (DRYAD_CONTINUAL_FAULTS drill wire)
# auto-rolls back by re-pushing the gen-1 artifact — zero failed
# interactive requests, zero unexpected recompiles across the swaps.
if ! env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/smoke_continual.py > /tmp/_continual_smoke.log 2>&1; then
  echo "CONTINUAL SMOKE FAIL: scripts/smoke_continual.py (see /tmp/_continual_smoke.log)" >&2
  tail -5 /tmp/_continual_smoke.log >&2
  exit 1
fi
tail -1 /tmp/_continual_smoke.log

# Serving bench smoke (r7): zero recompiles after warmup across BOTH the
# bucketed (forced-CPU) and sharded (8 fake devices) compiled-entry
# families — warm traffic must be structurally recompile-free.
if ! env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/bench_serve.py --smoke --sharded > /tmp/_serve_smoke.log 2>&1; then
  echo "SERVE SMOKE FAIL: bench_serve --smoke --sharded (see /tmp/_serve_smoke.log)" >&2
  tail -5 /tmp/_serve_smoke.log >&2
  exit 1
fi
tail -1 /tmp/_serve_smoke.log

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
