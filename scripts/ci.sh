#!/usr/bin/env bash
# Tier-1 gate — the ROADMAP.md "Tier-1 verify" command VERBATIM, so local
# builders and CI run the identical check (same timeout, same marker
# filter, same pass-count accounting).  Run from anywhere:
#
#     bash scripts/ci.sh
#
cd "$(dirname "$0")/.." || exit 1

# Wired-deep-phase lint (r6): engine/levelwise.py must never reach back to
# the per-level sort helpers directly — the wired path's whole point is
# that tile_plan/tile_plan_aligned are gone from the deep levels (the
# legacy fallback reaches them only through build_hist_segmented).  A
# direct reference here means the sort quietly re-grew; fail fast.
if grep -nE 'tile_plan' dryad_tpu/engine/levelwise.py; then
  echo "LINT FAIL: engine/levelwise.py references the per-level sort helper (tile_plan*)" >&2
  exit 1
fi

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
