#!/usr/bin/env bash
# Tier-1 gate — the ROADMAP.md "Tier-1 verify" command VERBATIM, so local
# builders and CI run the identical check (same timeout, same marker
# filter, same pass-count accounting).  Run from anywhere:
#
#     bash scripts/ci.sh
#
cd "$(dirname "$0")/.." || exit 1

# Wired-grower lint (r6, widened to the batched leaf-wise grower in r10):
# neither level-synchronous grower may reach back to the per-level sort
# helpers directly — the wired path's whole point is that
# tile_plan/tile_plan_aligned are gone from the growers (the legacy
# fallback reaches them only through build_hist_segmented).  A direct
# reference here means the deleted per-level sort/gather quietly re-grew;
# fail fast.
if grep -nE 'tile_plan' dryad_tpu/engine/levelwise.py dryad_tpu/engine/leafwise_fast.py; then
  echo "LINT FAIL: a wired grower references the per-level sort helper (tile_plan*)" >&2
  exit 1
fi

# Serving dispatch-loop lint (r7): the batcher must never touch the
# device result itself — the ONE real host fetch per chunk lives in the
# cache's execute stage (np.asarray on the raw scores).  A fetch growing
# back into the collect/dispatch loop would serialize the overlapped
# pipeline (and block_until_ready returns instantly on the tunnel, so it
# is banned everywhere in serve/ — CLAUDE.md measuring notes).
if grep -rnE '\.block_until_ready\(' dryad_tpu/serve/; then
  echo "LINT FAIL: serve/ uses block_until_ready (lies on the tunnel; use a real fetch)" >&2
  exit 1
fi
if grep -nE 'np\.asarray|asnumpy|device_get|import jax' dryad_tpu/serve/batcher.py; then
  echo "LINT FAIL: serve/batcher.py grew a device fetch — the single result fetch belongs in cache.execute_raw" >&2
  exit 1
fi

# Resilience fetch lint (r8, widened to obs/ in r9): the supervisor/
# journal layer and the observability collectors must never throttle or
# time anything on block_until_ready — it returns instantly through this
# tunnel (STATUS r5 / CLAUDE.md measuring notes), so a "wait" built on it
# is a no-op that would let the supervisor misjudge run health.  Same
# rule the batcher lint enforces for serve/.
if grep -rnE '\.block_until_ready\(' dryad_tpu/resilience/ dryad_tpu/obs/; then
  echo "LINT FAIL: resilience//obs/ uses block_until_ready (lies on the tunnel; use a real fetch)" >&2
  exit 1
fi

# Observability device lint (r9): obs collectors are HOST-SIDE ONLY — they
# may only record values the engine already fetched (CLAUDE.md's
# never-fetch-per-iteration rule).  The whole package must stay jax-free:
# no device fetches (device_get / addressable_data / np.asarray on device
# buffers) and no jax import anywhere, snapshot path included — the
# registry's "explicitly-annotated snapshot path" is annotated AND
# jax-free by construction, so the lint is strict over the package.
if grep -rnE 'import jax|device_get|addressable_data|np\.asarray|asnumpy' dryad_tpu/obs/; then
  echo "LINT FAIL: dryad_tpu/obs/ grew a jax/device dependency — obs collectors are host-side only" >&2
  exit 1
fi

# Observability smoke (r9): the CLI's live metrics endpoint — train 5
# trees with --metrics-port, scrape /healthz + /stats + /metrics while
# the run is up, assert span series non-empty and counters monotone.
if ! env JAX_PLATFORMS=cpu DRYAD_OBS=1 \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/smoke_obs.py > /tmp/_obs_smoke.log 2>&1; then
  echo "OBS SMOKE FAIL: scripts/smoke_obs.py (see /tmp/_obs_smoke.log)" >&2
  tail -5 /tmp/_obs_smoke.log >&2
  exit 1
fi
tail -1 /tmp/_obs_smoke.log

# Supervisor smoke (r8): two injected faults (one fetch-death) through a
# short supervised run — exactly-once resume per fault, chunk backoff to
# the known-safe 2, well-formed journal, bitwise-equal final model.
if ! env JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/smoke_supervisor.py > /tmp/_sup_smoke.log 2>&1; then
  echo "SUPERVISOR SMOKE FAIL: scripts/smoke_supervisor.py (see /tmp/_sup_smoke.log)" >&2
  tail -5 /tmp/_sup_smoke.log >&2
  exit 1
fi
tail -1 /tmp/_sup_smoke.log

# Serving bench smoke (r7): zero recompiles after warmup across BOTH the
# bucketed (forced-CPU) and sharded (8 fake devices) compiled-entry
# families — warm traffic must be structurally recompile-free.
if ! env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python scripts/bench_serve.py --smoke --sharded > /tmp/_serve_smoke.log 2>&1; then
  echo "SERVE SMOKE FAIL: bench_serve --smoke --sharded (see /tmp/_serve_smoke.log)" >&2
  tail -5 /tmp/_serve_smoke.log >&2
  exit 1
fi
tail -1 /tmp/_serve_smoke.log

set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
