"""Out-of-core streamed-training bench arm (Issue 17 / r20).

Two measurements, one JSON artifact line (bench.py merges it under
``BENCH_STREAM``; ``obs/trends.py`` tracks the metric fields):

1. **Overhead A/B** — resident vs streamed CPU training on a 200k-row
   fixture, min-of-reps walls with per-arm spreads.  The arms are
   bitwise-checked against each other first: a fast-but-wrong capture
   must fail loudly, never publish.  Fields: ``stream_train_rows_per_s``
   (streamed throughput), ``stream_overhead_pct`` (streamed vs resident
   wall), ``stream_overhead_spread`` (max per-arm spread — the >5%
   suspect-capture veto trends.py applies).

2. **RSS proof at >=1e7 rows** — resident and streamed arms run in
   SUBPROCESSES (``ru_maxrss`` is a process-lifetime peak, so each arm
   needs its own lifetime): chunked synthetic ingest (restartable seeded
   generator, frozen shared mapper) -> ``dataset_from_chunks`` with and
   without ``spill=`` -> one boosting tree.  The streamed arm's peak RSS
   must sit demonstrably BELOW the resident binned-matrix requirement
   (``stream_rss_peak_mb < resident_matrix_mb``) and below the resident
   arm's measured peak; both workers also report a tree digest and the
   parent asserts they match — the 1e7-scale bitwise proof rides the
   same run.  ``--skip-rss`` keeps only the cheap A/B part (bench.py's
   default unless ``BENCH_STREAM_RSS=1``).

This is pure-CPU numpy work (no device, no timed-fori program — the
harness rules for device probes don't apply); walls are min-of-reps
``perf_counter`` with spread fields, per the bench spread contract.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# ---- RSS-proof worker shape (both arms must agree exactly) ----------------
RSS_ROWS = 10_000_000
RSS_FEATURES = 128
RSS_BINS = 32
RSS_CHUNK = 250_000
RSS_SEED = 20_001


def _gen_chunk(lo: int, n: int, F: int) -> np.ndarray:
    """Restartable synthetic rows: a pure function of the row offset, so
    every pass over the chunk stream regenerates identical data."""
    rng = np.random.default_rng(RSS_SEED + lo)
    return rng.standard_normal((n, F), dtype=np.float32)


def _tree_digest(booster) -> str:
    h = hashlib.sha256()
    for key in ("feature", "threshold", "left", "right", "value"):
        h.update(np.ascontiguousarray(getattr(booster, key)).tobytes())
    return h.hexdigest()


def run_worker(arm: str, rows: int) -> int:
    """One RSS-proof arm in its own process lifetime."""
    import resource

    from dryad_tpu.config import Params
    from dryad_tpu.cpu.trainer import train_cpu
    from dryad_tpu.data.sketch import sketch_features
    from dryad_tpu.data.streaming import dataset_from_chunks

    N, F = int(rows), RSS_FEATURES

    def chunks():
        for lo in range(0, N, RSS_CHUNK):
            yield _gen_chunk(lo, min(RSS_CHUNK, N - lo), F)

    # frozen mapper from a fixed prefix — identical in both arms, so the
    # bin space (and therefore the grown tree) is shared bitwise
    mapper = sketch_features(_gen_chunk(0, 200_000, F), max_bins=RSS_BINS)

    ys = []
    for lo in range(0, N, RSS_CHUNK):
        c = _gen_chunk(lo, min(RSS_CHUNK, N - lo), F)
        ys.append((c[:, 0] + 0.5 * c[:, 1] > 0.2).astype(np.float32))
    y = np.concatenate(ys)
    del ys

    t0 = time.perf_counter()
    spill = None
    if arm == "streamed":
        spill = os.path.join(tempfile.mkdtemp(prefix="dryad_stream_"),
                             "bins.stream")
        ds = dataset_from_chunks(chunks, y, N, F, mapper=mapper,
                                 spill=spill, chunk_rows=RSS_CHUNK)
    else:
        ds = dataset_from_chunks(chunks, y, N, F, mapper=mapper)
    build_s = time.perf_counter() - t0

    p = Params(objective="binary", num_trees=1, num_leaves=3, seed=7)
    t1 = time.perf_counter()
    booster = train_cpu(p, ds)
    train_s = time.perf_counter() - t1

    peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    print(json.dumps({
        "arm": arm, "rows": N, "features": F,
        "rss_peak_mb": round(peak_kb / 1024.0, 1),
        "build_s": round(build_s, 2), "train_s": round(train_s, 2),
        "digest": _tree_digest(booster),
    }))
    if spill is not None:
        try:
            os.unlink(spill)
        except OSError:
            pass
    return 0


def overhead_ab(reps: int = 3) -> dict:
    """Resident-vs-streamed CPU training walls on a 200k fixture."""
    import dryad_tpu as dryad
    from dryad_tpu.data.stream_dataset import StreamedDataset

    N, F, TREES = 200_000, 32, 6
    rng = np.random.default_rng(11)
    X = rng.standard_normal((N, F)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + rng.normal(0, 0.1, N) > 0.4
         ).astype(np.float32)
    ds = dryad.Dataset(X, y, max_bins=64)
    sds = StreamedDataset.from_dataset(
        ds, os.path.join(tempfile.mkdtemp(prefix="dryad_ab_"), "bins.stream"),
        chunk_rows=N // 4)
    p = dryad.Params(objective="binary", num_trees=TREES, num_leaves=31,
                     seed=3, subsample=0.8)

    ref = dryad.train(p, ds, backend="cpu")
    got = dryad.train(p, sds, backend="cpu")
    for key in ("feature", "threshold", "left", "right", "value"):
        np.testing.assert_array_equal(getattr(ref, key), getattr(got, key))

    walls = {"resident": [], "streamed": []}
    for _ in range(reps):                   # alternate arms: drift-fair
        for arm, d in (("resident", ds), ("streamed", sds)):
            t0 = time.perf_counter()
            dryad.train(p, d, backend="cpu")
            walls[arm].append(time.perf_counter() - t0)
    res, stm = min(walls["resident"]), min(walls["streamed"])
    spread = max(
        (max(w) - min(w)) / min(w) * 100.0 for w in walls.values())
    try:
        os.unlink(sds.path)
    except OSError:
        pass
    return {
        "stream_ab_rows": N, "stream_ab_trees": TREES,
        "stream_train_rows_per_s": round(N * TREES / stm, 1),
        "stream_overhead_pct": round((stm - res) / res * 100.0, 2),
        "stream_overhead_spread": round(spread, 2),
        "stream_wall_resident_s": round(res, 3),
        "stream_wall_streamed_s": round(stm, 3),
        "stream_bitwise_ab": True,
    }


def rss_proof(rows: int) -> dict:
    """Run both RSS arms as subprocesses; assert the streamed peak is
    below the resident binned-matrix requirement AND the digests agree."""
    results = {}
    for arm in ("streamed", "resident"):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--worker", arm, "--rows", str(rows)],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "DRYAD_OBS": "0", "DRYAD_PROFILE": "0"})
        if proc.returncode != 0:
            raise SystemExit(
                f"{arm} worker failed:\n{proc.stdout}\n{proc.stderr}")
        results[arm] = json.loads(proc.stdout.strip().splitlines()[-1])
    stm, res = results["streamed"], results["resident"]
    if stm["digest"] != res["digest"]:
        raise SystemExit(
            f"streamed/resident tree digests diverge at {rows} rows: "
            f"{stm['digest']} vs {res['digest']}")
    matrix_mb = rows * RSS_FEATURES / (1024.0 * 1024.0)  # u8 bins
    out = {
        "stream_rss_rows": int(rows),
        "stream_rss_features": RSS_FEATURES,
        "resident_matrix_mb": round(matrix_mb, 1),
        "stream_rss_peak_mb": stm["rss_peak_mb"],
        "resident_rss_peak_mb": res["rss_peak_mb"],
        "stream_build_s": stm["build_s"], "stream_train_s": stm["train_s"],
        "resident_build_s": res["build_s"], "resident_train_s": res["train_s"],
        "stream_bitwise_10m": True,
    }
    if not (stm["rss_peak_mb"] < matrix_mb
            and stm["rss_peak_mb"] < res["rss_peak_mb"]):
        raise SystemExit(
            "RSS proof failed: streamed peak "
            f"{stm['rss_peak_mb']} MB is not below the resident matrix "
            f"({matrix_mb:.0f} MB) and the resident peak "
            f"({res['rss_peak_mb']} MB)\n{json.dumps(out)}")
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", choices=("streamed", "resident"),
                    help="internal: run one RSS arm and exit")
    ap.add_argument("--rows", type=int, default=RSS_ROWS,
                    help=f"RSS-proof row count (default {RSS_ROWS})")
    ap.add_argument("--reps", type=int, default=3,
                    help="A/B wall repetitions per arm")
    ap.add_argument("--skip-rss", action="store_true",
                    help="only the cheap overhead A/B (bench.py default)")
    ap.add_argument("--out", help="also write the JSON artifact here")
    args = ap.parse_args()

    if args.worker:
        return run_worker(args.worker, args.rows)

    out: dict = {"bench": "stream_train"}
    out.update(overhead_ab(args.reps))
    if not args.skip_rss:
        if args.rows < 10_000_000:
            print(f"# note: --rows {args.rows} is below the 1e7 acceptance "
                  "floor; artifact will say so", file=sys.stderr)
        out.update(rss_proof(args.rows))

    from dryad_tpu.obs.trends import artifact_stamp

    out.update(artifact_stamp(device_kind="cpu", root=REPO))
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
