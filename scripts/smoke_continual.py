"""Continual-boosting smoke for scripts/ci.sh (runs under JAX_PLATFORMS=cpu).

The r19 multi-generation drill, end to end on REAL subprocess replicas —
the full train -> serve -> drift -> retrain -> publish loop the continual
package closes:

* gen-0 trains with its reference profile and serves on a 2-replica
  fleet; baseline traffic keeps ``GET /drift`` green (no false positive),
* a sustained 3x covariate-shift burst journals ``drift_breach``; the
  REAL ``RetrainScheduler`` tails the journal, debounces, and append-
  trains gen-1 (``dryad_tpu retrain`` subprocess: warm-start
  ``init_model`` on the SHIFTED rows, fresh embedded profile),
* gen-1 goes out through the zero-drop rolling push into probation;
  because its profile matches the live traffic the verdict clears and
  the journal records ``generation_promoted`` — the breach is gone,
* a FORCED retrain (manual trigger) arms the ``bad_generation`` fault
  through ``DRYAD_CONTINUAL_FAULTS`` (the production drill wire): gen-2
  trains on covariate-scaled rows, so its fresh profile breaches against
  the live traffic during probation while gen-1's pre-push verdict was
  clean — the publisher auto-rolls back by RE-PUSHING the gen-1
  artifact (never an in-place registry mutation) and journals
  ``generation_rolled_back``,
* throughout: ZERO failed interactive requests, zero trace-id
  mismatches, and ``dryad_recompile_unexpected_total`` == 0 on every
  replica (generation swaps ride the deploy-window disarm).

Prints one JSON summary line on success, exits 1 with a reason otherwise.
"""

import http.client
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import dryad_tpu as dryad  # noqa: E402
from dryad_tpu.continual import (  # noqa: E402
    JournalTailer, ProbationPublisher, RetrainScheduler,
    make_http_verdicts, make_subprocess_launcher, make_supervisor_push)
from dryad_tpu.datasets import higgs_like  # noqa: E402
from dryad_tpu.fleet import FleetRouter, FleetSupervisor, serve_argv  # noqa: E402
from dryad_tpu.fleet.bench import _closed_loop  # noqa: E402
from dryad_tpu.obs.registry import Registry  # noqa: E402
from dryad_tpu.resilience import faults as F  # noqa: E402
from dryad_tpu.resilience.journal import RunJournal  # noqa: E402
from dryad_tpu.resilience.policy import RetryPolicy  # noqa: E402

PARAMS = dict(objective="binary", num_trees=10, num_leaves=7, max_bins=32,
              seed=5)
RETRAIN_TREES = 6
SHIFT = 3.0          # the covariate scale that flips the drift verdict


def fail(reason: str) -> int:
    print(f"CONTINUAL SMOKE FAIL: {reason}", flush=True)
    return 1


class TrafficPump(threading.Thread):
    """Closed interactive loops in 2 s chunks until stopped — the drift
    windows (gen-0's breach, gen-1's clear, gen-2's probation breach)
    only fill while requests flow, so traffic must span the whole drill,
    not just the burst."""

    def __init__(self, host, port, payloads):
        super().__init__(daemon=True)
        self.host, self.port, self.payloads = host, port, payloads
        self.stop_ev = threading.Event()
        self.failures = 0
        self.requests = 0
        self.trace_mismatches = 0

    def run(self):
        seed = 100
        while not self.stop_ev.is_set():
            seed += 1
            r = _closed_loop(self.host, self.port, self.payloads,
                             clients=1, duration_s=2.0, seed=seed,
                             priority="interactive", trace=True)
            self.failures += r["failures"]
            self.requests += r["requests"]
            self.trace_mismatches += r["trace_mismatches"]

    def halt(self):
        self.stop_ev.set()
        self.join(timeout=30.0)


def wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.5)
    raise TimeoutError(what)


def main() -> int:
    os.environ["DRYAD_PROFILE"] = "1"
    X, y = higgs_like(1200, seed=17)
    ds = dryad.Dataset(X, y, max_bins=32)
    booster = dryad.train(PARAMS, ds, backend="cpu")
    if booster.profile is None:
        return fail("dryad.train attached no reference profile")

    with tempfile.TemporaryDirectory(prefix="dryad-continual-smoke-") as td:
        gen0_path = os.path.join(td, "m-gen0.dryad")
        booster.save(gen0_path)
        # retrain corpus = the SHIFTED distribution: gen-1's embedded
        # profile must describe the live traffic for the breach to clear
        fresh_npz = os.path.join(td, "fresh.npz")
        np.savez(fresh_npz, X=(X * SHIFT).astype(np.float32), y=y)
        journal_path = os.path.join(td, "fleet.jsonl")
        out_dir = os.path.join(td, "continual")
        reg = Registry()

        def make_argv(index: int, port_file: str) -> list:
            # NAME=path alias: drift verdicts key on the registry alias,
            # so the label survives generation pushes (a bare path's
            # label would change v1 -> v2 on the first push)
            return serve_argv([f"m={gen0_path}"], port_file, backend="cpu",
                              max_batch_rows=64, max_wait_ms=0.5,
                              drift_window=512)

        sup = FleetSupervisor(
            make_argv, 2,
            policy=RetryPolicy(backoff_base_s=0.1, retry_budget=3),
            journal=journal_path, registry=reg,
            probe_interval_s=0.1, startup_timeout_s=180.0)
        sup.start()
        router = FleetRouter(sup, registry=reg, max_inflight=16,
                             drift_budget_psi=0.25,
                             drift_breach_after=2).start()
        # job 0 (the drift-triggered gen-1) is clean; job 1 (the forced
        # gen-2) trains bad — the production fault wire, env-armed
        bad_spec = F.encode_points(
            [F.FaultPoint(site="retrain", iteration=1,
                          kind=F.BAD_GENERATION)])
        launch = make_subprocess_launcher(
            fresh_npz, out_dir, trees=RETRAIN_TREES, backend="cpu",
            timeout_s=600.0, log_dir=out_dir,
            extra_env={F.CONTINUAL_FAULTS_ENV: bad_spec})
        publisher = ProbationPublisher(
            make_supervisor_push(sup),
            make_http_verdicts(router.host, router.port),
            journal=sup.journal, probation_polls=12, poll_interval_s=1.0,
            clear_after=2, registry=reg)
        rs = RetrainScheduler(
            {"m": gen0_path}, launch, journal=sup.journal,
            publisher=publisher,
            policy=RetryPolicy(backoff_base_s=0.5, retry_budget=3),
            cooldown_s=3.0, max_concurrent=1, poll_interval_s=0.5,
            source=JournalTailer(journal_path), registry=reg).start()

        def events():
            return RunJournal.read(journal_path)

        def has(kind, **match):
            return [e for e in events() if e["event"] == kind
                    and all(e.get(k) == v for k, v in match.items())]

        def drift_poll(conn):
            conn.request("GET", "/drift")
            return json.loads(conn.getresponse().read())

        pump = None
        try:
            conn = http.client.HTTPConnection(router.host, router.port,
                                              timeout=30.0)

            def slice_payloads(scale: float) -> dict:
                out = {}
                for n, start in ((37, 0), (83, 100), (129, 300), (211, 500)):
                    rows = (X[start:start + n] * scale).tolist()
                    out[n] = json.dumps({"rows": rows}).encode()
                return out

            # ---- phase 1: baseline green --------------------------------
            base = _closed_loop(router.host, router.port,
                                slice_payloads(1.0), clients=2,
                                duration_s=2.5, seed=5, trace=True)
            clean = drift_poll(conn)
            false_pos = {m: v for m, v in (clean.get("models") or {}).items()
                         if v.get("breached")}
            if false_pos:
                return fail("drift breached on training-distribution "
                            f"traffic (false positive): {false_pos}")

            # ---- phase 2: sustained shift -> breach -> gen-1 ------------
            pump = TrafficPump(router.host, router.port,
                               slice_payloads(SHIFT))
            pump.start()

            def breached():
                drift_poll(conn)
                return has("drift_breach", model="m")

            wait_for(breached, 90.0, "no drift_breach journaled for the "
                     "sustained covariate shift")
            wait_for(lambda: has("retrain_triggered", model="m",
                                 generation=1),
                     30.0, "the scheduler never picked the breach up from "
                     "the journal tail")
            wait_for(lambda: has("retrain_complete", model="m",
                                 generation=1),
                     300.0, "the gen-1 append retrain never completed")
            wait_for(lambda: has("generation_promoted", model="m",
                                 generation=1),
                     90.0, "gen-1 never promoted — the matching profile "
                     "should have cleared the breach in probation")
            # the fleet verdict must actually be green again (live proof,
            # not just the journal record)
            def green():
                doc = drift_poll(conn)
                v = (doc.get("models") or {}).get("m") or {}
                return bool(v.get("rows")) and not v.get("breached")
            wait_for(green, 60.0, "the fleet /drift verdict never went "
                     "green after the gen-1 push")

            # ---- phase 3: forced bad generation -> rollback -------------
            def forced():
                rs.trigger("m", origin="forced")
                return has("retrain_triggered", model="m", generation=2)
            wait_for(forced, 30.0, "the forced trigger never admitted "
                     "(cooldown never expired?)")
            wait_for(lambda: has("generation_rolled_back", model="m",
                                 generation=2),
                     300.0, "the bad generation was never rolled back")
            wait_for(green, 60.0, "the fleet /drift verdict never "
                     "recovered after the rollback re-push")
            tail = _closed_loop(router.host, router.port,
                                slice_payloads(SHIFT), clients=2,
                                duration_s=1.5, seed=7, trace=True)
            conn.close()
        except TimeoutError as e:
            return fail(f"{e} — journal tail: {events()[-12:]}")
        finally:
            if pump is not None:
                pump.halt()
            rs.stop(timeout_s=30.0)
            state = rs.state()
            # replica metrics BEFORE teardown: an absent counter is zero
            # (the tripwire only mints the line on first fire), but the
            # scrape itself must succeed or the check never ran
            recompiles = {}
            for slot in sup.slots:
                if slot.proc is None or slot.proc.host is None:
                    continue
                try:
                    c = http.client.HTTPConnection(
                        slot.proc.host, slot.proc.port, timeout=10.0)
                    c.request("GET", "/metrics")
                    text = c.getresponse().read().decode()
                    c.close()
                except OSError:
                    continue
                recompiles[slot.name] = 0.0
                for line in text.splitlines():
                    if line.startswith("dryad_recompile_unexpected_total"):
                        recompiles[slot.name] = float(line.split()[-1])
            router.stop()
            sup.stop()
        evs = RunJournal.read(journal_path)
        # load the promoted artifact while the tempdir still exists
        promoted = [e for e in evs if e["event"] == "generation_promoted"
                    and e.get("generation") == 1]
        gen1 = (dryad.Booster.load_any(promoted[0]["path"]) if promoted
                else None)

    # ---- assertions --------------------------------------------------------
    failures = base["failures"] + pump.failures + tail["failures"]
    if failures:
        return fail(f"{failures} failed interactive request(s) across the "
                    "generation swaps — the rolling push must be zero-drop")
    mism = (base["trace_mismatches"] + pump.trace_mismatches
            + tail["trace_mismatches"])
    if mism:
        return fail(f"{mism} response(s) did not echo their trace id")
    if pump.requests < 20:
        return fail(f"only {pump.requests} pumped requests — the drill "
                    "never exercised the fleet")

    def evts(kind, **match):
        return [e for e in evs if e["event"] == kind
                and all(e.get(k) == v for k, v in match.items())]

    for kind, gen in (("retrain_triggered", 1), ("retrain_complete", 1),
                      ("push_probation", 1), ("generation_promoted", 1),
                      ("retrain_triggered", 2), ("retrain_complete", 2),
                      ("push_probation", 2), ("generation_rolled_back", 2)):
        found = evts(kind, model="m", generation=gen)
        if len(found) != 1:
            return fail(f"expected exactly one {kind} for generation {gen}, "
                        f"got {len(found)}")
    rb = evts("generation_rolled_back", model="m", generation=2)[0]
    if not rb.get("prior", "").endswith("m-gen1.dryad"):
        return fail(f"rollback re-pushed {rb.get('prior')!r}, not the gen-1 "
                    "artifact")
    if not rb.get("restore_ok"):
        return fail(f"the rollback re-push itself failed: {rb}")
    if evts("generation_promoted", model="m", generation=2):
        return fail("the bad generation was ALSO promoted")
    if state["generation"].get("m") != 1:
        return fail(f"scheduler generation is {state['generation']} — the "
                    "rolled-back gen-2 must not supersede gen-1")
    if not state["artifacts"].get("m", "").endswith("m-gen1.dryad"):
        return fail(f"scheduler artifact is {state['artifacts']} — want the "
                    "promoted gen-1 path")
    if state["inflight"]:
        return fail(f"retrains still in flight at teardown: {state}")
    # the generations themselves: warm-start appends, fresh profiles
    if gen1 is None:
        return fail("no promoted gen-1 artifact to inspect")
    if gen1.num_iterations != PARAMS["num_trees"] + RETRAIN_TREES:
        return fail(f"gen-1 has {gen1.num_iterations} trees — the append "
                    f"should carry {PARAMS['num_trees']} + {RETRAIN_TREES}")
    if gen1.profile is None:
        return fail("gen-1 shipped without a fresh reference profile")
    if not recompiles:
        return fail("no replica /metrics scrape succeeded — the recompile "
                    "tripwire check never ran")
    if any(v != 0 for v in recompiles.values()):
        return fail(f"unexpected serve recompiles across the swaps: "
                    f"{recompiles}")
    if evts("replica_crash"):
        return fail("a replica crashed during the drill")

    print(json.dumps({
        "continual_smoke": "ok",
        "requests": base["requests"] + pump.requests + tail["requests"],
        "failed_interactive": 0,
        "trace_mismatches": 0,
        "drift_breaches": len(evts("drift_breach", model="m")),
        "gen1_trees": gen1.num_iterations,
        "promoted": 1,
        "rolled_back": 1,
        "recompiles_unexpected": recompiles,
        "journal_events": len(evs),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
