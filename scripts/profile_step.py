"""Component timing for the 10M-row train step (VERDICT r1 item 2).

CLAUDE.md methodology: K dependent iterations inside ONE jit via
lax.fori_loop, wall-clock / K.  Each stage's step consumes a scalar
perturbation and emits a scalar so the loop carries a true dependency.
Big arrays are jit ARGUMENTS (remote compile rejects large constants).

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/profile_step.py [rows] [K]
"""
# dryadlint: disable-file=jit-closure-constant -- r2-era probe: one-shot tree build, closure constants deliberate at the probe shape; kept verbatim for provenance

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.config import make_params
from dryad_tpu.engine.grower import grow_any
from dryad_tpu.engine.predict import tree_leaves
from dryad_tpu.objectives import get_objective


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    K = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    F, B = 28, 256
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} features={F} bins={B} reps={K} device={jax.devices()[0]}")

    Xb_h = rng.integers(1, B, size=(N, F), dtype=np.uint8)
    Xb = jnp.asarray(Xb_h)
    y = jnp.asarray((rng.random(N) < 0.5).astype(np.float32))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    bag = jnp.ones((N,), bool)
    fmask = jnp.ones((F,), bool)
    iscat = jnp.zeros((F,), bool)

    p = make_params(dict(objective="binary", num_leaves=255, max_depth=8,
                         growth="depthwise"))
    obj = get_objective(p)

    def loop_time(make_step, *arrays):
        """make_step(s, *arrays) -> scalar; K dependent reps in one jit."""
        def prog(s0, *arrays):
            return jax.lax.fori_loop(
                0, K, lambda i, s: make_step(s, *arrays), s0)
        f = jax.jit(prog)
        _ = float(f(jnp.float32(0.0), *arrays))  # compile + warm
        t0 = time.perf_counter()
        _ = float(f(jnp.float32(0.0), *arrays))
        return (time.perf_counter() - t0) / K

    # grad/hess
    t = loop_time(lambda s, gg, yy: obj.grad_hess_jax(gg + s, yy)[0][0] * 1e-30,
                  g, y)
    print(f"grad/hess:            {t*1e3:9.1f} ms")

    # grower
    def grow_step(s, X, gg, hh, bb):
        tr = grow_any(p, B, X, gg + s, hh, bb, fmask, iscat,
                      has_cat=False, platform=plat)
        return tr["value"][0] * 1e-30
    t_grow = loop_time(grow_step, Xb, g, h, bag)
    print(f"grower (depthwise):   {t_grow*1e3:9.1f} ms")

    # traversal on a grown tree (tree arrays as args)
    tree = jax.jit(lambda X, gg, hh: grow_any(
        p, B, X, gg, hh, bag, fmask, iscat, has_cat=False, platform=plat),
        )(Xb, g, h)
    tree = {k: v for k, v in tree.items()}

    def trav_step(s, X, tr):
        lv = tree_leaves({**tr, "value": tr["value"] + s}, X, p.max_depth)
        return lv[0].astype(jnp.float32) * 1e-30
    t_trav = loop_time(trav_step, Xb, tree)
    print(f"traversal (d={p.max_depth}):     {t_trav*1e3:9.1f} ms")

    # score update given leaves
    leaves = jax.jit(lambda X, tr: tree_leaves(tr, X, p.max_depth))(Xb, tree)

    def upd_step(s, lv, val, sc):
        col = jnp.take(sc, 0, axis=1) + (val + s)[lv]
        sc2 = jax.lax.dynamic_update_index_in_dim(sc, col, 0, axis=1)
        return sc2[0, 0] * 1e-30
    sc = jnp.zeros((N, 1), jnp.float32)
    t_upd = loop_time(upd_step, leaves, tree["value"], sc)
    print(f"score update:         {t_upd*1e3:9.1f} ms")

    # full step: grow + score update via the grower's row_leaf (no traversal)
    def full_step(s, X, gg, hh, bb, sc):
        tr = grow_any(p, B, X, gg + s, hh, bb, fmask, iscat,
                      has_cat=False, platform=plat)
        col = jnp.take(sc, 0, axis=1) + tr["value"][tr["row_leaf"]]
        return col[0] * 1e-30
    t_full = loop_time(full_step, Xb, g, h, bag, sc)
    print(f"grow+update(rowleaf): {t_full*1e3:9.1f} ms")
    print(f"  outside-grower:     {(t_full-t_grow)*1e3:9.1f} ms")


if __name__ == "__main__":
    main()
