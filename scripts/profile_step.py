"""Component timing for the 10M-row train step (VERDICT r1 item 2).

r13: rides the canonical harness (engine/probes.timed_fori — K dependent
iterations inside ONE jit, carried whole-unit perturbation, terminal
real fetch, runtime liveness proof).  The r2-era closure constants are
gone: every array — including the grown tree's — rides as a jit
ARGUMENT (the HTTP-413 rule), and the traversal stage perturbs the
THRESHOLDS (the old ``value + s`` perturbation never reached the
traversal, whose output is leaf ids — a dead input the harness would
reject).

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/profile_step.py [rows] [K]
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.config import make_params
from dryad_tpu.engine.grower import grow_any
from dryad_tpu.engine.predict import tree_leaves
from dryad_tpu.engine.probes import timed_fori
from dryad_tpu.objectives import get_objective


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    K = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    F, B = 28, 256
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} features={F} bins={B} reps={K} device={jax.devices()[0]}")

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    y = jnp.asarray((rng.random(N) < 0.5).astype(np.float32))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    bag = jnp.ones((N,), bool)
    fmask = jnp.ones((F,), bool)
    iscat = jnp.zeros((F,), bool)

    p = make_params(dict(objective="binary", num_leaves=255, max_depth=8,
                         growth="depthwise"))
    obj = get_objective(p)

    def show(tag, step, *args):
        ms, spread = timed_fori(step, K, 2, *args, label=tag)
        flag = "  SUSPECT" if spread > 0.05 else ""
        print(f"{tag:22s} {ms:9.1f} ms  spread {spread:.3f}{flag}")
        return ms

    # grad/hess
    def gh_step(s, gg, yy):
        gr, hs = obj.grad_hess_jax(gg + s, yy)
        return s + 1.0, gr[0] + hs[N // 2]

    show("grad/hess:", gh_step, g, y)

    # grower
    def grow_step(s, X, gg, hh, bb, fmask, iscat):
        tr = grow_any(p, B, X, gg + s, hh, bb, fmask, iscat,
                      has_cat=False, platform=plat)
        # whole value table: internal nodes' values stay 0, so a fixed
        # pair of entries can be constant and read as dead
        return s + 1.0, jnp.sum(tr["value"])

    t_grow = show("grower (depthwise):", grow_step, Xb, g, h, bag,
                  fmask, iscat)

    # traversal on a grown tree (tree arrays as jit args) — the
    # perturbation shifts the THRESHOLDS (period 8), so every level's
    # comparisons move and the leaf-id sum shifts far above fp32 ulp
    tree = dict(grow_any(p, B, Xb, g, h, bag, fmask, iscat,
                         has_cat=False, platform=plat))

    def trav_step(s, X, tr):
        si = s.astype(jnp.int32)
        lv = tree_leaves({**tr, "threshold": tr["threshold"] + si % 8},
                         X, p.max_depth)
        return s + 1.0, jnp.sum(lv.astype(jnp.float32))

    show(f"traversal (d={p.max_depth}):", trav_step, Xb, tree)

    # score update given leaves
    leaves = tree_leaves(tree, Xb, p.max_depth)
    sc = jnp.zeros((N, 1), jnp.float32)

    def upd_step(s, lv, val, sc):
        col = jnp.take(sc, 0, axis=1) + (val + s)[lv]
        sc2 = jax.lax.dynamic_update_index_in_dim(sc, col, 0, axis=1)
        return s + 1.0, sc2[0, 0] + sc2[N // 2, 0]

    show("score update:", upd_step, leaves, tree["value"], sc)

    # full step: grow + score update via the grower's row_leaf
    def full_step(s, X, gg, hh, bb, fmask, iscat, sc):
        tr = grow_any(p, B, X, gg + s, hh, bb, fmask, iscat,
                      has_cat=False, platform=plat)
        col = jnp.take(sc, 0, axis=1) + tr["value"][tr["row_leaf"]]
        return s + 1.0, jnp.sum(col) * jnp.float32(1.0 / N)

    t_full = show("grow+update(rowleaf):", full_step, Xb, g, h, bag,
                  fmask, iscat, sc)
    print(f"  outside-grower:     {(t_full - t_grow):9.1f} ms")


if __name__ == "__main__":
    main()
