"""Fine-grained stage breakdown of the segmented histogram pipeline at 10M.

profile_level.py showed the whole build_hist_segmented call dominated by
its surrounding data movement, not the kernel — this script times each
stage (tile plan, row gather, dtype cast, tile transpose, weight packing,
the kernel alone) and the packed single-word sort candidate in isolation.

r13: every stage rides the canonical harness (engine/probes.timed_fori)
with runtime liveness proofs; the r3-era ``block_until_ready`` setup
materializations are gone — device inputs passed as jit arguments are
forced by the harness's warm fetch before any timed wall starts, so no
explicit sync is needed (and ``block_until_ready`` returns instantly
through this tunnel anyway, CLAUDE.md).

Usage: PYTHONPATH=... python scripts/profile_plan.py [rows] [P] [reps]
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.engine.pallas_hist import (
    _TILE_ROWS, _hist_tiles, _pack_weights, _tiles_from_rows, tile_plan,
)
from dryad_tpu.engine.probes import timed_fori


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    K = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    F, B = 28, 256
    T = _TILE_ROWS
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} P={P} reps={K} device={jax.devices()[0]}")

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    sel_np = rng.integers(0, 2 * P, size=N).astype(np.int32)
    sel_np = np.where(sel_np < P, sel_np, P)
    sel = jnp.asarray(sel_np)
    bound = N // 2 + 1

    def show(tag, step, *args):
        ms, spread = timed_fori(step, K, 2, *args, label=tag)
        flag = "  SUSPECT" if spread > 0.05 else ""
        print(f"{tag:42s} {ms:9.1f} ms  spread {spread:.3f}{flag}")

    def rot(sel_, si):
        # rotate the SORT KEY mod P; sentinel P (dropped rows) stays put
        return jnp.where(sel_ < P, (sel_ + si) % P, P)

    # ---- stage 1: plan ------------------------------------------------------
    def argsort_step(s, ss):
        srt = jnp.argsort(rot(ss, s.astype(jnp.int32)), stable=True)
        return s + 1.0, (srt[0] + srt[N // 2]).astype(jnp.float32)

    show("argsort(sel) stable", argsort_step, sel)

    def packed_sort_step(s, ss):
        key = rot(ss, s.astype(jnp.int32)).astype(jnp.uint32) \
            * jnp.uint32(1 << 24) + jnp.arange(N, dtype=jnp.uint32)
        srt = jnp.sort(key)
        return s + 1.0, (srt[0] & jnp.uint32(0xFFFFFF)).astype(jnp.float32) \
            + (srt[N // 2] & jnp.uint32(0xFFFFFF)).astype(jnp.float32)

    show("packed uint32 single sort", packed_sort_step, sel)

    def plan_step(s, ss):
        buf, tl, tf = tile_plan(rot(ss, s.astype(jnp.int32)), N, P, T,
                                rows_bound=bound)
        return s + 1.0, (buf[0] + tl[0]).astype(jnp.float32)

    show("tile_plan total", plan_step, sel)

    buf, tile_leaf, tile_first = tile_plan(sel, N, P, T, rows_bound=bound)
    n_tiles = buf.shape[0] // T

    # ---- stage 2: gathers ---------------------------------------------------
    # the gather INDEX buffer rolls with the carried scalar: same access
    # volume every trip, different addresses — the stage cannot hoist
    # (gather locality measurably does not matter here, CLAUDE.md)
    Xp = jnp.concatenate([Xb, jnp.zeros((1, F), Xb.dtype)])

    def gx_step(s, xp, bb):
        rows = xp[jnp.roll(bb, s.astype(jnp.int32))]
        return s + 1.0, (rows[0, 0] + rows[rows.shape[0] // 2, 0]).astype(
            jnp.float32)

    show("X row gather uint8 (plan buf)", gx_step, Xp, buf)

    buf_sorted = jnp.sort(jnp.where(buf < N, buf, N))
    show("X row gather uint8 (sorted buf)", gx_step, Xp, buf_sorted)

    ghp = jnp.concatenate([jnp.stack([g, h], axis=1),
                           jnp.zeros((1, 2), jnp.float32)])

    def ggh_step(s, gp, bb):
        rows = gp[jnp.roll(bb, s.astype(jnp.int32))]
        return s + 1.0, rows[0, 0] + rows[rows.shape[0] // 2, 0]

    show("g/h two-col gather", ggh_step, ghp, buf)

    # ---- stage 3: cast + tile transpose ------------------------------------
    Xrows = Xp[buf]

    def cast_step(s, xr):
        si = s.astype(jnp.int32)
        # period-8 offset: a period-2 one repeats the same contrib
        # multiset across the liveness seeds at even K (harness-rejected)
        Xt = _tiles_from_rows(xr.astype(jnp.int32) + si % 8, n_tiles, T, B)
        return s + 1.0, Xt.reshape(-1)[0].astype(jnp.float32) \
            + Xt.reshape(-1)[-1].astype(jnp.float32)

    show("astype(i32) + tiles transpose", cast_step, Xrows)

    def t_u8_step(s, xr):
        si = s.astype(jnp.int32)
        xr = xr + (si % 8).astype(jnp.uint8)
        Fc = 32
        fpad = (-F) % Fc
        xrp = jnp.pad(xr, ((0, 0), (0, fpad)))
        Xt = xrp.reshape(n_tiles, T, 1, Fc).transpose(2, 0, 3, 1)
        return s + 1.0, Xt.reshape(-1)[0].astype(jnp.float32) \
            + Xt.reshape(-1)[-1].astype(jnp.float32)

    show("uint8 tiles transpose (no cast)", t_u8_step, Xrows)

    # ---- stage 4: weight packing -------------------------------------------
    ght = ghp[buf].reshape(n_tiles, T, 2)
    valid = (buf < N).reshape(n_tiles, T)

    def packw_step(s, gt, vv):
        Wt = _pack_weights(gt[:, :, 0] + s, gt[:, :, 1], vv)
        return s + 1.0, Wt[0, 0, 0].astype(jnp.float32) \
            + Wt[-1, 0, -1].astype(jnp.float32)

    show("pack_weights (current engine)", packw_step, ght, valid)

    # ---- stage 5: kernel alone ---------------------------------------------
    Xt = _tiles_from_rows(Xp[buf].astype(jnp.int32), n_tiles, T, B)
    Wt = _pack_weights(ght[:, :, 0], ght[:, :, 1], valid)
    tile_skip = jnp.zeros_like(tile_leaf)

    def kern_step(s, xt, wt, tl, tf, sk):
        hist = _hist_tiles(xt, wt + s.astype(jnp.bfloat16), tl,
                           tf, sk, num_cols=P, total_bins=B,
                           num_features=F, platform=plat)
        return s + 1.0, hist[0, 0].sum() + hist[-1, 0].sum()

    show("_hist_tiles kernel alone (i32 tiles)", kern_step, Xt, Wt,
         tile_leaf, tile_first, tile_skip)

    # ---- whole current pipeline for reference ------------------------------
    from dryad_tpu.engine.histogram import build_hist_segmented

    def whole_step(s, Xb, g, h, ss):
        hist = build_hist_segmented(Xb, g, h, rot(ss, s.astype(jnp.int32)),
                                    P, B, rows_per_chunk=65536,
                                    platform=plat, rows_bound=bound)
        return s + 1.0, hist[0, 0].sum()

    show("build_hist_segmented (whole)", whole_step, Xb, g, h, sel)


if __name__ == "__main__":
    main()
