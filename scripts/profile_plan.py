"""Fine-grained stage breakdown of the segmented histogram pipeline at 10M.

profile_level.py showed the whole build_hist_segmented call at ~675 ms with
the Pallas kernel only ~107 ms of it — this script times each surrounding
stage (tile plan, row gather, dtype cast, tile transpose, weight packing)
and candidate replacements (packed single-word sort, uint8 tiles,
unpadded weights, locality-structured gathers) in isolation with the
fori-loop methodology, to pick the round-3 data-movement levers.

Usage: PYTHONPATH=... python scripts/profile_plan.py [rows] [P] [reps]
"""
# dryadlint: disable-file=no-block-until-ready -- r3-era setup materialization, results recorded in BENCH_r03/STATUS; timed regions use the fori doctrine

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.engine.pallas_hist import (
    _TILE_ROWS, _hist_tiles, _pack_weights, _pow2_bins, _tiles_from_rows,
    tile_plan,
)


def main():
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    K = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    F, B = 28, 256
    T = _TILE_ROWS
    rng = np.random.default_rng(0)
    plat = jax.devices()[0].platform
    print(f"rows={N} P={P} reps={K} device={jax.devices()[0]}")

    Xb = jnp.asarray(rng.integers(1, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=N).astype(np.float32))
    sel_np = rng.integers(0, 2 * P, size=N).astype(np.int32)
    sel_np = np.where(sel_np < P, sel_np, P)
    sel = jnp.asarray(sel_np)
    bound = N // 2 + 1

    def loop_time(tag, step, *arrays):
        f = jax.jit(lambda s0, *a: jax.lax.fori_loop(
            0, K, lambda i, s: step(s, *a), s0))
        _ = float(f(jnp.float32(0.0), *arrays))
        t0 = time.perf_counter()
        _ = float(f(jnp.float32(0.0), *arrays))
        dt = (time.perf_counter() - t0) / K
        print(f"{tag:42s} {dt*1e3:9.1f} ms")
        return dt

    j32 = lambda s: (s * 1e-30).astype(jnp.int32)

    # ---- stage 1: plan ------------------------------------------------------
    loop_time("argsort(sel) stable", lambda s, ss: jnp.argsort(
        ss + j32(s), stable=True)[0].astype(jnp.float32) * 1e-30, sel)

    def packed_sort(s, ss):
        key = (ss + j32(s)).astype(jnp.uint32) * jnp.uint32(1 << 24) \
            + jnp.arange(N, dtype=jnp.uint32)
        srt = jnp.sort(key)
        return (srt[0] & jnp.uint32(0xFFFFFF)).astype(jnp.float32) * 1e-30
    loop_time("packed uint32 single sort", packed_sort, sel)

    def plan_only(s, ss):
        buf, tl, tf = tile_plan(ss + j32(s), N, P, T, rows_bound=bound)
        return buf[0].astype(jnp.float32) * 1e-30
    loop_time("tile_plan total", plan_only, sel)

    buf, tile_leaf, tile_first = tile_plan(sel, N, P, T, rows_bound=bound)
    buf = jax.block_until_ready(buf)
    n_tiles = buf.shape[0] // T

    # ---- stage 2: gathers ---------------------------------------------------
    Xp = jnp.concatenate([Xb, jnp.zeros((1, F), Xb.dtype)])

    def gx(s, xp, bb):
        rows = xp[bb + j32(s)]
        return rows[0, 0].astype(jnp.float32) * 1e-30
    loop_time("X row gather uint8 (plan buf)", gx, Xp, buf)

    # same gather with a locality-friendly buf (sorted within = sequential)
    buf_sorted = jnp.sort(jnp.where(buf < N, buf, N))
    loop_time("X row gather uint8 (sorted buf)", gx, Xp, buf_sorted)

    ghp = jnp.concatenate([jnp.stack([g, h], axis=1),
                           jnp.zeros((1, 2), jnp.float32)])

    def ggh(s, gp, bb):
        rows = gp[bb + j32(s)]
        return rows[0, 0] * 1e-30
    loop_time("g/h two-col gather", ggh, ghp, buf)

    # ---- stage 3: cast + tile transpose ------------------------------------
    Xrows = jax.block_until_ready(Xp[buf])

    def cast_t(s, xr):
        Xt = _tiles_from_rows(xr.astype(jnp.int32) + j32(s)[None, None],
                              n_tiles, T, B)
        return Xt[0, 0, 0, 0].astype(jnp.float32) * 1e-30
    loop_time("astype(i32) + tiles transpose", cast_t, Xrows)

    def t_u8(s, xr):
        xr = xr + j32(s).astype(jnp.uint8)[None, None]
        Fc = 32
        fpad = (-F) % Fc
        xrp = jnp.pad(xr, ((0, 0), (0, fpad)))
        Xt = xrp.reshape(n_tiles, T, 1, Fc).transpose(2, 0, 3, 1)
        return Xt[0, 0, 0, 0].astype(jnp.float32) * 1e-30
    loop_time("uint8 tiles transpose (no cast)", t_u8, Xrows)

    # ---- stage 4: weight packing -------------------------------------------
    ght = jax.block_until_ready(ghp[buf].reshape(n_tiles, T, 2))
    valid = (buf < N).reshape(n_tiles, T)

    def packw(s, gt, vv):
        Wt = _pack_weights(gt[:, :, 0] + s, gt[:, :, 1], vv)
        return Wt[0, 0, 0].astype(jnp.float32) * 1e-30
    loop_time("pack_weights (current engine)", packw, ght, valid)

    def packw8(s, gt, vv):
        from dryad_tpu.engine.pallas_hist import _split3
        v = vv.astype(jnp.float32)
        gv = (gt[:, :, 0] + s) * v
        hv = gt[:, :, 1] * v
        w = jnp.stack([*_split3(gv), *_split3(hv), v.astype(jnp.bfloat16)],
                      axis=-2)
        return w[0, 0, 0].astype(jnp.float32) * 1e-30
    loop_time("pack_weights 7-row inline", packw8, ght, valid)

    # ---- stage 5: kernel alone ---------------------------------------------
    Xt = jax.block_until_ready(_tiles_from_rows(Xp[buf].astype(jnp.int32),
                                                n_tiles, T, B))
    Wt = jax.block_until_ready(_pack_weights(ght[:, :, 0], ght[:, :, 1], valid))

    tile_skip = jnp.zeros_like(tile_leaf)

    def kern(s, xt, wt, tl, tf, sk):
        hist = _hist_tiles(xt, wt + s.astype(jnp.bfloat16), tl,
                           tf, sk, num_cols=P, total_bins=B,
                           num_features=F, platform=plat)
        return hist[0, 0, 0, 0] * 1e-30
    loop_time("_hist_tiles kernel alone (i32 tiles)", kern, Xt, Wt,
              tile_leaf, tile_first, tile_skip)

    # ---- whole current pipeline for reference ------------------------------
    from dryad_tpu.engine.histogram import build_hist_segmented

    loop_time("build_hist_segmented (whole)", lambda s, X, gg, hh, ss:
              build_hist_segmented(X, gg + s, hh, ss, P, B,
                                   rows_per_chunk=65536, platform=plat,
                                   rows_bound=bound)[0, 0, 0, 0] * 1e-30,
              Xb, g, h, sel)


if __name__ == "__main__":
    main()
