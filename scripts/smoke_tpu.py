"""On-device smoke checks that forced-CPU CI cannot cover (CLAUDE.md:
"new kernel shapes must be smoke-run on the real device once"; MXU
lowerings are fusion-sensitive, so program-level contracts need a check
on real hardware).

Run after touching histogram builders, growers, or predict (CLAUDE.md —
``--gate`` adds the on-device train-parity pass and exits non-zero on
any drift):
    PYTHONPATH=/root/.axon_site:/root/repo python scripts/smoke_tpu.py --gate
"""

import numpy as np


def smoke_shared_vs_per_class():
    """build_hist_classes per-class slices == build_hist, bitwise, on the
    attached device (the shared multiclass root pass rides on this)."""
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist, build_hist_classes

    rng = np.random.default_rng(53)
    N, F, B, K = 200_000, 28, 256, 7
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, size=(N, K)).astype(np.float32))
    mask = jnp.asarray(rng.random(N) < 0.8)
    shared = np.asarray(build_hist_classes(Xb, g, h, mask, B,
                                           rows_per_chunk=32768))
    for k in range(K):
        single = np.asarray(build_hist(Xb, g[:, k], h[:, k], mask, B,
                                       rows_per_chunk=32768))
        np.testing.assert_array_equal(shared[k], single)
    print(f"shared-vs-per-class roots: bitwise equal for all {K} classes")


def smoke_pallas_vs_xla():
    """Pallas segmented histogram vs the XLA oracle on the device."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist_segmented

    if jax.devices()[0].platform == "cpu":
        print("pallas-vs-xla: skipped (no accelerator attached)")
        return
    rng = np.random.default_rng(59)
    N, F, B, P = 100_000, 12, 64, 16
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, N).astype(np.float32))
    sel = jnp.asarray(rng.integers(0, P + 1, N).astype(np.int32))
    got = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B, backend="pallas"))
    want = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B, backend="xla"))
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-5)
    print("pallas-vs-xla segmented histogram: agree to tolerance")


def smoke_pallas_u16_and_records():
    """Mosaic must lower the uint16 tile load (bins > 256) and the records
    fused-gather path on the real device — interpret-mode CI cannot catch
    lowering failures for these shapes."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist_segmented
    from dryad_tpu.engine.pallas_hist import make_records

    if jax.devices()[0].platform == "cpu":
        print("pallas u16/records: skipped (no accelerator attached)")
        return
    rng = np.random.default_rng(61)
    N, F, B, P = 100_000, 10, 512, 16       # uint16 bins, F % 4 != 0
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint16))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, N).astype(np.float32))
    sel = jnp.asarray(rng.integers(0, P + 1, N).astype(np.int32))
    got = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                          backend="pallas"))
    rec = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                          backend="pallas",
                                          records=make_records(Xb, g, h)))
    np.testing.assert_array_equal(got, rec)  # records path bitwise
    want = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                           backend="xla"))
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-5)
    print("pallas u16 tiles + records path: lower and agree on device")


def smoke_pallas_wide_segment_count():
    """The batched leaf-wise expansion histograms up to P = 2^(D-1)
    segments (8192 at the depth-14 cap) — lower the widest grid on the
    real device once."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist_segmented

    if jax.devices()[0].platform == "cpu":
        print("pallas wide-P: skipped (no accelerator attached)")
        return
    rng = np.random.default_rng(67)
    N, F, B, P = 400_000, 8, 64, 8192
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, N).astype(np.float32))
    sel = jnp.asarray(rng.integers(0, P + 1, N).astype(np.int32))
    got = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                          backend="pallas"))
    want = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                           backend="xla"))
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-5)
    print(f"pallas segmented P={P}: lowers and agrees on device")


def smoke_pallas_natural_order():
    """The natural-order multi-slot kernel (shallow levels, <= 16 slots)
    — new Mosaic shapes (8-row weight block with the slot-id lane row,
    128-row in-VMEM expansion, i==0 output init)."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist_segmented
    from dryad_tpu.engine.pallas_hist import (
        _NAT_DROP, build_hist_nat, natural_tiles,
    )

    if jax.devices()[0].platform == "cpu":
        print("pallas natural-order: skipped (no accelerator attached)")
        return
    rng = np.random.default_rng(71)
    # B=256 exercises the FULL lane budget (Fc*Bp = 8192 -> a (128, 8192)
    # fp32 output block in VMEM), the shape gated production data uses
    N, F, B, P = 150_000, 32, 256, 8
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, N).astype(np.float32))
    sel = jnp.asarray(np.where(rng.integers(0, 2 * P, N) < P,
                               rng.integers(0, P, N), _NAT_DROP)
                      .astype(np.int32))
    got = np.asarray(build_hist_nat(natural_tiles(Xb, B), g, h, sel,
                                    total_bins=B, num_features=F))[:P]
    want = np.asarray(build_hist_segmented(
        Xb, g, h, jnp.minimum(sel, P), P, B, backend="xla"))
    np.testing.assert_array_equal(got[:, 2], want[:, 2])
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-5)
    print("pallas natural-order multi-slot: lowers and agrees on device")


def smoke_leafperm_wired_parity():
    """Wired levelwise grower (leaf-ordered layout carried through the
    level fori state, root-anchored since r10 so EVERY level is wired)
    vs the legacy sort+gather path ON THE REAL DEVICE:
    bitwise-identical tree structures on the tie-free gate fixture, leaf
    values to fp32 tolerance (post-permute layouts regroup per-tile f32
    histogram sums at ulp level — the documented tolerance class).  The
    movement kernel's DMA layout is hardware-sensitive (granule-indexed
    windowed writes, zero-aliased output), so interpret-mode CI cannot
    stand in for this check; any drift here exits 1 like the other
    kernel smokes."""
    import jax
    import numpy as np

    import dryad_tpu as dryad
    from dryad_tpu.config import make_params
    from dryad_tpu.datasets import higgs_like
    from dryad_tpu.engine.levelwise import deep_layout_supported, phase_plan
    from dryad_tpu.engine.train import train_device

    if jax.devices()[0].platform == "cpu":
        print("leafperm wired parity: skipped (no accelerator attached)")
        return
    X, y = higgs_like(50_000, seed=43)
    ds = dryad.Dataset(X, y, max_bins=64)
    base = dict(objective="binary", num_trees=4, num_leaves=128,
                max_bins=64, growth="depthwise", max_depth=8)
    p_w = make_params(base)
    B = int(ds.mapper.total_bins)
    F = ds.X_binned.shape[1]
    assert deep_layout_supported(p_w, F, B, ds.X_binned.dtype.itemsize), \
        "gate fixture no longer admits the wired path"
    d_switch, _, _ = phase_plan(p_w.max_depth, p_w.effective_num_leaves,
                                True)
    assert d_switch < p_w.max_depth, "fixture exercises only one fori phase"
    b_w = train_device(p_w, ds)
    b_l = train_device(make_params(dict(base, deep_layout="legacy")), ds)
    for k in ("feature", "threshold", "left", "right", "is_cat"):
        np.testing.assert_array_equal(
            b_w.tree_arrays()[k], b_l.tree_arrays()[k],
            err_msg=f"wired vs legacy levelwise: {k!r}")
    np.testing.assert_allclose(b_w.value, b_l.value, atol=1e-5)
    print("leafperm wired levelwise: trees bitwise vs legacy on device")


def smoke_leafwise_wired_parity():
    """Layout-wired batched leaf-wise expansion vs the legacy expansion ON
    THE REAL DEVICE: bitwise-identical trees on the tie-free fixture, leaf
    values to fp32 tolerance (same tolerance class as the levelwise smoke
    above — post-permute layouts regroup per-tile f32 partial sums).  The
    leaf-wise wiring's hardware-only risks are its own: heap-node run
    bookkeeping with sentinel HN and run capacity 2^D drive the same DMA
    movement kernel through different scalar prefetch values, which
    interpret-mode CI cannot vouch for."""
    import jax
    import numpy as np

    import dryad_tpu as dryad
    from dryad_tpu.config import make_params
    from dryad_tpu.datasets import higgs_like
    from dryad_tpu.engine.leafwise_fast import (
        leafwise_layout_supported, supports,
    )
    from dryad_tpu.engine.train import train_device

    if jax.devices()[0].platform == "cpu":
        print("leafwise wired parity: skipped (no accelerator attached)")
        return
    X, y = higgs_like(50_000, seed=43)
    ds = dryad.Dataset(X, y, max_bins=64)
    base = dict(objective="binary", num_trees=4, num_leaves=128,
                max_bins=64, growth="leafwise", max_depth=8)
    p_w = make_params(base)
    B = int(ds.mapper.total_bins)
    F = ds.X_binned.shape[1]
    assert supports(p_w, F, B, ds.X_binned.shape[0]), \
        "fixture no longer takes the batched expansion"
    assert leafwise_layout_supported(p_w, F, B, ds.X_binned.dtype.itemsize), \
        "gate fixture no longer admits the wired leaf-wise path"
    b_w = train_device(p_w, ds)
    b_l = train_device(make_params(dict(base, deep_layout="legacy")), ds)
    for k in ("feature", "threshold", "left", "right", "is_cat"):
        np.testing.assert_array_equal(
            b_w.tree_arrays()[k], b_l.tree_arrays()[k],
            err_msg=f"wired vs legacy leafwise expansion: {k!r}")
    np.testing.assert_allclose(b_w.value, b_l.value, atol=1e-5)
    print("leafwise wired expansion: trees bitwise vs legacy on device")


def smoke_hist_reduce_parity():
    """Feature-parallel reduction arm (r16, hist_reduce="feature") vs the
    fused arm ON THE REAL DEVICE: bitwise-identical trees (values
    included) on the tie-free fixture.  A single attached TPU runs the
    DEGENERATE feature program — full slice, packed-record combine, no
    collectives — which is exactly the program piece interpret-mode CI
    cannot vouch for: the sliced scan + bitcast pack/combine lower
    through different fusion shapes than the fused scan, and a lowering
    drift here would flip near-tie argmaxes on device.  (The collective
    halves — reduce-scatter bitwise vs psum slices, the all-gather
    combine — are pinned on the 8-virtual-device mesh in
    tests/test_hist_reduce.py; a multi-chip session should re-run that
    parity against real ICI once available.)"""
    import jax
    import numpy as np

    import dryad_tpu as dryad
    from dryad_tpu.config import make_params
    from dryad_tpu.datasets import higgs_like
    from dryad_tpu.engine.train import train_device

    if jax.devices()[0].platform == "cpu":
        print("hist-reduce parity: skipped (no accelerator attached)")
        return
    X, y = higgs_like(50_000, seed=47)
    ds = dryad.Dataset(X, y, max_bins=64)
    for growth, depth in (("depthwise", 8), ("leafwise", 8)):
        base = dict(objective="binary", num_trees=4, num_leaves=128,
                    max_bins=64, growth=growth, max_depth=depth)
        b_f = train_device(make_params(dict(base, hist_reduce="fused")), ds)
        b_x = train_device(make_params(dict(base, hist_reduce="feature")),
                           ds)
        for k in ("feature", "threshold", "left", "right", "is_cat",
                  "value", "gain"):
            np.testing.assert_array_equal(
                b_f.tree_arrays()[k], b_x.tree_arrays()[k],
                err_msg=f"hist_reduce fused vs feature ({growth}): {k!r}")
    print("hist-reduce fused vs feature: trees bitwise on device "
          "(both growers, degenerate 1-shard feature program)")


def smoke_predict_packed_parity():
    """Packed node-word traversal (r21) vs legacy ON THE REAL DEVICE:
    bitwise-identical raw scores across numeric/missing, categorical and
    multiclass models.  Interpret-mode CI pins the same identity on the
    CPU backend and the 8-virtual-device mesh; what only an attached TPU
    can vouch for is the LOWERING of the packed body — the uint32 limb
    shifts/masks and the single node-table gather fuse differently than
    the legacy seven-array reads, and a drift there would flip predict
    bits (the serve registry stages packed by default, so every fleet
    replica runs this program)."""
    import jax
    import numpy as np

    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like
    from dryad_tpu.engine.predict import stage_trees, staged_layout

    if jax.devices()[0].platform == "cpu":
        print("packed predict parity: skipped (no accelerator attached)")
        return
    X, y = higgs_like(20_000, seed=23)
    X = X.copy()
    X[::7, 3] = np.nan    # exercise default_left on device
    configs = [
        ("binary", dict(objective="binary", num_trees=6, num_leaves=31,
                        max_bins=64)),
        ("multiclass", dict(objective="multiclass", num_class=3,
                            num_trees=4, num_leaves=15, max_bins=64)),
    ]
    for name, p in configs:
        yy = (y if name == "binary"
              else (np.abs(X[:, 0]) * 7).astype(np.int32) % 3)
        ds = dryad.Dataset(X, yy, max_bins=64)
        booster = dryad.train(p, ds, backend="tpu")
        assert staged_layout(stage_trees(booster)[0]) == "packed", name
        booster.params = booster.params.replace(predict_layout="legacy")
        legacy = booster.predict_binned(ds.X_binned, raw_score=True,
                                        backend="tpu")
        booster.params = booster.params.replace(predict_layout="packed")
        packed = booster.predict_binned(ds.X_binned, raw_score=True,
                                        backend="tpu")
        np.testing.assert_array_equal(
            np.asarray(legacy), np.asarray(packed),
            err_msg=f"{name}: packed vs legacy predict on device")
    print(f"packed predict parity on device: {len(configs)} models — "
          "packed ≡ legacy bitwise (one node-word gather per level)")


def smoke_stage_profiler():
    """First per-stage device breakdown (r13): run the cheap tier of the
    stage-probe registry (engine/probes) on the attached device, each
    liveness-proven at runtime — a dead/hoisted stage raises instead of
    recording a 2x-fast lie.  Alongside the wired/legacy bench pairs this
    gives the next TPU-attached session its stage-level evidence in one
    command (ROADMAP standing satellite)."""
    import jax

    from dryad_tpu.engine import probes

    if jax.devices()[0].platform == "cpu":
        print("stage profiler: skipped (no accelerator attached)")
        return
    for name in probes.SMOKE_PROBES:
        r = probes.run_probe(name, rows=200_000, K=3, reps=2)
        flag = "  SUSPECT" if r["spread"] > probes.SPREAD_SUSPECT else ""
        print(f"stage {name}: {r['ms']:.2f} ms spread {r['spread']:.3f} "
              f"(liveness-proven){flag}")


def smoke_train_parity():
    """Tiny end-to-end train on the ATTACHED device vs the CPU reference:
    identical tree structures and bitwise same-booster predict (the
    CLAUDE.md parity invariant).  Covers the chunked device program (no
    callback), bagging, and the leaf-renewal sort in one pass — a TPU-only
    lowering regression in any of them lands here instead of surfacing as
    a silently wrong bench number (VERDICT r4 weak #4)."""
    import dryad_tpu as dryad
    from dryad_tpu.datasets import higgs_like

    X, y = higgs_like(20_000, seed=31)
    ds = dryad.Dataset(X, y, max_bins=64)
    configs = [
        ("gbdt", dict(objective="binary", num_trees=8, num_leaves=31,
                      max_bins=64)),
        ("bagged", dict(objective="binary", num_trees=6, num_leaves=15,
                        max_bins=64, subsample=0.7, colsample=0.8)),
        ("l1-renewal", dict(objective="l1", num_trees=6, num_leaves=15,
                            max_bins=64)),
    ]
    for name, p in configs:
        bc = dryad.train(p, ds, backend="cpu")
        bt = dryad.train(p, ds, backend="tpu")
        np.testing.assert_array_equal(bc.feature, bt.feature,
                                      err_msg=f"{name}: tree structures")
        np.testing.assert_array_equal(bc.threshold, bt.threshold,
                                      err_msg=f"{name}: thresholds")
        pc = bc.predict_binned(ds.X_binned, raw_score=True, backend="cpu")
        pt = bc.predict_binned(ds.X_binned, raw_score=True, backend="tpu")
        np.testing.assert_array_equal(pc, np.asarray(pt),
                                      err_msg=f"{name}: predict bit-identity")
    print(f"train parity on device: {len(configs)} configs — structures "
          "identical, predict bitwise")


_ALL_SMOKES = [
    smoke_shared_vs_per_class,
    smoke_pallas_vs_xla,
    smoke_pallas_u16_and_records,
    smoke_pallas_wide_segment_count,
    smoke_pallas_natural_order,
    smoke_leafperm_wired_parity,
    smoke_leafwise_wired_parity,
    smoke_hist_reduce_parity,
    smoke_predict_packed_parity,
    smoke_stage_profiler,
]


def main(argv=None) -> int:
    """``--gate``: the driver-runnable on-device check (CLAUDE.md) — all
    kernel smokes + the train-parity pass; every failure is reported and
    the exit code is non-zero on ANY drift."""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--gate", action="store_true",
                    help="run all smokes + train parity; exit 1 on drift")
    args = ap.parse_args(argv)
    smokes = list(_ALL_SMOKES) + ([smoke_train_parity] if args.gate else [])
    failed = []
    for fn in smokes:
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — aggregate, report, exit 1
            failed.append((fn.__name__, e))
            print(f"FAIL {fn.__name__}: {e}")
    if failed:
        print(f"GATE FAILED: {len(failed)}/{len(smokes)} smokes drifted")
        return 1
    print(f"GATE OK: {len(smokes)} smokes clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
