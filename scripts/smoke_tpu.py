"""On-device smoke checks that forced-CPU CI cannot cover (CLAUDE.md:
"new kernel shapes must be smoke-run on the real device once"; MXU
lowerings are fusion-sensitive, so program-level contracts need a check
on real hardware).

Run after touching histogram builders or the Pallas kernel:
    PYTHONPATH=/root/.axon_site:/root/repo python scripts/smoke_tpu.py
"""

import numpy as np


def smoke_shared_vs_per_class():
    """build_hist_classes per-class slices == build_hist, bitwise, on the
    attached device (the shared multiclass root pass rides on this)."""
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist, build_hist_classes

    rng = np.random.default_rng(53)
    N, F, B, K = 200_000, 28, 256, 7
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, size=(N, K)).astype(np.float32))
    mask = jnp.asarray(rng.random(N) < 0.8)
    shared = np.asarray(build_hist_classes(Xb, g, h, mask, B,
                                           rows_per_chunk=32768))
    for k in range(K):
        single = np.asarray(build_hist(Xb, g[:, k], h[:, k], mask, B,
                                       rows_per_chunk=32768))
        np.testing.assert_array_equal(shared[k], single)
    print(f"shared-vs-per-class roots: bitwise equal for all {K} classes")


def smoke_pallas_vs_xla():
    """Pallas segmented histogram vs the XLA oracle on the device."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist_segmented

    if jax.devices()[0].platform == "cpu":
        print("pallas-vs-xla: skipped (no accelerator attached)")
        return
    rng = np.random.default_rng(59)
    N, F, B, P = 100_000, 12, 64, 16
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, N).astype(np.float32))
    sel = jnp.asarray(rng.integers(0, P + 1, N).astype(np.int32))
    got = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B, backend="pallas"))
    want = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B, backend="xla"))
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-5)
    print("pallas-vs-xla segmented histogram: agree to tolerance")


def smoke_pallas_u16_and_records():
    """Mosaic must lower the uint16 tile load (bins > 256) and the records
    fused-gather path on the real device — interpret-mode CI cannot catch
    lowering failures for these shapes."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist_segmented
    from dryad_tpu.engine.pallas_hist import make_records

    if jax.devices()[0].platform == "cpu":
        print("pallas u16/records: skipped (no accelerator attached)")
        return
    rng = np.random.default_rng(61)
    N, F, B, P = 100_000, 10, 512, 16       # uint16 bins, F % 4 != 0
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint16))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, N).astype(np.float32))
    sel = jnp.asarray(rng.integers(0, P + 1, N).astype(np.int32))
    got = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                          backend="pallas"))
    rec = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                          backend="pallas",
                                          records=make_records(Xb, g, h)))
    np.testing.assert_array_equal(got, rec)  # records path bitwise
    want = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                           backend="xla"))
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-5)
    print("pallas u16 tiles + records path: lower and agree on device")


def smoke_pallas_wide_segment_count():
    """The batched leaf-wise expansion histograms up to P = 2^(D-1)
    segments (8192 at the depth-14 cap) — lower the widest grid on the
    real device once."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist_segmented

    if jax.devices()[0].platform == "cpu":
        print("pallas wide-P: skipped (no accelerator attached)")
        return
    rng = np.random.default_rng(67)
    N, F, B, P = 400_000, 8, 64, 8192
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, N).astype(np.float32))
    sel = jnp.asarray(rng.integers(0, P + 1, N).astype(np.int32))
    got = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                          backend="pallas"))
    want = np.asarray(build_hist_segmented(Xb, g, h, sel, P, B,
                                           backend="xla"))
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-5)
    print(f"pallas segmented P={P}: lowers and agrees on device")


def smoke_pallas_natural_order():
    """The natural-order multi-slot kernel (shallow levels, <= 16 slots)
    — new Mosaic shapes (8-row weight block with the slot-id lane row,
    128-row in-VMEM expansion, i==0 output init)."""
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.histogram import build_hist_segmented
    from dryad_tpu.engine.pallas_hist import (
        _NAT_DROP, build_hist_nat, natural_tiles,
    )

    if jax.devices()[0].platform == "cpu":
        print("pallas natural-order: skipped (no accelerator attached)")
        return
    rng = np.random.default_rng(71)
    # B=256 exercises the FULL lane budget (Fc*Bp = 8192 -> a (128, 8192)
    # fp32 output block in VMEM), the shape gated production data uses
    N, F, B, P = 150_000, 32, 256, 8
    Xb = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, N).astype(np.float32))
    sel = jnp.asarray(np.where(rng.integers(0, 2 * P, N) < P,
                               rng.integers(0, P, N), _NAT_DROP)
                      .astype(np.int32))
    got = np.asarray(build_hist_nat(natural_tiles(Xb, B), g, h, sel,
                                    total_bins=B, num_features=F))[:P]
    want = np.asarray(build_hist_segmented(
        Xb, g, h, jnp.minimum(sel, P), P, B, backend="xla"))
    np.testing.assert_array_equal(got[:, 2], want[:, 2])
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-5)
    print("pallas natural-order multi-slot: lowers and agrees on device")


if __name__ == "__main__":
    smoke_shared_vs_per_class()
    smoke_pallas_vs_xla()
    smoke_pallas_u16_and_records()
    smoke_pallas_wide_segment_count()
    smoke_pallas_natural_order()
