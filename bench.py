"""Headline benchmark: boosting iters/sec on the Higgs-shaped config
(BASELINE.json:2 — "boosting iters/sec + final AUC, Higgs, depth-8").

Runs the device trainer on the attached accelerator (TPU under the axon
tunnel; CPU otherwise), measures steady-state boosting iterations/second
after a warm-up that absorbs jit compilation, and prints ONE JSON line.

``vs_baseline`` is the speedup over the CPU canonical reference trainer on
an identical (sub-sampled) config — no published Dryad-on-A100 number exists
in this environment (BASELINE.md), so the CPU reference is the recorded
baseline the driver tracks across rounds.

The north-star metric (BASELINE.json:2) is defined at Higgs-10M scale, so
the same line also carries ``iters_per_sec_10m``: the warm MARGINAL
iteration cost at 10,000,000 rows measured as the (8-tree − 2-tree) warm
wall delta / 6 — fixed per-run costs (compile, upload, fetch) cancel in
the difference, leaving the steady-state per-iteration cost the asymptote
is made of.  Set BENCH_10M=0 to skip (~5 min: two compiles + four runs).

Env knobs: BENCH_ROWS (default 200000), BENCH_TREES (default 50),
BENCH_LEAVES (default 255), BENCH_GROWTH (default depthwise),
BENCH_10M (default 1), BENCH_DEEP / BENCH_LEAFWISE / BENCH_WIDE /
BENCH_PREDICT (default 1 — the wired-vs-legacy level probes, the r16
Epsilon-shaped hist_reduce fused-vs-feature scan probe, and the r21
packed-vs-legacy predict traversal probe).

r9 adds ``obs_overhead_ms``/``obs_overhead_pct``: instrumented-vs-
disabled telemetry registry (dryad_tpu/obs) on the 200k series, min-of-3
spread-checked arms — the zero-cost-when-disabled contract as a measured
number (acceptance: <= 2%).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


# The timed-fori scaffolding (K dependent reps inside ONE jit, carried
# perturbation, terminal REAL fetch, min-of-reps + spread) lives in
# engine/probes.timed_fori since r13 — the canonical harness, which adds
# the runtime LIVENESS PROOF: each probe runs at two perturbation seeds
# before timing and a dead/hoisted stage raises instead of measuring a
# lie.  dryadlint's ``unharnessed-timed-fori`` rule keeps hand copies of
# the discipline from growing back here.  (Imported inside the probes —
# bench.py defers every dryad/jax import past main()'s env setup.)


def deep_level_probe(rows: int, P: int = 64, B: int = 256,
                     F: int = 28, K: int = 3, reps: int = 2) -> dict | None:
    """Per-arm wall of ONE deep level's data movement + smaller-children
    histogram: the wired leaf-ordered-layout pipeline (level_moves ->
    permute_records -> hist_from_layout) vs the legacy plan pipeline
    (packed aligned sort -> record gather -> hist_from_plan).  Both arms
    exclude the natural-order partition the two paths share, so the
    numbers isolate exactly the stage the r6 wiring replaced.

    CLAUDE.md methodology: K dependent reps inside ONE jit; the
    perturbation reaches every stage (the wired arm's SIDE threshold and
    the legacy arm's SORT KEY rotate with the carried scalar, advanced by
    whole units); ends with a REAL host fetch (block_until_ready returns
    instantly through this tunnel).  Returns None on CPU — interpret-mode
    kernel walls are meaningless.
    """
    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform == "cpu":
        return None
    from dryad_tpu.engine import leafperm, pallas_hist
    from dryad_tpu.engine.histogram import build_hist_segmented
    from dryad_tpu.engine.probes import timed_fori

    T = leafperm._TILE_ROWS
    rng = np.random.default_rng(5)
    Xb = jnp.asarray(rng.integers(0, B, (rows, F), dtype=np.uint8))
    g_np = rng.normal(size=rows).astype(np.float32)
    g = jnp.asarray(g_np)
    h = jnp.asarray(rng.uniform(0.1, 1, rows).astype(np.float32))
    slot_np = rng.integers(0, P, rows).astype(np.int32)
    half_np = rng.random(rows) < 0.5

    # ---- wired arm --------------------------------------------------------
    rec_nat = leafperm.make_layout_records(Xb, g, h)
    n_buf = leafperm.wired_tiles_bound(-(-rows // T), P)
    # the histogrammed selection (all LEFT children below) must provably
    # cover < half the rows for the shared half-bound: thresholds stay
    # strictly negative so P(g <= thr) < 0.5 with ~sqrt(N) margin
    n_sel = leafperm.wired_sel_tiles_bound(-(-rows // T), n_buf, P,
                                           half=True)
    rec_lay, tile_run, run_slot = leafperm.initial_layout(
        rec_nat, jnp.asarray(slot_np), jnp.ones((P,), bool), P, n_buf)

    def wired_step(s, rec_lay, tile_run, run_slot):
        g_l, _, valid, _ = leafperm.unpack_layout_records(
            rec_lay, F, jnp.uint8)
        smod = s - jnp.floor(s / 8) * 8          # live: period-8 walk (a
        # period that fits inside K would repeat the same contrib multiset
        # at both liveness seeds — the harness would reject it as dead)
        # the grower's full per-level route rides in the arm: the
        # run->packed-word compose + ONE per-row small-table gather (the
        # dominant wired-only bookkeeping cost) and advance_runs — the
        # probe must price the level the GROWER pays, not just the kernel.
        # The run table is ROLLED by the carried scalar (whole units) and
        # the gathered word steps the side threshold: a non-carried table
        # would let XLA's while-loop LICM hoist the whole route gather out
        # of the timed fori (the CLAUDE.md dead-input trap, r10)
        si = s.astype(jnp.int32)
        rs_i = jnp.roll(run_slot, si)
        w0 = ((jnp.uint32(1) << 31)
              | jnp.arange(P, dtype=jnp.uint32))   # per-run packed words
        tab = jnp.concatenate([w0, jnp.zeros((1,), jnp.uint32)])
        rr = tab[jnp.minimum(rs_i, P)][
            jnp.repeat(tile_run, T)]               # composed row gather
        live_bit = (rr >> 31) != 0
        # per-run threshold steps stay strictly negative (half bound)
        thr = -0.45 + 0.025 * smod + 0.1 * (rr & 1).astype(jnp.float32)
        side = jnp.where(valid & live_bit,
                         (g_l > thr).astype(jnp.int32), 2)
        pos, dstl, dstr, base_l, base_r, _ = leafperm.level_moves(
            tile_run, side, P)
        out = leafperm.permute_records(rec_lay, pos, dstl, dstr, n_buf)
        run_do = (rr[:: leafperm._TILE_ROWS][:P] & 1) == 0  # ~half split
        tr2, rs2 = leafperm.advance_runs(run_slot, run_do[:P],
                                         jnp.arange(P, dtype=jnp.int32),
                                         base_l, base_r, n_buf)
        hist = leafperm.hist_from_layout(
            out, base_l[:P], base_l[1:] - base_l[:-1], P, B, F,
            jnp.uint8, n_sel)
        # every stage feeds the contrib at FULL magnitude — the harness
        # accumulates it apart from s, so no 1e-20 scaling (under which
        # the liveness signal would round away below fp32 resolution)
        return s + 1.0, (out[0, 0].astype(jnp.float32)
                         + hist[0, 0].sum()
                         + (tr2[0] + rs2[0] + base_l[P])
                         .astype(jnp.float32))

    t_wired, sp_wired = timed_fori(wired_step, K, reps,
                                   rec_lay, tile_run, run_slot,
                                   label="deep_level_wired")

    # ---- legacy arm -------------------------------------------------------
    records = pallas_hist.make_records(Xb, g, h)
    cnt0 = np.bincount(slot_np[half_np], minlength=P).astype(np.int32)
    sel0 = jnp.asarray(np.where(half_np, slot_np, P).astype(np.int32))
    cnt0_d = jnp.asarray(cnt0)

    # rows_bound must be MATHEMATICALLY guaranteed (tile_plan contract —
    # rows beyond it drop silently): the perturbation below only rotates
    # slot ids, never the selected SET, so the exact draw count is the
    # bound (a binomial ~N/2 draw can exceed N//2 itself)
    sel_rows = int(cnt0.sum())

    # Xb/g/h ride as ARGUMENTS, never closures: closure arrays lower as
    # jit constants and the tunneled remote compile rejects programs with
    # >~tens-of-MB constants (HTTP 413 — CLAUDE.md lowering facts; at 10M
    # rows the three arrays are ~360 MB)
    def legacy_step(s, sel0, cnt0_d, records, Xb, g, h):
        si = s.astype(jnp.int32)
        sel = jnp.where(sel0 < P, (sel0 + si) % P, P)  # perturb the SORT KEY
        cnt = jnp.roll(cnt0_d, si)               # exact counts, rotated too
        hist = build_hist_segmented(
            Xb, g, h, sel, P, B, backend="pallas",
            rows_bound=sel_rows, records=records, sel_counts=cnt)
        return s + 1.0, hist[0, 0, 0, 0]

    t_legacy, sp_legacy = timed_fori(legacy_step, K, reps,
                                     sel0, cnt0_d, records, Xb, g, h,
                                     label="deep_level_legacy")
    return {
        "deep_level_ms_wired": round(t_wired, 1),
        "deep_level_ms_legacy": round(t_legacy, 1),
        "deep_level_spread_wired": round(sp_wired, 3),
        "deep_level_spread_legacy": round(sp_legacy, 3),
        "deep_level_rows": rows,
    }


def leafwise_level_probe(rows: int, D: int = 7, B: int = 256,
                         F: int = 28, K: int = 3,
                         reps: int = 2) -> dict | None:
    """Per-arm wall of ONE batched leaf-wise EXPANSION level's data
    movement + smaller-children histogram, wired vs legacy — the r10
    counterpart of ``deep_level_probe`` for the second consumer of the
    layout.  The expansion differs from a levelwise deep level in its run
    bookkeeping (heap-node ids with sentinel HN, run capacity NR = 2^D =
    twice the candidate width, hence twice the mandated empty segments),
    so the wired arm prices exactly the level the expansion fori pays at
    its widest width P = 2^(D-1); the legacy arm is the per-level
    sort+gather segmented pass the wiring deletes.

    Same CLAUDE.md timed-fori rules as deep_level_probe (the perturbation
    rotates the wired SIDE threshold / the legacy SORT KEY by whole
    units; every timed program ends in a real host fetch), plus per-arm
    spread: each arm runs ``reps`` timed programs, reports the MIN (tunnel
    stalls only ever add time) and max/min-1 as the suspect-capture
    signal (>5% = suspect, CLAUDE.md).  None on CPU — interpret-mode
    kernel walls are meaningless."""
    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform == "cpu":
        return None
    from dryad_tpu.engine import leafperm, pallas_hist
    from dryad_tpu.engine.histogram import build_hist_segmented
    from dryad_tpu.engine.probes import timed_fori

    T = leafperm._TILE_ROWS
    P = 1 << (D - 1)                  # widest expansion level
    NR = 1 << D                       # run capacity (leafwise wiring)
    HN = 1 << (D + 1)                 # heap sentinel
    rng = np.random.default_rng(17)
    Xb = jnp.asarray(rng.integers(0, B, (rows, F), dtype=np.uint8))
    g = jnp.asarray(rng.normal(size=rows).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, rows).astype(np.float32))
    slot_np = rng.integers(0, P, rows).astype(np.int32)
    half_np = rng.random(rows) < 0.5

    # ---- wired arm: the expansion level at heap-id bookkeeping ------------
    rec_nat = leafperm.make_layout_records(Xb, g, h)
    n_buf = leafperm.wired_tiles_bound(-(-rows // T), NR)
    # thresholds stay strictly negative so the histogrammed left children
    # provably cover < half the rows (shared half-bound rule)
    n_sel = leafperm.wired_sel_tiles_bound(-(-rows // T), n_buf, P,
                                           half=True)
    rec_lay, tile_run, run_slot_p = leafperm.initial_layout(
        rec_nat, jnp.asarray(slot_np), jnp.ones((P,), bool), P, n_buf)
    # lift the (P,) slot table to the expansion's (NR,) heap-node table:
    # level-(D-1) nodes are [P, 2P), unused run indices hold sentinel HN
    run_slot = jnp.concatenate([
        jnp.where(run_slot_p < P, P + run_slot_p, HN),
        jnp.full((NR - P,), HN, jnp.int32)]).astype(jnp.int32)

    def wired_step(s, rec_lay, tile_run, run_slot):
        g_l, _, valid, _ = leafperm.unpack_layout_records(
            rec_lay, F, jnp.uint8)
        smod = s - jnp.floor(s / 8) * 8        # live: period-8 walk (see
        # deep_level_probe — a period inside K repeats the contrib
        # multiset across the liveness seeds and reads as dead)
        # the grower's per-level route: node -> packed word composed at the
        # (HN+1,) level, then ONE per-row small-table gather + advance_runs.
        # Table ROLLED by the carried scalar and the gathered word steps
        # the side threshold — a non-carried table would let while-loop
        # LICM hoist the route gather out of the timed fori (the CLAUDE.md
        # dead-input trap, r10; same fix as deep_level_probe)
        si = s.astype(jnp.int32)
        rs_i = jnp.roll(run_slot, si)
        w0 = ((jnp.uint32(1) << 31)
              | jnp.arange(HN + 1, dtype=jnp.uint32))
        rr = w0[jnp.minimum(rs_i, HN)][
            jnp.repeat(tile_run, T)]            # composed row gather
        live_bit = (rr >> 31) != 0
        # per-run threshold steps stay strictly negative (half bound)
        thr = -0.45 + 0.025 * smod + 0.1 * (rr & 1).astype(jnp.float32)
        side = jnp.where(valid & live_bit,
                         (g_l > thr).astype(jnp.int32), 2)
        pos, dstl, dstr, base_l, base_r, _ = leafperm.level_moves(
            tile_run, side, NR)
        out = leafperm.permute_records(rec_lay, pos, dstl, dstr, n_buf)
        run_do = ((rs_i & 1) == 0) & (rs_i < HN)           # ~half split
        ns2 = jnp.where(run_do, 2 * rs_i, rs_i)
        tr2, rs2 = leafperm.advance_runs(ns2, run_do, 2 * rs_i + 1,
                                         base_l, base_r, n_buf,
                                         sentinel=HN)
        hist = leafperm.hist_from_layout(
            out, base_l[:P], base_l[1:P + 1] - base_l[:P], P, B, F,
            jnp.uint8, n_sel)
        # full-magnitude contrib, accumulated apart from s by the harness
        # (the retired s + x*1e-20 idiom could not carry a liveness signal)
        return s + 1.0, (out[0, 0].astype(jnp.float32)
                         + hist[0, 0].sum()
                         + (tr2[0] + rs2[0] + base_l[P])
                         .astype(jnp.float32))

    t_wired, sp_wired = timed_fori(wired_step, K, reps,
                                   rec_lay, tile_run, run_slot,
                                   label="leafwise_level_wired")

    # ---- legacy arm: the per-expansion-level sort+gather pass -------------
    records = pallas_hist.make_records(Xb, g, h)
    cnt0 = np.bincount(slot_np[half_np], minlength=P).astype(np.int32)
    sel0 = jnp.asarray(np.where(half_np, slot_np, P).astype(np.int32))
    cnt0_d = jnp.asarray(cnt0)
    sel_rows = int(cnt0.sum())       # exact draw count (tile_plan contract)

    # Xb/g/h as ARGUMENTS, never closures (HTTP 413 jit-constant rule —
    # see deep_level_probe's legacy arm)
    def legacy_step(s, sel0, cnt0_d, records, Xb, g, h):
        si = s.astype(jnp.int32)
        sel = jnp.where(sel0 < P, (sel0 + si) % P, P)  # perturb the SORT KEY
        cnt = jnp.roll(cnt0_d, si)
        hist = build_hist_segmented(
            Xb, g, h, sel, P, B, backend="pallas",
            rows_bound=sel_rows, records=records, sel_counts=cnt)
        return s + 1.0, hist[0, 0, 0, 0]

    t_legacy, sp_legacy = timed_fori(legacy_step, K, reps,
                                     sel0, cnt0_d, records, Xb, g, h,
                                     label="leafwise_level_legacy")
    return {
        "leafwise_level_ms_wired": round(t_wired, 1),
        "leafwise_level_ms_legacy": round(t_legacy, 1),
        "leafwise_level_spread_wired": round(sp_wired, 3),
        "leafwise_level_spread_legacy": round(sp_legacy, 3),
        "leafwise_level_rows": rows,
    }


def hist_reduce_probe(rows: int = 400_000, F: int = 2000, B: int = 256,
                      P: int = 32, K: int = 3, reps: int = 2) -> dict | None:
    """Epsilon-shaped (2000 x 256) per-arm wall of the split-finding stage
    the r16 feature-parallel reduction changes: the fused full-F scan
    (the split_scan registry probe at this width — each device scans every
    feature) vs the feature arm's per-device stage (the hist_reduce
    registry probe: sliced F/8 scan + packed record combine).  Both ride
    ``engine/probes`` — liveness-proven timed-fori programs with the
    histogram arrays as jit ARGUMENTS and a scale-class perturbation that
    must reach the gains (run_probe applies the harness rules).  The wire
    win itself ((n-1)/n of the reduced payload) is static accounting
    (train._comm_stats, jaxpr-census-verified), not a single-device wall
    — these fields track the compute side of the trade across rounds.
    None on CPU — Epsilon-width scans take minutes there and the walls
    mean nothing."""
    import jax

    if jax.devices()[0].platform == "cpu":
        return None
    from dryad_tpu.engine.probes import run_probe

    fused = run_probe("split_scan", rows=rows, K=K, reps=reps,
                      num_features=F, total_bins=B, num_slots=P)
    feat = run_probe("hist_reduce", rows=rows, K=K, reps=reps,
                     num_features=F, total_bins=B, num_slots=P)
    return {
        "hist_reduce_ms_fused": round(fused["ms"], 2),
        "hist_reduce_ms_feature": round(feat["ms"], 2),
        "hist_reduce_spread_fused": round(fused["spread"], 3),
        "hist_reduce_spread_feature": round(feat["spread"], 3),
        "hist_reduce_features": F,
        "hist_reduce_bins": B,
        "hist_reduce_slots": P,
    }


def predict_layout_probe(rows: int = 1_000_000, K: int = 4,
                         reps: int = 2) -> dict | None:
    """Per-tree traversal wall per predict table layout (r21): the legacy
    structure-of-arrays arm (~7 small-table gathers per level) vs the
    packed node-word arm (ONE (M,2)-uint32 limb-table gather per level) on
    the same synthetic depth-6 tree.  Gather cost on TPU is per-ACCESS,
    so the packed/legacy gap here is the real per-level lookup saving the
    jaxpr census pins statically (18 vs 126 trip-weighted table gathers).
    Both arms ride ``engine/probes`` liveness-proven timed-fori programs;
    fields are us/row so serve-side percentiles have a unit to compare
    against.  None on CPU."""
    import jax

    if jax.devices()[0].platform == "cpu":
        return None
    from dryad_tpu.engine.probes import run_probe

    legacy = run_probe("predict_traversal", rows=rows, K=K, reps=reps)
    packed = run_probe("predict_traversal_packed", rows=rows, K=K, reps=reps)
    return {
        "predict_us_per_row_packed": round(packed["ms"] * 1000.0 / rows, 4),
        "predict_us_per_row_legacy": round(legacy["ms"] * 1000.0 / rows, 4),
        "predict_spread_packed": round(packed["spread"], 3),
        "predict_spread_legacy": round(legacy["spread"], 3),
        "predict_probe_rows": rows,
    }


def main() -> None:
    # Pin the device-resident chunked boosting path: the bench estimates the
    # LONG-run (500-tree-scale) steady state from short timed runs, and the
    # compile-vs-work heuristic (train.py, VERDICT r3 #5) would route runs
    # this short to per-iteration dispatch — a different program than the
    # one a long run uses.  Forcing the chunk path keeps the marginal arms
    # measuring the steady state the metric is defined on (and keeps the
    # BENCH series comparable with rounds 1-3, which always chunked here).
    os.environ.setdefault("DRYAD_CHUNK", "1")
    rows = int(os.environ.get("BENCH_ROWS", 200_000))
    # 50 trees: long enough that the steady-state chunked pipeline dominates
    # (20 trees left ~30% of wall in fixed per-run costs), short enough for
    # a ~2-minute bench incl. the identical-shape warmup run
    trees = int(os.environ.get("BENCH_TREES", 50))
    leaves = int(os.environ.get("BENCH_LEAVES", 255))
    growth = os.environ.get("BENCH_GROWTH", "depthwise")


    import dryad_tpu as dryad
    from dryad_tpu.config import make_params
    from dryad_tpu.datasets import higgs_like
    from dryad_tpu.metrics import auc

    X, y = higgs_like(rows, seed=7)
    ds = dryad.Dataset(X, y, max_bins=256)
    params = make_params(dict(
        objective="binary", num_trees=trees, num_leaves=leaves,
        max_depth=8, growth=growth, max_bins=256, learning_rate=0.1,
    ))

    from dryad_tpu.engine.train import train_device

    # iterations dispatch asynchronously (no per-iteration device sync), so
    # per-callback deltas are meaningless — time the full run wall-to-wall
    # (train_device's final fetch blocks on the whole pipeline) and subtract
    # a warmup run that absorbs jit compilation.
    # warmup with identical shapes (the output tree table is (num_trees, M)
    # — a different tree count would recompile in the timed run)
    train_device(params, ds)

    t0 = time.perf_counter()
    booster = train_device(params, ds)
    total_time = time.perf_counter() - t0
    iters_per_sec = trees / total_time

    train_auc = auc(y, booster.predict(X, raw_score=True))

    # CPU-reference baseline on a subsample, scaled to the full row count
    # (histogram work is linear in rows; SURVEY.md §3 hot loops)
    base_rows = min(rows, 50_000)
    Xs = X[:base_rows]
    ys = y[:base_rows]
    ds_s = dryad.Dataset(Xs, ys, max_bins=256)
    cpu_params = params.replace(num_trees=2)
    t0 = time.perf_counter()
    dryad.train(cpu_params, ds_s, backend="cpu")
    cpu_time = (time.perf_counter() - t0) / 2 * (rows / base_rows)
    vs_baseline = iters_per_sec * cpu_time  # = cpu_time_per_iter / dev_time_per_iter

    out = {
        "metric": f"boosting_iters_per_sec_higgs{rows // 1000}k_depth8_{leaves}leaves",
        "value": round(iters_per_sec, 3),
        "unit": "iters/s",
        "vs_baseline": round(vs_baseline, 3),
        "final_train_auc": round(float(train_auc), 5),
        "rows": rows,
        "trees_timed": trees,
    }

    # ---- artifact stamp (r12: the trend ledger keys history off data) -------
    # schema_version + git rev + device kind in the JSON itself, so
    # obs/trends.py never parses filenames; the reader stays tolerant of
    # the unstamped r1-r7 artifacts.  r23: device_kind comes from the ONE
    # derivation (policy/device.py) instead of a hand-rolled probe.
    from dryad_tpu.obs.trends import artifact_stamp

    out.update(artifact_stamp(
        root=os.path.dirname(os.path.abspath(__file__))))

    # ---- supervisor overhead (r8: the wrapper must be free on the hot path)
    # supervised vs direct short run, NO faults, BOTH arms checkpointed the
    # same way so the delta isolates the supervisor wrapper itself
    # (classification plumbing, journal-less hook threading, the retry
    # loop's bookkeeping) — not checkpoint I/O.
    import tempfile

    from dryad_tpu.resilience import supervise_train

    # a deliberately SHORT config (sub-second arms) so the wrapper's fixed
    # per-run cost is measured against a small noise floor — the wrapper
    # adds only host bookkeeping (one Checkpointer.latest probe, a hook
    # call per chunk/fetch, the retry-loop frame), none of it scaling with
    # rows, so a short run bounds the long-run overhead from above.
    # Per-arm min of 3 (stalls only ever ADD time) + spread observability.
    p_sup = params.replace(num_trees=8, num_leaves=15, max_depth=4)
    ds_sup = dryad.Dataset(X[:10_000], y[:10_000], max_bins=64)
    with tempfile.TemporaryDirectory() as td:
        dryad.train(p_sup, ds_sup, backend="tpu",                # warm/compile
                    checkpoint_dir=td + "/w", checkpoint_every=4)

        def arm(kind: str, i: int) -> float:
            ck = f"{td}/{kind}{i}"
            t0 = time.perf_counter()
            if kind == "sup":
                supervise_train(p_sup, ds_sup, backend="tpu",
                                checkpoint_dir=ck, checkpoint_every=4)
            else:
                dryad.train(p_sup, ds_sup, backend="tpu",
                            checkpoint_dir=ck, checkpoint_every=4)
            return time.perf_counter() - t0

        directs = [arm("direct", i) for i in range(3)]
        sups = [arm("sup", i) for i in range(3)]
    out["supervisor_overhead_ms"] = round(
        (min(sups) - min(directs)) * 1000, 1)
    out["supervisor_overhead_spread"] = round(
        max(max(directs) / min(directs), max(sups) / min(sups)) - 1, 3)

    # ---- observability overhead (r9: the zero-cost contract, measured) ------
    # Instrumented vs disabled on the SAME 200k series the headline times:
    # the obs wiring is a handful of host-side clock reads per chunk (and
    # per iteration on the dispatch path), so the delta must be noise-level
    # (acceptance: <= 2% of the arm wall).  Min-of-3 per arm — stalls only
    # ever ADD time — with the per-arm spread recorded next to the number.
    from dryad_tpu.obs.registry import default_registry

    _reg = default_registry()
    _was_enabled = _reg.enabled
    p_obs = params.replace(num_trees=12)
    train_device(p_obs, ds)                    # warm/compile the T=12 shape

    def obs_arm(enabled: bool) -> float:
        (_reg.enable if enabled else _reg.disable)()
        t0 = time.perf_counter()
        train_device(p_obs, ds)
        return time.perf_counter() - t0

    try:
        ons = [obs_arm(True) for _ in range(3)]
        offs = [obs_arm(False) for _ in range(3)]
    finally:
        # restore what the process started with (DRYAD_OBS=0 must keep the
        # 10M arm below uninstrumented)
        (_reg.enable if _was_enabled else _reg.disable)()
    out["obs_overhead_ms"] = round((min(ons) - min(offs)) * 1000, 2)
    out["obs_overhead_pct"] = round((min(ons) / min(offs) - 1) * 100, 3)
    out["obs_overhead_spread"] = round(
        max(max(ons) / min(ons), max(offs) / min(offs)) - 1, 3)

    # ---- 10M-row warm marginal (the BASELINE.json:2 scale) ------------------
    if os.environ.get("BENCH_10M", "1") != "0" and rows == 200_000:
        del X, y, ds  # host copies of the 200k run are dead weight now
        X10, y10 = higgs_like(10_000_000, seed=11)
        ds10 = dryad.Dataset(X10, y10, max_bins=256)
        del X10

        # Stall-robust pair methodology (VERDICT r3 weak #1): a tunnel
        # stall anywhere in a timed run ADDS seconds and poisons the
        # (8 - 2)-tree delta, and the old "< 0.5 s" guard only caught the
        # opposite failure.  Stalls are one-sided (they only ever ADD
        # time), so each arm is measured TWICE unconditionally and the
        # per-arm MINIMUM is the estimator; a third round is added only
        # when the two rounds of an arm disagree badly (> 15%), i.e. when
        # a stall visibly hit both attempts or the first was poisoned.
        p2 = params.replace(num_trees=2)
        p8 = params.replace(num_trees=8)
        train_device(p2, ds10)                 # compile + warm (own T shape)
        train_device(p8, ds10)

        def wall(p10) -> float:
            t0 = time.perf_counter()
            train_device(p10, ds10)
            return time.perf_counter() - t0

        walls2 = [wall(p2), wall(p2)]
        walls8 = [wall(p8), wall(p8)]
        for ws, p10 in ((walls2, p2), (walls8, p8)):
            if max(ws) > 1.15 * min(ws):
                ws.append(wall(p10))
        t2, t8 = min(walls2), min(walls8)
        marginal = max((t8 - t2) / 6.0, 1e-9)
        out["iters_per_sec_10m"] = round(1.0 / marginal, 4)
        out["marginal_s_per_iter_10m"] = round(marginal, 3)
        out["wall_2tree_10m"] = round(t2, 2)
        out["wall_8tree_10m"] = round(t8, 2)
        # observability: per-arm spread (max/min - 1) so a noisy capture is
        # visible in the artifact instead of silently shifting the headline
        out["spread_2tree_10m"] = round(max(walls2) / min(walls2) - 1, 3)
        out["spread_8tree_10m"] = round(max(walls8) / min(walls8) - 1, 3)
        out["rows_10m"] = 10_000_000
        del ds10                       # free HBM before the level probe

    # ---- wired-vs-legacy deep-level walls (the r6 trajectory field) ---------
    # Recorded per arm next to the spread fields so the wiring shows up as
    # a TREND across BENCH_*.json rounds, not a point.  BENCH_DEEP=0 skips.
    if os.environ.get("BENCH_DEEP", "1") != "0":
        probe_rows = out.get("rows_10m", rows)
        probe = deep_level_probe(probe_rows)
        if probe:
            out.update(probe)

    # ---- wired-vs-legacy leaf-wise expansion-level walls (r10) --------------
    # Same trend-not-point rule as BENCH_DEEP; BENCH_LEAFWISE=0 skips.
    if os.environ.get("BENCH_LEAFWISE", "1") != "0":
        probe_rows = out.get("rows_10m", rows)
        probe = leafwise_level_probe(probe_rows)
        if probe:
            out.update(probe)

    # ---- wide-shape split-scan walls per hist-reduce arm (r16) --------------
    # Epsilon-shaped fused vs feature-parallel scan stage; trend fields
    # like the wired/legacy pairs above.  BENCH_WIDE=0 skips.
    if os.environ.get("BENCH_WIDE", "1") != "0":
        probe = hist_reduce_probe()
        if probe:
            out.update(probe)

    # ---- packed-vs-legacy predict traversal walls (r21) ---------------------
    # One node-word table gather per level vs the structure-of-arrays ~7;
    # same trend-not-point rule as the arms above.  BENCH_PREDICT=0 skips.
    if os.environ.get("BENCH_PREDICT", "1") != "0":
        probe = predict_layout_probe()
        if probe:
            out.update(probe)

    # ---- out-of-core streamed-training arm (r20) ----------------------------
    # Resident-vs-streamed CPU walls + bitwise check via the standalone
    # probe (pure host work — run as a subprocess so its RSS accounting
    # and numpy temporaries never contaminate the TPU walls above).  The
    # 1e7-row RSS proof is heavy; opt in with BENCH_STREAM_RSS=1 or run
    # scripts/stream_rss_probe.py directly.  BENCH_STREAM=0 skips.
    if os.environ.get("BENCH_STREAM", "1") != "0":
        import subprocess as _sp
        import sys as _sys

        argv = [_sys.executable,
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "scripts", "stream_rss_probe.py")]
        if os.environ.get("BENCH_STREAM_RSS", "0") != "1":
            argv.append("--skip-rss")
        r = _sp.run(argv, capture_output=True, text=True)
        if r.returncode == 0 and r.stdout.strip():
            probe = json.loads(r.stdout.strip().splitlines()[-1])
            out.update({k: v for k, v in probe.items()
                        if k.startswith(("stream_", "resident_"))})
        else:
            out["stream_probe_error"] = (r.stderr or "").strip()[-400:]

    print(json.dumps(out))


if __name__ == "__main__":
    main()
