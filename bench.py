"""Headline benchmark: boosting iters/sec on the Higgs-shaped config
(BASELINE.json:2 — "boosting iters/sec + final AUC, Higgs, depth-8").

Runs the device trainer on the attached accelerator (TPU under the axon
tunnel; CPU otherwise), measures steady-state boosting iterations/second
after a warm-up that absorbs jit compilation, and prints ONE JSON line.

``vs_baseline`` is the speedup over the CPU canonical reference trainer on
an identical (sub-sampled) config — no published Dryad-on-A100 number exists
in this environment (BASELINE.md), so the CPU reference is the recorded
baseline the driver tracks across rounds.

The north-star metric (BASELINE.json:2) is defined at Higgs-10M scale, so
the same line also carries ``iters_per_sec_10m``: the warm MARGINAL
iteration cost at 10,000,000 rows measured as the (8-tree − 2-tree) warm
wall delta / 6 — fixed per-run costs (compile, upload, fetch) cancel in
the difference, leaving the steady-state per-iteration cost the asymptote
is made of.  Set BENCH_10M=0 to skip (~5 min: two compiles + four runs).

Env knobs: BENCH_ROWS (default 200000), BENCH_TREES (default 50),
BENCH_LEAVES (default 255), BENCH_GROWTH (default depthwise),
BENCH_10M (default 1).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    # Pin the device-resident chunked boosting path: the bench estimates the
    # LONG-run (500-tree-scale) steady state from short timed runs, and the
    # compile-vs-work heuristic (train.py, VERDICT r3 #5) would route runs
    # this short to per-iteration dispatch — a different program than the
    # one a long run uses.  Forcing the chunk path keeps the marginal arms
    # measuring the steady state the metric is defined on (and keeps the
    # BENCH series comparable with rounds 1-3, which always chunked here).
    os.environ.setdefault("DRYAD_CHUNK", "1")
    rows = int(os.environ.get("BENCH_ROWS", 200_000))
    # 50 trees: long enough that the steady-state chunked pipeline dominates
    # (20 trees left ~30% of wall in fixed per-run costs), short enough for
    # a ~2-minute bench incl. the identical-shape warmup run
    trees = int(os.environ.get("BENCH_TREES", 50))
    leaves = int(os.environ.get("BENCH_LEAVES", 255))
    growth = os.environ.get("BENCH_GROWTH", "depthwise")


    import dryad_tpu as dryad
    from dryad_tpu.config import make_params
    from dryad_tpu.datasets import higgs_like
    from dryad_tpu.metrics import auc

    X, y = higgs_like(rows, seed=7)
    ds = dryad.Dataset(X, y, max_bins=256)
    params = make_params(dict(
        objective="binary", num_trees=trees, num_leaves=leaves,
        max_depth=8, growth=growth, max_bins=256, learning_rate=0.1,
    ))

    from dryad_tpu.engine.train import train_device

    # iterations dispatch asynchronously (no per-iteration device sync), so
    # per-callback deltas are meaningless — time the full run wall-to-wall
    # (train_device's final fetch blocks on the whole pipeline) and subtract
    # a warmup run that absorbs jit compilation.
    # warmup with identical shapes (the output tree table is (num_trees, M)
    # — a different tree count would recompile in the timed run)
    train_device(params, ds)

    t0 = time.perf_counter()
    booster = train_device(params, ds)
    total_time = time.perf_counter() - t0
    iters_per_sec = trees / total_time

    train_auc = auc(y, booster.predict(X, raw_score=True))

    # CPU-reference baseline on a subsample, scaled to the full row count
    # (histogram work is linear in rows; SURVEY.md §3 hot loops)
    base_rows = min(rows, 50_000)
    Xs = X[:base_rows]
    ys = y[:base_rows]
    ds_s = dryad.Dataset(Xs, ys, max_bins=256)
    cpu_params = params.replace(num_trees=2)
    t0 = time.perf_counter()
    dryad.train(cpu_params, ds_s, backend="cpu")
    cpu_time = (time.perf_counter() - t0) / 2 * (rows / base_rows)
    vs_baseline = iters_per_sec * cpu_time  # = cpu_time_per_iter / dev_time_per_iter

    out = {
        "metric": f"boosting_iters_per_sec_higgs{rows // 1000}k_depth8_{leaves}leaves",
        "value": round(iters_per_sec, 3),
        "unit": "iters/s",
        "vs_baseline": round(vs_baseline, 3),
        "final_train_auc": round(float(train_auc), 5),
        "rows": rows,
        "trees_timed": trees,
    }

    # ---- 10M-row warm marginal (the BASELINE.json:2 scale) ------------------
    if os.environ.get("BENCH_10M", "1") != "0" and rows == 200_000:
        del X, y, ds  # host copies of the 200k run are dead weight now
        X10, y10 = higgs_like(10_000_000, seed=11)
        ds10 = dryad.Dataset(X10, y10, max_bins=256)
        del X10

        # Stall-robust pair methodology (VERDICT r3 weak #1): a tunnel
        # stall anywhere in a timed run ADDS seconds and poisons the
        # (8 - 2)-tree delta, and the old "< 0.5 s" guard only caught the
        # opposite failure.  Stalls are one-sided (they only ever ADD
        # time), so each arm is measured TWICE unconditionally and the
        # per-arm MINIMUM is the estimator; a third round is added only
        # when the two rounds of an arm disagree badly (> 15%), i.e. when
        # a stall visibly hit both attempts or the first was poisoned.
        p2 = params.replace(num_trees=2)
        p8 = params.replace(num_trees=8)
        train_device(p2, ds10)                 # compile + warm (own T shape)
        train_device(p8, ds10)

        def wall(p10) -> float:
            t0 = time.perf_counter()
            train_device(p10, ds10)
            return time.perf_counter() - t0

        walls2 = [wall(p2), wall(p2)]
        walls8 = [wall(p8), wall(p8)]
        for ws, p10 in ((walls2, p2), (walls8, p8)):
            if max(ws) > 1.15 * min(ws):
                ws.append(wall(p10))
        t2, t8 = min(walls2), min(walls8)
        marginal = max((t8 - t2) / 6.0, 1e-9)
        out["iters_per_sec_10m"] = round(1.0 / marginal, 4)
        out["marginal_s_per_iter_10m"] = round(marginal, 3)
        out["wall_2tree_10m"] = round(t2, 2)
        out["wall_8tree_10m"] = round(t8, 2)
        # observability: per-arm spread (max/min - 1) so a noisy capture is
        # visible in the artifact instead of silently shifting the headline
        out["spread_2tree_10m"] = round(max(walls2) / min(walls2) - 1, 3)
        out["spread_8tree_10m"] = round(max(walls8) / min(walls8) - 1, 3)
        out["rows_10m"] = 10_000_000

    print(json.dumps(out))


if __name__ == "__main__":
    main()
