"""Typed training-parameter surface for dryad_tpu.

Mirrors the ``dryad.train(params, dataset)`` API contract (BASELINE.json:5;
SURVEY.md §5 "Config/flag system").  The reference checkout was absent in this
environment (SURVEY.md header), so param names follow the de-facto GBDT
vocabulary (LightGBM/XGBoost family) that the capability contract in
SURVEY.md §2 implies; aliases can be grafted on once the reference's exact
names are observable.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping, Sequence

from dryad_tpu.policy.table import GATE_DEFAULTS as _POLICY_DEFAULTS

OBJECTIVES = ("binary", "multiclass", "regression", "lambdarank",
              "l1", "huber", "fair", "quantile", "poisson")
GROWTH_POLICIES = ("leafwise", "depthwise")

# Alias table so configs written against common GBDT engines keep working.
_PARAM_ALIASES = {
    "num_iterations": "num_trees",
    "n_estimators": "num_trees",
    "num_round": "num_trees",
    "num_boost_round": "num_trees",
    "eta": "learning_rate",
    "shrinkage_rate": "learning_rate",
    "max_bin": "max_bins",
    "reg_lambda": "lambda_l2",
    "lambda": "lambda_l2",
    "min_sum_hessian_in_leaf": "min_child_weight",
    "min_child_samples": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_gain_to_split": "min_split_gain",
    "bagging_fraction": "subsample",
    "feature_fraction": "colsample",
    "random_state": "seed",
    "bagging_seed": "seed",
    "application": "objective",
    "grow_policy": "growth",
    "num_classes": "num_class",
    "boosting_type": "boosting",
    "top_rate": "goss_top_rate",
    "other_rate": "goss_other_rate",
    "rate_drop": "drop_rate",
}

_OBJECTIVE_ALIASES = {
    "binary_logloss": "binary",
    "logistic": "binary",
    "binary:logistic": "binary",
    "softmax": "multiclass",
    "multi:softmax": "multiclass",
    "multiclassova": "multiclass",
    "l2": "regression",
    "mse": "regression",
    "reg:squarederror": "regression",
    "mae": "l1",
    "regression_l1": "l1",
    "reg:absoluteerror": "l1",
    "reg:quantileerror": "quantile",
    "count:poisson": "poisson",
    "lambdamart": "lambdarank",
    "rank:ndcg": "lambdarank",
}

_GROWTH_ALIASES = {
    "leaf": "leafwise",
    "lossguide": "leafwise",
    "leaf_wise": "leafwise",
    "depth": "depthwise",
    "depth_wise": "depthwise",
}


@dataclasses.dataclass(frozen=True)
class Params:
    """Frozen, validated hyper-parameters for one training run."""

    objective: str = "binary"
    num_class: int = 1
    num_trees: int = 100
    num_leaves: int = 31
    max_depth: int = -1          # -1: bounded only by num_leaves
    learning_rate: float = 0.1
    # includes the reserved missing bin (id 0).  Values above 1024 fall off
    # the Pallas histogram kernel onto the XLA builder (correct, measurably
    # slower per level) — keep <= 1024 on TPU unless accuracy demands more.
    max_bins: int = 256
    lambda_l2: float = 1.0
    min_child_weight: float = 1e-3
    min_data_in_leaf: int = 20
    min_split_gain: float = 0.0
    growth: str = "leafwise"
    # Policy for leaf-wise max_depth=-1 ("unlimited").  "auto" (default)
    # maps it to a documented effective cap min(ceil(log2(num_leaves))+4, 14)
    # whenever the batched leaf-wise grower can take the config — identical
    # policy on the CPU backend, so parity holds
    # (engine/leafwise_fast.effective_depth_params).  "exact" keeps true
    # unbounded best-first growth on the sequential grower.
    unbounded_depth: str = "auto"
    # gbdt: plain boosting (+ optional bagging). goss: gradient-based
    # one-side sampling — keep the goss_top_rate fraction with the largest
    # |grad|, Bernoulli-sample goss_other_rate of the rest and amplify their
    # grad/hess by (1-top)/other to stay unbiased.  dart: dropout boosting
    # (DART paper semantics): each iteration drops every previous
    # iteration's trees independently with prob drop_rate (whole
    # iterations for multiclass; skipped entirely with prob skip_drop),
    # fits the new tree against the pruned ensemble, then scales the new
    # tree by 1/(k+1) and the k dropped iterations by k/(k+1).
    # rf: random-forest mode (LightGBM boosting_type="rf" semantics):
    # every tree fits the gradients at the CONSTANT init score (no
    # residual chaining), trains on a fresh bagged subset (subsample < 1
    # required, per-iteration Philox draw), shrinkage is forced to 1.0
    # (see effective_learning_rate), and the prediction is
    # init + (sum of tree outputs) / n_iterations — an average of
    # full-strength trees rather than a boosted sum.
    boosting: str = "gbdt"
    goss_top_rate: float = 0.2
    goss_other_rate: float = 0.1
    drop_rate: float = 0.1
    skip_drop: float = 0.5
    max_drop: int = 50
    subsample: float = 1.0
    colsample: float = 1.0
    seed: int = 0
    categorical_features: tuple[int, ...] = ()
    # per-feature -1/0/+1; () = unconstrained. Split-level enforcement: a +1
    # feature may only split where right-child value >= left-child value.
    monotone_constraints: tuple[int, ...] = ()
    # evaluation / early stopping
    metric: str = ""              # "" = objective default
    # 0 = disabled.  Counts EVALUATIONS without improvement, not iterations:
    # with eval_period > 1 the effective patience in iterations is
    # early_stopping_rounds * eval_period (LightGBM counts iterations, but
    # it also evaluates every iteration — at eval_period=1 the two agree).
    early_stopping_rounds: int = 0
    # evaluate every k-th iteration (each eval forces a device->host fetch,
    # ~100ms through a remote tunnel); early stopping checks at that cadence
    eval_period: int = 1
    # binary: multiply the positive class's grad/hess (imbalanced data)
    scale_pos_weight: float = 1.0
    # Robust / count regression family (LightGBM conventions): ``alpha``
    # is the Huber delta AND the quantile level; ``fair_c`` the Fair-loss
    # scale; ``poisson_max_delta_step`` the Poisson hessian stabilizer
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    # LambdaMART
    sigmoid: float = 1.0
    ndcg_at: int = 10
    lambdarank_truncation: int = 30
    # Engine knobs (TPU path)
    hist_backend: str = "auto"   # auto | xla | pallas
    # Per-level data movement for BOTH level-synchronous growers
    # (levelwise + the batched leaf-wise expansion): "auto" carries the
    # leaf-ordered record layout through every level from the root (no
    # per-level sort / record gather, no shallow->deep handoff) whenever
    # the config admits it (engine/levelwise.deep_layout_supported; the
    # leaf-wise expansion adds a run-capacity depth cap on top —
    # engine/leafwise_fast.leafwise_layout_supported); "legacy" forces
    # the plan-based sort+gather path — the comparison arm for the
    # on-device parity gates and benches.  Switching arms changes
    # program/fusion shapes, so fp32 near-tie argmaxes may flip between
    # them (the documented chunked-vs-dispatch tolerance class in
    # engine/train.py); model quality is unaffected.
    deep_layout: str = "auto"    # auto | legacy
    # Device predict traversal table layout (engine/predict.stage_trees,
    # r21): "auto" stages the packed node-word tables — per node one
    # (2,)-uint32 limb pair holding children/threshold/feature/
    # default_left/is_cat/internal (width-asserted: children+threshold
    # 16 bits, feature 12), so the per-level traversal body pays ONE
    # small-table gather instead of the legacy structure-of-arrays ~7 —
    # falling back to "legacy" when a field exceeds its width.  "packed"
    # forces the packed arm (ValueError when it cannot fit); "legacy"
    # keeps the per-field tables — the comparison arm for parity gates
    # and benches.  Leaf-value accumulation is untouched by the layout,
    # so packed ≡ legacy predict is BITWISE on every arm (single-device,
    # sharded, serve cache) — tests/test_predict_packed.py pins it.
    predict_layout: str = "auto"    # auto | packed | legacy
    # Cross-shard histogram reduction for the level-synchronous growers
    # (levelwise + the batched leaf-wise expansion) under shard_map:
    # "fused" keeps the classic one fused grad/hess/count psum of the full
    # (P, 3, F, B) stack per builder call (the XGBoost-style allreduce —
    # the comparison arm); "feature" reduce-scatters a static contiguous
    # feature partition instead (each shard owns F/n fully-reduced
    # columns), runs the split scan on the owned slice only, and combines
    # tiny per-shard best-split records with one all-gather per level
    # (LightGBM's reduce-scatter data-parallel mode) — at Epsilon shape
    # (F=2000, B=256) the per-device reduced payload shrinks ~n-fold.
    # "auto" picks "feature" iff F * B * bin_bytes clears
    # HIST_REDUCE_WIDE_BYTES AND more than one shard participates — a pure
    # function of (params, feature/bin shape, shard count), never of rows
    # (CLAUDE.md same-program rule).  An explicit "feature" at 1 shard
    # runs the degenerate full-slice program, so near-tie argmaxes can
    # never flip between shard counts within the arm; switching ARMS
    # (fused <-> feature) is same-program per shard count by construction
    # (reduce-scatter slices measured bitwise-equal to the psum's), and
    # pinned bitwise on the tie-free parity fixtures.  The sequential
    # (unbounded-depth leaf-wise) grower ignores this knob — its per-split
    # masked pass always rides the fused psum.
    hist_reduce: str = "auto"    # auto | fused | feature
    # Cap on boosting iterations fused into one device program (the chunked
    # dispatch path in engine/train.py).  0 = no cap beyond the calibrated
    # watchdog budget.  Precedence (single documented order): the
    # DRYAD_CH_MAX env var, when set > 0, OVERRIDES this param (the
    # operational escape hatch stays the highest authority); otherwise this
    # param applies; the resilience supervisor's adaptive chunk policy
    # (resilience/policy.py) may additionally cap individual chunks at
    # runtime, below whichever of the two is in force.  ch_max=2 is the
    # known-safe setting for tunnel phases that kill standard ~20 s chunks
    # (STATUS r5: 6/6 first-fetch deaths at CH 6-8, zero at CH <= 2).
    ch_max: int = 0
    hist_subtraction: bool = True
    rows_per_chunk: int = 65536  # row-tile for the chunked histogram scan
    deterministic: bool = True
    # exact: fp32 MXU passes, keeps gain-argmax parity with the CPU ref.
    # fast: single-pass bf16 MXU (~6x histogram speedup); counts stay exact
    # (f32 accumulation of 0/1 products), grad/hess sums carry ~0.4%/elem
    # rounding — tree structures may differ slightly, model quality doesn't.
    hist_precision: str = "exact"

    # ---- derived -----------------------------------------------------------
    @property
    def effective_num_leaves(self) -> int:
        if self.growth == "depthwise" and self.max_depth > 0:
            return min(self.num_leaves, 2 ** self.max_depth) if self.num_leaves > 0 else 2 ** self.max_depth
        return self.num_leaves

    @property
    def max_nodes(self) -> int:
        return 2 * self.effective_num_leaves - 1

    @property
    def num_outputs(self) -> int:
        """Trees trained per boosting iteration (K for multiclass, else 1)."""
        return self.num_class if self.objective == "multiclass" else 1

    @property
    def effective_learning_rate(self) -> float:
        """1.0 under boosting='rf' — rf averages full-strength trees
        (LightGBM likewise forces shrinkage 1.0 in rf mode); shrinking
        them would just scale the average.  Both leaf-value finalizers
        (engine/grower.py, cpu/histogram.leaf_output) use THIS, never the
        raw learning_rate, so the two backends cannot diverge."""
        return 1.0 if self.boosting == "rf" else self.learning_rate

    def validate(self) -> "Params":
        if self.objective not in OBJECTIVES:
            raise ValueError(f"objective must be one of {OBJECTIVES}, got {self.objective!r}")
        if self.objective == "multiclass" and self.num_class < 2:
            raise ValueError("multiclass requires num_class >= 2")
        if self.growth not in GROWTH_POLICIES:
            raise ValueError(f"growth must be one of {GROWTH_POLICIES}, got {self.growth!r}")
        if not (2 <= self.max_bins <= 65536):
            raise ValueError("max_bins must be in [2, 65536]")
        if self.categorical_features and self.max_bins > 256:
            raise ValueError("categorical splits support max_bins <= 256 (bitset width)")
        if self.min_data_in_leaf < 1:
            raise ValueError("min_data_in_leaf must be >= 1")
        if any(m not in (-1, 0, 1) for m in self.monotone_constraints):
            raise ValueError("monotone_constraints entries must be -1, 0 or +1")
        if self.boosting not in ("gbdt", "goss", "dart", "rf"):
            raise ValueError("boosting must be 'gbdt', 'goss', 'dart' or 'rf'")
        if self.boosting == "rf" and self.subsample >= 1.0:
            # without row bagging every rf tree would fit the SAME
            # gradients on the SAME rows and the average would equal one
            # tree (LightGBM likewise requires bagging for rf)
            raise ValueError(
                "boosting='rf' requires subsample < 1.0: trees only "
                "de-correlate through per-iteration row bagging")
        if self.boosting == "dart":
            if not (0.0 <= self.drop_rate <= 1.0):
                raise ValueError("drop_rate must be in [0, 1]")
            if not (0.0 <= self.skip_drop <= 1.0):
                raise ValueError("skip_drop must be in [0, 1]")
            if self.max_drop < 1:
                raise ValueError("max_drop must be >= 1")
            if self.early_stopping_rounds:
                # best_iteration truncation is unsound under DART: drops
                # AFTER the best iteration rescale earlier trees in place,
                # so the truncated model no longer matches the metric that
                # selected it (LightGBM disables early stopping here too)
                raise ValueError(
                    "early_stopping_rounds is incompatible with "
                    "boosting='dart' (later drop iterations rescale the "
                    "trees the best iteration was scored with)")
        if self.boosting == "goss":
            if not (0 < self.goss_top_rate < 1) or not (0 < self.goss_other_rate < 1):
                raise ValueError("goss rates must be in (0, 1)")
            if self.goss_top_rate + self.goss_other_rate > 1:
                raise ValueError("goss_top_rate + goss_other_rate must be <= 1")
            if self.subsample < 1.0:
                raise ValueError("goss replaces bagging; set subsample=1.0")
        if self.num_leaves < 2:
            raise ValueError("num_leaves must be >= 2")
        if self.num_trees < 0:
            # 0 is the warm-start no-op append (train(init_model=m,
            # num_trees=0) returns a predict-identical copy); dryad.train
            # rejects it for a FRESH run, where an empty model is a typo
            raise ValueError("num_trees must be >= 0")
        if not (0.0 < self.learning_rate):
            raise ValueError("learning_rate must be > 0")
        if not (0.0 < self.subsample <= 1.0) or not (0.0 < self.colsample <= 1.0):
            raise ValueError("subsample/colsample must be in (0, 1]")
        if not (self.scale_pos_weight > 0.0):
            raise ValueError("scale_pos_weight must be > 0")
        if self.objective == "quantile" and not (0.0 < self.alpha < 1.0):
            raise ValueError("quantile objective needs alpha in (0, 1)")
        if self.objective == "huber" and not (self.alpha > 0.0):
            raise ValueError("huber objective needs alpha (delta) > 0")
        if self.objective == "fair" and not (self.fair_c > 0.0):
            raise ValueError("fair objective needs fair_c > 0")
        if (self.objective == "poisson"
                and not (self.poisson_max_delta_step >= 0.0)):
            raise ValueError("poisson_max_delta_step must be >= 0")
        if self.eval_period < 1:
            raise ValueError("eval_period must be >= 1")
        if self.unbounded_depth not in ("auto", "exact"):
            raise ValueError("unbounded_depth must be auto|exact")
        if self.hist_backend not in ("auto", "xla", "pallas"):
            raise ValueError("hist_backend must be auto|xla|pallas")
        if self.deep_layout not in ("auto", "legacy"):
            raise ValueError("deep_layout must be auto|legacy")
        if self.predict_layout not in ("auto", "packed", "legacy"):
            raise ValueError("predict_layout must be auto|packed|legacy")
        if self.hist_reduce not in ("auto", "fused", "feature"):
            raise ValueError("hist_reduce must be auto|fused|feature")
        if self.ch_max < 0:
            raise ValueError("ch_max must be >= 0 (0 = uncapped)")
        if self.hist_precision not in ("exact", "fast"):
            raise ValueError("hist_precision must be exact|fast")
        return self

    def replace(self, **kw: Any) -> "Params":
        return dataclasses.replace(self, **kw).validate()

    # ---- construction ------------------------------------------------------
    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Params":
        norm: dict[str, Any] = {}
        known = {f.name for f in dataclasses.fields(cls)}
        for key, value in d.items():
            key = _PARAM_ALIASES.get(key, key)
            if key == "objective" and isinstance(value, str):
                value = _OBJECTIVE_ALIASES.get(value, value)
            if key == "growth" and isinstance(value, str):
                value = _GROWTH_ALIASES.get(value, value)
            if key in ("categorical_features", "monotone_constraints") and isinstance(value, Sequence):
                value = tuple(int(v) for v in value)
            if key not in known:
                raise ValueError(f"unknown parameter {key!r}")
            norm[key] = value
        return cls(**norm).validate()

    @classmethod
    def from_json(cls, path: str) -> "Params":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


# ---- growth-policy helpers (jax-free: the CPU backend imports these) --------
# Shared by engine/leafwise_fast.py (which re-exports ``supports``) and both
# trainer entries, so the max_depth=-1 mapping can never diverge by backend.
LEAFWISE_HIST_BYTES_BUDGET = 256 << 20   # pinned expansion hist buffer cap
MAX_FAST_DEPTH = 14
# Peak-residency envelope for the batched grower (VERDICT r3 #7): the
# pinned (Pf, 3, F, B) expansion buffer transiently fans out ~6x at the
# widest level (small/large/l/r + the 2P children concat feeding the
# vmapped split finder), CO-RESIDENT with the N-scaled working set (binned
# matrix, per-tree record table, grad/hess/score columns).  12 GiB leaves
# headroom on a 16 GiB v5e HBM for the boosting loop's own buffers.  A
# pure function of params + data shape — NEVER of backend — so the CPU
# mirror routes identically and parity holds.
LEAFWISE_TOTAL_BYTES_BUDGET = 12 << 30


# Wide-shape threshold for hist_reduce="auto": the feature-parallel
# reduction pays one combine all-gather per level, so it only wins where
# the per-slot histogram column is big — F * B * bin_bytes at or past
# 256 KB (Epsilon's 2000 x 256 u8 = 500 KB clears it; Higgs' 28 x 256 =
# 7 KB stays fused).  bin_bytes is the binned-matrix itemsize (1 below
# 257 bins, else 2) so the gate is jax-free and shard-count aware only
# through its explicit argument.  r23: the constant lives in the policy
# calibration table (policy/table.GATE_DEFAULTS["hist_reduce"]); this
# name is the compatibility re-export of the committed default.
HIST_REDUCE_WIDE_BYTES = _POLICY_DEFAULTS["hist_reduce"]["wide_bytes"]


def hist_reduce_resolved(p: Params, num_features: int, total_bins: int,
                         n_shards: int) -> str:
    """The ONE hist_reduce gate — shared by both level-synchronous growers
    AND train._comm_stats so the observability accounting can never drift
    from the program choice (the nat-gate/phase-plan precedent, ADVICE
    r4).  A pure function of (params, feature/bin shape, shard count) —
    NEVER of the row count (CLAUDE.md same-program rule).  r23: the
    threshold comes from the device-keyed policy table; the committed
    default resolves bitwise-identically to the pre-r23 constant."""
    if p.hist_reduce != "auto":
        return p.hist_reduce
    from dryad_tpu.policy.gates import resolve

    return resolve("hist_reduce", {"num_features": num_features,
                                   "total_bins": total_bins,
                                   "n_shards": n_shards})


def leafwise_fast_supported(p: Params, num_features: int,
                            total_bins: int,
                            num_rows: int | None = None) -> bool:
    """Whether the batched leaf-wise grower can take this config (see
    engine/leafwise_fast.supports for the budget rationale).  ``num_rows``
    (GLOBAL rows — shard-count independent, or the 1-shard/N-shard
    invariant would break) adds the peak-residency check; None skips it
    (shape-only callers)."""
    D = p.max_depth
    if not 0 < D <= MAX_FAST_DEPTH:
        return False
    if not p.hist_subtraction:
        return False
    Pf = 1 << max(D - 1, 0)
    pinned = Pf * 3 * num_features * total_bins * 4
    if pinned > LEAFWISE_HIST_BYTES_BUDGET:
        return False
    if num_rows is not None:
        bin_bytes = 1 if total_bins <= 256 else 2
        rec_words = 2 + -(-num_features * bin_bytes // 4)
        K = p.num_outputs
        per_row = (num_features * bin_bytes      # binned matrix
                   + 4 * rec_words               # per-tree record table
                   + 16 * K + 8)                 # (N,K) g/h/score + slots
        if 6 * pinned + num_rows * per_row > LEAFWISE_TOTAL_BYTES_BUDGET:
            return False
    return True


def effective_depth_params(p: Params, num_features: int,
                           total_bins: int,
                           num_rows: int | None = None) -> Params:
    """The documented ``max_depth=-1`` policy for leaf-wise growth at scale.

    Unbounded-depth leaf-wise growth cannot be pre-expanded, so it takes the
    sequential O(N·L) grower — the out-of-the-box configuration's worst
    asymptotics (VERDICT r3 #3).  Under ``unbounded_depth="auto"`` (the
    default), "unlimited" maps to a documented effective cap

        min(ceil(log2(num_leaves)) + 4, MAX_FAST_DEPTH)

    — four levels of headroom past a balanced tree, enough that a best-first
    tree constrained by the cap is almost always the unconstrained one —
    whenever the resulting config rides the batched grower.  The SAME
    mapping runs in ``cpu/trainer.py`` and ``engine/train.py``, so CPU↔TPU
    tree parity is untouched (it is a pure function of params + data shape,
    never of backend).  Configs the batched grower cannot take (budget,
    subtraction disabled) keep true-unbounded sequential semantics, as does
    ``unbounded_depth="exact"``.
    """
    if p.max_depth > 0 or p.growth != "leafwise" or p.unbounded_depth == "exact":
        return p
    L = p.effective_num_leaves
    eff = min(max((L - 1).bit_length(), 1) + 4, MAX_FAST_DEPTH)
    if L > (1 << eff):
        return p                      # cap cannot express the leaf budget
    cand = p.replace(max_depth=eff)
    if leafwise_fast_supported(cand, num_features, total_bins, num_rows):
        return cand
    return p


def make_params(params: "Params | Mapping[str, Any] | None" = None, **kw: Any) -> Params:
    """Accept a Params, a plain dict, or kwargs — the ``dryad.train`` front door."""
    if params is None:
        return Params.from_dict(kw)
    if isinstance(params, Params):
        return (params.replace(**kw) if kw else params.validate())
    merged = dict(params)
    merged.update(kw)
    return Params.from_dict(merged)
