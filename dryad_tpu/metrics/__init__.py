"""Evaluation metrics: AUC, logloss, multiclass logloss, RMSE, NDCG@k.

Canonical numpy implementations (SURVEY.md §2 #11).  The headline metric pair
is boosting iters/sec + final AUC (BASELINE.json:2); NDCG serves the
LambdaMART config (BASELINE.json:10).
"""

from __future__ import annotations

import numpy as np

_EPS = 1e-15


def auc(y_true: np.ndarray, y_score: np.ndarray) -> float:
    """Exact ROC-AUC via the rank statistic, with midrank tie handling."""
    y_true = np.asarray(y_true).astype(np.float64)
    y_score = np.asarray(y_score).astype(np.float64)
    pos = y_true > 0.5
    n_pos = int(pos.sum())
    n_neg = y_true.size - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(y_score, kind="mergesort")
    sorted_scores = y_score[order]
    ranks = np.empty(y_true.size, np.float64)
    # midranks for ties
    i = 0
    while i < y_true.size:
        j = i
        while j + 1 < y_true.size and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        ranks[order[i : j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    sum_pos_ranks = ranks[pos].sum()
    return float((sum_pos_ranks - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def binary_logloss(y_true: np.ndarray, y_prob: np.ndarray) -> float:
    y = np.asarray(y_true, np.float64)
    p = np.clip(np.asarray(y_prob, np.float64), _EPS, 1.0 - _EPS)
    return float(-(y * np.log(p) + (1 - y) * np.log(1 - p)).mean())


def multi_logloss(y_true: np.ndarray, y_prob: np.ndarray) -> float:
    y = np.asarray(y_true).astype(np.int64)
    p = np.clip(np.asarray(y_prob, np.float64), _EPS, 1.0)
    p = p / p.sum(axis=1, keepdims=True)
    return float(-np.log(p[np.arange(y.size), y]).mean())


def accuracy(y_true: np.ndarray, y_prob: np.ndarray) -> float:
    y = np.asarray(y_true).astype(np.int64)
    pred = np.asarray(y_prob).argmax(axis=1)
    return float((pred == y).mean())


def error_rate(y_true: np.ndarray, y_prob: np.ndarray) -> float:
    """Misclassification fraction (LightGBM's 'error' convention)."""
    return 1.0 - accuracy(y_true, y_prob)


def rmse(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    d = np.asarray(y_true, np.float64) - np.asarray(y_pred, np.float64)
    return float(np.sqrt(np.mean(d * d)))


def mse(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    d = np.asarray(y_true, np.float64) - np.asarray(y_pred, np.float64)
    return float(np.mean(d * d))


def mae(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    d = np.asarray(y_true, np.float64) - np.asarray(y_pred, np.float64)
    return float(np.mean(np.abs(d)))


def poisson_deviance(y_true: np.ndarray, raw_score: np.ndarray) -> float:
    """Mean Poisson deviance from RAW (log-rate) scores: the y*log(y/mu)
    term drops for y == 0 (its limit), mu = exp(raw)."""
    y = np.asarray(y_true, np.float64)
    mu = np.exp(np.asarray(raw_score, np.float64))
    # clamp epsilon is 1e-30 to MATCH metrics.device.poisson_deviance_device
    # exactly (1e-300 is unrepresentable in f32); the clamp is live only for
    # 0 < y < 1e-30, where the y multiplier makes the difference immaterial,
    # but host and device must agree bit-for-bit on the formula (ADVICE r4)
    ylog = np.where(y > 0, y * np.log(np.maximum(y, 1e-30) / mu), 0.0)
    return float(np.mean(2.0 * (ylog - (y - mu))))


def dcg_at_k(rels: np.ndarray, k: int) -> float:
    rels = np.asarray(rels, np.float64)[:k]
    if rels.size == 0:
        return 0.0
    gains = np.power(2.0, rels) - 1.0
    discounts = 1.0 / np.log2(np.arange(2, rels.size + 2))
    return float((gains * discounts).sum())


def ndcg_at_k(
    y_true: np.ndarray, y_score: np.ndarray, query_offsets: np.ndarray, k: int = 10
) -> float:
    """Mean NDCG@k over queries; queries with zero ideal DCG count as 1.0
    (LightGBM convention)."""
    y_true = np.asarray(y_true, np.float64)
    y_score = np.asarray(y_score, np.float64)
    total, nq = 0.0, 0
    for q in range(query_offsets.size - 1):
        a, b = int(query_offsets[q]), int(query_offsets[q + 1])
        rels = y_true[a:b]
        order = np.argsort(-y_score[a:b], kind="mergesort")
        ideal = np.sort(rels)[::-1]
        idcg = dcg_at_k(ideal, k)
        total += 1.0 if idcg == 0.0 else dcg_at_k(rels[order], k) / idcg
        nq += 1
    return float(total / max(nq, 1))


METRICS = {
    "auc": auc,
    "binary_logloss": binary_logloss,
    "multi_logloss": multi_logloss,
    "accuracy": accuracy,
    "rmse": rmse,
    "mse": mse,
    "mae": mae,
    "error": error_rate,
}

_METRIC_ALIASES = {"l2": "mse", "l2_root": "rmse", "l1": "mae",
                   "logloss": "binary_logloss", "binary_error": "error",
                   "multi_error": "error"}

DEFAULT_METRIC = {
    "binary": "auc",
    "multiclass": "multi_logloss",
    "regression": "rmse",
    "lambdarank": "ndcg",
    "l1": "mae",
    "huber": "rmse",
    "fair": "rmse",
    "quantile": "mae",
    "poisson": "poisson_deviance",
}

HIGHER_BETTER = {"auc": True, "ndcg": True, "accuracy": True, "error": False,
                 "binary_logloss": False, "multi_logloss": False,
                 "rmse": False, "mse": False, "mae": False,
                 "poisson_deviance": False}


def evaluate_raw(
    objective: str,
    metric: str,
    y: np.ndarray,
    raw_score: np.ndarray,
    query_offsets: np.ndarray | None = None,
    ndcg_at: int = 10,
) -> tuple[str, float, bool]:
    """Evaluate a metric on raw (pre-link) scores → (name, value, higher_better)."""
    name = metric or DEFAULT_METRIC[objective]
    name = _METRIC_ALIASES.get(name, name)
    s = raw_score if raw_score.ndim == 1 else raw_score[:, 0] if raw_score.shape[1] == 1 else raw_score
    if name == "auc":
        value = auc(y, s)
    elif name == "binary_logloss":
        value = binary_logloss(y, 1.0 / (1.0 + np.exp(-s)))
    elif name == "multi_logloss":
        e = np.exp(s - s.max(axis=1, keepdims=True))
        value = multi_logloss(y, e / e.sum(axis=1, keepdims=True))
    elif name in ("accuracy", "error"):
        if s.ndim == 1:   # binary raw scores: class 1 iff score > 0
            acc = float((np.asarray(y).astype(np.int64)
                         == (s > 0).astype(np.int64)).mean())
        else:
            acc = accuracy(y, s)
        value = acc if name == "accuracy" else 1.0 - acc
    elif name == "rmse":
        value = rmse(y, s)
    elif name == "mse":
        value = mse(y, s)
    elif name == "mae":
        value = mae(y, s)
    elif name == "poisson_deviance":
        value = poisson_deviance(y, s)
    elif name == "ndcg":
        if query_offsets is None:
            raise ValueError("ndcg requires query groups on the validation set")
        value = ndcg_at_k(y, s, query_offsets, k=ndcg_at)
    else:
        raise ValueError(f"unknown metric {name!r}")
    return name, value, HIGHER_BETTER[name]
