"""Device-side evaluation metrics (SURVEY.md §5 metrics/observability).

The round-1 trainer fetched the full validation score matrix to the host
every eval (~100 ms latency through a remote device tunnel + O(N) transfer
+ host sort for AUC).  These jax implementations compute the metric where
the scores already live, so an eval costs one 4-byte scalar fetch — or no
fetch at all until training ends when nothing needs the value mid-run.

The numpy implementations in ``dryad_tpu.metrics`` remain the oracle:
``test_device_metrics.py`` pins each function against them to fp32
tolerance (device sums are f32 tree-reductions; at 1e6 rows the relative
error is ~1e-6, far below metric noise).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dryad_tpu.metrics import DEFAULT_METRIC, HIGHER_BETTER, _METRIC_ALIASES

_EPS = 1e-15


def auc_device(y, s):
    """ROC-AUC via the midrank statistic — jax mirror of metrics.auc.

    Tie-group boundaries are computed in exact int32 (f32 indices would
    collapse above 2^24 rows); the rank sum is an f32 tree reduction,
    ~1e-6 relative error at 1M rows."""
    n = s.shape[0]
    order = jnp.argsort(s, stable=True)
    ss = s[order]
    pos_sorted = y[order] > 0.5
    i_arr = jnp.arange(n, dtype=jnp.int32)
    is_first = jnp.concatenate([jnp.ones((1,), bool), ss[1:] != ss[:-1]])
    is_last = jnp.concatenate([ss[1:] != ss[:-1], jnp.ones((1,), bool)])
    # group start: running max of first-of-group indices; group end: the
    # same trick on the reversed array
    gs = jax.lax.cummax(jnp.where(is_first, i_arr, -1))
    ge_rev = jax.lax.cummax(jnp.where(is_last[::-1], i_arr, -1))
    ge = (n - 1) - ge_rev[::-1]
    ranks = 0.5 * (gs + ge).astype(jnp.float32) + 1.0  # midranks, 1-based
    n_pos = jnp.sum(pos_sorted.astype(jnp.float32))
    n_neg = n - n_pos
    sum_pos_ranks = jnp.sum(jnp.where(pos_sorted, ranks, 0.0))
    value = (sum_pos_ranks - n_pos * (n_pos + 1.0) * 0.5) / (n_pos * n_neg)
    return jnp.where((n_pos == 0) | (n_neg == 0), jnp.float32(jnp.nan), value)


def binary_logloss_device(y, s):
    # stable form: softplus(s) - y*s == -(y log p + (1-y) log(1-p)); the
    # f32-naive clip(sigmoid, eps, 1-eps) rounds 1-1e-15 to 1.0 and NaNs on
    # saturated scores.  Per-row cap mirrors the numpy oracle's eps clip.
    loss = jax.nn.softplus(s) - y * s
    return jnp.mean(jnp.minimum(loss, jnp.float32(-np.log(_EPS))))


def multi_logloss_device(y, s):
    p = jax.nn.softmax(s, axis=1)
    p = jnp.clip(p, _EPS, 1.0)
    p = p / jnp.sum(p, axis=1, keepdims=True)
    py = jnp.take_along_axis(p, y.astype(jnp.int32)[:, None], axis=1)[:, 0]
    return -jnp.mean(jnp.log(py))


def error_device(y, s):
    if s.ndim == 1:  # binary raw scores: class 1 iff score > 0
        pred = (s > 0).astype(jnp.int32)
    else:
        pred = jnp.argmax(s, axis=1).astype(jnp.int32)
    return 1.0 - jnp.mean((pred == y.astype(jnp.int32)).astype(jnp.float32))


def rmse_device(y, s):
    d = y - s
    return jnp.sqrt(jnp.mean(d * d))


def mse_device(y, s):
    d = y - s
    return jnp.mean(d * d)


def mae_device(y, s):
    return jnp.mean(jnp.abs(y - s))


def poisson_deviance_device(y, s):
    """Mirror of metrics.poisson_deviance (raw log-rate scores); the 1e-30
    clamp epsilon matches the host mirror exactly (ADVICE r4)."""
    mu = jnp.exp(s)
    ylog = jnp.where(y > 0, y * jnp.log(jnp.maximum(y, 1e-30) / mu), 0.0)
    return jnp.mean(2.0 * (ylog - (y - mu)))


def _pad_queries(query_offsets: np.ndarray) -> tuple[np.ndarray, int]:
    """(Q, S) row-id scatter plan for per-query padded views; pad slots get
    row id N (out of range, gathered via mode='fill')."""
    qoff = np.asarray(query_offsets, np.int64)
    sizes = np.diff(qoff)
    Q, S = sizes.size, int(sizes.max(initial=1))
    ids = np.full((Q, S), qoff[-1], np.int64)
    for q in range(Q):
        ids[q, : sizes[q]] = np.arange(qoff[q], qoff[q + 1])
    return ids.astype(np.int32), int(qoff[-1])


def ndcg_device(y, s, qids, k):
    """Mean NDCG@k over padded (Q, S) query views — mirror of
    metrics.ndcg_at_k incl. the idcg==0 → 1.0 convention.

    ``qids`` is the (Q, S) row-id plan from ``_pad_queries``; padding slots
    hold an out-of-range id and are filled with rel=0 / score=-inf."""
    Q, S = qids.shape
    rel = y[jnp.minimum(qids, y.shape[0] - 1)]
    sc = s[jnp.minimum(qids, s.shape[0] - 1)]
    pad = qids >= y.shape[0]
    rel = jnp.where(pad, 0.0, rel)
    sc = jnp.where(pad, -jnp.inf, sc)

    pos = jnp.arange(S, dtype=jnp.float32)[None, :]
    # numpy sorts by -score with a stable mergesort; -inf padding lands last
    order = jnp.argsort(-sc, axis=1, stable=True)
    rel_by_score = jnp.take_along_axis(rel, order, axis=1)
    rel_ideal = -jnp.sort(-rel, axis=1)
    topk = (pos < k) & (pos < jnp.sum(~pad, axis=1)[:, None])
    disc = jnp.where(topk, 1.0 / jnp.log2(pos + 2.0), 0.0)
    dcg = jnp.sum((jnp.exp2(rel_by_score) - 1.0) * disc, axis=1)
    idcg = jnp.sum((jnp.exp2(rel_ideal) - 1.0) * disc, axis=1)
    ndcg = jnp.where(idcg == 0.0, 1.0, dcg / idcg)
    return jnp.mean(ndcg)


def eval_value(name, ndcg_at, y, raw_score, qids=None):
    """Raw (traceable) metric value — shared by the standalone ``_eval_jit``
    and the chunked trainer, which evaluates INSIDE its device program."""
    s = raw_score
    if s.ndim == 2 and s.shape[1] == 1:
        s = s[:, 0]
    if name == "auc":
        return auc_device(y, s)
    if name == "binary_logloss":
        return binary_logloss_device(y, s)
    if name == "multi_logloss":
        return multi_logloss_device(y, s)
    if name == "accuracy":
        return 1.0 - error_device(y, s)
    if name == "error":
        return error_device(y, s)
    if name == "rmse":
        return rmse_device(y, s)
    if name == "mse":
        return mse_device(y, s)
    if name == "mae":
        return mae_device(y, s)
    if name == "poisson_deviance":
        return poisson_deviance_device(y, s)
    if name == "ndcg":
        return ndcg_device(y, s, qids, ndcg_at)
    raise ValueError(f"unknown metric {name!r}")


_eval_jit = partial(jax.jit, static_argnames=("name", "ndcg_at"))(eval_value)


def make_evaluator(objective: str, metric: str, valid_ds, ndcg_at: int = 10):
    """(name, higher_better, fn) — ``fn(vscore_device) -> f32 device scalar``.

    ``valid_ds``'s labels (and query plan for ndcg) upload once; the
    returned fn is a reusable jitted program keyed on (metric, shapes)."""
    name = metric or DEFAULT_METRIC[objective]
    name = _METRIC_ALIASES.get(name, name)
    if name not in HIGHER_BETTER:
        # same exception type as the CPU backend's evaluate_raw
        raise ValueError(f"unknown metric {name!r}")
    qids = None
    if name == "ndcg":
        if valid_ds.query_offsets is None:
            raise ValueError("ndcg requires query groups on the validation set")
        qoff = np.asarray(valid_ds.query_offsets, np.int64)
        sizes = np.diff(qoff)
        Q, S = sizes.size, int(sizes.max(initial=1))
        N = int(qoff[-1])
        # the dense (Q, S) plan explodes on skewed group sizes (100k tiny
        # queries + one 1M-row group -> Q*S ~ 1e11 ids): when the padded
        # view is much larger than the data, evaluate on the HOST instead —
        # one score fetch per eval (the deferred-fetch optimization is lost,
        # correctness is not)
        if Q * S > max(8 * N, 1 << 24):
            from dryad_tpu.metrics import ndcg_at_k

            y_np = np.asarray(valid_ds.y)
            qoff_np = qoff

            def fn_host(vscore):
                s = np.asarray(vscore)
                if s.ndim == 2 and s.shape[1] == 1:
                    s = s[:, 0]
                return np.float32(ndcg_at_k(y_np, s, qoff_np, ndcg_at))

            fn_host.host_only = True  # chunked trainer cannot inline this
            return name, HIGHER_BETTER[name], fn_host
        qids = jnp.asarray(_pad_queries(valid_ds.query_offsets)[0])

    # labels upload only when a device evaluator is actually returned
    y = jnp.asarray(np.asarray(valid_ds.y, np.float32))

    def fn(vscore):
        return _eval_jit(name, ndcg_at, y, vscore, qids)

    # the chunked trainer inlines the metric INSIDE its device program —
    # expose the pieces eval_value needs
    fn.host_only = False
    fn.metric_name = name
    fn.y_dev = y
    fn.qids = qids
    return name, HIGHER_BETTER[name], fn
