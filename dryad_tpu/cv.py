"""k-fold cross-validation — the LightGBM ``cv()`` entry point of the
de-facto GBDT surface (SURVEY.md §2 #9's API family).

Rows are binned ONCE (the input Dataset's frozen mapper is shared by
every fold — fold matrices are row slices of the already-binned table),
then each fold trains with its holdout as the validation set and the
per-iteration metric values aggregate to mean/std curves.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dryad_tpu.config import make_params
from dryad_tpu.dataset import Dataset


def _fold_indices(y: np.ndarray, nfold: int, stratified: bool,
                  shuffle: bool, seed: int) -> list[np.ndarray]:
    """Per-fold holdout row ids; stratified keeps label proportions by
    interleaving each class's (optionally shuffled) rows round-robin."""
    N = y.shape[0]
    rng = np.random.default_rng(seed)
    if stratified:
        order = np.empty(N, np.int64)
        pos = 0
        classes = np.unique(y)
        buckets: list[np.ndarray] = [[] for _ in range(nfold)]
        for c in classes:
            rows = np.flatnonzero(y == c)
            if shuffle:
                rows = rng.permutation(rows)
            for k in range(nfold):
                buckets[k].append(rows[k::nfold])
        return [np.sort(np.concatenate(b)) for b in buckets]
    rows = rng.permutation(N) if shuffle else np.arange(N)
    return [np.sort(rows[k::nfold]) for k in range(nfold)]


def cv(params, train_set: Dataset, nfold: int = 5, *,
       stratified: Optional[bool] = None, shuffle: bool = True,
       seed: int = 0, backend: str = "auto",
       return_boosters: bool = False) -> dict:
    """k-fold CV: returns ``{"valid_<metric>-mean": [...],
    "valid_<metric>-stdv": [...]}`` per-iteration curves (the -mean/-stdv
    suffix convention of LightGBM's cv, on THIS library's underscore
    eval-history keys, e.g. ``valid_auc-mean``), truncated to the
    shortest fold when early stopping ends folds at different lengths;
    ``return_boosters=True`` adds the per-fold boosters under
    ``"boosters"``.

    ``stratified`` defaults to True for binary/multiclass and False
    otherwise.  Ranking data (query groups) is rejected — row-level folds
    would split queries."""
    import dryad_tpu as dryad

    p = make_params(params)
    if train_set.group is not None:
        raise ValueError("cv does not support ranking data: row-level "
                         "folds would split query groups")
    if nfold < 2:
        raise ValueError("nfold must be >= 2")
    y = train_set.y
    if y is None:
        raise ValueError("cv needs labels on the Dataset")
    if stratified is None:
        stratified = p.objective in ("binary", "multiclass")

    folds = _fold_indices(y, nfold, stratified, shuffle, seed)
    all_rows = np.arange(train_set.num_rows)
    Xb = train_set.X_binned
    w = train_set.weight
    curves: list[dict[str, np.ndarray]] = []
    boosters = []
    for hold in folds:
        tr = np.setdiff1d(all_rows, hold, assume_unique=True)
        ds_tr = Dataset.from_binned(
            Xb[tr], train_set.mapper, y[tr],
            weight=None if w is None else w[tr],
            categorical_features=train_set.categorical_features)
        ds_va = Dataset.from_binned(
            Xb[hold], train_set.mapper, y[hold],
            weight=None if w is None else w[hold],
            categorical_features=train_set.categorical_features)
        b = dryad.train(p, ds_tr, [ds_va], backend=backend)
        hist = b.train_state.get("eval_history", {})
        curves.append({name: np.asarray([v for _, v in rows], np.float64)
                       for name, rows in hist.items()})
        boosters.append(b)

    out: dict = {}
    for name in curves[0]:
        L = min(c[name].shape[0] for c in curves)
        stack = np.stack([c[name][:L] for c in curves])
        out[f"{name}-mean"] = stack.mean(axis=0).tolist()
        out[f"{name}-stdv"] = stack.std(axis=0).tolist()
    if return_boosters:
        out["boosters"] = boosters
    return out
