"""ctypes bridge to the native host layer (``src/dryad_native.cpp``).

The reference keeps sketching/binning/predict hot loops in native code
(BASELINE.json:5); here they live in a zero-dependency shared object built
with ``make -C dryad_tpu/native`` and loaded through ctypes (the image has
no pybind11).  The pure-numpy implementations in ``data/sketch.py`` /
``cpu/predict.py`` remain the bit-exact *spec*; this module is the fast
path and must match them bit for bit (tests/test_native.py diffs them).

Loading is lazy and failure-tolerant: if the .so is absent we try one
quiet ``make``; if the toolchain is missing, ``available()`` is False and
every caller falls back to numpy.  ``DRYAD_NATIVE=0`` disables the native
path outright.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libdryad_native.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False
# must equal dryad_abi_version() in the .so; a stale binary that failed to
# rebuild would otherwise be called through the wrong signature
_ABI_VERSION = 2

_i64 = ctypes.c_int64
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")


def _build() -> bool:
    try:
        res = subprocess.run(
            ["make", "-C", _HERE],
            capture_output=True,
            timeout=120,
        )
        return res.returncode == 0 and os.path.exists(_SO)
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("DRYAD_NATIVE", "1") == "0":
        return None
    src = os.path.join(_HERE, "src", "dryad_native.cpp")
    stale = (
        os.path.exists(_SO)
        and os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(_SO)
    )
    if (not os.path.exists(_SO) or stale) and not _build() and not os.path.exists(_SO):
        return None
    try:
        lib = ctypes.CDLL(_SO)

        lib.dryad_abi_version.restype = _i64
        lib.dryad_abi_version.argtypes = []
        if lib.dryad_abi_version() != _ABI_VERSION:
            return None

        lib.sketch_numerical.restype = _i64
        lib.sketch_numerical.argtypes = [_f32p, _i64, _i64, _f32p]
        lib.bin_matrix.restype = None
        lib.bin_matrix.argtypes = [
            _f32p, _i64, _i64, _f32p, _i64p, _f32p, _i32p, _i64p, _u8p, _i32p,
            _u16p,
        ]
        lib.predict_accumulate.restype = None
        lib.predict_accumulate.argtypes = [
            _u16p, _i64, _i64, _i32p, _i32p, _i32p, _i32p, _u8p, _u32p, _u8p,
            _f32p, _i64, _i64, _i64, _i64, _i64, _f32p,
        ]
    except (OSError, AttributeError):
        # stale/incompatible binary: fall back to numpy rather than crash
        return None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def sketch_numerical(col: np.ndarray, max_bins: int) -> Optional[np.ndarray]:
    """Native numerical quantile sketch -> ascending float32 edges, or None."""
    lib = _load()
    if lib is None:
        return None
    col = np.ascontiguousarray(col, np.float32)
    out = np.empty(max(int(max_bins), 2), np.float32)
    k = lib.sketch_numerical(col, col.size, int(max_bins), out)
    return out[:k].copy()


def bin_matrix(X: np.ndarray, mapper) -> Optional[np.ndarray]:
    """Native dense binning through a frozen BinMapper, or None."""
    lib = _load()
    if lib is None:
        return None
    X = np.ascontiguousarray(X, np.float32)
    n, F = X.shape
    feats = mapper.features

    edge_offsets = np.zeros(F + 1, np.int64)
    cat_offsets = np.zeros(F + 1, np.int64)
    for f, fb in enumerate(feats):
        edge_offsets[f + 1] = edge_offsets[f] + fb.edges.size
        cat_offsets[f + 1] = cat_offsets[f] + fb.cat_values.size
    edges_flat = np.empty(max(int(edge_offsets[-1]), 1), np.float32)
    catv_flat = np.empty(max(int(cat_offsets[-1]), 1), np.float32)
    catb_flat = np.empty(max(int(cat_offsets[-1]), 1), np.int32)
    for f, fb in enumerate(feats):
        edges_flat[edge_offsets[f] : edge_offsets[f + 1]] = fb.edges
        catv_flat[cat_offsets[f] : cat_offsets[f + 1]] = fb.cat_values
        catb_flat[cat_offsets[f] : cat_offsets[f + 1]] = fb.cat_bins
    is_cat = mapper.is_categorical.astype(np.uint8)
    overflow = np.array([fb.overflow_bin for fb in feats], np.int32)

    out = np.empty((n, F), np.uint16)
    lib.bin_matrix(
        X, n, F, edges_flat, edge_offsets, catv_flat, catb_flat, cat_offsets,
        is_cat, overflow, out,
    )
    return out.astype(mapper.bin_dtype, copy=False)


def predict_accumulate(
    Xb: np.ndarray,
    trees: dict[str, np.ndarray],
    init_score: np.ndarray,
    num_trees: int,
    K: int,
    depth_bound: int,
) -> Optional[np.ndarray]:
    """Native booster predict: (N, K) raw scores, or None."""
    lib = _load()
    if lib is None:
        return None
    Xb = np.ascontiguousarray(Xb, np.uint16)
    n, F = Xb.shape
    feature = np.ascontiguousarray(trees["feature"], np.int32)
    max_nodes = feature.shape[1]
    cat_bitset = np.ascontiguousarray(trees["cat_bitset"], np.uint32)
    cat_words = cat_bitset.shape[2]
    score = np.broadcast_to(
        np.asarray(init_score, np.float32), (n, K)
    ).astype(np.float32, order="C")
    lib.predict_accumulate(
        Xb, n, F,
        feature,
        np.ascontiguousarray(trees["threshold"], np.int32),
        np.ascontiguousarray(trees["left"], np.int32),
        np.ascontiguousarray(trees["right"], np.int32),
        np.ascontiguousarray(trees["is_cat"], np.uint8),
        cat_bitset,
        np.ascontiguousarray(
            trees.get("default_left", np.ones_like(trees["feature"], dtype=bool)),
            np.uint8),
        np.ascontiguousarray(trees["value"], np.float32),
        int(num_trees), max_nodes, cat_words, int(K), max(int(depth_bound), 1),
        score,
    )
    return score
