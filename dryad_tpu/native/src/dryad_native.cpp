// dryad_tpu native host layer: quantile sketch, binning, CSR ingest, predict.
//
// The reference keeps its data layer in native code (BASELINE.json:5 —
// "categorical and sparse binning, quantile sketching" are engine-side
// CUDA/C++); the TPU build keeps the same split: device compute in
// XLA/Pallas, host data preparation in C++ behind ctypes.
//
// BIT-IDENTITY CONTRACT: every routine here must reproduce the canonical
// numpy implementation in dryad_tpu/data/sketch.py bit for bit — the numpy
// path is the spec, this is the fast path.  Tests diff them exhaustively
// (tests/test_native.py).  All float work is float32 with the same op
// order as numpy.
//
// Build: make -C dryad_tpu/native  (g++ -O3 -shared; zero dependencies).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Bumped on any signature change; the ctypes loader refuses a mismatched
// (or symbol-less, pre-versioning) binary and falls back to numpy instead
// of calling through a stale ABI.
int64_t dryad_abi_version() { return 2; }

// ---------------------------------------------------------------------------
// Numerical quantile sketch: reproduce _sketch_numerical (data/sketch.py).
//   col: n float32 values (may contain NaN/inf)
//   out_edges: caller-allocated buffer of size max_bins
//   returns number of edges written (k); total bins = k + 2
// ---------------------------------------------------------------------------
int64_t sketch_numerical(const float* col, int64_t n, int64_t max_bins,
                         float* out_edges) {
    std::vector<float> finite;
    finite.reserve(n);
    for (int64_t i = 0; i < n; ++i) {
        if (std::isfinite(col[i])) finite.push_back(col[i]);
    }
    if (finite.empty()) return 0;
    std::sort(finite.begin(), finite.end());
    // distinct values (np.unique = sort + adjacent dedup)
    std::vector<float> distinct;
    distinct.reserve(finite.size());
    for (float v : finite) {
        if (distinct.empty() || distinct.back() != v) distinct.push_back(v);
    }
    const int64_t max_edges = max_bins - 2;
    int64_t k = 0;
    if ((int64_t)distinct.size() - 1 <= max_edges) {
        // midpoints between neighbours, float32 arithmetic like numpy:
        // (a + b) * 0.5f
        for (size_t i = 0; i + 1 < distinct.size(); ++i) {
            out_edges[k++] = (distinct[i] + distinct[i + 1]) * 0.5f;
        }
    } else {
        // equal-frequency positions over the sorted sample, deduplicated
        const int64_t sz = (int64_t)finite.size();
        float prev = 0.0f;
        bool has_prev = false;
        for (int64_t i = 1; i <= max_edges; ++i) {
            const int64_t pos = (i * sz) / (max_edges + 1);
            const float e = finite[pos];
            if (!has_prev || e != prev) {   // np.unique on ascending picks
                out_edges[k++] = e;
                prev = e;
                has_prev = true;
            }
        }
    }
    return k;
}

// ---------------------------------------------------------------------------
// Numerical binning: out[i] = 1 + lower_bound(edges, x) ; NaN -> 0.
// Matches transform_column's searchsorted(side='left') + missing rule.
// ---------------------------------------------------------------------------
void bin_numerical(const float* col, int64_t n, const float* edges,
                   int64_t n_edges, int32_t* out) {
    const float* lo = edges;
    const float* hi = edges + n_edges;
    for (int64_t i = 0; i < n; ++i) {
        const float x = col[i];
        if (std::isnan(x)) {
            out[i] = 0;
        } else {
            out[i] = 1 + (int32_t)(std::lower_bound(lo, hi, x) - lo);
        }
    }
}

// ---------------------------------------------------------------------------
// Categorical binning: sorted vocab lookup; miss/unseen -> overflow bin;
// NaN -> 0.  Matches transform_column's categorical branch.
// ---------------------------------------------------------------------------
void bin_categorical(const float* col, int64_t n, const float* cat_values,
                     const int32_t* cat_bins, int64_t n_cats,
                     int32_t overflow_bin, int32_t* out) {
    const float* lo = cat_values;
    const float* hi = cat_values + n_cats;
    for (int64_t i = 0; i < n; ++i) {
        const float x = col[i];
        if (std::isnan(x)) {
            out[i] = 0;
            continue;
        }
        if (n_cats == 0) {
            out[i] = overflow_bin;
            continue;
        }
        const float* it = std::lower_bound(lo, hi, x);
        if (it != hi && *it == x) {
            out[i] = cat_bins[it - lo];
        } else {
            out[i] = overflow_bin;
        }
    }
}

// ---------------------------------------------------------------------------
// Dense matrix binning, column-parallel-friendly layout.
//   X: (n, F) row-major float32;  edge data packed per feature.
//   edges_flat + edge_offsets[f..f+1]: feature f's edges
//   catv_flat/catb_flat + cat_offsets: categorical vocab (empty for numeric)
//   is_cat: per-feature flag;  overflow: per-feature overflow bin id
//   out: (n, F) row-major uint16
// ---------------------------------------------------------------------------
void bin_matrix(const float* X, int64_t n, int64_t F,
                const float* edges_flat, const int64_t* edge_offsets,
                const float* catv_flat, const int32_t* catb_flat,
                const int64_t* cat_offsets, const uint8_t* is_cat,
                const int32_t* overflow, uint16_t* out) {
    std::vector<float> colbuf(n);
    std::vector<int32_t> outbuf(n);
    for (int64_t f = 0; f < F; ++f) {
        for (int64_t i = 0; i < n; ++i) colbuf[i] = X[i * F + f];
        if (is_cat[f]) {
            bin_categorical(colbuf.data(), n, catv_flat + cat_offsets[f],
                            catb_flat + cat_offsets[f],
                            cat_offsets[f + 1] - cat_offsets[f], overflow[f],
                            outbuf.data());
        } else {
            bin_numerical(colbuf.data(), n, edges_flat + edge_offsets[f],
                          edge_offsets[f + 1] - edge_offsets[f], outbuf.data());
        }
        for (int64_t i = 0; i < n; ++i) out[i * F + f] = (uint16_t)outbuf[i];
    }
}

// ---------------------------------------------------------------------------
// Vectorized single-tree traversal on binned rows (CPU predict hot loop).
// Mirrors cpu/predict.py::predict_tree_leaves: compare bin ids, categorical
// bitset membership, self-loop at leaves.
// ---------------------------------------------------------------------------
void tree_leaves(const uint16_t* Xb, int64_t n, int64_t F,
                 const int32_t* feature, const int32_t* threshold,
                 const int32_t* left, const int32_t* right,
                 const uint8_t* is_cat, const uint32_t* cat_bitset,
                 const uint8_t* default_left, int64_t cat_words,
                 int64_t depth_bound, int32_t* out_leaf) {
    for (int64_t i = 0; i < n; ++i) {
        int32_t node = 0;
        for (int64_t d = 0; d < depth_bound; ++d) {
            const int32_t f = feature[node];
            if (f < 0) break;
            const int32_t b = (int32_t)Xb[i * F + f];
            bool go_left;
            if (is_cat[node]) {
                int64_t w = b >> 5;
                if (w > cat_words - 1) w = cat_words - 1;
                go_left = (cat_bitset[node * cat_words + w] >> (b & 31)) & 1u;
            } else {
                // learned missing direction: bin 0 only goes left when the
                // node's default_left bit is set (cpu/predict.py contract)
                go_left = b <= threshold[node] && (default_left[node] || b != 0);
            }
            node = go_left ? left[node] : right[node];
        }
        out_leaf[i] = node;
    }
}

// Full-booster predict accumulation: score[i*K + k] += value[t][leaf].
void predict_accumulate(const uint16_t* Xb, int64_t n, int64_t F,
                        const int32_t* feature, const int32_t* threshold,
                        const int32_t* left, const int32_t* right,
                        const uint8_t* is_cat, const uint32_t* cat_bitset,
                        const uint8_t* default_left,
                        const float* value, int64_t num_trees, int64_t max_nodes,
                        int64_t cat_words, int64_t K, int64_t depth_bound,
                        float* score) {
    std::vector<int32_t> leaves(n);
    for (int64_t t = 0; t < num_trees; ++t) {
        const int64_t off = t * max_nodes;
        tree_leaves(Xb, n, F, feature + off, threshold + off, left + off,
                    right + off, is_cat + off, cat_bitset + off * cat_words,
                    default_left + off, cat_words, depth_bound, leaves.data());
        const float* vt = value + off;
        const int64_t k = t % K;
        for (int64_t i = 0; i < n; ++i) {
            score[i * K + k] += vt[leaves[i]];
        }
    }
}

}  // extern "C"
