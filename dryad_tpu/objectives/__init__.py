"""Training objectives: gradients/hessians + init score + output transform.

Each objective ships a canonical numpy implementation (used by the CPU
reference trainer, the parity oracle per BASELINE.json:5) and a jax
implementation (used on-device by the TPU engine).  Tests assert the two
agree to fp32 tolerance (SURVEY.md §4 "each objective's grad/hess vs
autodiff").

Sign convention: we *minimize* the loss; ``g = dL/ds`` for raw score s, and
the Newton leaf value is ``-G/(H + lambda_l2)``.
"""

from __future__ import annotations

import numpy as np

from dryad_tpu.metrics import dcg_at_k


def _sigmoid_np(x):
    return 1.0 / (1.0 + np.exp(-x))


class Binary:
    """Binary cross-entropy on logit scores (Higgs config, BASELINE.json:7).

    ``scale_pos_weight`` multiplies the positive class's grad/hess (and its
    share of the init score) — an implicit per-row weight composing
    multiplicatively with explicit sample weights.
    """

    name = "binary"
    num_outputs = 1

    def __init__(self, scale_pos_weight: float = 1.0):
        self.spw = float(scale_pos_weight)

    def _weights_np(self, y, weight):
        w = np.ones_like(y, np.float32) if weight is None else np.asarray(weight, np.float32)
        if self.spw != 1.0:
            w = w * np.where(y > 0.5, np.float32(self.spw), np.float32(1.0))
        return w

    def init_score(self, y: np.ndarray, weight=None) -> float:
        w = self._weights_np(np.asarray(y, np.float32), weight)
        p = float(np.clip(np.average(y, weights=w), 1e-12, 1 - 1e-12))
        return float(np.log(p / (1 - p)))

    def grad_hess_np(self, score: np.ndarray, y: np.ndarray, weight=None):
        p = _sigmoid_np(score.astype(np.float32))
        g = (p - y).astype(np.float32)
        h = (p * (1.0 - p)).astype(np.float32)
        w = self._weights_np(np.asarray(y, np.float32), weight)
        return g * w, h * w

    def grad_hess_jax(self, score, y, weight=None):
        import jax.numpy as jnp  # local: keep numpy path importable without jax init

        p = jnp.asarray(1.0, jnp.float32) / (1.0 + jnp.exp(-score))
        g = p - y
        h = p * (1.0 - p)
        # combine explicit weight and scale_pos_weight into ONE vector before
        # multiplying g/h — same rounding order as _weights_np, so gain-argmax
        # ties cannot flip between backends when both are in play
        w = weight
        if self.spw != 1.0:
            wp = jnp.where(y > 0.5, jnp.float32(self.spw), jnp.float32(1.0))
            w = wp if w is None else w * wp
        if w is not None:
            g, h = g * w, h * w
        return g, h

    @staticmethod
    def transform_np(score: np.ndarray) -> np.ndarray:
        return _sigmoid_np(score)


class Regression:
    """Squared error on raw scores (Epsilon config, BASELINE.json:9)."""

    name = "regression"
    num_outputs = 1

    @staticmethod
    def init_score(y: np.ndarray, weight=None) -> float:
        w = np.ones_like(y) if weight is None else weight
        return float(np.average(y, weights=w))

    @staticmethod
    def grad_hess_np(score, y, weight=None):
        g = (score - y).astype(np.float32)
        h = np.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    @staticmethod
    def grad_hess_jax(score, y, weight=None):
        import jax.numpy as jnp

        g = score - y
        h = jnp.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    @staticmethod
    def transform_np(score):
        return score


def _weighted_percentile(y: np.ndarray, weight, q: float) -> float:
    """Percentile of y at level q in [0, 1], weight-aware (sorted cumsum
    convention — reduces to the lower-interpolation percentile unweighted).
    Shared init-score helper for the robust-regression family."""
    y = np.asarray(y, np.float64)
    order = np.argsort(y, kind="mergesort")
    ys = y[order]
    w = (np.ones_like(ys) if weight is None
         else np.asarray(weight, np.float64)[order])
    cw = np.cumsum(w)
    target = q * cw[-1]
    idx = int(np.searchsorted(cw, target, side="left"))
    return float(ys[min(idx, ys.size - 1)])


class L1:
    """Absolute error on raw scores.  Gradient sign(s - y), hessian 1
    (LightGBM's formulation); after growth the trainers RENEW each leaf to
    the median of its in-bag residuals (see renew_alpha — LightGBM's
    RenewTreeOutput semantics), replacing the sign-mean Newton value."""

    name = "l1"
    num_outputs = 1

    @staticmethod
    def init_score(y: np.ndarray, weight=None) -> float:
        return _weighted_percentile(y, weight, 0.5)

    @staticmethod
    def grad_hess_np(score, y, weight=None):
        g = np.sign(score - y).astype(np.float32)
        h = np.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    @staticmethod
    def grad_hess_jax(score, y, weight=None):
        import jax.numpy as jnp

        g = jnp.sign(score - y)
        h = jnp.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    @staticmethod
    def transform_np(score):
        return score


class Huber:
    """Huber loss: squared near zero, linear past ``delta`` (params.alpha,
    the LightGBM convention).  Gradient clips the residual at ±delta,
    hessian stays 1 (the piecewise-zero true hessian would stall leaves)."""

    name = "huber"
    num_outputs = 1

    def __init__(self, delta: float = 0.9):
        self.delta = float(delta)

    @staticmethod
    def init_score(y: np.ndarray, weight=None) -> float:
        return _weighted_percentile(y, weight, 0.5)

    def grad_hess_np(self, score, y, weight=None):
        r = (score - y).astype(np.float32)
        d = np.float32(self.delta)
        g = np.clip(r, -d, d)
        h = np.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def grad_hess_jax(self, score, y, weight=None):
        import jax.numpy as jnp

        d = jnp.float32(self.delta)
        g = jnp.clip(score - y, -d, d)
        h = jnp.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    @staticmethod
    def transform_np(score):
        return score


class Fair:
    """Fair loss c^2 * (|r|/c - log(1 + |r|/c)): a smooth robust loss with
    everywhere-positive hessian c^2/(|r| + c)^2 (params.fair_c)."""

    name = "fair"
    num_outputs = 1

    def __init__(self, c: float = 1.0):
        self.c = float(c)

    @staticmethod
    def init_score(y: np.ndarray, weight=None) -> float:
        return _weighted_percentile(y, weight, 0.5)

    def grad_hess_np(self, score, y, weight=None):
        r = (score - y).astype(np.float32)
        c = np.float32(self.c)
        denom = np.abs(r) + c
        g = c * r / denom
        h = c * c / (denom * denom)
        if weight is not None:
            g, h = g * weight, h * weight
        return g.astype(np.float32), h.astype(np.float32)

    def grad_hess_jax(self, score, y, weight=None):
        import jax.numpy as jnp

        c = jnp.float32(self.c)
        r = score - y
        denom = jnp.abs(r) + c
        g = c * r / denom
        h = c * c / (denom * denom)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    @staticmethod
    def transform_np(score):
        return score


class Quantile:
    """Pinball loss at level ``alpha``: the booster estimates the alpha-
    quantile of y | x.  Gradient is -alpha below the data, (1 - alpha)
    above; hessian 1 (LightGBM's formulation; leaves are renewed to the
    alpha-percentile of in-bag residuals post-growth, see renew_alpha)."""

    name = "quantile"
    num_outputs = 1

    def __init__(self, alpha: float = 0.9):
        self.alpha = float(alpha)

    def init_score(self, y: np.ndarray, weight=None) -> float:
        return _weighted_percentile(y, weight, self.alpha)

    def grad_hess_np(self, score, y, weight=None):
        a = np.float32(self.alpha)
        g = np.where(score < y, -a, np.float32(1.0) - a).astype(np.float32)
        h = np.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def grad_hess_jax(self, score, y, weight=None):
        import jax.numpy as jnp

        a = jnp.float32(self.alpha)
        g = jnp.where(score < y, -a, jnp.float32(1.0) - a)
        h = jnp.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    @staticmethod
    def transform_np(score):
        return score


class Poisson:
    """Poisson regression on a log link: raw score is log(rate); predict
    applies exp.  Gradient exp(s) - y; hessian exp(s + max_delta_step)
    (the LightGBM stabilizer — pure exp(s) underestimates curvature for
    small rates and overshoots leaves)."""

    name = "poisson"
    num_outputs = 1

    def __init__(self, max_delta_step: float = 0.7):
        self.mds = float(max_delta_step)

    @staticmethod
    def init_score(y: np.ndarray, weight=None) -> float:
        ya = np.asarray(y, np.float64)
        if (ya < 0).any():
            raise ValueError("poisson objective requires non-negative labels")
        w = np.ones_like(ya) if weight is None else weight
        mean = float(np.average(ya, weights=w))
        return float(np.log(max(mean, 1e-12)))

    def grad_hess_np(self, score, y, weight=None):
        s = score.astype(np.float32)
        g = (np.exp(s) - y).astype(np.float32)
        h = np.exp(s + np.float32(self.mds)).astype(np.float32)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def grad_hess_jax(self, score, y, weight=None):
        import jax.numpy as jnp

        g = jnp.exp(score) - y
        h = jnp.exp(score + jnp.float32(self.mds))
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    @staticmethod
    def transform_np(score):
        return np.exp(score)


class Multiclass:
    """Softmax cross-entropy; K parallel trees per iteration (Covertype,
    BASELINE.json:8).  score shape (N, K); y holds class ids."""

    name = "multiclass"

    def __init__(self, num_class: int):
        self.num_class = int(num_class)
        self.num_outputs = self.num_class

    def init_score(self, y: np.ndarray, weight=None) -> np.ndarray:
        # uniform prior start (all-zero logits) keeps CPU/TPU trivially identical
        return np.zeros(self.num_class, np.float32)

    def grad_hess_np(self, score: np.ndarray, y: np.ndarray, weight=None):
        s = score.astype(np.float64)
        s -= s.max(axis=1, keepdims=True)
        e = np.exp(s)
        p = (e / e.sum(axis=1, keepdims=True)).astype(np.float32)
        onehot = np.zeros_like(p)
        onehot[np.arange(y.shape[0]), y.astype(np.int64)] = 1.0
        g = p - onehot
        h = p * (1.0 - p)
        if weight is not None:
            g, h = g * weight[:, None], h * weight[:, None]
        return g, h

    def grad_hess_jax(self, score, y, weight=None):
        import jax
        import jax.numpy as jnp

        p = jax.nn.softmax(score, axis=1)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), self.num_class, dtype=jnp.float32)
        g = p - onehot
        h = p * (1.0 - p)
        if weight is not None:
            g, h = g * weight[:, None], h * weight[:, None]
        return g, h

    @staticmethod
    def transform_np(score):
        s = score.astype(np.float64)
        s -= s.max(axis=1, keepdims=True)
        e = np.exp(s)
        return (e / e.sum(axis=1, keepdims=True)).astype(np.float32)


class LambdaRank:
    """LambdaMART pairwise ranking with |ΔNDCG| weighting (MSLR config,
    BASELINE.json:10).  Canonical numpy path iterates queries with a
    vectorized pair matrix per query; the TPU path (engine/lambdarank) uses
    padded per-query segments (SURVEY.md §3, §7 hard part d).
    """

    name = "lambdarank"
    num_outputs = 1

    def __init__(self, sigmoid: float = 1.0, truncation: int = 30):
        self.sigma = float(sigmoid)
        self.truncation = int(truncation)

    @staticmethod
    def init_score(y: np.ndarray, weight=None) -> float:
        return 0.0

    def grad_hess_np(self, score, y, weight=None, query_offsets=None):
        assert query_offsets is not None, "lambdarank requires query groups"
        n = score.shape[0]
        g = np.zeros(n, np.float32)
        h = np.zeros(n, np.float32)
        for q in range(query_offsets.size - 1):
            a, b = int(query_offsets[q]), int(query_offsets[q + 1])
            gq, hq = self._query_grad(score[a:b], y[a:b])
            g[a:b], h[a:b] = gq, hq
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def _query_grad(self, s: np.ndarray, rel: np.ndarray):
        m = s.shape[0]
        g = np.zeros(m, np.float32)
        h = np.zeros(m, np.float32)
        if m < 2:
            return g, h
        order = np.argsort(-s, kind="mergesort")  # current ranking, stable
        rank_of = np.empty(m, np.int64)
        rank_of[order] = np.arange(m)
        ideal = np.sort(rel)[::-1]
        inv_max_dcg = dcg_at_k(ideal, m)
        if inv_max_dcg <= 0.0:
            return g, h
        inv_max_dcg = 1.0 / inv_max_dcg
        gains = np.power(2.0, rel.astype(np.float64)) - 1.0
        discounts = 1.0 / np.log2(rank_of.astype(np.float64) + 2.0)
        # truncation: only pairs where the better-ranked doc sits in top-k
        topk = rank_of < self.truncation
        rel_diff = rel[:, None] - rel[None, :]
        valid = (rel_diff > 0) & (topk[:, None] | topk[None, :])
        if not valid.any():
            return g, h
        sdiff = (s[:, None] - s[None, :]).astype(np.float64)
        rho = 1.0 / (1.0 + np.exp(self.sigma * sdiff))  # P(pair mis-ordered-ish)
        delta_ndcg = (
            np.abs(gains[:, None] - gains[None, :])
            * np.abs(discounts[:, None] - discounts[None, :])
            * inv_max_dcg
        )
        lam = np.where(valid, self.sigma * rho * delta_ndcg, 0.0)
        hes = np.where(valid, self.sigma * self.sigma * rho * (1.0 - rho) * delta_ndcg, 0.0)
        # i preferred over j: push s_i up (negative gradient), s_j down
        g -= lam.sum(axis=1).astype(np.float32)
        g += lam.sum(axis=0).astype(np.float32)
        h += (hes.sum(axis=1) + hes.sum(axis=0)).astype(np.float32)
        return g, h

    @staticmethod
    def transform_np(score):
        return score


def renew_alpha(params, weighted: bool = False) -> float | None:
    """Percentile level for post-growth leaf renewal, or None.

    LightGBM refits L1-family leaf outputs to residual percentiles after
    the tree is grown (RenewTreeOutput): the Newton step -G/(H+λ) with
    unit hessians estimates the leaf MEAN of the gradient signs, while the
    L1-optimal leaf value is the residual MEDIAN (and the pinball-optimal
    value the alpha-quantile).  Applied for l1 (median), quantile
    (params.alpha), and huber (median — the L1-family treatment; huber's
    minimizer lies between mean and median and the median is the robust
    choice).

    The ENTIRE gate lives here (not at the call sites, so a new caller
    can't forget part of it — same rule as update_best's DART gate):
    renewal is OFF for weighted datasets (our percentile is unweighted —
    documented divergence), for boosting dart/rf (dart redefines the
    ensemble mid-iteration; rf gradients live at the constant init
    score), and for monotone constraints (the grower clamps Newton values
    to the monotone bounds; an unclamped percentile could re-break the
    ordering)."""
    if weighted or params.boosting not in ("gbdt", "goss"):
        return None
    if params.monotone_constraints and any(params.monotone_constraints):
        return None
    if params.objective in ("l1", "huber"):
        return 0.5
    if params.objective == "quantile":
        return params.alpha
    return None


def get_objective(params) -> object:
    if params.objective == "binary":
        return Binary(params.scale_pos_weight)
    if params.objective == "regression":
        return Regression()
    if params.objective == "l1":
        return L1()
    if params.objective == "huber":
        return Huber(params.alpha)
    if params.objective == "fair":
        return Fair(params.fair_c)
    if params.objective == "quantile":
        return Quantile(params.alpha)
    if params.objective == "poisson":
        return Poisson(params.poisson_max_delta_step)
    if params.objective == "multiclass":
        return Multiclass(params.num_class)
    if params.objective == "lambdarank":
        return LambdaRank(params.sigmoid, params.lambdarank_truncation)
    raise ValueError(f"unknown objective {params.objective!r}")
