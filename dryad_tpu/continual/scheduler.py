"""Drift-triggered retrain scheduling — the decision half of continual
boosting.

The scheduler is a journal CONSUMER: the fleet router already owns the
drift verdict (obs/drift.py merges raw counts exactly and journals
``drift_breach`` on a sustained breach); this module's job is everything
between that event and a candidate artifact — debounce, budget, launch,
and the hand-off to the probation publisher.  Design rules:

* **Jax-free, always importable.**  The scheduler lives in the fleet
  control plane.  It must start, tail, and launch while a device is
  wedged mid-collective, so the retrain itself runs as a subprocess
  (``make_subprocess_launcher`` → ``python -m dryad_tpu retrain``) and
  the only wait the control plane ever does is a host ``subprocess``
  wait with a timeout.
* **One lock, nothing blocking under it.**  All debounce state sits
  behind ``_lock`` (declared in ``GUARDED_BY``); journal writes, metric
  bumps, file sniffs, subprocess waits, and the publisher's probation
  window all happen OUTSIDE it.  The atomic check-and-mark in
  ``_admit`` is the race-sensitive step — the schedule drill
  ``scheduler-breach-vs-push`` reverts it mechanically and proves the
  seeded scheduler catches the double-launch.
* **Skips are journaled, never silent.**  A breach that does not launch
  a retrain writes ``retrain_skipped`` with a machine-readable reason
  (``in_flight`` / ``budget`` / ``cooldown`` /
  ``retry_budget_exhausted`` / ``no_profile`` / ``unknown_model``).
  Pre-r18 profile-less artifacts are a *reason*, not a crash.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Mapping, Optional

from dryad_tpu.obs.registry import Registry, default_registry
from dryad_tpu.resilience.policy import RetryPolicy


def model_has_profile(path: str) -> bool:
    """Jax-free artifact sniff: does this saved model embed an r18
    reference profile?

    Mirrors ``Booster.load_any``'s magic dispatch (``PK`` → npz binary,
    else the JSON text dump) without importing the booster — the
    scheduler must answer this while a device is wedged, and the profile
    lives in the artifact's JSON metadata either way.  Raises ``OSError``
    / ``ValueError`` on an unreadable artifact; the scheduler maps that
    to a journaled skip.
    """
    with open(path, "rb") as f:
        magic = f.read(2)
    if magic == b"PK":
        import numpy as np

        with np.load(path) as z:
            meta = json.loads(
                np.asarray(z["meta"], dtype=np.uint8).tobytes().decode("utf-8"))
        return meta.get("profile") is not None
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    return doc.get("profile") is not None


class JournalTailer:
    """Incremental reader over a ``RunJournal`` JSONL file.

    Each call returns the events appended since the previous call, in
    order.  Only COMPLETE lines (newline-terminated) are consumed — a
    writer caught mid-line keeps its bytes for the next poll, so a torn
    read can never drop or mangle an event.  Single-consumer by design
    (the scheduler's tail thread); it owns no lock.
    """

    def __init__(self, path: str, *, start_at_end: bool = False):
        self.path = str(path)
        self._offset = 0
        if start_at_end:
            try:
                self._offset = os.path.getsize(self.path)
            except OSError:
                self._offset = 0

    def __call__(self) -> list[dict]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                f.seek(self._offset)
                chunk = f.read()
        except OSError:
            return []
        end = chunk.rfind("\n")
        if end < 0:
            return []
        self._offset += end + 1
        out = []
        for line in chunk[:end].split("\n"):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
        return out


class RetrainScheduler:
    """Debounced drift-breach → retrain-job dispatcher.

    ``models`` maps each served model name to its CURRENT artifact path;
    the scheduler owns that mapping from then on — a promoted generation
    replaces the path, a rollback keeps the old one (the publisher
    re-pushed it).  ``launch(model, generation, job, artifact)`` runs
    one retrain to completion and returns ``(ok, out_path, detail)``;
    the production launcher is :func:`make_subprocess_launcher`, drills
    and tests inject fakes.  ``journal`` is a ``(kind, **fields)``
    callable (``FleetSupervisor.journal`` in the fleet process);
    ``publisher`` is a :class:`~dryad_tpu.continual.publish.
    ProbationPublisher` (``None`` promotes unconditionally — retrain-only
    operation).

    Debounce semantics per breach delivery, checked atomically in
    ``_admit``:

    * a retrain (incl. its probation window) already in flight for the
      model → ``in_flight``;
    * ``max_concurrent`` jobs running fleet-wide → ``budget``;
    * inside the per-model cooldown (``cooldown_s`` after any finished
      job, or ``policy.backoff_s`` after a FAILED one) → ``cooldown``;
    * more than ``policy.retry_budget`` consecutive failures →
      ``retry_budget_exhausted`` (latched until a later success).
    """

    GUARDED_BY = {
        "_artifacts": "_lock",
        "_cooldown_until": "_lock",
        "_fails": "_lock",
        "_generation": "_lock",
        "_inflight": "_lock",
        "_jobs": "_lock",
        "_workers": "_lock",
    }

    def __init__(
        self,
        models: Mapping[str, str],
        launch: Callable[[str, int, int, str], tuple],
        *,
        journal: Optional[Callable[..., None]] = None,
        publisher: Optional[Any] = None,
        policy: Optional[RetryPolicy] = None,
        cooldown_s: float = 300.0,
        max_concurrent: int = 1,
        poll_interval_s: float = 1.0,
        source: Optional[Callable[[], list]] = None,
        has_profile: Callable[[str], bool] = model_has_profile,
        registry: Optional[Registry] = None,
    ):
        self.launch = launch
        self.publisher = publisher
        self.policy = policy if policy is not None else RetryPolicy()
        self.cooldown_s = float(cooldown_s)
        self.max_concurrent = int(max_concurrent)
        self.poll_interval_s = float(poll_interval_s)
        self._source = source
        self._journal_fn = journal
        self._has_profile = has_profile
        self._registry = registry if registry is not None else default_registry()
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._workers: list[threading.Thread] = []
        self._artifacts = {str(k): str(v) for k, v in dict(models).items()}
        self._generation = {m: 0 for m in self._artifacts}
        self._inflight: set = set()
        self._cooldown_until: dict = {}
        self._fails: dict = {}
        self._jobs = 0  # global job counter — the fault-injection index

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RetrainScheduler":
        """Start the journal tail loop (requires a ``source``)."""
        if self._source is None:
            raise ValueError(
                "start() needs an event source (e.g. JournalTailer over the "
                "fleet journal); trigger() works without one")
        t = threading.Thread(target=self._loop, name="retrain-scheduler",
                             daemon=True)
        self._thread = t
        t.start()
        return self

    def stop(self, timeout_s: float = 30.0) -> None:
        """Stop tailing and wait for the tail thread and any in-flight
        retrain workers (bounded — a stuck subprocess is the launcher's
        timeout to kill, not ours to wait out forever)."""
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(timeout_s)
        with self._lock:
            workers = list(self._workers)
        for w in workers:
            w.join(timeout_s)

    def _loop(self) -> None:
        while not self._stop_ev.is_set():
            try:
                events = self._source()
            except Exception:
                events = []
            for ev in events:
                if ev.get("event") == "drift_breach" and ev.get("model"):
                    self._on_breach(str(ev["model"]), origin="drift_breach")
            self._stop_ev.wait(self.poll_interval_s)

    # -- triggering --------------------------------------------------------

    def trigger(self, model: str, *, origin: str = "manual") -> bool:
        """Operator surface: evaluate a retrain for ``model`` NOW, through
        the same debounce as a journaled breach.  Returns True when a job
        launched (a False is journaled as ``retrain_skipped``)."""
        return self._on_breach(str(model), origin=origin)

    def _on_breach(self, model: str, *, origin: str) -> bool:
        with self._lock:
            path = self._artifacts.get(model)
        if path is None:
            self._skip(model, "unknown_model", origin)
            return False
        # profile sniff outside the lock — it is file I/O; pre-r18
        # profile-less artifacts are a journaled skip, never a crash
        try:
            has = self._has_profile(path)
        except Exception as e:
            self._skip(model, f"artifact_unreadable:{type(e).__name__}", origin)
            return False
        if not has:
            self._skip(model, "no_profile", origin)
            return False
        admitted, reason, gen, job = self._admit(model)
        if not admitted:
            self._skip(model, reason, origin)
            return False
        self._event("retrain_triggered", model=model, generation=gen,
                    job=job, origin=origin)
        self._count("retrain_triggered", model=model)
        w = threading.Thread(target=self._retrain_job,
                             args=(model, gen, job, path),
                             name=f"retrain-{model}-g{gen}", daemon=True)
        with self._lock:
            self._workers = [t for t in self._workers if t.is_alive()]
            self._workers.append(w)
        w.start()
        return True

    def _admit(self, model: str) -> tuple:
        """Atomic debounce check-and-mark.  The checks and the in-flight
        mark MUST be one critical section: split them and two concurrent
        breach deliveries both pass the check before either marks,
        double-launching the retrain (the ``scheduler-breach-vs-push``
        drill reverts exactly this and catches it)."""
        now = time.monotonic()
        with self._lock:
            if model in self._inflight:
                return False, "in_flight", 0, 0
            if len(self._inflight) >= self.max_concurrent:
                return False, "budget", 0, 0
            if now < self._cooldown_until.get(model, 0.0):
                return False, "cooldown", 0, 0
            if self._fails.get(model, 0) > self.policy.retry_budget:
                return False, "retry_budget_exhausted", 0, 0
            self._inflight.add(model)
            gen = self._generation.get(model, 0) + 1
            job = self._jobs
            self._jobs += 1
        return True, "", gen, job

    # -- the retrain worker ------------------------------------------------

    def _retrain_job(self, model: str, gen: int, job: int,
                     artifact: str) -> None:
        t0 = time.monotonic()
        ok, out_path, detail = False, None, ""
        try:
            ok, out_path, detail = self.launch(model, gen, job, artifact)
        except Exception as e:  # the control plane survives any launcher
            detail = repr(e)
        wall = time.monotonic() - t0
        if not ok or not out_path:
            now = time.monotonic()
            with self._lock:
                fails = self._fails.get(model, 0) + 1
                self._fails[model] = fails
                self._cooldown_until[model] = now + self.policy.backoff_s(
                    fails - 1)
                self._inflight.discard(model)
            self._event("retrain_failed", model=model, generation=gen,
                        job=job, wall_s=round(wall, 3), fails=fails,
                        detail=str(detail)[:500])
            self._count("retrain_failed", model=model)
            return
        self._event("retrain_complete", model=model, generation=gen,
                    job=job, wall_s=round(wall, 3), path=out_path)
        self._count("retrain_complete", model=model)
        outcome = "promoted"
        if self.publisher is not None:
            try:
                outcome = self.publisher.publish(out_path, model=model,
                                                 prior_path=artifact,
                                                 generation=gen)
            except Exception as e:
                outcome = "publish_error"
                self._event("publish_error", model=model, generation=gen,
                            detail=repr(e)[:500])
        now = time.monotonic()
        with self._lock:
            if outcome == "promoted":
                self._artifacts[model] = out_path
                self._generation[model] = gen
                self._fails[model] = 0
            self._cooldown_until[model] = now + self.cooldown_s
            self._inflight.discard(model)
            cur_gen = self._generation.get(model, 0)
        self._gauge("generation", cur_gen, model=model)

    # -- introspection -----------------------------------------------------

    def state(self) -> dict:
        """Snapshot for tests/smokes: current generations, artifact paths,
        in-flight set, failure counts, and the global job counter."""
        with self._lock:
            return {
                "artifacts": dict(self._artifacts),
                "generation": dict(self._generation),
                "inflight": sorted(self._inflight),
                "fails": dict(self._fails),
                "jobs": self._jobs,
            }

    # -- plumbing (all called WITHOUT the lock held) -----------------------

    def _skip(self, model: str, reason: str, origin: str) -> None:
        self._event("retrain_skipped", model=model, reason=reason,
                    origin=origin)
        self._count("retrain_skipped", model=model, reason=reason)

    def _event(self, kind: str, **fields) -> None:
        j = self._journal_fn
        if j is None:
            return
        try:
            j(kind, **fields)
        except Exception:
            pass  # telemetry must never kill the control plane

    def _count(self, name: str, **labels) -> None:
        reg = self._registry
        if reg is not None and reg.enabled:
            reg.counter(f"dryad_continual_{name}_total",
                        "continual-boosting scheduler decisions"
                        ).labels(**labels).inc()

    def _gauge(self, name: str, value: float, **labels) -> None:
        reg = self._registry
        if reg is not None and reg.enabled:
            reg.gauge(f"dryad_continual_{name}",
                      "continual-boosting scheduler state"
                      ).labels(**labels).set(float(value))


def make_subprocess_launcher(
    data_path: str,
    out_dir: str,
    *,
    trees: int = 20,
    backend: str = "cpu",
    timeout_s: float = 1800.0,
    refit_decay: float = 0.0,
    supervise: bool = False,
    python: Optional[str] = None,
    log_dir: Optional[str] = None,
    extra_env: Optional[Mapping[str, str]] = None,
) -> Callable[[str, int, int, str], tuple]:
    """Build the production ``launch`` callable: one retrain = one fresh
    ``python -m dryad_tpu retrain`` subprocess.

    The worker is the only jax-importing piece of the loop — it loads the
    served artifact, warm-start appends ``trees`` new trees on the rows
    in ``data_path`` (an npz with ``X``/``y``), optionally after a
    ``Booster.refit`` re-weighting pass, and saves the new generation
    with a FRESH reference profile (``DRYAD_PROFILE=1`` is forced into
    the worker env).  ``supervise=True`` routes the worker's own training
    through ``resilience.supervise_train`` (fault classes degrade and
    resume bitwise inside the subprocess).  The parent environment is
    inherited, so an armed ``DRYAD_CONTINUAL_FAULTS`` spec reaches the
    worker's fault injector (``faults.take('retrain', job)``).
    """
    out_dir = str(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    if log_dir is not None:
        os.makedirs(log_dir, exist_ok=True)

    def launch(model: str, generation: int, job: int, artifact: str) -> tuple:
        out_path = os.path.join(out_dir, f"{model}-gen{generation}.dryad")
        argv = [python or sys.executable, "-m", "dryad_tpu", "retrain",
                "--model", artifact, "--data", str(data_path),
                "--out", out_path, "--trees", str(trees),
                "--backend", backend, "--job-index", str(job)]
        if refit_decay:
            argv += ["--refit-decay", str(refit_decay)]
        if supervise:
            argv += ["--supervise"]
        env = dict(os.environ)
        env["DRYAD_PROFILE"] = "1"  # every generation ships a fresh baseline
        if extra_env:
            env.update(extra_env)
        log_path = os.path.join(log_dir or out_dir,
                                f"retrain-{model}-g{generation}.log")
        with open(log_path, "wb") as log:
            try:
                rc = subprocess.call(argv, stdout=log,
                                     stderr=subprocess.STDOUT, env=env,
                                     timeout=timeout_s)
            except subprocess.TimeoutExpired:
                return False, None, f"timeout {timeout_s}s (log: {log_path})"
        if rc != 0:
            return False, None, f"exit {rc} (log: {log_path})"
        if not os.path.exists(out_path):
            return False, None, f"no artifact at {out_path} (log: {log_path})"
        return True, out_path, ""

    return launch
