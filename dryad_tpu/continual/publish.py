"""Probationed rolling publish with score-shift auto-rollback.

A retrained generation never replaces its predecessor blindly: it goes
out through the fleet's EXISTING zero-drop rolling swap
(``FleetSupervisor.rolling_push`` — the same machinery behind
``POST /models/push``) and then sits in a PROBATION window while the
merged fleet drift verdict accumulates evidence against its own fresh
reference profile.  The decision rule compares against the DISPLACED
generation's last-known verdict, captured immediately before the push:

* the new generation's verdict clears (``clear_after`` polls with
  traffic and no breach) → ``generation_promoted``;
* the new generation SUSTAINS a breach while its predecessor was clean
  → ``generation_rolled_back``: the prior ARTIFACT is re-pushed through
  the same rolling swap — the registry is never mutated in place, a
  rollback is just another zero-drop deploy of a file that still exists;
* the predecessor was already breaching (the usual case — a breach is
  what triggered the retrain): a breach by the new generation is not
  conclusive regression, so probation keeps polling for a clear;
* the window expires without decisive evidence (e.g. no traffic) →
  promoted with ``verdict="expired"`` in the journal — visible, not
  silent.

The publisher is deliberately STATELESS per call — every probation
lives on its caller's (retrain worker thread's) stack, so concurrent
publishes of different models share nothing here; the fleet's swap
mutex already serializes the actual swaps.  jax-free by lint; the
verdict source is injected (``make_http_verdicts`` polls the router's
``GET /drift``, whose handler performs the fresh replica scrape — each
probation poll IS a drift window advancing).
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Any, Callable, Mapping, Optional


class ProbationPublisher:
    """Push → probation → promote-or-rollback.

    ``push(path, model) -> (ok, detail)`` performs one zero-drop rolling
    swap (:func:`make_supervisor_push` adapts ``FleetSupervisor``);
    ``verdicts() -> {model: verdict}`` returns the merged fleet drift
    verdicts (:func:`make_http_verdicts`, or ``DriftGate.verdicts()``
    directly in-process).  ``journal`` is a ``(kind, **fields)``
    callable.  ``publish`` returns one of ``"promoted"`` /
    ``"rolled_back"`` / ``"push_failed"``.
    """

    def __init__(
        self,
        push: Callable[[str, str], tuple],
        verdicts: Callable[[], Mapping[str, Any]],
        *,
        journal: Optional[Callable[..., None]] = None,
        probation_polls: int = 5,
        poll_interval_s: float = 2.0,
        clear_after: int = 1,
        registry: Optional[Any] = None,
    ):
        self.push = push
        self.verdicts = verdicts
        self._journal_fn = journal
        self.probation_polls = int(probation_polls)
        self.poll_interval_s = float(poll_interval_s)
        self.clear_after = max(1, int(clear_after))
        if registry is None:
            from dryad_tpu.obs.registry import default_registry

            registry = default_registry()
        self._registry = registry

    def publish(self, path: str, *, model: str, prior_path: str,
                generation: int) -> str:
        prior = self._verdict_of(model)
        # the displaced generation's standing at the moment it leaves:
        # rollback is only armed when the predecessor was NOT already in
        # sustained breach (a breach-triggered retrain's predecessor is)
        prior_clean = not bool((prior or {}).get("sustained"))
        ok, detail = self.push(path, model)
        if not ok:
            self._event("push_failed", model=model, generation=generation,
                        path=path, detail=str(detail)[:300])
            self._count("push_failed", model=model)
            return "push_failed"
        self._event("push_probation", model=model, generation=generation,
                    path=path, prior_clean=prior_clean,
                    polls=self.probation_polls,
                    interval_s=self.poll_interval_s)
        self._count("push_probation", model=model)
        clean_streak = 0
        for _ in range(self.probation_polls):
            time.sleep(self.poll_interval_s)
            verdict = self._verdict_of(model)
            if not verdict or not verdict.get("rows"):
                continue  # no traffic evidence — this poll decides nothing
            if verdict.get("sustained"):
                if prior_clean:
                    return self._rollback(model, generation, path,
                                          prior_path, verdict)
                clean_streak = 0
                continue
            if verdict.get("breached"):
                clean_streak = 0
                continue
            clean_streak += 1
            if clean_streak >= self.clear_after:
                return self._promote(model, generation, path, "clear")
        return self._promote(model, generation, path, "expired")

    # -- outcomes ----------------------------------------------------------

    def _promote(self, model: str, generation: int, path: str,
                 verdict: str) -> str:
        self._event("generation_promoted", model=model, generation=generation,
                    path=path, verdict=verdict)
        self._count("generation_promoted", model=model)
        return "promoted"

    def _rollback(self, model: str, generation: int, path: str,
                  prior_path: str, verdict: Mapping[str, Any]) -> str:
        # re-push the prior artifact through the same zero-drop swap —
        # NEVER an in-place registry mutation
        ok, detail = self.push(prior_path, model)
        self._event("generation_rolled_back", model=model,
                    generation=generation, path=path, prior=prior_path,
                    psi_max=verdict.get("psi_max"),
                    score_psi=verdict.get("score_psi"),
                    restore_ok=bool(ok), restore_detail=str(detail)[:200])
        self._count("generation_rolled_back", model=model)
        return "rolled_back"

    # -- plumbing ----------------------------------------------------------

    def _verdict_of(self, model: str) -> Optional[Mapping[str, Any]]:
        try:
            return dict(self.verdicts()).get(model)
        except Exception:
            return None

    def _event(self, kind: str, **fields) -> None:
        j = self._journal_fn
        if j is None:
            return
        try:
            j(kind, **fields)
        except Exception:
            pass  # telemetry must never kill a publish decision

    def _count(self, name: str, **labels) -> None:
        reg = self._registry
        if reg is not None and reg.enabled:
            reg.counter(f"dryad_continual_{name}_total",
                        "continual-boosting publish decisions"
                        ).labels(**labels).inc()


def make_supervisor_push(supervisor, *, activate: bool = True,
                         auth_token: Optional[str] = None,
                         drain_timeout_s: float = 30.0,
                         load_timeout_s: float = 120.0):
    """Adapt ``FleetSupervisor.rolling_push`` to the publisher's push
    contract — the identical zero-drop swap ``POST /models/push``
    drives."""

    def push(path: str, model: str) -> tuple:
        res = supervisor.rolling_push(path, name=model, activate=activate,
                                      auth_token=auth_token,
                                      drain_timeout_s=drain_timeout_s,
                                      load_timeout_s=load_timeout_s)
        errs = list(res.get("errors") or [])
        if errs:
            return False, "; ".join(str(e) for e in errs)[:300]
        return True, ""

    return push


def make_http_verdicts(host: str, port: int, *,
                       auth_token: Optional[str] = None,
                       timeout_s: float = 10.0):
    """Poll the fleet router's ``GET /drift`` for merged per-model
    verdicts.  The handler performs a fresh replica scrape + exact count
    merge + gate evaluation per call, so each probation poll advances
    the drift windows it is judging."""
    url = f"http://{host}:{port}/drift"

    def verdicts() -> Mapping[str, Any]:
        req = urllib.request.Request(url)
        if auth_token:
            req.add_header("Authorization", f"Bearer {auth_token}")
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                doc = json.loads(r.read().decode("utf-8"))
        except Exception:
            return {}
        return doc.get("models") or {}

    return verdicts
