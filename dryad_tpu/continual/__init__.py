"""Continual boosting — close the train → serve → drift → retrain →
publish loop (r19).

The fleet's drift telemetry (r18) journals ``drift_breach`` when served
traffic sustainably departs a model's embedded reference profile; this
package turns that event into a new model generation and gets it back
into the fleet safely:

* :class:`~dryad_tpu.continual.scheduler.RetrainScheduler` tails the
  fleet journal, debounces breaches per model (cooldown + a
  max-concurrent-retrains budget, failure backoff riding
  ``resilience.RetryPolicy``), and launches each retrain as a SUPERVISED
  SUBPROCESS (``python -m dryad_tpu retrain``) — a wedged device can
  never hang the fleet control plane.
* The worker warm-starts from the served artifact
  (``dryad.train(init_model=...)``): boosting resumes from the loaded
  model's carried scores on fresh rows, in the model's frozen bin space.
* :class:`~dryad_tpu.continual.publish.ProbationPublisher` pushes the
  new generation through the existing zero-drop rolling swap, then holds
  it in a PROBATION window: the merged fleet score-shift verdict is
  compared against the displaced generation's pre-push verdict —
  promote on clear, AUTO-ROLLBACK (a rolling push of the prior
  artifact; the registry is never mutated in place) when the new
  generation breaches while its predecessor did not.

Every decision is journaled (``retrain_triggered`` / ``retrain_skipped``
/ ``retrain_complete`` / ``retrain_failed`` / ``push_probation`` /
``generation_promoted`` / ``generation_rolled_back``) and exported as
``dryad_continual_*`` counters/gauges on the fleet registry.

jax-free by lint (``continual-jax-free``, transitive): the scheduler and
publisher run in the fleet control plane, which must keep supervising
replicas while a device is wedged — the only jax-importing piece of the
loop is the retrain worker subprocess itself.
"""

from dryad_tpu.continual.publish import (ProbationPublisher,
                                         make_http_verdicts,
                                         make_supervisor_push)
from dryad_tpu.continual.scheduler import (JournalTailer, RetrainScheduler,
                                           make_subprocess_launcher,
                                           model_has_profile)

__all__ = [
    "JournalTailer",
    "ProbationPublisher",
    "RetrainScheduler",
    "make_http_verdicts",
    "make_subprocess_launcher",
    "make_supervisor_push",
    "model_has_profile",
]
