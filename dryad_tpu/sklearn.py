"""scikit-learn-style estimator API over ``dryad.train`` / ``dryad.predict``.

Mirrors the estimator surface GBDT users expect (LGBMClassifier-family):
``fit(X, y)``, ``predict``, ``predict_proba``, ``feature_importances_``,
``get_params``/``set_params`` — implemented without importing sklearn so the
package has no hard dependency on it (but instances duck-type cleanly into
sklearn pipelines and CV utilities).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from dryad_tpu import Booster, Dataset, train
from dryad_tpu.config import Params, make_params


class _DryadModel:
    _objective: str = "regression"

    def __init__(
        self,
        num_trees: int = 100,
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        max_bins: int = 256,
        lambda_l2: float = 1.0,
        min_child_weight: float = 1e-3,
        min_data_in_leaf: int = 20,
        min_split_gain: float = 0.0,
        growth: str = "leafwise",
        subsample: float = 1.0,
        colsample: float = 1.0,
        seed: int = 0,
        categorical_features: Sequence[int] = (),
        early_stopping_rounds: int = 0,
        backend: str = "auto",
        **extra_params: Any,
    ):
        self.num_trees = num_trees
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.max_bins = max_bins
        self.lambda_l2 = lambda_l2
        self.min_child_weight = min_child_weight
        self.min_data_in_leaf = min_data_in_leaf
        self.min_split_gain = min_split_gain
        self.growth = growth
        self.subsample = subsample
        self.colsample = colsample
        self.seed = seed
        self.categorical_features = tuple(categorical_features)
        self.early_stopping_rounds = early_stopping_rounds
        self.backend = backend
        self.extra_params = dict(extra_params)
        self.booster_: Optional[Booster] = None

    # ---- sklearn protocol ---------------------------------------------------
    _PARAM_NAMES = (
        "num_trees", "num_leaves", "max_depth", "learning_rate", "max_bins",
        "lambda_l2", "min_child_weight", "min_data_in_leaf", "min_split_gain",
        "growth", "subsample", "colsample", "seed", "categorical_features",
        "early_stopping_rounds", "backend",
    )

    def get_params(self, deep: bool = True) -> dict:
        out = {k: getattr(self, k) for k in self._PARAM_NAMES}
        out.update(self.extra_params)
        return out

    def set_params(self, **kw: Any) -> "_DryadModel":
        for k, v in kw.items():
            if k in self._PARAM_NAMES:
                setattr(self, k, v)
            else:
                self.extra_params[k] = v
        return self

    def _params(self, **overrides: Any) -> Params:
        d = {k: getattr(self, k) for k in self._PARAM_NAMES if k != "backend"}
        d["objective"] = self._objective
        d.update(self.extra_params)
        d.update(overrides)
        return make_params(d)

    def _fit(self, X, y, *, sample_weight=None, group=None, eval_set=None,
             eval_group=None, **param_overrides):
        p = self._params(**param_overrides)
        ds = Dataset(np.asarray(X, np.float32), np.asarray(y, np.float32),
                     weight=sample_weight, group=group,
                     categorical_features=self.categorical_features,
                     max_bins=p.max_bins)
        valid = None
        if eval_set is not None:
            Xv, yv = eval_set[0] if isinstance(eval_set, list) else eval_set
            valid = ds.bind(np.asarray(Xv, np.float32),
                            np.asarray(yv, np.float32),
                            group=eval_group)
        self.booster_ = train(p, ds, [valid] if valid is not None else None,
                              backend=self.backend)
        self.n_features_in_ = ds.num_features
        return self

    # ---- shared inference ---------------------------------------------------
    def _check_fitted(self) -> Booster:
        if self.booster_ is None:
            raise RuntimeError("call fit() first")
        return self.booster_

    @property
    def feature_importances_(self) -> np.ndarray:
        return self._check_fitted().feature_importance("gain")

    @property
    def best_iteration_(self) -> int:
        return self._check_fitted().best_iteration


class DryadRegressor(_DryadModel):
    """L2 regression estimator."""

    _objective = "regression"

    def fit(self, X, y, sample_weight=None, eval_set=None) -> "DryadRegressor":
        return self._fit(X, y, sample_weight=sample_weight, eval_set=eval_set)

    def predict(self, X) -> np.ndarray:
        return self._check_fitted().predict(np.asarray(X, np.float32))


class DryadClassifier(_DryadModel):
    """Binary / multiclass classifier (objective inferred from n classes)."""

    _objective = "binary"

    def fit(self, X, y, sample_weight=None, eval_set=None) -> "DryadClassifier":
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        n_class = self.classes_.size
        if n_class < 2:
            raise ValueError("DryadClassifier needs at least 2 classes in y")
        y_enc = np.searchsorted(self.classes_, y).astype(np.float32)
        if n_class == 2:
            self._objective = "binary"
            over = {}
        else:
            self._objective = "multiclass"
            over = {"num_class": n_class}
        if eval_set is not None:
            Xv, yv = eval_set[0] if isinstance(eval_set, list) else eval_set
            yv = np.asarray(yv)
            unknown = np.setdiff1d(np.unique(yv), self.classes_)
            if unknown.size:
                raise ValueError(
                    f"eval_set labels {unknown.tolist()} never appear in the "
                    "training labels")
            yv = np.searchsorted(self.classes_, yv).astype(np.float32)
            eval_set = (Xv, yv)
        return self._fit(X, y_enc, sample_weight=sample_weight,
                         eval_set=eval_set, **over)

    def predict_proba(self, X) -> np.ndarray:
        prob = self._check_fitted().predict(np.asarray(X, np.float32))
        if prob.ndim == 1:                       # binary: P(class 1)
            return np.stack([1.0 - prob, prob], axis=1)
        return prob

    def predict(self, X) -> np.ndarray:
        return self.classes_[np.argmax(self.predict_proba(X), axis=1)]


class DryadRanker(_DryadModel):
    """LambdaMART pairwise ranker (NDCG-optimizing)."""

    _objective = "lambdarank"

    def fit(self, X, y, group, sample_weight=None, eval_set=None,
            eval_group=None) -> "DryadRanker":
        return self._fit(X, y, sample_weight=sample_weight, group=group,
                         eval_set=eval_set, eval_group=eval_group)

    def predict(self, X) -> np.ndarray:
        return self._check_fitted().predict(np.asarray(X, np.float32),
                                            raw_score=True)
